"""Benchmark: device frontier checker vs host BFS on the 2PC-4 workload
(the BASELINE.json metric config: "states/sec/chip, 2PC-4").

Runs the whole-search resident engine (one device dispatch) on the current
default JAX backend (the TPU chip under the driver; CPU elsewhere), measures
generated-states/sec after a compile warm-up, and compares against the
host-Python multithread-free BFS checker on the same model. The reference
publishes no absolute numbers (BASELINE.md), so `vs_baseline` is the ratio
against the locally-measured host BFS states/sec.

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    from stateright_tpu.examples.two_phase_commit import TwoPhaseSys
    from stateright_tpu.tensor.models import TensorTwoPhaseSys
    from stateright_tpu.tensor.resident import ResidentSearch

    rm = 4

    # -- host BFS baseline (pure Python, same model family) --------------------
    t0 = time.monotonic()
    host = TwoPhaseSys(rm).checker().spawn_bfs().join()
    host_dur = time.monotonic() - t0
    host_sps = host.state_count() / host_dur

    # -- device resident search ------------------------------------------------
    search = ResidentSearch(TensorTwoPhaseSys(rm), batch_size=1024, table_log2=16)
    search.run()  # compile + warm-up dispatch
    best = None
    for _ in range(3):
        r = search.run()
        if best is None or r.duration < best.duration:
            best = r
    assert best.unique_state_count == host.unique_state_count(), (
        best.unique_state_count,
        host.unique_state_count(),
    )
    sps = best.state_count / best.duration

    print(
        json.dumps(
            {
                "metric": f"2pc-{rm} generated states/sec (device, whole search)",
                "value": round(sps, 1),
                "unit": "states/sec",
                "vs_baseline": round(sps / host_sps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
