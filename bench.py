"""Benchmark: device whole-search checker vs host BFS on the Paxos register
workload (BASELINE.json metric: states/sec/chip on Paxos; golden 16,668
unique states @ 2 clients, ref: examples/paxos.rs:327,351).

Runs the host multithread-free Python BFS checker on the 2-client / 3-server
Paxos actor model (linearizability-tested register), then the device-resident
whole-search engine on the tensor encoding of the SAME system — including the
on-device linearizability property — asserts exact unique/generated-state
count parity, and reports generated states/sec with `vs_baseline` = the ratio
against the locally-measured host BFS (the reference publishes no absolute
numbers — BASELINE.md).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    from stateright_tpu.examples.paxos import PaxosModelCfg
    from stateright_tpu.tensor.paxos import TensorPaxos
    from stateright_tpu.tensor.resident import ResidentSearch

    clients = 2

    # -- host BFS baseline (pure Python, same model) ---------------------------
    t0 = time.monotonic()
    host = (
        PaxosModelCfg(client_count=clients, server_count=3)
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    host_dur = time.monotonic() - t0
    host_sps = host.state_count() / host_dur

    # -- device resident search ------------------------------------------------
    search = ResidentSearch(
        TensorPaxos(client_count=clients), batch_size=2048, table_log2=16
    )
    search.run()  # compile + warm-up dispatch
    best = None
    for _ in range(3):
        r = search.run()
        if best is None or r.duration < best.duration:
            best = r
    assert best.unique_state_count == host.unique_state_count(), (
        best.unique_state_count,
        host.unique_state_count(),
    )
    assert best.state_count == host.state_count()
    sps = best.state_count / best.duration

    print(
        json.dumps(
            {
                "metric": f"paxos-{clients} generated states/sec (device, whole search, on-device linearizability)",
                "value": round(sps, 1),
                "unit": "states/sec",
                "vs_baseline": round(sps / host_sps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
