"""Benchmark: device whole-search checker vs the compiled CPU baseline on the
BASELINE.json metric workloads — Paxos-3 (north star) and 2PC-4 — plus the
reference's 2-client Paxos golden config as the parity anchor.

Baseline: this image has no cargo/rustc, so the reference's multithreaded Rust
`BfsChecker` (the thing BASELINE.md says to measure via bench.sh) is
approximated by `stateright_tpu/_native/baseline_bfs.cpp` — a C++ port of the
same search over the same state spaces, validated against the reference's
golden counts (2pc-3=288, 2pc-5=8,832, paxos-2=16,668 — examples/2pc.rs:153-159,
examples/paxos.rs:327). It packs states into u32 lanes, so it does *less* work
per state than the Rust checker's boxed states: a conservative baseline.

Robustness contract (VERDICT round 1): exactly ONE JSON line is printed on
stdout no matter what. The device is probed with a trivial jitted op (with
retries) before any search kernel compiles; if the device is unusable the line
carries `value: null, vs_baseline: null` plus a `device_error` field (the CPU
baseline stays in detail.cpu_baseline) instead of dying with rc=1 and no
output. Count-parity failures are reported in an `error`
field (never a bare `assert`, which `python -O` would strip).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time
import traceback

# Golden counts (generated, unique): reference examples/paxos.rs:327 for
# paxos-2; the rest were computed by the compiled baseline checker and
# cross-validated against the device engines and the host checkers
# (BASELINE_MEASURED.md; increment_lock sym golden is host-DFS-sym
# cross-validated in tests/test_tensor_symmetry.py). Lowered workloads
# (abd-ordered, paxos5s4c) carry NO pinned golden: the exact-closure host
# traversal computes the oracle at build time and the worker asserts against
# it (closure_stats).
GOLDEN = {
    ("paxos", 2): (32_971, 16_668),
    ("paxos", 3): (2_420_477, 1_194_428),
    ("2pc", 4): (8_258, 1_568),
    ("2pc", 10): (817_760_258, 61_515_776),
    ("inclock", 6): (7_825, 7_825),
    ("increment_lock", 6): (7_825, 7_825),  # C++ baseline name for the same
    ("inclock-sym", 6): (40, 25),
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- compiled CPU baseline -----------------------------------------------------


def compile_baseline() -> str | None:
    try:
        from stateright_tpu._native import build

        return build("baseline_bfs", exe=True)
    except Exception as e:  # noqa: BLE001 — baseline is best-effort
        log(f"baseline compile failed: {e}")
        return None


def run_baseline(exe: str, model: str, n: int, repeats: int = 3,
                 threads: int | None = None):
    """Best-of-N run of the compiled checker. Returns dict or None; keeps the
    best run that *succeeded* even if later repeats fail. `threads` pins the
    checker's thread count (baseline_bfs.cpp argv[3]); None lets it default
    to hardware_concurrency."""
    cmd = [exe, model, str(n)]
    if threads is not None:
        cmd.append(str(threads))
    best = None
    for _ in range(repeats):
        try:
            proc = subprocess.run(
                cmd,
                check=True,
                capture_output=True,
                text=True,
                timeout=1800,
            )
        except Exception as e:  # noqa: BLE001
            log(f"baseline run {model}-{n} failed: {e}")
            continue
        m = re.search(
            r"states=(\d+) unique=(\d+) depth=(\d+) sec=([\d.]+) threads=(\d+) "
            r"violations=(\d+)",
            proc.stdout,
        )
        if not m:
            log(f"baseline output unparseable: {proc.stdout!r}")
            continue
        r = {
            "states": int(m.group(1)),
            "unique": int(m.group(2)),
            "depth": int(m.group(3)),
            "sec": float(m.group(4)),
            "threads": int(m.group(5)),
            "violations": int(m.group(6)),
        }
        if best is None or r["sec"] < best["sec"]:
            best = r
    if best:
        best["states_per_sec"] = best["states"] / max(best["sec"], 1e-9)
    return best


# -- device ----------------------------------------------------------------


# Persistent XLA compilation cache: the resident kernels take tens of seconds
# to compile over the device tunnel; caching them means repeat bench runs (and
# any warm-up run done earlier in the same checkout) skip compilation
# entirely. CPU-pinned rehearsals use a SEPARATE directory: XLA:CPU AOT
# entries embed the compiling machine's CPU features, and `.jax_cache`
# carries entries from a prior host that this machine rejects on every load
# (ROUND4_NOTES.md); `.jax_cache_cpu` is native to the current host and
# gitignored.
_REPO = os.path.dirname(os.path.abspath(__file__))
_CACHE_DIR = os.path.join(
    _REPO,
    ".jax_cache_cpu" if os.environ.get("JAX_PLATFORMS") == "cpu" else ".jax_cache",
)

# The image's site config re-registers the axon TPU platform and overrides a
# plain JAX_PLATFORMS env var; applying the env var at the jax.config level
# restores it, so `JAX_PLATFORMS=cpu python bench.py` really benches on CPU
# (used by verification runs when the TPU tunnel is down).
_PIN_SNIPPET = (
    "import os, jax;"
    "p = os.environ.get('JAX_PLATFORMS');"
    "jax.config.update('jax_platforms', p) if p else None;"
    f"jax.config.update('jax_compilation_cache_dir', {_CACHE_DIR!r});"
)

_PROBE_SNIPPET = _PIN_SNIPPET + (
    "import jax.numpy as jnp;"
    "x = jax.jit(lambda a: a * 2 + 1)(jnp.arange(8));"
    "x.block_until_ready();"
    "print('PROBE_OK', jax.devices())"
)


def _pin_platform() -> None:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)


def probe_device(attempts: int = 6, delay: float = 20.0):
    """Run a trivial jitted op on the default backend in a SUBPROCESS;
    returns (ok, error).

    The axon TPU tunnel is single-client: while any other process holds the
    chip, backend init fails with "UNAVAILABLE: TPU backend setup/compile
    error" (the round-1 bench death). That clears when the holder exits, so
    the probe retries patiently — and in a fresh subprocess each time, because
    a failed backend init can be cached for the life of a process, which would
    make in-process retries (and the real run afterwards) futile.

    Failure-mode triage (round-4 postmortem: three 180 s probe TIMEOUTS
    burned 9+ min of driver budget on a tunnel that was wedged, not busy):
    a busy tunnel FAILS FAST with an UNAVAILABLE error — retrying with a
    delay is right; a wedged tunnel HANGS until the timeout — two
    consecutive hangs have never been followed by a recovery within the
    bench's time horizon, so the probe gives up after the second timeout
    instead of burning attempts x 180 s.
    """
    last = "unknown"
    consecutive_timeouts = 0
    for i in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                capture_output=True,
                text=True,
                timeout=120,
            )
        except subprocess.TimeoutExpired as e:
            consecutive_timeouts += 1
            last = f"probe subprocess timed out: {e}"
            log(last)
            if consecutive_timeouts >= 2:
                log("two consecutive probe timeouts: tunnel wedged, giving up")
                return False, last
            continue  # a hung tunnel needs no inter-attempt delay
        except Exception as e:  # noqa: BLE001
            consecutive_timeouts = 0
            last = f"probe subprocess failed: {e}"
            log(last)
            if i + 1 < attempts:
                time.sleep(delay)
            continue
        consecutive_timeouts = 0
        if proc.returncode == 0 and "PROBE_OK" in proc.stdout:
            log(f"device probe ok: {proc.stdout.strip()}")
            return True, ""
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        last = tail[-1] if tail else f"rc={proc.returncode}"
        log(f"device probe attempt {i + 1}/{attempts} failed: {last}")
        if i + 1 < attempts:
            time.sleep(delay)
    return False, last


def device_search_subprocess(
    model_name: str,
    n: int,
    timeout: float = 1500.0,
    mode: str = "--worker",
    env_extra: dict | None = None,
):
    """Run one device workload in a FRESH subprocess (`bench.py --worker`).

    Isolation serves two purposes on the tunneled single-client device:
    a workload that hangs (e.g. a pathological compile) is bounded by
    `timeout` instead of eating the whole bench, and a crashed workload
    cannot poison the backend state of the remaining ones. Workloads still
    run strictly sequentially — the tunnel admits one client at a time.

    Returns (result dict | None, error str | None).
    """
    env = None
    if env_extra:
        env = dict(os.environ)
        env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), mode, model_name, str(n)],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=env,
        )
    except subprocess.TimeoutExpired as e:
        # The kill that subprocess.run just delivered can itself wedge the
        # single-client tunnel (see ROUND2_NOTES.md); keep the partial stderr
        # so the hung phase is attributable, and flag the contamination risk.
        if e.stderr:
            err_text = e.stderr if isinstance(e.stderr, str) else e.stderr.decode(errors="replace")
            sys.stderr.write(err_text)
        return None, (
            f"workload timed out after {timeout:.0f}s and was killed "
            "(subsequent workload failures may be kill-induced tunnel wedge)"
        )
    except Exception as e:  # noqa: BLE001
        return None, f"worker spawn failed: {e}"
    sys.stderr.write(proc.stderr)
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    if not line.startswith("{"):
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        return None, tail[-1] if tail else f"worker rc={proc.returncode}"
    try:
        payload = json.loads(line)
    except ValueError:
        return None, f"unparseable worker output: {line[:200]!r}"
    return payload.get("result"), payload.get("error")


def _abd_ordered_lowered(depth: int):
    """ABD linearizable register, 2 clients / 3 servers, ORDERED network
    (BASELINE.json config #3; ref examples/linearizable-register.rs,
    bench.sh:31-33), via the exact-closure generic lowering bounded at
    `depth` (the full ordered space is not host-enumerable)."""
    from stateright_tpu.actor import Network
    from stateright_tpu.examples.abd import AbdModelCfg
    from stateright_tpu.tensor.lowering import lower_actor_model

    cfg = AbdModelCfg(2, 3, network=Network.new_ordered())
    return lower_actor_model(
        cfg.into_model(),
        closure="exact",
        closure_max_depth=depth,
        max_joint_states=1 << 22,
    )


def _paxos5s4c_lowered(depth: int):
    """Paxos 5 servers / 4 clients deep BFS (BASELINE.json config #5) via
    the exact-closure generic lowering bounded at `depth`."""
    from stateright_tpu.actor import Network
    from stateright_tpu.examples.paxos import PaxosModelCfg
    from stateright_tpu.tensor.lowering import lower_actor_model

    cfg = PaxosModelCfg(
        client_count=4,
        server_count=5,
        network=Network.new_unordered_nonduplicating(),
    )
    return lower_actor_model(
        cfg.into_model(),
        closure="exact",
        closure_max_depth=depth,
        max_joint_states=1 << 22,
        max_emit=6,
        # pool_size: auto-sized by the exact closure to the PROVEN max
        # occupancy (18 at depth 10 — round 5; the explicit 24 it replaces
        # cost 6 dead lanes AND 6 dead deliver slots per state).
    )


def _build_workload(model_name: str, n: int):
    """-> (model, batch, table_log2, run_kwargs, engine_kwargs, golden
    (gen, unique) or None, closure_sec). Lowered workloads compute their
    own oracle (closure_stats) during the host closure."""
    t0 = time.monotonic()
    engine_kwargs: dict = {}
    import jax

    # The two backends want opposite batch sizes (v5e sweep vs CPU sweep,
    # both in ROUND4_NOTES.md): on TPU step cost is near-linear in batch
    # while the frontier is often sub-batch, so small batches win — final
    # v5e bracket at session-end kernels: 627k/s @3072 vs 616k @4096,
    # 599k @2560, 572k @6144, 280k @32768; the 1-core CPU backend
    # amortizes per-step overhead with big batches (101k @32768 vs 53k
    # @65536, smaller is worse). Key off the EFFECTIVE backend so CPU
    # rehearsals stay comparable round over round.
    on_cpu = jax.default_backend() == "cpu"
    if model_name == "paxos":
        from stateright_tpu.tensor.paxos import TensorPaxos

        model = TensorPaxos(client_count=n)
        big = (32768, 22) if on_cpu else (3072, 22)
        batch, table_log2 = (2048, 16) if n <= 2 else big
        run_kwargs, golden = {}, GOLDEN[(model_name, n)]
    elif model_name == "2pc":
        from stateright_tpu.tensor.models import TensorTwoPhaseSys

        model = TensorTwoPhaseSys(n)
        # 2pc-10: batch 32768 amortizes the per-step table/queue traffic 4x
        # vs 8192, and donated chunk dispatches avoid the multi-GB carry
        # copy if the run is ever chunked (round-4 CPU A/B: ~140k gen/s
        # sustained, full space in ~100 min on one core; ROUND4_NOTES.md).
        # queue_log2=26 right-sizes the frontier queue (61.5 M uniques
        # < 2^26): at table 2^27 a table-sized queue alone is 9.1 GB and
        # the workload crashed the 16 GB v5e worker mid-run.
        batch, table_log2 = (512, 14) if n < 8 else (32768, 27)
        run_kwargs = {}
        if n >= 8:
            engine_kwargs["donate_chunks"] = True
            engine_kwargs["queue_log2"] = 26
            # Chunked dispatches (donated, so near-free): the whole-search
            # form is ONE multi-minute device program, which the tunneled
            # TPU worker kills mid-run ("worker crashed or restarted", both
            # round-4 attempts); ~64-step dispatches stay minutes under any
            # watchdog.
            run_kwargs["budget"] = 64
        golden = GOLDEN[(model_name, n)]
    elif model_name in ("inclock", "inclock-sym"):
        from stateright_tpu.tensor.models import TensorIncrementLock

        model = TensorIncrementLock(n, symmetry=model_name == "inclock-sym")
        batch, table_log2 = (1024, 14) if model_name == "inclock" else (512, 10)
        run_kwargs, golden = {}, GOLDEN[(model_name, n)]
    elif model_name == "abd-ordered":
        model = _abd_ordered_lowered(depth=n)
        batch, table_log2 = 2048, 16
        run_kwargs = {"target_max_depth": n}
        s = model.closure_stats
        golden = (s["generated"], s["unique"])
    elif model_name == "paxos5s4c":
        model = _paxos5s4c_lowered(depth=n)
        batch, table_log2 = 4096, 19
        run_kwargs = {"target_max_depth": n}
        s = model.closure_stats
        golden = (s["generated"], s["unique"])
    else:
        raise ValueError(f"unknown workload {model_name!r}")
    # BENCH_STORE=tiered: race the two-tier state store (device hot set +
    # host spill tier) on every workload; BENCH_HIGH_WATER /
    # BENCH_SUMMARY_LOG2 tune it. Malformed values fall back to the store
    # defaults — an observability knob must never kill the bench — but an
    # unknown store NAME is called out loudly: a typo'd value silently
    # benching the device store would cost tunnel day exactly the spill
    # rows the env var exists for (same policy as unknown bench flags).
    bench_store = os.environ.get("BENCH_STORE", "")
    if bench_store and bench_store not in ("device", "tiered"):
        log(f"unknown BENCH_STORE {bench_store!r} ignored "
            "(known: device | tiered)")
    if bench_store == "tiered":
        engine_kwargs["store"] = "tiered"
        try:
            engine_kwargs["high_water"] = float(
                os.environ.get("BENCH_HIGH_WATER", "0.85")
            )
        except ValueError:
            pass
        try:
            engine_kwargs["summary_log2"] = int(
                os.environ.get("BENCH_SUMMARY_LOG2", "20")
            )
        except ValueError:
            pass
    return (
        model, batch, table_log2, run_kwargs, engine_kwargs, golden,
        time.monotonic() - t0,
    )


def _parity_err(model_name, n, result, golden):
    if golden is None:
        return None
    if (result.state_count, result.unique_state_count) != golden:
        return (
            f"{model_name}-{n} parity failure: device "
            f"(gen={result.state_count}, "
            f"unique={result.unique_state_count}) != "
            f"golden (gen={golden[0]}, unique={golden[1]})"
        )
    return None


def _time_search(search, run_kwargs, repeats: int, closure_s: float):
    """Shared timing protocol: one compile/warm-up run, then best-of-N.

    Chunked runs (a `budget` in run_kwargs) keep a carry across `run()`
    calls — without a reset, a completed search would make every repeat a
    no-op resume reporting near-zero duration (the 2pc-10 worker once
    "measured" 12 billion states/s that way). Fresh-start every repeat;
    whole-search engines ignore the reset."""
    t0 = time.monotonic()
    warm = search.run(**run_kwargs)  # compile + warm-up
    compile_s = time.monotonic() - t0
    # Long workloads get best-of-1: a ~15-min search repeated 3x would blow
    # the per-workload subprocess timeout for no extra signal.
    if warm.duration > 120:
        repeats = 1
    best = None
    for _ in range(repeats):
        if hasattr(search, "reset"):
            search.reset()
        r = search.run(**run_kwargs)
        if best is None or r.duration < best.duration:
            best = r
    out = {
        "states": best.state_count,
        "unique": best.unique_state_count,
        "sec": round(best.duration, 4),
        "states_per_sec": best.state_count / max(best.duration, 1e-9),
        "compile_sec": round(compile_s, 1),
    }
    if closure_s > 1.0:
        out["closure_sec"] = round(closure_s, 1)
    return best, out


def _attach_roofline(out: dict, best, model, batch: int, table_log2: int,
                     search) -> None:
    """Cost-model utilization fields (VERDICT r5 #6): bytes touched per
    generated state from tensor/costmodel.py, and the effective-HBM
    fraction (the MFU analogue) when the run was on real accelerator HBM.
    CPU-backend rehearsals get the byte count as `cpu_bytes_per_state`
    instead — the model's CPU *times* are low-confidence, its bytes exact.
    """
    try:
        import jax

        from stateright_tpu.tensor import costmodel as cm

        layout = getattr(search, "table_layout", "split")
        insert_variant = getattr(search, "insert_variant", "sort")
        variant = cm.ENGINE_VARIANTS.get((layout, insert_variant), "split")
        states_per_step = best.state_count / max(best.steps, 1)
        # new_frac: populated-lane fraction of B = generated-per-step over
        # the flat successor lane count — what the capped path tiles over.
        B = batch * model.max_actions
        new_frac = min(states_per_step / B, 1.0)
        bps = cm.bytes_per_state(
            model.lanes, model.max_actions, batch, table_log2,
            states_per_step,
            variant=variant,
            append=getattr(search, "append", "dus"),
            new_frac=new_frac,
        )
        out["bytes_per_state"] = round(bps, 1)
        if jax.default_backend() == "cpu":
            out["cpu_bytes_per_state"] = out["bytes_per_state"]
        else:
            out["hbm_frac"] = round(
                cm.hbm_frac(out["states_per_sec"], bps, cm.V5E), 5
            )
    except Exception as e:  # noqa: BLE001 — reporting must never kill a run
        log(f"roofline annotation failed: {e}")


def device_search(model_name: str, n: int, repeats: int = 3):
    """Run the resident engine; returns (result dict, parity error or None)."""
    _pin_platform()
    from stateright_tpu.tensor.resident import ResidentSearch

    model, batch, table_log2, run_kwargs, engine_kwargs, golden, closure_s = (
        _build_workload(model_name, n)
    )
    search = ResidentSearch(
        model, batch_size=batch, table_log2=table_log2, **engine_kwargs
    )
    best, out = _time_search(search, run_kwargs, repeats, closure_s)
    _attach_roofline(out, best, model, batch, table_log2, search)
    _attach_store_stats(out, search)
    _attach_telemetry(out, best)
    return out, _parity_err(model_name, n, best, golden)


def _attach_telemetry(out: dict, best) -> None:
    """Step-telemetry digest (obs/ring.py) in the bench row — lane
    utilization, fill trajectory, step-time percentiles ride in
    detail.device so every BENCH_r*.json can answer "where did the step
    budget go" without a rerun."""
    try:
        if best.detail and "telemetry" in best.detail:
            out["telemetry"] = best.detail["telemetry"]
    except Exception as e:  # noqa: BLE001 — reporting must never kill a run
        log(f"telemetry annotation failed: {e}")


def device_search_obs(model_name: str, n: int):
    """BENCH_OBS=1 row: the r4 anchor workload run twice on the resident
    engine — telemetry OFF then telemetry ON — proving the ring buffer's
    overhead on the pinned row (acceptance: <= 2% step time; the ring adds
    no per-step host sync, so the delta is one ~32-byte in-loop scatter).
    Returns (result dict for the telemetry-ON run plus `sec_off` and
    `telemetry_overhead_pct`, parity error or None)."""
    _pin_platform()
    from stateright_tpu.tensor.resident import ResidentSearch

    model, batch, table_log2, run_kwargs, engine_kwargs, golden, closure_s = (
        _build_workload(model_name, n)
    )
    runs = {}
    for telemetry in (False, True):
        search = ResidentSearch(
            model, batch_size=batch, table_log2=table_log2,
            telemetry=telemetry, **engine_kwargs,
        )
        best, out = _time_search(search, run_kwargs, repeats=2,
                                 closure_s=closure_s)
        runs[telemetry] = (best, out)
    best_on, out = runs[True]
    _attach_telemetry(out, best_on)
    sec_off = runs[False][1]["sec"]
    out["sec_off"] = sec_off
    out["telemetry_overhead_pct"] = round(
        100.0 * (out["sec"] - sec_off) / max(sec_off, 1e-9), 2
    )
    perr = _parity_err(model_name, n, best_on, golden) or _parity_err(
        model_name, n, runs[False][0], golden
    )
    return out, perr


def device_search_calib(model_name: str, n: int):
    """BENCH_CALIB=1 row: the 2pc-4 anchor run twice on the resident
    engine — calibration comparator OFF (SR_TPU_CALIB=0) then ON —
    proving the measured-vs-predicted join's overhead on the pinned row
    (host arithmetic at chunk granularity, no device work; acceptance:
    within noise). The ON run's `detail.calib` digest (predicted vs
    measured ms, drift ratio, per-term attribution) rides in the row."""
    _pin_platform()
    from stateright_tpu.tensor.resident import ResidentSearch

    model, batch, table_log2, run_kwargs, engine_kwargs, golden, closure_s = (
        _build_workload(model_name, n)
    )
    runs = {}
    try:
        for enabled in (False, True):
            os.environ["SR_TPU_CALIB"] = "1" if enabled else "0"
            search = ResidentSearch(
                model, batch_size=batch, table_log2=table_log2,
                telemetry=True, **engine_kwargs,
            )
            best, out = _time_search(search, run_kwargs, repeats=2,
                                     closure_s=closure_s)
            runs[enabled] = (best, out)
    finally:
        os.environ.pop("SR_TPU_CALIB", None)
    best_on, out = runs[True]
    _attach_telemetry(out, best_on)
    if best_on.detail and "calib" in best_on.detail:
        out["calib"] = best_on.detail["calib"]
    sec_off = runs[False][1]["sec"]
    out["sec_off"] = sec_off
    out["calib_overhead_pct"] = round(
        100.0 * (out["sec"] - sec_off) / max(sec_off, 1e-9), 2
    )
    perr = _parity_err(model_name, n, best_on, golden) or _parity_err(
        model_name, n, runs[False][0], golden
    )
    return out, perr


def device_search_journal(model_name: str, n: int):
    """BENCH_OBS=1 journal sub-row: the anchor workload through a
    foreground CheckService twice — flight recorder OFF then ON
    (events_out= JSONL journal, obs/events.py) — pricing the journal's
    per-step emit + bounded-flush cost on the service path where it
    actually runs (acceptance: <= 5%, expected within noise: one dict +
    one buffered JSON line per fused step and per job transition).
    Cold-vs-cold like the faults row: each side builds a fresh service
    (fresh jit closures), best-of-2. Returns (result dict for the
    journal-ON run plus `sec_journal_off`, `journal_overhead_pct`, and
    the recorded `journal_events` count, parity error or None)."""
    _pin_platform()
    import tempfile

    from stateright_tpu.obs.events import read_journal
    from stateright_tpu.service import CheckService

    model, batch, table_log2, run_kwargs, engine_kwargs, golden, closure_s = (
        _build_workload(model_name, n)
    )
    svc_kw = {
        k: v for k, v in engine_kwargs.items()
        if k in ("store", "high_water", "summary_log2")
    }
    runs = {}
    journal_events = 0
    with tempfile.TemporaryDirectory(prefix="srtpu-bench-journal-") as td:
        for journal in (False, True):
            best, best_sec = None, None
            for rep in range(2):
                jpath = os.path.join(td, f"j{rep}.jsonl")
                extra = {"events_out": jpath} if journal else {}
                svc = CheckService(
                    batch_size=batch, table_log2=table_log2,
                    background=False, **svc_kw, **extra,
                )
                try:
                    t0 = time.monotonic()
                    h = svc.submit(model, **{
                        k: v for k, v in run_kwargs.items()
                        if k in ("target_state_count", "target_max_depth")
                    })
                    svc.drain()
                    r = h.result()
                    sec = time.monotonic() - t0
                finally:
                    svc.close()
                if best_sec is None or sec < best_sec:
                    best, best_sec = r, sec
                if journal:
                    journal_events = max(
                        journal_events, len(read_journal(jpath))
                    )
            runs[journal] = (best, best_sec)
    best_on, sec_on = runs[True]
    sec_off = runs[False][1]
    out = {
        "states": best_on.state_count,
        "unique": best_on.unique_state_count,
        "sec": round(sec_on, 4),
        "states_per_sec": best_on.state_count / max(sec_on, 1e-9),
        "sec_journal_off": round(sec_off, 4),
        "journal_overhead_pct": round(
            100.0 * (sec_on - sec_off) / max(sec_off, 1e-9), 2
        ),
        "journal_events": journal_events,
    }
    perr = _parity_err(model_name, n, best_on, golden) or _parity_err(
        model_name, n, runs[False][0], golden
    )
    return out, perr


def device_search_pallas(model_name: str, n: int):
    """BENCH_PALLAS=1 row: the anchor workload run twice on the resident
    engine — insert_variant="capped" (the r6 winner) then "pallas" (the
    SURVEY §7 end-state kernel, ROADMAP item 2) — the insert-design A/B.
    On CPU images the kernel runs under Pallas interpret mode, so this
    number prices plumbing and parity, not the silicon bet; the committed
    pre-hardware ranking lives in tensor/costmodel.py (predict_ranking)
    and ROUND12_NOTES.md. Returns (result dict for the PALLAS run plus
    `sec_capped` and the `pallas_vs_capped` speed ratio, parity error or
    None)."""
    _pin_platform()
    from stateright_tpu.tensor.resident import ResidentSearch

    model, batch, table_log2, run_kwargs, engine_kwargs, golden, closure_s = (
        _build_workload(model_name, n)
    )
    runs = {}
    search_p = None
    for variant in ("capped", "pallas"):
        search = ResidentSearch(
            model, batch_size=batch, table_log2=table_log2,
            insert_variant=variant, **engine_kwargs,
        )
        best, out = _time_search(search, run_kwargs, repeats=2,
                                 closure_s=closure_s)
        runs[variant] = (best, out)
        if variant == "pallas":
            search_p = search
        # The capped engine's table/queue buffers are dropped here, before
        # the pallas engine is built — keeping both alive would double
        # device memory pressure during the timed run at anchor sizes.
        del search
    best_p, out = runs["pallas"]
    _attach_roofline(out, best_p, model, batch, table_log2, search_p)
    sec_capped = runs["capped"][1]["sec"]
    out["sec_capped"] = sec_capped
    # >1 = pallas beats capped on this backend/workload.
    out["pallas_vs_capped"] = round(sec_capped / max(out["sec"], 1e-9), 3)
    perr = _parity_err(model_name, n, best_p, golden) or _parity_err(
        model_name, n, runs["capped"][0], golden
    )
    return out, perr


def device_search_faults(model_name: str, n: int):
    """BENCH_FAULTS=1 row: the anchor workload run twice — plain resident
    engine vs `run_supervised` with injection DISABLED — proving the
    supervisor's overhead (run slicing + periodic atomic checkpoints +
    watchdog plumbing) is within noise when nothing faults. Returns (result
    dict for the SUPERVISED run plus `sec_unsupervised`,
    `supervisor_overhead_pct`, and the `faults` recovery digest, parity
    error or None)."""
    _pin_platform()
    import os
    import shutil
    import tempfile

    from stateright_tpu.faults import FaultPlan, SupervisorConfig, run_supervised
    from stateright_tpu.tensor.resident import ResidentSearch

    model, batch, table_log2, run_kwargs, engine_kwargs, golden, closure_s = (
        _build_workload(model_name, n)
    )
    # Cold-vs-cold A/B: `run_supervised` necessarily builds a fresh engine
    # (per-instance jit closures recompile), so the plain side is timed the
    # same way — fresh instance, end-to-end including compile — or the
    # "overhead" would mostly be the compile asymmetry.
    plain_best = None
    plain_sec = None
    for _ in range(2):
        search = ResidentSearch(
            model, batch_size=batch, table_log2=table_log2, **engine_kwargs
        )
        t0 = time.monotonic()
        r = search.run(**run_kwargs)
        sec = time.monotonic() - t0 - closure_s
        if plain_sec is None or sec < plain_sec:
            plain_best, plain_sec = r, sec

    cfg = SupervisorConfig(checkpoint_every_steps=512)
    sup = None
    best_sec = None
    for rep in range(2):  # same best-of-N protocol as the plain run
        # Fresh checkpoint dir per rep: reusing one path would make rep 2
        # restore rep 1's FINAL generation and time a vacuous resume.
        ckpt_dir = tempfile.mkdtemp(prefix="bench_faults_")
        try:
            t0 = time.monotonic()
            sup = run_supervised(
                model,
                engine="resident",
                # Injection disabled: an EMPTY plan, not None — None falls
                # back to SR_TPU_FAULTS, and a leftover chaos env var must
                # not contaminate the overhead measurement.
                plan=FaultPlan(),
                config=cfg,
                checkpoint_path=os.path.join(ckpt_dir, "bench.ckpt.npz"),
                engine_kwargs=dict(
                    batch_size=batch, table_log2=table_log2, **engine_kwargs
                ),
                run_kwargs=run_kwargs,
            )
            sec = time.monotonic() - t0 - closure_s
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        if best_sec is None or sec < best_sec:
            best_sec = sec

    out = {
        "states": sup.state_count,
        "unique": sup.unique_state_count,
        "sec": round(best_sec, 4),
        "states_per_sec": sup.state_count / max(best_sec, 1e-9),
        "sec_unsupervised": round(plain_sec, 4),
        "supervisor_overhead_pct": round(
            100.0 * (best_sec - plain_sec) / max(plain_sec, 1e-9), 2
        ),
        "faults": sup.detail.get("faults", {}),
    }
    perr = _parity_err(model_name, n, sup, golden) or _parity_err(
        model_name, n, plain_best, golden
    )
    return out, perr


def _attach_store_stats(out: dict, search) -> None:
    """Per-tier occupancy counters in every artifact of a tiered run (the
    DEVICE_DETAIL_FIELDS tail); no-op on the plain device store."""
    try:
        stats = getattr(search, "store_stats", lambda: None)()
        if stats:
            for f in ("hot_fill", "spilled_states", "spill_events"):
                out[f] = stats[f]
    except Exception as e:  # noqa: BLE001 — reporting must never kill a run
        log(f"store-stats annotation failed: {e}")


def device_search_service(n_jobs: int = 8):
    """BENCH_SERVICE=1 row: throughput of a mixed job batch through the
    multi-job check service (one shared device table, continuous batching)
    vs the SAME jobs run serially on fresh standalone engines — the
    serving-layer A/B. Composition: 3x 2pc-3, 3x 2pc-4, 2x inclock-6.
    Returns (result dict, parity error or None); parity = every service
    job's counts equal its serial twin's."""
    _pin_platform()
    from stateright_tpu.service import CheckService
    from stateright_tpu.tensor.frontier import FrontierSearch
    from stateright_tpu.tensor.models import (
        TensorIncrementLock,
        TensorTwoPhaseSys,
    )

    m3, m4, mi = (
        TensorTwoPhaseSys(3), TensorTwoPhaseSys(4), TensorIncrementLock(6)
    )
    jobs = ([m3] * 3 + [m4] * 3 + [mi] * 2)[:n_jobs]

    # Serial reference: a fresh standalone engine per job — the deployment
    # story the service replaces (each engine compiles its own step and
    # owns the whole table for its run).
    t0 = time.monotonic()
    serial = []
    serial_steps = 0
    for m in jobs:
        fs = FrontierSearch(m, batch_size=1024, table_log2=16)
        r = fs.run()
        serial.append(r)
        serial_steps += r.steps
    serial_sec = time.monotonic() - t0

    t0 = time.monotonic()
    svc = CheckService(batch_size=1024, table_log2=18, background=False)
    handles = [svc.submit(m) for m in jobs]
    svc.drain()
    service_sec = time.monotonic() - t0
    results = [h.result() for h in handles]
    service_steps = svc.stats()["device_steps"]
    svc.close()

    err = None
    for i, (r, s) in enumerate(zip(results, serial)):
        got = (r.state_count, r.unique_state_count, r.max_depth)
        want = (s.state_count, s.unique_state_count, s.max_depth)
        # Full items comparison: the discovery FINGERPRINTS must survive
        # the salting round-trip bit-identically, not just the names.
        if got != want or sorted(r.discoveries.items()) != sorted(
            s.discoveries.items()
        ):
            err = (
                f"service parity failure on job {i}: {got} / "
                f"{sorted(r.discoveries.items())} != serial {want} / "
                f"{sorted(s.discoveries.items())}"
            )
            break
    states = sum(r.state_count for r in results)
    out = {
        "states": states,
        "unique": sum(r.unique_state_count for r in results),
        "sec": round(service_sec, 4),
        "states_per_sec": states / max(service_sec, 1e-9),
        "compile_sec": 0.0,  # compiles are inside both wall clocks (A/B fair)
        "n_jobs": len(jobs),
        "jobs_per_sec": round(len(jobs) / max(service_sec, 1e-9), 4),
        "serial_sec": round(serial_sec, 4),
        "vs_serial": round(serial_sec / max(service_sec, 1e-9), 3),
        "service_steps": service_steps,
        "serial_steps": serial_steps,
    }
    return out, err


def device_search_fleet(n_replicas: int = 3):
    """BENCH_FLEET=1 row: the mixed job set through an N-replica service
    fleet (consistent-hash router, work stealing) vs the SAME jobs through
    a 1-replica fleet — the scale-out A/B the ROADMAP item 1 acceptance
    names. Reports jobs/s, the N-vs-1 ratio, and the p50/p99 submit→result
    latency of the fleet run. Composition: 3x 2pc-3, 3x 2pc-4, 2x
    inclock-4. Parity = every fleet job's counts and discovery
    fingerprints equal its 1-replica twin's (bit-identical scale-out)."""
    _pin_platform()
    from stateright_tpu.service import ServiceFleet
    from stateright_tpu.tensor.models import (
        TensorIncrementLock,
        TensorTwoPhaseSys,
    )

    m3, m4, mi = (
        TensorTwoPhaseSys(3), TensorTwoPhaseSys(4), TensorIncrementLock(4)
    )
    jobs = [m3] * 3 + [m4] * 3 + [mi] * 2

    def run_fleet(n):
        fleet = ServiceFleet(
            n_replicas=n,
            background=True,
            max_resident=2,
            service_kwargs=dict(batch_size=1024, table_log2=17),
        )
        t0 = time.monotonic()
        handles = [fleet.submit(m) for m in jobs]
        fleet.drain(timeout=1800)
        sec = time.monotonic() - t0
        results = [h.result() for h in handles]
        lat_ms = sorted(
            (h._job.finished_at - h._job.submitted_at) * 1000.0
            for h in handles
        )
        stats = fleet.stats()
        fleet.close()
        return sec, results, lat_ms, stats

    one_sec, one_results, _one_lat, _ = run_fleet(1)
    sec, results, lat_ms, stats = run_fleet(n_replicas)

    err = None
    for i, (r, s) in enumerate(zip(results, one_results)):
        got = (r.state_count, r.unique_state_count, r.max_depth)
        want = (s.state_count, s.unique_state_count, s.max_depth)
        if got != want or sorted(r.discoveries.items()) != sorted(
            s.discoveries.items()
        ):
            err = (
                f"fleet parity failure on job {i}: {got} / "
                f"{sorted(r.discoveries.items())} != 1-replica {want} / "
                f"{sorted(s.discoveries.items())}"
            )
            break

    def pct(sorted_ms, q):
        return sorted_ms[min(int(q * (len(sorted_ms) - 1)), len(sorted_ms) - 1)]

    states = sum(r.state_count for r in results)
    out = {
        "states": states,
        "unique": sum(r.unique_state_count for r in results),
        "sec": round(sec, 4),
        "states_per_sec": states / max(sec, 1e-9),
        "compile_sec": 0.0,  # compiles inside both wall clocks (A/B fair)
        "n_replicas": n_replicas,
        "n_jobs": len(jobs),
        "fleet_jobs_per_sec": round(len(jobs) / max(sec, 1e-9), 4),
        "sec_one_replica": round(one_sec, 4),
        "vs_one_replica": round(one_sec / max(sec, 1e-9), 3),
        "fleet_p50_ms": round(pct(lat_ms, 0.50), 1),
        "fleet_p99_ms": round(pct(lat_ms, 0.99), 1),
        "fleet_steals": stats["steals"],
        "fleet_requeued": stats["requeued_jobs"],
    }
    return out, err


def device_search_autoscale(max_replicas: int = 3):
    """BENCH_AUTOSCALE=1 row: the autoscaler A/B (ISSUE 17). The SAME
    mixed job burst runs twice — once through a fleet pinned at 1 replica,
    once through a fleet that STARTS at 1 replica with an aggressive
    Autoscaler allowed up to `max_replicas` — and the row reports both
    throughputs, the ratio, the autoscaled run's p99, and the control
    loop's own evidence (replicas_high_water, scale_outs, scale_ins).
    Parity = every autoscaled job's counts and discovery fingerprints
    equal its fixed-1 twin's: scaling mid-burst must be invisible in the
    answers, only in the wall clock."""
    _pin_platform()
    from stateright_tpu.service import (
        AutoscaleConfig,
        Autoscaler,
        ServiceFleet,
    )
    from stateright_tpu.tensor.models import (
        TensorIncrementLock,
        TensorTwoPhaseSys,
    )

    m3, m4, mi = (
        TensorTwoPhaseSys(3), TensorTwoPhaseSys(4), TensorIncrementLock(4)
    )
    jobs = [m3] * 3 + [m4] * 3 + [mi] * 2

    def run(n_max, autoscale):
        fleet = ServiceFleet(
            n_replicas=1,
            background=True,
            max_resident=2,
            service_kwargs=dict(batch_size=1024, table_log2=17),
        )
        scaler = None
        if autoscale:
            # Aggressive bands: any queue is "over", one tick is enough,
            # short cooldown — the burst should force growth fast enough
            # to show up inside one bench row's wall clock.
            scaler = Autoscaler(fleet, AutoscaleConfig(
                min_replicas=1,
                max_replicas=n_max,
                queue_high=1.0,
                scale_out_after=1,
                scale_in_after=6,
                cooldown_ticks=2,
            ))
            scaler.start(interval_s=0.1)
        t0 = time.monotonic()
        handles = [fleet.submit(m) for m in jobs]
        fleet.drain(timeout=1800)
        sec = time.monotonic() - t0
        results = [h.result() for h in handles]
        lat_ms = sorted(
            (h._job.finished_at - h._job.submitted_at) * 1000.0
            for h in handles
        )
        counters = dict(scaler.counters) if scaler else {}
        if scaler is not None:
            scaler.close()
        fleet.close()
        return sec, results, lat_ms, counters

    fixed_sec, fixed_results, _fixed_lat, _ = run(1, autoscale=False)
    sec, results, lat_ms, counters = run(max_replicas, autoscale=True)

    err = None
    for i, (r, s) in enumerate(zip(results, fixed_results)):
        got = (r.state_count, r.unique_state_count, r.max_depth)
        want = (s.state_count, s.unique_state_count, s.max_depth)
        if got != want or sorted(r.discoveries.items()) != sorted(
            s.discoveries.items()
        ):
            err = (
                f"autoscale parity failure on job {i}: {got} / "
                f"{sorted(r.discoveries.items())} != fixed-1 {want} / "
                f"{sorted(s.discoveries.items())}"
            )
            break

    def pct(sorted_ms, q):
        return sorted_ms[min(int(q * (len(sorted_ms) - 1)), len(sorted_ms) - 1)]

    states = sum(r.state_count for r in results)
    out = {
        "states": states,
        "unique": sum(r.unique_state_count for r in results),
        "sec": round(sec, 4),
        "states_per_sec": states / max(sec, 1e-9),
        "compile_sec": 0.0,  # compiles inside both wall clocks (A/B fair)
        "n_jobs": len(jobs),
        "auto_max_replicas": max_replicas,
        "auto_jobs_per_sec": round(len(jobs) / max(sec, 1e-9), 4),
        "auto_p50_ms": round(pct(lat_ms, 0.50), 1),
        "auto_p99_ms": round(pct(lat_ms, 0.99), 1),
        "auto_replicas_high_water": counters.get("replicas_high_water", 0),
        "auto_scale_outs": counters.get("scale_outs", 0),
        "auto_scale_ins": counters.get("scale_ins", 0),
        "sec_fixed_one": round(fixed_sec, 4),
        "vs_fixed_one": round(fixed_sec / max(sec, 1e-9), 3),
    }
    return out, err


def device_search_blob(n_replicas: int = 2):
    """BENCH_BLOB=1 row: local-vs-wire checkpoint-backend overhead A/B
    (ISSUE 15, managed dialects ISSUE 20). The SAME mixed job set runs
    through an N-replica in-proc fleet once per backend —
    requeue-resume checkpoint plane + lease fence on a local directory,
    then on the in-proc blob emulator (faults/blobstore.py: HTTP
    conditional puts, bounded retry, CRC'd generations), then on the
    s3 and gcs managed-dialect emulators (faults/blobdialect.py:
    SigV4 / OAuth-bearer signing plus the credential chain per op) —
    and each measured overhead lands next to that backend client's own
    op/retry counters. Parity = every wire-side job's counts and
    discoveries equal its local twin's (the backend must be
    bit-identical, only slower by the wire + signing)."""
    _pin_platform()
    from stateright_tpu.faults.blobstore import serve_blobd, uri_client
    from stateright_tpu.service import ServiceFleet
    from stateright_tpu.tensor.models import (
        TensorIncrementLock,
        TensorTwoPhaseSys,
    )

    m3, mi = TensorTwoPhaseSys(3), TensorIncrementLock(4)
    jobs = [m3] * 4 + [mi] * 2

    def run(fleet_kw):
        fleet = ServiceFleet(
            n_replicas=n_replicas,
            background=True,
            max_resident=2,
            service_kwargs=dict(batch_size=1024, table_log2=17),
            **fleet_kw,
        )
        t0 = time.monotonic()
        handles = [fleet.submit(m) for m in jobs]
        fleet.drain(timeout=1800)
        sec = time.monotonic() - t0
        results = [h.result() for h in handles]
        fleet.close()
        return sec, results

    def run_wire(dialect):
        """One timed leg on an in-proc wire backend: the native blob
        emulator or an s3/gcs managed dialect (whose endpoint +
        credential environment is installed for the leg's duration —
        the fleet is in-proc, so os.environ is the live config)."""
        srv = serve_blobd(dialect=dialect)
        saved = {k: os.environ.get(k) for k in srv.env}
        os.environ.update(srv.env)
        root = srv.root_uri + "/bench"
        try:
            sec, results = run(
                {"ckpt_dir": root + "/ckpt", "lease_dir": root + "/leases"}
            )
            client, _name = uri_client(root)
            counters = dict(client.counters)
        finally:
            for key, old in saved.items():
                if old is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = old
            srv.shutdown()
        return sec, results, counters

    run({})  # untimed warm-up: compiles land here, not in any timed leg
    sec_local, local_results = run({})
    sec_blob, blob_results, blob_counters = run_wire("blob")
    sec_s3, s3_results, s3_counters = run_wire("s3")
    sec_gcs, gcs_results, gcs_counters = run_wire("gcs")

    err = None
    for leg, results in (
        ("blob", blob_results), ("s3", s3_results), ("gcs", gcs_results)
    ):
        for i, (r, s) in enumerate(zip(results, local_results)):
            got = (r.state_count, r.unique_state_count, r.max_depth)
            want = (s.state_count, s.unique_state_count, s.max_depth)
            if got != want or sorted(r.discoveries.items()) != sorted(
                s.discoveries.items()
            ):
                err = (
                    f"{leg}-backend parity failure on job {i}: "
                    f"{got} != {want}"
                )
                break
        if err is not None:
            break

    def overhead_pct(sec):
        return round((sec - sec_local) / max(sec_local, 1e-9) * 100.0, 2)

    states = sum(r.state_count for r in blob_results)
    out = {
        "states": states,
        "unique": sum(r.unique_state_count for r in blob_results),
        "sec": round(sec_blob, 4),
        "states_per_sec": states / max(sec_blob, 1e-9),
        "compile_sec": 0.0,  # compiles paid by the untimed warm-up run
        "n_replicas": n_replicas,
        "n_jobs": len(jobs),
        "sec_local_fs": round(sec_local, 4),
        "blob_overhead_pct": overhead_pct(sec_blob),
        "blob_ops": int(blob_counters.get("ops", 0)),
        "blob_retries": int(blob_counters.get("retries", 0)),
        "sec_s3": round(sec_s3, 4),
        "s3_overhead_pct": overhead_pct(sec_s3),
        "s3_ops": int(s3_counters.get("ops", 0)),
        "s3_retries": int(s3_counters.get("retries", 0)),
        "sec_gcs": round(sec_gcs, 4),
        "gcs_overhead_pct": overhead_pct(sec_gcs),
        "gcs_ops": int(gcs_counters.get("ops", 0)),
        "gcs_retries": int(gcs_counters.get("retries", 0)),
    }
    return out, err


def device_search_semantics(model_name: str = "single_copy", n: int = 6):
    """BENCH_SEMANTICS=1 row: cold-vs-optimized A/B of the dedup-first
    verdict plane (semantics/canonical.py + batch.py) on a register-model
    anchor's PROPERTY-EVALUATION phase. The anchor is the single-copy
    register with n clients / 2 servers (the not-linearizable config, so
    most verdicts are the expensive exhaustive refutations), its first 6000
    DFS states' history testers — the post-dedup batch a checker block
    hands the plane. Side A evaluates every tester through the pre-PR
    cache-only path (canonical plane disabled, per-identity lru memo only);
    side B clears all caches and runs ONE batched plane call (canonical
    collapse + witness guidance + native-parallel search). Acceptance:
    >= 2x wall-clock with bit-identical verdict booleans."""
    _pin_platform()
    from stateright_tpu.actor import Network
    from stateright_tpu.examples.single_copy_register import (
        SingleCopyModelCfg,
    )
    from stateright_tpu.semantics import (
        canonical,
        clear_serialization_caches,
    )
    from stateright_tpu.semantics.batch import evaluate_batch

    model = SingleCopyModelCfg(
        client_count=n,
        server_count=2,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()

    # The anchor's post-dedup testers, depth-first (shared enumerator —
    # the smoke script measures the same batch shape).
    from stateright_tpu.semantics.batch import collect_history_testers

    testers, n_unique = collect_history_testers(model, 6000)

    # Side A: the pre-PR cache-only path (per-identity lru memo, fresh).
    clear_serialization_caches()
    prev = canonical.set_enabled(False)
    t0 = time.monotonic()
    legacy = [t.serialized_history() is not None for t in testers]
    sec_legacy = time.monotonic() - t0
    canonical.set_enabled(prev)

    # Side B: the dedup-first plane, cold (both caches cleared).
    clear_serialization_caches()
    counters0 = dict(canonical.CACHE.counters)
    t0 = time.monotonic()
    optimized = evaluate_batch(testers)
    sec = time.monotonic() - t0
    stats = canonical.CACHE.stats()
    delta = {
        k: stats[k] - counters0.get(k, 0)
        for k in (
            "canonical_collapsed", "witness_guided_hits", "full_searches",
            "batch_parallel_evals",
        )
    }

    err = None
    if optimized != legacy:
        err = "semantics parity failure: plane verdicts != cache-only verdicts"
    speedup = round(sec_legacy / max(sec, 1e-9), 2)
    if err is None and speedup < 2.0:
        # The acceptance bar is part of the row contract, not just prose.
        err = (
            f"dedup-first plane only {speedup}x faster than the cache-only "
            "path (acceptance >= 2x)"
        )

    out = {
        "states": len(testers),
        "unique": n_unique,
        "sec": round(sec, 4),
        "states_per_sec": len(testers) / max(sec, 1e-9),
        "compile_sec": 0.0,  # host-only phase: nothing compiles
        "sec_legacy": round(sec_legacy, 4),
        "semantics_speedup": speedup,
        "verdict_negatives": int(legacy.count(False)),
        "canonical_collapsed": int(delta["canonical_collapsed"]),
        "witness_guided_hits": int(delta["witness_guided_hits"]),
        "full_searches": int(delta["full_searches"]),
        "batch_parallel_evals": int(delta["batch_parallel_evals"]),
    }
    return out, err


def device_search_simulation(model_name: str = "2pc", n: int = 3):
    """BENCH_SIM=1 row: cold A/B of the fourth checker mode on the 2pc-3
    anchor (CPU rehearsal) — the host thread-pool `SimulationChecker` vs
    the device walk engine (tensor/simulation.py), both running random
    walks until the same generated-state budget. Walks are counted on the
    host side by a counting chooser (`new_state` fires once per trace) and
    on the device side by the engine's own telemetry; both sides exclude
    compile (the device side times rounds 2+ of one engine — continuous
    batching makes those steady-state). Acceptance: device >= 2x host
    walks/s, identical property verdicts on the anchor (abort agreement
    found, safety never violated), and same-seed device runs bit-identical
    (counts + discoveries)."""
    _pin_platform()
    from stateright_tpu.checker.simulation import UniformChooser
    from stateright_tpu.examples.two_phase_commit import TwoPhaseSys
    from stateright_tpu.tensor.models import TensorTwoPhaseSys
    from stateright_tpu.tensor.simulation import DeviceSimulation

    target = 60_000

    class CountingChooser(UniformChooser):
        def __init__(self):
            self.walks = 0

        def new_state(self, seed):
            self.walks += 1
            return super().new_state(seed)

    chooser = CountingChooser()
    t0 = time.monotonic()
    host = (
        TwoPhaseSys(n)
        .checker()
        .target_state_count(target)
        .spawn_simulation(seed=0, chooser=chooser)
        .join()
    )
    sec_host = time.monotonic() - t0
    host_walks = chooser.walks
    host_states = host.state_count()
    host_found = set(host.discoveries())

    def fresh():
        return DeviceSimulation(
            TensorTwoPhaseSys(n), seed=0, traces=1024, max_depth=64,
            dedup="shared", table_log2=18,
        )

    def measure(sim):
        """Round 1 absorbs the compile; time rounds 2+ to the same state
        budget (continuous batching makes every round steady-state)."""
        r = sim.run()
        base_states, base_walks = r.state_count, sim._totals["walks"]
        t0 = time.monotonic()
        while r.state_count - base_states < target:
            r = sim.run()
        sec = time.monotonic() - t0
        return (
            r,
            sec,
            r.state_count - base_states,
            sim._totals["walks"] - base_walks,
        )

    sim = fresh()
    r, sec, dev_states, dev_walks = measure(sim)
    sim_b = fresh()
    r_b, _sec_b, dev_states_b, dev_walks_b = measure(sim_b)

    tel = r.detail["telemetry"]
    host_wps = host_walks / max(sec_host, 1e-9)
    dev_wps = dev_walks / max(sec, 1e-9)
    speedup = round(dev_wps / max(host_wps, 1e-9), 2)

    err = None
    if (dev_states, dev_walks, r.unique_state_count, sorted(r.discoveries)) \
            != (dev_states_b, dev_walks_b, r_b.unique_state_count,
                sorted(r_b.discoveries)):
        err = "simulation determinism failure: same-seed runs differ"
    dev_found = set(r.discoveries)
    for found, side in ((host_found, "host"), (dev_found, "device")):
        if err is None and "abort agreement" not in found:
            err = f"simulation verdict failure: {side} missed abort agreement"
        if err is None and "consistent" in found:
            err = f"simulation verdict failure: {side} violated safety"
    if err is None and speedup < 2.0:
        # The acceptance bar is part of the row contract, not just prose.
        err = (
            f"device simulation only {speedup}x host walks/s "
            "(acceptance >= 2x)"
        )

    out = {
        "states": dev_states,
        "unique": r.unique_state_count,
        "sec": round(sec, 4),
        "states_per_sec": dev_states / max(sec, 1e-9),
        "compile_sec": 0.0,  # both sides measured post-compile (A/B fair)
        "sec_host_sim": round(sec_host, 4),
        "host_states_per_sec": round(host_states / max(sec_host, 1e-9), 1),
        "sim_walks_per_sec": round(dev_wps, 1),
        "host_walks_per_sec": round(host_wps, 1),
        "sim_speedup": speedup,
        "sim_lane_util": tel["lane_util"],
        "sim_restarts": tel["restarts"],
        "sim_dedup_hit_rate": tel["dedup_hit_rate"],
        "sim_bit_identical": err is None or "determinism" not in err,
    }
    return out, err


def device_search_corpus(model_name: str = "2pc", n: int = 4):
    """BENCH_CORPUS=1 row: cold-vs-warm A/B of the cross-job warm-start
    corpus (store/corpus.py, ROADMAP item 4). Two tiered services with a
    pre-compiled step each (a throwaway first submission absorbs the
    compile on BOTH sides, so the ratio is pure search time): the cold
    side re-explores the anchor from scratch; the corpus side's first
    submission published the visited set, so its measured submission
    preloads the entry and completes warm. Acceptance: warm >= 5x faster,
    results bit-identical, and a corrupted entry (one flipped byte) is
    detected by the ckptio CRC and ignored — the third submission runs
    cold and still completes correctly.

    Corpus v2 edit-warm sub-rows: `warm_speedup_near` re-checks the same
    definition under a RETUNED lowering (table_log2 + 1) — the family
    index serves the published set through the near rung; and
    `warm_speedup_partial` cancels a run past half the space — the cut
    publishes the visited prefix + frontier snapshot and the successor
    continues from it. Both must be >= 2x over their post-compile cold
    reference with bit-identical results."""
    _pin_platform()
    import tempfile

    from stateright_tpu.service import CheckService

    model, _batch, _tl2, _run_kwargs, _ekw, golden, _cs = _build_workload(
        model_name, n
    )
    svc_kw = dict(
        batch_size=1024,
        table_log2=18,
        store="tiered",
        high_water=0.9,
        summary_log2=18,
        background=False,
    )

    def timed_submit(svc, **opts):
        t0 = time.monotonic()
        h = svc.submit(model, **opts)
        svc.drain(timeout=1800)
        return time.monotonic() - t0, h.result()

    # Cold reference: corpus-less service, post-compile second submission.
    cold_svc = CheckService(**svc_kw)
    timed_submit(cold_svc)  # compile warm-up (timing discarded)
    cold_sec, cold_r = timed_submit(cold_svc)
    cold_svc.close()

    with tempfile.TemporaryDirectory(prefix="srtpu-corpus-") as corpus_dir:
        warm_svc = CheckService(corpus_dir=corpus_dir, **svc_kw)
        timed_submit(warm_svc)  # compile warm-up + corpus publish
        warm_sec, warm_r = timed_submit(warm_svc)
        warm_corpus = dict(warm_r.detail.get("corpus") or {})

        # Satellite: flip one payload byte in the published entry — the
        # CRC footer must catch it and the next submission must complete
        # correctly COLD (never wrong results). (The directory also holds
        # the v2 family index; target the ENTRY generation specifically.)
        import glob as _glob

        from stateright_tpu.faults.ckptio import corrupt_one_byte

        corrupt_one_byte(
            [
                p
                for p in _glob.glob(os.path.join(corpus_dir, "corpus-*.npz"))
                if "-family-" not in os.path.basename(p)
                and "-spec-" not in os.path.basename(p)
            ][0]
        )
        _sec3, third_r = timed_submit(warm_svc)
        stats = warm_svc.stats()
        corrupt_detected = stats.get("corpus", {}).get(
            "corrupt_entries", 0
        ) >= 1
        warm_svc.close()

        # -- corpus v2 edit-warm A/B: the NEAR rung ------------------------
        # Same definition, retuned lowering (table_log2 + 1): the retuned
        # key misses the exact rung, and the family index serves the
        # published set for a delta-proportional (here: replay) re-check.
        # Submissions with a huge target_state_count carry a different
        # finish signature, so they absorb compile and give the retuned
        # cold reference WITHOUT ever publishing a near-replayable member.
        near_kw = dict(svc_kw, table_log2=svc_kw["table_log2"] + 1)
        near_svc = CheckService(corpus_dir=corpus_dir, **near_kw)
        big = 1 << 40
        timed_submit(near_svc, target_state_count=big)  # compile warm-up
        cold_near_sec, _ = timed_submit(near_svc, target_state_count=big + 1)
        warm_near_sec, near_r = timed_submit(near_svc)
        near_corpus = dict(near_r.detail.get("corpus") or {})
        near_svc.close()

    # -- corpus v2 edit-warm A/B: the PARTIAL rung -------------------------
    # A mid-run cancel publishes the visited prefix + frontier snapshot;
    # the successor continues from the cut instead of starting over. The
    # cut lands past two thirds of the space so the continuation's win is
    # the prefix it skips (cold reference: the post-compile cold_sec
    # above) with headroom over the preload/pump overhead.
    with tempfile.TemporaryDirectory(prefix="srtpu-corpus-p-") as pdir:
        part_svc = CheckService(corpus_dir=pdir, **svc_kw)
        hp = part_svc.submit(model)
        cut = 2 * (golden[0] if golden else 1 << 20) // 3
        while part_svc.pump() and hp._job.state_count < cut:
            pass
        hp.cancel()
        warm_part_sec, part_r = timed_submit(part_svc)
        part_corpus = dict(part_r.detail.get("corpus") or {})
        part_svc.close()

    err = None
    for name, r in (
        ("warm", warm_r),
        ("corrupt-cold", third_r),
        ("near-warm", near_r),
        ("partial-warm", part_r),
    ):
        got = (r.state_count, r.unique_state_count, r.max_depth)
        want = (
            cold_r.state_count, cold_r.unique_state_count, cold_r.max_depth,
        )
        if got != want or sorted(r.discoveries.items()) != sorted(
            cold_r.discoveries.items()
        ):
            err = (
                f"corpus parity failure ({name}): {got} / "
                f"{sorted(r.discoveries.items())} != cold {want} / "
                f"{sorted(cold_r.discoveries.items())}"
            )
            break
    if err is None and golden is not None and (
        warm_r.state_count, warm_r.unique_state_count,
    ) != golden:
        err = (
            f"corpus golden failure: "
            f"{(warm_r.state_count, warm_r.unique_state_count)} != {golden}"
        )
    if err is None and not warm_corpus.get("warm_start"):
        err = "corpus warm submission did not take the warm path"
    if err is None and not corrupt_detected:
        err = "corrupted corpus entry was not detected by the CRC check"
    warm_speedup = round(cold_sec / max(warm_sec, 1e-9), 2)
    if err is None and warm_speedup < 5.0:
        # The acceptance bar is part of the row contract, not just prose.
        err = (
            f"warm submission only {warm_speedup}x faster than cold "
            "(acceptance >= 5x)"
        )
    warm_speedup_near = round(cold_near_sec / max(warm_near_sec, 1e-9), 2)
    warm_speedup_partial = round(cold_sec / max(warm_part_sec, 1e-9), 2)
    if err is None and near_corpus.get("warm_kind") != "near":
        err = (
            "retuned submission did not take the near rung "
            f"(detail: {near_corpus})"
        )
    if err is None and part_corpus.get("warm_kind") != "partial":
        err = (
            "post-cut submission did not take the partial rung "
            f"(detail: {part_corpus})"
        )
    if err is None and warm_speedup_near < 2.0:
        err = (
            f"near-warm submission only {warm_speedup_near}x faster than "
            "cold (acceptance >= 2x)"
        )
    if err is None and warm_speedup_partial < 2.0:
        err = (
            f"partial-warm submission only {warm_speedup_partial}x faster "
            "than cold (acceptance >= 2x)"
        )

    out = {
        "states": warm_r.state_count,
        "unique": warm_r.unique_state_count,
        "sec": round(warm_sec, 4),
        "states_per_sec": warm_r.state_count / max(warm_sec, 1e-9),
        "compile_sec": 0.0,  # both sides measured post-compile (A/B fair)
        "sec_cold": round(cold_sec, 4),
        "warm_speedup": warm_speedup,
        "warm_speedup_near": warm_speedup_near,
        "warm_speedup_partial": warm_speedup_partial,
        "corpus_preloaded": int(warm_corpus.get("preloaded_states", 0)),
        "corrupt_detected": corrupt_detected,
    }
    return out, err


def device_search_delta(model_name: str = "2pc", n: int = 4):
    """BENCH_DELTA=1 row: Spec-CI definition-delta A/B on the anchor —
    cold exploration of a property-EDITED model vs the same edited model
    served from the corpus on the "delta" rung (store/specdelta.py). The
    corpus side first publishes the base model's visited set, then
    submits an edited model whose first property condition is negated
    (class name preserved, so the geometry digest keeps it in the same
    spec family); the delta classifier names the edit "properties-only"
    and replays the published set with only the changed verdict
    re-evaluated. Acceptance: rung == "delta", class == properties-only,
    counts bit-identical to the edited model's own cold run, the edited
    property's discovery present, and >= 2x over the post-compile cold
    reference."""
    _pin_platform()
    import dataclasses
    import tempfile

    from stateright_tpu.service import CheckService

    model, _batch, _tl2, _run_kwargs, _ekw, _golden, _cs = _build_workload(
        model_name, n
    )
    svc_kw = dict(
        batch_size=1024,
        table_log2=18,
        store="tiered",
        high_water=0.9,
        summary_log2=18,
        background=False,
    )

    # The one-line edit: negate the first property's condition. The
    # subclass keeps the base class's NAME — the geometry digest includes
    # it, and a renamed model is a different spec family, not an edit.
    base_cls = type(model)

    def _edited_props(self, _base=base_cls):
        props = list(_base.properties(self))
        p0 = props[0]
        props[0] = dataclasses.replace(
            p0,
            name=p0.name + " negated",
            condition=lambda m, s, _c=p0.condition: ~_c(m, s),
        )
        return props

    edited_cls = type(
        base_cls.__name__, (base_cls,), {"properties": _edited_props}
    )
    edited = edited_cls(
        **{f.name: getattr(model, f.name)
           for f in dataclasses.fields(model)}
    )

    def timed_submit(svc, m):
        t0 = time.monotonic()
        h = svc.submit(m)
        svc.drain(timeout=1800)
        return time.monotonic() - t0, h.result()

    # Cold reference: corpus-less service, post-compile second submission
    # of the EDITED model (the delta rung's counts must match this).
    cold_svc = CheckService(**svc_kw)
    timed_submit(cold_svc, edited)  # compile warm-up (timing discarded)
    cold_sec, cold_r = timed_submit(cold_svc, edited)
    cold_svc.close()

    with tempfile.TemporaryDirectory(prefix="srtpu-delta-") as corpus_dir:
        svc = CheckService(corpus_dir=corpus_dir, **svc_kw)
        timed_submit(svc, model)  # base model: compile + corpus publish
        # A delta replay never publishes, so a second edited submission
        # takes the delta rung again — the first absorbs the edited
        # model's own kernel compiles (the cold side absorbed its in the
        # warm-up above; the measured ratio is pure replay-vs-search).
        timed_submit(svc, edited)
        delta_sec, delta_r = timed_submit(svc, edited)
        delta_corpus = dict(delta_r.detail.get("corpus") or {})
        stats = dict(svc.stats().get("corpus") or {})
        svc.close()

    err = None
    got = (delta_r.state_count, delta_r.unique_state_count, delta_r.max_depth)
    want = (cold_r.state_count, cold_r.unique_state_count, cold_r.max_depth)
    if got != want or sorted(delta_r.discoveries) != sorted(
        cold_r.discoveries
    ):
        err = (
            f"delta parity failure: {got} / {sorted(delta_r.discoveries)} "
            f"!= cold {want} / {sorted(cold_r.discoveries)}"
        )
    if err is None and delta_corpus.get("warm_kind") != "delta":
        err = (
            "edited submission did not take the delta rung "
            f"(detail: {delta_corpus})"
        )
    if err is None and delta_corpus.get("delta_class") != "properties-only":
        err = (
            "edit was not classified properties-only "
            f"(detail: {delta_corpus})"
        )
    if err is None and not stats.get("delta_hits"):
        err = f"delta_hits counter did not advance (stats: {stats})"
    warm_speedup_delta = round(cold_sec / max(delta_sec, 1e-9), 2)
    if err is None and warm_speedup_delta < 2.0:
        err = (
            f"delta submission only {warm_speedup_delta}x faster than "
            "cold (acceptance >= 2x)"
        )

    out = {
        "states": delta_r.state_count,
        "unique": delta_r.unique_state_count,
        "sec": round(delta_sec, 4),
        "states_per_sec": delta_r.state_count / max(delta_sec, 1e-9),
        "compile_sec": 0.0,  # both sides measured post-compile (A/B fair)
        "sec_cold": round(cold_sec, 4),
        "warm_speedup_delta": warm_speedup_delta,
        "delta_class": delta_corpus.get("delta_class"),
    }
    return out, err


def device_search_sharded(model_name: str, n: int, n_chips: int = 8):
    """Run the multi-chip sharded engine over a mesh of `n_chips` (virtual
    CPU devices when real multi-chip hardware is absent — the bench marks
    the result accordingly)."""
    _pin_platform()
    import jax

    from stateright_tpu.parallel import ShardedSearch, make_mesh

    # engine_kwargs are mostly resident-engine options (donate_chunks) with
    # no sharded equivalent — intentionally dropped — except the tiered
    # store, which the sharded engine supports as per-shard rank-local
    # spill.
    model, batch, table_log2, run_kwargs, engine_kwargs, golden, closure_s = (
        _build_workload(model_name, n)
    )
    store_kwargs = {
        k: engine_kwargs[k]
        for k in ("store", "high_water", "low_water", "summary_log2")
        if k in engine_kwargs
    }
    n_chips = min(n_chips, len(jax.devices()))
    search = ShardedSearch(
        model,
        mesh=make_mesh(n_chips),
        batch_size=batch // 2,
        table_log2=max(table_log2 - 2, 10),
        **store_kwargs,
    )
    best, out = _time_search(search, run_kwargs, repeats=2, closure_s=closure_s)
    out.update(
        n_chips=n_chips,
        virtual_mesh=jax.devices()[0].platform == "cpu",
        per_chip_unique=best.detail["per_chip_unique"],
    )
    _attach_store_stats(out, search)
    _attach_telemetry(out, best)
    return out, _parity_err(model_name, n, best, golden)


# -- static-analysis budget row (BENCH_ANALYSIS=1) -----------------------------

#: detail.analysis row shape (pinned by tests/test_bench_contract.py).
ANALYSIS_ROW_FIELDS = ("srlint_findings", "knob_drift", "engines", "clean")
#: per-engine audit fields inside detail.analysis.engines.<name>.
ANALYSIS_ENGINE_FIELDS = (
    "step_hbm_bytes", "step_flops", "transfer_bytes", "model_bytes",
    "ratio", "ratio_ok", "violations", "skipped",
)


def worker_analysis() -> dict:
    """`bench.py --worker-analysis`: the static-analysis budget row —
    srlint over the repo, knob-registry drift, and the three engine
    anchors' audited step totals (abstract jaxpr tracing on CPU; nothing
    executes on a device). Runs in a fresh subprocess so the forced
    8-device CPU mesh never leaks into the TPU workers."""
    from stateright_tpu.analysis.anchors import audit_anchors
    from stateright_tpu.analysis.srlint import lint_paths
    from stateright_tpu.knobs import check_registry

    findings = lint_paths()
    drift = check_registry()
    engines = {}
    violations = 0
    ratios_ok = True
    for name, ar in audit_anchors().items():
        if ar.skipped:
            engines[name] = {"skipped": ar.skipped}
            continue
        s = ar.report.summary()
        engines[name] = {
            "step_hbm_bytes": s["step_hbm_bytes"],
            "step_flops": s["step_flops"],
            "transfer_bytes": s["transfer_bytes"],
            "model_bytes": round(ar.model_bytes),
            "ratio": round(ar.ratio, 2),
            "ratio_ok": ar.ratio_ok,
            "violations": s["violations"],
        }
        violations += len(s["violations"])
        ratios_ok = ratios_ok and ar.ratio_ok
    # Same verdict the CLI gate reaches over the project's own passes
    # (srlint, drift, jaxpr violations, costmodel cross-check). ruff/mypy
    # are deliberately excluded: the artifact row must not flip with what
    # happens to be installed on the bench image.
    return {
        "srlint_findings": len(findings),
        "knob_drift": len(drift),
        "engines": engines,
        "clean": not findings and not drift and violations == 0 and ratios_ok,
    }


def analysis_row(timeout: float = 600.0) -> dict:
    """Run worker_analysis in a subprocess (fresh jax, CPU backend, 8 host
    devices for the sharded anchor) and return its row; errors become an
    {"error": ...} row, never a bench death."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker-analysis"],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        payload = json.loads(line)
    except Exception as e:  # noqa: BLE001 — reporting must never kill a run
        log(f"analysis row failed: {e}")
        return {"error": str(e)}
    if payload.get("error"):
        log(f"analysis row failed: {payload['error']}")
        return {"error": payload["error"]}
    return payload["result"]


# -- main ----------------------------------------------------------------------

# Per-workload fields copied into detail.device verbatim when present. The
# last three are the tiered store's per-tier occupancy counters (hot-tier
# fill fraction, states spilled to the host tier, spill-event count) —
# degradation past HBM is observable in every artifact
# (tests/test_bench_contract.py pins the keys).
DEVICE_DETAIL_FIELDS = (
    "virtual_mesh", "n_chips", "per_chip_unique",
    "closure_sec", "bytes_per_state", "cpu_bytes_per_state", "hbm_frac",
    "hot_fill", "spilled_states", "spill_events",
    # Check-service row (BENCH_SERVICE=1): mixed-job-batch throughput and
    # the serial A/B ratio (>1 = continuous batching beats serial runs).
    "n_jobs", "jobs_per_sec", "vs_serial", "serial_sec",
    "service_steps", "serial_steps",
    # Telemetry spine (stateright_tpu/obs/): the step-telemetry digest of
    # the run, and — on the BENCH_OBS=1 A/B row — the telemetry-off wall
    # time plus the measured on-vs-off overhead (acceptance: <= 2%).
    "telemetry", "sec_off", "telemetry_overhead_pct",
    # Flight recorder (obs/events.py, BENCH_OBS=1 journal sub-row): the
    # journal-off wall time, the measured journal-on overhead through the
    # check service (acceptance: <= 5%), and how many events the run
    # recorded.
    "sec_journal_off", "journal_overhead_pct", "journal_events",
    # Calibration observatory (obs/calib.py, BENCH_CALIB=1 A/B row): the
    # measured-vs-predicted join's digest (predicted/measured ms, drift
    # ratio, per-term attribution) plus the comparator-off wall time and
    # the measured on-vs-off overhead (acceptance: within noise — the
    # comparator is host arithmetic at chunk granularity).
    "calib", "calib_overhead_pct",
    # Chaos plane / supervisor (BENCH_FAULTS=1 A/B row): the recovery
    # digest plus the unsupervised wall time and the measured supervisor
    # overhead with injection disabled (expected within noise).
    "faults", "sec_unsupervised", "supervisor_overhead_pct",
    # Pallas insert A/B (BENCH_PALLAS=1 row): the capped-insert wall time
    # next to the pallas run's, and the speed ratio (>1 = pallas wins).
    "sec_capped", "pallas_vs_capped",
    # Service fleet (BENCH_FLEET=1 row): N-replica mixed-set throughput vs
    # the same jobs through one replica (>1 = scale-out wins), plus the
    # fleet run's submit→result latency digest and robustness counters.
    "n_replicas", "fleet_jobs_per_sec", "sec_one_replica",
    "vs_one_replica", "fleet_p50_ms", "fleet_p99_ms",
    "fleet_steals", "fleet_requeued",
    # Autoscaling fleet (BENCH_AUTOSCALE=1 row): fixed 1-replica vs a
    # fleet that starts at 1 and grows under the Autoscaler on the same
    # burst — both throughputs, the ratio, the autoscaled run's latency
    # digest, and the control loop's own scale-event evidence.
    "auto_max_replicas", "auto_jobs_per_sec", "auto_p50_ms", "auto_p99_ms",
    "auto_replicas_high_water", "auto_scale_outs", "auto_scale_ins",
    "sec_fixed_one", "vs_fixed_one",
    # Blob checkpoint backend (BENCH_BLOB=1 row): the local-filesystem
    # wall time next to the blob-emulator run's (`sec`), the measured
    # overhead percentage, and the blob client's op/retry counters —
    # the "object store costs only the wire, never the answers" claim.
    # Managed-dialect legs (s3 = SigV4-signed dialect emulator, gcs =
    # OAuth-bearer dialect emulator) carry the same trio each: signed
    # wall time, overhead vs sec_local_fs, and that client's counters.
    "sec_local_fs", "blob_overhead_pct", "blob_ops", "blob_retries",
    "sec_s3", "s3_overhead_pct", "s3_ops", "s3_retries",
    "sec_gcs", "gcs_overhead_pct", "gcs_ops", "gcs_retries",
    # Warm-start corpus (BENCH_CORPUS=1 row): the cold wall time next to
    # the warm submission's (`sec`), the cold/warm ratio (acceptance >=
    # 5x), the preloaded-state count, and the corrupted-entry CRC verdict
    # (True = a flipped byte was detected and the run fell back cold).
    # v2 edit-warm sub-rows: the near rung (same definition, retuned
    # lowering — family-index replay) and the partial rung (mid-run cut,
    # frontier continuation), each against a post-compile cold reference
    # (acceptance >= 2x each).
    "sec_cold", "warm_speedup", "warm_speedup_near", "warm_speedup_partial",
    "corpus_preloaded", "corrupt_detected",
    # Spec-CI definition delta (BENCH_DELTA=1 row): the property-edit
    # cold reference next to the delta-rung submission's (`sec`), the
    # measured ratio (acceptance >= 2x with bit-identical counts and the
    # re-evaluated verdict present), and the classifier's named edit
    # class ("properties-only" on this row).
    "warm_speedup_delta", "delta_class",
    # Dedup-first semantics (BENCH_SEMANTICS=1 row): the cache-only wall
    # time next to the plane's (`sec`), the measured ratio (acceptance >=
    # 2x with bit-identical verdicts), and the plane's own evidence —
    # classes collapsed by canonicalization, witness-guided resolutions,
    # full searches actually run, and native-pool evaluations.
    "sec_legacy", "semantics_speedup", "verdict_negatives",
    "canonical_collapsed", "witness_guided_hits", "full_searches",
    "batch_parallel_evals",
    # Device random simulation (BENCH_SIM=1 row): the host walker's wall
    # time and rates next to the device engine's (`sec`/`states_per_sec`),
    # the walks/s ratio (acceptance >= 2x), the lane-utilization and
    # restart evidence of continuous walk batching, the shared-table dedup
    # hit rate, and the same-seed determinism verdict.
    "sec_host_sim", "host_states_per_sec", "sim_walks_per_sec",
    "host_walks_per_sec", "sim_speedup", "sim_lane_util", "sim_restarts",
    "sim_dedup_hit_rate", "sim_bit_identical",
)


def device_detail(v: dict) -> dict:
    """One workload's detail.device row (shape pinned by the bench-contract
    tests): headline rate + the optional DEVICE_DETAIL_FIELDS."""
    return {
        "states_per_sec": round(v["states_per_sec"], 1),
        "sec": v["sec"],
        **{f: v[f] for f in DEVICE_DETAIL_FIELDS if f in v},
    }


def headline_summary(dev: dict, base: dict, smoke: bool = False):
    """Headline metric for the one-line JSON: Paxos-3 (the BASELINE.json
    north-star workload).

    Contract: ``value``/``vs_baseline`` describe the DEVICE engine only.
    When no device result exists both are None — never the C++ baseline
    number — so a dashboard reading ``value`` cannot mistake the baseline
    for a result.  Returns ``(metric, value, vs_baseline)``.
    """
    headline_dev = dev.get("paxos-3")
    headline_base = base.get("paxos-3")
    if headline_dev is not None:
        value = headline_dev["states_per_sec"]
        metric = (
            "paxos-3 generated states/sec (device whole-search, on-device "
            "linearizability; 1,194,428 unique states)"
        )
    else:
        value = None
        if smoke:
            why = "paxos-3 not run in smoke mode"
        elif dev:
            why = "device failed on paxos-3"
        else:
            why = "device unavailable"
        metric = (
            f"paxos-3 generated states/sec (no device result: {why}; "
            "CPU baseline in detail.cpu_baseline)"
        )
    vs_baseline = (
        round(value / headline_base["states_per_sec"], 3)
        if headline_base and value
        else None
    )
    return metric, round(value, 1) if value is not None else None, vs_baseline


def main(argv: list | None = None) -> int:
    detail: dict = {}
    errors: list[str] = []

    # --baseline-threads N: additionally run every C++ baseline workload
    # with an explicit N-thread row (VERDICT r5 #5 — the north-star
    # denominator is the MULTITHREADED reference checker; the default row
    # keeps baseline_bfs's own hardware_concurrency default). Malformed
    # values are ignored rather than killing the bench.
    args = list(sys.argv[1:] if argv is None else argv)
    baseline_threads = None
    if "--baseline-threads" in args:
        i = args.index("--baseline-threads")
        try:
            baseline_threads = max(1, int(args[i + 1]))
        except (IndexError, ValueError):
            log("ignoring malformed --baseline-threads")
    if baseline_threads is None and (os.cpu_count() or 1) > 1:
        # Multicore host: record the pinned threads=N multithreaded row by
        # DEFAULT (VERDICT r5 #5 residue — every artifact to date carried
        # only threads:1 denominators because the flag was opt-in and the
        # TPU box reports one core). --baseline-threads still overrides.
        baseline_threads = os.cpu_count()
        log(f"multicore host: recording threads={baseline_threads} "
            "baseline rows by default")
    for a in args:
        # A typo'd flag silently dropped on tunnel day would cost the
        # multithread rows the flag exists for — say so loudly.
        if a.startswith("--") and a != "--baseline-threads":
            log(f"unknown bench.py flag {a!r} ignored "
                "(known: --baseline-threads N)")

    # BENCH_SMOKE=1: harness smoke mode — smallest baseline + device
    # workloads only, so the full pipeline (C++ baseline, device probe,
    # worker subprocess, parity oracle, JSON emission) can be exercised in
    # minutes. The emitted line is marked so it can't be mistaken for a
    # real benchmark.
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    baseline_cfgs = (
        (("paxos", 2, 1), ("2pc", 4, 1))
        if smoke
        else (
            ("paxos", 2, 3),
            ("paxos", 3, 3),
            ("2pc", 4, 3),
            ("increment_lock", 6, 3),
            # The full reference bench.sh config; one repeat — it runs for
            # minutes and best-of-N would eat the device budget.
            ("2pc", 10, 1),
        )
    )

    exe = compile_baseline()
    base = {}
    if exe:
        for model, n, repeats in baseline_cfgs:
            runs = [(f"{model}-{n}", None)]
            if baseline_threads is not None:
                # Always emit the pinned row when asked — -t1 is meaningful
                # on a multicore host, where the default row runs at
                # hardware_concurrency.
                runs.append(
                    (f"{model}-{n}-t{baseline_threads}", baseline_threads)
                )
            for key, threads in runs:
                r = run_baseline(exe, model, n, repeats=repeats, threads=threads)
                if not r:
                    continue
                gen_gold, uniq_gold = GOLDEN[(model, n)]
                if (r["states"], r["unique"]) != (gen_gold, uniq_gold):
                    errors.append(
                        f"baseline {key} golden mismatch: "
                        f"(gen={r['states']}, unique={r['unique']}) != "
                        f"(gen={gen_gold}, unique={uniq_gold})"
                    )
                if r["violations"]:
                    errors.append(
                        f"baseline {key} reported {r['violations']} "
                        "property violations (expected none)"
                    )
                base[key] = r
                log(
                    f"baseline {key}: {r['states']} states in "
                    f"{r['sec']}s ({r['states_per_sec']:.0f}/s, "
                    f"{r['threads']} threads)"
                )
    detail["cpu_baseline"] = {
        k: {
            "states_per_sec": round(v["states_per_sec"], 1),
            "sec": v["sec"],
            "threads": v["threads"],
        }
        for k, v in base.items()
    }

    device_error = None
    dev: dict = {}
    dev_errors: dict = {}
    ok, probe_err = probe_device()
    if not ok:
        device_error = f"device probe failed: {probe_err}"
    else:
        # Smallest-to-largest: each validated workload de-risks the next.
        # Workloads are independent — one failing (e.g. OOM at a big table
        # size) must not misreport the device as unavailable for the others.
        # (name, n, timeout, mode, extra env) — the sharded multi-chip config
        # runs on a virtual 8-device CPU mesh (real multi-chip hardware is
        # not reachable from this harness; the result is marked
        # virtual_mesh=true).
        virtual8 = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
        # BENCH_TIMEOUT_SCALE: multiply per-workload subprocess timeouts —
        # CPU-backend rehearsals need it (the completed 2pc-10 CPU run takes
        # ~115 min vs the 50-min TPU budget; BENCH_CPU_2PC10_r04.json).
        # Malformed/zero/negative values fall back to 1 (never crash the
        # bench or zero the timeouts mid-run).
        try:
            tscale = float(os.environ.get("BENCH_TIMEOUT_SCALE", "1"))
        except ValueError:
            tscale = 1.0
        if not (0 < tscale < float("inf")):  # rejects NaN/inf/<=0 too
            tscale = 1.0
        workloads = (
            (("2pc", 4, 600.0, "--worker", None),)
            if smoke
            else (
                ("2pc", 4, 1500.0, "--worker", None),
                ("inclock", 6, 1500.0, "--worker", None),
                ("inclock-sym", 6, 1500.0, "--worker", None),
                ("paxos", 2, 1500.0, "--worker", None),
                ("abd-ordered", 16, 1500.0, "--worker", None),
                ("paxos", 3, 1500.0, "--worker", None),
                ("paxos5s4c", 10, 2400.0, "--worker", None),
                ("paxos5s4c", 10, 2400.0, "--worker-sharded", virtual8),
                ("2pc", 10, 3000.0, "--worker", None),
            )
        )
        # BENCH_SERVICE=1: add the check-service mixed-job row (8 jobs
        # through one shared table vs the same jobs serially; the ratio
        # lands in detail.device["service-mixed-8"].vs_serial).
        if os.environ.get("BENCH_SERVICE") == "1" and not smoke:
            workloads += (("service-mixed", 8, 2400.0, "--worker-service", None),)
        # BENCH_OBS=1: add the telemetry on/off A/B on the r4 anchor row
        # (paxos-3 — the costmodel's pinned 12.9 ms/step workload); the
        # measured overhead lands in
        # detail.device["paxos-3-obs"].telemetry_overhead_pct.
        if os.environ.get("BENCH_OBS") == "1" and not smoke:
            workloads += (("paxos", 3, 2400.0, "--worker-obs", None),)
            # ...and the flight-recorder journal on/off A/B on the 2pc-4
            # anchor THROUGH the check service (where the journal actually
            # emits: one event per fused step + job transitions; the
            # measured overhead lands in
            # detail.device["2pc-4-journal"].journal_overhead_pct,
            # acceptance <= 5%).
            workloads += (("2pc", 4, 2400.0, "--worker-journal", None),)
        # BENCH_CALIB=1: add the calibration-comparator on/off A/B on the
        # 2pc-4 anchor (resident engine; the measured-vs-predicted join of
        # obs/calib.py costs host arithmetic per 32-step chunk — the
        # measured overhead lands in
        # detail.device["2pc-4-calib"].calib_overhead_pct, acceptance
        # within noise, with the drift digest in .calib).
        if os.environ.get("BENCH_CALIB") == "1" and not smoke:
            workloads += (("2pc", 4, 2400.0, "--worker-calib", None),)
        # BENCH_FAULTS=1: add the supervisor-overhead A/B on the 2pc-4
        # anchor (plain resident vs run_supervised with injection off; the
        # measured overhead lands in
        # detail.device["2pc-4-faults"].supervisor_overhead_pct).
        if os.environ.get("BENCH_FAULTS") == "1" and not smoke:
            workloads += (("2pc", 4, 2400.0, "--worker-faults", None),)
        # BENCH_PALLAS=1: add the pallas-vs-capped insert A/B on the 2pc-4
        # and paxos-2 anchors (resident engine; the Pallas route-then-probe
        # kernel vs the r6 capped insert — the measured ratio lands in
        # detail.device["<wl>-pallas"].pallas_vs_capped next to the
        # costmodel's committed ranking in ROUND12_NOTES.md).
        if os.environ.get("BENCH_PALLAS") == "1" and not smoke:
            workloads += (
                ("2pc", 4, 2400.0, "--worker-pallas", None),
                ("paxos", 2, 2400.0, "--worker-pallas", None),
            )
        # BENCH_FLEET=1: add the N-replica fleet scale-out A/B on the mixed
        # job set (the same composition as the service row, through a
        # 3-replica fleet vs 1 replica; jobs/s ratio + p50/p99 latency land
        # in detail.device["fleet-mixed-3"]).
        if os.environ.get("BENCH_FLEET") == "1" and not smoke:
            workloads += (("fleet-mixed", 3, 2400.0, "--worker-fleet", None),)
        # BENCH_AUTOSCALE=1: add the autoscaler A/B on the mixed job set
        # (fixed 1-replica fleet vs a fleet that starts at 1 and may grow
        # to 3 under an aggressive Autoscaler; jobs/s both ways, the
        # ratio, p99, and replicas_high_water/scale_outs/scale_ins land
        # in detail.device["fleet-auto-3"]).
        if os.environ.get("BENCH_AUTOSCALE") == "1" and not smoke:
            workloads += (("fleet-auto", 3, 2400.0, "--worker-autoscale", None),)
        # BENCH_BLOB=1: add the local-vs-blob checkpoint-backend overhead
        # A/B (the mixed job set through a 2-replica fleet with the
        # requeue-resume plane + lease fence on a local dir vs the blob
        # emulator; overhead + blob op/retry counters land in
        # detail.device["fleet-blob-2"]).
        if os.environ.get("BENCH_BLOB") == "1" and not smoke:
            workloads += (("fleet-blob", 2, 2400.0, "--worker-blob", None),)
        # BENCH_CORPUS=1: add the cross-job warm-start cold-vs-warm A/B on
        # the 2pc-4 anchor (second submission of the same content key
        # through a corpus-enabled tiered service; the measured ratio
        # lands in detail.device["2pc-4-corpus"].warm_speedup — acceptance
        # >= 5x with bit-identical results — next to the corrupted-entry
        # CRC verdict).
        if os.environ.get("BENCH_CORPUS") == "1" and not smoke:
            workloads += (("2pc", 4, 2400.0, "--worker-corpus", None),)
        # BENCH_DELTA=1: add the Spec-CI definition-delta A/B on the
        # 2pc-4 anchor (publish the base model, then submit a
        # property-edited variant; the classifier names the edit and the
        # delta rung replays the published set with only the changed
        # verdict re-evaluated — the measured ratio lands in
        # detail.device["2pc-4-delta"].warm_speedup_delta, acceptance
        # >= 2x with bit-identical counts).
        if os.environ.get("BENCH_DELTA") == "1" and not smoke:
            workloads += (("2pc", 4, 2400.0, "--worker-delta", None),)
        # BENCH_SEMANTICS=1: add the dedup-first verdict-plane A/B on the
        # single-copy-register 6c2s anchor (property-evaluation phase only,
        # host-side; the measured ratio lands in
        # detail.device["single_copy-6-semantics"].semantics_speedup —
        # acceptance >= 2x with bit-identical verdicts).
        if os.environ.get("BENCH_SEMANTICS") == "1" and not smoke:
            workloads += (
                ("single_copy", 6, 2400.0, "--worker-semantics", None),
            )
        # BENCH_SIM=1: add the fourth checker mode's host-vs-device A/B on
        # the 2pc-3 anchor (host thread-pool SimulationChecker vs the
        # continuous-batched device walk engine to the same state budget;
        # the measured walks/s ratio lands in
        # detail.device["2pc-3-sim"].sim_speedup — acceptance >= 2x with
        # identical verdicts and bit-identical same-seed device runs).
        if os.environ.get("BENCH_SIM") == "1" and not smoke:
            workloads += (("2pc", 3, 2400.0, "--worker-sim", None),)
        for model, n, wl_timeout, mode, env_extra in workloads:
            key = f"{model}-{n}" + (
                {
                    "--worker-sharded": "-sharded8",
                    "--worker-obs": "-obs",
                    "--worker-journal": "-journal",
                    "--worker-calib": "-calib",
                    "--worker-faults": "-faults",
                    "--worker-pallas": "-pallas",
                    "--worker-corpus": "-corpus",
                    "--worker-delta": "-delta",
                    "--worker-semantics": "-semantics",
                    "--worker-sim": "-sim",
                    "--worker-fleet": "",
                    "--worker-autoscale": "",
                    "--worker-blob": "",
                }.get(mode, "")
            )
            r, perr = device_search_subprocess(
                model,
                n,
                timeout=wl_timeout * tscale,
                mode=mode,
                env_extra=env_extra,
            )
            if r is None:
                # No result is a failure even without an error string (e.g.
                # a truncated worker payload missing both keys).
                dev_errors[key] = perr or "worker returned no result"
                log(f"device {key} failed: {perr or 'no result'}")
                continue
            if perr:
                errors.append(perr)
            dev[key] = r
            log(
                f"device {key}: {r['states']} states in {r['sec']}s "
                f"({r['states_per_sec']:.0f}/s, compile {r['compile_sec']}s)"
            )
        if dev_errors and not dev:
            device_error = "; ".join(
                f"{k}: {v}" for k, v in dev_errors.items()
            )
    detail["device"] = {k: device_detail(v) for k, v in dev.items()}
    # Sharding overhead ratio (VERDICT r4 next #4): sharded-N vs the
    # single-device engine on the SAME workload — <1 means the sharded
    # engine's per-step machinery (send-buffer scatters, all-to-all,
    # N-fold insert width) costs more than it parallelizes on this mesh.
    for k, v in dev.items():
        if k.endswith("-sharded8") and k[: -len("-sharded8")] in dev:
            single = dev[k[: -len("-sharded8")]]["states_per_sec"]
            if single > 0:
                detail["device"][k]["vs_single_device"] = round(
                    v["states_per_sec"] / single, 3
                )
    if dev_errors:
        detail["device_errors"] = dev_errors

    # BENCH_ANALYSIS=1: the static-analysis budget row — srlint finding
    # count, knob drift, and each engine anchor's audited step
    # FLOP/byte/transfer totals vs the costmodel (abstract CPU tracing in a
    # fresh subprocess; no device). Keys pinned in test_bench_contract.py:
    # the budget trend is part of the artifact, so a BENCH_r*.json can
    # answer "did the compiled step program grow" without re-profiling.
    if os.environ.get("BENCH_ANALYSIS") == "1" and not smoke:
        detail["analysis"] = analysis_row()

    metric, value, vs_baseline = headline_summary(dev, base, smoke=smoke)
    if smoke:
        metric = f"[SMOKE MODE — not a benchmark] {metric}"

    out = {
        "metric": metric,
        "value": value,
        "unit": "states/sec",
        "vs_baseline": vs_baseline,
        "detail": detail,
    }
    if device_error:
        out["device_error"] = device_error
    if errors:
        out["error"] = "; ".join(errors)
    print(json.dumps(out), flush=True)
    return 1 if errors else 0


def worker_main(model_name: str, n: int, mode: str = "--worker") -> int:
    """`bench.py --worker[-sharded|-service] MODEL N`: run one device
    workload, print one JSON line {"result": ..., "error": ...} on stdout."""
    try:
        if mode == "--worker-service":
            r, perr = device_search_service(n)
        elif mode == "--worker-fleet":
            r, perr = device_search_fleet(n)
        elif mode == "--worker-autoscale":
            r, perr = device_search_autoscale(n)
        elif mode == "--worker-blob":
            r, perr = device_search_blob(n)
        elif mode == "--worker-sharded":
            r, perr = device_search_sharded(model_name, n)
        elif mode == "--worker-obs":
            r, perr = device_search_obs(model_name, n)
        elif mode == "--worker-journal":
            r, perr = device_search_journal(model_name, n)
        elif mode == "--worker-calib":
            r, perr = device_search_calib(model_name, n)
        elif mode == "--worker-faults":
            r, perr = device_search_faults(model_name, n)
        elif mode == "--worker-pallas":
            r, perr = device_search_pallas(model_name, n)
        elif mode == "--worker-corpus":
            r, perr = device_search_corpus(model_name, n)
        elif mode == "--worker-delta":
            r, perr = device_search_delta(model_name, n)
        elif mode == "--worker-semantics":
            r, perr = device_search_semantics(model_name, n)
        elif mode == "--worker-sim":
            r, perr = device_search_simulation(model_name, n)
        else:
            r, perr = device_search(model_name, n)
        print(json.dumps({"result": r, "error": perr}), flush=True)
        return 0
    except Exception:  # noqa: BLE001
        traceback.print_exc()
        err = traceback.format_exc(limit=3).strip().splitlines()[-1]
        print(json.dumps({"result": None, "error": err}), flush=True)
        return 1


if __name__ == "__main__":
    if len(sys.argv) == 4 and sys.argv[1] in (
        "--worker", "--worker-sharded", "--worker-service", "--worker-obs",
        "--worker-journal", "--worker-calib", "--worker-faults",
        "--worker-pallas",
        "--worker-fleet", "--worker-autoscale", "--worker-blob",
        "--worker-corpus", "--worker-delta", "--worker-semantics",
        "--worker-sim",
    ):
        sys.exit(worker_main(sys.argv[2], int(sys.argv[3]), mode=sys.argv[1]))
    if len(sys.argv) == 2 and sys.argv[1] == "--worker-analysis":
        try:
            print(
                json.dumps({"result": worker_analysis(), "error": None}),
                flush=True,
            )
            sys.exit(0)
        except Exception:  # noqa: BLE001 — one-JSON-line contract
            traceback.print_exc()
            err = traceback.format_exc(limit=3).strip().splitlines()[-1]
            print(json.dumps({"result": None, "error": err}), flush=True)
            sys.exit(1)
    try:
        sys.exit(main())
    except Exception:  # noqa: BLE001 — the one-JSON-line contract is absolute
        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": "paxos-3 states/sec",
                    "value": 0.0,
                    "unit": "states/sec",
                    "vs_baseline": None,
                    "error": traceback.format_exc(limit=2)
                    .strip()
                    .splitlines()[-1],
                }
            ),
            flush=True,
        )
        sys.exit(1)
