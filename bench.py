"""Benchmark: device whole-search checker vs the compiled CPU baseline on the
BASELINE.json metric workloads — Paxos-3 (north star) and 2PC-4 — plus the
reference's 2-client Paxos golden config as the parity anchor.

Baseline: this image has no cargo/rustc, so the reference's multithreaded Rust
`BfsChecker` (the thing BASELINE.md says to measure via bench.sh) is
approximated by `stateright_tpu/_native/baseline_bfs.cpp` — a C++ port of the
same search over the same state spaces, validated against the reference's
golden counts (2pc-3=288, 2pc-5=8,832, paxos-2=16,668 — examples/2pc.rs:153-159,
examples/paxos.rs:327). It packs states into u32 lanes, so it does *less* work
per state than the Rust checker's boxed states: a conservative baseline.

Robustness contract (VERDICT round 1): exactly ONE JSON line is printed on
stdout no matter what. The device is probed with a trivial jitted op (with
retries) before any search kernel compiles; if the device is unusable the line
carries the CPU baseline number and a `device_error` field instead of dying
with rc=1 and no output. Count-parity failures are reported in an `error`
field (never a bare `assert`, which `python -O` would strip).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time
import traceback

# Golden counts (generated, unique): reference examples/paxos.rs:327 for
# paxos-2; 2pc-4 and paxos-3 were computed by the compiled baseline checker
# and cross-validated against the device engines (BASELINE_MEASURED.md).
GOLDEN = {
    ("paxos", 2): (32_971, 16_668),
    ("paxos", 3): (2_420_477, 1_194_428),
    ("2pc", 4): (8_258, 1_568),
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- compiled CPU baseline -----------------------------------------------------


def compile_baseline() -> str | None:
    try:
        from stateright_tpu._native import build

        return build("baseline_bfs", exe=True)
    except Exception as e:  # noqa: BLE001 — baseline is best-effort
        log(f"baseline compile failed: {e}")
        return None


def run_baseline(exe: str, model: str, n: int, repeats: int = 3):
    """Best-of-N run of the compiled checker. Returns dict or None; keeps the
    best run that *succeeded* even if later repeats fail."""
    best = None
    for _ in range(repeats):
        try:
            proc = subprocess.run(
                [exe, model, str(n)],
                check=True,
                capture_output=True,
                text=True,
                timeout=1800,
            )
        except Exception as e:  # noqa: BLE001
            log(f"baseline run {model}-{n} failed: {e}")
            continue
        m = re.search(
            r"states=(\d+) unique=(\d+) depth=(\d+) sec=([\d.]+) threads=(\d+) "
            r"violations=(\d+)",
            proc.stdout,
        )
        if not m:
            log(f"baseline output unparseable: {proc.stdout!r}")
            continue
        r = {
            "states": int(m.group(1)),
            "unique": int(m.group(2)),
            "depth": int(m.group(3)),
            "sec": float(m.group(4)),
            "threads": int(m.group(5)),
            "violations": int(m.group(6)),
        }
        if best is None or r["sec"] < best["sec"]:
            best = r
    if best:
        best["states_per_sec"] = best["states"] / max(best["sec"], 1e-9)
    return best


# -- device ----------------------------------------------------------------


# Persistent XLA compilation cache: the resident kernels take tens of seconds
# to compile over the device tunnel; caching them means repeat bench runs (and
# any warm-up run done earlier in the same checkout) skip compilation
# entirely. The cache is keyed by backend+topology, so CPU-pinned runs and
# real-TPU runs never collide.
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")

# The image's site config re-registers the axon TPU platform and overrides a
# plain JAX_PLATFORMS env var; applying the env var at the jax.config level
# restores it, so `JAX_PLATFORMS=cpu python bench.py` really benches on CPU
# (used by verification runs when the TPU tunnel is down).
_PIN_SNIPPET = (
    "import os, jax;"
    "p = os.environ.get('JAX_PLATFORMS');"
    "jax.config.update('jax_platforms', p) if p else None;"
    f"jax.config.update('jax_compilation_cache_dir', {_CACHE_DIR!r});"
)

_PROBE_SNIPPET = _PIN_SNIPPET + (
    "import jax.numpy as jnp;"
    "x = jax.jit(lambda a: a * 2 + 1)(jnp.arange(8));"
    "x.block_until_ready();"
    "print('PROBE_OK', jax.devices())"
)


def _pin_platform() -> None:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)


def probe_device(attempts: int = 6, delay: float = 20.0):
    """Run a trivial jitted op on the default backend in a SUBPROCESS;
    returns (ok, error).

    The axon TPU tunnel is single-client: while any other process holds the
    chip, backend init fails with "UNAVAILABLE: TPU backend setup/compile
    error" (the round-1 bench death). That clears when the holder exits, so
    the probe retries patiently — and in a fresh subprocess each time, because
    a failed backend init can be cached for the life of a process, which would
    make in-process retries (and the real run afterwards) futile.
    """
    last = "unknown"
    for i in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                capture_output=True,
                text=True,
                timeout=180,
            )
        except Exception as e:  # noqa: BLE001
            last = f"probe subprocess failed: {e}"
            log(last)
            if i + 1 < attempts:
                time.sleep(delay)
            continue
        if proc.returncode == 0 and "PROBE_OK" in proc.stdout:
            log(f"device probe ok: {proc.stdout.strip()}")
            return True, ""
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        last = tail[-1] if tail else f"rc={proc.returncode}"
        log(f"device probe attempt {i + 1}/{attempts} failed: {last}")
        if i + 1 < attempts:
            time.sleep(delay)
    return False, last


def device_search_subprocess(model_name: str, n: int, timeout: float = 1500.0):
    """Run one device workload in a FRESH subprocess (`bench.py --worker`).

    Isolation serves two purposes on the tunneled single-client device:
    a workload that hangs (e.g. a pathological compile) is bounded by
    `timeout` instead of eating the whole bench, and a crashed workload
    cannot poison the backend state of the remaining ones. Workloads still
    run strictly sequentially — the tunnel admits one client at a time.

    Returns (result dict | None, error str | None).
    """
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", model_name, str(n)],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        # The kill that subprocess.run just delivered can itself wedge the
        # single-client tunnel (see ROUND2_NOTES.md); keep the partial stderr
        # so the hung phase is attributable, and flag the contamination risk.
        if e.stderr:
            err_text = e.stderr if isinstance(e.stderr, str) else e.stderr.decode(errors="replace")
            sys.stderr.write(err_text)
        return None, (
            f"workload timed out after {timeout:.0f}s and was killed "
            "(subsequent workload failures may be kill-induced tunnel wedge)"
        )
    except Exception as e:  # noqa: BLE001
        return None, f"worker spawn failed: {e}"
    sys.stderr.write(proc.stderr)
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    if not line.startswith("{"):
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        return None, tail[-1] if tail else f"worker rc={proc.returncode}"
    try:
        payload = json.loads(line)
    except ValueError:
        return None, f"unparseable worker output: {line[:200]!r}"
    return payload.get("result"), payload.get("error")


def device_search(model_name: str, n: int, repeats: int = 3):
    """Run the resident engine; returns (result dict, parity error or None)."""
    _pin_platform()
    from stateright_tpu.tensor.resident import ResidentSearch

    if model_name == "paxos":
        from stateright_tpu.tensor.paxos import TensorPaxos

        model = TensorPaxos(client_count=n)
        batch, table_log2 = (2048, 16) if n <= 2 else (8192, 22)
    else:
        from stateright_tpu.tensor.models import TensorTwoPhaseSys

        model = TensorTwoPhaseSys(n)
        batch, table_log2 = 512, 14

    search = ResidentSearch(model, batch_size=batch, table_log2=table_log2)
    t0 = time.monotonic()
    first = search.run()  # compile + warm-up
    compile_s = time.monotonic() - t0
    best = None
    for _ in range(repeats):
        r = search.run()
        if best is None or r.duration < best.duration:
            best = r
    gen_gold, uniq_gold = GOLDEN[(model_name, n)]
    err = None
    if (best.state_count, best.unique_state_count) != (gen_gold, uniq_gold):
        err = (
            f"{model_name}-{n} parity failure: device "
            f"(gen={best.state_count}, unique={best.unique_state_count}) != "
            f"golden (gen={gen_gold}, unique={uniq_gold})"
        )
    return {
        "states": best.state_count,
        "unique": best.unique_state_count,
        "sec": round(best.duration, 4),
        "states_per_sec": best.state_count / max(best.duration, 1e-9),
        "compile_sec": round(compile_s, 1),
    }, err


# -- main ----------------------------------------------------------------------


def main() -> int:
    detail: dict = {}
    errors: list[str] = []

    exe = compile_baseline()
    base = {}
    if exe:
        for model, n in (("paxos", 2), ("paxos", 3), ("2pc", 4)):
            r = run_baseline(exe, model, n)
            if r:
                gen_gold, uniq_gold = GOLDEN[(model, n)]
                if (r["states"], r["unique"]) != (gen_gold, uniq_gold):
                    errors.append(
                        f"baseline {model}-{n} golden mismatch: "
                        f"(gen={r['states']}, unique={r['unique']}) != "
                        f"(gen={gen_gold}, unique={uniq_gold})"
                    )
                if r["violations"]:
                    errors.append(
                        f"baseline {model}-{n} reported {r['violations']} "
                        "property violations (expected none)"
                    )
                base[f"{model}-{n}"] = r
                log(
                    f"baseline {model}-{n}: {r['states']} states in "
                    f"{r['sec']}s ({r['states_per_sec']:.0f}/s, "
                    f"{r['threads']} threads)"
                )
    detail["cpu_baseline"] = {
        k: {
            "states_per_sec": round(v["states_per_sec"], 1),
            "sec": v["sec"],
            "threads": v["threads"],
        }
        for k, v in base.items()
    }

    device_error = None
    dev: dict = {}
    dev_errors: dict = {}
    ok, probe_err = probe_device()
    if not ok:
        device_error = f"device probe failed: {probe_err}"
    else:
        # Smallest-to-largest: each validated workload de-risks the next.
        # Workloads are independent — one failing (e.g. OOM at a big table
        # size) must not misreport the device as unavailable for the others.
        for model, n in (("2pc", 4), ("paxos", 2), ("paxos", 3)):
            r, perr = device_search_subprocess(model, n)
            if r is None:
                # No result is a failure even without an error string (e.g.
                # a truncated worker payload missing both keys).
                dev_errors[f"{model}-{n}"] = perr or "worker returned no result"
                log(f"device {model}-{n} failed: {perr or 'no result'}")
                continue
            if perr:
                errors.append(perr)
            dev[f"{model}-{n}"] = r
            log(
                f"device {model}-{n}: {r['states']} states in {r['sec']}s "
                f"({r['states_per_sec']:.0f}/s, compile {r['compile_sec']}s)"
            )
        if dev_errors and not dev:
            device_error = "; ".join(
                f"{k}: {v}" for k, v in dev_errors.items()
            )
    detail["device"] = {
        k: {"states_per_sec": round(v["states_per_sec"], 1), "sec": v["sec"]}
        for k, v in dev.items()
    }
    if dev_errors:
        detail["device_errors"] = dev_errors

    # Headline: Paxos-3 (the BASELINE.json north-star workload).
    headline_dev = dev.get("paxos-3")
    headline_base = base.get("paxos-3")
    if headline_dev is not None:
        value = headline_dev["states_per_sec"]
        metric = (
            "paxos-3 generated states/sec (device whole-search, on-device "
            "linearizability; 1,194,428 unique states)"
        )
    elif headline_base is not None:
        value = headline_base["states_per_sec"]
        why = "device failed on paxos-3" if dev else "device unavailable"
        metric = f"paxos-3 generated states/sec (CPU baseline only; {why})"
    else:
        value = 0.0
        metric = "paxos-3 states/sec (no engine available)"
    vs_baseline = (
        round(value / headline_base["states_per_sec"], 3)
        if headline_base and value
        else None
    )

    out = {
        "metric": metric,
        "value": round(value, 1),
        "unit": "states/sec",
        "vs_baseline": vs_baseline,
        "detail": detail,
    }
    if device_error:
        out["device_error"] = device_error
    if errors:
        out["error"] = "; ".join(errors)
    print(json.dumps(out), flush=True)
    return 1 if errors else 0


def worker_main(model_name: str, n: int) -> int:
    """`bench.py --worker MODEL N`: run one device workload, print one JSON
    line {"result": ..., "error": ...} on stdout."""
    try:
        r, perr = device_search(model_name, n)
        print(json.dumps({"result": r, "error": perr}), flush=True)
        return 0
    except Exception:  # noqa: BLE001
        traceback.print_exc()
        err = traceback.format_exc(limit=3).strip().splitlines()[-1]
        print(json.dumps({"result": None, "error": err}), flush=True)
        return 1


if __name__ == "__main__":
    if len(sys.argv) == 4 and sys.argv[1] == "--worker":
        sys.exit(worker_main(sys.argv[2], int(sys.argv[3])))
    try:
        sys.exit(main())
    except Exception:  # noqa: BLE001 — the one-JSON-line contract is absolute
        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": "paxos-3 states/sec",
                    "value": 0.0,
                    "unit": "states/sec",
                    "vs_baseline": None,
                    "error": traceback.format_exc(limit=2)
                    .strip()
                    .splitlines()[-1],
                }
            ),
            flush=True,
        )
        sys.exit(1)
