#!/usr/bin/env python
"""Single-copy register example CLI
(ref: examples/single-copy-register.rs:139-231)."""

from _cli import (
    argv_int,
    argv_network,
    argv_str,
    argv_subcommand,
    network_names,
    report,
    thread_count,
)

from stateright_tpu.examples.single_copy_register import SingleCopyModelCfg


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        client_count = argv_int(2, 2)
        network = argv_network(3)
        print(f"Model checking a single-copy register with {client_count} clients.")
        report(
            SingleCopyModelCfg(
                client_count=client_count, server_count=1, network=network
            )
            .into_model()
            .checker()
            .threads(thread_count())
            .spawn_dfs()
        )
    elif cmd == "explore":
        client_count = argv_int(2, 2)
        address = argv_str(3, "localhost:3000")
        network = argv_network(4)
        print(
            f"Exploring state space for single-copy register with "
            f"{client_count} clients on {address}."
        )
        SingleCopyModelCfg(
            client_count=client_count, server_count=1, network=network
        ).into_model().checker().serve(address, block=True)
    elif cmd == "spawn":
        from stateright_tpu.actor import Id
        from stateright_tpu.actor.spawn import spawn
        from stateright_tpu.examples.single_copy_register import SingleCopyActor

        port = 3000
        print("  A server that implements a single-copy register.")
        print(f"  Interact via UDP JSON, e.g. nc -u localhost {port}")
        from stateright_tpu.actor.register import Get, GetOk, Put, PutOk

        spawn(
            [(Id.from_addr("127.0.0.1", port), SingleCopyActor())],
            msg_types=[Put, Get, PutOk, GetOk],
        )
    else:
        print("USAGE:")
        print("  ./single_copy_register.py check [CLIENT_COUNT]")
        print("  ./single_copy_register.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  ./single_copy_register.py spawn")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
