"""Shared CLI plumbing for the example scripts (the reference uses pico_args
subcommand CLIs; these mirror that shape: `./example check [ARGS]`)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stateright_tpu import WriteReporter  # noqa: E402
from stateright_tpu.actor import Network  # noqa: E402


def pin_device_platform() -> None:
    """Honor JAX_PLATFORMS for the device (`check-tpu`) subcommands: this
    image's site config re-pins the axon TPU platform over a plain env var,
    so apply it at the jax.config level (same workaround as bench.py).
    Called only from device branches — host-only subcommands never import
    jax at all."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def argv_subcommand():
    return sys.argv[1] if len(sys.argv) > 1 else None


def argv_int(pos: int, default: int) -> int:
    try:
        return int(sys.argv[pos])
    except (IndexError, ValueError):
        return default


def argv_str(pos: int, default: str) -> str:
    try:
        return sys.argv[pos]
    except IndexError:
        return default


def argv_network(pos: int, default: str = "unordered_nonduplicating") -> Network:
    try:
        return Network.from_str(sys.argv[pos])
    except IndexError:
        return Network.from_str(default)


def report(checker) -> None:
    checker.report(WriteReporter())


def thread_count() -> int:
    return os.cpu_count() or 1


def network_names() -> str:
    return " | ".join(Network.names())
