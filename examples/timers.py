#!/usr/bin/env python
"""Timer-driven pinger example CLI (ref: examples/timers.rs:119-165)."""

from _cli import (
    argv_network,
    argv_str,
    argv_subcommand,
    network_names,
    report,
    thread_count,
)

from stateright_tpu.examples.timers import PingerModelCfg


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        network = argv_network(2)
        print("Model checking Pingers")
        report(
            PingerModelCfg(server_count=3, network=network)
            .into_model()
            .checker()
            .threads(thread_count())
            .spawn_dfs()
        )
    elif cmd == "explore":
        address = argv_str(2, "localhost:3000")
        network = argv_network(3)
        print(f"Exploring state space for Pingers on {address}.")
        PingerModelCfg(server_count=3, network=network).into_model().checker().serve(
            address, block=True
        )
    else:
        print("USAGE:")
        print("  ./timers.py check [NETWORK]")
        print("  ./timers.py explore [ADDRESS] [NETWORK]")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
