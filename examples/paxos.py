#!/usr/bin/env python
"""Single Decree Paxos example CLI (ref: examples/paxos.rs:354-510)."""

from _cli import (
    argv_int,
    argv_network,
    argv_str,
    argv_subcommand,
    network_names,
    report,
    thread_count,
)

from stateright_tpu.examples.paxos import PaxosModelCfg


def main():
    cmd = argv_subcommand()
    if cmd in ("check", "check-bfs", "check-dfs"):
        client_count = argv_int(2, 2)
        network = argv_network(3)
        print(f"Model checking Single Decree Paxos with {client_count} clients.")
        builder = (
            PaxosModelCfg(client_count=client_count, server_count=3, network=network)
            .into_model()
            .checker()
            .threads(thread_count())
        )
        checker = builder.spawn_dfs() if cmd == "check-dfs" else builder.spawn_bfs()
        report(checker)
    elif cmd == "check-tpu":
        client_count = argv_int(2, 2)
        if client_count > 3:
            print(
                "The hand tensor encoding supports at most 3 clients; for "
                "bigger configs lower the actor model generically "
                "(stateright_tpu.tensor.refine_check or closure='exact')."
            )
            return
        print(
            f"Model checking Single Decree Paxos with {client_count} clients "
            "on the device frontier checker."
        )
        from _cli import pin_device_platform

        pin_device_platform()
        from stateright_tpu.tensor.paxos import TensorPaxos

        batch, table = (2048, 16) if client_count <= 2 else (8192, 22)
        report(
            TensorPaxos(client_count=client_count)
            .checker()
            .spawn_tpu(batch_size=batch, table_log2=table)
        )
    elif cmd == "check-simulation":
        client_count = argv_int(2, 2)
        network = argv_network(3)
        print(f"Model checking Single Decree Paxos with {client_count} clients.")
        report(
            PaxosModelCfg(client_count=client_count, server_count=3, network=network)
            .into_model()
            .checker()
            .threads(thread_count())
            .timeout(10.0)
            .spawn_simulation(0)
        )
    elif cmd == "explore":
        client_count = argv_int(2, 2)
        address = argv_str(3, "localhost:3000")
        network = argv_network(4)
        print(
            f"Exploring state space for Single Decree Paxos with "
            f"{client_count} clients on {address}."
        )
        PaxosModelCfg(
            client_count=client_count, server_count=3, network=network
        ).into_model().checker().serve(address, block=True)
    elif cmd == "spawn":
        from stateright_tpu.actor import Id
        from stateright_tpu.actor.spawn import spawn
        from stateright_tpu.examples.paxos import PaxosActor

        port = 3000
        print("  A set of servers that implement Single Decree Paxos.")
        print("  You can monitor and interact using tcpdump and netcat, e.g.")
        print(f"$ nc -u localhost {port}")
        print('  {"Put": [1, "X"]}')
        print('  {"Get": [2]}')
        from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
        from stateright_tpu.examples.paxos import (
            Accept,
            Accepted,
            Decided,
            Prepare,
            Prepared,
        )

        ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
        spawn(
            [
                (ids[i], PaxosActor([pid for pid in ids if pid != ids[i]]))
                for i in range(3)
            ],
            msg_types=[
                Put, Get, PutOk, GetOk, Internal,
                Prepare, Prepared, Accept, Accepted, Decided,
            ],
        )
    else:
        print("USAGE:")
        print("  ./paxos.py check-dfs [CLIENT_COUNT] [NETWORK]")
        print("  ./paxos.py check-bfs [CLIENT_COUNT] [NETWORK]")
        print("  ./paxos.py check-simulation [CLIENT_COUNT] [NETWORK]")
        print("  ./paxos.py check-tpu [CLIENT_COUNT<=3]")
        print("  ./paxos.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  ./paxos.py spawn")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
