#!/usr/bin/env python
"""ABD linearizable register example CLI
(ref: examples/linearizable-register.rs:252-334)."""

from _cli import (
    argv_int,
    argv_network,
    argv_str,
    argv_subcommand,
    network_names,
    report,
    thread_count,
)

from stateright_tpu.examples.abd import AbdModelCfg


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        client_count = argv_int(2, 2)
        network = argv_network(3)
        print(f"Model checking a linearizable register with {client_count} clients.")
        report(
            AbdModelCfg(client_count=client_count, server_count=3, network=network)
            .into_model()
            .checker()
            .threads(thread_count())
            .spawn_dfs()
        )
    elif cmd == "explore":
        client_count = argv_int(2, 2)
        address = argv_str(3, "localhost:3000")
        network = argv_network(4)
        print(
            f"Exploring state space for linearizable register with "
            f"{client_count} clients on {address}."
        )
        AbdModelCfg(
            client_count=client_count, server_count=3, network=network
        ).into_model().checker().serve(address, block=True)
    elif cmd == "spawn":
        from stateright_tpu.actor import Id
        from stateright_tpu.actor.spawn import spawn
        from stateright_tpu.examples.abd import AbdActor

        port = 3000
        print("  A server that implements a linearizable register.")
        print(f"  Interact via UDP JSON, e.g. nc -u localhost {port}")
        from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
        from stateright_tpu.examples.abd import AckQuery, AckRecord, Query, Record

        ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
        spawn(
            [
                (ids[i], AbdActor([pid for pid in ids if pid != ids[i]]))
                for i in range(3)
            ],
            msg_types=[
                Put, Get, PutOk, GetOk, Internal,
                Query, AckQuery, Record, AckRecord,
            ],
        )
    else:
        print("USAGE:")
        print("  ./linearizable_register.py check [CLIENT_COUNT] [NETWORK]")
        print("  ./linearizable_register.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  ./linearizable_register.py spawn")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
