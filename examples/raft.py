#!/usr/bin/env python
"""Raft leader election example CLI — the model-zoo workload built for the
device simulation engine (ISSUE 14 / ROADMAP item 5): election safety
(ALWAYS) + leader elected (EVENTUALLY) on a tensor-encoded election protocol
whose bounded-term space explodes combinatorially with the server count.

Small configs (the default `check`) run the exhaustive device frontier
checker against the pinned goldens; `simulate` runs the fourth checker mode —
thousands of continuously-rebatched random walks with a shared visited
table — on spaces the exhaustive engines can't finish (try
`./raft.py simulate 7 7`)."""

from _cli import argv_int, report

from stateright_tpu.core.discovery import HasDiscoveries


def _model(server_count: int, max_term: int):
    from _cli import pin_device_platform

    pin_device_platform()
    from stateright_tpu.tensor.models import TensorRaft

    return TensorRaft(server_count, max_term)


def main():
    import sys

    cmd = sys.argv[1] if len(sys.argv) > 1 else None
    if cmd == "check":
        n = argv_int(2, 3)
        max_term = argv_int(3, 3)
        print(
            f"Checking Raft leader election with {n} servers, "
            f"terms <= {max_term} (exhaustive device frontier checker)."
        )
        report(_model(n, max_term).checker().spawn_tpu())
    elif cmd == "simulate":
        n = argv_int(2, 5)
        max_term = argv_int(3, 5)
        print(
            f"Simulating Raft leader election with {n} servers, "
            f"terms <= {max_term} (device random walks, shared visited "
            "table)."
        )
        report(
            _model(n, max_term)
            .checker()
            .finish_when(HasDiscoveries.ANY)
            .target_state_count(2_000_000)
            .spawn_tpu(
                mode="simulation",
                traces=2048,
                max_depth=256,
                dedup="shared",
                table_log2=22,
            )
        )
    else:
        print("USAGE:")
        print("  ./raft.py check [SERVER_COUNT] [MAX_TERM]")
        print("  ./raft.py simulate [SERVER_COUNT] [MAX_TERM]")


if __name__ == "__main__":
    main()
