#!/usr/bin/env python
"""External-input modeling example CLI (ref: examples/interaction.rs:17-68)."""

from _cli import argv_str, argv_subcommand, report, thread_count

from stateright_tpu.examples.interaction import build_model


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        # target_max_depth bounds the loosely-bounded space
        # (ref: examples/interaction.rs:43).
        checker = (
            build_model()
            .checker()
            .threads(thread_count())
            .target_max_depth(30)
            .spawn_bfs()
        )
        report(checker)
        checker.assert_properties()
    elif cmd == "explore":
        address = argv_str(2, "0.0.0.0:3000")
        build_model().checker().target_max_depth(30).serve(address, block=True)
    else:
        print("USAGE:")
        print("  ./interaction.py check")
        print("  ./interaction.py explore")


if __name__ == "__main__":
    main()
