#!/usr/bin/env python
"""LWW-register CRDT example CLI (ref: examples/lww-register.rs:188-262)."""

from _cli import argv_int, argv_str, argv_subcommand, report

from stateright_tpu.examples.lww_register import build_model


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        client_count = argv_int(2, 2)
        depth = argv_int(3, 8)
        report(
            build_model(client_count)
            .checker()
            .target_max_depth(depth)
            .spawn_dfs()
        )
    elif cmd == "explore":
        client_count = argv_int(2, 2)
        address = argv_str(3, "localhost:3000")
        print(
            f"Exploring state space for last-writer-wins register with "
            f"{client_count} clients on {address}."
        )
        build_model(client_count).checker().serve(address, block=True)
    elif cmd == "spawn":
        from stateright_tpu.actor import Id
        from stateright_tpu.actor.spawn import spawn
        from stateright_tpu.examples.lww_register import LwwActor

        port = 3000
        from stateright_tpu.examples.lww_register import LwwRegister

        ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
        print("  A server that implements a last-writer-wins register.")
        spawn(
            [
                (ids[i], LwwActor([pid for pid in ids if pid != ids[i]]))
                for i in range(3)
            ],
            msg_types=[LwwRegister],
        )
    else:
        print("USAGE:")
        print("  ./lww_register.py check [CLIENT_COUNT] [DEPTH]")
        print("  ./lww_register.py explore [CLIENT_COUNT] [ADDRESS]")
        print("  ./lww_register.py spawn")


if __name__ == "__main__":
    main()
