#!/usr/bin/env python
"""Two-phase commit example CLI (ref: examples/2pc.rs:172-253)."""

from _cli import argv_int, argv_str, argv_subcommand, report, thread_count

from stateright_tpu.examples.two_phase_commit import TwoPhaseSys


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        rm_count = argv_int(2, 2)
        print(f"Checking two phase commit with {rm_count} resource managers.")
        report(
            TwoPhaseSys(rm_count).checker().threads(thread_count()).spawn_dfs()
        )
    elif cmd == "check-bfs":
        rm_count = argv_int(2, 2)
        print(f"Checking two phase commit with {rm_count} resource managers.")
        report(
            TwoPhaseSys(rm_count).checker().threads(thread_count()).spawn_bfs()
        )
    elif cmd == "check-tpu":
        rm_count = argv_int(2, 2)
        print(
            f"Checking two phase commit with {rm_count} resource managers "
            "on the device frontier checker."
        )
        from _cli import pin_device_platform

        pin_device_platform()
        from stateright_tpu.tensor.models import TensorTwoPhaseSys

        report(TensorTwoPhaseSys(rm_count).checker().spawn_tpu())
    elif cmd == "check-sym":
        rm_count = argv_int(2, 2)
        print(
            f"Checking two phase commit with {rm_count} resource managers "
            "using symmetry reduction."
        )
        report(
            TwoPhaseSys(rm_count)
            .checker()
            .threads(thread_count())
            .symmetry()
            .spawn_dfs()
        )
    elif cmd == "explore":
        rm_count = argv_int(2, 2)
        address = argv_str(3, "localhost:3000")
        print(
            f"Exploring state space for two phase commit with {rm_count} "
            f"resource managers on {address}."
        )
        TwoPhaseSys(rm_count).checker().serve(address, block=True)
    else:
        print("USAGE:")
        print("  ./2pc.py check [RESOURCE_MANAGER_COUNT]")
        print("  ./2pc.py check-bfs [RESOURCE_MANAGER_COUNT]")
        print("  ./2pc.py check-tpu [RESOURCE_MANAGER_COUNT]")
        print("  ./2pc.py check-sym [RESOURCE_MANAGER_COUNT]")
        print("  ./2pc.py explore [RESOURCE_MANAGER_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
