#!/usr/bin/env python
"""Lock-fixed increment example CLI (ref: examples/increment_lock.rs)."""

from _cli import argv_int, argv_str, argv_subcommand, report, thread_count

from stateright_tpu.examples.increment import IncrementLockSys


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        n = argv_int(2, 3)
        print(f"Model checking increment_lock with {n} threads.")
        report(IncrementLockSys(n).checker().threads(thread_count()).spawn_dfs())
    elif cmd == "check-sym":
        n = argv_int(2, 3)
        print(
            f"Model checking increment_lock with {n} threads using symmetry reduction."
        )
        report(
            IncrementLockSys(n)
            .checker()
            .threads(thread_count())
            .symmetry()
            .spawn_dfs()
        )
    elif cmd in ("check-tpu", "check-tpu-sym"):
        n = argv_int(2, 3)
        sym = cmd == "check-tpu-sym"
        print(
            f"Model checking increment_lock with {n} threads on the device "
            f"frontier checker{' using symmetry reduction' if sym else ''}."
        )
        from _cli import pin_device_platform

        pin_device_platform()
        from stateright_tpu.tensor.models import TensorIncrementLock

        report(
            TensorIncrementLock(n, symmetry=sym)
            .checker()
            .spawn_tpu(batch_size=1024, table_log2=14)
        )
    elif cmd == "explore":
        n = argv_int(2, 3)
        address = argv_str(3, "localhost:3000")
        print(
            f"Exploring the state space of increment_lock with {n} threads on {address}."
        )
        IncrementLockSys(n).checker().serve(address, block=True)
    else:
        print("USAGE:")
        print("  ./increment_lock.py check [THREAD_COUNT]")
        print("  ./increment_lock.py check-sym [THREAD_COUNT]")
        print("  ./increment_lock.py check-tpu [THREAD_COUNT]")
        print("  ./increment_lock.py check-tpu-sym [THREAD_COUNT]")
        print("  ./increment_lock.py explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
