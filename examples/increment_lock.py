#!/usr/bin/env python
"""Lock-fixed increment example CLI (ref: examples/increment_lock.rs)."""

from _cli import argv_int, argv_str, argv_subcommand, report, thread_count

from stateright_tpu.examples.increment import IncrementLockSys


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        n = argv_int(2, 3)
        print(f"Model checking increment_lock with {n} threads.")
        report(IncrementLockSys(n).checker().threads(thread_count()).spawn_dfs())
    elif cmd == "check-sym":
        n = argv_int(2, 3)
        print(
            f"Model checking increment_lock with {n} threads using symmetry reduction."
        )
        report(
            IncrementLockSys(n)
            .checker()
            .threads(thread_count())
            .symmetry()
            .spawn_dfs()
        )
    elif cmd == "explore":
        n = argv_int(2, 3)
        address = argv_str(3, "localhost:3000")
        print(
            f"Exploring the state space of increment_lock with {n} threads on {address}."
        )
        IncrementLockSys(n).checker().serve(address, block=True)
    else:
        print("USAGE:")
        print("  ./increment_lock.py check [THREAD_COUNT]")
        print("  ./increment_lock.py check-sym [THREAD_COUNT]")
        print("  ./increment_lock.py explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
