#!/usr/bin/env python
"""Data-race increment example CLI (ref: examples/increment.rs:203-258)."""

from _cli import argv_int, argv_str, argv_subcommand, report, thread_count

from stateright_tpu.examples.increment import IncrementSys


def main():
    cmd = argv_subcommand()
    if cmd == "check":
        n = argv_int(2, 3)
        print(f"Model checking increment with {n} threads.")
        report(IncrementSys(n).checker().threads(thread_count()).spawn_dfs())
    elif cmd == "check-sym":
        n = argv_int(2, 3)
        print(f"Model checking increment with {n} threads using symmetry reduction.")
        report(
            IncrementSys(n).checker().threads(thread_count()).symmetry().spawn_dfs()
        )
    elif cmd == "explore":
        n = argv_int(2, 3)
        address = argv_str(3, "localhost:3000")
        print(f"Exploring the state space of increment with {n} threads on {address}.")
        IncrementSys(n).checker().serve(address, block=True)
    else:
        print("USAGE:")
        print("  ./increment.py check [THREAD_COUNT]")
        print("  ./increment.py check-sym [THREAD_COUNT]")
        print("  ./increment.py explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
