#!/usr/bin/env python
"""blobd: the standalone HTTP object-store emulator.

    python scripts/blobd.py [--address 0.0.0.0:3700]

Serves the conditional-put/generation-token blob protocol from
`stateright_tpu/faults/blobstore.py` (PUT /b/<name> with If-None-Match /
If-Match and server-side `.prev` rotation, GET /b/<name>, DELETE,
GET /list?prefix=, GET /healthz). Point a fleet at it with

    ServiceFleet(remote=True, store_root="blob://host:3700/myfleet")

or any `*_dir` knob spelled as a ``blob://`` URI — checkpoint
generations, lease records, corpus entries, member-discovery records,
and flush-synced journals then all live here, and the URI is the only
configuration the fleet's processes share. Storage is in-memory: an
emulator for development, CI, and chaos runs — the S3/GCS shape without
the credentials (the managed-store backend is the ROADMAP residue).

Stdlib-only (no jax import): runs anywhere.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--address", default="localhost:3700",
                    help="host:port to bind (default localhost:3700)")
    args = ap.parse_args(argv)

    from stateright_tpu.faults.blobstore import serve_blobd

    print(f"blobd serving blob://{args.address}", flush=True)
    serve_blobd(args.address, block=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
