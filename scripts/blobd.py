#!/usr/bin/env python
"""blobd: the standalone HTTP object-store emulator.

    python scripts/blobd.py [--address 0.0.0.0:3700] [--dialect blob|s3|gcs]

The default ``blob`` dialect serves the conditional-put/generation-token
blob protocol from `stateright_tpu/faults/blobstore.py` (PUT /b/<name>
with If-None-Match / If-Match and server-side `.prev` rotation,
GET /b/<name>, DELETE, GET /list?prefix=, GET /healthz). Point a fleet
at it with

    ServiceFleet(remote=True, store_root="blob://host:3700/myfleet")

or any `*_dir` knob spelled as a ``blob://`` URI — checkpoint
generations, lease records, corpus entries, member-discovery records,
and flush-synced journals then all live here, and the URI is the only
configuration the fleet's processes share.

``--dialect s3`` / ``--dialect gcs`` serve the provider-conformance
dialects instead (`stateright_tpu/faults/blobdialect.py`): SigV4 /
OAuth-bearer auth verification, provider error XML/JSON shapes,
conditional-write preconditions, and a credential plane (IMDSv2 /
GCE metadata + token grant). The process prints the environment to
export so ``s3://bucket/...`` or ``gs://bucket/...`` roots resolve
to it.

Storage is in-memory: an emulator for development, CI, and chaos runs.
Stdlib-only (no jax import): runs anywhere.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--address", default="localhost:3700",
                    help="host:port to bind (default localhost:3700)")
    ap.add_argument("--dialect", choices=("blob", "s3", "gcs"),
                    default="blob",
                    help="wire protocol: native blob (default), or the "
                         "s3/gcs provider-conformance dialects")
    args = ap.parse_args(argv)

    from stateright_tpu.faults.blobstore import serve_blobd

    handle = serve_blobd(args.address, block=False, dialect=args.dialect)
    print(f"blobd[{handle.dialect}] serving {handle.root_uri} "
          f"on {handle.address}", flush=True)
    for key, val in sorted(handle.env.items()):
        print(f"  export {key}={val}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        handle.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
