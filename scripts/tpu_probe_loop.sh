#!/bin/bash
# Continuous tunnel probe: one fresh subprocess every ~5 min, logging to
# /tmp/tpu_probe_r5.log. Exits (leaving PROBE_OK as the last line) the
# moment a probe succeeds so a watcher can react.
LOG=/tmp/tpu_probe_r5.log
while true; do
  echo "$(date -u +%FT%TZ) probing..." >> "$LOG"
  if timeout 150 python -c "
import jax
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
x = jax.jit(lambda a: a*2+1)(jnp.arange(8)); x.block_until_ready()
print('PROBE_OK', jax.devices())
" >> "$LOG" 2>&1; then
    if tail -3 "$LOG" | grep -q PROBE_OK; then
      echo "$(date -u +%FT%TZ) TUNNEL ALIVE" >> "$LOG"
      exit 0
    fi
  fi
  sleep 300
done
