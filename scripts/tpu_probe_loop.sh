#!/bin/bash
# Continuous tunnel probe; on the FIRST successful probe it immediately
# runs the staged tunnel-day sequence (scripts/tunnel_day.sh: tune sweep +
# hashtable/kv races + full bench) so even a transient tunnel window turns
# into silicon numbers. Log: /tmp/tpu_probe_r5.log; tunnel-day output under
# /tmp/tunnel_day.
LOG=/tmp/tpu_probe_r5.log
cd /root/repo || exit 1
while true; do
  echo "$(date -u +%FT%TZ) probing..." >> "$LOG"
  if timeout 150 python -c "
import jax
assert jax.devices()[0].platform != 'cpu', jax.devices()
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
x = jax.jit(lambda a: a*2+1)(jnp.arange(8)); x.block_until_ready()
print('PROBE_OK', jax.devices())
" >> "$LOG" 2>&1; then
    if tail -3 "$LOG" | grep -q PROBE_OK; then
      echo "$(date -u +%FT%TZ) TUNNEL ALIVE - launching tunnel_day.sh" >> "$LOG"
      bash scripts/tunnel_day.sh /tmp/tunnel_day >> "$LOG" 2>&1
      echo "$(date -u +%FT%TZ) tunnel_day.sh finished rc=$?" >> "$LOG"
      exit 0
    fi
  fi
  sleep 300
done
