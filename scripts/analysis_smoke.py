#!/usr/bin/env python
"""Analysis smoke: the full static-analysis CLI against the repo, end-to-end.

CI-shaped proof of the analysis subsystem (stateright_tpu/analysis/) in one
command: runs `python -m stateright_tpu.analysis` as a subprocess exactly
the way CI does (fresh interpreter, 8-device CPU mesh for the sharded
anchor), requires exit 0 + a clean summary line, then seeds one known-bad
fixture per srlint rule through lint_source to prove the gate still has
teeth — a lint pass that silently stopped firing would otherwise look
identical to a clean repo. Exit code 0 iff every check passes.

    python scripts/analysis_smoke.py [--skip-audit]

--skip-audit skips the jaxpr half of the CLI run (for jax-free images);
the srlint teeth checks always run.
"""

import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: one minimal tripwire per srlint rule: (rule, fixture source).
TRIPWIRES = [
    ("SR001", """\
        import jax

        def step(c):
            return c + c.sum().item()

        jitted = jax.jit(step)
        """),
    ("SR002", """\
        import numpy as np

        def save(path, t):
            np.savez(path, t=t)
        """),
    ("SR003", """\
        def build(detail):
            detail["invented_counter"] = 1
        """),
    ("SR004", """\
        def transfer(buf):
            raise RuntimeError("boom")
        """),
    ("SR005", """\
        def build(store):
            return store == "teired"
        """),
]


def main(argv) -> int:
    failures = []

    def check(ok: bool, what: str):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    # 1) The CLI, exactly as CI invokes it. JAX_PLATFORMS pinned so the
    # audit traces on CPU wherever this runs; the module sets the 8-device
    # flag itself.
    cmd = [sys.executable, "-m", "stateright_tpu.analysis"]
    if "--skip-audit" in argv:
        cmd.append("--skip-audit")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        cmd, cwd=ROOT, env=env, capture_output=True, text=True, timeout=600
    )
    sys.stdout.write(textwrap.indent(proc.stdout, "     | "))
    check(proc.returncode == 0, f"CLI exit 0 (got {proc.returncode})")
    check("analysis: clean" in proc.stdout, "CLI reports 'analysis: clean'")
    check("srlint: 0 finding(s)" in proc.stdout, "srlint repo run is clean")
    if "--skip-audit" not in argv:
        check(
            proc.stdout.count("audit ") >= 3,
            "all three engine anchors audited",
        )

    # 2) The gate has teeth: each rule still fires on its tripwire.
    from stateright_tpu.analysis.srlint import lint_source

    for rule, src in TRIPWIRES:
        found = lint_source(
            textwrap.dedent(src),
            module="stateright_tpu.store.fixture",
            root=ROOT,
        )
        check(
            any(f.rule == rule for f in found),
            f"{rule} fires on its known-bad fixture",
        )

    print(
        "analysis smoke:",
        "PASS" if not failures else f"{len(failures)} FAILURE(S)",
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
