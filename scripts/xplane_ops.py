"""Per-op time attribution from a jax.profiler trace — the one-command form
of the analysis that cracked round 4's biggest win (the DUS queue append:
trace-viewer totals hid the row-scatter cost inside a mega-fusion; the
xplane op stats named it).

Usage:
  TPU_TUNE_TRACE=/tmp/tr python scripts/tpu_tune.py paxos 3 3072 22 3
  python scripts/xplane_ops.py /tmp/tr [top_n] [tool]

tool: hlo_stats (default) | framework_op_stats | op_profile — whatever the
installed xprof converter supports; output is the tool's JSON/CSV reduced to
the top-N self-time rows.
"""
import csv
import glob
import io
import json
import os
import sys


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    trace_dir = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    tool = sys.argv[3] if len(sys.argv) > 3 else "hlo_stats"
    paths = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        )
    )
    if not paths:
        print(f"no *.xplane.pb under {trace_dir}")
        return 1
    print(f"xplane: {paths[-1]}", file=sys.stderr)

    from xprof.convert import raw_to_tool_data as r

    data, ctype = r.xspace_to_tool_data([paths[-1]], tool, {})
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")

    def gviz_rows(payload):
        """GViz table(s) -> list of dict rows (first table that has any)."""
        tables = payload if isinstance(payload, list) else [payload]
        for t in tables:
            if isinstance(t, dict) and t.get("rows"):
                cols = [c.get("label") or c.get("id") for c in t["cols"]]
                return [
                    dict(zip(cols, [c.get("v") for c in row["c"]]))
                    for row in t["rows"]
                ]
        return []

    rows = None
    if "json" in ctype:
        payload = json.loads(data)
        rows = gviz_rows(payload)
        if not rows:
            print(json.dumps(payload)[:4000])
            return 0
    else:  # CSV
        rows = list(csv.DictReader(io.StringIO(data)))
    if not rows:
        print("no rows")
        return 1

    # Find a self-time-like column to rank by.
    keys = rows[0].keys()
    rank_key = next(
        (
            k
            for k in keys
            if k and "self" in k.lower() and "time" in k.lower()
        ),
        None,
    ) or next((k for k in keys if k and "time" in k.lower()), None)
    if rank_key is None:
        print(f"columns: {sorted(keys)}")
        return 1

    def num(v):
        try:
            return float(v)
        except (TypeError, ValueError):
            return 0.0

    rows.sort(key=lambda x: num(x.get(rank_key)), reverse=True)
    total = sum(num(x.get(rank_key)) for x in rows)
    name_key = next(
        (
            k
            for pref in ("hlo op name", "operation", "name", "op")
            for k in keys
            if k and pref in k.lower() and "type" not in k.lower()
        ),
        list(keys)[0],
    )
    print(f"rank by {rank_key!r} (total {total:,.0f}); name {name_key!r}")
    for x in rows[:top_n]:
        t = num(x.get(rank_key))
        pct = 100 * t / total if total else 0
        print(f"{t:>14,.0f} {pct:5.1f}%  {str(x.get(name_key))[:90]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
