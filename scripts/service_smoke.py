#!/usr/bin/env python
"""Check-service smoke: submit 8 mixed jobs, assert all complete, print
jobs/sec.

CI-shaped: exercises the whole serving path — admission, continuous
batching across model groups, shared-table salting, result/discovery
retrieval — in one command. Exit code 0 iff every job completed with its
expected golden counts.

    JAX_PLATFORMS=cpu python scripts/service_smoke.py [--tiered]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD = {
    "2pc-3": (1_146, 288),
    "2pc-4": (8_258, 1_568),
    "inclock-4": (257, 257),
}


def main(argv) -> int:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        # The image's site config re-registers the axon TPU platform over a
        # plain env var; pin at the jax.config level (same move as bench.py).
        jax.config.update("jax_platforms", p)

    from stateright_tpu.service import CheckService
    from stateright_tpu.tensor.models import (
        TensorIncrementLock,
        TensorTwoPhaseSys,
    )

    tiered = "--tiered" in argv
    m3, m4, mi = (
        TensorTwoPhaseSys(3), TensorTwoPhaseSys(4), TensorIncrementLock(4)
    )
    jobs = [
        ("2pc-3", m3), ("2pc-3", m3), ("2pc-3", m3),
        ("2pc-4", m4), ("2pc-4", m4), ("2pc-4", m4),
        ("inclock-4", mi), ("inclock-4", mi),
    ]
    svc = CheckService(
        batch_size=512,
        table_log2=16,
        **(
            {"store": "tiered", "high_water": 0.7, "summary_log2": 16}
            if tiered
            else {}
        ),
    )
    t0 = time.monotonic()
    handles = [(name, svc.submit(m)) for name, m in jobs]
    svc.drain(timeout=600)
    sec = time.monotonic() - t0

    failures = []
    for name, h in handles:
        r = h.result()
        got = (r.state_count, r.unique_state_count)
        if got != GOLD[name] or not r.complete:
            failures.append(f"job {h.id} ({name}): {got} != {GOLD[name]}")
        print(
            f"job {h.id} {name}: states={r.state_count} "
            f"unique={r.unique_state_count} steps={r.steps} "
            f"complete={r.complete} metrics={h.metrics()}"
        )
    print(
        f"{len(jobs)} jobs in {sec:.2f}s -> {len(jobs) / sec:.2f} jobs/sec "
        f"({svc.stats()['device_steps']} fused device steps, "
        f"{svc.stats()['groups']} model groups)"
    )
    if tiered:
        print("store:", svc.store_stats())
    svc.close()
    if failures:
        print("FAILURES:", "; ".join(failures), file=sys.stderr)
        return 1
    print("service smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
