"""Two-process `jax.distributed` validation of ShardedSearch (VERDICT r3 #7).

Each process contributes 4 virtual CPU devices (gloo collectives) and runs
the SAME SPMD program: one 8-device global mesh, one whole-search dispatch.
This proves the `make_mesh` multi-host claim — under
`jax.distributed.initialize()` the engine code is unchanged; the all_to_all
successor shuffle and psum termination ride the cross-process transport
(gloo here; ICI/DCN on real multi-host TPU slices).

Run one process per rank (the test harness does this):

    python scripts/multihost_sharded.py --num-processes 2 --process-id 0 \
        --coordinator 127.0.0.1:19735
    python scripts/multihost_sharded.py --num-processes 2 --process-id 1 \
        --coordinator 127.0.0.1:19735

Each rank prints one JSON line with the global counts; the counts must be
identical on every rank and match the single-process goldens
(2PC-4: 8,258 generated / 1,568 unique — BASELINE_MEASURED.md).
"""

import argparse
import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--coordinator", default="127.0.0.1:19735")
    ap.add_argument("--devices-per-process", type=int, default=4)
    ap.add_argument(
        "--checkpoint",
        default=None,
        help="suspend mid-search, checkpoint to this path (rank 0 writes), "
        "then resume to completion",
    )
    args = ap.parse_args()

    # Env must be set before jax initializes its backends. Any inherited
    # device-count flag (e.g. the test conftest's =8) must be REPLACED, not
    # kept — each rank contributes exactly devices_per_process devices.
    os.environ["JAX_PLATFORMS"] = "cpu"
    kept = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    kept.append(
        "--xla_force_host_platform_device_count="
        f"{args.devices_per_process}"
    )
    os.environ["XLA_FLAGS"] = " ".join(kept)

    import jax

    jax.config.update("jax_platforms", "cpu")
    # Cross-process collectives on the CPU backend need a real transport.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )

    from stateright_tpu.parallel import ShardedSearch, make_mesh
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    n_global = args.num_processes * args.devices_per_process
    assert len(jax.devices()) == n_global, (
        f"expected {n_global} global devices, got {len(jax.devices())}"
    )

    search = ShardedSearch(
        TensorTwoPhaseSys(4),
        mesh=make_mesh(n_global),
        batch_size=256,
        table_log2=12,
    )
    ckpt_exists = None
    if args.checkpoint:
        # Cross-process checkpoint contract: EVERY rank calls checkpoint()
        # (the carry gather is a collective); only process 0 writes.
        from jax.experimental import multihost_utils

        from stateright_tpu.tensor.resident import _ckpt_path

        r = search.run(budget=6, max_steps=6)  # suspend mid-search
        search.checkpoint(args.checkpoint)
        # Barrier before the existence check: rank 0 returns from
        # checkpoint() only after writing, other ranks return right after
        # the collective gather — without the sync their check races the
        # write.
        multihost_utils.sync_global_devices("ckpt-written")
        ckpt_exists = os.path.exists(_ckpt_path(args.checkpoint))
        r = search.run()  # then finish from the suspended carry
    else:
        r = search.run()
    out = {
        "process_id": args.process_id,
        "num_processes": args.num_processes,
        "global_devices": n_global,
        "local_devices": jax.local_device_count(),
        "generated": r.state_count,
        "unique": r.unique_state_count,
        "max_depth": r.max_depth,
        "complete": r.complete,
        "discoveries": sorted(r.discoveries),
        "per_chip_unique": r.detail["per_chip_unique"],
        "checkpoint_file_exists": ckpt_exists,
    }
    print("MULTIHOST_RESULT " + json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
