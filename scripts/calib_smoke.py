#!/usr/bin/env python
"""Calibration-observatory smoke (ISSUE 19): the full drift story e2e.

Four phases on the CPU 2pc-3 anchor, one command:

  A. cold run         — comparator populates `detail["calib"]` and flushes
                        durable observation records (obs/calib.py).
  B. mis-scaled model — a deliberately wrong coefficient overlay
                        (SR_TPU_COSTMODEL_CALIB) trips the drift detector:
                        `calib.drift_*` counters, the journaled
                        `calib.drift` event, and the timeline CLI report
                        naming engine/term/jobs. Search results stay
                        bit-identical — the observatory observes, never
                        steers.
  C. fit              — `tpu_tune --calibrate` least-squares-fits theta
                        from phase-B's recorded observations and writes a
                        fitted overlay.
  D. fitted run       — the fitted overlay pulls measured/predicted back
                        toward 1 (>=2x closer than the mis-scaled run).

    JAX_PLATFORMS=cpu python scripts/calib_smoke.py [--keep]

Exit code 0 iff every check passes. Artifacts land in a temp dir (kept
with --keep, printed either way).
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv) -> int:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        # The image's site config re-registers the axon TPU platform over a
        # plain env var; pin at the jax.config level (same move as bench.py).
        jax.config.update("jax_platforms", p)

    from stateright_tpu.obs.calib import default_device_kind, theta_of
    from stateright_tpu.service import CheckService
    from stateright_tpu.tensor import costmodel as cm
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    keep = "--keep" in argv
    outdir = tempfile.mkdtemp(prefix="calib_smoke_")
    failures = []

    def check(ok: bool, what: str):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    # Small chunks so the short anchor run closes several comparison
    # windows (K=3 consecutive out-of-band chunks arm a drift episode).
    os.environ["SR_TPU_CALIB_CHUNK"] = "4"
    kind = default_device_kind()
    stock = cm.stock_device(kind)
    model = TensorTwoPhaseSys(3)

    def run_phase(tag: str, repeats: int = 1):
        """One service run; returns (results, calib detail, counters)."""
        os.environ["SR_TPU_CALIB_DIR"] = os.path.join(outdir, f"rec_{tag}")
        svc = CheckService(
            batch_size=128, table_log2=12, background=False,
            events_out=os.path.join(outdir, f"journal_{tag}.jsonl"),
        )
        results = []
        for _ in range(repeats):
            h = svc.submit(model)
            svc.drain(timeout=600)
            results.append(h.result())
        calib = (results[-1].detail or {}).get("calib")
        counters = (
            svc._engine._calib.metrics()
            if svc._engine._calib is not None else {}
        )
        svc.close()
        return results, calib, counters

    # -- A: cold run, stock coefficients ---------------------------------
    os.environ.pop("SR_TPU_COSTMODEL_CALIB", None)
    res_a, calib_a, _ = run_phase("a")
    golden = (res_a[0].state_count, res_a[0].unique_state_count)
    check(calib_a is not None and calib_a["chunks"] > 0,
          f"A: comparator populated ({calib_a and calib_a['chunks']} chunks, "
          f"drift_ratio {calib_a and calib_a['drift_ratio']})")
    check(os.path.isdir(os.path.join(outdir, "rec_a", "calib")),
          "A: durable observation records flushed")

    # -- B: deliberately mis-scaled overlay ------------------------------
    # Every bandwidth 1000x too fast, every per-element/dispatch term
    # 1000x too small: predicted collapses toward 0, measured/predicted
    # blows out the [0.7, 1.4] band on every chunk.
    bad = {
        "base": kind,
        "rates": {
            "gbps_gather": stock.gbps_gather * 1e3,
            "gbps_sort": stock.gbps_sort * 1e3,
            "gbps_scatter": stock.gbps_scatter * 1e3,
            "gbps_stream": stock.gbps_stream * 1e3,
            "ns_expand_elem": stock.ns_expand_elem / 1e3,
            "ns_other_lane": stock.ns_other_lane / 1e3,
            "ms_dispatch": stock.ms_dispatch / 1e3,
            "pcie_gbps": stock.pcie_gbps * 1e3,
        },
    }
    bad_path = os.path.join(outdir, "bad_overlay.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    os.environ["SR_TPU_COSTMODEL_CALIB"] = bad_path
    res_b, calib_b, counters_b = run_phase("b", repeats=3)
    check(all((r.state_count, r.unique_state_count) == golden
              for r in res_b),
          "B: search results bit-identical under mis-scaled overlay")
    check(counters_b.get("drift_events", 0) >= 1
          and counters_b.get("out_of_band", 0) >= 3,
          f"B: drift detector tripped (drift_events="
          f"{counters_b.get('drift_events')}, out_of_band="
          f"{counters_b.get('out_of_band')})")
    journal_b = os.path.join(outdir, "journal_b.jsonl")
    drifted = [
        json.loads(line) for line in open(journal_b)
        if '"calib.drift"' in line
    ]
    check(len(drifted) >= 1 and drifted[0].get("engine") == "service"
          and drifted[0].get("term"),
          f"B: calib.drift journaled (term {drifted and drifted[0]['term']})")

    # Timeline CLI names job/engine/term — and drift is NOT an anomaly.
    tl = subprocess.run(
        [sys.executable, "-m", "stateright_tpu.obs.timeline",
         journal_b, "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    check(tl.returncode == 0, f"timeline: exit 0 (got {tl.returncode})")
    rep = json.loads(tl.stdout) if tl.stdout.strip() else {}
    rows = rep.get("drift") or []
    check(bool(rows) and rows[0].get("engine") and rows[0].get("term"),
          f"timeline: drift report names engine/term ({rows[:1]})")
    check(not rep.get("anomalies"),
          "timeline: drift is not a lifecycle anomaly")

    # -- C: fit from the recorded observations ---------------------------
    fit_path = os.path.join(outdir, "fit_overlay.json")
    fit = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpu_tune.py"),
         "--calibrate", os.path.join(outdir, "rec_b"),
         "--device", kind, "--out", fit_path],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    sys.stdout.write(fit.stdout)
    check(fit.returncode == 0 and os.path.exists(fit_path),
          "C: tpu_tune --calibrate wrote the fitted overlay")
    overlay = json.load(open(fit_path))
    check(overlay.get("base") == kind
          and len(overlay.get("theta", [])) == len(theta_of(stock)),
          "C: overlay is the loadable costmodel shape")

    # -- D: fitted overlay restores the band -----------------------------
    os.environ["SR_TPU_COSTMODEL_CALIB"] = fit_path
    res_d, calib_d, _ = run_phase("d")
    check(all((r.state_count, r.unique_state_count) == golden
              for r in res_d),
          "D: search results bit-identical under fitted overlay")
    drift_b = abs(calib_b["drift_ratio"] - 1.0)
    drift_d = abs(calib_d["drift_ratio"] - 1.0)
    check(drift_d * 2 <= drift_b,
          f"D: fitted overlay >=2x closer to measured "
          f"(|ratio-1| {drift_b:.3f} -> {drift_d:.3f})")
    lo, hi = 0.7, 1.4
    in_band = lo <= calib_d["drift_ratio"] <= hi
    print(f"     D drift_ratio {calib_d['drift_ratio']:.3f} "
          f"({'inside' if in_band else 'outside'} the [{lo}, {hi}] band; "
          "CPU step times are compile/noise-heavy, the >=2x restoration "
          "above is the pinned check)")

    print(f"artifacts in {outdir}" + ("" if keep else " (temp)"))
    if failures:
        print(f"{len(failures)} FAILURE(S)")
        return 1
    print("calib smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
