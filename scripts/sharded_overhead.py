"""Sharded-vs-single overhead ratio on the virtual CPU mesh (VERDICT r4
next #4): run the SAME workload on the single-device resident engine and on
the N-device sharded engine, print states/s for both and the ratio.

Usage: python scripts/sharded_overhead.py [workload=2pc7] [n_chips=8]
Workloads: 2pc7 | 2pc5 | paxos2-lowered | paxos5s4c-10
"""
import math
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
n_chips = int(sys.argv[2]) if len(sys.argv) > 2 else 8
flags = os.environ.get("XLA_FLAGS", "")
want = f"--xla_force_host_platform_device_count={n_chips}"
if want not in flags:
    # Strip any stale device-count flag (a leftover value would silently
    # size the mesh wrong) and pin the requested one.
    flags = " ".join(
        f for f in flags.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
import jax
jax.config.update("jax_platforms", "cpu")

from stateright_tpu.parallel import ShardedSearch, make_mesh
from stateright_tpu.tensor.resident import ResidentSearch

wl = sys.argv[1] if len(sys.argv) > 1 else "2pc7"
if wl in ("2pc7", "2pc5"):
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    n = int(wl[3:])
    model = TensorTwoPhaseSys(n)
    batch, table = (4096, 20) if n == 7 else (1024, 16)
    golden = {7: (2_744_706, 296_448), 5: (58_146, 8_832)}[n]
elif wl == "paxos5s4c-10":
    from bench import _paxos5s4c_lowered

    t0 = time.monotonic()
    model = _paxos5s4c_lowered(10)
    print(f"closure: {time.monotonic()-t0:.1f}s", flush=True)
    batch, table = 4096, 19
    st = model.closure_stats
    golden = (st["generated"], st["unique"])
elif wl == "paxos2-lowered":
    from stateright_tpu.actor import Network
    from stateright_tpu.actor.register import GetOk
    from stateright_tpu.examples.paxos import NULL_VALUE, PaxosModelCfg
    from stateright_tpu.tensor import TensorProperty
    from stateright_tpu.tensor.lowering import lower_actor_model

    cfg = PaxosModelCfg(
        client_count=2, server_count=3,
        network=Network.new_unordered_nonduplicating(),
    )

    def properties(view):
        lin = view.history_pred(
            lambda h: h.is_consistent()
        )
        chosen = view.any_env(
            lambda e: isinstance(e.msg, GetOk) and e.msg.value != NULL_VALUE
        )
        return [
            TensorProperty.always("linearizable", lambda m, s: lin(s)),
            TensorProperty.sometimes("value chosen", lambda m, s: chosen(s)),
        ]

    t0 = time.monotonic()
    model = lower_actor_model(
        cfg.into_model(), properties=properties, closure="exact"
    )
    print(f"closure: {time.monotonic()-t0:.1f}s", flush=True)
    batch, table = 1024, 17
    golden = (32_971, 16_668)
else:
    raise SystemExit(f"unknown workload {wl}")


RUN_KW = {"target_max_depth": 10} if wl == "paxos5s4c-10" else {}


def best_of(mk, runs=2):
    s = mk()
    r = s.run(**RUN_KW)  # compile + first
    best = r
    for _ in range(runs):
        r = s.run(**RUN_KW)
        if r.duration < best.duration:
            best = r
    return best


single = best_of(lambda: ResidentSearch(model, batch_size=batch, table_log2=table))
assert (single.state_count, single.unique_state_count) == golden, single
sps_single = single.state_count / single.duration
print(f"single-device: {sps_single:,.0f} states/s ({single.duration:.2f}s)")

mesh = make_mesh(n_chips)
shard = best_of(
    lambda: ShardedSearch(
        model,
        mesh=mesh,
        batch_size=max(batch // n_chips, 64),
        table_log2=table - int(math.log2(n_chips)),
    )
)
assert (shard.state_count, shard.unique_state_count) == golden, shard
sps_shard = shard.state_count / shard.duration
print(f"sharded-{n_chips}:  {sps_shard:,.0f} states/s ({shard.duration:.2f}s)")
print(
    f"RATIO sharded/single = {sps_shard / sps_single:.3f} "
    f"(>0.5 means <2x overhead — VERDICT r4 next #4 target)"
)
