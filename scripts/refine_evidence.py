"""Timing evidence for warm-round refinement (VERDICT r4 next #6): refine
paxos-C end-to-end on this host and print per-round + total wall time.
Usage: python scripts/refine_evidence.py [clients=2] [batch=2048] [table_log2=21]
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")

from stateright_tpu.actor import Network
from stateright_tpu.actor.register import GetOk
from stateright_tpu.examples.paxos import NULL_VALUE, PaxosModelCfg
from stateright_tpu.tensor.lowering import refine_check
from stateright_tpu.tensor.model import TensorProperty

C = int(sys.argv[1]) if len(sys.argv) > 1 else 2
B = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
T = int(sys.argv[3]) if len(sys.argv) > 3 else 21

cfg = PaxosModelCfg(
    client_count=C, server_count=3,
    network=Network.new_unordered_nonduplicating(),
)

def properties(view):
    lin = view.history_pred(lambda h: h.is_consistent())
    chosen = view.any_env(
        lambda e: isinstance(e.msg, GetOk) and e.msg.value != NULL_VALUE
    )
    return [
        TensorProperty.always("linearizable", lambda m, s: lin(s)),
        TensorProperty.sometimes("value chosen", lambda m, s: chosen(s)),
    ]

t0 = time.monotonic()

def prog(rnd, gaps, result):
    print(
        f"  round {rnd}: {gaps} gaps, {result.state_count:,} gen, "
        f"+{time.monotonic()-t0:.1f}s",
        flush=True,
    )

r, lowered = refine_check(
    cfg.into_model(),
    batch_size=B,
    table_log2=T,
    seed_states=2048,
    max_rounds=96,
    progress=prog,
    properties=properties,
    max_histories=1 << 17,
    max_local_states=1 << 16,
    max_envelopes=1 << 15,
)
dt = time.monotonic() - t0
print(
    f"paxos-{C} refined: {r.unique_state_count:,} unique / "
    f"{r.state_count:,} gen complete={r.complete} "
    f"{sorted(r.discoveries)}"
)
print(f"TOTAL {dt:.1f}s")
