#!/usr/bin/env python
"""Device-simulation smoke: host walker -> device cold -> device shared-dedup,
end to end on one model (2pc-3).

CI-shaped: exercises the whole fourth-checker-mode plane (ISSUE 14,
stateright_tpu/tensor/simulation.py) in one command —

1. HOST: the thread-pool `SimulationChecker` walks the 2pc-3 anchor to a
   state budget (the reference's per-thread trace loop).
2. DEVICE COLD: the continuous-batched device engine with per-walk dedup
   (`dedup="trace"` — host-parity accounting, unique == states) through
   the first-class wiring (`spawn_tpu(mode="simulation")`).
3. DEVICE SHARED: the shared visited table (`dedup="shared"`) — real
   unique coverage bounded by the exhaustive golden, nonzero dedup hits.

Asserts: identical property verdicts on all three sides (abort agreement
found, safety never violated), nonzero lane restarts (continuous batching
actually engaged), and a replayable counterexample path (the discovery
re-executes through the model to a valid `Path`).

Exit code 0 iff every phase agreed.

    JAX_PLATFORMS=cpu python scripts/sim_smoke.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from stateright_tpu.core.discovery import HasDiscoveries
    from stateright_tpu.examples.two_phase_commit import TwoPhaseSys
    from stateright_tpu.tensor.models import TensorTwoPhaseSys
    from stateright_tpu.tensor.simulation import DeviceSimulation

    target = 30_000
    failures = []

    # -- 1. host walker --------------------------------------------------------
    t0 = time.monotonic()
    host = (
        TwoPhaseSys(3)
        .checker()
        .target_state_count(target)
        .spawn_simulation(seed=0)
        .join()
    )
    host_sec = time.monotonic() - t0
    host_found = set(host.discoveries())
    print(
        f"host: {host.state_count()} states in {host_sec:.2f}s, "
        f"found={sorted(host_found)}"
    )

    # -- 2. device cold (per-walk dedup, first-class wiring) -------------------
    t0 = time.monotonic()
    cold = (
        TensorTwoPhaseSys(3)
        .checker()
        .finish_when(HasDiscoveries.ALL)
        .target_state_count(target)
        .spawn_tpu(mode="simulation", traces=256, max_depth=64)
        .join()
    )
    cold_sec = time.monotonic() - t0
    cold_found = set(cold.discoveries())
    cold_tel = cold.telemetry_summary()
    print(
        f"device cold: {cold.state_count()} states in {cold_sec:.2f}s "
        f"(walks={cold_tel['walks']}, restarts={cold_tel['restarts']}, "
        f"lane_util={cold_tel['lane_util']}), found={sorted(cold_found)}"
    )
    if cold.unique_state_count() != cold.state_count():
        failures.append("device cold: unique != states under dedup='trace'")
    if cold_tel["restarts"] == 0:
        failures.append("device cold: continuous batching never restarted")

    # -- 3. device shared-dedup ------------------------------------------------
    sim = DeviceSimulation(
        TensorTwoPhaseSys(3), seed=0, traces=256, max_depth=64,
        dedup="shared", table_log2=16,
    )
    r = sim.run()
    while r.state_count < target:
        r = sim.run()
    tel = r.detail["telemetry"]
    shared_found = set(r.discoveries)
    print(
        f"device shared: {r.state_count} states, unique={r.unique_state_count} "
        f"(dedup_hit_rate={tel['dedup_hit_rate']}, walks={tel['walks']}), "
        f"found={sorted(shared_found)}"
    )
    if not 0 < r.unique_state_count <= 288:
        failures.append(
            f"device shared: unique {r.unique_state_count} outside the "
            "2pc-3 exhaustive golden bound (288)"
        )
    if tel["dedup_hit_rate"] <= 0:
        failures.append("device shared: dedup never hit")
    if tel["restarts"] == 0:
        failures.append("device shared: continuous batching never restarted")

    # -- verdict parity across all three sides ---------------------------------
    for found, side in (
        (host_found, "host"),
        (cold_found, "device-cold"),
        (shared_found, "device-shared"),
    ):
        if "abort agreement" not in found:
            failures.append(f"{side}: missed 'abort agreement'")
        if "consistent" in found:
            failures.append(f"{side}: safety 'consistent' falsely violated")
    if host_found != cold_found or host_found != shared_found:
        failures.append(
            f"verdict sets differ: host={sorted(host_found)} "
            f"cold={sorted(cold_found)} shared={sorted(shared_found)}"
        )

    # -- replayable counterexample path ----------------------------------------
    name = "abort agreement"
    if name in shared_found:
        path = sim.discovery_path(name)
        states = path.states()
        if len(states) != len(sim._discoveries[name]):
            failures.append(
                f"discovery path replay length {len(states)} != recorded "
                f"fingerprint chain {len(sim._discoveries[name])}"
            )
        else:
            print(
                f"replayed '{name}' counterexample: {len(states)} states, "
                f"ends at {states[-1]}"
            )

    if failures:
        print("\nSIM SMOKE FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("\nSIM SMOKE OK: host/device verdicts identical, restarts "
          "engaged, counterexample replays.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
