"""Cross-process fleet smoke: real subprocesses, kill -9, a zombie, and a
partition — then the forensic timeline must read clean.

The ISSUE 12 acceptance run, end to end. Each phase starts a 3-process
fleet (`ServiceFleet(remote=True)`: one `replica_main` subprocess per
replica over a shared store root, epoch-fence lease plane + flight
recorder on), pins a same-route-key job backlog on one victim replica
(steal disabled, max_resident=1 — so the victim still holds running AND
queued jobs when it is interrupted), then:

1. **kill -9** — SIGKILL the victim mid-job: lease revoked, orphans
   requeued onto survivors from re-sealed checkpoint generations;
2. **zombie** — SIGSTOP the victim until the router declares it dead,
   then SIGCONT: the resurrected zombie keeps stepping orphaned job
   copies and every write it attempts is fenced (refused write-side,
   rejected read-side), counted as lease.rejected > 0, never read back;
3. **partition** — inject `fleet.partition` against the victim: the
   router sees it dead while the PROCESS keeps running — the
   false-positive death, fenced exactly like the zombie.

In every phase all jobs complete with counts bit-identical to the
single-replica goldens and the merged journals reconstruct to ZERO
anomalies through the timeline CLI (run as a real subprocess).

    JAX_PLATFORMS=cpu python scripts/fleet_procs_smoke.py

Exit 0 = fenced, recovered, reconstructed. Anything else is a regression.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD_2PC3 = (1_146, 288)
REF = ("2pc", {"n": 3})


def start_fleet(root, n_jobs=5):
    from stateright_tpu.service import ServiceFleet
    from stateright_tpu.service.server import ModelRegistry

    fleet = ServiceFleet(
        n_replicas=3, remote=True, store_root=root, max_resident=1,
        service_kwargs=dict(batch_size=128, table_log2=14),
        router_kwargs=dict(
            probe_timeout_s=0.5, unhealthy_after=2, steal=False,
        ),
    )
    reg = ModelRegistry()
    handles = [
        fleet.submit(reg.get(*REF), model_ref=REF) for _ in range(n_jobs)
    ]
    victim = fleet.replicas[handles[0]._job.replica]
    # Wait for the victim to be mid-work (compiled + >= 1 device step):
    # the interruption must land while it still holds a backlog.
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            p = victim._get_json("/.probe", timeout=1.0)
            if p.get("device_steps", 0) >= 1:
                break
        except Exception:
            pass
        time.sleep(0.02)
    else:
        raise TimeoutError("victim never stepped")
    return fleet, handles, victim


def wait_crashes(fleet, n, timeout=90.0):
    deadline = time.monotonic() + timeout
    while fleet.stats()["replica_crashes"] < n:
        assert time.monotonic() < deadline, fleet.stats()
        time.sleep(0.05)


def check_golden(handles):
    for h in handles:
        r = h.result()
        got = (r.state_count, r.unique_state_count)
        assert got == GOLD_2PC3, (got, GOLD_2PC3)


def zombie_rejections(victim, timeout=30.0):
    """The victim process's own lease.rejected_total, over its
    still-serving HTTP plane (that it still answers is the point)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            st = json.loads(urllib.request.urlopen(
                victim.base_url + "/.status", timeout=2).read())
            rej = st.get("lease", {}).get("rejected_total", 0)
            if rej > 0:
                return rej
        except Exception:
            pass
        time.sleep(0.1)
    return 0


def run_timeline(journal_dir):
    proc = subprocess.run(
        [
            sys.executable, "-m", "stateright_tpu.obs.timeline",
            journal_dir, "--json",
        ],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-800:])
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    from stateright_tpu.faults import FaultPlan, active

    print("== phase 1: 3-proc fleet, kill -9 the victim mid-backlog ==")
    root = tempfile.mkdtemp(prefix="srtpu-procs-kill9-")
    fleet, handles, victim = start_fleet(root)
    os.kill(victim.proc.pid, signal.SIGKILL)
    wait_crashes(fleet, 1)
    fleet.drain(timeout=300)
    check_golden(handles)
    s = fleet.stats()
    assert s["lease_revokes"] == 1 and s["requeued_jobs"] >= 1, s
    fleet.close()
    report = run_timeline(os.path.join(root, "journal"))
    assert report["anomalies"] == [], report["anomalies"]
    print(f"   kill -9 survived: requeued={s['requeued_jobs']} "
          f"restored={s['restored_jobs']} reseals={s['lease_reseals']}; "
          "timeline clean")

    print("== phase 2: SIGSTOP -> declared dead -> SIGCONT zombie ==")
    root = tempfile.mkdtemp(prefix="srtpu-procs-zombie-")
    fleet, handles, victim = start_fleet(root)
    os.kill(victim.proc.pid, signal.SIGSTOP)
    wait_crashes(fleet, 1)
    os.kill(victim.proc.pid, signal.SIGCONT)  # the zombie rises
    fleet.drain(timeout=300)
    check_golden(handles)
    rejected = zombie_rejections(victim)
    assert rejected > 0, "zombie wrote nothing / was not fenced"
    s = fleet.stats()
    fleet.close()
    report = run_timeline(os.path.join(root, "journal"))
    assert report["anomalies"] == [], report["anomalies"]
    print(f"   zombie fenced: lease.rejected={rejected}, "
          f"requeued={s['requeued_jobs']} restored={s['restored_jobs']}; "
          "timeline clean")

    print("== phase 3: injected router<->replica partition ==")
    root = tempfile.mkdtemp(prefix="srtpu-procs-part-")
    fleet, handles, victim = start_fleet(root)
    plan = FaultPlan().rule(
        "fleet.partition", "io", times=-1, match={"replica": victim.idx}
    )
    with active(plan):
        wait_crashes(fleet, 1)
        fleet.drain(timeout=300)
    check_golden(handles)
    assert plan.injected_total() >= 1
    # The partitioned process never died: it is a zombie by another name,
    # and the shared-filesystem lease fences it the same way.
    rejected = zombie_rejections(victim)
    assert rejected > 0, "partitioned replica was not fenced"
    s = fleet.stats()
    assert s["lease_revokes"] == 1, s
    fleet.close()
    report = run_timeline(os.path.join(root, "journal"))
    assert report["anomalies"] == [], report["anomalies"]
    print(f"   partition survived + fenced: lease.rejected={rejected}, "
          f"probe_failures={s['probe_failures']} "
          f"probe_skipped={s['probe_skipped']}; timeline clean")

    print("FLEET PROCS SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
