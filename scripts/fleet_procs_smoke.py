"""Cross-process fleet smoke: real subprocesses, kill -9, a zombie, and a
partition — on BOTH store backends — then the forensic timeline must read
clean.

The ISSUE 12 acceptance run end to end, re-run per backend (ISSUE 15's
blob phase). Each phase starts a 3-process fleet
(`ServiceFleet(remote=True)`: one `replica_main` subprocess per replica
over a shared store root, epoch-fence lease plane + flight recorder on),
pins a same-route-key job backlog on one victim replica (steal disabled,
max_resident=1 — so the victim still holds running AND queued jobs when
it is interrupted), then:

1. **kill -9** — SIGKILL the victim mid-job: lease revoked, orphans
   requeued onto survivors from re-sealed checkpoint generations;
2. **zombie** — SIGSTOP the victim until the router declares it dead,
   then SIGCONT: the resurrected zombie keeps stepping orphaned job
   copies and every write it attempts is fenced (refused write-side,
   rejected read-side), counted as lease.rejected > 0, never read back;
3. **partition** — inject `fleet.partition` against the victim: the
   router sees it dead while the PROCESS keeps running — the
   false-positive death, fenced exactly like the zombie;
4. **rejoin** — kill -9, then re-admit a fresh incarnation through the
   probation quarantine mid-backlog;
5. **autoscale** — with a partition AND a zombie both active, the fleet
   scales OUT (a brand-new subprocess joins through probation — after
   an injected `fleet.autoscale` fault first aborts the grow with
   nothing changed) and then IN (the least-loaded member drains
   mid-backlog through `FleetRouter.retire`): zero lost jobs, counts
   bit-identical, timeline clean.

Backends:

- **file** — a shared local directory (the r16 machine-boundary story);
- **blob** — an in-proc `blobd` object-store emulator
  (`faults/blobstore.py`): checkpoint generations, lease records, and
  member-discovery records live behind HTTP conditional puts; journals
  are local-write and blob-synced at flush boundaries, and the timeline
  CLI reads them back FROM THE BLOB ROOT (`blob://...` argument);
- **s3** / **gs** — the managed-dialect emulators
  (`faults/blobdialect.py`): the same store surfaces behind SigV4 /
  OAuth-bearer authenticated conditional writes, with the credential
  chain resolving against the emulator's metadata/token plane through
  environment the replica subprocesses inherit.

`--backend both` runs (file, blob) — the historical default; `--backend
all` adds the two managed dialects.

In every phase all jobs complete with counts bit-identical to the
single-replica goldens and the merged journals reconstruct to ZERO
anomalies through the timeline CLI (run as a real subprocess).

    JAX_PLATFORMS=cpu python scripts/fleet_procs_smoke.py [--backend file|blob|s3|gs|both|all]

Exit 0 = fenced, recovered, reconstructed. Anything else is a regression.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD_2PC3 = (1_146, 288)
REF = ("2pc", {"n": 3})


def start_fleet(root, n_jobs=5):
    from stateright_tpu.service import ServiceFleet
    from stateright_tpu.service.server import ModelRegistry

    fleet = ServiceFleet(
        n_replicas=3, remote=True, store_root=root, max_resident=1,
        service_kwargs=dict(batch_size=128, table_log2=14),
        router_kwargs=dict(
            probe_timeout_s=0.5, unhealthy_after=2, steal=False,
        ),
    )
    reg = ModelRegistry()
    handles = [
        fleet.submit(reg.get(*REF), model_ref=REF) for _ in range(n_jobs)
    ]
    victim = fleet.replicas[handles[0]._job.replica]
    # Wait for the victim to be mid-work (compiled + >= 1 device step):
    # the interruption must land while it still holds a backlog.
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            p = victim._get_json("/.probe", timeout=1.0)
            if p.get("device_steps", 0) >= 1:
                break
        except Exception:
            pass
        time.sleep(0.02)
    else:
        raise TimeoutError("victim never stepped")
    return fleet, handles, victim


def wait_crashes(fleet, n, timeout=90.0):
    deadline = time.monotonic() + timeout
    while fleet.stats()["replica_crashes"] < n:
        assert time.monotonic() < deadline, fleet.stats()
        time.sleep(0.05)


def check_golden(handles):
    for h in handles:
        r = h.result()
        got = (r.state_count, r.unique_state_count)
        assert got == GOLD_2PC3, (got, GOLD_2PC3)


def zombie_rejections(victim, timeout=30.0):
    """The victim process's own lease.rejected_total, over its
    still-serving HTTP plane (that it still answers is the point)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            st = json.loads(urllib.request.urlopen(
                victim.base_url + "/.status", timeout=2).read())
            rej = st.get("lease", {}).get("rejected_total", 0)
            if rej > 0:
                return rej
        except Exception:
            pass
        time.sleep(0.1)
    return 0


def run_timeline(journal_root):
    """The forensic CLI as a real subprocess; `journal_root` is a local
    directory or a blob:// journal root (the blob phase reads the
    flush-synced journals straight from the object store)."""
    proc = subprocess.run(
        [
            sys.executable, "-m", "stateright_tpu.obs.timeline",
            journal_root, "--json",
        ],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-800:])
    return json.loads(proc.stdout.strip().splitlines()[-1])


class _Roots:
    """Per-backend store-root factory: fresh local tempdirs, or fresh
    prefixes on one in-proc emulator (native blobd, or an s3/gs
    dialect server whose endpoint + credential-plane environment is
    installed into os.environ so the replica subprocesses — which
    inherit it — resolve and sign against the same emulator)."""

    def __init__(self, backend):
        self.backend = backend
        self._srv = None
        self._env_saved = None
        self._n = 0
        if backend != "file":
            from stateright_tpu.faults.blobstore import serve_blobd

            self._srv = serve_blobd(dialect=backend)
            env = self._srv.env
            if env:
                self._env_saved = {k: os.environ.get(k) for k in env}
                os.environ.update(env)

    def fresh(self, tag):
        self._n += 1
        if self.backend == "file":
            return tempfile.mkdtemp(prefix=f"srtpu-procs-{tag}-")
        return f"{self._srv.root_uri}/{tag}{self._n}"

    def journal_root(self, root):
        if self.backend == "file":
            return os.path.join(root, "journal")
        return root + "/journal"

    def close(self):
        if self._env_saved:
            for key, old in self._env_saved.items():
                if old is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = old
        if self._srv is not None:
            self._srv.shutdown()


def run_matrix(backend) -> None:
    from stateright_tpu.faults import FaultPlan, active

    roots = _Roots(backend)
    try:
        print(f"== [{backend}] phase 1: 3-proc fleet, kill -9 the victim "
              "mid-backlog ==")
        root = roots.fresh("kill9")
        fleet, handles, victim = start_fleet(root)
        os.kill(victim.proc.pid, signal.SIGKILL)
        wait_crashes(fleet, 1)
        fleet.drain(timeout=300)
        check_golden(handles)
        s = fleet.stats()
        assert s["lease_revokes"] == 1 and s["requeued_jobs"] >= 1, s
        fleet.close()
        report = run_timeline(roots.journal_root(root))
        assert report["anomalies"] == [], report["anomalies"]
        print(f"   kill -9 survived: requeued={s['requeued_jobs']} "
              f"restored={s['restored_jobs']} reseals={s['lease_reseals']}; "
              "timeline clean")

        print(f"== [{backend}] phase 2: SIGSTOP -> declared dead -> "
              "SIGCONT zombie ==")
        root = roots.fresh("zombie")
        fleet, handles, victim = start_fleet(root)
        os.kill(victim.proc.pid, signal.SIGSTOP)
        wait_crashes(fleet, 1)
        os.kill(victim.proc.pid, signal.SIGCONT)  # the zombie rises
        fleet.drain(timeout=300)
        check_golden(handles)
        rejected = zombie_rejections(victim)
        assert rejected > 0, "zombie wrote nothing / was not fenced"
        s = fleet.stats()
        fleet.close()
        report = run_timeline(roots.journal_root(root))
        assert report["anomalies"] == [], report["anomalies"]
        print(f"   zombie fenced: lease.rejected={rejected}, "
              f"requeued={s['requeued_jobs']} restored={s['restored_jobs']}; "
              "timeline clean")

        print(f"== [{backend}] phase 3: injected router<->replica "
              "partition ==")
        root = roots.fresh("part")
        fleet, handles, victim = start_fleet(root)
        plan = FaultPlan().rule(
            "fleet.partition", "io", times=-1, match={"replica": victim.idx}
        )
        if backend != "file":
            # Wire-backend chaos rides along (blob, s3, and gs all route
            # through the same blob.* points): throttle some puts (429 ->
            # bounded retry) and tear one (CRC-rejected, .prev serves) —
            # outcomes must stay bit-identical and counted.
            plan.rule("blob.put", "http", times=2)
            plan.rule("blob.put", "torn", times=1, after=4)
        with active(plan):
            wait_crashes(fleet, 1)
            fleet.drain(timeout=300)
        check_golden(handles)
        assert plan.injected_total() >= 1
        # The partitioned process never died: it is a zombie by another
        # name, and the shared store root's lease fences it the same way.
        rejected = zombie_rejections(victim)
        assert rejected > 0, "partitioned replica was not fenced"
        s = fleet.stats()
        assert s["lease_revokes"] == 1, s
        fleet.close()
        report = run_timeline(roots.journal_root(root))
        assert report["anomalies"] == [], report["anomalies"]
        print(f"   partition survived + fenced: lease.rejected={rejected}, "
              f"probe_failures={s['probe_failures']} "
              f"probe_skipped={s['probe_skipped']}; timeline clean")

        print(f"== [{backend}] phase 4: kill -9 -> REJOIN mid-backlog ==")
        root = roots.fresh("rejoin")
        fleet, handles, victim = start_fleet(root, n_jobs=6)
        os.kill(victim.proc.pid, signal.SIGKILL)
        wait_crashes(fleet, 1)
        assert fleet.rejoin_replica(victim.idx), "rejoin refused"
        deadline = time.monotonic() + 90
        while fleet.stats()["rejoin_promotions"] < 1:
            assert time.monotonic() < deadline, fleet.stats()
            time.sleep(0.05)
        fleet.drain(timeout=300)
        check_golden(handles)
        s = fleet.stats()
        assert s["rejoins"] == 1 and s["rejoin_promotions"] == 1, s
        fleet.close()
        report = run_timeline(roots.journal_root(root))
        assert report["anomalies"] == [], report["anomalies"]
        print(f"   rejoin survived: rejoins={s['rejoins']} "
              f"promotions={s['rejoin_promotions']} "
              f"requeued={s['requeued_jobs']}; timeline clean")

        print(f"== [{backend}] phase 5: AUTOSCALE out + in during an "
              "active partition + zombie ==")
        root = roots.fresh("autoscale")
        fleet, handles, victim = start_fleet(root, n_jobs=6)
        partner = fleet.replicas[(victim.idx + 1) % 3]
        plan = FaultPlan().rule(
            "fleet.partition", "io", times=-1, match={"replica": partner.idx}
        )
        # The reconciler's own chaos seam: the FIRST scale attempt is
        # killed mid-decision and must change nothing.
        plan.rule("fleet.autoscale", "crash", times=1)
        with active(plan):
            os.kill(victim.proc.pid, signal.SIGSTOP)
            wait_crashes(fleet, 2)  # zombie-to-be + partitioned member
            n_before = len(fleet.replicas)
            assert fleet.scale_out() is None, (
                "injected fleet.autoscale fault did not abort the grow"
            )
            assert len(fleet.replicas) == n_before, (
                "aborted scale_out changed the fleet"
            )
            idx_new = fleet.scale_out()
            assert idx_new is not None, "scale_out refused mid-chaos"
            deadline = time.monotonic() + 90
            while fleet.stats()["rejoin_promotions"] < 1:
                assert time.monotonic() < deadline, fleet.stats()
                time.sleep(0.05)
            os.kill(victim.proc.pid, signal.SIGCONT)  # the zombie rises
            retired = fleet.scale_in()  # mid-backlog: drain is loss-free
            assert retired is not None, "scale_in refused mid-chaos"
            fleet.drain(timeout=300)
        check_golden(handles)
        rejected = zombie_rejections(victim)
        assert rejected > 0, "zombie was not fenced during autoscale"
        s = fleet.stats()
        assert s["scale_outs"] == 1 and s["scale_ins"] == 1, s
        fleet.close()
        report = run_timeline(roots.journal_root(root))
        assert report["anomalies"] == [], report["anomalies"]
        print(f"   autoscale survived chaos: grew to replica{idx_new}, "
              f"retired replica{retired}, lease.rejected={rejected}, "
              f"requeued={s['requeued_jobs']} restored={s['restored_jobs']}; "
              "timeline clean")
    finally:
        roots.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--backend",
        choices=("file", "blob", "s3", "gs", "both", "all"),
        default="both",
        help="store backend(s); both=(file,blob) is the historical "
             "default, all adds the s3/gs managed-dialect emulators",
    )
    args = ap.parse_args(argv)
    matrix = {"both": ("file", "blob"), "all": ("file", "blob", "s3", "gs")}
    for backend in matrix.get(args.backend, (args.backend,)):
        run_matrix(backend)
    print("FLEET PROCS SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
