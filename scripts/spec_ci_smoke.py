#!/usr/bin/env python
"""Spec-CI smoke: the edit loop end to end through the REAL CLI
(`python -m stateright_tpu.ci`), one command, exit 0 iff every leg held.

The loop a spec author lives in: (1) check a model cold — the run
publishes its visited set to the corpus; (2) flip ONE property condition
and re-run — the delta classifier names the edit "properties-only" and
the delta rung replays the published set with only the changed verdict
re-evaluated (asserted: rung fires, counts and verdicts match the edited
model's own cold run in a FRESH corpus); (3) edit `expand` — the
classifier refuses salvage (asserted: counted in `delta_refusals`, run
completes COLD with counts identical to a never-warmed check).

    JAX_PLATFORMS=cpu python scripts/spec_ci_smoke.py
"""

import os
import re
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE_SPEC = """\
from stateright_tpu.tensor.models import TensorTwoPhaseSys as _Base

TensorTwoPhaseSys = _Base

def model():
    return TensorTwoPhaseSys(3)
"""

# The one-line edit: negate one SOMETIMES condition. The subclass KEEPS
# the base class name — the geometry digest includes it, and a renamed
# model is a different spec family, not an edit of this one.
PROP_EDIT_SPEC = """\
import dataclasses
from stateright_tpu.tensor.models import TensorTwoPhaseSys as _Base

def _props(self):
    props = list(_Base.properties(self))
    p0 = props[0]
    props[0] = dataclasses.replace(
        p0, name=p0.name + " flipped",
        condition=lambda model, s, _c=p0.condition: ~_c(model, s))
    return props

TensorTwoPhaseSys = type("TensorTwoPhaseSys", (_Base,), {"properties": _props})

def model():
    return TensorTwoPhaseSys(3)
"""

# A semantic `expand` edit (masking the last action) — unsalvageable: the
# published visited set was explored under a different successor
# relation, so the classifier must REFUSE and the run must go cold.
EXPAND_EDIT_SPEC = """\
from stateright_tpu.tensor.models import TensorTwoPhaseSys as _Base

def _expand(self, states):
    succs, valid = _Base.expand(self, states)
    valid = valid.at[:, -1].set(False)
    return succs, valid

TensorTwoPhaseSys = type("TensorTwoPhaseSys", (_Base,), {"expand": _expand})

def model():
    return TensorTwoPhaseSys(3)
"""

_ROW = re.compile(
    r"\[\s*(?P<status>ok|FAIL)\] (?P<spec>\S+): rung=(?P<rung>\S+)"
    r"(?: \((?P<cls>[a-z/-]+)\))? states=(?P<states>\d+) "
    r"unique=(?P<unique>\d+)"
)
_VERDICT = re.compile(r"^ {7}(?P<mark>[+-]) (?P<kind>\S+)\s+(?P<rest>.+)$")
_STATS = re.compile(
    r"corpus: delta_hits=(?P<hits>\d+) delta_refusals=(?P<refusals>\d+) "
    r"component_reuse=(?P<reuse>\d+)"
)


def run_ci(spec_path, corpus_dir):
    """Invoke the real `python -m stateright_tpu.ci` and parse its report:
    (exit, rung, delta_class, (states, unique), verdict lines, stats)."""
    proc = subprocess.run(
        [
            sys.executable, "-m", "stateright_tpu.ci",
            "--corpus", corpus_dir, "--batch-size", "128",
            "--table-log2", "14", f"{spec_path}:model",
        ],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    print(proc.stdout, end="")
    if proc.stderr.strip():
        print(proc.stderr, end="", file=sys.stderr)
    row = _ROW.search(proc.stdout)
    stats = _STATS.search(proc.stdout)
    if row is None or stats is None:
        raise RuntimeError(f"unparseable CI report:\n{proc.stdout}")
    verdicts = sorted(
        m.group("mark") + " " + m.group("kind") + " " + m.group("rest")
        for line in proc.stdout.splitlines()
        if (m := _VERDICT.match(line))
    )
    return (
        proc.returncode,
        row.group("rung"),
        row.group("cls"),
        (int(row.group("states")), int(row.group("unique"))),
        verdicts,
        {k: int(v) for k, v in stats.groupdict().items()},
    )


def main() -> int:
    failures = []
    with tempfile.TemporaryDirectory(prefix="srtpu-specci-") as tmp:
        corpus = os.path.join(tmp, "corpus")
        spec = os.path.join(tmp, "spec.py")

        # Leg 1: cold check publishes the base model's visited set.
        with open(spec, "w") as f:
            f.write(BASE_SPEC)
        rc, rung, _cls, counts, _v, _s = run_ci(spec, corpus)
        if rc != 0 or rung != "cold":
            failures.append(f"base check: rc={rc} rung={rung} (want cold)")

        # Cold references for both edits, in corpora that never saw the
        # base model — what "never warmed" returns.
        with open(spec, "w") as f:
            f.write(PROP_EDIT_SPEC)
        rc, rung, _cls, prop_cold, prop_cold_v, _s = run_ci(
            spec, os.path.join(tmp, "cold-prop")
        )
        if rc != 0 or rung != "cold":
            failures.append(f"prop cold ref: rc={rc} rung={rung}")
        with open(spec, "w") as f:
            f.write(EXPAND_EDIT_SPEC)
        rc, rung, _cls, exp_cold, exp_cold_v, _s = run_ci(
            spec, os.path.join(tmp, "cold-exp")
        )
        if rc != 0 or rung != "cold":
            failures.append(f"expand cold ref: rc={rc} rung={rung}")

        # Leg 2: the property edit re-runs on the delta rung with the
        # re-evaluated verdicts matching its own cold check.
        with open(spec, "w") as f:
            f.write(PROP_EDIT_SPEC)
        rc, rung, cls, got, verdicts, stats = run_ci(spec, corpus)
        if rc != 0:
            failures.append(f"prop edit: rc={rc}")
        if rung != "delta" or cls != "properties-only":
            failures.append(
                f"prop edit: rung={rung} class={cls} "
                "(want delta/properties-only)"
            )
        if got != prop_cold:
            failures.append(f"prop edit counts {got} != cold {prop_cold}")
        if verdicts != prop_cold_v:
            failures.append(
                f"prop edit verdicts {verdicts} != cold {prop_cold_v}"
            )
        if stats["hits"] < 1:
            failures.append(f"prop edit: delta_hits never moved ({stats})")

        # Leg 3: the expand edit is REFUSED (counted) and falls back to a
        # cold run identical to the never-warmed reference.
        with open(spec, "w") as f:
            f.write(EXPAND_EDIT_SPEC)
        rc, rung, _cls, got, verdicts, stats = run_ci(spec, corpus)
        if rc != 0 or rung != "cold":
            failures.append(f"expand edit: rc={rc} rung={rung} (want cold)")
        if stats["refusals"] < 1:
            failures.append(
                f"expand edit: delta_refusals never moved ({stats})"
            )
        if got != exp_cold or verdicts != exp_cold_v:
            failures.append(
                f"expand edit {got}/{verdicts} != never-warmed "
                f"{exp_cold}/{exp_cold_v}"
            )

    if failures:
        print("FAILURES:", "; ".join(failures), file=sys.stderr)
        return 1
    print("spec-ci smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
