"""Probe: lower the real 2-client Paxos ActorModel with the GENERIC lowering
and compare against the reference golden (32,971 generated / 16,668 unique,
ref examples/paxos.rs:327,351) and the hand-built TensorPaxos. Reports closure
wall time and table sizes (VERDICT r2 'next' #3)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")

from stateright_tpu.actor import Network
from stateright_tpu.actor.register import GetOk
from stateright_tpu.examples.paxos import NULL_VALUE, PaxosModelCfg
from stateright_tpu.tensor import FrontierSearch
from stateright_tpu.tensor.lowering import lower_actor_model
from stateright_tpu.tensor.model import TensorProperty

C = int(sys.argv[1]) if len(sys.argv) > 1 else 2

cfg = PaxosModelCfg(
    client_count=C, server_count=3,
    network=Network.new_unordered_nonduplicating(),
)

def _unused_local_boundary(i, s):
    return i >= 3 or s.state.ballot[0] <= C

def properties(view):
    lin = view.history_pred(lambda h: h.is_consistent())
    chosen = view.any_env(
        lambda e: isinstance(e.msg, GetOk) and e.msg.value != NULL_VALUE
    )
    return [
        TensorProperty.always("linearizable", lambda m, s: lin(s)),
        TensorProperty.sometimes("value chosen", lambda m, s: chosen(s)),
    ]

t0 = time.monotonic()
lowered = lower_actor_model(
    cfg.into_model(),

    properties=properties,
    max_histories=1 << 17,
    closure="exact",
    max_local_states=1 << 16,
    max_joint_states=1 << 22,
    max_envelopes=1 << 15,
)
t1 = time.monotonic()
print(f"closure: {t1-t0:.1f}s", flush=True)
print(f"  envelopes: {len(lowered.envs)}")
print(f"  local states/actor: {[len(s) for s in lowered.states]}")
print(f"  histories: {len(lowered.histories)}  hevents: {len(lowered.hevents)}")
print(f"  lanes: {lowered.lanes}  max_actions: {lowered.max_actions}", flush=True)

t2 = time.monotonic()
r = FrontierSearch(lowered, batch_size=2048, table_log2=22).run()
t3 = time.monotonic()
print(f"search: {t3-t2:.1f}s  states={r.state_count} unique={r.unique_state_count} depth={r.max_depth}")
print(f"discoveries: {sorted(r.discoveries)}")
