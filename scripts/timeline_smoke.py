"""Flight-recorder smoke: chaos fleet run -> journals -> timeline CLI ->
anomaly-free verdict.

The end-to-end the observability plane promises (ISSUE 9): an N=3
foreground fleet runs a mixed job set through an injected mid-load
replica crash AND a work steal with the recorder attached
(`journal_dir=` + a flushing Tracer); then the forensic CLI
(`python -m stateright_tpu.obs.timeline`) must reconstruct every job's
full lifecycle from the journals alone — zero anomalies, event counts
consistent with the fleet counters, and a Perfetto-loadable merged
Chrome trace. Exercises BOTH the in-process API and the installed
console entry (a subprocess run of the module), so the CLI contract
itself is smoked, not just the library.

    JAX_PLATFORMS=cpu python scripts/timeline_smoke.py

Exit 0 = recorded, reconstructed, clean. Anything else is a regression.
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from stateright_tpu.faults import FaultPlan, active
    from stateright_tpu.obs import Tracer
    from stateright_tpu.obs import timeline as tl
    from stateright_tpu.service import ServiceFleet
    from stateright_tpu.tensor.models import (
        TensorIncrementLock,
        TensorTwoPhaseSys,
    )

    td = tempfile.mkdtemp(prefix="srtpu-timeline-smoke-")
    journal_dir = os.path.join(td, "journal")
    trace_path = os.path.join(td, "trace.json")
    m3, mi = TensorTwoPhaseSys(3), TensorIncrementLock(4)

    print("== chaos fleet run (N=3, crash + steal, recorder on) ==")
    tracer = Tracer(out=trace_path, flush_every=20)
    fleet = ServiceFleet(
        n_replicas=3, background=False, max_resident=1,
        service_kwargs=dict(batch_size=128, table_log2=14),
        journal_dir=journal_dir, tracer=tracer,
    )
    handles = [fleet.submit(m) for m in (m3, m3, mi, m3, mi)]
    victim = sorted({h._job.replica for h in handles})[0]
    plan = FaultPlan().rule(
        "fleet.replica_crash", "crash", after=6, match={"replica": victim}
    )
    with active(plan):
        fleet.drain(timeout=600)
    stats = fleet.stats()
    for h in handles:
        r = h.result()
        assert r.complete, f"job {h.id} incomplete"
    assert plan.injected_total() == 1, plan.spec()
    assert stats["replica_crashes"] == 1, stats
    assert stats["steals"] >= 1, stats
    fleet.close()
    print(
        f"   crash replica {victim}, requeued {stats['requeued_jobs']}, "
        f"restored {stats['restored_jobs']}, steals {stats['steals']} "
        f"(plan: {plan.spec()})"
    )

    print("== timeline reconstruction (library) ==")
    events = tl.load_events([journal_dir])
    traces, _untraced = tl.group_traces(events)
    anomalies = tl.find_anomalies(traces)
    counts = tl.event_counts(events)
    assert len(traces) == len(handles), (len(traces), len(handles))
    assert anomalies == [], anomalies
    assert counts.get("job.requeued", 0) == stats["requeued_jobs"], counts
    assert counts.get("fleet.steal", 0) == stats["steals"], counts
    assert counts.get("replica.crash", 0) == stats["replica_crashes"]
    print(f"   {len(events)} events, {len(traces)} traces, 0 anomalies")

    print("== timeline CLI (subprocess) + merged Chrome trace ==")
    merged = os.path.join(td, "merged.json")
    proc = subprocess.run(
        [
            sys.executable, "-m", "stateright_tpu.obs.timeline",
            journal_dir, "--traces", trace_path, "--chrome-out", merged,
            "--json",
        ],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-500:])
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["anomalies"] == []
    assert all(
        lc["terminal"] == "job.done" for lc in report["traces"].values()
    )
    env = json.load(open(merged))
    assert isinstance(env["traceEvents"], list) and env["traceEvents"]
    print(
        f"   CLI verdict clean; merged Chrome trace "
        f"{len(env['traceEvents'])} events at {merged}"
    )
    print("TIMELINE SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
