#!/bin/bash
# Sweep the resident engine on the real TPU: smoke test first, then the
# north-star paxos-3 across batch/table configs. Each config is its own
# subprocess (the tunnel is single-client; a hang only costs that config).
cd "$(dirname "$0")/.." || exit 1
set -u
run() {
  echo "== $* =="
  timeout 900 python scripts/tpu_tune.py "$@"
  echo
}
# Round-4 v5e sweep found smaller batches win on TPU (step cost near-linear
# in batch, frontier often sub-batch): 2048/4096 tie at ~565k states/s,
# 8192 -8%, 32768 -40%. Re-probe around the optimum.
run 2pc 4 512 14 2
run paxos 3 2048 22 2
run paxos 3 3072 22 3
run paxos 3 4096 22 3
run paxos 3 4096 21 2
run paxos 3 8192 22 2
run paxos 3 16384 22 2
run paxos 3 32768 22 2
# paxos-2 small-space fixed-cost check (VERDICT r4 next #7: >=1M/s target)
run paxos 2 1024 18 3
run paxos 2 2048 18 3
# Interleaved-kv table race (halved probe-gather bytes; round-5 staging)
run paxos 3 3072 22 3 kv
run paxos 2 2048 18 3 kv
# Phased scatter-max race for tiny-frontier fixed costs (VERDICT r4 #7)
run paxos 2 2048 18 3 phased
run paxos 2 1024 18 3 phased
run paxos 3 3072 22 2 phased
# Round-6 capped insert (batch-monotonic claim tiles): the cost model
# predicts capped-kv wins every steady-state config (ROUND6_NOTES.md);
# this is the decisive race, dumped as a machine-readable ranking.
run paxos 3 3072 22 3 capped
run paxos 3 3072 22 3 capped-kv
run paxos 3 32768 22 2 capped
run paxos 2 2048 18 3 capped
echo "== sweep ranking (variants x batches -> tune_ranking.json) =="
# Outer timeout sized to the worst case (15 configs x 900 s per-config
# subprocess timeout + slack); the sweep also rewrites tune_ranking.json
# after every config, so even a killed sweep keeps what it measured.
timeout 14400 python scripts/tpu_tune.py --sweep paxos 3 22 \
  --batches 3072,8192,32768 --variants split,kv,phased,capped,capped-kv \
  --repeats 2 --out tune_ranking.json
# Tiniest spaces (r4: inclock-sym-6 ran at 475/s — pure fixed cost)
run inclock-sym 6 512 10 3
run inclock-sym 6 512 10 3 phased
run inclock 6 1024 14 3 phased

# Visited-set design race on silicon (VERDICT r3 #5): XLA scatter-max vs the
# Pallas partitioned-VMEM insert. Parity cross-check built in; the winner
# becomes the engines' default.
echo "== race_hashtable =="
timeout 1200 python scripts/race_hashtable.py
