#!/bin/bash
# The ONE command to run when the axon TPU tunnel finally admits a client
# (it has refused backend init for rounds 1-4; see ROUND4_NOTES.md).
# Runs the full staged silicon sequence in the right order, logging each
# step. Serialize with everything else — the tunnel is single-client: kill
# probe loops (pkill -f tpu_probe) and any other JAX process first.
#
#   bash scripts/tunnel_day.sh [logdir]
#
# Sequence:
#   1. probe     — one trivial jitted op in a fresh subprocess.
#   2. tune      — scripts/tpu_tune.sh: parity-checked batch/table sweep on
#                  paxos-3 (+ the XLA-vs-Pallas visited-set race).
#   3. bench     — python bench.py: all BASELINE workloads with golden
#                  parity oracles; writes the one-line JSON the driver
#                  records as BENCH_r{N}.json.
set -u
cd "$(dirname "$0")/.." || exit 1
LOG="${1:-/tmp/tunnel_day}"
mkdir -p "$LOG"

echo "[tunnel_day] probing..." | tee "$LOG/status"
if ! timeout 240 python -c "
import jax
# Platform check FIRST: a silent CPU fallback must not compile anything
# into the TPU cache (host-specific XLA:CPU AOT entries poison it for
# other machines — ROUND4_NOTES.md).
assert jax.devices()[0].platform != 'cpu', jax.devices()
jax.config.update('jax_compilation_cache_dir', '/root/repo/.jax_cache')
import jax.numpy as jnp
x = jax.jit(lambda a: a * 2 + 1)(jnp.arange(8))
x.block_until_ready()
print('PROBE_OK', jax.devices())
" > "$LOG/probe.log" 2>&1; then
  echo "[tunnel_day] probe FAILED — tunnel still dead (see $LOG/probe.log)" | tee -a "$LOG/status"
  exit 1
fi
echo "[tunnel_day] probe OK: $(tail -1 "$LOG/probe.log")" | tee -a "$LOG/status"

echo "[tunnel_day] tune sweep + hashtable race..." | tee -a "$LOG/status"
if bash scripts/tpu_tune.sh > "$LOG/tune.log" 2>&1; then
  echo "[tunnel_day] tune done (see $LOG/tune.log); best configs go into bench.py _build_workload" | tee -a "$LOG/status"
else
  # A non-zero rc includes the hashtable race's PARITY MISMATCH exit —
  # do NOT crown an engine default from this run.
  echo "[tunnel_day] tune FAILED (rc!=0 — check $LOG/tune.log before trusting any config or race verdict)" | tee -a "$LOG/status"
fi

echo "[tunnel_day] profiled paxos-3 run + per-op attribution..." | tee -a "$LOG/status"
TPU_TUNE_TRACE="$LOG/trace" timeout 900 python scripts/tpu_tune.py paxos 3 3072 22 2   > "$LOG/trace_run.log" 2>&1   && python scripts/xplane_ops.py "$LOG/trace" 30 > "$LOG/op_stats.txt" 2>&1   && echo "[tunnel_day] op stats in $LOG/op_stats.txt" | tee -a "$LOG/status"   || echo "[tunnel_day] profiling step failed (non-fatal)" | tee -a "$LOG/status"

echo "[tunnel_day] full bench..." | tee -a "$LOG/status"
python bench.py > "$LOG/bench.json" 2> "$LOG/bench.log"
echo "[tunnel_day] bench JSON:" | tee -a "$LOG/status"
cat "$LOG/bench.json" | tee -a "$LOG/status"
