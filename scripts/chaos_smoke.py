#!/usr/bin/env python
"""Chaos smoke: the anchor workload under a canned FaultPlan, end-to-end.

CI-shaped proof of the robustness subsystem (stateright_tpu/faults/) in one
command: a seeded plan injects every fault class — device OOM, XLA error,
mid-chunk preemption, spill-tier I/O error, torn checkpoint write, a hang
(watchdog-converted), a one-shard transfer failure, a poison service job,
and an HTTP-plane fault — and every run must still converge BIT-IDENTICAL
to the fault-free golden, with the recovery counters accounting for every
injected fault. Exit code 0 iff every check passes.

    JAX_PLATFORMS=cpu python scripts/chaos_smoke.py [--skip-sharded]

The replayable plan specs are printed for each scenario (paste one into
SR_TPU_FAULTS= to reproduce it against any entry point).
"""

import json
import os
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD = (1_146, 288)  # 2pc-3 generated/unique (ref examples/2pc.rs:153-159)


def main(argv) -> int:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        # The image's site config re-registers the axon TPU platform over a
        # plain env var; pin at the jax.config level (same move as bench.py).
        jax.config.update("jax_platforms", p)

    from stateright_tpu.faults import (
        FaultPlan,
        SupervisorConfig,
        active,
        run_supervised,
    )
    from stateright_tpu.service import CheckService, serve_service
    from stateright_tpu.tensor.models import (
        TensorIncrementLock,
        TensorTwoPhaseSys,
    )

    failures = []

    def check(ok: bool, what: str):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    outdir = tempfile.mkdtemp(prefix="chaos_smoke_")
    model = TensorTwoPhaseSys(3)
    cfg = SupervisorConfig(
        backoff_base_s=0.0, checkpoint_every_steps=6, watchdog_s=2.0,
        seed=7,
    )
    # Tiny tiered config: 288 uniques overflow a 2^9 table at high_water
    # 0.5, so the spill/resolve boundaries genuinely run.
    tiered = dict(
        batch_size=16, table_log2=9,
        store="tiered", high_water=0.5, summary_log2=12,
    )

    def supervised(name, engine, plan, engine_kwargs):
        ck = os.path.join(outdir, f"{name}.ckpt.npz")
        r = run_supervised(
            model, engine=engine, plan=plan, config=cfg,
            checkpoint_path=ck, engine_kwargs=engine_kwargs,
        )
        f = r.detail["faults"]
        got = (r.state_count, r.unique_state_count)
        print(f"     {name}: plan={plan.spec() if plan else None}")
        print(f"     {name}: counts={got} faults={json.dumps(f)}")
        check(got == GOLD, f"{name}: counts bit-identical to golden {GOLD}")
        want = sum(max(r_.times, 0) for r_ in plan.rules) if plan else 0
        check(
            f["injected_total"] == want,
            f"{name}: recovery counters account for all {want} injected "
            f"faults (got {f['injected_total']})",
        )
        return r

    # 1. fault-free golden parity (supervisor overhead path only). An
    # EMPTY plan, not None: None falls back to SR_TPU_FAULTS, and a
    # leftover env var must not contaminate the baseline.
    supervised("baseline", "frontier", FaultPlan(), dict(
        batch_size=64, table_log2=12,
    ))

    # 2. frontier: device OOM + XLA error + spill-tier I/O + resolve fault.
    plan = (
        FaultPlan(seed=7)
        .rule("engine.step", "oom", after=2)
        .rule("engine.step", "xla", after=6)
        .rule("store.spill", "io", times=1)
        .rule("store.resolve", "io", times=1)
    )
    supervised("frontier-chaos", "frontier", plan, dict(tiered))

    # 3. resident: mid-chunk preemption + torn checkpoint + OOM (the torn
    # generation must be absorbed by the .prev fallback on restore) + hang
    # (watchdog-converted).
    plan = (
        FaultPlan(seed=8)
        .rule("engine.chunk", "preempt", after=1)
        .rule("ckpt.write", "torn", times=1)
        .rule("engine.step", "oom", after=4)
        .rule("engine.step", "hang", after=8, times=1)
    )
    r = supervised("resident-chaos", "resident", plan, dict(tiered))
    check(
        r.detail["faults"]["watchdog_fired"] >= 1
        or "engine.step:hang" in r.detail["faults"]["injected"],
        "resident-chaos: hang was converted, not waited out",
    )

    # 4. sharded: one-shard transfer failure on a 2-chip mesh.
    if "--skip-sharded" not in argv:
        from stateright_tpu.parallel import make_mesh

        plan = FaultPlan(seed=9).rule(
            "shard.transfer", "shard", times=1, match={"shard": 1}
        )
        # Per-shard 2^8 tables at high_water 0.5 (trigger ~120): 2pc-3's
        # ~144 uniques per shard force real per-shard spill transfers. The
        # small batch keeps one all-to-all receive within the table.
        supervised("sharded-chaos", "sharded", plan, dict(
            mesh=make_mesh(2), batch_size=4, table_log2=8,
            store="tiered", high_water=0.5, summary_log2=12,
        ))

    # 5. service: poison job quarantined; siblings + unrelated groups
    # bit-identical.
    m3 = TensorTwoPhaseSys(3)
    mi = TensorIncrementLock(4)
    svc = CheckService(
        batch_size=256, table_log2=17, background=False, retry_limit=1
    )
    h_ok = svc.submit(m3)
    h_poison = svc.submit(m3)
    h_other = svc.submit(mi)
    plan = FaultPlan().rule(
        "service.step", "poison", times=-1, match={"job": h_poison.id}
    )
    with active(plan):
        svc.drain(timeout=300)
    r_ok, r_other = h_ok.result(), h_other.result()
    check(
        (r_ok.state_count, r_ok.unique_state_count) == GOLD,
        "service: poison job's group sibling bit-identical to golden",
    )
    check(
        (r_other.state_count, r_other.unique_state_count) == (257, 257),
        "service: unrelated group unaffected by the poison job",
    )
    check(
        svc.poll(h_poison.id)["quarantined"],
        "service: poison job quarantined",
    )
    sf = svc.stats()["faults"]
    print(f"     service faults={json.dumps(sf)}")
    check(sf["quarantined_jobs"] == 1, "service: quarantine accounted")

    # 6. HTTP plane: an injected front-end fault degrades to a 503 and the
    # server keeps serving.
    server = serve_service(svc, address="localhost:0")
    port = server.httpd.server_address[1]
    plan = FaultPlan().rule("service.http", "http", times=1)
    with active(plan):
        try:
            urllib.request.urlopen(f"http://localhost:{port}/.status")
            code = 200
        except urllib.error.HTTPError as e:
            code = e.code
        check(code == 503, "http: injected fault served as 503")
        with urllib.request.urlopen(
            f"http://localhost:{port}/.status"
        ) as resp:
            check(resp.status == 200, "http: server alive after the fault")
    server.shutdown()
    svc.close()

    print(f"\nartifacts: {outdir}")
    if failures:
        print(f"{len(failures)} check(s) FAILED")
        return 1
    print("all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
