"""Incremental real-TPU validation + warm-up of the device stack.

Runs smallest-to-largest with flushed, timestamped progress so a stall is
attributable to a specific phase (the device is reached over a single-client
tunnel; killing a client mid-transfer can wedge it — prefer letting this
script finish). Shares bench.py's persistent compilation-cache dir and its
exact workload shapes, so a completed run leaves every bench kernel compiled.

Usage: python -u scripts/tpu_validate.py [phase...]
  phases (default all, in order): probe kernels frontier resident bench2pc
  benchpaxos2 benchpaxos3
"""

from __future__ import annotations

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402 — shares the platform pin + compile-cache dir
import jax  # noqa: E402

bench._pin_platform()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[{time.monotonic() - T0:8.1f}s] {msg}", flush=True)


def timed(label: str, fn, *args, **kw):
    t = time.monotonic()
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    log(f"{label}: {time.monotonic() - t:.3f}s")
    return out


def phase_probe():
    log(f"devices: {jax.devices()}")
    x = timed("trivial jit", jax.jit(lambda a: a * 2 + 1), jnp.arange(8))
    assert x[-1] == 15
    timed("trivial jit (cached)", jax.jit(lambda a: a * 2 + 1), jnp.arange(8))


def phase_kernels():
    from stateright_tpu.tensor.hashtable import HashTable

    rng = np.random.default_rng(7)
    table = HashTable(14)
    lo = jnp.asarray(rng.integers(1, 2**32, 4096, dtype=np.uint32))
    hi = jnp.asarray(rng.integers(0, 2**32, 4096, dtype=np.uint32))
    z = jnp.zeros(4096, dtype=jnp.uint32)
    act = jnp.ones(4096, dtype=bool)
    r = timed("hashtable insert 4k (compile+run)", table.insert, lo, hi, z, z, act)
    n_first = int(np.asarray(r.is_new).sum())
    r = timed("hashtable re-insert 4k (cached)", table.insert, lo, hi, z, z, act)
    assert int(np.asarray(r.is_new).sum()) == 0, "re-insert must dedup"
    log(f"hashtable: {n_first} unique of 4096 inserted, re-insert deduped")


def phase_frontier():
    from stateright_tpu.tensor.frontier import FrontierSearch
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    s = FrontierSearch(TensorTwoPhaseSys(3), batch_size=512, table_log2=14)
    r = timed("FrontierSearch 2pc-3 (compile+run)", s.run)
    assert r.unique_state_count == 288, r
    log(f"frontier 2pc-3: {r.state_count} gen / {r.unique_state_count} unique ok")


def phase_resident():
    from stateright_tpu.tensor.models import TensorTwoPhaseSys
    from stateright_tpu.tensor.resident import ResidentSearch

    s = ResidentSearch(TensorTwoPhaseSys(3), batch_size=512, table_log2=14)
    r = timed("ResidentSearch 2pc-3 (compile+run)", s.run)
    assert r.unique_state_count == 288, r
    r = timed("ResidentSearch 2pc-3 (cached)", s.run)
    log(f"resident 2pc-3: {r.state_count} gen / {r.unique_state_count} unique ok")


def _bench_workload(model_name: str, n: int):
    import bench

    r, err = bench.device_search(model_name, n)
    log(
        f"bench workload {model_name}-{n}: {r['states']} gen in {r['sec']}s "
        f"({r['states_per_sec']:.0f}/s, compile {r['compile_sec']}s)"
        + (f" PARITY ERROR: {err}" if err else " parity ok")
    )


def phase_bench2pc():
    _bench_workload("2pc", 4)


def phase_benchpaxos2():
    _bench_workload("paxos", 2)


def phase_benchpaxos3():
    _bench_workload("paxos", 3)


PHASES = {
    "probe": phase_probe,
    "kernels": phase_kernels,
    "frontier": phase_frontier,
    "resident": phase_resident,
    "bench2pc": phase_bench2pc,
    "benchpaxos2": phase_benchpaxos2,
    "benchpaxos3": phase_benchpaxos3,
}


if __name__ == "__main__":
    names = sys.argv[1:] or list(PHASES)
    for name in names:
        log(f"=== phase {name} ===")
        PHASES[name]()
    log("ALL PHASES OK")
