#!/usr/bin/env python
"""Fleet load generator: hundreds of concurrent HTTP clients against the
fleet front door, reporting p50/p99 submit→result latency and jobs/s.

The ROADMAP item 1 acceptance harness: starts an N-replica ServiceFleet
behind `serve_fleet`, drives `--clients` threads submitting `--jobs` mixed
jobs (POST /jobs + poll GET /jobs/<id>), honors 503 `Retry-After` backoff,
and verifies every job finished with its golden counts. `--compare` runs
the same load twice — N replicas, then 1 — and prints the jobs/s ratio
(the scale-out claim: N=3 beats N=1 on the mixed set).

    JAX_PLATFORMS=cpu python scripts/fleet_load.py \
        [--replicas 3] [--clients 100] [--jobs 200] [--compare] [--crash] \
        [--warm] [--procs] [--blob]

`--crash` additionally kills one replica mid-load and asserts zero lost
jobs: in-proc through the chaos plane (`fleet.replica_crash`), with
`--procs` by a real `kill -9` of one replica subprocess. `--warm`
pre-publishes the mixed model set into a shared warm-start corpus
(store/corpus.py) and runs the load against it, then runs the SAME load
cold and prints warm-vs-cold jobs/s and p50 side by side (with `--compare`
both modes also get their 1-replica baseline). `--procs` runs the fleet
CROSS-PROCESS (`ServiceFleet(remote=True)`): one `replica_main` subprocess
per replica over a shared store root, with the epoch-fence lease plane on
— the load (and the crash) then exercises real process boundaries.
`--blob` puts the shared store root behind the in-proc object-store
emulator (faults/blobstore.py): checkpoint generations, lease records,
member-discovery records (and the corpus with `--warm`) ride HTTP
conditional puts with bounded-retry/backoff — the true multi-host
storage path under load.
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: (registry model name, args, (golden generated, golden unique))
MIX = (
    ("2pc", {"n": 3}, (1_146, 288)),
    ("inclock", {"n": 4}, (257, 257)),
)


def prepublish_corpus(corpus_dir):
    """Pre-publish the mixed model set: one cold submission per model
    through a corpus-enabled 1-replica fleet fills the shared directory
    the warm load then hits."""
    from stateright_tpu.service import ServiceFleet
    from stateright_tpu.service.server import ModelRegistry

    fleet = ServiceFleet(
        n_replicas=1,
        background=True,
        service_kwargs=dict(batch_size=512, table_log2=16),
        corpus_dir=corpus_dir,
    )
    registry = ModelRegistry()
    try:
        handles = [
            fleet.submit(registry.get(name, args)) for name, args, _ in MIX
        ]
        fleet.drain(timeout=600)
        for h in handles:
            h.result()
    finally:
        fleet.close()


def run_load(n_replicas, clients, jobs, crash=False, corpus_dir=None,
             tiered=False, procs=False, blob_root=None):
    from stateright_tpu.faults import FaultPlan, active
    from stateright_tpu.service import ServiceFleet, serve_fleet

    svc_kw = dict(batch_size=512, table_log2=16)
    if tiered or corpus_dir is not None:
        # Warm A/B fairness: the cold side of --warm runs the SAME tiered
        # store config as the corpus side, so the ratio measures the
        # corpus, not the store kind.
        svc_kw["store"] = "tiered"
    fleet_kw = {}
    if blob_root is not None:
        if procs:
            fleet_kw["store_root"] = blob_root
        else:
            # In-proc over the blob backend: the requeue-resume checkpoint
            # plane and the lease fence ride HTTP conditional puts.
            fleet_kw["ckpt_dir"] = blob_root + "/ckpt"
            fleet_kw["lease_dir"] = blob_root + "/leases"
    fleet = ServiceFleet(
        n_replicas=n_replicas,
        background=True,
        max_resident=4,
        service_kwargs=svc_kw,
        corpus_dir=corpus_dir,
        remote=procs,
        **fleet_kw,
    )
    srv = serve_fleet(fleet, address="localhost:0")
    base = "http://" + srv.address
    latencies = []
    failures = []
    lock = threading.Lock()
    per_client = max(jobs // clients, 1)

    def post(path, body):
        req = urllib.request.Request(
            base + path, data=json.dumps(body).encode(), method="POST"
        )
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    def get(path):
        return json.loads(
            urllib.request.urlopen(base + path, timeout=30).read()
        )

    def client(ci):
        for j in range(per_client):
            name, args, gold = MIX[(ci + j) % len(MIX)]
            t0 = time.monotonic()
            while True:  # submit with Retry-After backoff (503 and 429)
                try:
                    jid = post("/jobs", {"model": name, "args": args})["job"]
                    break
                except urllib.error.HTTPError as e:
                    if e.code not in (503, 429):
                        raise
                    time.sleep(float(e.headers.get("Retry-After") or 1))
            while True:  # poll to completion
                try:
                    p = get(f"/jobs/{jid}")
                except urllib.error.HTTPError as e:
                    if e.code not in (503, 429):
                        raise
                    time.sleep(float(e.headers.get("Retry-After") or 1))
                    continue
                if p["status"] in ("done", "error", "cancelled"):
                    break
                time.sleep(0.01)
            lat = time.monotonic() - t0
            got = (p.get("state_count"), p.get("unique_state_count"))
            with lock:
                latencies.append(lat)
                if p["status"] != "done" or got != gold:
                    failures.append(
                        f"client {ci} job {jid} ({name}): "
                        f"status={p['status']} counts={got} != {gold}"
                    )

    plan = None
    killer = None
    if crash and n_replicas > 1:
        if procs:
            # Cross-process crash: a REAL kill -9 of one replica
            # subprocess mid-load — the router must revoke its lease and
            # requeue from checkpoints, zero lost jobs.
            import signal

            def kill_one():
                time.sleep(1.0)
                try:
                    os.kill(fleet.replicas[0].proc.pid, signal.SIGKILL)
                except OSError:
                    pass

            killer = threading.Thread(target=kill_one, daemon=True)
        else:
            # In-proc: kill one replica a few driver turns in through the
            # chaos plane.
            plan = FaultPlan().rule(
                "fleet.replica_crash", "crash", after=20,
                match={"replica": 0},
            )

    t0 = time.monotonic()
    ctx = active(plan) if plan is not None else None
    if ctx is not None:
        ctx.__enter__()
    if killer is not None:
        killer.start()
    try:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
    wall = time.monotonic() - t0
    stats = fleet.stats()
    srv.shutdown()
    fleet.close()

    lat_ms = sorted(x * 1000 for x in latencies) or [0.0]

    def pct(q):
        return lat_ms[min(int(q * (len(lat_ms) - 1)), len(lat_ms) - 1)]

    done = len(latencies)
    row = {
        "replicas": n_replicas,
        "clients": clients,
        "jobs": done,
        "sec": round(wall, 2),
        "jobs_per_sec": round(done / max(wall, 1e-9), 2),
        "p50_ms": round(pct(0.50), 1),
        "p99_ms": round(pct(0.99), 1),
        "steals": stats["steals"],
        "requeued": stats["requeued_jobs"],
        "restored": stats["restored_jobs"],
        "replica_crashes": stats["replica_crashes"],
    }
    return row, failures


def run_tenants_load(max_replicas, clients, jobs, slo_ms):
    """Mixed-tenant load against an AUTOSCALING fleet: a quiet 1x tenant
    and a noisy ~10x tenant (with an in-flight quota) share the front
    door; the Autoscaler grows the fleet from its own signals. Reports
    per-tenant p50/p99 and asserts the isolation claims: the quiet
    tenant's p99 stays under `slo_ms`, the noisy tenant's flood trips
    the quota (counted + journaled), and every 429'd submission
    eventually succeeds on retry (the Retry-After contract)."""
    from stateright_tpu.service import ServiceFleet, TenantQuotas, serve_fleet
    from stateright_tpu.service.autoscale import AutoscaleConfig, Autoscaler

    quotas = TenantQuotas()
    quotas.set_quota("noisy", max_in_flight=6)
    fleet = ServiceFleet(
        n_replicas=1,
        background=True,
        max_resident=4,
        service_kwargs=dict(batch_size=512, table_log2=16),
        quotas=quotas,
    )
    auto = Autoscaler(
        fleet,
        AutoscaleConfig(
            min_replicas=1,
            max_replicas=max_replicas,
            queue_high=2.0,
            scale_out_after=2,
            scale_in_after=10,
            cooldown_ticks=4,
        ),
    )
    auto.start(interval_s=0.2)
    srv = serve_fleet(fleet, address="localhost:0")
    base = "http://" + srv.address
    lock = threading.Lock()
    lat = {"quiet": [], "noisy": []}
    rejected = {"quiet": 0, "noisy": 0}
    failures = []

    def post(path, body):
        req = urllib.request.Request(
            base + path, data=json.dumps(body).encode(), method="POST"
        )
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    def get(path):
        return json.loads(
            urllib.request.urlopen(base + path, timeout=30).read()
        )

    def client(tenant, ci, n_jobs):
        for j in range(n_jobs):
            name, margs, gold = MIX[(ci + j) % len(MIX)]
            t0 = time.monotonic()
            while True:  # submit honoring 503 AND 429 Retry-After
                try:
                    jid = post(
                        "/jobs",
                        {"model": name, "args": margs, "tenant": tenant},
                    )["job"]
                    break
                except urllib.error.HTTPError as e:
                    if e.code not in (503, 429):
                        raise
                    if e.code == 429:
                        with lock:
                            rejected[tenant] += 1
                    time.sleep(float(e.headers.get("Retry-After") or 1))
            while True:  # poll to completion
                try:
                    p = get(f"/jobs/{jid}")
                except urllib.error.HTTPError as e:
                    if e.code not in (503, 429):
                        raise
                    time.sleep(float(e.headers.get("Retry-After") or 1))
                    continue
                if p["status"] in ("done", "error", "cancelled"):
                    break
                time.sleep(0.01)
            got = (p.get("state_count"), p.get("unique_state_count"))
            with lock:
                lat[tenant].append(time.monotonic() - t0)
                if p["status"] != "done" or got != gold:
                    failures.append(
                        f"{tenant} client {ci} job {jid} ({name}): "
                        f"status={p['status']} counts={got} != {gold}"
                    )

    # ~10x asymmetry: the noisy tenant floods, the quiet tenant trickles.
    quiet_jobs = max(jobs // 11, 2)
    noisy_jobs = max(jobs - quiet_jobs, quiet_jobs)
    quiet_clients = max(clients // 10, 1)
    noisy_clients = max(clients - quiet_clients, 1)
    threads = [
        threading.Thread(
            target=client,
            args=("quiet", i, max(quiet_jobs // quiet_clients, 1)),
        )
        for i in range(quiet_clients)
    ] + [
        threading.Thread(
            target=client,
            args=("noisy", i, max(noisy_jobs // noisy_clients, 1)),
        )
        for i in range(noisy_clients)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    stats = fleet.stats()
    am = auto.metrics()
    auto.close()
    srv.shutdown()
    fleet.close()

    def pct(samples, q):
        s = sorted(x * 1000 for x in samples) or [0.0]
        return round(s[min(int(q * (len(s) - 1)), len(s) - 1)], 1)

    for tenant in ("quiet", "noisy"):
        print(
            f"{tenant}:",
            json.dumps(
                {
                    "jobs": len(lat[tenant]),
                    "p50_ms": pct(lat[tenant], 0.50),
                    "p99_ms": pct(lat[tenant], 0.99),
                    "throttled_429": rejected[tenant],
                }
            ),
        )
    print(
        "autoscale:",
        json.dumps(
            {
                "jobs_per_sec": round(
                    sum(len(v) for v in lat.values()) / max(wall, 1e-9), 2
                ),
                "replicas_high_water": am["replicas_high_water"],
                "scale_outs": am["scale_outs"],
                "scale_ins": am["scale_ins"],
                "quota_rejected": stats["quota_rejected"],
            }
        ),
    )
    quiet_p99 = pct(lat["quiet"], 0.99)
    if quiet_p99 > slo_ms:
        failures.append(
            f"quiet tenant p99 {quiet_p99}ms blew the {slo_ms}ms SLO "
            "(noisy tenant leaked through the isolation)"
        )
    if stats["quota_rejected"] < 1:
        failures.append(
            "noisy flood never tripped its quota (gate not exercised)"
        )
    if max_replicas > 1 and am["replicas_high_water"] < 2:
        failures.append(
            "autoscaler never scaled out under the flood "
            f"(high water {am['replicas_high_water']})"
        )
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=100)
    ap.add_argument("--jobs", type=int, default=200)
    ap.add_argument("--compare", action="store_true",
                    help="also run the same load on 1 replica; print ratio")
    ap.add_argument("--crash", action="store_true",
                    help="kill replica 0 mid-load via the chaos plane")
    ap.add_argument("--warm", action="store_true",
                    help="pre-publish the mixed set into a shared corpus, "
                         "then report warm-vs-cold jobs/s side by side")
    ap.add_argument("--procs", action="store_true",
                    help="cross-process fleet: one replica_main subprocess "
                         "per replica over a shared store root (lease "
                         "plane on; --crash becomes a real kill -9)")
    ap.add_argument("--blob", action="store_true",
                    help="shared store root behind the in-proc object-store "
                         "emulator (blob:// backend: conditional puts, "
                         "bounded retry, member discovery)")
    ap.add_argument("--tenants", action="store_true",
                    help="mixed-tenant isolation run: quiet 1x + noisy 10x "
                         "tenants against an AUTOSCALING fleet (--replicas "
                         "is the autoscaler's max); asserts the quiet "
                         "tenant's p99 SLO and the noisy tenant's quota")
    ap.add_argument("--slo-ms", type=float, default=30_000.0,
                    help="quiet-tenant p99 SLO for --tenants (ms)")
    args = ap.parse_args(argv)

    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        # The image's site config re-registers the axon TPU platform over a
        # plain env var; pin at the jax.config level (same move as bench.py).
        jax.config.update("jax_platforms", p)

    blobd = None
    roots = [0]

    def fresh_blob_root():
        if blobd is None:
            return None
        roots[0] += 1
        return f"{blobd.root_uri}/load{roots[0]}"

    if args.blob:
        from stateright_tpu.faults.blobstore import serve_blobd

        blobd = serve_blobd()
        print(f"blob emulator at {blobd.root_uri}")

    if args.tenants:
        bad = run_tenants_load(
            args.replicas, args.clients, args.jobs, args.slo_ms
        )
        if blobd is not None:
            blobd.shutdown()
        if bad:
            print("FAILURES:", "; ".join(bad[:10]), file=sys.stderr)
            return 1
        print("tenant load OK")
        return 0

    if args.warm:
        # Warm-vs-cold A/B: pre-publish the mixed set into one shared
        # corpus, run the load against it, then run the identical load
        # cold (same tiered store config) and report side by side. With
        # --compare the 1-replica baseline is ALSO warm (same corpus) so
        # the scale-out ratio stays a replicas-only comparison instead of
        # conflating warm-start speedup into it.
        import tempfile

        with tempfile.TemporaryDirectory(prefix="srtpu-corpus-") as td:
            # With --blob the shared corpus ALSO lives in the object store
            # (content-addressed conditional puts de-duplicate publishes
            # server-side).
            d = td if blobd is None else fresh_blob_root() + "/corpus"
            prepublish_corpus(d)
            row, failures = run_load(
                args.replicas, args.clients, args.jobs, crash=args.crash,
                corpus_dir=d, procs=args.procs,
                blob_root=fresh_blob_root(),
            )
            row1, fail1 = (
                run_load(1, args.clients, args.jobs, corpus_dir=d,
                         procs=args.procs, blob_root=fresh_blob_root())
                if args.compare
                else (None, [])
            )
        cold_row, cold_fail = run_load(
            args.replicas, args.clients, args.jobs, tiered=True,
            procs=args.procs, blob_root=fresh_blob_root(),
        )
        print("warm:", json.dumps(row))
        print("cold:", json.dumps(cold_row))
        ratio = row["jobs_per_sec"] / max(cold_row["jobs_per_sec"], 1e-9)
        print(
            f"warm-start: {row['jobs_per_sec']} jobs/s p50 {row['p50_ms']}ms "
            f"warm vs {cold_row['jobs_per_sec']} jobs/s p50 "
            f"{cold_row['p50_ms']}ms cold -> {ratio:.2f}x"
        )
        bad = list(failures) + cold_fail + fail1
    else:
        row, failures = run_load(
            args.replicas, args.clients, args.jobs, crash=args.crash,
            procs=args.procs, blob_root=fresh_blob_root(),
        )
        print("fleet:", json.dumps(row))
        bad = list(failures)
        row1, fail1 = (
            run_load(1, args.clients, args.jobs, procs=args.procs,
                     blob_root=fresh_blob_root())
            if args.compare
            else (None, [])
        )
        bad += fail1
    if args.compare:
        print("one-replica:", json.dumps(row1))
        ratio = row["jobs_per_sec"] / max(row1["jobs_per_sec"], 1e-9)
        print(
            f"scale-out: {args.replicas} replicas at {row['jobs_per_sec']} "
            f"jobs/s vs 1 replica at {row1['jobs_per_sec']} jobs/s "
            f"-> {ratio:.2f}x"
        )
    if args.crash and row["replica_crashes"] < 1:
        bad.append("crash requested but no replica crash was recorded")
    if blobd is not None:
        blobd.shutdown()
    if bad:
        print("FAILURES:", "; ".join(bad[:10]), file=sys.stderr)
        return 1
    print("fleet load OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
