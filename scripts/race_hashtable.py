"""Race the two visited-set insert designs on real hardware (VERDICT r3 #5).

Usage (serialize with other TPU clients — the axon tunnel is single-client):

    python scripts/race_hashtable.py            # real device (TPU if alive)
    JAX_PLATFORMS=cpu python scripts/race_hashtable.py --cpu

Prints ms/batch and keys/s for the XLA scatter-max insert
(tensor/hashtable.py) vs the partitioned-VMEM Pallas insert
(tensor/pallas_hashtable.py) across bench-relevant (batch, table) shapes,
plus a cross-check that both report the same new-key count. The winner
becomes the engines' default (the loser stays behind the flag).
"""

import argparse
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="pin CPU + interpret")
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    import os

    cpu_requested = args.cpu or "cpu" in [
        p.strip().lower()
        for p in os.environ.get("JAX_PLATFORMS", "").split(",")
    ]
    if cpu_requested:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if cpu_requested:
        jax.config.update("jax_platforms", "cpu")
    # Backend-split compile cache keyed on the EFFECTIVE backend (same
    # policy as bench.py): .jax_cache holds TPU entries; XLA:CPU AOT entries
    # are host-specific and live in .jax_cache_cpu. Keying on the real
    # device (not the flag) means a silent CPU fallback can't poison the
    # TPU cache — and is called out so its timings are never mistaken for
    # a silicon verdict.
    on_cpu = jax.devices()[0].platform == "cpu"
    jax.config.update(
        "jax_compilation_cache_dir",
        "/root/repo/.jax_cache_cpu" if on_cpu else "/root/repo/.jax_cache",
    )
    if on_cpu and not cpu_requested:
        print(
            "WARNING: no accelerator reachable — running on the CPU "
            "backend. These timings are NOT a silicon verdict; do not pick "
            "an engine default from them.",
            flush=True,
        )
    args.cpu = on_cpu  # interpret-mode Pallas + honest labels below
    import jax.numpy as jnp
    import numpy as np

    from stateright_tpu.tensor.hashtable import HashTable
    from stateright_tpu.tensor.pallas_hashtable import PallasHashTable

    print(f"devices: {jax.devices()}", flush=True)
    rng = np.random.default_rng(0)
    rc = 0

    for B, tlog, parts in ((131072, 22, 64), (425984, 25, 256), (425984, 27, 512)):
        batches = []
        for _ in range(args.repeats + 1):
            batches.append(
                (
                    jnp.asarray(rng.integers(1, 2**32, B, dtype=np.uint32)),
                    jnp.asarray(rng.integers(0, 2**32, B, dtype=np.uint32)),
                )
            )
        act = jnp.ones(B, dtype=bool)

        new_counts = {}
        for name, make in (
            ("xla ", lambda: HashTable(tlog)),
            (
                "plas",
                lambda: PallasHashTable(
                    tlog, n_partitions=parts, interpret=args.cpu
                ),
            ),
        ):
            try:
                ht = make()
                lo, hi = batches[0]
                r = ht.insert(lo, hi, lo, hi, act)  # compile + warm
                jax.block_until_ready(r.is_new)
                new_total = int(np.asarray(r.is_new).sum())
                t0 = time.monotonic()
                for lo, hi in batches[1:]:
                    r = ht.insert(lo, hi, lo, hi, act)
                    new_total += int(np.asarray(r.is_new).sum())
                jax.block_until_ready(r.is_new)
                dt = (time.monotonic() - t0) / args.repeats
                new_counts[name] = new_total
                print(
                    f"{name} B={B:>7} table=2^{tlog:<2} "
                    f"{dt * 1e3:8.1f} ms/batch  {B / dt / 1e6:7.2f} Mkeys/s "
                    f"(total new={new_total})",
                    flush=True,
                )
            except Exception as e:  # noqa: BLE001 — a failed variant must
                # not kill the race; the other side's number still matters.
                print(f"{name} B={B} table=2^{tlog} FAILED: {e}", flush=True)
        if len(new_counts) == 2 and len(set(new_counts.values())) != 1:
            # Same batches -> the designs must agree on how many keys were
            # new; a mismatch on real hardware is a correctness bug the
            # interpret-mode parity tests could not see. Loudly disqualify.
            print(
                f"PARITY MISMATCH at B={B} table=2^{tlog}: {new_counts} — "
                "do NOT crown a winner from this run",
                flush=True,
            )
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
