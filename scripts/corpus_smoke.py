#!/usr/bin/env python
"""Warm-start corpus smoke (v2): the full delta-proportional re-verification
ladder end to end through the check service, one command, exit 0 iff every
leg held.

v1 legs (exact rung): publish -> warm hit -> corrupt -> cold fallback ->
re-publish -> warm again. Corpus v2 legs: preempt a job mid-run (the cut
publishes the visited prefix + frontier snapshot as a PARTIAL entry), cancel
the parked job, re-submit — the successor warm-starts from the partial and
its completion SUPERSEDES the partial under the same content key; then a
retuned service (different lowering, same definition) re-checks through the
NEAR rung via the family index. Every leg must return the golden counts;
warm legs must take their expected rung (detail["corpus"]["warm_kind"]).

    JAX_PLATFORMS=cpu python scripts/corpus_smoke.py
"""

import glob
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD_2PC3 = (1_146, 288)

SVC_KW = dict(
    batch_size=256, table_log2=15, store="tiered",
    summary_log2=16, background=False,
)


def _entry_files(corpus_dir):
    """Corpus ENTRY generations (complete + partial), excluding the v2
    near-match family index and the Spec-CI spec index riding in the
    same directory."""
    return [
        p for p in glob.glob(os.path.join(corpus_dir, "corpus-*.npz"))
        if "-family-" not in os.path.basename(p)
        and "-spec-" not in os.path.basename(p)
    ]


def main() -> int:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        # The image's site config re-registers the axon TPU platform over a
        # plain env var; pin at the jax.config level (same move as bench.py).
        jax.config.update("jax_platforms", p)

    from stateright_tpu.service import CheckService
    from stateright_tpu.store.corpus import CorpusStore
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    model = TensorTwoPhaseSys(3)
    failures = []

    def submit(svc, label, expect_warm, expect_kind=None, m=None):
        t0 = time.monotonic()
        h = svc.submit(m if m is not None else model)
        svc.drain(timeout=600)
        sec = time.monotonic() - t0
        r = h.result()
        corpus = r.detail.get("corpus") or {}
        print(
            f"{label}: states={r.state_count} unique={r.unique_state_count} "
            f"steps={r.steps} sec={sec:.2f} corpus={corpus}"
        )
        if m is None and (r.state_count, r.unique_state_count) != GOLD_2PC3:
            failures.append(f"{label}: counts != {GOLD_2PC3}")
        if corpus.get("warm_start", False) != expect_warm:
            failures.append(
                f"{label}: warm_start={corpus.get('warm_start')} "
                f"(expected {expect_warm})"
            )
        if expect_kind is not None and corpus.get("warm_kind") != expect_kind:
            failures.append(
                f"{label}: warm_kind={corpus.get('warm_kind')} "
                f"(expected {expect_kind})"
            )
        return r

    # -- v1 legs: exact rung + corruption fallback -----------------------------
    with tempfile.TemporaryDirectory(prefix="srtpu-corpus-") as corpus_dir:
        svc = CheckService(corpus_dir=corpus_dir, **SVC_KW)
        r_cold = submit(svc, "cold (publishes)", expect_warm=False)
        if not (r_cold.detail.get("corpus") or {}).get("published"):
            failures.append("cold run did not publish a corpus entry")

        r_warm = submit(
            svc, "warm (corpus hit)", expect_warm=True, expect_kind="exact"
        )
        if r_warm.steps >= r_cold.steps:
            failures.append(
                f"warm run used {r_warm.steps} steps vs cold {r_cold.steps}"
            )
        if r_warm.discoveries != r_cold.discoveries:
            failures.append("warm discoveries != cold discoveries")

        # Corrupt the published entry (one flipped payload byte): the
        # ckptio CRC footer must catch it and the next submission must
        # fall back to a CORRECT cold run, then re-publish.
        from stateright_tpu.faults.ckptio import corrupt_one_byte

        (entry,) = _entry_files(corpus_dir)
        corrupt_one_byte(entry)
        print(f"corrupted one byte of {os.path.basename(entry)}")

        r_corrupt = submit(svc, "corrupt (cold fallback)", expect_warm=False)
        stats = svc.stats().get("corpus") or {}
        print("corpus stats:", stats)
        if stats.get("corrupt_entries", 0) < 1:
            failures.append("corrupted entry was not detected by the CRC")
        if not (r_corrupt.detail.get("corpus") or {}).get("published"):
            failures.append("cold fallback did not re-publish the entry")

        submit(svc, "re-warm (healed corpus)", expect_warm=True)
        svc.close()

    # -- v2 legs: partial publish -> warm continuation -> supersede -> near ----
    with tempfile.TemporaryDirectory(prefix="srtpu-corpus-v2-") as corpus_dir:
        svc = CheckService(
            corpus_dir=corpus_dir, max_resident=1, preempt_steps=2, **SVC_KW
        )
        hA = svc.submit(model)
        for _ in range(4):  # past the preemption budget
            svc.pump()
        key = hA._job.content_key
        hB = svc.submit(TensorTwoPhaseSys(2))  # the waiter that forces a park
        for _ in range(32):
            svc.pump()
            if hA._job.status == "preempted":
                break
        if hA._job.status != "preempted":
            failures.append(f"job never preempted (status {hA._job.status})")
        store = CorpusStore(corpus_dir)
        pe = store.lookup_partial(key)
        if pe is None or pe.complete or pe.frontier is None:
            failures.append("preemption cut did not publish a frontier partial")
        else:
            print(
                f"preempt partial: states={pe.states} "
                f"frontier_rows={pe.frontier['lo'].size} meta={pe.meta}"
            )
        # Cancel the PARKED job: its preemption-time partial (with the
        # frontier) must survive — the shutdown cut must not overwrite it
        # with a frontier-less one.
        hA.cancel()
        svc.drain(timeout=600)  # the 2pc-2 waiter completes
        if store.lookup_partial(key) is None:
            failures.append("cancelling the parked job clobbered its partial")

        # The successor continues from the published prefix and its
        # completion supersedes the partial under the same content key.
        submit(
            svc, "successor (warm from partial)",
            expect_warm=True, expect_kind="partial",
        )
        stats = svc.stats().get("corpus") or {}
        print("corpus stats:", stats)
        if stats.get("partial_publishes", 0) < 1:
            failures.append("partial_publishes counter never moved")
        if stats.get("partial_preloads", 0) < 1:
            failures.append("partial_preloads counter never moved")
        if stats.get("superseded_entries", 0) < 1:
            failures.append("complete publish did not supersede the partial")
        if store.lookup_partial(key) is not None:
            failures.append("superseded partial entry still on disk")
        if store.lookup(key) is None:
            failures.append("successor did not publish the complete entry")
        svc.close()

        # Near-match after a retune: a DIFFERENT lowering (table_log2 + 1)
        # misses the exact rung; the family index serves the same
        # definition's published set through the near rung.
        near_svc = CheckService(
            corpus_dir=corpus_dir, **dict(SVC_KW, table_log2=16)
        )
        submit(
            near_svc, "retuned (warm via near match)",
            expect_warm=True, expect_kind="near",
        )
        near_stats = near_svc.stats().get("corpus") or {}
        print("corpus stats:", near_stats)
        if near_stats.get("near_match_hits", 0) < 1:
            failures.append("near_match_hits counter never moved")
        near_svc.close()

    if failures:
        print("FAILURES:", "; ".join(failures), file=sys.stderr)
        return 1
    print("corpus smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
