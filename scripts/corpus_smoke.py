#!/usr/bin/env python
"""Warm-start corpus smoke: publish -> warm hit -> corrupt -> cold fallback
-> re-publish -> warm again, end to end through the check service.

CI-shaped: exercises the whole cross-job warm-start path (store/corpus.py)
in one command — content-key derivation, corpus publish on completion,
tiered preload + device Bloom dedup on the second submission, the CRC
corrupt-entry fallback (one flipped byte => detected, ignored, correct cold
run), and the re-publish that heals the corpus. Exit code 0 iff every
submission returned the golden counts, the warm submissions actually took
the warm path (fewer fused steps), and the corruption was detected.

    JAX_PLATFORMS=cpu python scripts/corpus_smoke.py
"""

import glob
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD_2PC3 = (1_146, 288)


def main() -> int:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        # The image's site config re-registers the axon TPU platform over a
        # plain env var; pin at the jax.config level (same move as bench.py).
        jax.config.update("jax_platforms", p)

    from stateright_tpu.service import CheckService
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    model = TensorTwoPhaseSys(3)
    failures = []

    def submit(svc, label, expect_warm):
        t0 = time.monotonic()
        h = svc.submit(model)
        svc.drain(timeout=600)
        sec = time.monotonic() - t0
        r = h.result()
        corpus = r.detail.get("corpus") or {}
        print(
            f"{label}: states={r.state_count} unique={r.unique_state_count} "
            f"steps={r.steps} sec={sec:.2f} corpus={corpus}"
        )
        if (r.state_count, r.unique_state_count) != GOLD_2PC3:
            failures.append(f"{label}: counts != {GOLD_2PC3}")
        if corpus.get("warm_start", False) != expect_warm:
            failures.append(
                f"{label}: warm_start={corpus.get('warm_start')} "
                f"(expected {expect_warm})"
            )
        return r

    with tempfile.TemporaryDirectory(prefix="srtpu-corpus-") as corpus_dir:
        svc = CheckService(
            batch_size=256, table_log2=15, store="tiered",
            summary_log2=16, corpus_dir=corpus_dir, background=False,
        )
        r_cold = submit(svc, "cold (publishes)", expect_warm=False)
        if not (r_cold.detail.get("corpus") or {}).get("published"):
            failures.append("cold run did not publish a corpus entry")

        r_warm = submit(svc, "warm (corpus hit)", expect_warm=True)
        if r_warm.steps >= r_cold.steps:
            failures.append(
                f"warm run used {r_warm.steps} steps vs cold {r_cold.steps}"
            )
        if r_warm.discoveries != r_cold.discoveries:
            failures.append("warm discoveries != cold discoveries")

        # Corrupt the published entry (one flipped payload byte): the
        # ckptio CRC footer must catch it and the next submission must
        # fall back to a CORRECT cold run, then re-publish.
        from stateright_tpu.faults.ckptio import corrupt_one_byte

        (entry,) = glob.glob(os.path.join(corpus_dir, "corpus-*.npz"))
        corrupt_one_byte(entry)
        print(f"corrupted one byte of {os.path.basename(entry)}")

        r_corrupt = submit(svc, "corrupt (cold fallback)", expect_warm=False)
        stats = svc.stats().get("corpus") or {}
        print("corpus stats:", stats)
        if stats.get("corrupt_entries", 0) < 1:
            failures.append("corrupted entry was not detected by the CRC")
        if not (r_corrupt.detail.get("corpus") or {}).get("published"):
            failures.append("cold fallback did not re-publish the entry")

        submit(svc, "re-warm (healed corpus)", expect_warm=True)
        svc.close()

    if failures:
        print("FAILURES:", "; ".join(failures), file=sys.stderr)
        return 1
    print("corpus smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
