#!/usr/bin/env python
"""Observability smoke: run a pinned model with telemetry + tracing on,
assert every artifact exists and validates.

CI-shaped: exercises the whole telemetry spine in one command — device step
ring (drain totals vs golden counts), Chrome trace-event JSON (trace_out
through the builder), and the Prometheus `/metrics` plane on the service
HTTP front end. Exit code 0 iff every check passes.

    JAX_PLATFORMS=cpu python scripts/obs_smoke.py [--keep]

Artifacts land in a temp dir (kept with --keep, printed either way); load
the trace in https://ui.perfetto.dev.
"""

import json
import os
import re
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLD = (1_146, 288)  # 2pc-3 generated/unique (ref examples/2pc.rs:153-159)

_PROM_LINE = re.compile(
    r"^(# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.eE]+)$"
)


def main(argv) -> int:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        # The image's site config re-registers the axon TPU platform over a
        # plain env var; pin at the jax.config level (same move as bench.py).
        jax.config.update("jax_platforms", p)

    from stateright_tpu.service import CheckService
    from stateright_tpu.service.server import serve_service
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    keep = "--keep" in argv
    outdir = tempfile.mkdtemp(prefix="obs_smoke_")
    trace_path = os.path.join(outdir, "engine.trace.json")
    svc_trace_path = os.path.join(outdir, "service.trace.json")
    failures = []

    def check(ok: bool, what: str):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    model = TensorTwoPhaseSys(3)
    from stateright_tpu.tensor.frontier import seed_init

    init, _, _, n_raw = seed_init(model)
    n0 = len(init)

    # 1. Engine telemetry + tracing through the builder surface.
    checker = (
        model.checker()
        .trace_out(trace_path)
        .spawn_tpu(batch_size=256, table_log2=12)
        .join()
    )
    t = checker.telemetry_summary()
    check(checker.unique_state_count() == GOLD[1], "engine golden unique count")
    check(t is not None and t["steps"] > 0, "telemetry digest present")
    # Conservation law: every fresh claim (resp. generated state) appears
    # in exactly one drained step row, so the ring totals reconstruct the
    # golden counts from the seed.
    check(
        t["dropped_steps"] == 0
        and t["claimed_total"] == checker.unique_state_count() - n0
        and t["generated_total"] == checker.state_count() - n_raw,
        "telemetry claim/generation accounting",
    )
    check(os.path.exists(trace_path), f"trace file exists ({trace_path})")
    doc = json.load(open(trace_path))
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    check(len(events) > 0, f"trace has {len(events)} complete spans")
    check(
        all({"name", "ts", "dur", "pid", "tid"} <= set(e) for e in events),
        "trace events are Chrome trace-event shaped",
    )

    # 2. Service telemetry + /metrics scrape + service trace.
    svc = CheckService(
        batch_size=256, table_log2=14, background=False,
        trace_out=svc_trace_path,
    )
    handle = svc.submit(model)
    svc.drain(timeout=600)
    r = handle.result()
    check(
        (r.state_count, r.unique_state_count) == GOLD,
        "service job golden counts",
    )
    check(
        r.detail is not None and "telemetry" in r.detail,
        "job result carries telemetry detail",
    )
    st = svc.stats()
    check(
        st["telemetry"]["steps"] == st["device_steps"] > 0,
        "service ring saw every fused step",
    )
    server = serve_service(svc, "localhost:0")
    try:
        body = (
            urllib.request.urlopen(
                f"http://{server.address}/metrics", timeout=10
            )
            .read()
            .decode()
        )
        lines = [ln for ln in body.splitlines() if ln.strip()]
        check(
            bool(lines) and all(_PROM_LINE.match(ln) for ln in lines),
            f"/metrics parses as Prometheus text ({len(lines)} lines)",
        )
        status = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/.status", timeout=10
            ).read()
        )
        check("telemetry" in status, "/.status merged the telemetry digest")
    finally:
        server.shutdown()
    svc.close()
    check(os.path.exists(svc_trace_path), "service trace file exists")

    print(f"artifacts in {outdir}" + ("" if keep else " (temp)"))
    if failures:
        print("FAILURES:", "; ".join(failures), file=sys.stderr)
        return 1
    print("obs smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
