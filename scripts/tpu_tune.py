"""Real-TPU tuning sweep for the resident engine on the north-star workload.

Single-config mode runs one (workload, batch, table, layout) on the DEFAULT
jax backend (i.e. the axon TPU when the tunnel is up), asserting golden
parity and printing states/sec — one config per invocation so a wedged
tunnel can't eat a whole sweep (scripts/tpu_tune.sh drives it that way).

Sweep mode makes tunnel day a single command: it races
insert_variant x batch in subprocess-isolated single-config runs, collects
the machine-readable RESULT_JSON line each prints, joins the measurements
with the cost model's committed predictions (tensor/costmodel.py), and
dumps a ranking JSON.

Usage:
  python scripts/tpu_tune.py MODEL N BATCH TABLE_LOG2 [REPEATS] [LAYOUT] \
      [STORE] [HIGH_WATER] [SUMMARY_LOG2]
  python scripts/tpu_tune.py --sweep MODEL N TABLE_LOG2 \
      [--batches 2048,4096,8192] [--variants split,kv,phased,capped,pallas] \
      [--stores device,tiered] [--high-waters 0.85] [--summary-bits 20] \
      [--repeats R] [--timeout SEC] [--out tune_ranking.json]
  python scripts/tpu_tune.py sim MODEL N TRACES DEDUP [WALKS] [MAX_DEPTH] \
      [REPEATS] [TABLE_LOG2]
  python scripts/tpu_tune.py --sweep MODEL N TABLE_LOG2 --sim \
      [--traces 1024,2048,4096] [--dedup trace,shared] [--walks W] \
      [--max-depth D] [--repeats R] [--timeout SEC] [--out ...]

The `sim` forms race the fourth engine (tensor/simulation.py, the device
random-walk checker): `--sim` switches the sweep axes to traces x dedup
(DEDUP values: trace | shared — knobs.SIM_DEDUP_KINDS; shared runs the
global visited table so walks/s AND real unique coverage are measured),
ranking configs by walks/s next to the costmodel's committed
sim_step_cost/sim_walks_per_sec predictions.

LAYOUT / --variants values: split (default) | kv | phased | capped |
capped-kv | capped-phased | pallas — the visited-table designs to race
(kv = interleaved buckets; phased = pre-sort-claim scatter-max insert;
capped = batch-monotonic claim-tile insert, see
hashtable.make_capped_insert; pallas = the partitioned-VMEM
route-then-probe kernel, tensor/pallas_hashtable.py — the SURVEY §7
end-state design; needs table_log2 >= 10 and runs interpret-mode off-TPU).

STORE / --stores values: device (default) | tiered — the two-tier state
store (stateright_tpu/store/: device hot set + host spill tier). With
--stores including "tiered", the sweep races every water-mark x summary-bit
combination from --high-waters / --summary-bits alongside the insert
variants, so tunnel day prices the spill machinery with one command.
(tiered composes with the split-layout insert variants only.)

Set TPU_TUNE_TRACE=/path to capture a jax.profiler trace of the timed runs
(inspect with tensorboard or xprof to see the per-step op breakdown).
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: F401 — backend selected by _pin_platform below

from bench import GOLDEN, _pin_platform  # one golden table, one platform pin

_pin_platform()

# LAYOUT name -> (table_layout, insert_variant) engine options. The
# costmodel variant for predicted_ms comes from the shared
# costmodel.ENGINE_VARIANTS mapping (one source of truth with bench.py).
LAYOUTS = {
    "split": ("split", "sort"),
    "kv": ("kv", "sort"),
    "phased": ("split", "phased"),
    "capped": ("split", "capped"),
    "capped-kv": ("kv", "capped"),
    "capped-phased": ("split", "capped-phased"),
    "pallas": ("split", "pallas"),
}


def _build_model(model_name: str, n: int):
    if model_name == "paxos":
        from stateright_tpu.tensor.paxos import TensorPaxos

        return TensorPaxos(client_count=n)
    if model_name in ("inclock", "inclock-sym"):
        from stateright_tpu.tensor.models import TensorIncrementLock

        return TensorIncrementLock(n, symmetry=model_name == "inclock-sym")
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    return TensorTwoPhaseSys(n)


def run_single(model_name, n, batch, table_log2, repeats, layout,
               store="device", high_water=0.85, summary_log2=20) -> int:
    if layout not in LAYOUTS:
        print(f"unknown LAYOUT {layout!r} ({' | '.join(LAYOUTS)})")
        return 2
    table_layout, insert_variant = LAYOUTS[layout]

    from stateright_tpu.tensor.resident import ResidentSearch

    model = _build_model(model_name, n)
    store_desc = (
        f" store=tiered(hw={high_water},sb={summary_log2})"
        if store == "tiered"
        else ""
    )
    print(
        f"devices={jax.devices()} workload={model_name}-{n} "
        f"batch={batch} table=2^{table_log2} layout={layout}{store_desc}",
        flush=True,
    )
    search = ResidentSearch(
        model,
        batch_size=batch,
        table_log2=table_log2,
        table_layout=table_layout,
        insert_variant=insert_variant,
        store=store,
        high_water=high_water,
        summary_log2=summary_log2,
    )
    t0 = time.monotonic()
    r = search.run()
    compile_s = time.monotonic() - t0
    print(f"compile+first: {compile_s:.1f}s", flush=True)
    trace_dir = os.environ.get("TPU_TUNE_TRACE")
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    best = None
    try:
        for i in range(repeats):
            # Tiered runs are chunked and retain a carry across run()
            # calls; without the reset every repeat would be a no-op
            # resume "measuring" near-zero durations (the 2pc-10 bench
            # lesson). Whole-search engines start fresh regardless.
            search.reset()
            r = search.run()
            print(
                f"  run {i}: {r.duration:.4f}s "
                f"({r.state_count / max(r.duration, 1e-9):,.0f} states/s, "
                f"steps={r.steps})",
                flush=True,
            )
            if best is None or r.duration < best.duration:
                best = r
    finally:
        if trace_dir:
            # Flush even when a run dies mid-loop — that is exactly when
            # the trace explains the failure.
            jax.profiler.stop_trace()
            print(f"profiler trace written to {trace_dir}", flush=True)
    gold = GOLDEN.get((model_name, n))
    parity_ok = gold is None or (
        (best.state_count, best.unique_state_count) == gold
    )
    sps = best.state_count / max(best.duration, 1e-9)
    # Machine-readable line the sweep driver parses.
    rec = {
        "workload": f"{model_name}-{n}",
        "batch": batch,
        "table_log2": table_log2,
        "layout": layout,
        "store": store,
        "sec": round(best.duration, 4),
        "states_per_sec": round(sps, 1),
        "steps": best.steps,
        "compile_sec": round(compile_s, 1),
        "parity_ok": parity_ok,
    }
    if store == "tiered":
        rec["high_water"] = high_water
        rec["summary_log2"] = summary_log2
        stats = search.store_stats()
        if stats:
            rec.update(
                {
                    k: stats[k]
                    for k in ("hot_fill", "spilled_states", "spill_events")
                }
            )
    print("RESULT_JSON " + json.dumps(rec), flush=True)
    if not parity_ok:
        print(
            f"PARITY FAIL: {best.state_count}/{best.unique_state_count} "
            f"!= {gold}"
        )
        return 1
    print(
        f"BEST {model_name}-{n} b={batch} t={table_log2}: "
        f"{best.duration:.4f}s {sps:,.0f}/s"
    )
    return 0


def run_sim_single(model_name, n, traces, dedup, walks, max_depth,
                   repeats, table_log2) -> int:
    """One simulation-engine config: repeated rounds on a fresh engine per
    repeat (the rounds loop is cumulative by design), reporting walks/s and
    the walk-plane telemetry digest as the RESULT_JSON line."""
    from stateright_tpu.knobs import SIM_DEDUP_KINDS
    from stateright_tpu.tensor.simulation import DeviceSimulation

    if dedup not in SIM_DEDUP_KINDS:
        print(f"unknown DEDUP {dedup!r} ({' | '.join(SIM_DEDUP_KINDS)})")
        return 2
    model = _build_model(model_name, n)
    print(
        f"devices={jax.devices()} workload={model_name}-{n} sim "
        f"traces={traces} dedup={dedup} walks={walks} depth={max_depth}",
        flush=True,
    )

    def fresh():
        return DeviceSimulation(
            model, seed=7, traces=traces, max_depth=max_depth,
            dedup=dedup, table_log2=table_log2, walks=walks,
        )

    t0 = time.monotonic()
    fresh().run()
    compile_s = time.monotonic() - t0
    print(f"compile+first: {compile_s:.1f}s", flush=True)
    best = None
    for i in range(repeats):
        sim = fresh()  # same seed per repeat: bit-identical rounds
        t0 = time.monotonic()
        r = sim.run()
        sec = time.monotonic() - t0
        tel = r.detail["telemetry"]
        print(
            f"  run {i}: {sec:.4f}s ({tel['walks'] / max(sec, 1e-9):,.0f} "
            f"walks/s, {r.state_count / max(sec, 1e-9):,.0f} states/s, "
            f"lane_util={tel['lane_util']})",
            flush=True,
        )
        if best is None or sec < best[0]:
            best = (sec, r, tel)
    sec, r, tel = best
    rec = {
        "workload": f"{model_name}-{n}",
        "sim": True,
        "traces": traces,
        "dedup": dedup,
        "walks": tel["walks"],
        "max_depth": max_depth,
        "table_log2": table_log2,
        "sec": round(sec, 4),
        "walks_per_sec": round(tel["walks"] / max(sec, 1e-9), 1),
        "states_per_sec": round(r.state_count / max(sec, 1e-9), 1),
        "unique": r.unique_state_count,
        "lane_util": tel["lane_util"],
        "restarts": tel["restarts"],
        "compile_sec": round(compile_s, 1),
        "parity_ok": True,  # simulation has no exhaustive golden to pin
    }
    if dedup == "shared":
        rec["dedup_hit_rate"] = tel["dedup_hit_rate"]
    print("RESULT_JSON " + json.dumps(rec), flush=True)
    print(
        f"BEST {model_name}-{n} sim traces={traces} dedup={dedup}: "
        f"{rec['walks_per_sec']:,.0f} walks/s"
    )
    return 0


def run_sweep(argv: list) -> int:
    def opt(name, default):
        if name in argv:
            i = argv.index(name)
            if i + 1 >= len(argv):
                raise SystemExit(f"missing value for {name} (see --help)")
            v = argv[i + 1]
            del argv[i : i + 2]
            return v
        return default

    sim = "--sim" in argv
    if sim:
        argv.remove("--sim")
    traces_axis = [int(t) for t in opt("--traces", "1024,2048,4096").split(",")]
    dedup_axis = opt("--dedup", "trace,shared").split(",")
    sim_walks = opt("--walks", None)
    sim_depth = int(opt("--max-depth", "256"))
    batches = [int(b) for b in opt("--batches", "2048,4096,8192").split(",")]
    variants = opt("--variants", "split,kv,phased,capped,pallas").split(",")
    stores = opt("--stores", "device").split(",")
    high_waters = [float(x) for x in opt("--high-waters", "0.85").split(",")]
    summary_bits = [int(x) for x in opt("--summary-bits", "20").split(",")]
    repeats = int(opt("--repeats", "3"))
    timeout = float(opt("--timeout", "900"))
    out_path = opt("--out", "tune_ranking.json")
    if len(argv) < 3:  # re-check arity AFTER option pairs are stripped
        print(__doc__)
        return 2
    model_name, n, table_log2 = argv[0], int(argv[1]), int(argv[2])

    if sim:
        return run_sim_sweep(
            model_name, n, table_log2, traces_axis, dedup_axis,
            sim_walks, sim_depth, repeats, timeout, out_path,
        )

    bad = [v for v in variants if v not in LAYOUTS]
    if bad:
        print(f"unknown variants {bad} ({' | '.join(LAYOUTS)})")
        return 2
    bad = [s for s in stores if s not in ("device", "tiered")]
    if bad:
        print(f"unknown stores {bad} (device | tiered)")
        return 2
    # Store axis: the plain device store plus every requested
    # water-mark x summary-bit combination of the tiered store.
    store_cfgs = [("device", None, None)] if "device" in stores else []
    if "tiered" in stores:
        store_cfgs += [
            ("tiered", hw, sb) for hw in high_waters for sb in summary_bits
        ]

    model = _build_model(model_name, n)
    from stateright_tpu.tensor import costmodel as cm

    configs = []

    def flush() -> list:
        """Rewrite the ranking JSON after EVERY config: a wedged tunnel (or
        the driver's outer timeout) killing the sweep mid-way must not
        discard the configs that already measured."""
        measured = [c for c in configs if "states_per_sec" in c]
        ranking = sorted(
            measured, key=lambda c: c["states_per_sec"], reverse=True
        )
        result = {
            "workload": f"{model_name}-{n}",
            "table_log2": table_log2,
            "backend": jax.default_backend(),
            "model": {
                "lanes": model.lanes, "max_actions": model.max_actions,
            },
            "configs": configs,
            "ranking": [
                {
                    "layout": c["layout"],
                    "batch": c["batch"],
                    "store": c.get("store", "device"),
                    **(
                        {
                            "high_water": c["high_water"],
                            "summary_log2": c["summary_log2"],
                        }
                        if c.get("store") == "tiered"
                        else {}
                    ),
                    "states_per_sec": c["states_per_sec"],
                    "predicted_ms": round(c.get("predicted_ms", 0.0), 3),
                    "parity_ok": c["parity_ok"],
                }
                for c in ranking
            ],
        }
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
        return ranking

    for batch in batches:
        for layout in variants:
            for store, hw, sb in store_cfgs:
                if store == "tiered" and LAYOUTS[layout][0] != "split":
                    continue  # tiered eviction is split-bucket-layout only
                tag = (
                    f" store=tiered(hw={hw},sb={sb})"
                    if store == "tiered"
                    else ""
                )
                print(
                    f"== {model_name}-{n} b={batch} layout={layout}{tag}",
                    flush=True,
                )
                rec = {
                    "workload": f"{model_name}-{n}",
                    "batch": batch,
                    "table_log2": table_log2,
                    "layout": layout,
                    "store": store,
                }
                cmd = [
                    sys.executable,
                    os.path.abspath(__file__),
                    model_name,
                    str(n),
                    str(batch),
                    str(table_log2),
                    str(repeats),
                    layout,
                ]
                if store == "tiered":
                    rec["high_water"] = hw
                    rec["summary_log2"] = sb
                    cmd += [store, str(hw), str(sb)]
                try:
                    proc = subprocess.run(
                        cmd,
                        capture_output=True,
                        text=True,
                        timeout=timeout,
                    )
                except subprocess.TimeoutExpired:
                    rec["error"] = f"timed out after {timeout:.0f}s"
                    configs.append(rec)
                    flush()
                    print("   TIMEOUT", flush=True)
                    continue
                sys.stderr.write(proc.stderr)
                line = next(
                    (
                        ln[len("RESULT_JSON "):]
                        for ln in proc.stdout.splitlines()
                        if ln.startswith("RESULT_JSON ")
                    ),
                    None,
                )
                if line is None:
                    tail = proc.stdout.strip().splitlines()
                    rec["error"] = (
                        tail[-1] if tail else f"rc={proc.returncode}"
                    )
                    configs.append(rec)
                    flush()
                    print(f"   FAILED: {rec['error']}", flush=True)
                    continue
                rec.update(json.loads(line))
                rec["predicted_ms"] = cm.step_cost(
                    model.lanes,
                    model.max_actions,
                    batch,
                    table_log2,
                    variant=cm.ENGINE_VARIANTS[LAYOUTS[layout]],
                    # Probe-only spill term: per-step eviction volume is
                    # workload-dependent and unknown pre-run; the measured
                    # spill_events in the RESULT_JSON calibrate it later.
                    spill={"summary_hashes": 4} if store == "tiered" else None,
                ).total_ms
                configs.append(rec)
                flush()
                print(
                    f"   {rec['states_per_sec']:,.0f}/s "
                    f"(predicted {rec['predicted_ms']:.2f} ms/step, "
                    f"parity_ok={rec['parity_ok']})",
                    flush=True,
                )

    ranking = flush()
    measured = [c for c in configs if "states_per_sec" in c]
    print(f"ranking written to {out_path}")
    if ranking:
        best = ranking[0]
        print(
            f"WINNER {best['layout']} b={best['batch']}: "
            f"{best['states_per_sec']:,.0f}/s"
        )
    # Parity failures or wholly-failed sweeps are errors.
    if not measured or not all(c["parity_ok"] for c in measured):
        return 1
    return 0


def run_sim_sweep(model_name, n, table_log2, traces_axis, dedup_axis,
                  sim_walks, sim_depth, repeats, timeout, out_path) -> int:
    """The fourth engine's tunnel-day command: race traces x dedup in
    subprocess-isolated single-config runs, join with the costmodel's
    committed walk-step predictions, rank by walks/s."""
    from stateright_tpu.knobs import SIM_DEDUP_KINDS

    bad = [d for d in dedup_axis if d not in SIM_DEDUP_KINDS]
    if bad:
        print(f"unknown dedup values {bad} ({' | '.join(SIM_DEDUP_KINDS)})")
        return 2
    model = _build_model(model_name, n)
    from stateright_tpu.tensor import costmodel as cm

    configs = []

    def flush() -> list:
        measured = [c for c in configs if "walks_per_sec" in c]
        ranking = sorted(
            measured, key=lambda c: c["walks_per_sec"], reverse=True
        )
        with open(out_path, "w") as f:
            json.dump(
                {
                    "workload": f"{model_name}-{n}",
                    "sim": True,
                    "table_log2": table_log2,
                    "backend": jax.default_backend(),
                    "model": {
                        "lanes": model.lanes,
                        "max_actions": model.max_actions,
                    },
                    "configs": configs,
                    "ranking": [
                        {
                            "traces": c["traces"],
                            "dedup": c["dedup"],
                            "walks_per_sec": c["walks_per_sec"],
                            "states_per_sec": c["states_per_sec"],
                            "lane_util": c["lane_util"],
                            "predicted_ms": round(
                                c.get("predicted_ms", 0.0), 3
                            ),
                        }
                        for c in ranking
                    ],
                },
                f,
                indent=1,
            )
        return ranking

    for traces in traces_axis:
        for dedup in dedup_axis:
            print(
                f"== {model_name}-{n} sim traces={traces} dedup={dedup}",
                flush=True,
            )
            rec = {
                "workload": f"{model_name}-{n}",
                "traces": traces,
                "dedup": dedup,
            }
            walks = sim_walks or str(4 * traces)
            cmd = [
                sys.executable, os.path.abspath(__file__),
                "sim", model_name, str(n), str(traces), dedup,
                str(walks), str(sim_depth), str(repeats), str(table_log2),
            ]
            try:
                proc = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=timeout
                )
            except subprocess.TimeoutExpired:
                rec["error"] = f"timed out after {timeout:.0f}s"
                configs.append(rec)
                flush()
                print("   TIMEOUT", flush=True)
                continue
            sys.stderr.write(proc.stderr)
            line = next(
                (
                    ln[len("RESULT_JSON "):]
                    for ln in proc.stdout.splitlines()
                    if ln.startswith("RESULT_JSON ")
                ),
                None,
            )
            if line is None:
                tail = proc.stdout.strip().splitlines()
                rec["error"] = tail[-1] if tail else f"rc={proc.returncode}"
                configs.append(rec)
                flush()
                print(f"   FAILED: {rec['error']}", flush=True)
                continue
            rec.update(json.loads(line))
            rec["predicted_ms"] = cm.sim_step_cost(
                model.lanes, model.max_actions, traces,
                dedup=dedup, table_log2=table_log2,
            ).total_ms
            configs.append(rec)
            flush()
            print(
                f"   {rec['walks_per_sec']:,.0f} walks/s "
                f"(predicted {rec['predicted_ms']:.2f} ms/step, "
                f"lane_util={rec['lane_util']})",
                flush=True,
            )

    ranking = flush()
    print(f"ranking written to {out_path}")
    if ranking:
        best = ranking[0]
        print(
            f"WINNER sim traces={best['traces']} dedup={best['dedup']}: "
            f"{best['walks_per_sec']:,.0f} walks/s"
        )
    return 0 if ranking else 1


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "sim":
        if len(argv) < 5:
            print(__doc__)
            return 2
        return run_sim_single(
            argv[1], int(argv[2]), int(argv[3]), argv[4],
            int(argv[5]) if len(argv) > 5 else None,
            int(argv[6]) if len(argv) > 6 else 256,
            max(1, int(argv[7])) if len(argv) > 7 else 3,
            int(argv[8]) if len(argv) > 8 else 20,
        )
    if argv and argv[0] == "--sweep":
        if len(argv) < 4:
            print(__doc__)
            return 2
        return run_sweep(argv[1:])
    if len(argv) < 4:
        print(__doc__)
        return 2
    model_name, n, batch, table_log2 = (
        argv[0], int(argv[1]), int(argv[2]), int(argv[3])
    )
    repeats = max(1, int(argv[4])) if len(argv) > 4 else 3
    layout = argv[5] if len(argv) > 5 else "split"
    store = argv[6] if len(argv) > 6 else "device"
    high_water = float(argv[7]) if len(argv) > 7 else 0.85
    summary_log2 = int(argv[8]) if len(argv) > 8 else 20
    return run_single(
        model_name, n, batch, table_log2, repeats, layout,
        store=store, high_water=high_water, summary_log2=summary_log2,
    )


if __name__ == "__main__":
    sys.exit(main())
