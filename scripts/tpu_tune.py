"""Real-TPU tuning sweep for the resident engine on the north-star workload.

Runs paxos-3 (and optionally 2pc-4 as a smoke test) across a grid of
(batch_size, table_log2) configs on the DEFAULT jax backend (i.e. the axon
TPU when the tunnel is up), asserting golden parity every time and printing
states/sec per config. One workload config per subprocess invocation keeps a
wedged tunnel from eating the whole sweep — run via scripts/tpu_tune.sh.

Usage: python scripts/tpu_tune.py MODEL N BATCH TABLE_LOG2 [REPEATS] [LAYOUT]
LAYOUT: split (default) | kv | phased — the visited-table design to race
(kv = interleaved buckets; phased = pre-sort-claim scatter-max insert).
Set TPU_TUNE_TRACE=/path to capture a jax.profiler trace of the timed runs
(inspect with tensorboard or xprof to see the per-step op breakdown).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: F401 — backend selected by _pin_platform below

from bench import GOLDEN, _pin_platform  # one golden table, one platform pin

_pin_platform()


def main() -> int:
    if len(sys.argv) < 5:
        print(__doc__)
        return 2
    model_name, n, batch, table_log2 = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        int(sys.argv[4]),
    )
    repeats = max(1, int(sys.argv[5])) if len(sys.argv) > 5 else 3
    layout = sys.argv[6] if len(sys.argv) > 6 else "split"
    if layout not in ("split", "kv", "phased"):
        print(f"unknown LAYOUT {layout!r} (split | kv | phased)")
        return 2

    from stateright_tpu.tensor.resident import ResidentSearch

    if model_name == "paxos":
        from stateright_tpu.tensor.paxos import TensorPaxos

        model = TensorPaxos(client_count=n)
    elif model_name in ("inclock", "inclock-sym"):
        from stateright_tpu.tensor.models import TensorIncrementLock

        model = TensorIncrementLock(n, symmetry=model_name == "inclock-sym")
    else:
        from stateright_tpu.tensor.models import TensorTwoPhaseSys

        model = TensorTwoPhaseSys(n)

    print(
        f"devices={jax.devices()} workload={model_name}-{n} "
        f"batch={batch} table=2^{table_log2} layout={layout}",
        flush=True,
    )
    search = ResidentSearch(
        model,
        batch_size=batch,
        table_log2=table_log2,
        table_layout="kv" if layout == "kv" else "split",
        insert_variant="phased" if layout == "phased" else "sort",
    )
    t0 = time.monotonic()
    r = search.run()
    compile_s = time.monotonic() - t0
    print(f"compile+first: {compile_s:.1f}s", flush=True)
    trace_dir = os.environ.get("TPU_TUNE_TRACE")
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    best = None
    try:
        for i in range(repeats):
            r = search.run()
            print(
                f"  run {i}: {r.duration:.4f}s "
                f"({r.state_count / max(r.duration, 1e-9):,.0f} states/s, "
                f"steps={r.steps})",
                flush=True,
            )
            if best is None or r.duration < best.duration:
                best = r
    finally:
        if trace_dir:
            # Flush even when a run dies mid-loop — that is exactly when
            # the trace explains the failure.
            jax.profiler.stop_trace()
            print(f"profiler trace written to {trace_dir}", flush=True)
    gold = GOLDEN.get((model_name, n))
    if gold and (best.state_count, best.unique_state_count) != gold:
        print(f"PARITY FAIL: {best.state_count}/{best.unique_state_count} != {gold}")
        return 1
    print(
        f"BEST {model_name}-{n} b={batch} t={table_log2}: "
        f"{best.duration:.4f}s {best.state_count / max(best.duration, 1e-9):,.0f}/s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
