"""Real-TPU tuning sweep for the resident engine on the north-star workload.

Single-config mode runs one (workload, batch, table, layout) on the DEFAULT
jax backend (i.e. the axon TPU when the tunnel is up), asserting golden
parity and printing states/sec — one config per invocation so a wedged
tunnel can't eat a whole sweep (scripts/tpu_tune.sh drives it that way).

Sweep mode makes tunnel day a single command: it races
insert_variant x batch in subprocess-isolated single-config runs, collects
the machine-readable RESULT_JSON line each prints, joins the measurements
with the cost model's committed predictions (tensor/costmodel.py), and
dumps a ranking JSON.

Usage:
  python scripts/tpu_tune.py MODEL N BATCH TABLE_LOG2 [REPEATS] [LAYOUT] \
      [STORE] [HIGH_WATER] [SUMMARY_LOG2]
  python scripts/tpu_tune.py --sweep MODEL N TABLE_LOG2 \
      [--batches 2048,4096,8192] [--variants split,kv,phased,capped,pallas] \
      [--stores device,tiered] [--high-waters 0.85] [--summary-bits 20] \
      [--repeats R] [--timeout SEC] [--out tune_ranking.json]
  python scripts/tpu_tune.py sim MODEL N TRACES DEDUP [WALKS] [MAX_DEPTH] \
      [REPEATS] [TABLE_LOG2]
  python scripts/tpu_tune.py --sweep MODEL N TABLE_LOG2 --sim \
      [--traces 1024,2048,4096] [--dedup trace,shared] [--walks W] \
      [--max-depth D] [--repeats R] [--timeout SEC] [--out ...]
  python scripts/tpu_tune.py --calibrate ROOT \
      [--device KIND] [--ridge R] [--out overlay.json]

`--calibrate` is the calibration-observatory fitter (obs/calib.py): it
loads every durable observation record the comparators flushed under
ROOT (a store root or blob:// URI; records land in ROOT/calib/),
least-squares-fits the costmodel coefficient vector per device kind,
prints stock-vs-fitted rates plus a leave-one-key-out holdout table,
writes the loadable overlay JSON (activate with
SR_TPU_COSTMODEL_CALIB=<overlay>), and re-evaluates the two committed
pre-hardware rankings (r12 capped-vs-pallas insert crossover, r18
sim-walk shared-table overhead) under the fitted coefficients, printing
whether either committed default flips.

The `sim` forms race the fourth engine (tensor/simulation.py, the device
random-walk checker): `--sim` switches the sweep axes to traces x dedup
(DEDUP values: trace | shared — knobs.SIM_DEDUP_KINDS; shared runs the
global visited table so walks/s AND real unique coverage are measured),
ranking configs by walks/s next to the costmodel's committed
sim_step_cost/sim_walks_per_sec predictions.

LAYOUT / --variants values: split (default) | kv | phased | capped |
capped-kv | capped-phased | pallas — the visited-table designs to race
(kv = interleaved buckets; phased = pre-sort-claim scatter-max insert;
capped = batch-monotonic claim-tile insert, see
hashtable.make_capped_insert; pallas = the partitioned-VMEM
route-then-probe kernel, tensor/pallas_hashtable.py — the SURVEY §7
end-state design; needs table_log2 >= 10 and runs interpret-mode off-TPU).

STORE / --stores values: device (default) | tiered — the two-tier state
store (stateright_tpu/store/: device hot set + host spill tier). With
--stores including "tiered", the sweep races every water-mark x summary-bit
combination from --high-waters / --summary-bits alongside the insert
variants, so tunnel day prices the spill machinery with one command.
(tiered composes with the split-layout insert variants only.)

Set TPU_TUNE_TRACE=/path to capture a jax.profiler trace of the timed runs
(inspect with tensorboard or xprof to see the per-step op breakdown).
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: F401 — backend selected by _pin_platform below

from bench import GOLDEN, _pin_platform  # one golden table, one platform pin

_pin_platform()

# LAYOUT name -> (table_layout, insert_variant) engine options. The
# costmodel variant for predicted_ms comes from the shared
# costmodel.ENGINE_VARIANTS mapping (one source of truth with bench.py).
LAYOUTS = {
    "split": ("split", "sort"),
    "kv": ("kv", "sort"),
    "phased": ("split", "phased"),
    "capped": ("split", "capped"),
    "capped-kv": ("kv", "capped"),
    "capped-phased": ("split", "capped-phased"),
    "pallas": ("split", "pallas"),
}


def _build_model(model_name: str, n: int):
    if model_name == "paxos":
        from stateright_tpu.tensor.paxos import TensorPaxos

        return TensorPaxos(client_count=n)
    if model_name in ("inclock", "inclock-sym"):
        from stateright_tpu.tensor.models import TensorIncrementLock

        return TensorIncrementLock(n, symmetry=model_name == "inclock-sym")
    from stateright_tpu.tensor.models import TensorTwoPhaseSys

    return TensorTwoPhaseSys(n)


def run_single(model_name, n, batch, table_log2, repeats, layout,
               store="device", high_water=0.85, summary_log2=20) -> int:
    if layout not in LAYOUTS:
        print(f"unknown LAYOUT {layout!r} ({' | '.join(LAYOUTS)})")
        return 2
    table_layout, insert_variant = LAYOUTS[layout]

    from stateright_tpu.tensor.resident import ResidentSearch

    model = _build_model(model_name, n)
    store_desc = (
        f" store=tiered(hw={high_water},sb={summary_log2})"
        if store == "tiered"
        else ""
    )
    print(
        f"devices={jax.devices()} workload={model_name}-{n} "
        f"batch={batch} table=2^{table_log2} layout={layout}{store_desc}",
        flush=True,
    )
    search = ResidentSearch(
        model,
        batch_size=batch,
        table_log2=table_log2,
        table_layout=table_layout,
        insert_variant=insert_variant,
        store=store,
        high_water=high_water,
        summary_log2=summary_log2,
    )
    t0 = time.monotonic()
    r = search.run()
    compile_s = time.monotonic() - t0
    print(f"compile+first: {compile_s:.1f}s", flush=True)
    trace_dir = os.environ.get("TPU_TUNE_TRACE")
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    best = None
    try:
        for i in range(repeats):
            # Tiered runs are chunked and retain a carry across run()
            # calls; without the reset every repeat would be a no-op
            # resume "measuring" near-zero durations (the 2pc-10 bench
            # lesson). Whole-search engines start fresh regardless.
            search.reset()
            r = search.run()
            print(
                f"  run {i}: {r.duration:.4f}s "
                f"({r.state_count / max(r.duration, 1e-9):,.0f} states/s, "
                f"steps={r.steps})",
                flush=True,
            )
            if best is None or r.duration < best.duration:
                best = r
    finally:
        if trace_dir:
            # Flush even when a run dies mid-loop — that is exactly when
            # the trace explains the failure.
            jax.profiler.stop_trace()
            print(f"profiler trace written to {trace_dir}", flush=True)
    gold = GOLDEN.get((model_name, n))
    parity_ok = gold is None or (
        (best.state_count, best.unique_state_count) == gold
    )
    sps = best.state_count / max(best.duration, 1e-9)
    # Machine-readable line the sweep driver parses.
    rec = {
        "workload": f"{model_name}-{n}",
        "batch": batch,
        "table_log2": table_log2,
        "layout": layout,
        "store": store,
        "sec": round(best.duration, 4),
        "states_per_sec": round(sps, 1),
        "steps": best.steps,
        "compile_sec": round(compile_s, 1),
        "parity_ok": parity_ok,
    }
    if store == "tiered":
        rec["high_water"] = high_water
        rec["summary_log2"] = summary_log2
        stats = search.store_stats()
        if stats:
            rec.update(
                {
                    k: stats[k]
                    for k in ("hot_fill", "spilled_states", "spill_events")
                }
            )
    print("RESULT_JSON " + json.dumps(rec), flush=True)
    if not parity_ok:
        print(
            f"PARITY FAIL: {best.state_count}/{best.unique_state_count} "
            f"!= {gold}"
        )
        return 1
    print(
        f"BEST {model_name}-{n} b={batch} t={table_log2}: "
        f"{best.duration:.4f}s {sps:,.0f}/s"
    )
    return 0


def run_sim_single(model_name, n, traces, dedup, walks, max_depth,
                   repeats, table_log2) -> int:
    """One simulation-engine config: repeated rounds on a fresh engine per
    repeat (the rounds loop is cumulative by design), reporting walks/s and
    the walk-plane telemetry digest as the RESULT_JSON line."""
    from stateright_tpu.knobs import SIM_DEDUP_KINDS
    from stateright_tpu.tensor.simulation import DeviceSimulation

    if dedup not in SIM_DEDUP_KINDS:
        print(f"unknown DEDUP {dedup!r} ({' | '.join(SIM_DEDUP_KINDS)})")
        return 2
    model = _build_model(model_name, n)
    print(
        f"devices={jax.devices()} workload={model_name}-{n} sim "
        f"traces={traces} dedup={dedup} walks={walks} depth={max_depth}",
        flush=True,
    )

    def fresh():
        return DeviceSimulation(
            model, seed=7, traces=traces, max_depth=max_depth,
            dedup=dedup, table_log2=table_log2, walks=walks,
        )

    t0 = time.monotonic()
    fresh().run()
    compile_s = time.monotonic() - t0
    print(f"compile+first: {compile_s:.1f}s", flush=True)
    best = None
    for i in range(repeats):
        sim = fresh()  # same seed per repeat: bit-identical rounds
        t0 = time.monotonic()
        r = sim.run()
        sec = time.monotonic() - t0
        tel = r.detail["telemetry"]
        print(
            f"  run {i}: {sec:.4f}s ({tel['walks'] / max(sec, 1e-9):,.0f} "
            f"walks/s, {r.state_count / max(sec, 1e-9):,.0f} states/s, "
            f"lane_util={tel['lane_util']})",
            flush=True,
        )
        if best is None or sec < best[0]:
            best = (sec, r, tel)
    sec, r, tel = best
    rec = {
        "workload": f"{model_name}-{n}",
        "sim": True,
        "traces": traces,
        "dedup": dedup,
        "walks": tel["walks"],
        "max_depth": max_depth,
        "table_log2": table_log2,
        "sec": round(sec, 4),
        "walks_per_sec": round(tel["walks"] / max(sec, 1e-9), 1),
        "states_per_sec": round(r.state_count / max(sec, 1e-9), 1),
        "unique": r.unique_state_count,
        "lane_util": tel["lane_util"],
        "restarts": tel["restarts"],
        "compile_sec": round(compile_s, 1),
        "parity_ok": True,  # simulation has no exhaustive golden to pin
    }
    if dedup == "shared":
        rec["dedup_hit_rate"] = tel["dedup_hit_rate"]
    print("RESULT_JSON " + json.dumps(rec), flush=True)
    print(
        f"BEST {model_name}-{n} sim traces={traces} dedup={dedup}: "
        f"{rec['walks_per_sec']:,.0f} walks/s"
    )
    return 0


def run_sweep(argv: list) -> int:
    def opt(name, default):
        if name in argv:
            i = argv.index(name)
            if i + 1 >= len(argv):
                raise SystemExit(f"missing value for {name} (see --help)")
            v = argv[i + 1]
            del argv[i : i + 2]
            return v
        return default

    sim = "--sim" in argv
    if sim:
        argv.remove("--sim")
    traces_axis = [int(t) for t in opt("--traces", "1024,2048,4096").split(",")]
    dedup_axis = opt("--dedup", "trace,shared").split(",")
    sim_walks = opt("--walks", None)
    sim_depth = int(opt("--max-depth", "256"))
    batches = [int(b) for b in opt("--batches", "2048,4096,8192").split(",")]
    variants = opt("--variants", "split,kv,phased,capped,pallas").split(",")
    stores = opt("--stores", "device").split(",")
    high_waters = [float(x) for x in opt("--high-waters", "0.85").split(",")]
    summary_bits = [int(x) for x in opt("--summary-bits", "20").split(",")]
    repeats = int(opt("--repeats", "3"))
    timeout = float(opt("--timeout", "900"))
    out_path = opt("--out", "tune_ranking.json")
    if len(argv) < 3:  # re-check arity AFTER option pairs are stripped
        print(__doc__)
        return 2
    model_name, n, table_log2 = argv[0], int(argv[1]), int(argv[2])

    if sim:
        return run_sim_sweep(
            model_name, n, table_log2, traces_axis, dedup_axis,
            sim_walks, sim_depth, repeats, timeout, out_path,
        )

    bad = [v for v in variants if v not in LAYOUTS]
    if bad:
        print(f"unknown variants {bad} ({' | '.join(LAYOUTS)})")
        return 2
    bad = [s for s in stores if s not in ("device", "tiered")]
    if bad:
        print(f"unknown stores {bad} (device | tiered)")
        return 2
    # Store axis: the plain device store plus every requested
    # water-mark x summary-bit combination of the tiered store.
    store_cfgs = [("device", None, None)] if "device" in stores else []
    if "tiered" in stores:
        store_cfgs += [
            ("tiered", hw, sb) for hw in high_waters for sb in summary_bits
        ]

    model = _build_model(model_name, n)
    from stateright_tpu.tensor import costmodel as cm

    configs = []

    def flush() -> list:
        """Rewrite the ranking JSON after EVERY config: a wedged tunnel (or
        the driver's outer timeout) killing the sweep mid-way must not
        discard the configs that already measured."""
        measured = [c for c in configs if "states_per_sec" in c]
        ranking = sorted(
            measured, key=lambda c: c["states_per_sec"], reverse=True
        )
        result = {
            "workload": f"{model_name}-{n}",
            "table_log2": table_log2,
            "backend": jax.default_backend(),
            "model": {
                "lanes": model.lanes, "max_actions": model.max_actions,
            },
            "configs": configs,
            "ranking": [
                {
                    "layout": c["layout"],
                    "batch": c["batch"],
                    "store": c.get("store", "device"),
                    **(
                        {
                            "high_water": c["high_water"],
                            "summary_log2": c["summary_log2"],
                        }
                        if c.get("store") == "tiered"
                        else {}
                    ),
                    "states_per_sec": c["states_per_sec"],
                    "predicted_ms": round(c.get("predicted_ms", 0.0), 3),
                    "parity_ok": c["parity_ok"],
                }
                for c in ranking
            ],
        }
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
        return ranking

    for batch in batches:
        for layout in variants:
            for store, hw, sb in store_cfgs:
                if store == "tiered" and LAYOUTS[layout][0] != "split":
                    continue  # tiered eviction is split-bucket-layout only
                tag = (
                    f" store=tiered(hw={hw},sb={sb})"
                    if store == "tiered"
                    else ""
                )
                print(
                    f"== {model_name}-{n} b={batch} layout={layout}{tag}",
                    flush=True,
                )
                rec = {
                    "workload": f"{model_name}-{n}",
                    "batch": batch,
                    "table_log2": table_log2,
                    "layout": layout,
                    "store": store,
                }
                cmd = [
                    sys.executable,
                    os.path.abspath(__file__),
                    model_name,
                    str(n),
                    str(batch),
                    str(table_log2),
                    str(repeats),
                    layout,
                ]
                if store == "tiered":
                    rec["high_water"] = hw
                    rec["summary_log2"] = sb
                    cmd += [store, str(hw), str(sb)]
                try:
                    proc = subprocess.run(
                        cmd,
                        capture_output=True,
                        text=True,
                        timeout=timeout,
                    )
                except subprocess.TimeoutExpired:
                    rec["error"] = f"timed out after {timeout:.0f}s"
                    configs.append(rec)
                    flush()
                    print("   TIMEOUT", flush=True)
                    continue
                sys.stderr.write(proc.stderr)
                line = next(
                    (
                        ln[len("RESULT_JSON "):]
                        for ln in proc.stdout.splitlines()
                        if ln.startswith("RESULT_JSON ")
                    ),
                    None,
                )
                if line is None:
                    tail = proc.stdout.strip().splitlines()
                    rec["error"] = (
                        tail[-1] if tail else f"rc={proc.returncode}"
                    )
                    configs.append(rec)
                    flush()
                    print(f"   FAILED: {rec['error']}", flush=True)
                    continue
                rec.update(json.loads(line))
                rec["predicted_ms"] = cm.step_cost(
                    model.lanes,
                    model.max_actions,
                    batch,
                    table_log2,
                    variant=cm.ENGINE_VARIANTS[LAYOUTS[layout]],
                    # Probe-only spill term: per-step eviction volume is
                    # workload-dependent and unknown pre-run; the measured
                    # spill_events in the RESULT_JSON calibrate it later.
                    spill={"summary_hashes": 4} if store == "tiered" else None,
                ).total_ms
                configs.append(rec)
                flush()
                print(
                    f"   {rec['states_per_sec']:,.0f}/s "
                    f"(predicted {rec['predicted_ms']:.2f} ms/step, "
                    f"parity_ok={rec['parity_ok']})",
                    flush=True,
                )

    ranking = flush()
    measured = [c for c in configs if "states_per_sec" in c]
    print(f"ranking written to {out_path}")
    if ranking:
        best = ranking[0]
        print(
            f"WINNER {best['layout']} b={best['batch']}: "
            f"{best['states_per_sec']:,.0f}/s"
        )
    # Parity failures or wholly-failed sweeps are errors.
    if not measured or not all(c["parity_ok"] for c in measured):
        return 1
    return 0


def run_sim_sweep(model_name, n, table_log2, traces_axis, dedup_axis,
                  sim_walks, sim_depth, repeats, timeout, out_path) -> int:
    """The fourth engine's tunnel-day command: race traces x dedup in
    subprocess-isolated single-config runs, join with the costmodel's
    committed walk-step predictions, rank by walks/s."""
    from stateright_tpu.knobs import SIM_DEDUP_KINDS

    bad = [d for d in dedup_axis if d not in SIM_DEDUP_KINDS]
    if bad:
        print(f"unknown dedup values {bad} ({' | '.join(SIM_DEDUP_KINDS)})")
        return 2
    model = _build_model(model_name, n)
    from stateright_tpu.tensor import costmodel as cm

    configs = []

    def flush() -> list:
        measured = [c for c in configs if "walks_per_sec" in c]
        ranking = sorted(
            measured, key=lambda c: c["walks_per_sec"], reverse=True
        )
        with open(out_path, "w") as f:
            json.dump(
                {
                    "workload": f"{model_name}-{n}",
                    "sim": True,
                    "table_log2": table_log2,
                    "backend": jax.default_backend(),
                    "model": {
                        "lanes": model.lanes,
                        "max_actions": model.max_actions,
                    },
                    "configs": configs,
                    "ranking": [
                        {
                            "traces": c["traces"],
                            "dedup": c["dedup"],
                            "walks_per_sec": c["walks_per_sec"],
                            "states_per_sec": c["states_per_sec"],
                            "lane_util": c["lane_util"],
                            "predicted_ms": round(
                                c.get("predicted_ms", 0.0), 3
                            ),
                        }
                        for c in ranking
                    ],
                },
                f,
                indent=1,
            )
        return ranking

    for traces in traces_axis:
        for dedup in dedup_axis:
            print(
                f"== {model_name}-{n} sim traces={traces} dedup={dedup}",
                flush=True,
            )
            rec = {
                "workload": f"{model_name}-{n}",
                "traces": traces,
                "dedup": dedup,
            }
            walks = sim_walks or str(4 * traces)
            cmd = [
                sys.executable, os.path.abspath(__file__),
                "sim", model_name, str(n), str(traces), dedup,
                str(walks), str(sim_depth), str(repeats), str(table_log2),
            ]
            try:
                proc = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=timeout
                )
            except subprocess.TimeoutExpired:
                rec["error"] = f"timed out after {timeout:.0f}s"
                configs.append(rec)
                flush()
                print("   TIMEOUT", flush=True)
                continue
            sys.stderr.write(proc.stderr)
            line = next(
                (
                    ln[len("RESULT_JSON "):]
                    for ln in proc.stdout.splitlines()
                    if ln.startswith("RESULT_JSON ")
                ),
                None,
            )
            if line is None:
                tail = proc.stdout.strip().splitlines()
                rec["error"] = tail[-1] if tail else f"rc={proc.returncode}"
                configs.append(rec)
                flush()
                print(f"   FAILED: {rec['error']}", flush=True)
                continue
            rec.update(json.loads(line))
            rec["predicted_ms"] = cm.sim_step_cost(
                model.lanes, model.max_actions, traces,
                dedup=dedup, table_log2=table_log2,
            ).total_ms
            configs.append(rec)
            flush()
            print(
                f"   {rec['walks_per_sec']:,.0f} walks/s "
                f"(predicted {rec['predicted_ms']:.2f} ms/step, "
                f"lane_util={rec['lane_util']})",
                flush=True,
            )

    ranking = flush()
    print(f"ranking written to {out_path}")
    if ranking:
        best = ranking[0]
        print(
            f"WINNER sim traces={best['traces']} dedup={best['dedup']}: "
            f"{best['walks_per_sec']:,.0f} walks/s"
        )
    return 0 if ranking else 1


def _reeval_rankings(cm, stock_dev, fitted_dev) -> list:
    """Re-derive the two committed pre-hardware rankings under the fitted
    coefficients, next to the stock derivation. Returns the list of grid
    points whose winner flipped (empty = both committed defaults hold).

    r12 (ROUND12_NOTES): capped-vs-pallas insert on the paxos-3 geometry
    (lanes 21, max_actions 14) — committed call: capped stays the default
    at the r4 anchor (batch 3072, table 2^22); pallas wins small tables
    and huge batches. r18 (ROUND14/18 sim notes): shared-table sim dedup
    is priced as the same insert ops at batch=traces — committed call:
    trace-dedup stays the sim default; shared's insert term is under ~7%
    of the step until traces ~4k.
    """
    flips = []

    def w12(dev, table_log2, batch):
        capped = cm.step_cost(
            21, 14, batch, table_log2, variant="capped", device=dev
        ).total_ms
        pallas = cm.step_cost(
            21, 14, batch, table_log2, variant="pallas", device=dev
        ).total_ms
        return ("capped" if capped <= pallas else "pallas", capped, pallas)

    print("\nr12 capped-vs-pallas insert crossover (paxos-3, lanes 21 x "
          "acts 14) — stock | fitted:")
    grid = [(t, 3072) for t in (16, 18, 20, 22)]
    grid += [(22, 32768), (22, 131072)]
    for table_log2, batch in grid:
        s_win, s_c, s_p = w12(stock_dev, table_log2, batch)
        f_win, f_c, f_p = w12(fitted_dev, table_log2, batch)
        mark = ""
        if s_win != f_win:
            mark = "  <-- FLIP"
            flips.append(f"r12 table=2^{table_log2} batch={batch}: "
                         f"{s_win} -> {f_win}")
        print(f"  table=2^{table_log2:<2} batch={batch:<6} "
              f"stock: {s_win:<6} (capped {s_c:.2f} / pallas {s_p:.2f} ms)"
              f" | fitted: {f_win:<6} (capped {f_c:.2f} / pallas "
              f"{f_p:.2f} ms){mark}")
    s_anchor = w12(stock_dev, 22, 3072)[0]
    f_anchor = w12(fitted_dev, 22, 3072)[0]
    if s_anchor == f_anchor:
        print(f"  committed default at the r4 anchor holds: {f_anchor}")
    else:
        print(f"  COMMITTED DEFAULT FLIPS at the r4 anchor: "
              f"{s_anchor} -> {f_anchor}")

    def sim_row(dev, traces):
        tr = cm.sim_step_cost(21, 14, traces, dedup="trace", device=dev)
        sh = cm.sim_step_cost(
            21, 14, traces, dedup="shared", table_log2=22, device=dev
        )
        ins = sum(o.ms for o in sh.ops if o.name.startswith("insert"))
        return tr.total_ms, sh.total_ms, ins / max(sh.total_ms, 1e-12)

    print("\nr18 sim-walk shared-table overhead (paxos-3, table 2^22) — "
          "stock | fitted:")
    for traces in (1024, 2048, 4096, 8192):
        s_tr, s_sh, s_frac = sim_row(stock_dev, traces)
        f_tr, f_sh, f_frac = sim_row(fitted_dev, traces)
        s_win = "trace" if s_tr <= s_sh else "shared"
        f_win = "trace" if f_tr <= f_sh else "shared"
        mark = ""
        if s_win != f_win:
            mark = "  <-- FLIP"
            flips.append(f"r18 traces={traces}: {s_win} -> {f_win}")
        print(f"  traces={traces:<5} stock: trace {s_tr:.2f} / shared "
              f"{s_sh:.2f} ms, insert {100 * s_frac:.1f}% | fitted: "
              f"trace {f_tr:.2f} / shared {f_sh:.2f} ms, insert "
              f"{100 * f_frac:.1f}%{mark}")
    crossed = [t for t in (1024, 2048, 4096, 8192)
               if sim_row(fitted_dev, t)[2] > 0.07]
    if crossed:
        print(f"  fitted shared-insert term exceeds 7% of the step from "
              f"traces={crossed[0]} (committed call said ~4k)")
    else:
        print("  fitted shared-insert term stays under 7% across the grid")

    if flips:
        print("\nRANKING FLIPS under fitted coefficients:")
        for f in flips:
            print(f"  {f}")
    else:
        print("\nno committed ranking flips under fitted coefficients")
    return flips


def run_calibrate(argv: list) -> int:
    from stateright_tpu.obs.calib import (
        THETA_FIELDS,
        device_from_theta,
        fit_theta,
        holdout_eval,
        load_observations,
        overlay_dict,
    )
    from stateright_tpu.tensor import costmodel as cm

    def opt(name, default):
        if name in argv:
            return argv[argv.index(name) + 1]
        return default

    root = argv[0]
    only = opt("--device", None)
    ridge = float(opt("--ridge", 1e-2))
    out_arg = opt("--out", None)

    records = load_observations(root)
    if not records:
        print(f"no calibration records under {root} "
              f"(comparators flush to <root>/calib/)")
        return 1
    by_dev: dict = {}
    for rec in records:
        by_dev.setdefault(rec.get("device") or "tpu-v5e", []).append(rec)
    kinds = [only] if only else sorted(by_dev)
    rc = 0
    for kind in kinds:
        recs = by_dev.get(kind)
        if not recs:
            print(f"no records for device kind {kind!r} "
                  f"(have: {sorted(by_dev)})")
            rc = 1
            continue
        base = cm.stock_device(kind)
        theta, report = fit_theta(recs, base, ridge=ridge)
        n_rows = report["rows"]
        print(f"== {kind}: {len(recs)} record(s), {n_rows} observation "
              f"row(s) ==")
        print(f"  median |drift-1|: stock "
              f"{report['median_abs_drift_stock']:.4f} -> fitted "
              f"{report['median_abs_drift_fitted']:.4f}")
        fitted_dev = device_from_theta(base, theta)
        print("  coefficient rates (stock -> fitted):")
        for name, field, _kind in THETA_FIELDS:
            print(f"    {field:<16} {getattr(base, field):>12.4g} -> "
                  f"{getattr(fitted_dev, field):>12.4g}")
        holdout = holdout_eval(recs, base, ridge=ridge)
        if holdout:
            print("  leave-one-key-out holdout (median |drift-1|):")
            for key, h in sorted(holdout.items()):
                verdict = "better" if h["fitted"] < h["stock"] else "WORSE"
                print(f"    {key}: stock {h['stock']:.4f} -> fitted "
                      f"{h['fitted']:.4f} ({verdict})")

        overlay = overlay_dict(base, theta, report)
        out_path = out_arg or f"calib-overlay-{kind}.json"
        try:
            with open(out_path, "w") as f:
                json.dump(overlay, f, indent=2)
            print(f"  overlay written to {out_path}; activate with "
                  f"SR_TPU_COSTMODEL_CALIB={out_path}")
        except OSError as e:
            print(f"  overlay write failed: {e}")
            rc = 1

        _reeval_rankings(cm, base, fitted_dev)
    return rc


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "sim":
        if len(argv) < 5:
            print(__doc__)
            return 2
        return run_sim_single(
            argv[1], int(argv[2]), int(argv[3]), argv[4],
            int(argv[5]) if len(argv) > 5 else None,
            int(argv[6]) if len(argv) > 6 else 256,
            max(1, int(argv[7])) if len(argv) > 7 else 3,
            int(argv[8]) if len(argv) > 8 else 20,
        )
    if argv and argv[0] == "--calibrate":
        if len(argv) < 2:
            print(__doc__)
            return 2
        return run_calibrate(argv[1:])
    if argv and argv[0] == "--sweep":
        if len(argv) < 4:
            print(__doc__)
            return 2
        return run_sweep(argv[1:])
    if len(argv) < 4:
        print(__doc__)
        return 2
    model_name, n, batch, table_log2 = (
        argv[0], int(argv[1]), int(argv[2]), int(argv[3])
    )
    repeats = max(1, int(argv[4])) if len(argv) > 4 else 3
    layout = argv[5] if len(argv) > 5 else "split"
    store = argv[6] if len(argv) > 6 else "device"
    high_water = float(argv[7]) if len(argv) > 7 else 0.85
    summary_log2 = int(argv[8]) if len(argv) > 8 else 20
    return run_single(
        model_name, n, batch, table_log2, repeats, layout,
        store=store, high_water=high_water, summary_log2=summary_log2,
    )


if __name__ == "__main__":
    sys.exit(main())
