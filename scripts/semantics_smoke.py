#!/usr/bin/env python
"""Dedup-first semantics smoke: register anchors cold -> optimized ->
corpus-warm, end to end.

CI-shaped: exercises the whole dedup-first verdict plane (ISSUE 13,
stateright_tpu/semantics/{canonical,batch}.py) in one command —

1. COLD: the abd and single-copy register anchors' post-dedup testers
   evaluated through the pre-PR cache-only path (plane disabled).
2. OPTIMIZED: the same testers through the batched plane (canonical
   collapse + witness guidance + native-parallel search) — verdicts must
   be bit-identical and `witness_guided_hits` must be nonzero.
3. CORPUS-WARM: the packed verdict table round-trips through a real
   corpus entry via the check service (publish on a register-model
   submission, verdict preload on the repeat), replaying the cold run's
   result bit-identically with `verdict_preloads > 0`.

Exit code 0 iff every phase agreed.

    JAX_PLATFORMS=cpu python scripts/semantics_smoke.py
"""

import glob
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def collect_testers(model, cap):
    """The anchor's post-dedup batch (shared enumerator — the bench
    BENCH_SEMANTICS worker measures the same batch shape)."""
    from stateright_tpu.semantics.batch import collect_history_testers

    return collect_history_testers(model, cap)[0]


def main() -> int:
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)

    from stateright_tpu.actor import Network
    from stateright_tpu.actor.register import GetOk
    from stateright_tpu.examples.abd import AbdModelCfg
    from stateright_tpu.examples.single_copy_register import (
        NULL_VALUE,
        SingleCopyModelCfg,
    )
    from stateright_tpu.semantics import canonical, clear_serialization_caches
    from stateright_tpu.semantics.batch import evaluate_batch
    from stateright_tpu.semantics.canonical import CACHE
    from stateright_tpu.service import CheckService
    from stateright_tpu.tensor.lowering import lower_actor_model
    from stateright_tpu.tensor.model import TensorProperty

    failures = []
    net = Network.new_unordered_nonduplicating

    # -- phases 1+2: cold vs optimized on the register anchors -----------------
    anchors = {
        "abd-2c2s": AbdModelCfg(
            client_count=2, server_count=2, network=net()
        ).into_model(),
        "single_copy-5c2s": SingleCopyModelCfg(
            client_count=5, server_count=2, network=net()
        ).into_model(),
    }
    for name, model in anchors.items():
        testers = collect_testers(model, 3000)
        clear_serialization_caches()
        prev = canonical.set_enabled(False)
        t0 = time.monotonic()
        cold = [t.serialized_history() is not None for t in testers]
        cold_sec = time.monotonic() - t0
        canonical.set_enabled(prev)

        clear_serialization_caches()
        guided0 = CACHE.counters["witness_guided_hits"]
        t0 = time.monotonic()
        optimized = evaluate_batch(testers)
        opt_sec = time.monotonic() - t0
        guided = CACHE.counters["witness_guided_hits"] - guided0
        ok = optimized == cold
        print(
            f"[{name}] n={len(testers)} cold={cold_sec:.3f}s "
            f"optimized={opt_sec:.3f}s "
            f"speedup={cold_sec / max(opt_sec, 1e-9):.2f}x "
            f"guided={guided} identical={ok}"
        )
        if not ok:
            failures.append(f"{name}: optimized verdicts != cold verdicts")
        if guided == 0:
            failures.append(f"{name}: witness_guided_hits == 0")

    # -- phase 3: corpus-warm through the check service ------------------------
    def lowered_register():
        cfg = SingleCopyModelCfg(client_count=2, server_count=1)

        def properties(view):
            lin = view.history_pred(lambda h: h.is_consistent())
            chosen = view.any_env(
                lambda env: isinstance(env.msg, GetOk)
                and env.msg.value != NULL_VALUE
            )
            return [
                TensorProperty.always("linearizable", lambda m, s: lin(s)),
                TensorProperty.sometimes(
                    "value chosen", lambda m, s: chosen(s)
                ),
            ]

        return lower_actor_model(cfg.into_model(), properties=properties)

    with tempfile.TemporaryDirectory(prefix="srtpu-semantics-") as corpus_dir:
        clear_serialization_caches()
        svc = CheckService(
            batch_size=128, table_log2=14, store="tiered",
            summary_log2=16, background=False, corpus_dir=corpus_dir,
        )
        try:
            h = svc.submit(lowered_register())
            svc.drain(timeout=600)
            cold_r = h.result()
            entries = glob.glob(os.path.join(corpus_dir, "corpus-*.npz"))
            if not cold_r.detail["corpus"]["published"] or not entries:
                failures.append("corpus: cold run did not publish an entry")

            # "Fresh process": empty verdict caches, fresh lowering.
            clear_serialization_caches()
            guided0 = CACHE.counters["witness_guided_hits"]
            model2 = lowered_register()
            guided = CACHE.counters["witness_guided_hits"] - guided0
            clear_serialization_caches()
            h = svc.submit(model2)
            svc.drain(timeout=600)
            warm_r = h.result()
            cd = warm_r.detail["corpus"]
            print(
                f"[service] warm_start={cd['warm_start']} "
                f"verdict_preloads={cd['verdict_preloads']} "
                f"lowering_guided={guided}"
            )
            if guided + cd["verdict_preloads"] <= 0:
                failures.append(
                    "corpus: witness_guided_hits + verdict_preloads == 0"
                )
            same = (
                warm_r.state_count, warm_r.unique_state_count,
                warm_r.max_depth, sorted(warm_r.discoveries.items()),
            ) == (
                cold_r.state_count, cold_r.unique_state_count,
                cold_r.max_depth, sorted(cold_r.discoveries.items()),
            )
            if not same:
                failures.append("corpus: warm result != cold result")
        finally:
            svc.close()

    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print("semantics smoke: all phases identical, plane live")
    return 0


if __name__ == "__main__":
    sys.exit(main())
