"""Device simulation checker: vmapped random root-to-terminal walks — the
TPU analogue of the host `SimulationChecker` (ref:
src/checker/simulation.rs:102-209), closing the promise in
stateright_tpu/checker/simulation.py.

Where the reference runs one walk per OS thread, here a whole BATCH of traces
advances in lockstep inside one `lax.while_loop` dispatch: per step every
active trace evaluates the property masks on its current state, detects
cycles against its own per-trace visited table, chooses uniformly among the
valid successors with a counter-based `jax.random` stream (explicit keys —
reproducible by construction, unlike the reference's FIXMEd StdRng,
ref: src/checker/simulation.rs:47,154), and steps. Finished traces go
inactive; the dispatch returns when all traces end or a finish policy hits.

Walk-semantics parity with the host checker (same order of checks per
iteration, ref: src/checker/simulation.rs:254-397):
 depth cap -> return WITHOUT the eventually check; boundary exit, cycle
 exit, and genuine terminals DO record pending eventually-bits as
 counterexamples; properties are evaluated before expansion; there is no
 global dedup (`unique_state_count == state_count`).

Discoveries record the discovering trace's fingerprint path (the per-trace
ring); the host reconstructs a `Path` by re-executing the model along those
fingerprints, exactly like the exhaustive engines.
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from ..core.path import Path
from .fingerprint import pack_fp
from .frontier import SearchResult, state_fingerprint
from .model import TensorModel


class _Carry(NamedTuple):
    keys: jnp.ndarray  # PRNG keys [T]
    states: jnp.ndarray  # uint32[T, L] current state per trace
    done: jnp.ndarray  # bool[T]
    at_depth_cap: jnp.ndarray  # bool[T] — ended by cap (skip ebits)
    ebits: jnp.ndarray  # uint32[T]
    v_lo: jnp.ndarray  # uint32[T, C] per-trace cycle table
    v_hi: jnp.ndarray  # uint32[T, C]
    path_lo: jnp.ndarray  # uint32[T, D] per-trace fingerprint path
    path_hi: jnp.ndarray  # uint32[T, D]
    path_len: jnp.ndarray  # int32[T]
    state_count: jnp.ndarray  # int32 (total across traces)
    max_depth: jnp.ndarray  # int32
    discovered: jnp.ndarray  # uint32 bitmask
    disc_trace: jnp.ndarray  # int32[P] trace index of first witness
    disc_len: jnp.ndarray  # int32[P] fingerprint-path length at witness
    step: jnp.ndarray  # int32


class DeviceSimulation:
    """One dispatch = `traces` independent random walks of length <=
    `max_depth`. Call `run()` repeatedly (the seed advances) for more
    coverage, like the host checker's per-thread trace loop."""

    def __init__(
        self,
        model: TensorModel,
        seed: int = 0,
        traces: int = 256,
        max_depth: int = 256,
        table_log2: int = 9,
    ):
        self.model = model
        self.seed = seed
        self.traces = traces
        self.max_depth = max_depth
        self.table_log2 = table_log2
        if (1 << table_log2) < 2 * max_depth:
            raise ValueError(
                "per-trace cycle table must hold 2x max_depth entries; "
                "raise table_log2"
            )
        self.props = model.properties()
        self._kernel = self._build()
        self._rounds = 0
        self._totals = dict(states=0, max_depth=0, steps=0)
        self._discoveries: dict = {}  # name -> list of packed fps (the path)

    def _build(self):
        model = self.model
        T = self.traces
        D = self.max_depth
        C = 1 << self.table_log2
        props = self.props
        P = len(props)
        always_i = [i for i, p in enumerate(props) if p.expectation == Expectation.ALWAYS]
        sometimes_i = [i for i, p in enumerate(props) if p.expectation == Expectation.SOMETIMES]
        eventually_i = [i for i, p in enumerate(props) if p.expectation == Expectation.EVENTUALLY]
        ebits0 = np.uint32(sum(1 << i for i in eventually_i))
        all_bits = jnp.uint32((1 << P) - 1)

        def record(c_discovered, c_trace, c_len, i, hit, path_len):
            bit = jnp.uint32(1 << i)
            already = (c_discovered & bit) != 0
            any_hit = jnp.any(hit)
            first = jnp.argmax(hit).astype(jnp.int32)
            rec = (~already) & any_hit
            c_trace = c_trace.at[i].set(
                jnp.where(rec, first, c_trace[i])
            )
            c_len = c_len.at[i].set(
                jnp.where(rec, path_len[first], c_len[i])
            )
            return jnp.where(rec, c_discovered | bit, c_discovered), c_trace, c_len

        def probe_insert(v_lo, v_hi, lo, hi, active):
            """Per-trace linear probe of (lo, hi) in each trace's own table.
            Returns (v_lo, v_hi, seen)."""
            idx0 = (hi % jnp.uint32(C)).astype(jnp.int32)

            def cond(s):
                _vl, _vh, _idx, resolved, _seen, n = s
                return (~jnp.all(resolved)) & (n < C)

            def body(s):
                v_lo, v_hi, idx, resolved, seen, n = s
                cur_lo = jnp.take_along_axis(v_lo, idx[:, None], axis=1)[:, 0]
                cur_hi = jnp.take_along_axis(v_hi, idx[:, None], axis=1)[:, 0]
                hit = (cur_lo == lo) & (cur_hi == hi)
                free = cur_lo == 0
                claim = (~resolved) & free
                # One fp per trace per call: no intra-trace races possible.
                tgt = jnp.where(claim, idx, C)[:, None]
                v_lo = jnp.put_along_axis(
                    v_lo, tgt, jnp.where(claim, lo, 0)[:, None], axis=1,
                    inplace=False, mode="drop",
                )
                v_hi = jnp.put_along_axis(
                    v_hi, tgt, jnp.where(claim, hi, 0)[:, None], axis=1,
                    inplace=False, mode="drop",
                )
                seen = seen | ((~resolved) & hit)
                resolved = resolved | hit | claim
                idx = jnp.where(resolved, idx, (idx + 1) % C)
                return v_lo, v_hi, idx, resolved, seen, n + 1

            resolved0 = ~active
            seen0 = jnp.zeros_like(active)
            v_lo, v_hi, _i, _r, seen, _n = jax.lax.while_loop(
                cond, body,
                (v_lo, v_hi, idx0, resolved0, seen0, jnp.int32(0)),
            )
            return v_lo, v_hi, seen

        def body(c: _Carry) -> _Carry:
            active = ~c.done
            # Host parity order (simulation.rs:254-397): depth cap first.
            capped = active & (c.path_len >= D)
            # Boundary.
            in_bounds = model.within_boundary(c.states)
            out_b = active & ~capped & ~in_bounds
            # Fingerprint + per-trace cycle check.
            lo, hi = state_fingerprint(model, c.states)
            live = active & ~capped & in_bounds
            v_lo, v_hi, seen = probe_insert(c.v_lo, c.v_hi, lo, hi, live)
            looped = live & seen
            walking = live & ~seen

            # Record the fp into the trace path (also for loop/boundary
            # breaks, matching the host's fingerprint_path.append order:
            # the fp is appended BEFORE the loop check).
            rec_fp = active & ~capped & in_bounds
            ppos = jnp.where(
                rec_fp, c.path_len, D
            )  # boundary-exited traces do NOT append (host breaks first)
            path_lo = jnp.put_along_axis(
                c.path_lo, ppos[:, None], lo[:, None], axis=1,
                inplace=False, mode="drop",
            )
            path_hi = jnp.put_along_axis(
                c.path_hi, ppos[:, None], hi[:, None], axis=1,
                inplace=False, mode="drop",
            )
            path_len = c.path_len + rec_fp.astype(jnp.int32)

            state_count = c.state_count + walking.sum(dtype=jnp.int32)
            max_depth = jnp.maximum(c.max_depth, jnp.max(path_len))

            # Properties on the current state (walking traces only).
            discovered = c.discovered
            disc_trace, disc_len = c.disc_trace, c.disc_len
            ebits = c.ebits
            if P:
                masks = jnp.stack([p.condition(model, c.states) for p in props])
                for i in always_i:
                    discovered, disc_trace, disc_len = record(
                        discovered, disc_trace, disc_len, i,
                        walking & ~masks[i], path_len,
                    )
                for i in sometimes_i:
                    discovered, disc_trace, disc_len = record(
                        discovered, disc_trace, disc_len, i,
                        walking & masks[i], path_len,
                    )
                for i in eventually_i:
                    ebits = jnp.where(
                        walking & masks[i],
                        ebits & jnp.uint32(~(1 << i) & 0xFFFFFFFF),
                        ebits,
                    )

            # Expand and choose uniformly among valid successors.
            succs, valid = model.expand(c.states)
            vcount = valid.sum(axis=1).astype(jnp.int32)
            sub = jax.vmap(jax.random.fold_in)(c.keys, jnp.arange(T))
            sub = jax.vmap(jax.random.fold_in)(
                sub, jnp.broadcast_to(c.step, (T,))
            )
            r = jax.vmap(
                lambda k, n: jax.random.randint(k, (), 0, jnp.maximum(n, 1))
            )(sub, vcount)
            pick = jnp.argmax(
                jnp.cumsum(valid.astype(jnp.int32), axis=1) == (r + 1)[:, None],
                axis=1,
            )
            next_states = jnp.take_along_axis(
                succs, pick[:, None, None], axis=1
            )[:, 0]
            terminal = walking & (vcount == 0)
            stepping = walking & (vcount > 0)
            states = jnp.where(stepping[:, None], next_states, c.states)

            # Trace endings. Terminal/loop/boundary record pending
            # eventually-bits; the depth cap does not (host `return` parity).
            ended_ebits = looped | out_b | terminal
            if eventually_i:
                for i in eventually_i:
                    bad = ended_ebits & (
                        (ebits >> jnp.uint32(i)) & 1
                    ).astype(bool)
                    discovered, disc_trace, disc_len = record(
                        discovered, disc_trace, disc_len, i, bad, path_len
                    )
            done = c.done | capped | ended_ebits

            return _Carry(
                keys=c.keys,
                states=states,
                done=done,
                at_depth_cap=c.at_depth_cap | capped,
                ebits=ebits,
                v_lo=v_lo,
                v_hi=v_hi,
                path_lo=path_lo,
                path_hi=path_hi,
                path_len=path_len,
                state_count=state_count,
                max_depth=max_depth,
                discovered=discovered,
                disc_trace=disc_trace,
                disc_len=disc_len,
                step=c.step + 1,
            )

        @partial(jax.jit, static_argnums=(2, 3))
        def simulate(seed, init_states, required_mask: int, any_mask: int):
            n0 = init_states.shape[0]
            base = jax.random.key(seed)
            keys = jax.random.split(base, T)
            pick0 = jax.vmap(
                lambda k: jax.random.randint(k, (), 0, n0)
            )(jax.vmap(lambda k: jax.random.fold_in(k, 0x5EED))(keys))
            states0 = init_states[pick0]

            req = jnp.uint32(required_mask)
            anym = jnp.uint32(any_mask)

            def cond(c: _Carry):
                all_done = jnp.all(c.done)
                all_found = (P > 0) & (c.discovered == all_bits)
                policy = ((req != 0) & ((c.discovered & req) == req)) | (
                    (c.discovered & anym) != 0
                )
                return (~all_done) & (~all_found) & (~policy) & (
                    c.step < D + 2
                )

            carry = _Carry(
                keys=keys,
                states=states0,
                done=jnp.zeros(T, bool),
                at_depth_cap=jnp.zeros(T, bool),
                ebits=jnp.full(T, jnp.uint32(ebits0)),
                v_lo=jnp.zeros((T, 1 << self.table_log2), jnp.uint32),
                v_hi=jnp.zeros((T, 1 << self.table_log2), jnp.uint32),
                path_lo=jnp.zeros((T, D), jnp.uint32),
                path_hi=jnp.zeros((T, D), jnp.uint32),
                path_len=jnp.zeros(T, jnp.int32),
                state_count=jnp.int32(0),
                max_depth=jnp.int32(0),
                discovered=jnp.uint32(0),
                disc_trace=jnp.zeros(max(P, 1), jnp.int32),
                disc_len=jnp.zeros(max(P, 1), jnp.int32),
                step=jnp.int32(0),
            )
            carry = jax.lax.while_loop(cond, body, carry)
            summary = jnp.concatenate(
                [
                    jnp.stack(
                        [
                            carry.state_count,
                            carry.max_depth,
                            carry.discovered.astype(jnp.int32),
                            carry.step,
                        ]
                    ),
                    carry.disc_trace,
                    carry.disc_len,
                ]
            )
            return carry.path_lo, carry.path_hi, summary

        return simulate

    # -- host entry ------------------------------------------------------------

    def run(
        self, finish_when: HasDiscoveries = HasDiscoveries.ALL
    ) -> SearchResult:
        from .resident import _finish_masks

        start = time.monotonic()
        model = self.model
        init = np.asarray(model.init_states(), dtype=np.uint32)
        in_bounds = np.asarray(model.within_boundary(jnp.asarray(init)))
        init = init[in_bounds]
        required_mask, any_mask = _finish_masks(finish_when, self.props)
        path_lo, path_hi, summary = self._kernel(
            self.seed + self._rounds,
            jnp.asarray(init),
            required_mask,
            any_mask,
        )
        self._rounds += 1
        summary = np.asarray(summary)
        state_count, max_depth, discovered, steps = (
            int(x) for x in summary[:4]
        )
        P = max(len(self.props), 1)
        disc_trace = summary[4 : 4 + P]
        disc_len = summary[4 + P :]
        path_lo = np.asarray(path_lo)
        path_hi = np.asarray(path_hi)
        for i, p in enumerate(self.props):
            if discovered & (1 << i) and p.name not in self._discoveries:
                t = int(disc_trace[i])
                ln = int(disc_len[i])
                fps = pack_fp(path_lo[t, :ln], path_hi[t, :ln])
                self._discoveries[p.name] = [int(f) for f in fps]

        self._totals["states"] += state_count
        self._totals["max_depth"] = max(self._totals["max_depth"], max_depth)
        self._totals["steps"] += steps
        return SearchResult(
            state_count=self._totals["states"],
            unique_state_count=self._totals["states"],  # no global dedup
            max_depth=self._totals["max_depth"],
            discoveries={
                name: fps[-1] for name, fps in self._discoveries.items()
            },
            complete=False,  # simulation never proves exhaustion
            duration=time.monotonic() - start,
            steps=self._totals["steps"],
        )

    def discovery_path(self, name: str) -> Path:
        """Re-execute the model along the recorded fingerprint path of the
        discovering trace (the host checkers' Path.from_fingerprints
        technique, ref: src/checker/path.rs:20-97)."""
        from .frontier import replay_fp_chain

        return replay_fp_chain(self.model, self._discoveries[name])
