"""Device simulation checker: the FOURTH first-class engine — vmapped random
root-to-terminal walks (ref: src/checker/simulation.rs:102-209), promoted from
the original lockstep-batch island to the full cross-cutting treatment the
exhaustive engines got.

Where the reference runs one walk per OS thread, here thousands of traces
advance together inside one `lax.while_loop` dispatch: per step every lane
evaluates the property masks on its current state, detects cycles, chooses
uniformly among the valid successors with a counter-based `jax.random` stream
(explicit keys — reproducible by construction, unlike the reference's FIXMEd
StdRng, ref: src/checker/simulation.rs:47,154), and steps.

Two designs beyond the original lockstep batch:

- **Continuous walk batching** (`continuous=True`, the default): when a trace
  ends (terminal / cycle / boundary / depth cap / staleness), its lane
  immediately re-seeds from a fresh fold-in key and starts a new walk within
  the SAME dispatch, bounded by the `walks` budget — lane utilization stays
  ~1 instead of collapsing to the tail walk (the r8 service's
  continuous-batching insight applied inside the walk kernel), so walks/s
  scales with the trace count. `continuous=False` reproduces the original
  one-walk-per-lane dispatch (the lane_util A/B in ROUND14_NOTES.md).
- **Shared visited table** (`dedup="shared"`; knobs.SIM_DEDUP_KINDS): the
  per-trace [T, 2^C] cycle tables are replaced by a small per-walk depth RING
  (cycles with period <= `ring` are detected; longer walks fall to the depth
  cap) plus ONE global visited table shared by every walk — the same
  tensor/inserts.py dispatch table the exhaustive engines use (capped/pallas
  variants, optionally job-salted keys via `salt=`), persisted across
  rounds — so `unique_state_count` becomes real coverage instead of aliasing
  `state_count`, and the `stale_limit` knob restarts walks stuck in
  fully-explored territory (`stale_limit` consecutive already-visited
  states ends the walk WITHOUT the eventually check, like the depth cap).
  The default `dedup="trace"` keeps exact per-walk cycle tables
  (generation-stamped so a lane restart is O(1), not a table clear) and the
  host checker's no-global-dedup accounting.

Walk-semantics parity with the host checker (same order of checks per
iteration, ref: src/checker/simulation.rs:254-397):
 depth cap -> walk ends WITHOUT the eventually check; boundary exit, cycle
 exit, and genuine terminals DO record pending eventually-bits as
 counterexamples; properties are evaluated before expansion.

Discoveries snapshot the discovering walk's fingerprint path at record time
(lane re-seeding overwrites the live path arrays, so the witness is copied
out the moment it is found); the host reconstructs a `Path` by re-executing
the model along those fingerprints, exactly like the exhaustive engines.

First-class wiring: `CheckerBuilder.spawn_simulation(device=True, ...)` /
`spawn_tpu(mode="simulation")` (checker/simulation.py DeviceSimulationChecker),
`engine.step` chaos point per round, checkpoint/resume of the rounds loop
through the ckptio plane, telemetry digest under
`SearchResult.detail["telemetry"]` (keys pinned in obs/schema.py), a
costmodel walk-step term (tensor/costmodel.py sim_step_cost), tpu_tune
traces x dedup sweep axes, and the BENCH_SIM=1 host-vs-device A/B row.
"""

from __future__ import annotations

import json
import math
import time
from functools import partial
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from ..core.path import Path
from ..faults.ckptio import fenced_savez, load_latest
from ..faults.plan import maybe_fault
from ..knobs import SIM_DEDUP_KINDS, WARM_KINDS
from ..obs import REGISTRY, build_detail
from .costmodel import ENGINE_VARIANTS
from ..store import warm as warm_seam
from .fingerprint import job_salt, pack_fp, salt_fp
from .frontier import SearchResult, state_fingerprint
from .inserts import make_table, resolve_insert
from .model import TensorModel


class _Carry(NamedTuple):
    states: jnp.ndarray  # uint32[T, L] current state per lane
    done: jnp.ndarray  # bool[T] (continuous=False only; else all-False)
    ebits: jnp.ndarray  # uint32[T] pending eventually bits of the walk
    gen: jnp.ndarray  # uint32[T] walk generation (stamps cycle structures)
    restart_n: jnp.ndarray  # int32[T] walks started on this lane - 1
    # dedup="trace": exact per-walk cycle table, generation-stamped.
    v_lo: jnp.ndarray  # uint32[T, C] (dummy [1, 1] in shared mode)
    v_hi: jnp.ndarray
    v_gen: jnp.ndarray
    # dedup="shared": per-walk depth ring + the global visited table.
    ring_lo: jnp.ndarray  # uint32[T, R] (dummy [1, 1] in trace mode)
    ring_hi: jnp.ndarray
    ring_gen: jnp.ndarray
    t_lo: jnp.ndarray  # uint32[S] global table (dummy [1] in trace mode)
    t_hi: jnp.ndarray
    p_lo: jnp.ndarray
    p_hi: jnp.ndarray
    prev_lo: jnp.ndarray  # uint32[T] parent fp of the current state
    prev_hi: jnp.ndarray
    stale: jnp.ndarray  # int32[T] consecutive already-visited states
    # live walk paths + the record-time discovery snapshots.
    path_lo: jnp.ndarray  # uint32[T, D]
    path_hi: jnp.ndarray
    path_len: jnp.ndarray  # int32[T]
    disc_lo: jnp.ndarray  # uint32[Pm, D] witness path snapshot per property
    disc_hi: jnp.ndarray
    disc_len: jnp.ndarray  # int32[Pm]
    # counters
    state_count: jnp.ndarray  # int32
    unique_count: jnp.ndarray  # int32 (shared mode: fresh global claims)
    walks: jnp.ndarray  # int32 completed walks
    restarts: jnp.ndarray  # int32 lane re-seeds (walks beyond the first T)
    stale_restarts: jnp.ndarray  # int32 walks ended by the staleness knob
    dedup_hits: jnp.ndarray  # int32 walk states already in the global table
    active_sum: jnp.ndarray  # int32 sum of live lanes per step (lane_util)
    overflow_steps: jnp.ndarray  # int32 steps whose global insert overflowed
    max_depth: jnp.ndarray  # int32
    discovered: jnp.ndarray  # uint32 bitmask
    step: jnp.ndarray  # int32


class DeviceSimulation:
    """Continuous-batched random walks on device; `run()` executes one ROUND
    (up to `walks` completed walks in one dispatch) and may be called
    repeatedly — the seed advances per round, totals and the shared visited
    table persist across rounds, and `checkpoint`/`load_checkpoint` persist
    the rounds loop itself."""

    #: THE dedup-design universe — aliased from the one knob registry
    #: (stateright_tpu/knobs.py); knobs.check_registry() pins the alias.
    DEDUP_KINDS = SIM_DEDUP_KINDS
    # Warm-knob registry pins (knobs.check_registry): the kind vocabulary
    # and the mechanics both alias the ONE seam, never a local copy.
    WARM_KINDS = WARM_KINDS
    WARM_SEAM = warm_seam

    def __init__(
        self,
        model: TensorModel,
        seed: int = 0,
        traces: int = 2048,
        max_depth: int = 256,
        dedup: str = "trace",
        cycle_log2: int = 9,
        ring: int = 64,
        table_log2: int = 20,
        insert_variant: str = "capped",
        walks: Optional[int] = None,
        stale_limit: int = 0,
        salt: int = 0,
        continuous: bool = True,
        telemetry: bool = True,
    ):
        """`traces` lanes walk concurrently; one `run()` completes at least
        `walks` walks (default: `traces`). `dedup`/"shared" knobs are
        documented in the module docstring; `cycle_log2` sizes the exact
        per-walk cycle table (trace mode), `ring` the per-walk cycle ring
        and `table_log2`/`insert_variant`/`salt` the shared global table
        (shared mode). `stale_limit` > 0 restarts a walk after that many
        consecutive already-visited states (shared mode only)."""
        self.model = model
        self.seed = seed
        self.traces = traces
        self.max_depth = max_depth
        if dedup not in SIM_DEDUP_KINDS:  # knob universe: knobs.py
            raise ValueError(
                f"dedup must be one of {SIM_DEDUP_KINDS}, got {dedup!r}"
            )
        self.dedup = dedup
        self.cycle_log2 = cycle_log2
        self.ring = ring
        self.table_log2 = table_log2
        self.insert_variant = insert_variant
        self.walks = walks
        self.stale_limit = stale_limit
        self.salt = salt
        self.continuous = continuous
        self.telemetry = telemetry
        if dedup == "trace" and (1 << cycle_log2) < 2 * max_depth:
            raise ValueError(
                "per-walk cycle table must hold 2x max_depth entries; "
                "raise cycle_log2"
            )
        if stale_limit and dedup != "shared":
            raise ValueError(
                "stale_limit needs the shared visited table (dedup='shared')"
            )
        self.table = (
            make_table(insert_variant, table_log2)
            if dedup == "shared"
            else None
        )
        self.props = model.properties()
        self._kernel = self._build()
        self._rounds = 0
        self._totals = dict(
            states=0, unique=0, max_depth=0, steps=0, walks=0, restarts=0,
            stale_restarts=0, dedup_hits=0, active_sum=0, overflow_steps=0,
            duration=0.0,
        )
        self._discoveries: dict = {}  # name -> list of packed fps (the path)
        self._warm_states = 0
        self._warm_kind: Optional[str] = None
        self._metrics_name = REGISTRY.register("simulation", self.metrics)
        # Calibration comparator (obs/calib.py): one observation per run()
        # round (the engine's only sync boundary) against sim_step_cost for
        # this exact walk config — observes, never steers.
        self._calib = None
        if telemetry:
            # Lazy import: obs.calib prices through tensor.costmodel, so a
            # module-level import would cycle when obs loads first.
            from ..obs.calib import CalibConfig, Comparator, calib_enabled

        if telemetry and calib_enabled():
            self._calib = Comparator(CalibConfig(
                engine="simulation",
                variant=ENGINE_VARIANTS.get(
                    ("split", insert_variant), "capped"
                ),
                lanes=model.lanes,
                max_actions=model.max_actions,
                batch=traces,
                table_log2=table_log2,
                sim=True,
                dedup=dedup,
                cycle_log2=cycle_log2,
                ring=ring,
            ))
            REGISTRY.register("calib", self._calib.metrics)

    def warm_start(self, entry, kind: Optional[str] = None) -> int:
        """Preload the shared visited table from a published `CorpusEntry`
        (store/warm.py seam): walks re-entering the published set then
        count as `dedup_hits` instead of fresh coverage, so a warm second
        job spends its walk budget on the NEW part of the space. Any entry
        kind serves — coverage is sound whether the source run completed
        or not (`salt=` re-keys exactly as the engine's own inserts do),
        including frontier-less coverage-only entries published by
        `publish_coverage` and Spec-CI salvages (pass kind="delta";
        coverage needs no edit gate — a visited SET is sound under any
        property/boundary edit of the same geometry). Best-effort on
        table overflow. Returns states inserted."""
        if self.table is None:
            raise ValueError(
                "warm_start needs the shared visited table (dedup='shared')"
            )
        n = warm_seam.preload_table(
            self.table, entry.fps, entry.parents, salt=self.salt
        )
        self._warm_states += n
        self._warm_kind = kind or (
            "exact" if getattr(entry, "complete", True) else "partial"
        )
        return n

    def publish_coverage(self, corpus, tenant: Optional[str] = None) -> bool:
        """Publish this simulation's shared visited table as a COVERAGE-ONLY
        partial corpus entry (complete=False, no frontier) — the random-walk
        campaign's contribution to the corpus: a later campaign on the same
        model definition preloads it through `corpus.lookup_family` +
        `warm_start` and spends its walk budget on the unexplored part of
        the space. The exhaustive ladder stays safe by construction:
        `warm.can_continue` refuses frontier-less entries, the service's
        near rung never matches the simulation's batch_size=0 lowering, and
        the Spec-CI delta rung serves complete entries only. Requires
        dedup="shared"; the dumped table is UNSALTED back to canonical
        fingerprints before publish (salt_fp is an involution; the parent-0
        root sentinel survives). Returns True when the entry was written."""
        if self.table is None:
            raise ValueError(
                "publish_coverage needs the shared visited table "
                "(dedup='shared')"
            )
        from ..store.corpus import content_key, key_components

        dump = self.table.dump()
        fps = np.fromiter(dump.keys(), dtype=np.uint64, count=len(dump))
        parents = np.fromiter(
            dump.values(), dtype=np.uint64, count=len(dump)
        )
        if self.salt:
            s_lo, s_hi = job_salt(self.salt)
            lo, hi = warm_seam.split_fps(fps)
            lo, hi = salt_fp(lo, hi, s_lo, s_hi)
            fps = pack_fp(lo, hi)
            plo, phi = warm_seam.split_fps(parents)
            root = parents == 0
            plo, phi = salt_fp(plo, phi, s_lo, s_hi)
            parents = np.where(root, np.uint64(0), pack_fp(plo, phi))
        lowering = {
            "engine": "simulation",
            "dedup": self.dedup,
            "table_log2": self.table_log2,
            "insert_variant": self.insert_variant,
            # batch_size 0 / finish None: a coverage lowering can never
            # collide with (or near-match) an exhaustive engine's key.
            "batch_size": 0,
            "finish": None,
        }
        key = content_key(self.model, lowering, tenant=tenant)
        comp = key_components(self.model, lowering, tenant=tenant)
        meta = {
            "state_count": int(self._totals["states"]),
            "unique_count": int(fps.size),
            "max_depth": int(self._totals["max_depth"]),
            # Coverage only: simulation witnesses are walk paths, not the
            # exhaustive engines' first-match fingerprints — replaying
            # them from a membership preload would claim discoveries the
            # warmed run never re-verified.
            "discoveries": {},
        }
        return corpus.publish(
            key, fps, parents, meta,
            complete=False, frontier=None, components=comp,
        )

    # -- kernel ----------------------------------------------------------------

    def _build(self):
        model = self.model
        T = self.traces
        D = self.max_depth
        shared = self.dedup == "shared"
        C = 1 << self.cycle_log2
        R = self.ring
        stale_limit = self.stale_limit
        continuous = self.continuous
        props = self.props
        P = len(props)
        Pm = max(P, 1)
        always_i = [i for i, p in enumerate(props) if p.expectation == Expectation.ALWAYS]
        sometimes_i = [i for i, p in enumerate(props) if p.expectation == Expectation.SOMETIMES]
        eventually_i = [i for i, p in enumerate(props) if p.expectation == Expectation.EVENTUALLY]
        ebits0 = np.uint32(sum(1 << i for i in eventually_i))
        all_bits = jnp.uint32((1 << P) - 1)
        insert_fn = resolve_insert(self.insert_variant) if shared else None
        salt_words = job_salt(self.salt) if self.salt else None

        def record(c, i, hit, path_lo, path_hi, path_len):
            """First-witness recording for property bit `i`, SNAPSHOTTING
            the discovering lane's fingerprint path (lane re-seeding reuses
            the live path arrays, so the witness is copied out now)."""
            disc, dlo, dhi, dlen = c
            bit = jnp.uint32(1 << i)
            already = (disc & bit) != 0
            any_hit = jnp.any(hit)
            first = jnp.argmax(hit).astype(jnp.int32)
            rec = (~already) & any_hit
            dlo = dlo.at[i].set(jnp.where(rec, path_lo[first], dlo[i]))
            dhi = dhi.at[i].set(jnp.where(rec, path_hi[first], dhi[i]))
            dlen = dlen.at[i].set(jnp.where(rec, path_len[first], dlen[i]))
            return jnp.where(rec, disc | bit, disc), dlo, dhi, dlen

        def probe_insert(v_lo, v_hi, v_gen, g, lo, hi, active):
            """Per-lane linear probe of (lo, hi) in each lane's own cycle
            table, generation-stamped: slots written by a previous walk of
            the same lane (v_gen != g) count as free, so a lane restart
            costs nothing instead of an O(C) clear. Returns
            (v_lo, v_hi, v_gen, seen)."""
            idx0 = (hi % jnp.uint32(C)).astype(jnp.int32)

            def cond(s):
                _vl, _vh, _vg, _idx, resolved, _seen, n = s
                return (~jnp.all(resolved)) & (n < C)

            def body(s):
                v_lo, v_hi, v_gen, idx, resolved, seen, n = s
                cur_lo = jnp.take_along_axis(v_lo, idx[:, None], axis=1)[:, 0]
                cur_hi = jnp.take_along_axis(v_hi, idx[:, None], axis=1)[:, 0]
                cur_g = jnp.take_along_axis(v_gen, idx[:, None], axis=1)[:, 0]
                current = cur_g == g
                hit = current & (cur_lo == lo) & (cur_hi == hi)
                free = (cur_lo == 0) | ~current
                claim = (~resolved) & free
                # One fp per lane per call: no intra-lane races possible;
                # within a generation claimed slots are never freed, so the
                # linear-probe membership argument holds per walk.
                tgt = jnp.where(claim, idx, C)[:, None]
                v_lo = jnp.put_along_axis(
                    v_lo, tgt, jnp.where(claim, lo, 0)[:, None], axis=1,
                    inplace=False, mode="drop",
                )
                v_hi = jnp.put_along_axis(
                    v_hi, tgt, jnp.where(claim, hi, 0)[:, None], axis=1,
                    inplace=False, mode="drop",
                )
                v_gen = jnp.put_along_axis(
                    v_gen, tgt, jnp.where(claim, g, 0)[:, None], axis=1,
                    inplace=False, mode="drop",
                )
                seen = seen | ((~resolved) & hit)
                resolved = resolved | hit | claim
                idx = jnp.where(resolved, idx, (idx + 1) % C)
                return v_lo, v_hi, v_gen, idx, resolved, seen, n + 1

            resolved0 = ~active
            seen0 = jnp.zeros_like(active)
            v_lo, v_hi, v_gen, _i, _r, seen, _n = jax.lax.while_loop(
                cond, body,
                (v_lo, v_hi, v_gen, idx0, resolved0, seen0, jnp.int32(0)),
            )
            return v_lo, v_hi, v_gen, seen

        @partial(jax.jit, static_argnums=(4, 5))
        def simulate(
            seed, init_states, walks_target, step_cap,
            required_mask: int, any_mask: int, tables,
        ):
            n0 = init_states.shape[0]
            base_keys = jax.random.split(jax.random.key(seed), T)

            def walk_keys(restart_n):
                return jax.vmap(jax.random.fold_in)(base_keys, restart_n)

            def pick_init(wk):
                ik = jax.vmap(lambda k: jax.random.fold_in(k, 0x5EED))(wk)
                return jax.vmap(
                    lambda k: jax.random.randint(k, (), 0, n0)
                )(ik)

            req = jnp.uint32(required_mask)
            anym = jnp.uint32(any_mask)

            def body(c: _Carry) -> _Carry:
                active = ~c.done
                # Host parity order (simulation.rs:254-397): depth cap first.
                capped = active & (c.path_len >= D)
                in_bounds = model.within_boundary(c.states)
                out_b = active & ~capped & ~in_bounds
                lo, hi = state_fingerprint(model, c.states)
                live = active & ~capped & in_bounds

                # Cycle detection: exact per-walk table (trace) or the
                # per-walk depth ring (shared; period <= R cycles).
                v_lo, v_hi, v_gen = c.v_lo, c.v_hi, c.v_gen
                ring_lo, ring_hi, ring_gen = c.ring_lo, c.ring_hi, c.ring_gen
                if shared:
                    in_ring = ring_gen == c.gen[:, None]
                    seen = jnp.any(
                        in_ring
                        & (ring_lo == lo[:, None])
                        & (ring_hi == hi[:, None]),
                        axis=1,
                    )
                    rpos = jnp.where(live, c.path_len % R, R)[:, None]
                    ring_lo = jnp.put_along_axis(
                        ring_lo, rpos, lo[:, None], axis=1,
                        inplace=False, mode="drop",
                    )
                    ring_hi = jnp.put_along_axis(
                        ring_hi, rpos, hi[:, None], axis=1,
                        inplace=False, mode="drop",
                    )
                    ring_gen = jnp.put_along_axis(
                        ring_gen, rpos, c.gen[:, None], axis=1,
                        inplace=False, mode="drop",
                    )
                else:
                    v_lo, v_hi, v_gen, seen = probe_insert(
                        v_lo, v_hi, v_gen, c.gen, lo, hi, live
                    )
                looped = live & seen
                walking = live & ~seen

                # Record the fp into the walk path (also for loop breaks,
                # matching the host's fingerprint_path.append order: the fp
                # is appended BEFORE the loop check; boundary-exited walks
                # do NOT append — the host breaks first).
                ppos = jnp.where(live, c.path_len, D)
                path_lo = jnp.put_along_axis(
                    c.path_lo, ppos[:, None], lo[:, None], axis=1,
                    inplace=False, mode="drop",
                )
                path_hi = jnp.put_along_axis(
                    c.path_hi, ppos[:, None], hi[:, None], axis=1,
                    inplace=False, mode="drop",
                )
                path_len = c.path_len + live.astype(jnp.int32)

                # Shared global dedup/coverage insert (job-salted keys when
                # co-resident with other users of the table).
                t_lo, t_hi, p_lo, p_hi = c.t_lo, c.t_hi, c.p_lo, c.p_hi
                unique_count = c.unique_count
                dedup_hits = c.dedup_hits
                stale = c.stale
                overflow_steps = c.overflow_steps
                stale_out = jnp.zeros_like(walking)
                if shared:
                    if salt_words is not None:
                        key_lo, key_hi = salt_fp(lo, hi, *salt_words)
                        par_lo, par_hi = salt_fp(
                            c.prev_lo, c.prev_hi, *salt_words
                        )
                    else:
                        key_lo, key_hi = lo, hi
                        par_lo, par_hi = c.prev_lo, c.prev_hi
                    t_lo, t_hi, p_lo, p_hi, is_new, overflow = insert_fn(
                        t_lo, t_hi, p_lo, p_hi,
                        key_lo, key_hi, par_lo, par_hi, walking,
                    )
                    fresh = walking & is_new
                    unique_count = unique_count + fresh.sum(dtype=jnp.int32)
                    dedup_hits = dedup_hits + (
                        walking & ~is_new
                    ).sum(dtype=jnp.int32)
                    stale = jnp.where(
                        walking & ~is_new,
                        stale + 1,
                        jnp.where(walking, 0, stale),
                    )
                    if stale_limit:
                        stale_out = walking & (stale >= stale_limit)
                    overflow_steps = overflow_steps + overflow.astype(
                        jnp.int32
                    )

                state_count = c.state_count + walking.sum(dtype=jnp.int32)
                max_depth = jnp.maximum(c.max_depth, jnp.max(path_len))
                active_sum = c.active_sum + active.sum(dtype=jnp.int32)

                # Properties on the current state (walking lanes only).
                disc = (c.discovered, c.disc_lo, c.disc_hi, c.disc_len)
                ebits = c.ebits
                if P:
                    masks = jnp.stack(
                        [p.condition(model, c.states) for p in props]
                    )
                    for i in always_i:
                        disc = record(
                            disc, i, walking & ~masks[i],
                            path_lo, path_hi, path_len,
                        )
                    for i in sometimes_i:
                        disc = record(
                            disc, i, walking & masks[i],
                            path_lo, path_hi, path_len,
                        )
                    for i in eventually_i:
                        ebits = jnp.where(
                            walking & masks[i],
                            ebits & jnp.uint32(~(1 << i) & 0xFFFFFFFF),
                            ebits,
                        )

                # Expand and choose uniformly among valid successors.
                succs, valid = model.expand(c.states)
                vcount = valid.sum(axis=1).astype(jnp.int32)
                sk = jax.vmap(jax.random.fold_in)(
                    walk_keys(c.restart_n), jnp.broadcast_to(c.step, (T,))
                )
                r = jax.vmap(
                    lambda k, n: jax.random.randint(k, (), 0, jnp.maximum(n, 1))
                )(sk, vcount)
                pick = jnp.argmax(
                    jnp.cumsum(valid.astype(jnp.int32), axis=1)
                    == (r + 1)[:, None],
                    axis=1,
                )
                next_states = jnp.take_along_axis(
                    succs, pick[:, None, None], axis=1
                )[:, 0]
                terminal = walking & (vcount == 0)
                stepping = walking & (vcount > 0) & ~stale_out
                states = jnp.where(stepping[:, None], next_states, c.states)
                prev_lo, prev_hi = c.prev_lo, c.prev_hi
                if shared:
                    prev_lo = jnp.where(stepping, lo, jnp.uint32(0))
                    prev_hi = jnp.where(stepping, hi, jnp.uint32(0))

                # Walk endings. Terminal/loop/boundary record pending
                # eventually-bits; the depth cap and the staleness restart
                # do not (host `return` parity: the walk is cut short, not
                # known to be terminal).
                ended_record = looped | out_b | terminal
                for i in eventually_i:
                    bad = ended_record & (
                        (ebits >> jnp.uint32(i)) & 1
                    ).astype(bool)
                    disc = record(disc, i, bad, path_lo, path_hi, path_len)
                discovered, disc_lo, disc_hi, disc_len = disc
                ended_all = ended_record | capped | stale_out
                walks = c.walks + ended_all.sum(dtype=jnp.int32)
                stale_restarts = c.stale_restarts + stale_out.sum(
                    dtype=jnp.int32
                )

                done = c.done
                gen = c.gen
                restart_n = c.restart_n
                restarts = c.restarts
                if continuous:
                    # Continuous walk batching: ended lanes re-seed NOW and
                    # start a fresh walk next step — utilization stays ~1.
                    restart = ended_all
                    restarts = restarts + restart.sum(dtype=jnp.int32)
                    restart_n = c.restart_n + restart.astype(jnp.int32)
                    pick0 = pick_init(walk_keys(restart_n))
                    states = jnp.where(
                        restart[:, None], init_states[pick0], states
                    )
                    path_len = jnp.where(restart, 0, path_len)
                    ebits = jnp.where(restart, jnp.uint32(ebits0), ebits)
                    gen = c.gen + restart.astype(jnp.uint32)
                    if shared:
                        stale = jnp.where(restart, 0, stale)
                        prev_lo = jnp.where(restart, jnp.uint32(0), prev_lo)
                        prev_hi = jnp.where(restart, jnp.uint32(0), prev_hi)
                else:
                    done = c.done | ended_all

                return _Carry(
                    states=states,
                    done=done,
                    ebits=ebits,
                    gen=gen,
                    restart_n=restart_n,
                    v_lo=v_lo,
                    v_hi=v_hi,
                    v_gen=v_gen,
                    ring_lo=ring_lo,
                    ring_hi=ring_hi,
                    ring_gen=ring_gen,
                    t_lo=t_lo,
                    t_hi=t_hi,
                    p_lo=p_lo,
                    p_hi=p_hi,
                    prev_lo=prev_lo,
                    prev_hi=prev_hi,
                    stale=stale,
                    path_lo=path_lo,
                    path_hi=path_hi,
                    path_len=path_len,
                    disc_lo=disc_lo,
                    disc_hi=disc_hi,
                    disc_len=disc_len,
                    state_count=state_count,
                    unique_count=unique_count,
                    walks=walks,
                    restarts=restarts,
                    stale_restarts=stale_restarts,
                    dedup_hits=dedup_hits,
                    active_sum=active_sum,
                    overflow_steps=overflow_steps,
                    max_depth=max_depth,
                    discovered=discovered,
                    step=c.step + 1,
                )

            def cond(c: _Carry):
                all_found = (P > 0) & (c.discovered == all_bits)
                policy = (
                    (req != 0) & ((c.discovered & req) == req)
                ) | ((c.discovered & anym) != 0)
                if continuous:
                    running = c.walks < walks_target
                else:
                    running = ~jnp.all(c.done)
                return running & (~all_found) & (~policy) & (
                    c.step < step_cap
                )

            states0 = init_states[pick_init(walk_keys(jnp.zeros(T, jnp.int32)))]
            if shared:
                t_lo, t_hi, p_lo, p_hi = tables
                v_shape, r_shape, s_shape = (1, 1), (T, R), T
            else:
                t_lo = t_hi = p_lo = p_hi = jnp.zeros(1, jnp.uint32)
                v_shape, r_shape, s_shape = (T, C), (1, 1), 1
            carry = _Carry(
                states=states0,
                done=jnp.zeros(T, bool),
                ebits=jnp.full(T, jnp.uint32(ebits0)),
                gen=jnp.ones(T, jnp.uint32),
                restart_n=jnp.zeros(T, jnp.int32),
                v_lo=jnp.zeros(v_shape, jnp.uint32),
                v_hi=jnp.zeros(v_shape, jnp.uint32),
                v_gen=jnp.zeros(v_shape, jnp.uint32),
                ring_lo=jnp.zeros(r_shape, jnp.uint32),
                ring_hi=jnp.zeros(r_shape, jnp.uint32),
                ring_gen=jnp.zeros(r_shape, jnp.uint32),
                t_lo=t_lo,
                t_hi=t_hi,
                p_lo=p_lo,
                p_hi=p_hi,
                prev_lo=jnp.zeros(s_shape, jnp.uint32),
                prev_hi=jnp.zeros(s_shape, jnp.uint32),
                stale=jnp.zeros(s_shape, jnp.int32),
                path_lo=jnp.zeros((T, D), jnp.uint32),
                path_hi=jnp.zeros((T, D), jnp.uint32),
                path_len=jnp.zeros(T, jnp.int32),
                disc_lo=jnp.zeros((Pm, D), jnp.uint32),
                disc_hi=jnp.zeros((Pm, D), jnp.uint32),
                disc_len=jnp.zeros(Pm, jnp.int32),
                state_count=jnp.int32(0),
                unique_count=jnp.int32(0),
                walks=jnp.int32(0),
                restarts=jnp.int32(0),
                stale_restarts=jnp.int32(0),
                dedup_hits=jnp.int32(0),
                active_sum=jnp.int32(0),
                overflow_steps=jnp.int32(0),
                max_depth=jnp.int32(0),
                discovered=jnp.uint32(0),
                step=jnp.int32(0),
            )
            carry = jax.lax.while_loop(cond, body, carry)
            out = {
                "disc_lo": carry.disc_lo,
                "disc_hi": carry.disc_hi,
                "disc_len": carry.disc_len,
                "counters": jnp.stack(
                    [
                        carry.state_count,
                        carry.unique_count,
                        carry.max_depth,
                        carry.discovered.astype(jnp.int32),
                        carry.step,
                        carry.walks,
                        carry.restarts,
                        carry.stale_restarts,
                        carry.dedup_hits,
                        carry.active_sum,
                        carry.overflow_steps,
                    ]
                ),
            }
            if shared:
                out["table"] = (carry.t_lo, carry.t_hi, carry.p_lo, carry.p_hi)
            return out

        return simulate

    # -- host entry ------------------------------------------------------------

    def run(
        self,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        walks: Optional[int] = None,
    ) -> SearchResult:
        from .resident import _finish_masks

        # Chaos-plane boundary: one round = one device dispatch.
        maybe_fault(
            "engine.step", engine="simulation", round=self._rounds
        )
        start = time.monotonic()
        model = self.model
        init = np.asarray(model.init_states(), dtype=np.uint32)
        in_bounds = np.asarray(model.within_boundary(jnp.asarray(init)))
        init = init[in_bounds]
        required_mask, any_mask = _finish_masks(finish_when, self.props)
        walks_target = walks or self.walks or self.traces
        if self.continuous:
            waves = math.ceil(walks_target / self.traces) + 1
            step_cap = waves * (self.max_depth + 2)
        else:
            step_cap = self.max_depth + 2
        tables = (
            (self.table.t_lo, self.table.t_hi, self.table.p_lo,
             self.table.p_hi)
            if self.table is not None
            else ()
        )
        out = self._kernel(
            np.uint32(self.seed + self._rounds),
            jnp.asarray(init),
            np.int32(walks_target),
            np.int32(step_cap),
            required_mask,
            any_mask,
            tables,
        )
        self._rounds += 1
        if self.table is not None:
            (self.table.t_lo, self.table.t_hi,
             self.table.p_lo, self.table.p_hi) = out["table"]
        counters = np.asarray(out["counters"])
        (states, unique, max_depth, discovered, steps, walks_done, restarts,
         stale_restarts, dedup_hits, active_sum, overflow_steps) = (
            int(x) for x in counters
        )
        disc_len = np.asarray(out["disc_len"])
        disc_lo = np.asarray(out["disc_lo"])
        disc_hi = np.asarray(out["disc_hi"])
        for i, p in enumerate(self.props):
            if discovered & (1 << i) and p.name not in self._discoveries:
                ln = int(disc_len[i])
                fps = pack_fp(disc_lo[i, :ln], disc_hi[i, :ln])
                self._discoveries[p.name] = [int(f) for f in fps]

        t = self._totals
        t["states"] += states
        t["unique"] += unique
        t["max_depth"] = max(t["max_depth"], max_depth)
        t["steps"] += steps
        t["walks"] += walks_done
        t["restarts"] += restarts
        t["stale_restarts"] += stale_restarts
        t["dedup_hits"] += dedup_hits
        t["active_sum"] += active_sum
        t["overflow_steps"] += overflow_steps
        duration = time.monotonic() - start
        t["duration"] += duration
        if self._calib is not None:
            # One observation per round: cumulative walk steps vs the
            # round's wall window (cold first rounds include compile time;
            # the K-consecutive drift guard absorbs that).
            self._calib.observe(t["steps"], duration * 1e6, t["states"])
        detail = build_detail(
            {
                "corpus": {
                    "warm_start": True,
                    "preloaded_states": self._warm_states,
                    "warm_kind": self._warm_kind,
                }
            }
            if self._warm_kind is not None
            else None,
            self.telemetry_summary(),
        )
        if self._calib is not None:
            self._calib.finish()
        if self._calib is not None and self._calib.chunks:
            detail = dict(detail or {})
            detail["calib"] = self._calib.detail()
            self._calib.flush_records()
        return SearchResult(
            state_count=t["states"],
            unique_state_count=(
                t["unique"] if self.dedup == "shared" else t["states"]
            ),
            max_depth=t["max_depth"],
            discoveries={
                name: fps[-1] for name, fps in self._discoveries.items()
            },
            complete=False,  # simulation never proves exhaustion
            duration=duration,
            steps=t["steps"],
            detail=detail,
        )

    # -- observability ---------------------------------------------------------

    def telemetry_summary(self) -> Optional[dict]:
        """The walk-plane digest for `SearchResult.detail["telemetry"]`
        (keys pinned in obs/schema.py TELEMETRY_KEYS); None with telemetry
        off."""
        if not self.telemetry:
            return None
        t = self._totals
        out = {
            "steps": t["steps"],
            "generated_total": t["states"],
            "walks": t["walks"],
            "walks_per_sec": round(
                t["walks"] / max(t["duration"], 1e-9), 1
            ),
            "lane_util": round(
                t["active_sum"] / max(t["steps"] * self.traces, 1), 4
            ),
            "restarts": t["restarts"],
        }
        if self.dedup == "shared":
            out["dedup_hit_rate"] = round(
                t["dedup_hits"] / max(t["states"], 1), 4
            )
            out["stale_restarts"] = t["stale_restarts"]
        return out

    def metrics(self) -> dict:
        """The "simulation" obs-REGISTRY source (`/metrics` scrape)."""
        t = self._totals
        return {
            "rounds": self._rounds,
            "states": t["states"],
            "unique": t["unique"],
            "walks": t["walks"],
            "restarts": t["restarts"],
            "stale_restarts": t["stale_restarts"],
            "dedup_hits": t["dedup_hits"],
            "overflow_steps": t["overflow_steps"],
            "discoveries": len(self._discoveries),
        }

    def discovery_path(self, name: str) -> Path:
        """Re-execute the model along the snapshotted fingerprint path of
        the discovering walk (the host checkers' Path.from_fingerprints
        technique, ref: src/checker/path.rs:20-97)."""
        from .frontier import replay_fp_chain

        return replay_fp_chain(self.model, self._discoveries[name])

    # -- checkpoint / resume ---------------------------------------------------

    def checkpoint(self, path: str) -> None:
        """Persist the rounds loop — seed position, cumulative totals,
        discoveries, and (shared mode) the global visited table — through
        the crash-atomic ckptio plane; `load_checkpoint` continues the
        walk schedule exactly where this dump left off (same seed stream,
        same coverage table)."""
        arrays = {}
        if self.table is not None:
            arrays.update(
                t_lo=np.asarray(self.table.t_lo),
                t_hi=np.asarray(self.table.t_hi),
                p_lo=np.asarray(self.table.p_lo),
                p_hi=np.asarray(self.table.p_hi),
            )
        arrays["meta"] = np.frombuffer(
            json.dumps(
                {
                    "engine": "simulation",
                    "seed": self.seed,
                    "rounds": self._rounds,
                    "totals": self._totals,
                    "discoveries": self._discoveries,
                    "lanes": self.model.lanes,
                    "max_actions": self.model.max_actions,
                    "properties": [p.name for p in self.props],
                    "traces": self.traces,
                    "max_depth": self.max_depth,
                    "dedup": self.dedup,
                    "cycle_log2": self.cycle_log2,
                    "ring": self.ring,
                    "table_log2": self.table_log2,
                    "insert_variant": self.insert_variant,
                    "walks": self.walks,
                    "stale_limit": self.stale_limit,
                    "salt": self.salt,
                    "continuous": self.continuous,
                    "telemetry": self.telemetry,
                }
            ).encode(),
            dtype=np.uint8,
        )
        fenced_savez(path, arrays)

    @classmethod
    def load_checkpoint(
        cls, model: TensorModel, path: str
    ) -> "DeviceSimulation":
        """Rebuild a simulation from a `checkpoint` dump; the next `run()`
        continues the rounds loop (seed advance, totals, discoveries, and
        the shared coverage table) exactly where the dump left off."""
        data, _src = load_latest(path)
        meta = json.loads(bytes(data["meta"].tobytes()).decode())
        if (meta["lanes"], meta["max_actions"]) != (
            model.lanes, model.max_actions,
        ):
            raise ValueError(
                "checkpoint was taken with a different model layout "
                f"(lanes/max_actions {meta['lanes']}/{meta['max_actions']} "
                f"!= {model.lanes}/{model.max_actions})"
            )
        prop_names = [p.name for p in model.properties()]
        if meta.get("properties", prop_names) != prop_names:
            raise ValueError(
                "checkpoint was taken with a different property list "
                f"({meta['properties']} != {prop_names})"
            )
        sim = cls(
            model,
            seed=meta["seed"],
            traces=meta["traces"],
            max_depth=meta["max_depth"],
            dedup=meta["dedup"],
            cycle_log2=meta["cycle_log2"],
            ring=meta["ring"],
            table_log2=meta["table_log2"],
            insert_variant=meta["insert_variant"],
            walks=meta["walks"],
            stale_limit=meta["stale_limit"],
            salt=meta["salt"],
            continuous=meta["continuous"],
            telemetry=meta.get("telemetry", True),
        )
        sim._rounds = meta["rounds"]
        sim._totals = dict(meta["totals"])
        sim._discoveries = {
            name: [int(f) for f in fps]
            for name, fps in meta["discoveries"].items()
        }
        if sim.table is not None:
            sim.table.t_lo = jnp.asarray(data["t_lo"])
            sim.table.t_hi = jnp.asarray(data["t_hi"])
            sim.table.p_lo = jnp.asarray(data["p_lo"])
            sim.table.p_hi = jnp.asarray(data["p_hi"])
        return sim
