"""Tensor-encoded single-decree Paxos — the north-star device workload family
(BASELINE.json names the 3-client model; the encoding supports 1-3 clients,
and the validated golden config is 2 clients / 3 servers = 16,668 unique
states, ref: examples/paxos.rs:327,351).

This is a hand-built device encoding of the exact actor system in
`stateright_tpu.examples.paxos` (itself a port of examples/paxos.rs):
RegisterServer(PaxosActor) x S plus RegisterClient(put_count=1) x C over an
unordered non-duplicating network, with the LinearizabilityTester history and
both properties ("linearizable" always, "value chosen" sometimes) evaluated
ON DEVICE as vectorized masks.

Encoding decisions (all bounds are exact consequences of the protocol, see the
per-field comments):

- The network multiset is a sorted pool of `pool_size` u32 lanes holding
  envelope vocabulary ids (empty = 0xFFFFFFFF); sorting makes the multiset
  encoding canonical, and duplicate-id action slots are masked so the action
  enumeration matches the host's one-Deliver-per-distinct-envelope exactly.
- Each server packs into two lanes (ballot/proposal/accepted/decided/accepts
  and the per-peer `prepares` entries); each client packs into 8 bits of one
  shared lane (phase, read return value, and the real-time frontier its Get
  captured — everything the LinearizabilityTester state adds to the checker
  state for this workload).
- The linearizability property enumerates, at build time, every interleaving
  of the <= 2C client ops that respects per-thread order (puts are mandatory
  once completed, in-flight ops optional — ref:
  src/semantics/linearizability.rs:193-280), compiles each to constant
  constraint tables, and evaluates ALL of them branchlessly per state batch:
  an exhaustive linearizability check as a TPU mask.

Count parity with the host model was validated against the 16,668-state
golden (tests/test_tensor_paxos.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax.numpy as jnp

from .model import TensorModel, TensorProperty
from .poolops import rank_sort

EMPTY = np.uint32(0xFFFFFFFF)

# Client phases (host RegisterClient with put_count=1 never rests between
# PutOk and the Get send, so only three phases exist).
PH_PUT_INFLIGHT, PH_GET_INFLIGHT, PH_DONE = 0, 1, 2


def _bits(n_values: int) -> int:
    return max(int(n_values - 1).bit_length(), 1)


@dataclass
class TensorPaxos(TensorModel):
    """Device Paxos over C clients / S servers (default matches the golden)."""

    client_count: int
    server_count: int = 3
    pool_size: int = 14

    # -- static layout ---------------------------------------------------------

    def __post_init__(self):
        C, S = self.client_count, self.server_count
        if S != 3:
            # Broadcast emission slots and quorum arithmetic are laid out for
            # the reference's 3-server configuration (em1/em2 = the two peers).
            raise ValueError("TensorPaxos currently supports server_count=3")
        if C > 3:
            # 2-bit proposal field and 8-bit client field (2 phase + 2 ret +
            # 2*(C-1) frontier bits) both cap C at 3.
            raise ValueError("client field encoding supports client_count <= 3")
        self.NB = 1 + C * S  # ballot codes: 0 = (0, Id(0)); 1+(r-1)*S+l
        self.NLA = 1 + C * S * C  # last_accepted codes: 0 = None; 1+(b-1)*C+k
        self.bb = _bits(self.NB)
        self.bla = _bits(self.NLA)
        self.bprep = 1 + self.bla  # per-peer prepares: present | la
        self.maj = S // 2 + 1

        # Server lane A: ballot | proposal(2b) | accepted(bla) | decided(1) |
        # accepts(S)
        self.off_prop = self.bb
        self.off_acc = self.bb + 2
        self.off_dec = self.off_acc + self.bla
        self.off_accs = self.off_dec + 1
        if self.off_accs + S > 32 or S * self.bprep > 32:
            raise ValueError("server fields exceed one u32 lane")

        # Lanes: [srvA, srvB] * S, clients, pool.
        self.client_lane = 2 * S
        self.pool_off = 2 * S + 1
        self.lanes = self.pool_off + self.pool_size
        self.max_actions = self.pool_size

        self._build_vocab()
        self._build_lin_tables()

    def _build_vocab(self):
        """Envelope vocabulary: contiguous id ranges per message type
        (ref message set: examples/paxos.rs:66-89 + src/actor/register.rs:17-31).
        """
        C, S = self.client_count, self.server_count
        NBALLOT = C * S  # proposed ballots only (r >= 1)
        self.PUT0 = 0  # Put(S+k, 'A'+k) client k -> server (S+k)%S
        self.GET0 = self.PUT0 + C  # Get(2(S+k)) client k -> server (S+k+1)%S
        self.PUTOK0 = self.GET0 + C  # PutOk(S+k) server s -> client k
        self.GETOK0 = self.PUTOK0 + S * C  # GetOk(2(S+k), 'A'+v) -> client k
        self.PREPARE0 = self.GETOK0 + C * C  # Prepare(b) leader -> peer slot d
        self.PREPARED0 = self.PREPARE0 + NBALLOT * (S - 1)
        self.ACCEPT0 = self.PREPARED0 + NBALLOT * (S - 1) * self.NLA
        self.ACCEPTED0 = self.ACCEPT0 + NBALLOT * C * (S - 1)
        self.DECIDED0 = self.ACCEPTED0 + NBALLOT * (S - 1)
        self.V = self.DECIDED0 + NBALLOT * C * (S - 1)

        # Decode tables (numpy, gathered on device with jnp.take).
        TYP = np.zeros(self.V, np.uint32)  # 0..8 in id-range order
        DST = np.zeros(self.V, np.uint32)  # server index or client index
        BAL = np.zeros(self.V, np.uint32)  # ballot code (1-based; 0 n/a)
        PROP = np.zeros(self.V, np.uint32)  # proposal k
        LA = np.zeros(self.V, np.uint32)  # last_accepted code
        SRC = np.zeros(self.V, np.uint32)  # sender actor index
        VAL = np.zeros(self.V, np.uint32)  # GetOk value k

        def leader(b):
            return (b - 1) % S

        def peer(s, d):  # d-th peer of server s, in increasing id order
            return d + (d >= s)

        for k in range(C):
            i = self.PUT0 + k
            TYP[i], DST[i], PROP[i], SRC[i] = 0, (S + k) % S, k, S + k
            i = self.GET0 + k
            TYP[i], DST[i], PROP[i], SRC[i] = 1, (S + k + 1) % S, k, S + k
        for s in range(S):
            for k in range(C):
                i = self.PUTOK0 + s * C + k
                TYP[i], DST[i], PROP[i], SRC[i] = 2, k, k, s
        for k in range(C):
            for v in range(C):
                i = self.GETOK0 + k * C + v
                TYP[i], DST[i], PROP[i], VAL[i] = 3, k, k, v
                SRC[i] = (S + k + 1) % S
        for b in range(1, NBALLOT + 1):
            for d in range(S - 1):
                i = self.PREPARE0 + (b - 1) * (S - 1) + d
                TYP[i], DST[i], BAL[i], SRC[i] = 4, peer(leader(b), d), b, leader(b)
                for la in range(self.NLA):
                    j = self.PREPARED0 + ((b - 1) * (S - 1) + d) * self.NLA + la
                    TYP[j], DST[j], BAL[j], LA[j] = 5, leader(b), b, la
                    SRC[j] = peer(leader(b), d)
                i = self.ACCEPTED0 + (b - 1) * (S - 1) + d
                TYP[i], DST[i], BAL[i] = 7, leader(b), b
                SRC[i] = peer(leader(b), d)
                for k in range(C):
                    i = self.ACCEPT0 + ((b - 1) * C + k) * (S - 1) + d
                    TYP[i], DST[i], BAL[i], PROP[i] = 6, peer(leader(b), d), b, k
                    SRC[i] = leader(b)
                    i = self.DECIDED0 + ((b - 1) * C + k) * (S - 1) + d
                    TYP[i], DST[i], BAL[i], PROP[i] = 8, peer(leader(b), d), b, k
                    SRC[i] = leader(b)
        self._TYP, self._DST, self._BAL = TYP, DST, BAL
        self._PROP, self._LA, self._SRC, self._VAL = PROP, LA, SRC, VAL

        # Pack all seven decode fields into ONE u32 per envelope id: the
        # expand kernel then pays a single [B, M] table gather instead of
        # seven (TPU gathers cost per element — the 7-table form was the
        # bulk of the 5.8 ms/step expand fusion on v5e). Field widths are
        # exact for the supported C <= 3 / S == 3 configs (sum <= 23 bits).
        widths = [
            ("typ", 4, TYP),
            ("dst", _bits(max(S, C)), DST),
            ("bal", _bits(self.NB), BAL),
            ("prp", _bits(C), PROP),
            ("la", _bits(self.NLA), LA),
            ("src", _bits(S + C), SRC),
            ("val", _bits(C), VAL),
        ]
        assert sum(w for _, w, _t in widths) <= 32
        packed = np.zeros(self.V, np.uint32)
        off = 0
        self._field_off = {}
        for name, w, tbl in widths:
            assert int(tbl.max()) < (1 << w), (name, int(tbl.max()), w)
            self._field_off[name] = (off, (1 << w) - 1)
            packed |= tbl.astype(np.uint32) << np.uint32(off)
            off += w
        self._PACKED = packed

    def _build_lin_tables(self):
        """Static interleaving enumeration for the on-device linearizability
        mask. Each combo = (which ops are included, in which order); compiled
        to: allowed-phase bitmask per client, expected Get return per client
        (-1: no Get / unconstrained), and the max real-time frontier each
        included Get tolerates toward each peer."""
        C = self.client_count
        NULL = -2  # register holds no client value yet

        combos_phase, combos_ret, combos_maxf = [], [], []

        def orders(included):
            """All interleavings of the included ops (tuples of (client,
            'p'|'g')) that keep each client's put before its get."""
            ops = []
            for c, pat in enumerate(included):
                if pat >= 1:
                    ops.append((c, "p"))
                if pat == 2:
                    ops.append((c, "g"))
            seqs = [[]]
            for _ in range(len(ops)):
                nxt = []
                for seq in seqs:
                    used = set(seq)
                    for op in ops:
                        if op in used:
                            continue
                        if op[1] == "g" and (op[0], "p") not in used:
                            continue
                        nxt.append(seq + [op])
                seqs = nxt
            return seqs or [[]]

        def gen(prefix):
            if len(prefix) == C:
                for seq in orders(prefix):
                    # Phase constraints per client: pattern 0 (put excluded)
                    # requires phase==PUT_INFLIGHT; pattern 1 (put only)
                    # requires the get not completed; pattern 2 allows any
                    # phase with the get in existence.
                    pm, ret, maxf = [], [], []
                    for c, pat in enumerate(prefix):
                        if pat == 0:
                            pm.append(1 << PH_PUT_INFLIGHT)
                        elif pat == 1:
                            pm.append((1 << PH_PUT_INFLIGHT) | (1 << PH_GET_INFLIGHT))
                        else:
                            pm.append((1 << PH_GET_INFLIGHT) | (1 << PH_DONE))
                    # Replay the register through the sequence; expected value
                    # of each included get is static.
                    val = NULL
                    expected = {c: None for c in range(C)}
                    for c, kind in seq:
                        if kind == "p":
                            val = c
                        else:
                            expected[c] = val
                    for c, pat in enumerate(prefix):
                        if pat == 2:
                            e = expected[c]
                            ret.append(-1 if e == NULL else e)
                        else:
                            ret.append(-1 if pat < 2 else 0)
                    # -1 ret with pattern 2 means: only an in-flight get can
                    # satisfy this combo (a completed get returned a real
                    # value, but the combo serializes it before any write).
                    mf = [[2] * C for _ in range(C)]
                    for c, pat in enumerate(prefix):
                        if pat != 2:
                            continue
                        gpos = seq.index((c, "g"))
                        for c2 in range(C):
                            if c2 == c:
                                continue
                            before = set(seq[:gpos])
                            if (c2, "p") not in before:
                                mf[c][c2] = 0
                            elif (c2, "g") not in before:
                                mf[c][c2] = 1
                    combos_phase.append(pm)
                    combos_ret.append(ret)
                    combos_maxf.append(mf)
                return
            for pat in (0, 1, 2):
                gen(prefix + [pat])

        gen([])
        phase = np.asarray(combos_phase, np.uint32)  # [NC, C]
        ret = np.asarray(combos_ret, np.int32)  # [NC, C]
        maxf = np.asarray(combos_maxf, np.uint32)  # [NC, C, C]
        # Distinct interleavings often compile to identical constraint rows
        # (e.g. two puts both overwritten before any included read); dedupe —
        # every row costs a [B, NC, C] mask evaluation in the hot loop.
        stacked = np.concatenate(
            [phase, ret.astype(np.int64), maxf.reshape(len(maxf), -1)], axis=1
        )
        _, keep = np.unique(stacked, axis=0, return_index=True)
        keep = np.sort(keep)
        self._lin_phase = phase[keep]
        self._lin_ret = ret[keep]
        self._lin_maxf = maxf[keep]

    # -- field unpack helpers (all shapes broadcast) ---------------------------

    def _srv_unpack(self, laneA):
        m = jnp.uint32
        ballot = laneA & m((1 << self.bb) - 1)
        prop = (laneA >> m(self.off_prop)) & m(3)
        accepted = (laneA >> m(self.off_acc)) & m((1 << self.bla) - 1)
        decided = (laneA >> m(self.off_dec)) & m(1)
        accepts = (laneA >> m(self.off_accs)) & m((1 << self.server_count) - 1)
        return ballot, prop, accepted, decided, accepts

    def _srv_pack(self, ballot, prop, accepted, decided, accepts):
        m = jnp.uint32
        return (
            ballot.astype(jnp.uint32)
            | (prop.astype(jnp.uint32) << m(self.off_prop))
            | (accepted.astype(jnp.uint32) << m(self.off_acc))
            | (decided.astype(jnp.uint32) << m(self.off_dec))
            | (accepts.astype(jnp.uint32) << m(self.off_accs))
        )

    # -- TensorModel interface -------------------------------------------------

    def init_states(self):
        C = self.client_count
        row = np.zeros(self.lanes, np.uint32)
        pool = sorted([self.PUT0 + k for k in range(C)]) + [int(EMPTY)] * (
            self.pool_size - C
        )
        row[self.pool_off :] = pool
        return jnp.asarray(row[None, :])

    def expand(self, states):
        C, S, M = self.client_count, self.server_count, self.pool_size
        B = states.shape[0]
        u = jnp.uint32
        pool = states[:, self.pool_off :]  # [B, M]
        clients = states[:, self.client_lane]  # [B]

        e = pool  # delivered envelope id per action slot
        idx = jnp.minimum(e, u(self.V - 1)).astype(jnp.int32)
        # ONE packed-table gather; fields unpack with fused shifts/masks
        # (see _build_vocab — seven separate gathers dominated the expand
        # fusion on v5e).
        packed = jnp.take(jnp.asarray(self._PACKED), idx)

        def field(name):
            off, mask = self._field_off[name]
            return (packed >> u(off)) & u(mask)

        typ = field("typ")
        dst = field("dst")
        bal = field("bal")
        prp = field("prp")
        la_m = field("la")
        src = field("src")
        val = field("val")

        # One Deliver action per DISTINCT in-flight envelope (host parity:
        # nonduplicating iter_deliverable yields distinct envelopes). The pool
        # is sorted, so duplicates are adjacent.
        nonempty = e != EMPTY
        first = jnp.concatenate(
            [jnp.ones((B, 1), bool), e[:, 1:] != e[:, :-1]], axis=1
        )
        deliverable = nonempty & first

        is_server_msg = (typ == 0) | (typ == 1) | (typ >= 4)

        # Select the target server's lanes per action slot as a one-hot sum
        # over the S=3 servers — branchless VPU selects fuse; a
        # take_along_axis gather does not.
        srvA_all = states[:, 0 : 2 * S : 2]  # [B, S]
        srvB_all = states[:, 1 : 2 * S : 2]
        d_srv = jnp.where(is_server_msg, dst, 0).astype(jnp.int32)
        sA = jnp.zeros((B, M), u)
        sB = jnp.zeros((B, M), u)
        for s in range(S):
            sel_s = d_srv == s
            sA = jnp.where(sel_s, srvA_all[:, s : s + 1], sA)
            sB = jnp.where(sel_s, srvB_all[:, s : s + 1], sB)
        ballot, prop, accepted, decided, accepts = self._srv_unpack(sA)
        not_dec = decided == 0

        # Per-client fields of the delivered-to client (client msgs).
        csh = (jnp.where(is_server_msg, 0, dst) * 8).astype(jnp.uint32)
        cfield = (clients[:, None] >> csh) & u(0xFF)
        cphase = cfield & u(3)

        # ---- outcome scaffolding -------------------------------------------
        nA, nB = sA, sB  # new server lanes
        ncf = cfield  # new client field
        em1 = jnp.full((B, M), EMPTY)  # up to three emissions
        em2 = jnp.full((B, M), EMPTY)
        em3 = jnp.full((B, M), EMPTY)
        ok = jnp.zeros((B, M), bool)  # transition not elided

        maskS = u((1 << S) - 1)

        def r_of(b):  # ballot code -> round
            return jnp.where(b == 0, u(0), (b - 1) // u(S) + 1)

        # ---- Put (typ 0): propose (ref: examples/paxos.rs:163-183) ----------
        g = (typ == 0) & not_dec & (prop == 0)
        nb = u(1) + r_of(ballot) * u(S) + dst  # (r+1, dst)
        prepB = (u(1) | (accepted << u(1))) << (dst * u(self.bprep)).astype(u)
        nA = jnp.where(g, self._srv_pack(nb, prp + u(1), accepted, u(0), u(0)), nA)
        nB = jnp.where(g, prepB, nB)
        pre0 = u(self.PREPARE0) + (nb - u(1)) * u(S - 1)
        em1 = jnp.where(g, pre0, em1)
        em2 = jnp.where(g, pre0 + u(1), em2)
        ok = ok | g

        # ---- Get (typ 1): reply when decided (ref: paxos.rs:145-157) --------
        g = (typ == 1) & (decided == 1)
        vprop = jnp.where(accepted > 0, (accepted - u(1)) % u(C), u(0))
        em1 = jnp.where(g, u(self.GETOK0) + prp * u(C) + vprop, em1)
        ok = ok | g  # state unchanged; reply makes it a real transition

        # ---- Prepare (typ 4) (ref: paxos.rs:186-192) ------------------------
        g = (typ == 4) & not_dec & (ballot < bal)
        nA = jnp.where(g, self._srv_pack(bal, prop, accepted, u(0), accepts), nA)
        lead = (bal - u(1)) % u(S)
        slot = dst - (dst > lead)
        em1 = jnp.where(
            g,
            u(self.PREPARED0)
            + ((bal - u(1)) * u(S - 1) + slot) * u(self.NLA)
            + accepted,
            em1,
        )
        ok = ok | g

        # ---- Prepared (typ 5) (ref: paxos.rs:193-231) -----------------------
        g = (typ == 5) & not_dec & (bal == ballot)
        sslot = src  # replier server id
        pbit = u(1) << (sslot * u(self.bprep)).astype(u)
        already = (sB & pbit) != 0
        addB = sB | pbit | (la_m << (sslot * u(self.bprep) + u(1)).astype(u))
        # popcount of present bits after insertion
        pres = jnp.zeros((B, M), u)
        best_la = jnp.zeros((B, M), u)
        for j in range(S):
            pj = (addB >> u(j * self.bprep)) & u(1)
            laj = (addB >> u(j * self.bprep + 1)) & u((1 << self.bla) - 1)
            pres = pres + pj
            best_la = jnp.maximum(best_la, jnp.where(pj == 1, laj, u(0)))
        quorum = (~already) & (pres == self.maj)
        chosen = jnp.where(
            best_la > 0, (best_la - u(1)) % u(C), prop - u(1)
        )  # proposal k
        acc0 = u(self.ACCEPT0) + ((bal - u(1)) * u(C) + chosen) * u(S - 1)
        em1 = jnp.where(g & quorum, acc0, em1)
        em2 = jnp.where(g & quorum, acc0 + u(1), em2)
        nA = jnp.where(
            g,
            jnp.where(
                quorum,
                self._srv_pack(
                    ballot,
                    chosen + u(1),
                    u(1) + (bal - u(1)) * u(C) + chosen,  # accepted=(b, chosen)
                    u(0),
                    u(1) << dst,  # accepts = {self}
                ),
                self._srv_pack(ballot, prop, accepted, u(0), accepts),
            ),
            nA,
        )
        nB = jnp.where(g, addB, nB)
        ok = ok | g

        # ---- Accept (typ 6) (ref: paxos.rs:232-240) -------------------------
        g = (typ == 6) & not_dec & (ballot <= bal)
        nacc = u(1) + (bal - u(1)) * u(C) + prp
        nA = jnp.where(g, self._srv_pack(bal, prop, nacc, u(0), accepts), nA)
        lead = (bal - u(1)) % u(S)
        slot = dst - (dst > lead)
        em1 = jnp.where(g, u(self.ACCEPTED0) + (bal - u(1)) * u(S - 1) + slot, em1)
        ok = ok | g

        # ---- Accepted (typ 7) (ref: paxos.rs:241-263) -----------------------
        g = (typ == 7) & not_dec & (bal == ballot)
        abit = u(1) << src
        naccs = (accepts | abit) & maskS
        cnt = jnp.zeros((B, M), u)
        for j in range(S):
            cnt = cnt + ((naccs >> u(j)) & u(1))
        aquorum = ((accepts & abit) == 0) & (cnt == self.maj)
        dec0 = u(self.DECIDED0) + ((bal - u(1)) * u(C) + (prop - u(1))) * u(S - 1)
        em1 = jnp.where(g & aquorum, dec0, em1)
        em2 = jnp.where(g & aquorum, dec0 + u(1), em2)
        em3 = jnp.where(
            g & aquorum, u(self.PUTOK0) + dst * u(C) + (prop - u(1)), em3
        )
        nA = jnp.where(
            g,
            self._srv_pack(
                ballot, prop, accepted, jnp.where(aquorum, u(1), u(0)), naccs
            ),
            nA,
        )
        ok = ok | g

        # ---- Decided (typ 8) (ref: paxos.rs:264-271) ------------------------
        g = (typ == 8) & not_dec
        nacc = u(1) + (bal - u(1)) * u(C) + prp
        nA = jnp.where(g, self._srv_pack(bal, prop, nacc, u(1), accepts), nA)
        ok = ok | g

        # ---- PutOk (typ 2): client advances to Get --------------------------
        # History effects in one transition: on_return(Write) then
        # on_invoke(Read) with the real-time frontier captured from the other
        # clients' CURRENT completed-op counts (ref:
        # src/actor/model.rs:348-357 ordering; linearizability.rs:102-129).
        g = (typ == 2) & (cphase == PH_PUT_INFLIGHT)
        frontier = jnp.zeros((B, M), u)
        fshift = u(0)
        for c2 in range(C):
            # completed ops of client c2: 0 / 1 / 2 by phase
            f2 = (clients[:, None] >> u(8 * c2)) & u(3)
            comp = jnp.where(f2 == PH_DONE, u(2), jnp.where(f2 == PH_GET_INFLIGHT, u(1), u(0)))
            is_peer = dst != c2
            frontier = frontier | jnp.where(is_peer, comp << fshift, u(0))
            # peer slots are assigned in increasing client order, skipping self
            fshift = fshift + jnp.where(is_peer, u(2), u(0))
        ncf = jnp.where(g, u(PH_GET_INFLIGHT) | (frontier << u(4)), ncf)
        em1 = jnp.where(g, u(self.GET0) + dst, em1)
        ok = ok | g

        # ---- GetOk (typ 3): client done -------------------------------------
        g = (typ == 3) & (cphase == PH_GET_INFLIGHT)
        ncf = jnp.where(g, (cfield & ~u(3) & ~u(3 << 2)) | u(PH_DONE) | (val << u(2)), ncf)
        ok = ok | g

        valid = deliverable & ok

        # ---- assemble successors -------------------------------------------
        # Server lanes: scatter the new pair back into the dst server's slot.
        succ = jnp.broadcast_to(states[:, None, :], (B, M, self.lanes))
        srv_sel = (
            jnp.arange(S)[None, None, :] == d_srv[:, :, None]
        ) & is_server_msg[:, :, None]  # [B, M, S]
        newA = jnp.where(srv_sel, nA[:, :, None], srvA_all[:, None, :])
        newB = jnp.where(srv_sel, nB[:, :, None], srvB_all[:, None, :])
        succ = succ.at[:, :, 0 : 2 * S : 2].set(newA)
        succ = succ.at[:, :, 1 : 2 * S : 2].set(newB)

        # Client lane.
        ncl = (
            clients[:, None] & ~(u(0xFF) << csh)
        ) | (ncf << csh)
        ncl = jnp.where(is_server_msg, clients[:, None], ncl)
        succ = succ.at[:, :, self.client_lane].set(ncl)

        # Pool: drop the delivered slot, add emissions, restore the
        # canonical sorted-multiset form via the unrolled rank-sort
        # (tensor/poolops.py — a jnp.sort along the minor axis was the
        # single largest slice of this kernel's fusion on v5e). pool_size
        # has slack over the measured max in-flight; if a successor would
        # exceed it anyway, the row becomes the reserved all-ones POISON
        # state (terminal — its pool is all EMPTY) and the "pool capacity"
        # property below reports it as a discovery instead of silently
        # truncating the state space.
        act = jnp.arange(M, dtype=jnp.uint32)[None, :]
        parts = [
            jnp.where(act == i, EMPTY, pool[:, i : i + 1]) for i in range(M)
        ] + [em1, em2, em3]
        npool, overflow = rank_sort(parts, M)
        succ = succ.at[:, :, self.pool_off :].set(npool)
        succ = jnp.where(overflow[:, :, None], jnp.uint32(EMPTY), succ)

        return succ, valid

    # -- properties ------------------------------------------------------------

    def properties(self):
        C = self.client_count

        def linearizable(model, states):
            clients = states[:, model.client_lane]
            u = jnp.uint32
            phase = jnp.stack(
                [(clients >> u(8 * c)) & u(3) for c in range(C)], axis=1
            )  # [B, C]
            ret = jnp.stack(
                [(clients >> u(8 * c + 2)) & u(3) for c in range(C)], axis=1
            )
            frontier = jnp.stack(
                [
                    jnp.stack(
                        [
                            (
                                (clients >> u(8 * c + 4 + 2 * (c2 - (c2 > c))))
                                & u(3)
                                if c2 != c
                                else jnp.zeros_like(clients)
                            )
                            for c2 in range(C)
                        ],
                        axis=1,
                    )
                    for c in range(C)
                ],
                axis=1,
            )  # [B, C, C] — f of get_c toward peer c2 (0 when c2 == c)

            pm = jnp.asarray(model._lin_phase)  # [NC, C]
            exp = jnp.asarray(model._lin_ret)  # [NC, C]
            maxf = jnp.asarray(model._lin_maxf)  # [NC, C, C]

            ph = phase[:, None, :]  # [B, 1, C]
            phase_ok = ((pm[None] >> ph) & u(1)) == 1  # [B, NC, C]
            has_get = (pm[None] & u(1 << PH_DONE)) != 0
            ret_ok = (
                ~has_get
                | (ph == PH_GET_INFLIGHT)
                | ((exp[None] >= 0) & (ret[:, None, :] == exp[None].astype(u)))
            )
            # Completed gets in combos whose sequence reads NULL can never
            # match (GetOk always returns a real value): exp < 0 with a
            # completed get fails unless the get is merely in flight.
            rt_ok = jnp.all(
                frontier[:, None, :, :] <= maxf[None], axis=3
            )  # [B, NC, C]
            combo_ok = jnp.all(phase_ok & ret_ok & rt_ok, axis=2)  # [B, NC]
            # Poison (pool-overflow) rows are reported by "pool capacity",
            # not as spurious linearizability violations.
            return jnp.any(combo_ok, axis=1) | _is_poison(states)

        def value_chosen(model, states):
            pool = states[:, model.pool_off :]
            return jnp.any(
                (pool >= model.GETOK0) & (pool < model.GETOK0 + C * C), axis=1
            )

        def _is_poison(states):
            return jnp.all(states == jnp.uint32(EMPTY), axis=1)

        def pool_capacity(model, states):
            return ~_is_poison(states)

        return [
            TensorProperty.always("linearizable", linearizable),
            TensorProperty.sometimes("value chosen", value_chosen),
            TensorProperty.always("pool capacity", pool_capacity),
        ]

    # -- display ---------------------------------------------------------------

    def decode(self, row):
        C, S = self.client_count, self.server_count
        row = [int(x) for x in row]
        servers = []
        for s in range(S):
            a, b = row[2 * s], row[2 * s + 1]
            ballot = a & ((1 << self.bb) - 1)
            servers.append(
                dict(
                    ballot=ballot,
                    proposal=(a >> self.off_prop) & 3,
                    accepted=(a >> self.off_acc) & ((1 << self.bla) - 1),
                    decided=(a >> self.off_dec) & 1,
                    accepts=(a >> self.off_accs) & ((1 << S) - 1),
                    prepares=[
                        (
                            (b >> (j * self.bprep)) & 1,
                            (b >> (j * self.bprep + 1)) & ((1 << self.bla) - 1),
                        )
                        for j in range(S)
                    ],
                )
            )
        clients = []
        for c in range(C):
            f = (row[self.client_lane] >> (8 * c)) & 0xFF
            clients.append(dict(phase=f & 3, ret=(f >> 2) & 3, frontier=f >> 4))
        pool = [x for x in row[self.pool_off :] if x != int(EMPTY)]
        return dict(servers=servers, clients=clients, network=pool)

    def action_label(self, row, action_index):
        e = int(row[self.pool_off + action_index])
        if e == int(EMPTY):
            return "noop"
        names = ["Put", "Get", "PutOk", "GetOk", "Prepare", "Prepared", "Accept", "Accepted", "Decided"]
        return f"Deliver({int(self._SRC[e])}->{int(self._DST[e])}, {names[int(self._TYP[e])]}#{e})"
