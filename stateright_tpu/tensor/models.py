"""Tensor-state encodings of the canonical workloads, built TPU-first: static
action fan-out, branchless lane updates via `where`, everything batched.

These pair with the host models for count-parity testing (the "exact unique
state counts as cross-implementation oracle" strategy, SURVEY.md §4).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .model import TensorModel, TensorProperty


@dataclass
class TensorLinearEquation(TensorModel):
    """a*x + b*y == c (mod 256) — the canonical checker workload
    (ref: src/test_util.rs:140-192). Lanes: [x, y]; actions: IncreaseX,
    IncreaseY. Full space 256*256 = 65,536 states."""

    a: int
    b: int
    c: int
    lanes = 2
    max_actions = 2

    def init_states(self):
        return jnp.zeros((1, 2), dtype=jnp.uint32)

    def expand(self, states):
        x, y = states[:, 0], states[:, 1]
        inc_x = jnp.stack([(x + 1) % 256, y], axis=1)
        inc_y = jnp.stack([x, (y + 1) % 256], axis=1)
        succs = jnp.stack([inc_x, inc_y], axis=1).astype(jnp.uint32)
        valid = jnp.ones((states.shape[0], 2), dtype=bool)
        return succs, valid

    def properties(self):
        def solvable(model, states):
            x, y = states[:, 0], states[:, 1]
            return (model.a * x + model.b * y) % 256 == model.c % 256

        return [TensorProperty.sometimes("solvable", solvable)]

    def decode(self, row):
        return (int(row[0]), int(row[1]))

    def action_label(self, row, action_index):
        return ["IncreaseX", "IncreaseY"][action_index]


# -- 2PC ----------------------------------------------------------------------

# RM states (2 bits each, packed one per lane for simplicity).
_WORKING, _PREPARED, _COMMITTED, _ABORTED = 0, 1, 2, 3
_TM_INIT, _TM_COMMITTED, _TM_ABORTED = 0, 1, 2


@dataclass
class TensorTwoPhaseSys(TensorModel):
    """Two-phase commit (ref: examples/2pc.rs:59-147), tensor-encoded.

    Lanes: [rm_state[0..N], tm_state, tm_prepared_bitmask, msgs_bitmask]
    where msgs bit i = "Prepared{rm=i}" in flight, bit N = Commit,
    bit N+1 = Abort.

    Actions (static slots): 0 = TmCommit, 1 = TmAbort, then per RM:
    [TmRcvPrepared, RmPrepare, RmChooseToAbort, RmRcvCommit, RmRcvAbort].
    """

    rm_count: int

    def __post_init__(self):
        self.lanes = self.rm_count + 3
        self.max_actions = 2 + 5 * self.rm_count

    def init_states(self):
        return jnp.zeros((1, self.lanes), dtype=jnp.uint32)

    def expand(self, states):
        n = self.rm_count
        B = states.shape[0]
        rm = states[:, :n]  # [B, n]
        tm = states[:, n]
        prepared_mask = states[:, n + 1]
        msgs = states[:, n + 2]
        commit_bit = jnp.uint32(1 << n)
        abort_bit = jnp.uint32(1 << (n + 1))

        all_prepared = prepared_mask == jnp.uint32((1 << n) - 1)
        tm_init = tm == _TM_INIT

        succ_list = []
        valid_list = []

        def assemble(rm_new, tm_new, prep_new, msgs_new):
            return jnp.concatenate(
                [
                    rm_new.astype(jnp.uint32),
                    tm_new.astype(jnp.uint32)[:, None],
                    prep_new.astype(jnp.uint32)[:, None],
                    msgs_new.astype(jnp.uint32)[:, None],
                ],
                axis=1,
            )

        # TmCommit (ref: 2pc.rs:73-75, 104-107)
        succ_list.append(
            assemble(rm, jnp.full(B, _TM_COMMITTED), prepared_mask, msgs | commit_bit)
        )
        valid_list.append(tm_init & all_prepared)
        # TmAbort (ref: 2pc.rs:76-78, 108-111)
        succ_list.append(
            assemble(rm, jnp.full(B, _TM_ABORTED), prepared_mask, msgs | abort_bit)
        )
        valid_list.append(tm_init)

        for i in range(n):
            rm_bit = jnp.uint32(1 << i)
            rm_i = rm[:, i]
            one_hot = jnp.arange(n) == i  # [n]

            def set_rm(value):
                return jnp.where(one_hot[None, :], jnp.uint32(value), rm)

            # TmRcvPrepared(i) (ref: 2pc.rs:80-82, 101-103)
            succ_list.append(assemble(rm, tm, prepared_mask | rm_bit, msgs))
            valid_list.append(tm_init & ((msgs & rm_bit) != 0))
            # RmPrepare(i) (ref: 2pc.rs:83-85, 112-115)
            succ_list.append(
                assemble(set_rm(_PREPARED), tm, prepared_mask, msgs | rm_bit)
            )
            valid_list.append(rm_i == _WORKING)
            # RmChooseToAbort(i) (ref: 2pc.rs:86-88, 116-118)
            succ_list.append(assemble(set_rm(_ABORTED), tm, prepared_mask, msgs))
            valid_list.append(rm_i == _WORKING)
            # RmRcvCommitMsg(i) (ref: 2pc.rs:89-91, 119-121)
            succ_list.append(assemble(set_rm(_COMMITTED), tm, prepared_mask, msgs))
            valid_list.append((msgs & commit_bit) != 0)
            # RmRcvAbortMsg(i) (ref: 2pc.rs:92-94, 122-124)
            succ_list.append(assemble(set_rm(_ABORTED), tm, prepared_mask, msgs))
            valid_list.append((msgs & abort_bit) != 0)

        succs = jnp.stack(succ_list, axis=1)  # [B, A, L]
        valid = jnp.stack(valid_list, axis=1)  # [B, A]
        return succs, valid

    def properties(self):
        n = self.rm_count

        def rm_all(states, value):
            return jnp.all(states[:, :n] == jnp.uint32(value), axis=1)

        return [
            TensorProperty.sometimes(
                "abort agreement", lambda m, s: rm_all(s, _ABORTED)
            ),
            TensorProperty.sometimes(
                "commit agreement", lambda m, s: rm_all(s, _COMMITTED)
            ),
            TensorProperty.always(
                "consistent",
                lambda m, s: ~(
                    jnp.any(s[:, :n] == jnp.uint32(_ABORTED), axis=1)
                    & jnp.any(s[:, :n] == jnp.uint32(_COMMITTED), axis=1)
                ),
            ),
        ]

    def decode(self, row):
        n = self.rm_count
        names = {0: "working", 1: "prepared", 2: "committed", 3: "aborted"}
        tm_names = {0: "init", 1: "committed", 2: "aborted"}
        msgs = int(row[n + 2])
        msg_set = {f"prepared({i})" for i in range(n) if msgs & (1 << i)}
        if msgs & (1 << n):
            msg_set.add("commit")
        if msgs & (1 << (n + 1)):
            msg_set.add("abort")
        return (
            tuple(names[int(x)] for x in row[:n]),
            tm_names[int(row[n])],
            int(row[n + 1]),
            frozenset(msg_set),
        )

    def action_label(self, row, action_index):
        if action_index == 0:
            return "tm_commit"
        if action_index == 1:
            return "tm_abort"
        i, kind = divmod(action_index - 2, 5)
        return (
            ["tm_rcv_prepared", "rm_prepare", "rm_choose_abort",
             "rm_rcv_commit", "rm_rcv_abort"][kind],
            i,
        )
