"""Tensor-state encodings of the canonical workloads, built TPU-first: static
action fan-out, branchless lane updates via `where`, everything batched.

These pair with the host models for count-parity testing (the "exact unique
state counts as cross-implementation oracle" strategy, SURVEY.md §4).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .model import TensorModel, TensorProperty


@dataclass
class TensorLinearEquation(TensorModel):
    """a*x + b*y == c (mod 256) — the canonical checker workload
    (ref: src/test_util.rs:140-192). Lanes: [x, y]; actions: IncreaseX,
    IncreaseY. Full space 256*256 = 65,536 states."""

    a: int
    b: int
    c: int
    lanes = 2
    max_actions = 2

    def init_states(self):
        return jnp.zeros((1, 2), dtype=jnp.uint32)

    def expand(self, states):
        x, y = states[:, 0], states[:, 1]
        inc_x = jnp.stack([(x + 1) % 256, y], axis=1)
        inc_y = jnp.stack([x, (y + 1) % 256], axis=1)
        succs = jnp.stack([inc_x, inc_y], axis=1).astype(jnp.uint32)
        valid = jnp.ones((states.shape[0], 2), dtype=bool)
        return succs, valid

    def properties(self):
        def solvable(model, states):
            x, y = states[:, 0], states[:, 1]
            return (model.a * x + model.b * y) % 256 == model.c % 256

        return [TensorProperty.sometimes("solvable", solvable)]

    def decode(self, row):
        return (int(row[0]), int(row[1]))

    def action_label(self, row, action_index):
        return ["IncreaseX", "IncreaseY"][action_index]


# -- 2PC ----------------------------------------------------------------------

# RM states (2 bits each, packed one per lane for simplicity).
_WORKING, _PREPARED, _COMMITTED, _ABORTED = 0, 1, 2, 3
_TM_INIT, _TM_COMMITTED, _TM_ABORTED = 0, 1, 2


@dataclass
class TensorTwoPhaseSys(TensorModel):
    """Two-phase commit (ref: examples/2pc.rs:59-147), tensor-encoded.

    Lanes: [rm_state[0..N], tm_state, tm_prepared_bitmask, msgs_bitmask]
    where msgs bit i = "Prepared{rm=i}" in flight, bit N = Commit,
    bit N+1 = Abort.

    Actions (static slots): 0 = TmCommit, 1 = TmAbort, then per RM:
    [TmRcvPrepared, RmPrepare, RmChooseToAbort, RmRcvCommit, RmRcvAbort].
    """

    rm_count: int
    # Opt-in like the host builder's .symmetry(). True selects the full-key
    # orbit invariant (the device default — traversal-order-independent,
    # 2PC-5: 314); "value" selects the reference's value-only sort
    # (ref: src/checker/rewrite_plan.rs:81-107), whose reduced count is
    # traversal-order-DEPENDENT — it reproduces the published 665 golden
    # only under reference DFS order (see tensor/symmetry.py
    # device_dfs_unique_count and the module docstring's measured table).
    symmetry: "bool | str" = False

    def __post_init__(self):
        self.lanes = self.rm_count + 3
        self.max_actions = 2 + 5 * self.rm_count
        if self.symmetry == "value":
            self.representative = self._representative_value_sort
        elif self.symmetry:
            self.representative = self._representative

    def init_states(self):
        return jnp.zeros((1, self.lanes), dtype=jnp.uint32)

    def expand(self, states):
        n = self.rm_count
        B = states.shape[0]
        rm = states[:, :n]  # [B, n]
        tm = states[:, n]
        prepared_mask = states[:, n + 1]
        msgs = states[:, n + 2]
        commit_bit = jnp.uint32(1 << n)
        abort_bit = jnp.uint32(1 << (n + 1))

        all_prepared = prepared_mask == jnp.uint32((1 << n) - 1)
        tm_init = tm == _TM_INIT

        succ_list = []
        valid_list = []

        def assemble(rm_new, tm_new, prep_new, msgs_new):
            return jnp.concatenate(
                [
                    rm_new.astype(jnp.uint32),
                    tm_new.astype(jnp.uint32)[:, None],
                    prep_new.astype(jnp.uint32)[:, None],
                    msgs_new.astype(jnp.uint32)[:, None],
                ],
                axis=1,
            )

        # TmCommit (ref: 2pc.rs:73-75, 104-107)
        succ_list.append(
            assemble(rm, jnp.full(B, _TM_COMMITTED), prepared_mask, msgs | commit_bit)
        )
        valid_list.append(tm_init & all_prepared)
        # TmAbort (ref: 2pc.rs:76-78, 108-111)
        succ_list.append(
            assemble(rm, jnp.full(B, _TM_ABORTED), prepared_mask, msgs | abort_bit)
        )
        valid_list.append(tm_init)

        for i in range(n):
            rm_bit = jnp.uint32(1 << i)
            rm_i = rm[:, i]
            one_hot = jnp.arange(n) == i  # [n]

            def set_rm(value):
                return jnp.where(one_hot[None, :], jnp.uint32(value), rm)

            # TmRcvPrepared(i) (ref: 2pc.rs:80-82, 101-103)
            succ_list.append(assemble(rm, tm, prepared_mask | rm_bit, msgs))
            valid_list.append(tm_init & ((msgs & rm_bit) != 0))
            # RmPrepare(i) (ref: 2pc.rs:83-85, 112-115)
            succ_list.append(
                assemble(set_rm(_PREPARED), tm, prepared_mask, msgs | rm_bit)
            )
            valid_list.append(rm_i == _WORKING)
            # RmChooseToAbort(i) (ref: 2pc.rs:86-88, 116-118)
            succ_list.append(assemble(set_rm(_ABORTED), tm, prepared_mask, msgs))
            valid_list.append(rm_i == _WORKING)
            # RmRcvCommitMsg(i) (ref: 2pc.rs:89-91, 119-121)
            succ_list.append(assemble(set_rm(_COMMITTED), tm, prepared_mask, msgs))
            valid_list.append((msgs & commit_bit) != 0)
            # RmRcvAbortMsg(i) (ref: 2pc.rs:92-94, 122-124)
            succ_list.append(assemble(set_rm(_ABORTED), tm, prepared_mask, msgs))
            valid_list.append((msgs & abort_bit) != 0)

        succs = jnp.stack(succ_list, axis=1)  # [B, A, L]
        valid = jnp.stack(valid_list, axis=1)  # [B, A]
        return succs, valid

    def properties(self):
        n = self.rm_count

        def rm_all(states, value):
            return jnp.all(states[:, :n] == jnp.uint32(value), axis=1)

        return [
            TensorProperty.sometimes(
                "abort agreement", lambda m, s: rm_all(s, _ABORTED)
            ),
            TensorProperty.sometimes(
                "commit agreement", lambda m, s: rm_all(s, _COMMITTED)
            ),
            TensorProperty.always(
                "consistent",
                lambda m, s: ~(
                    jnp.any(s[:, :n] == jnp.uint32(_ABORTED), axis=1)
                    & jnp.any(s[:, :n] == jnp.uint32(_COMMITTED), axis=1)
                ),
            ),
        ]

    def _representative(self, states):
        """Canonicalize under RM permutation by stable-sorting RMs on their
        FULL per-RM key (state value, prepared bit, in-flight message bit) and
        permuting the satellite bits to match.

        Using the full key makes this a true orbit invariant, so the reduced
        count is deterministic and traversal-order-independent: 8,832 → 314 at
        5 RMs. The reference sorts on the state value alone, which splits
        orbits on satellite-bit ties and yields the weaker, DFS-order-dependent
        665 (ref: examples/2pc.rs:163-168); the host checker reproduces that
        behavior for parity, while the device models take the stronger
        reduction (cross-validated against host DFS with the same full-key
        canonicalization)."""
        n = self.rm_count
        rm = states[:, :n]
        prepared_mask = states[:, n + 1]
        msgs = states[:, n + 2]
        lanes = jnp.arange(n, dtype=jnp.uint32)
        prep_bits = (prepared_mask[:, None] >> lanes) & jnp.uint32(1)
        msg_bits = (msgs[:, None] >> lanes) & jnp.uint32(1)
        keys = rm * jnp.uint32(4) + prep_bits * jnp.uint32(2) + msg_bits
        return self._permute_rms(states, keys)

    def _representative_value_sort(self, states):
        """The reference's value-only sort (ref: examples/2pc.rs:163-168 via
        src/checker/rewrite_plan.rs:81-107): RMs sort on their state value
        alone, ties broken by original index (stable). Satellite-bit ties
        split orbits, so the reduced count depends on traversal order —
        opt-in for reference-golden parity (2PC-5 = 665 under DFS order),
        not the device default."""
        return self._permute_rms(states, states[:, : self.rm_count])

    def _permute_rms(self, states, keys):
        """Apply the RM permutation given per-RM sort keys: sort RM lanes and
        permute the prepared/message bit positions to match."""
        from .symmetry import gather_entities, permute_mask_bits, stable_argsort

        n = self.rm_count
        rm = states[:, :n]
        prepared_mask = states[:, n + 1]
        msgs = states[:, n + 2]
        perm = stable_argsort(keys)
        rm_new = gather_entities(rm, perm)
        prep_new = permute_mask_bits(prepared_mask, perm)
        rm_bits_new = permute_mask_bits(msgs, perm)
        ctl_bits = msgs & jnp.uint32(0b11 << n)  # commit/abort: not per-RM
        return jnp.concatenate(
            [
                rm_new,
                states[:, n : n + 1],
                prep_new[:, None],
                (rm_bits_new | ctl_bits)[:, None],
            ],
            axis=1,
        ).astype(jnp.uint32)

    def decode(self, row):
        n = self.rm_count
        names = {0: "working", 1: "prepared", 2: "committed", 3: "aborted"}
        tm_names = {0: "init", 1: "committed", 2: "aborted"}
        msgs = int(row[n + 2])
        msg_set = {f"prepared({i})" for i in range(n) if msgs & (1 << i)}
        if msgs & (1 << n):
            msg_set.add("commit")
        if msgs & (1 << (n + 1)):
            msg_set.add("abort")
        return (
            tuple(names[int(x)] for x in row[:n]),
            tm_names[int(row[n])],
            int(row[n + 1]),
            frozenset(msg_set),
        )

    def action_label(self, row, action_index):
        if action_index == 0:
            return "tm_commit"
        if action_index == 1:
            return "tm_abort"
        i, kind = divmod(action_index - 2, 5)
        return (
            ["tm_rcv_prepared", "rm_prepare", "rm_choose_abort",
             "rm_rcv_commit", "rm_rcv_abort"][kind],
            i,
        )


# -- increment (shared-memory interleaving / data-race demo) -------------------


@dataclass
class TensorIncrement(TensorModel):
    """Lost-update race demo (ref: examples/increment.rs:108-202),
    tensor-encoded. Lanes: [i, t0, pc0, t1, pc1, ...]; one action slot per
    thread (each thread has at most one enabled step: read at pc=1, write at
    pc=2). Goldens with 2 threads: 13 states, 8 under symmetry
    (ref: examples/increment.rs:32-105).

    The "fin" property (ALWAYS sum(pc==3) == i) is violated by the race; an
    undiscoverable `sometimes` property forces full enumeration when needed,
    mirroring the host test strategy.
    """

    thread_count: int
    symmetry: bool = False
    full_enumeration: bool = False  # add an unfindable sometimes property

    def __post_init__(self):
        self.lanes = 1 + 2 * self.thread_count
        self.max_actions = self.thread_count
        if self.symmetry:
            self.representative = self._representative

    def init_states(self):
        row = [0] + [0, 1] * self.thread_count
        return jnp.asarray([row], dtype=jnp.uint32)

    def expand(self, states):
        i = states[:, 0]
        succ_list, valid_list = [], []
        for tid in range(self.thread_count):
            t = states[:, 1 + 2 * tid]
            pc = states[:, 2 + 2 * tid]
            is_read = pc == 1
            is_write = pc == 2
            # read: t <- i, pc <- 2;  write: i <- t + 1, pc <- 3.
            new_i = jnp.where(is_write, t + 1, i)
            new_t = jnp.where(is_read, i, t)
            new_pc = jnp.where(is_read, 2, jnp.where(is_write, 3, pc))
            cols = [new_i]
            for o in range(self.thread_count):
                if o == tid:
                    cols += [new_t, new_pc]
                else:
                    cols += [states[:, 1 + 2 * o], states[:, 2 + 2 * o]]
            succ_list.append(jnp.stack(cols, axis=1))
            valid_list.append(is_read | is_write)
        succs = jnp.stack(succ_list, axis=1).astype(jnp.uint32)
        valid = jnp.stack(valid_list, axis=1)
        return succs, valid

    def _representative(self, states):
        """Sort per-thread (t, pc) pairs — the device analogue of the host
        IncrementState.representative (13 → 8 at 2 threads)."""
        from .symmetry import gather_entities, stable_argsort

        n = self.thread_count
        t = states[:, 1::2]
        pc = states[:, 2::2]
        # Key order matches the host's sorted((t, pc)) tuples.
        perm = stable_argsort(t * jnp.uint32(8) + pc)
        t_new = gather_entities(t, perm)
        pc_new = gather_entities(pc, perm)
        out = [states[:, 0:1]]
        for k in range(n):
            out += [t_new[:, k : k + 1], pc_new[:, k : k + 1]]
        return jnp.concatenate(out, axis=1).astype(jnp.uint32)

    def properties(self):
        n = self.thread_count

        def fin(model, states):
            done = jnp.stack(
                [states[:, 2 + 2 * t] == 3 for t in range(n)], axis=1
            ).sum(axis=1)
            return done == states[:, 0]

        props = [TensorProperty.always("fin", fin)]
        if self.full_enumeration:
            props.append(
                TensorProperty.sometimes(
                    "unreachable",
                    lambda m, s: jnp.zeros(s.shape[0], dtype=bool),
                )
            )
        return props

    def decode(self, row):
        n = self.thread_count
        return (
            int(row[0]),
            tuple((int(row[1 + 2 * t]), int(row[2 + 2 * t])) for t in range(n)),
        )

    def action_label(self, row, action_index):
        pc = int(row[2 + 2 * action_index])
        return ("read" if pc == 1 else "write", action_index)


@dataclass
class TensorIncrementLock(TensorModel):
    """Lock-fixed increment (ref: examples/increment_lock.rs), tensor-encoded.
    Lanes: [i, lock, t0, pc0, t1, pc1, ...]; one action slot per thread (each
    thread has at most one enabled step: lock at pc=0, read at pc=1, write at
    pc=2, release at pc=3).

    Device symmetry sorts the per-thread (t, pc) pairs — identical to the
    host representative (``tuple(sorted(s))``), and since that pair IS the
    entire per-entity state there are no satellite-bit ties to split: the
    reduced counts match the host ``check-sym`` goldens exactly (contrast the
    2PC case in tensor/symmetry.py's COUNT CONTRACT)."""

    thread_count: int
    symmetry: bool = False

    def __post_init__(self):
        self.lanes = 2 + 2 * self.thread_count
        self.max_actions = self.thread_count
        if self.symmetry:
            self.representative = self._representative

    def init_states(self):
        return jnp.asarray(
            [[0, 0] + [0, 0] * self.thread_count], dtype=jnp.uint32
        )

    def expand(self, states):
        i = states[:, 0]
        lock = states[:, 1]
        succ_list, valid_list = [], []
        for tid in range(self.thread_count):
            t = states[:, 2 + 2 * tid]
            pc = states[:, 3 + 2 * tid]
            can_lock = (pc == 0) & (lock == 0)
            is_read = pc == 1
            is_write = pc == 2
            can_rel = (pc == 3) & (lock == 1)
            new_i = jnp.where(is_write, t + 1, i)
            new_lock = jnp.where(
                can_lock, 1, jnp.where(can_rel, 0, lock)
            ).astype(jnp.uint32)
            new_t = jnp.where(is_read, i, t)
            new_pc = jnp.where(
                can_lock,
                1,
                jnp.where(
                    is_read, 2, jnp.where(is_write, 3, jnp.where(can_rel, 4, pc))
                ),
            ).astype(jnp.uint32)
            cols = [new_i, new_lock]
            for o in range(self.thread_count):
                if o == tid:
                    cols += [new_t, new_pc]
                else:
                    cols += [states[:, 2 + 2 * o], states[:, 3 + 2 * o]]
            succ_list.append(jnp.stack(cols, axis=1))
            valid_list.append(can_lock | is_read | is_write | can_rel)
        succs = jnp.stack(succ_list, axis=1).astype(jnp.uint32)
        valid = jnp.stack(valid_list, axis=1)
        return succs, valid

    def _representative(self, states):
        from .symmetry import gather_entities, stable_argsort

        t = states[:, 2::2]
        pc = states[:, 3::2]
        # Key order matches the host's sorted((t, pc)) tuples (t <= threads,
        # pc <= 4, so t*8+pc is collision-free and order-preserving).
        perm = stable_argsort(t * jnp.uint32(8) + pc)
        t_new = gather_entities(t, perm)
        pc_new = gather_entities(pc, perm)
        out = [states[:, 0:1], states[:, 1:2]]
        for k in range(self.thread_count):
            out += [t_new[:, k : k + 1], pc_new[:, k : k + 1]]
        return jnp.concatenate(out, axis=1).astype(jnp.uint32)

    def properties(self):
        n = self.thread_count

        def fin(model, states):
            done = jnp.stack(
                [states[:, 3 + 2 * t] >= 3 for t in range(n)], axis=1
            ).sum(axis=1)
            return done == states[:, 0]

        def mutex(model, states):
            held = jnp.stack(
                [
                    (states[:, 3 + 2 * t] >= 1) & (states[:, 3 + 2 * t] < 4)
                    for t in range(n)
                ],
                axis=1,
            ).sum(axis=1)
            return held <= 1

        return [
            TensorProperty.always("fin", fin),
            TensorProperty.always("mutex", mutex),
        ]

    def decode(self, row):
        n = self.thread_count
        return (
            int(row[0]),
            bool(row[1]),
            tuple((int(row[2 + 2 * t]), int(row[3 + 2 * t])) for t in range(n)),
        )

    def action_label(self, row, action_index):
        pc = int(row[3 + 2 * action_index])
        return (
            {0: "lock", 1: "read", 2: "write", 3: "release"}.get(pc, "?"),
            action_index,
        )


# -- Raft leader election ------------------------------------------------------

# Server roles (one lane each).
_FOLLOWER, _CANDIDATE, _LEADER = 0, 1, 2


@dataclass
class TensorRaft(TensorModel):
    """Raft leader election (Ongaro & Ousterhout §5.2), tensor-encoded — the
    model-zoo workload built FOR the device simulation engine: terms are
    bounded by `max_term`, so the space is finite but grows so fast with
    `server_count`/`max_term` that the exhaustive engines only finish the
    small configs (the goldens), while random walks cover the large ones.

    Lanes (grouped): [term[0..n], role[0..n], voted[0..n]] — per server its
    current term, role (follower/candidate/leader), and vote in its current
    term (0 = none, k+1 = server k). Message passing is collapsed into
    direct peer-state actions (the classic shared-memory reduction of the
    election protocol — votes are granted only for a strictly newer term,
    so each server votes at most once per term and two leaders can never
    share a term).

    Actions (static slots):
      [0, n)            timeout(i):  non-leader i starts an election —
                        term+1, candidate, votes for itself
      [n, 2n)           win(i):      candidate i with a strict majority of
                        same-term votes becomes leader
      [2n, 2n + n(n-1)) vote(i<-j):  j grants its vote to candidate i
                        (only when term_j < term_i; j adopts the term)
      [.., + n(n-1))    beat(i->j):  leader i brings j to its term (j
                        follows, vote cleared — it never voted in that
                        term)

    Properties: "election safety" (ALWAYS — no two leaders share a term),
    "leader elected" (EVENTUALLY — split-vote walks that exhaust max_term
    without a leader are genuine counterexamples: Raft's liveness needs
    randomized timeouts the adversarial scheduler doesn't grant), and
    "can elect" (SOMETIMES — the positive witness)."""

    server_count: int = 3
    max_term: int = 3

    def __post_init__(self):
        n = self.server_count
        self.lanes = 3 * n
        self.max_actions = 2 * n + 2 * n * (n - 1)

    def init_states(self):
        return jnp.zeros((1, self.lanes), dtype=jnp.uint32)

    def _split(self, states):
        n = self.server_count
        return states[:, :n], states[:, n : 2 * n], states[:, 2 * n :]

    def expand(self, states):
        n = self.server_count
        terms, roles, voted = self._split(states)
        succs, valids = [], []

        def build(t, r, v, valid):
            succs.append(jnp.concatenate([t, r, v], axis=1))
            valids.append(valid)

        for i in range(n):
            valid = (roles[:, i] != _LEADER) & (
                terms[:, i] < jnp.uint32(self.max_term)
            )
            build(
                terms.at[:, i].set(terms[:, i] + 1),
                roles.at[:, i].set(_CANDIDATE),
                voted.at[:, i].set(i + 1),
                valid,
            )
        for i in range(n):
            votes = (
                (terms == terms[:, i : i + 1]) & (voted == jnp.uint32(i + 1))
            ).sum(axis=1)
            valid = (roles[:, i] == _CANDIDATE) & (votes * 2 > n)
            build(terms, roles.at[:, i].set(_LEADER), voted, valid)
        for i in range(n):
            for j in range(n):
                if i == j:
                    continue
                valid = (roles[:, i] == _CANDIDATE) & (
                    terms[:, j] < terms[:, i]
                )
                build(
                    terms.at[:, j].set(terms[:, i]),
                    roles.at[:, j].set(_FOLLOWER),
                    voted.at[:, j].set(i + 1),
                    valid,
                )
        for i in range(n):
            for j in range(n):
                if i == j:
                    continue
                valid = (roles[:, i] == _LEADER) & (
                    terms[:, j] < terms[:, i]
                )
                build(
                    terms.at[:, j].set(terms[:, i]),
                    roles.at[:, j].set(_FOLLOWER),
                    voted.at[:, j].set(0),
                    valid,
                )
        return (
            jnp.stack(succs, axis=1).astype(jnp.uint32),
            jnp.stack(valids, axis=1),
        )

    def properties(self):
        n = self.server_count

        def safety(model, states):
            terms, roles, _v = model._split(states)
            bad = jnp.zeros(states.shape[0], dtype=bool)
            for i in range(n):
                for j in range(i + 1, n):
                    bad = bad | (
                        (roles[:, i] == _LEADER)
                        & (roles[:, j] == _LEADER)
                        & (terms[:, i] == terms[:, j])
                    )
            return ~bad

        def has_leader(model, states):
            _t, roles, _v = model._split(states)
            return (roles == _LEADER).any(axis=1)

        return [
            TensorProperty.always("election safety", safety),
            TensorProperty.eventually("leader elected", has_leader),
            TensorProperty.sometimes("can elect", has_leader),
        ]

    def decode(self, row):
        n = self.server_count
        role = {_FOLLOWER: "F", _CANDIDATE: "C", _LEADER: "L"}
        return tuple(
            (int(row[i]), role[int(row[n + i])], int(row[2 * n + i]) - 1)
            for i in range(n)
        )

    def action_label(self, row, action_index):
        n = self.server_count
        a = action_index
        if a < n:
            return f"timeout({a})"
        if a < 2 * n:
            return f"win({a - n})"
        a -= 2 * n
        pairs = [(i, j) for i in range(n) for j in range(n) if i != j]
        if a < n * (n - 1):
            i, j = pairs[a]
            return f"vote({i}<-{j})"
        i, j = pairs[a - n * (n - 1)]
        return f"beat({i}->{j})"
