"""Host-Model adapter over a `TensorModel`: the bridge that lets device
workloads use every host-side facility — the Explorer web UI (which re-executes
states on demand, ref: src/checker/explorer.rs:224-320), the on-demand checker,
host BFS/DFS for cross-validation, and visitor-driven exact state-set
assertions (ref: src/checker/visitor.rs:40-111).

States on the host side are the encoded uint32 rows as plain tuples (hashable
and stably-encodable); `actions` are the valid action-slot labels from
`TensorModel.action_label`, and each expansion is a 1-row device `expand`
call — interactive-browsing sized, by design. `format_state` decodes rows via
`TensorModel.decode`, so the Explorer shows human-readable states, not lane
dumps.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.model import Property
from .model import TensorModel


class TensorModelAdapter:
    """`Model`-protocol view of a `TensorModel` (duck-typed like every host
    model; `Model` is a protocol, not a required base)."""

    def __init__(self, tm: TensorModel):
        self.tensor_model = tm

        class Row(tuple):
            """Encoded state row whose repr is the DECODED state, so the
            Explorer and reports show protocol-level values, not u32 lanes.
            A tuple subclass keeps host fingerprinting/identity unchanged
            (stable_encode treats it as a tuple)."""

            __slots__ = ()

            def __repr__(row) -> str:  # noqa: N805 — row, not self
                return repr(tm.decode(np.asarray(row, dtype=np.uint32)))

        self._row = Row
        # Per-row expansion memo: the host checker protocol calls
        # actions(s) and then next_state(s, a) for EACH action — without
        # the memo that is (1 + n_actions) eager single-row device expands
        # per state, and the eager jax dispatch overhead dominates host
        # cross-validation runs (~8x on the 2pc-3 adapter BFS). Bounded for
        # long Explorer sessions; cleared wholesale when full (re-expanding
        # is always correct).
        self._expand_memo: dict = {}

    _EXPAND_MEMO_MAX = 1 << 16

    # -- expansion -------------------------------------------------------------

    def _expand_row(self, row):
        tm = self.tensor_model
        batch = jnp.asarray(np.asarray(row, dtype=np.uint32)[None])
        succs, valid = tm.expand(batch)
        in_bounds = tm.within_boundary(succs[0])
        return np.asarray(succs)[0], np.asarray(valid)[0] & np.asarray(
            in_bounds
        )

    def _expand_state(self, state):
        key = tuple(state)
        got = self._expand_memo.get(key)
        if got is None:
            if len(self._expand_memo) >= self._EXPAND_MEMO_MAX:
                self._expand_memo.clear()
            got = self._expand_row(np.asarray(state, dtype=np.uint32))
            self._expand_memo[key] = got
        return got

    def init_states(self) -> list:
        rows = np.asarray(self.tensor_model.init_states(), dtype=np.uint32)
        return [self._row(int(x) for x in r) for r in rows]

    def actions(self, state, actions: list) -> None:
        tm = self.tensor_model
        row = np.asarray(state, dtype=np.uint32)
        _succs, valid = self._expand_state(state)
        for a in range(tm.max_actions):
            if valid[a]:
                actions.append(tm.action_label(row, a))

    def next_state(self, state, action):
        tm = self.tensor_model
        row = np.asarray(state, dtype=np.uint32)
        succs, valid = self._expand_state(state)
        for a in range(tm.max_actions):
            if valid[a] and tm.action_label(row, a) == action:
                return self._row(int(x) for x in succs[a])
        return None

    def next_steps(self, state) -> list:
        """One device expand per state (the Model-protocol default would do
        one per action; the memo reduces the checker's actions+next_state
        protocol to one as well)."""
        tm = self.tensor_model
        row = np.asarray(state, dtype=np.uint32)
        succs, valid = self._expand_state(state)
        return [
            (tm.action_label(row, a), self._row(int(x) for x in succs[a]))
            for a in range(tm.max_actions)
            if valid[a]
        ]

    def next_states(self, state) -> list:
        return [ns for _, ns in self.next_steps(state)]

    # -- properties / boundary -------------------------------------------------

    def properties(self) -> list[Property]:
        def host_cond(tp):
            def cond(_model, state):
                batch = jnp.asarray(np.asarray(state, dtype=np.uint32)[None])
                return bool(
                    np.asarray(tp.condition(self.tensor_model, batch))[0]
                )

            return cond

        return [
            Property(p.expectation, p.name, host_cond(p))
            for p in self.tensor_model.properties()
        ]

    def within_boundary(self, state) -> bool:
        # srlint: host-ok host-side explorer adapter (single-state path), never traced
        batch = jnp.asarray(np.asarray(state, dtype=np.uint32)[None])
        # srlint: host-ok host-side explorer adapter (single-state path), never traced
        return bool(np.asarray(self.tensor_model.within_boundary(batch))[0])

    # -- display ---------------------------------------------------------------

    def format_action(self, action) -> str:
        return self.tensor_model.format_action(action)

    def format_state(self, state) -> str:
        return repr(self.tensor_model.decode(np.asarray(state, np.uint32)))

    def format_step(self, last_state, action):
        return None

    def as_svg(self, path):
        return None

    def checker(self):
        from ..checker.builder import CheckerBuilder

        return CheckerBuilder(self)


def as_host_model(tm: TensorModel) -> TensorModelAdapter:
    """Wrap a `TensorModel` so host checkers, visitors, and the Explorer can
    drive it: `as_host_model(tm).checker().serve("localhost:3000")`."""
    return TensorModelAdapter(tm)
