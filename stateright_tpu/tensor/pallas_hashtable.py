"""Pallas TPU prototype of the visited-set insert (SURVEY §7's prescribed
"open-addressing hash table in HBM updated by a Pallas kernel", replacing the
reference's sharded `DashMap` — ref: src/checker/bfs.rs:29-31).

This is the measured alternative to the pure-XLA scatter-max insert in
`tensor/hashtable.py` (VERDICT r3 next #5). The two designs answer the same
question — batched insert-if-absent of 64-bit fingerprints — with opposite
hardware bets:

- XLA design: keep the batch parallel; resolve claim races with phased
  scatter-max over the whole table in HBM. Every probe round re-gathers and
  re-scatters the full still-unresolved batch (HBM-latency bound).
- Pallas design (here): make the table RANDOM-ACCESS-CHEAP instead. The
  table is split into partitions sized to fit VMEM; one XLA sort routes each
  key to its partition; the kernel then pulls a whole partition into VMEM,
  probes/claims ALL its keys serially on the scalar core (VMEM random access
  is ~register-speed next to HBM), and writes the partition back.
  Serialization within a partition makes insert-if-absent EXACT — no
  scatter-max phases, no phase-3 arena: a batch duplicate simply hits the
  slot its twin claimed one iteration earlier.

Hash-bit layout (disjoint, so routing cannot skew in-partition occupancy):
partition id = hi mod P (low bits); in-partition bucket = (hi div P) mod
(V/8). Compare `tensor/hashtable.py` (global bucket = hi mod n_buckets) and
the sharded engine's chip owner (lo mod n_chips) — every level keys off
independent fingerprint bits.

Capacity contract: a partition receiving more than W = route_factor *
ceil(B/P) keys this batch spills the excess — spilled lanes are reported
(`spilled` mask, never silently dropped) and the caller retries them (the
engines re-offer unfinished lanes the same way on table overflow). With
uniform fingerprints P(spill) is negligible for route_factor >= 4.

Parity contract (tests/test_pallas_hashtable.py): for any batch sequence the
SET of stored fingerprints and the per-call `is_new` attributions match
`tensor/hashtable.py` exactly; a key's stored parent is one of the parents
offered for it by the call that inserted it (when one batch offers the same
key with different parents, WHICH lane wins differs between the designs —
the same insert race the reference tolerates in its DashMap,
ref: src/checker/bfs.rs:243). Slot LAYOUTS differ by design (bucket chains wrap within a partition here, globally there) — both
tables are only read through their own probe scheme and through `dump()`
(an order-free dict), so nothing downstream can observe the layout.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

BUCKET = 8


class PallasInsertResult(NamedTuple):
    t_lo: jnp.ndarray  # uint32[S]
    t_hi: jnp.ndarray  # uint32[S]
    p_lo: jnp.ndarray  # uint32[S]
    p_hi: jnp.ndarray  # uint32[S]
    is_new: jnp.ndarray  # bool[B] — inserted by this call
    spilled: jnp.ndarray  # bool[B] — not processed (route overflow); retry
    overflow: jnp.ndarray  # bool — some partition's bucket chains are full


def _make_kernel(V: int, W: int, P: int):
    """Kernel over one partition: serial probe/claim in VMEM."""
    from jax.experimental import pallas as pl

    n_buckets = V // BUCKET

    def kernel(
        count_ref,  # int32[1, 1]   keys routed to this partition
        tl_ref,  # uint32[V]
        th_ref,
        pl_ref,
        ph_ref,
        klo_ref,  # uint32[1, W]
        khi_ref,
        plo_ref,
        phi_ref,
        tl_out,  # uint32[V]
        th_out,
        pl_out,
        ph_out,
        new_ref,  # int32[1, W]
        ovf_ref,  # int32[1, 1]
    ):
        tl_out[...] = tl_ref[...]
        th_out[...] = th_ref[...]
        pl_out[...] = pl_ref[...]
        ph_out[...] = ph_ref[...]
        new_ref[...] = jnp.zeros_like(new_ref)
        ovf_ref[0, 0] = 0

        def per_key(i, _):
            lo = klo_ref[0, i]
            hi = khi_ref[0, i]
            b0 = ((hi // jnp.uint32(P)) % jnp.uint32(n_buckets)).astype(
                jnp.int32
            )

            def cond(carry):
                off, done, _slot, _new = carry
                return (~done) & (off < n_buckets)

            def probe(carry):
                off, done, slot, found_new = carry
                b = (b0 + off) % n_buckets
                base = b * BUCKET
                rows_lo = tl_out[pl.ds(base, BUCKET)]
                rows_hi = th_out[pl.ds(base, BUCKET)]
                hit_j = (rows_lo == lo) & (rows_hi == hi)
                hit = jnp.any(hit_j)
                free_j = rows_lo == 0
                has_free = jnp.any(free_j)
                j_hit = jnp.argmax(hit_j).astype(jnp.int32)
                j_free = jnp.argmax(free_j).astype(jnp.int32)
                slot = jnp.where(
                    hit,
                    base + j_hit,
                    jnp.where(has_free, base + j_free, slot),
                )
                return off + 1, hit | has_free, slot, (~hit) & has_free

            _off, done, slot, found_new = jax.lax.while_loop(
                cond, probe, (jnp.int32(0), False, jnp.int32(0), False)
            )

            @pl.when(found_new)
            def _claim():
                tl_out[slot] = lo
                th_out[slot] = hi
                pl_out[slot] = plo_ref[0, i]
                ph_out[slot] = phi_ref[0, i]
                new_ref[0, i] = 1

            @pl.when(~done)
            def _chain_full():
                ovf_ref[0, 0] = 1

            return 0

        jax.lax.fori_loop(0, count_ref[0, 0], per_key, 0)

    return kernel


@partial(
    jax.jit,
    static_argnames=("n_partitions", "route_factor", "interpret"),
    donate_argnums=(0, 1, 2, 3),
)
def pallas_insert(
    t_lo,
    t_hi,
    p_lo,
    p_hi,
    lo,
    hi,
    parent_lo,
    parent_hi,
    active,
    *,
    n_partitions: int = 64,
    route_factor: int = 4,
    interpret: bool = False,
) -> PallasInsertResult:
    """Batched insert-if-absent via the partitioned-VMEM Pallas kernel.

    XLA routing pre-pass: one stable sort of the batch by partition id plus
    a searchsorted yields contiguous per-partition segments; each segment's
    first W lanes are scatter-packed into dense [P, W] buffers (W =
    route_factor * ceil(B/P)); the rest spill (see module docstring).
    """
    from jax.experimental import pallas as pl

    S = t_lo.shape[0]
    B = lo.shape[0]
    P = n_partitions
    if S % (P * BUCKET):
        raise ValueError(
            f"table size {S} must split into {P} BUCKET-aligned partitions"
        )
    V = S // P
    W = route_factor * -(-B // P)

    pid = jnp.where(active, (hi % jnp.uint32(P)).astype(jnp.int32), P)
    order = jnp.argsort(pid, stable=True)  # lane ids grouped by pid
    pid_sorted = pid[order]
    seg_start = jnp.searchsorted(
        pid_sorted, jnp.arange(P + 1, dtype=pid_sorted.dtype)
    )
    counts = jnp.minimum(seg_start[1:] - seg_start[:-1], W).astype(jnp.int32)

    rank = (
        jnp.arange(B, dtype=jnp.int32)
        - seg_start[jnp.clip(pid_sorted, 0, P - 1)].astype(jnp.int32)
    )
    in_row = (pid_sorted < P) & (rank < W)
    flat_pos = jnp.where(in_row, pid_sorted * W + rank, P * W)

    def route(x):
        return (
            jnp.zeros((P * W,), x.dtype)
            .at[flat_pos]
            .set(x[order], mode="drop")
            .reshape(P, W)
        )

    klo, khi, plo, phi = map(route, (lo, hi, parent_lo, parent_hi))

    part = pl.BlockSpec((V,), lambda p: (p,))
    row = pl.BlockSpec((1, W), lambda p: (p, 0))
    one = pl.BlockSpec((1, 1), lambda p: (p, 0))

    tl, th, pll, phh, new_rows, ovf = pl.pallas_call(
        _make_kernel(V, W, P),
        grid=(P,),
        in_specs=[one, part, part, part, part, row, row, row, row],
        out_specs=[part, part, part, part, row, one],
        out_shape=[
            jax.ShapeDtypeStruct((S,), jnp.uint32),
            jax.ShapeDtypeStruct((S,), jnp.uint32),
            jax.ShapeDtypeStruct((S,), jnp.uint32),
            jax.ShapeDtypeStruct((S,), jnp.uint32),
            jax.ShapeDtypeStruct((P, W), jnp.int32),
            jax.ShapeDtypeStruct((P, 1), jnp.int32),
        ],
        interpret=interpret,
    )(
        counts.reshape(P, 1),
        t_lo,
        t_hi,
        p_lo,
        p_hi,
        klo,
        khi,
        plo,
        phi,
    )

    # Un-route is_new back to lane order: sorted lane k's verdict sits at
    # flat_pos[k]; invert the sort with one scatter.
    gathered = (
        new_rows.reshape(-1)
        .at[flat_pos]
        .get(mode="fill", fill_value=0)
        .astype(bool)
    )
    is_new = jnp.zeros(B, bool).at[order].set(gathered)
    spilled = jnp.zeros(B, bool).at[order].set(active[order] & ~in_row)
    return PallasInsertResult(
        tl, th, pll, phh, is_new, spilled, ovf.astype(bool).any()
    )


class PallasHashTable:
    """Host-side handle mirroring `tensor.hashtable.HashTable`, backed by the
    partitioned Pallas insert. `insert` retries spilled lanes internally so
    the caller-visible contract (every active lane resolved, exactly one
    is_new per distinct new key) matches the XLA table exactly."""

    def __init__(
        self,
        log2_size: int,
        n_partitions: int = 64,
        interpret: bool = False,
    ):
        self.log2_size = log2_size
        self.size = 1 << log2_size
        self.n_partitions = n_partitions
        self.interpret = interpret
        if self.size % (n_partitions * BUCKET):
            raise ValueError("table too small for the partition count")
        self.t_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.t_hi = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_hi = jnp.zeros(self.size, dtype=jnp.uint32)

    def insert(self, lo, hi, parent_lo, parent_hi, active):
        is_new = jnp.zeros(lo.shape[0], bool)
        pending = active
        overflow = jnp.asarray(False)
        while True:
            res = pallas_insert(
                self.t_lo,
                self.t_hi,
                self.p_lo,
                self.p_hi,
                lo,
                hi,
                parent_lo,
                parent_hi,
                pending,
                n_partitions=self.n_partitions,
                interpret=self.interpret,
            )
            self.t_lo, self.t_hi, self.p_lo, self.p_hi = res[:4]
            is_new = is_new | res.is_new
            overflow = overflow | res.overflow
            if not bool(res.spilled.any()):
                break
            pending = res.spilled
        return res._replace(is_new=is_new, spilled=res.spilled, overflow=overflow)

    def dump(self) -> dict:
        from .fingerprint import pack_fp

        t_lo = np.asarray(self.t_lo)
        nz = t_lo != 0
        keys = pack_fp(t_lo[nz], np.asarray(self.t_hi)[nz])
        parents = pack_fp(
            np.asarray(self.p_lo)[nz], np.asarray(self.p_hi)[nz]
        )
        return dict(zip(keys.tolist(), parents.tolist()))
