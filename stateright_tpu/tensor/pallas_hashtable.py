"""Pallas TPU prototype of the visited-set insert (SURVEY §7's prescribed
"open-addressing hash table in HBM updated by a Pallas kernel", replacing the
reference's sharded `DashMap` — ref: src/checker/bfs.rs:29-31).

This is the measured alternative to the pure-XLA scatter-max insert in
`tensor/hashtable.py` (VERDICT r3 next #5). The two designs answer the same
question — batched insert-if-absent of 64-bit fingerprints — with opposite
hardware bets:

- XLA design: keep the batch parallel; sort lanes by (bucket, key) so
  duplicates and same-bucket claimants are adjacent, then claim distinct
  free slots with race-free unique-indices scatters (see
  `tensor/hashtable.py` — its original phased scatter-max claim lost the
  round-4 silicon race and was replaced by the sort-claim form).
- Pallas design (here): make the table RANDOM-ACCESS-CHEAP instead. The
  table is split into partitions sized to fit VMEM; one XLA sort routes each
  key to its partition; the kernel then pulls a whole partition into VMEM,
  probes/claims ALL its keys serially on the scalar core (VMEM random access
  is ~register-speed next to HBM), and writes the partition back.
  Serialization within a partition makes insert-if-absent EXACT with no
  claim races at all: a batch duplicate simply hits the slot its twin
  claimed one iteration earlier.

TPU-tiling layout (the round-4 lesson: interpret mode does NOT check Mosaic's
lowering constraints — the first on-silicon run rejected (1,1)/(1,W) VMEM
blocks, so every block here is (8,128)-tile-aligned):

- a BUCKET is one full 128-lane VMEM row: tables are viewed as
  uint32[rows, 128]; a probe loads one row and resolves hit/free with a
  vector compare + lane-min, a claim writes the row back through a one-hot
  mask (no sub-row scatter);
- per-partition key/parent/verdict buffers are (W/128, 128) blocks with W a
  multiple of 1024, so the sublane dim stays divisible by 8;
- per-partition routed-key counts ride in SMEM as one whole-array (P, 1)
  ref indexed by program_id (Mosaic's block validator rejects blocked
  (1, 1) SMEM specs too);
- the chain-full (overflow) flag is folded into the per-key verdict code
  (0 = not new, 1 = inserted, 2 = chain full, 3 = inserted AND
  Bloom-summary-positive — the tiered store's fused suspect probe, see
  `_make_kernel`) — no awkward scalar output.

Hash-bit layout (disjoint, so routing cannot skew in-partition occupancy):
partition id = hi mod P (low bits); in-partition bucket row = (hi div P) mod
(V/128). Compare `tensor/hashtable.py` (global bucket = hi mod n_buckets)
and the sharded engine's chip owner (lo mod n_chips) — every level keys off
independent fingerprint bits.

Capacity contract: a partition receiving more than W keys this batch spills
the excess — spilled lanes are reported (`spilled` mask, never silently
dropped) and the caller retries them (the engines re-offer unfinished lanes
the same way on table overflow). W = route_factor * ceil(B/P) rounded up to
a multiple of 1024 (rounding only reduces spill probability). With uniform
fingerprints P(spill) is negligible for route_factor >= 4.

Parity contract (tests/test_pallas_hashtable.py): for any batch sequence the
SET of stored fingerprints and the per-call `is_new` attributions match
`tensor/hashtable.py` exactly; a key's stored parent is one of the parents
offered for it by the call that inserted it (when one batch offers the same
key with different parents, WHICH lane wins differs between the designs —
the same insert race the reference tolerates in its DashMap,
ref: src/checker/bfs.rs:243). Slot LAYOUTS differ by design (bucket chains
wrap within a partition here, globally there) — both tables are only read
through their own probe scheme and through `dump()` (an order-free dict), so
nothing downstream can observe the layout.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..faults.plan import maybe_fault

LANES = 128  # bucket width: one VMEM row
ROW_ALIGN = 1024  # 8 sublanes x 128 lanes — min tile-aligned 1D granularity

#: default partition count (capped by the table size so tiny test tables
#: still split into tile-aligned partitions — see pallas_partitions()).
DEFAULT_PARTITIONS = 64

#: bound on the spilled-lane re-offer loop (host handle and the engines'
#: in-trace lax.while_loop retry alike). Each round drains up to W keys per
#: partition, so B/W <= P/route_factor rounds suffice for any batch; lanes
#: still pending past the bound surface as `overflow` (the engines' existing
#: table-full abort), never a silent drop.
MAX_RETRY_ROUNDS = 16


def pallas_partitions(size: int) -> int:
    """The partition count the engines use for a table of `size` slots:
    DEFAULT_PARTITIONS, shrunk so every partition stays a whole number of
    ROW_ALIGN tiles (power-of-two sizes always divide exactly). Tables
    under ROW_ALIGN slots cannot be tiled at all — the engines reject
    insert_variant="pallas" below table_log2=10."""
    if size < ROW_ALIGN:
        raise ValueError(
            f"pallas table needs >= {ROW_ALIGN} slots (table_log2 >= 10); "
            f"got {size}"
        )
    return max(1, min(DEFAULT_PARTITIONS, size // ROW_ALIGN))


def default_interpret() -> bool:
    """Interpret mode off only on real TPU backends: CPU (tier-1) and any
    other backend run the kernel through the Pallas interpreter, which is
    what keeps the variant selectable — and parity-testable — off-silicon."""
    return jax.default_backend() != "tpu"


class PallasInsertResult(NamedTuple):
    t_lo: jnp.ndarray  # uint32[S]
    t_hi: jnp.ndarray  # uint32[S]
    p_lo: jnp.ndarray  # uint32[S]
    p_hi: jnp.ndarray  # uint32[S]
    is_new: jnp.ndarray  # bool[B] — inserted by this call
    spilled: jnp.ndarray  # bool[B] — not processed (route overflow); retry
    overflow: jnp.ndarray  # bool — some partition's bucket chains are full
    suspect: jnp.ndarray  # bool[B] — inserted AND Bloom-summary-positive
    #                       (always all-False without a summary operand)


def _make_kernel(V: int, W: int, P: int, summary_cfg=None):
    """Kernel over one partition: serial probe/claim of VMEM bucket rows.

    `summary_cfg=(summary_log2, hashes)` fuses the tiered store's Bloom
    probe (store/summary.py) into the same partition pass: the whole word
    array rides into VMEM once per partition, and each freshly-claimed key
    tests its k probe bits right where it was claimed — verdict 3 marks
    "inserted AND summary-positive" (a suspect), so the engines need no
    separate post-insert gather pass over the summary."""
    from jax.experimental import pallas as pl

    # Lazy import, matching the engines (the store package pulls in the
    # spill tier; the kernel only needs the hash-pair helper).
    from ..store.summary import _h1h2

    n_buckets = V // LANES  # bucket rows per partition

    def kernel(
        count_ref,  # int32[P, 1] whole array in SMEM (indexed by program_id)
        tl_ref,  # uint32[V/128, 128] table partition (aliased with *_out)
        th_ref,
        pl_ref,
        ph_ref,
        klo_ref,  # uint32[W/128, 128] routed keys
        khi_ref,
        plo_ref,
        phi_ref,
        *rest,  # [sum_ref?], tl_out, th_out, pl_out, ph_out, new_ref
    ):
        if summary_cfg is not None:
            sum_ref, tl_out, th_out, pl_out, ph_out, new_ref = rest
            slog2, khash = summary_cfg
        else:
            tl_out, th_out, pl_out, ph_out, new_ref = rest
        tl_out[...] = tl_ref[...]
        th_out[...] = th_ref[...]
        pl_out[...] = pl_ref[...]
        ph_out[...] = ph_ref[...]
        new_ref[...] = jnp.zeros_like(new_ref)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        miss = jnp.int32(LANES)  # lane-min sentinel: "no lane matched"

        def lane_pick(sel, row_u32):
            """Extract the single sel-lane of a (1,128) uint32 row as a
            scalar. Mosaic has no unsigned reductions, so sum the one-hot
            masked row as int32 (bit-exact: one nonzero lane) and bitcast
            back."""
            picked = jnp.where(sel, row_u32.astype(jnp.int32), 0)
            return jnp.sum(picked).astype(jnp.uint32)

        def per_key(i, _):
            # Mosaic forbids dynamic sub-row scalar access to VMEM (loads
            # AND stores must be lane-aligned): read the key by loading its
            # whole 128-lane row and reducing through a one-hot mask.
            r, c = i // LANES, i % LANES
            sel = lane == c
            lo = lane_pick(sel, klo_ref[pl.ds(r, 1), :])
            hi = lane_pick(sel, khi_ref[pl.ds(r, 1), :])
            b0 = ((hi // jnp.uint32(P)) % jnp.uint32(n_buckets)).astype(
                jnp.int32
            )

            def cond(carry):
                off, done, _row, _col, _new = carry
                return (~done) & (off < n_buckets)

            def probe(carry):
                off, done, row, col, _found_new = carry
                b = (b0 + off) % n_buckets
                rows_lo = tl_out[pl.ds(b, 1), :]
                rows_hi = th_out[pl.ds(b, 1), :]
                hit_m = (rows_lo == lo) & (rows_hi == hi)
                free_m = rows_lo == jnp.uint32(0)
                col_hit = jnp.min(jnp.where(hit_m, lane, miss))
                col_free = jnp.min(jnp.where(free_m, lane, miss))
                hit = col_hit < miss
                has_free = col_free < miss
                row = jnp.where(hit | has_free, b, row)
                col = jnp.where(
                    hit, col_hit, jnp.where(has_free, col_free, col)
                )
                return off + 1, hit | has_free, row, col, (~hit) & has_free

            _off, done, row, col, found_new = jax.lax.while_loop(
                cond,
                probe,
                (
                    jnp.int32(0),
                    jnp.bool_(False),
                    jnp.int32(0),
                    jnp.int32(0),
                    jnp.bool_(False),
                ),
            )

            @pl.when(found_new)
            def _claim():
                onehot = lane == col
                tl_out[pl.ds(row, 1), :] = jnp.where(
                    onehot, lo, tl_out[pl.ds(row, 1), :]
                )
                th_out[pl.ds(row, 1), :] = jnp.where(
                    onehot, hi, th_out[pl.ds(row, 1), :]
                )
                p_lo_v = lane_pick(sel, plo_ref[pl.ds(r, 1), :])
                p_hi_v = lane_pick(sel, phi_ref[pl.ds(r, 1), :])
                pl_out[pl.ds(row, 1), :] = jnp.where(
                    onehot, p_lo_v, pl_out[pl.ds(row, 1), :]
                )
                ph_out[pl.ds(row, 1), :] = jnp.where(
                    onehot, p_hi_v, ph_out[pl.ds(row, 1), :]
                )

            # Verdict writes go through the same one-hot masked row write as
            # the table claims — no dynamic sub-row scalar stores.
            verdict = jnp.where(
                found_new, jnp.int32(1), jnp.where(~done, jnp.int32(2), 0)
            )
            if summary_cfg is not None:
                # Fused Bloom probe (store/summary.py bit layout exactly):
                # a freshly-claimed key whose k probe bits are all set might
                # be a revisit of a spilled state — verdict 3 marks it a
                # SUSPECT in the same pass, instead of a separate
                # maybe_contains gather sweep after the insert. Word reads
                # use the same whole-row + one-hot reduction as the key
                # loads (no dynamic sub-row scalar access).
                smask = jnp.uint32((1 << slog2) - 1)
                h1, h2 = _h1h2(lo, hi)
                bloom_hit = jnp.bool_(True)
                for k in range(khash):
                    pos = (h1 + jnp.uint32(k) * h2) & smask
                    widx = (pos >> jnp.uint32(5)).astype(jnp.int32)
                    wsel = lane == (widx % LANES)
                    word = lane_pick(wsel, sum_ref[pl.ds(widx // LANES, 1), :])
                    bit = (word >> (pos & jnp.uint32(31))) & jnp.uint32(1)
                    bloom_hit = bloom_hit & (bit == jnp.uint32(1))
                verdict = jnp.where(
                    found_new & bloom_hit, jnp.int32(3), verdict
                )

            @pl.when(verdict > 0)
            def _record():
                new_ref[pl.ds(r, 1), :] = jnp.where(
                    sel, verdict, new_ref[pl.ds(r, 1), :]
                )

            return 0

        jax.lax.fori_loop(0, count_ref[pl.program_id(0), 0], per_key, 0)

    return kernel


def _pallas_insert(
    t_lo,
    t_hi,
    p_lo,
    p_hi,
    lo,
    hi,
    parent_lo,
    parent_hi,
    active,
    summary=None,
    *,
    n_partitions: int = DEFAULT_PARTITIONS,
    route_factor: int = 4,
    interpret: bool = False,
    summary_cfg=None,
) -> PallasInsertResult:
    """Batched insert-if-absent via the partitioned-VMEM Pallas kernel
    (pure/traceable — the engines inline it inside their jitted steps and
    while_loop retry carries; `pallas_insert` below is the jitted host
    entry).

    XLA routing pre-pass: one stable sort of the batch by partition id plus
    a searchsorted yields contiguous per-partition segments; each segment's
    first W lanes are scatter-packed into dense per-partition rows (W as in
    the module docstring); the rest spill and are retried by the caller.

    `summary` (uint32 Bloom words, with `summary_cfg=(summary_log2,
    hashes)`) fuses the tiered store's suspect probe into the partition
    pass — see `_make_kernel`; the result's `suspect` mask is then
    `is_new & maybe_contains(...)` bit-for-bit.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S = t_lo.shape[0]
    B = lo.shape[0]
    P = n_partitions
    if S % (P * ROW_ALIGN):
        raise ValueError(
            f"table size {S} must split into {P} partitions of a multiple "
            f"of {ROW_ALIGN} slots (TPU tile alignment)"
        )
    V = S // P
    W = -(-(route_factor * -(-B // P)) // ROW_ALIGN) * ROW_ALIGN

    pid = jnp.where(active, (hi % jnp.uint32(P)).astype(jnp.int32), P)
    order = jnp.argsort(pid, stable=True)  # lane ids grouped by pid
    pid_sorted = pid[order]
    seg_start = jnp.searchsorted(
        pid_sorted, jnp.arange(P + 1, dtype=pid_sorted.dtype)
    )
    counts = jnp.minimum(seg_start[1:] - seg_start[:-1], W).astype(jnp.int32)

    rank = (
        jnp.arange(B, dtype=jnp.int32)
        - seg_start[jnp.clip(pid_sorted, 0, P - 1)].astype(jnp.int32)
    )
    in_row = (pid_sorted < P) & (rank < W)
    flat_pos = jnp.where(in_row, pid_sorted * W + rank, P * W)

    def route(x):
        return (
            jnp.zeros((P * W,), x.dtype)
            .at[flat_pos]
            .set(x[order], mode="drop")
            .reshape(P * W // LANES, LANES)
        )

    klo, khi, plo, phi = map(route, (lo, hi, parent_lo, parent_hi))

    part = pl.BlockSpec((V // LANES, LANES), lambda p: (p, 0))
    row = pl.BlockSpec((W // LANES, LANES), lambda p: (p, 0))
    # Whole-array SMEM ref (this jax's Mosaic validator applies the
    # (8,128) block rule even to blocked SMEM specs, so no (1,1) blocks);
    # the kernel indexes it with program_id.
    smem_counts = pl.BlockSpec(memory_space=pltpu.SMEM)

    def as_rows(x):
        return x.reshape(S // LANES, LANES)

    in_specs = [smem_counts, part, part, part, part, row, row, row, row]
    operands = [
        counts.reshape(P, 1),
        as_rows(t_lo),
        as_rows(t_hi),
        as_rows(p_lo),
        as_rows(p_hi),
        klo,
        khi,
        plo,
        phi,
    ]
    if summary_cfg is not None:
        if summary is None:
            raise ValueError("summary_cfg given without a summary operand")
        # The whole word array rides into VMEM once per partition, padded
        # up to a tile-aligned row count (extra zero words are never
        # probed: positions are masked to 2^summary_log2 bits). 2^20 bits
        # is 128 KB — far inside the VMEM partition budget.
        SW = max(ROW_ALIGN, summary.shape[0])
        if summary.shape[0] < SW:
            summary = jnp.zeros(SW, jnp.uint32).at[: summary.shape[0]].set(
                summary
            )
        in_specs.append(
            pl.BlockSpec((SW // LANES, LANES), lambda p: (0, 0))
        )
        operands.append(summary.reshape(SW // LANES, LANES))

    tl, th, pll, phh, new_rows = pl.pallas_call(
        _make_kernel(V, W, P, summary_cfg),
        grid=(P,),
        in_specs=in_specs,
        out_specs=[part, part, part, part, row],
        out_shape=[
            jax.ShapeDtypeStruct((S // LANES, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((S // LANES, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((S // LANES, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((S // LANES, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((P * W // LANES, LANES), jnp.int32),
        ],
        input_output_aliases={1: 0, 2: 1, 3: 2, 4: 3},
        interpret=interpret,
    )(*operands)

    # Un-route verdicts back to lane order: sorted lane k's verdict sits at
    # flat_pos[k]; invert the sort with one scatter.
    verdicts = new_rows.reshape(-1)
    gathered = verdicts.at[flat_pos].get(mode="fill", fill_value=0)
    is_new = jnp.zeros(B, bool).at[order].set(
        (gathered == 1) | (gathered == 3)
    )
    suspect = jnp.zeros(B, bool).at[order].set(gathered == 3)
    spilled = jnp.zeros(B, bool).at[order].set(active[order] & ~in_row)
    return PallasInsertResult(
        tl.reshape(S),
        th.reshape(S),
        pll.reshape(S),
        phh.reshape(S),
        is_new,
        spilled,
        jnp.any(verdicts == 2),
        suspect,
    )


pallas_insert = partial(
    jax.jit,
    static_argnames=("n_partitions", "route_factor", "interpret", "summary_cfg"),
    donate_argnums=(0, 1, 2, 3),
)(_pallas_insert)


def make_engine_insert(
    summary_cfg=None,
    n_partitions: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """The engine-facing traced insert: same 9-arg signature / 6-tuple
    result as `hashtable._insert_impl` (10-arg / 7-tuple with the fused
    Bloom probe — see tensor/inserts.py), with the spilled-lane re-offer
    loop folded into the trace as a `lax.while_loop`, so the whole thing
    lives inside the engines' jitted steps and device-resident search
    loops. Lanes still pending after MAX_RETRY_ROUNDS fold into `overflow`
    — the engines' existing table-full abort path (checkpoint + regrow),
    never a silent drop.

    `n_partitions` defaults to `pallas_partitions(table size)` at trace
    time; `interpret` defaults to `default_interpret()` (on for every
    non-TPU backend, which is what makes the variant runnable — and parity
    -pinned — on the CPU tier-1 suite)."""

    def insert(
        t_lo, t_hi, p_lo, p_hi, lo, hi, parent_lo, parent_hi, active,
        summary=None,
    ):
        P = (
            n_partitions
            if n_partitions is not None
            else pallas_partitions(t_lo.shape[0])
        )
        interp = default_interpret() if interpret is None else interpret
        B = lo.shape[0]

        def cond(c):
            return jnp.any(c[4]) & (c[7] < MAX_RETRY_ROUNDS)

        def body(c):
            t_lo, t_hi, p_lo, p_hi, pending, is_new, sus, rounds, ovf = c
            res = _pallas_insert(
                t_lo, t_hi, p_lo, p_hi,
                lo, hi, parent_lo, parent_hi, pending,
                summary,
                n_partitions=P,
                interpret=interp,
                summary_cfg=summary_cfg,
            )
            return (
                *res[:4],
                res.spilled,
                is_new | res.is_new,
                sus | res.suspect,
                rounds + 1,
                ovf | res.overflow,
            )

        c = jax.lax.while_loop(
            cond,
            body,
            (
                t_lo, t_hi, p_lo, p_hi, active,
                jnp.zeros(B, bool), jnp.zeros(B, bool),
                jnp.int32(0), jnp.bool_(False),
            ),
        )
        # Retry exhaustion is an overflow: the pending lanes were offered
        # MAX_RETRY_ROUNDS times without draining.
        overflow = c[8] | jnp.any(c[4])
        if summary_cfg is not None:
            return c[0], c[1], c[2], c[3], c[5], c[6], overflow
        return c[0], c[1], c[2], c[3], c[5], overflow

    if summary_cfg is not None:
        # Marker the shared expand_insert dispatch keys on: this insert
        # takes the summary operand and returns the suspect mask itself.
        insert.fused_summary = True
    return insert


class PallasHashTable:
    """Host-side handle mirroring `tensor.hashtable.HashTable`, backed by the
    partitioned Pallas insert. `insert` retries spilled lanes internally so
    the caller-visible contract (every active lane resolved, exactly one
    is_new per distinct new key) matches the XLA table exactly."""

    def __init__(
        self,
        log2_size: int,
        n_partitions: Optional[int] = None,
        interpret: Optional[bool] = None,
    ):
        self.log2_size = log2_size
        self.size = 1 << log2_size
        self.n_partitions = (
            n_partitions
            if n_partitions is not None
            else pallas_partitions(self.size)
        )
        self.interpret = (
            interpret if interpret is not None else default_interpret()
        )
        if self.size % (self.n_partitions * ROW_ALIGN):
            raise ValueError(
                "table too small for the partition count: need size % "
                f"(n_partitions * {ROW_ALIGN}) == 0"
            )
        self.t_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.t_hi = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_hi = jnp.zeros(self.size, dtype=jnp.uint32)

    def insert(self, lo, hi, parent_lo, parent_hi, active):
        is_new = jnp.zeros(lo.shape[0], bool)
        pending = active
        overflow = jnp.asarray(False)
        rounds = 0
        while True:
            res = pallas_insert(
                self.t_lo,
                self.t_hi,
                self.p_lo,
                self.p_hi,
                lo,
                hi,
                parent_lo,
                parent_hi,
                pending,
                n_partitions=self.n_partitions,
                interpret=self.interpret,
            )
            self.t_lo, self.t_hi, self.p_lo, self.p_hi = res[:4]
            is_new = is_new | res.is_new
            overflow = overflow | res.overflow
            if not bool(res.spilled.any()):
                break
            rounds += 1
            if rounds >= MAX_RETRY_ROUNDS:
                # Route-spill retries never drained: surface as the same
                # overflow signal as full bucket chains (callers abort with
                # the table-full reason and recover via regrow).
                overflow = jnp.asarray(True)
                break
            # Chaos-plane boundary (faults/plan.py `table.insert_retry`):
            # the re-offer happens BEFORE any further table mutation, so a
            # fault here is exactly retriable — the caller re-runs the whole
            # insert (seed paths sit behind the engines' step retry; the
            # table arrays updated above already hold the non-spilled lanes,
            # and re-offering a committed key resolves as a duplicate).
            maybe_fault(
                "table.insert_retry",
                pending=int(np.asarray(res.spilled).sum()),
                round=rounds,
            )
            pending = res.spilled
        return res._replace(is_new=is_new, spilled=res.spilled, overflow=overflow)

    def dump(self) -> dict:
        from .fingerprint import pack_fp

        t_lo = np.asarray(self.t_lo)
        nz = t_lo != 0
        keys = pack_fp(t_lo[nz], np.asarray(self.t_hi)[nz])
        parents = pack_fp(
            np.asarray(self.p_lo)[nz], np.asarray(self.p_hi)[nz]
        )
        return dict(zip(keys.tolist(), parents.tolist()))
