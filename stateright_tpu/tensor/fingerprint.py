"""On-device state fingerprinting as a PAIR of uint32 lanes.

The host fingerprint (blake2b over a canonical encoding,
stateright_tpu.core.fingerprint) identifies Python states; device states are
uint32 lane rows, identified by two independent 32-bit murmur3-style folds
(= one 64-bit identity). The two fingerprint domains never need to agree —
parity of unique-state counts only requires each encoding to be injective per
model (SURVEY.md §7 "hard parts") — but both honor the same contracts as the
reference's `Fingerprint` (ref: src/lib.rs:340-387): stable across
runs/processes/chips, and nonzero.

Why a u32 pair instead of one u64: TPUs have no native 64-bit integer ALU —
XLA emulates u64 arithmetic with 32-bit pairs — so the hot sort/probe/compare
ops on fingerprints would pay emulation cost on exactly the hardware this
framework targets. All device code handles (lo, hi) pairs; the host packs
them into one Python int (`pack_fp`) only at the API boundary (parent maps,
Explorer URLs, discovery fingerprints).

Sentinel contract: `lo` is forced nonzero, so a (0, *) pair never denotes a
real state — lo==0 marks empty hash-table slots and "no parent" exactly as
the reference's NonZeroU64 fingerprint does (ref: src/lib.rs:341).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

# murmur3 fmix32 constants (public domain). numpy scalars, NOT jnp: a
# module-level jnp constant would initialize the device backend at import
# time (and hang if the TPU tunnel is down before the caller pins a platform).
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)


def _mix32(h: jnp.ndarray) -> jnp.ndarray:
    h = (h ^ (h >> jnp.uint32(16))) * _M1
    h = (h ^ (h >> jnp.uint32(13))) * _M2
    return h ^ (h >> jnp.uint32(16))


def device_fingerprint(states: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """uint32[B, L] -> (lo uint32[B] nonzero, hi uint32[B])."""
    lo = jnp.full(states.shape[0], jnp.uint32(0x6C078965))
    hi = jnp.full(states.shape[0], jnp.uint32(0xB5297A4D))
    for i in range(states.shape[1]):  # static, small
        lane = states[:, i] + _GOLDEN * jnp.uint32(i + 1)
        lo = _mix32(lo ^ lane)
        hi = _mix32(hi ^ (lane * _M1 + jnp.uint32(i + 0x1B873593)))
    lo = jnp.where(lo == 0, jnp.uint32(1), lo)
    return lo, hi


def pack_fp(lo, hi):
    """Device pair -> host Python int / numpy uint64 (vectorized)."""
    return (np.uint64(np.asarray(hi)) << np.uint64(32)) | np.uint64(
        np.asarray(lo)
    )


def unpack_fp(fp: int) -> tuple[int, int]:
    """Host int -> (lo, hi) pair."""
    return int(fp) & 0xFFFFFFFF, (int(fp) >> 32) & 0xFFFFFFFF
