"""On-device state fingerprinting as a PAIR of uint32 lanes.

The host fingerprint (blake2b over a canonical encoding,
stateright_tpu.core.fingerprint) identifies Python states; device states are
uint32 lane rows, identified by two independent 32-bit murmur3-style folds
(= one 64-bit identity). The two fingerprint domains never need to agree —
parity of unique-state counts only requires each encoding to be injective per
model (SURVEY.md §7 "hard parts") — but both honor the same contracts as the
reference's `Fingerprint` (ref: src/lib.rs:340-387): stable across
runs/processes/chips, and nonzero.

Why a u32 pair instead of one u64: TPUs have no native 64-bit integer ALU —
XLA emulates u64 arithmetic with 32-bit pairs — so the hot sort/probe/compare
ops on fingerprints would pay emulation cost on exactly the hardware this
framework targets. All device code handles (lo, hi) pairs; the host packs
them into one Python int (`pack_fp`) only at the API boundary (parent maps,
Explorer URLs, discovery fingerprints).

Sentinel contract: `lo` is forced nonzero, so a (0, *) pair never denotes a
real state — lo==0 marks empty hash-table slots and "no parent" exactly as
the reference's NonZeroU64 fingerprint does (ref: src/lib.rs:341).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

# murmur3 fmix32 constants (public domain). numpy scalars, NOT jnp: a
# module-level jnp constant would initialize the device backend at import
# time (and hang if the TPU tunnel is down before the caller pins a platform).
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)


def _mix32(h: jnp.ndarray) -> jnp.ndarray:
    h = (h ^ (h >> jnp.uint32(16))) * _M1
    h = (h ^ (h >> jnp.uint32(13))) * _M2
    return h ^ (h >> jnp.uint32(16))


def device_fingerprint(states: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """uint32[B, L] -> (lo uint32[B] nonzero, hi uint32[B])."""
    lo = jnp.full(states.shape[0], jnp.uint32(0x6C078965))
    hi = jnp.full(states.shape[0], jnp.uint32(0xB5297A4D))
    for i in range(states.shape[1]):  # static, small
        lane = states[:, i] + _GOLDEN * jnp.uint32(i + 1)
        lo = _mix32(lo ^ lane)
        hi = _mix32(hi ^ (lane * _M1 + jnp.uint32(i + 0x1B873593)))
    lo = jnp.where(lo == 0, jnp.uint32(1), lo)
    return lo, hi


def pack_fp(lo, hi):
    """Device pair -> host Python int / numpy uint64 (vectorized)."""
    return (np.uint64(np.asarray(hi)) << np.uint64(32)) | np.uint64(
        np.asarray(lo)
    )


def unpack_fp(fp: int) -> tuple[int, int]:
    """Host int -> (lo, hi) pair."""
    return int(fp) & 0xFFFFFFFF, (int(fp) >> 32) & 0xFFFFFFFF


# -- job-salted fingerprints (check service) -----------------------------------
#
# The multi-job check service (stateright_tpu/service/) packs many concurrent
# check jobs into ONE device hash table. Co-resident jobs must never collide
# on identical states, so each job folds a per-job salt into its table keys:
# `salt_fp` is a BIJECTION of the (lo, hi) pair per salt — injectivity within
# a job is preserved exactly (unique-count parity with a standalone run), and
# two jobs checking the same model map the same state to different keys with
# the same 2^-64 accidental-collision odds as any two unrelated states.
#
# The map is an involution (salt_fp(salt_fp(x)) == x), so unsalting a table
# key back to the standalone fingerprint is the same call — discovery
# fingerprints leave the service bit-identical to a single-job run.


def _mix32_int(h: int) -> int:
    """fmix32 over plain Python ints (no numpy overflow warnings)."""
    h &= 0xFFFFFFFF
    h = ((h ^ (h >> 16)) * int(_M1)) & 0xFFFFFFFF
    h = ((h ^ (h >> 13)) * int(_M2)) & 0xFFFFFFFF
    return h ^ (h >> 16)


def job_salt(job_id: int) -> tuple[np.uint32, np.uint32]:
    """Two well-mixed uint32 salt words for a job id (host-side).

    Distinct job ids give distinct salts (fmix32 is a bijection of u32, and
    the two words mix independent streams), and job ids are never reused
    within one service, so co-resident jobs always carry distinct salts."""
    j = int(job_id) & 0xFFFFFFFF
    lo = _mix32_int((j * int(_GOLDEN)) ^ 0x243F6A88)
    hi = _mix32_int((j * int(_M2)) ^ 0x85A308D3)
    return np.uint32(lo), np.uint32(hi)


def salt_fp(lo, hi, salt_lo, salt_hi):
    """Fold a job salt into (lo, hi) fingerprint pairs — array-generic
    (numpy or jax.numpy), traceable, and an involution per salt.

    XOR is the bijection; the one wrinkle is the engine-wide sentinel
    contract (lo == 0 marks empty slots / "no parent"): `lo ^ salt_lo` hits
    zero exactly when lo == salt_lo, so that single point is remapped to
    `salt_lo` — which is otherwise unreachable (it would need lo == 0, and
    real fingerprints are never zero). The remap keeps the map injective
    over nonzero lo, keeps outputs nonzero, and makes the function its own
    inverse, so the same call salts and unsalts."""
    slo = lo ^ salt_lo
    xp = np if isinstance(slo, (np.ndarray, np.generic)) else jnp
    slo = xp.where(slo == 0, salt_lo, slo)
    return slo, hi ^ salt_hi
