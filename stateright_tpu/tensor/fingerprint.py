"""On-device 64-bit state fingerprinting.

The host fingerprint (blake2b over a canonical encoding,
stateright_tpu.core.fingerprint) identifies Python states; device states are
uint32 lane rows, identified by a splitmix64-style multiply-xor fold computed
entirely on device. The two fingerprint domains never need to agree — parity of
unique-state counts only requires each encoding to be injective per model
(SURVEY.md §7 "hard parts") — but both honor the same contracts as the
reference's `Fingerprint` (ref: src/lib.rs:340-387): stable across
runs/processes/chips, and nonzero (0 is the empty-slot/no-parent sentinel).
"""

from __future__ import annotations

import jax.numpy as jnp

# splitmix64 constants (public domain PRNG finalizer).
_GOLDEN = jnp.uint64(0x9E3779B97F4A7C15)
_MIX1 = jnp.uint64(0xBF58476D1CE4E5B9)
_MIX2 = jnp.uint64(0x94D049BB133111EB)


def _mix64(h: jnp.ndarray) -> jnp.ndarray:
    h = (h ^ (h >> jnp.uint64(30))) * _MIX1
    h = (h ^ (h >> jnp.uint64(27))) * _MIX2
    return h ^ (h >> jnp.uint64(31))


def device_fingerprint(states: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, L] -> uint64[B], avoiding both sentinels: 0 (empty slot /
    no parent) and 2^64-1 (the engines' invalid-lane sort key)."""
    h = jnp.full(states.shape[0], jnp.uint64(0x5851F42D4C957F2D))
    lanes = states.astype(jnp.uint64)
    for i in range(states.shape[1]):  # static, small
        h = _mix64(h ^ (lanes[:, i] + _GOLDEN * jnp.uint64(i + 1)))
    h = jnp.where(h == 0, jnp.uint64(1), h)
    return jnp.where(h == jnp.uint64(0xFFFFFFFFFFFFFFFF), jnp.uint64(2), h)
