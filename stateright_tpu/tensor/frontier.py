"""Frontier-synchronous batched BFS on device — the TPU replacement for the
reference's hot loop (`check_block`, src/checker/bfs.rs:177-335).

One jitted step fuses, for a batch of up to `batch_size` frontier states:
property-mask evaluation, successor expansion (`TensorModel.expand`), boundary
masking, on-device fingerprinting, intra-batch dedup (sort + neighbor compare),
and visited-set insertion with parent tracking. The host orchestrates the
frontier queue, eventually-bit bookkeeping, discovery recording, and early
exit — exactly the split SURVEY.md §7 prescribes (host keeps the user-facing
API and path reconstruction; the device owns the hot loop).

Search semantics match the host BFS checker bit-for-bit where observable:
state/unique counts, boundary handling, depth cutoffs, eventually-bit false
negatives at revisits, early exit once every property has a discovery.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from ..core.path import Path
from .fingerprint import device_fingerprint
from .hashtable import HashTable
from .model import TensorModel

_MAX_U64 = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def state_fingerprint(model: "TensorModel", states: jnp.ndarray) -> jnp.ndarray:
    """Fingerprint for identity purposes: the canonical (symmetry
    representative) form when the model defines one, else the state itself."""
    if model.representative is not None:
        states = model.representative(states)
    return device_fingerprint(states)


def seed_init(model: "TensorModel"):
    """Boundary-filter and fingerprint-dedup the initial states on host.

    Returns (states uint32[n0, L], fps uint64[n0], n_raw) where n_raw is the
    PRE-dedup in-boundary count — the host checkers seed state_count with the
    raw init list length (ref: src/checker/bfs.rs:54), so count parity
    requires it.
    """
    init = np.asarray(model.init_states(), dtype=np.uint32)
    in_bounds = np.asarray(model.within_boundary(jnp.asarray(init)))
    init = init[in_bounds]
    n_raw = len(init)
    init_fps = np.asarray(state_fingerprint(model, jnp.asarray(init)))
    _, first_pos = np.unique(init_fps, return_index=True)
    keep = np.sort(first_pos)
    return init[keep], init_fps[keep], n_raw


def expand_insert(model: "TensorModel", keys, parents, states, fps, active):
    """The traced core of one frontier step, shared by the host-orchestrated
    and device-resident engines: expand, boundary-mask, fingerprint, intra-
    batch dedup (sort + neighbor compare), visited-set insert with parent
    tracking, and compaction of the newly-discovered states to the front.

    Returns (keys, parents, out_states, out_fps, src_rows, new_count,
    gen_count, has_succ, overflow); `src_rows[i] // max_actions` is the input
    row that produced compacted output row i.
    """
    from .hashtable import _insert_impl

    K = states.shape[0]
    A = model.max_actions
    succs, valid = model.expand(states)
    valid = valid & active[:, None]
    flat = succs.reshape(K * A, model.lanes)
    validf = valid.reshape(-1) & model.within_boundary(flat)
    # Generated-state count is pre-dedup, post-boundary (ref: bfs.rs:288-291).
    gen_count = validf.sum()
    # Terminality counts deduped successors too, but not boundary-excluded
    # ones (ref: bfs.rs:287-333).
    has_succ = validf.reshape(K, A).any(axis=1)

    sfps = state_fingerprint(model, flat)
    sort_key = jnp.where(validf, sfps, _MAX_U64)
    order = jnp.argsort(sort_key)
    so_fps = sort_key[order]
    uniq = so_fps != jnp.roll(so_fps, 1)
    uniq = uniq.at[0].set(True) & (so_fps != _MAX_U64)
    parent_rep = jnp.repeat(fps, A)[order]
    keys, parents, is_new, overflow = _insert_impl(
        keys, parents, so_fps, parent_rep, uniq
    )

    rank = jnp.argsort(~is_new, stable=True)
    src_rows = order[rank]
    out_states = flat[src_rows]
    out_fps = so_fps[rank]
    new_count = is_new.sum()
    return (
        keys,
        parents,
        out_states,
        out_fps,
        src_rows.astype(jnp.int32),
        new_count,
        gen_count,
        has_succ,
        overflow,
    )


def record_discovery(discovered, disc_fps, i, hit, fps):
    """First-witness discovery recording for property bit `i` inside a traced
    search body (shared by the resident and sharded engines). Keeps the first
    hit only; cross-batch/cross-chip races are tolerated exactly as the
    reference tolerates discovery-insertion races (ref: src/checker/bfs.rs:243).
    """
    bit = jnp.uint32(1 << i)
    already = (discovered & bit) != 0
    any_hit = jnp.any(hit)
    first = jnp.argmax(hit)
    record = (~already) & any_hit
    disc_fps = disc_fps.at[i].set(jnp.where(record, fps[first], disc_fps[i]))
    discovered = jnp.where(record, discovered | bit, discovered)
    return discovered, disc_fps


def reconstruct_path(model: TensorModel, parent_map: dict, fp: int) -> Path:
    """Walk device parent pointers, then re-execute the tensor model to
    recover decoded states and action labels (the TLC fingerprint-stack
    technique, ref: src/checker/bfs.rs:380-409)."""
    chain: list[int] = []
    cur = fp
    while cur:
        chain.append(cur)
        cur = parent_map.get(cur, 0)
    chain.reverse()

    init = np.asarray(model.init_states(), dtype=np.uint32)
    init_fps = np.asarray(state_fingerprint(model, jnp.asarray(init)))
    rows = np.nonzero(init_fps == np.uint64(chain[0]))[0]
    if len(rows) == 0:
        raise RuntimeError(
            "failed to reconstruct init state from device fingerprint; "
            "the tensor model may be nondeterministic"
        )
    cur_row = init[rows[0]]
    pairs = []
    for next_fp in chain[1:]:
        succs, valid = model.expand(jnp.asarray(cur_row[None]))
        sfps = np.asarray(state_fingerprint(model, succs[0]))
        succs = np.asarray(succs)[0]
        valid = np.asarray(valid)[0]
        hits = np.nonzero(valid & (sfps == np.uint64(next_fp)))[0]
        if len(hits) == 0:
            raise RuntimeError(
                "failed to reconstruct a step from device fingerprints; "
                "the tensor model may be nondeterministic"
            )
        a = int(hits[0])
        pairs.append((model.decode(cur_row), model.action_label(cur_row, a)))
        cur_row = succs[a]
    pairs.append((model.decode(cur_row), None))
    return Path(pairs)


@dataclass
class SearchResult:
    state_count: int
    unique_state_count: int
    max_depth: int
    discoveries: dict  # name -> device fingerprint
    complete: bool  # queue exhausted (vs early exit)
    duration: float
    steps: int = 0


@dataclass
class _Chunk:
    states: np.ndarray  # uint32[n, L]
    fps: np.ndarray  # uint64[n]
    ebits: np.ndarray  # bool[n, P]
    depth: int


class FrontierSearch:
    def __init__(
        self,
        model: TensorModel,
        batch_size: int = 1024,
        table_log2: int = 20,
    ):
        self.model = model
        self.batch_size = batch_size
        self.table = HashTable(table_log2)
        self.properties = model.properties()
        self._step = self._build_step()

    # -- the fused device step -------------------------------------------------

    def _build_step(self):
        model = self.model
        K = self.batch_size
        A = model.max_actions
        props = self.properties

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(keys, parents, states, fps, active):
            # Property masks on the input states (ref: bfs.rs:230-280).
            prop_masks = (
                jnp.stack([p.condition(model, states) for p in props])
                if props
                else jnp.zeros((0, K), dtype=bool)
            )
            return (
                *expand_insert(model, keys, parents, states, fps, active),
                prop_masks,
            )

        return step

    # -- host orchestration ----------------------------------------------------

    def run(
        self,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        target_state_count: Optional[int] = None,
        target_max_depth: Optional[int] = None,
        timeout: Optional[float] = None,
        progress: Optional[callable] = None,
    ) -> SearchResult:
        model = self.model
        K = self.batch_size
        A = model.max_actions
        P = len(self.properties)
        start = time.monotonic()
        props = self.properties
        prop_is = {
            "always": [i for i, p in enumerate(props) if p.expectation == Expectation.ALWAYS],
            "sometimes": [i for i, p in enumerate(props) if p.expectation == Expectation.SOMETIMES],
            "eventually": [i for i, p in enumerate(props) if p.expectation == Expectation.EVENTUALLY],
        }

        discoveries: dict = {}
        steps = 0

        # Seed: boundary-filter init states, dedup, insert with parent 0.
        init, init_fps, n_raw = seed_init(model)
        n0 = len(init)
        state_count = n_raw  # host checkers count pre-dedup (bfs.rs:54)
        unique_count = 0
        max_depth = 0

        # Insert init states (chunked to batch size).
        for lo in range(0, n0, K):
            sl = slice(lo, min(lo + K, n0))
            fps_pad = np.zeros(K, dtype=np.uint64)
            n = sl.stop - sl.start
            fps_pad[:n] = init_fps[sl]
            res = self.table.insert(
                jnp.asarray(fps_pad),
                jnp.zeros(K, dtype=jnp.uint64),
                jnp.asarray(np.arange(K) < n),
            )
            if bool(res.overflow):
                raise RuntimeError("hash table full; raise table_log2")
            unique_count += int(np.asarray(res.is_new).sum())

        ebits0 = np.zeros((n0, P), dtype=bool)
        for i in prop_is["eventually"]:
            ebits0[:, i] = True
        queue: deque = deque()
        queue.append(_Chunk(init, init_fps, ebits0, depth=1))

        complete = True
        while queue:
            if timeout is not None and time.monotonic() - start > timeout:
                complete = False
                break
            chunk = queue.popleft()
            # Coalesce same-depth chunks so narrow frontiers still fill the
            # batch (depths in the queue are monotonically nondecreasing).
            while queue and queue[0].depth == chunk.depth:
                nxt = queue.popleft()
                chunk = _Chunk(
                    np.concatenate([chunk.states, nxt.states]),
                    np.concatenate([chunk.fps, nxt.fps]),
                    np.concatenate([chunk.ebits, nxt.ebits]),
                    chunk.depth,
                )
            max_depth = max(max_depth, chunk.depth)
            if target_max_depth is not None and chunk.depth >= target_max_depth:
                # Not expanded, not evaluated (ref: bfs.rs:219-224).
                continue
            n = len(chunk.states)
            for lo in range(0, n, K):
                hi = min(lo + K, n)
                m = hi - lo
                st = np.zeros((K, model.lanes), dtype=np.uint32)
                st[:m] = chunk.states[lo:hi]
                fp = np.zeros(K, dtype=np.uint64)
                fp[:m] = chunk.fps[lo:hi]
                active = np.arange(K) < m

                (
                    keys,
                    parents,
                    out_states,
                    out_fps,
                    src_rows,
                    new_count,
                    gen_count,
                    has_succ,
                    overflow,
                    prop_masks,
                ) = self._step(
                    self.table.keys,
                    self.table.parents,
                    jnp.asarray(st),
                    jnp.asarray(fp),
                    jnp.asarray(active),
                )
                self.table.keys, self.table.parents = keys, parents
                steps += 1
                if bool(overflow):
                    raise RuntimeError("hash table full; raise table_log2")

                prop_masks = np.asarray(prop_masks)
                ebits = chunk.ebits[lo:hi]

                # Discoveries (ref: bfs.rs:230-280).
                for i in prop_is["always"]:
                    if props[i].name in discoveries:
                        continue
                    viol = active[:m] & ~prop_masks[i][:m]
                    if viol.any():
                        discoveries[props[i].name] = int(fp[np.argmax(viol)])
                for i in prop_is["sometimes"]:
                    if props[i].name in discoveries:
                        continue
                    sat = active[:m] & prop_masks[i][:m]
                    if sat.any():
                        discoveries[props[i].name] = int(fp[np.argmax(sat)])
                if prop_is["eventually"]:
                    for i in prop_is["eventually"]:
                        # Clear pending bits where observed; successors
                        # inherit the cleared bits below.
                        ebits[:, i] &= ~prop_masks[i][:m]
                    # Terminal states with pending eventually bits are
                    # counterexamples (ref: bfs.rs:326-333).
                    term = ~np.asarray(has_succ)[:m]
                    for i in prop_is["eventually"]:
                        if props[i].name in discoveries:
                            continue
                        bad = term & ebits[:, i]
                        if bad.any():
                            discoveries[props[i].name] = int(fp[np.argmax(bad)])

                # Early exit when every property is discovered
                # (ref: bfs.rs:278-280) or finish_when matches.
                if props and len(discoveries) == len(props):
                    complete = False
                    queue.clear()
                    break
                if finish_when.matches(props, set(discoveries)):
                    complete = False
                    queue.clear()
                    break

                state_count += int(gen_count)
                nc = int(new_count)
                unique_count += nc
                if nc:
                    out_states = np.asarray(out_states[:nc])
                    out_fps = np.asarray(out_fps[:nc])
                    parent_rows = np.asarray(src_rows[:nc]) // A
                    child_ebits = (
                        ebits[parent_rows]
                        if P
                        else np.zeros((nc, 0), dtype=bool)
                    )
                    queue.append(
                        _Chunk(out_states, out_fps, child_ebits, chunk.depth + 1)
                    )
                if (
                    target_state_count is not None
                    and state_count >= target_state_count
                ):
                    complete = False
                    queue.clear()
                    break
                if progress is not None:
                    progress(state_count, unique_count, max_depth)
            else:
                continue
            break

        return SearchResult(
            state_count=state_count,
            unique_state_count=unique_count,
            max_depth=max_depth,
            discoveries=discoveries,
            complete=complete and not queue,
            duration=time.monotonic() - start,
            steps=steps,
        )

    # -- path reconstruction ---------------------------------------------------

    def reconstruct_path(self, fp: int) -> Path:
        return reconstruct_path(self.model, self.table.dump(), fp)
