"""Frontier-synchronous batched BFS on device — the TPU replacement for the
reference's hot loop (`check_block`, src/checker/bfs.rs:177-335).

One jitted step fuses, for a batch of up to `batch_size` frontier states:
property-mask evaluation, successor expansion (`TensorModel.expand`), boundary
masking, on-device fingerprinting (u32 pairs — no 64-bit emulation on TPU),
and visited-set insertion with parent tracking. Intra-batch duplicates are
resolved INSIDE the hash-table insert (phase-3 arena, tensor/hashtable.py),
so there is no per-step sort; new states are compacted with a cumsum scatter.
The host orchestrates the frontier queue, eventually-bit bookkeeping,
discovery recording, and early exit — exactly the split SURVEY.md §7
prescribes (host keeps the user-facing API and path reconstruction; the
device owns the hot loop).

Search semantics match the host BFS checker bit-for-bit where observable:
state/unique counts, boundary handling, depth cutoffs, eventually-bit false
negatives at revisits, early exit once every property has a discovery.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from ..core.path import Path
from ..knobs import APPEND_KINDS, STORE_KINDS, WARM_KINDS
from ..faults.ckptio import fenced_savez, load_latest
from ..faults.plan import maybe_fault
from ..store import warm as warm_seam
from ..obs import REGISTRY, StepRing, as_tracer, build_detail
from .costmodel import ENGINE_VARIANTS
from .fingerprint import device_fingerprint, pack_fp
from .hashtable import _insert_impl
from .inserts import INSERT_TABLE, make_table, resolve_insert
from .model import TensorModel


def state_fingerprint(model: "TensorModel", states: jnp.ndarray):
    """(lo, hi) fingerprint for identity purposes: the canonical (symmetry
    representative) form when the model defines one, else the state itself."""
    if model.representative is not None:
        states = model.representative(states)
    return device_fingerprint(states)


def seed_init(model: "TensorModel"):
    """Boundary-filter and fingerprint-dedup the initial states on host.

    Returns (states uint32[n0, L], lo uint32[n0], hi uint32[n0], n_raw) where
    n_raw is the PRE-dedup in-boundary count — the host checkers seed
    state_count with the raw init list length (ref: src/checker/bfs.rs:54), so
    count parity requires it.
    """
    init = np.asarray(model.init_states(), dtype=np.uint32)
    in_bounds = np.asarray(model.within_boundary(jnp.asarray(init)))
    init = init[in_bounds]
    n_raw = len(init)
    lo, hi = (np.asarray(x) for x in state_fingerprint(model, jnp.asarray(init)))
    _, first_pos = np.unique(pack_fp(lo, hi), return_index=True)
    keep = np.sort(first_pos)
    return init[keep], lo[keep], hi[keep], n_raw


# -- u64-as-u32-pair counters (device counts can exceed 2^32) ------------------


def count_add(clo, chi, x):
    """(lo, hi) += x for u32 pair counters; x is u32/i32 (< 2^32)."""
    nlo = clo + x.astype(jnp.uint32)
    return nlo, chi + (nlo < clo).astype(jnp.uint32)


def count_ge(clo, chi, tlo, thi):
    return (chi > thi) | ((chi == thi) & (clo >= tlo))


def expand_insert(
    model, t_lo, t_hi, p_lo, p_hi, states, lo, hi, active,
    insert=_insert_impl, salt_lo=None, salt_hi=None,
    summary=None, summary_cfg=None,
):
    """The traced core of one frontier step, shared by the host-orchestrated
    and device-resident engines: expand, boundary-mask, fingerprint, visited-
    set insert with parent tracking (the insert also dedups within the batch).

    Returns (t_lo, t_hi, p_lo, p_hi, flat_states, succ_lo, succ_hi, is_new,
    suspect, gen_rows, has_succ, overflow); row i of the flattened successor
    arrays came from input row i // max_actions; `gen_rows` is the
    per-input-row post-boundary pre-dedup successor count (ref:
    bfs.rs:288-291 — callers sum it for the generated-state counter; the
    check service segments it by the lane's job). `insert` swaps the
    visited-set implementation (same 9-arg signature/6-tuple result as
    hashtable._insert_impl; resolve via tensor/inserts.py) — the engines use
    it for the interleaved-kv table layout, where t_lo is the uint32[2S] kv
    array and t_hi is a zero-length placeholder.

    `salt_lo`/`salt_hi` (uint32[K] per-lane, optional) fold a per-job salt
    into every key the visited set sees — successor keys AND the parent
    pointers stored beside them — so concurrent jobs can share one table
    with zero cross-job collisions (see fingerprint.salt_fp). The RETURNED
    succ_lo/succ_hi stay unsalted: they are the state identities the host
    uses for discovery recording and queue bookkeeping, bit-identical to a
    standalone (unsalted) run.

    `summary` (+ `summary_cfg=(summary_log2, hashes)`) is the tiered
    store's Bloom summary of the spilled set: when given, the returned
    `suspect` mask marks fresh claims whose TABLE key (salted when salts
    are given — the spill tier stores table keys) hits the summary and so
    needs exact host resolution. Inserts marked `fused_summary` (the
    Pallas kernel) compute the probe inside their own partition pass; for
    every other insert the probe is the usual maybe_contains gather sweep.
    Without a summary, `suspect` is all-False.
    """
    K = states.shape[0]
    A = model.max_actions
    succs, valid = model.expand(states)
    valid = valid & active[:, None]
    flat = succs.reshape(K * A, model.lanes)
    validf = valid.reshape(-1) & model.within_boundary(flat)
    # Generated-state count is pre-dedup, post-boundary (ref: bfs.rs:288-291).
    gen_rows = validf.reshape(K, A).sum(axis=1).astype(jnp.uint32)
    # Terminality counts deduped successors too, but not boundary-excluded
    # ones (ref: bfs.rs:287-333).
    has_succ = validf.reshape(K, A).any(axis=1)

    slo, shi = state_fingerprint(model, flat)
    par_lo = jnp.repeat(lo, A)
    par_hi = jnp.repeat(hi, A)
    if salt_lo is not None:
        from .fingerprint import salt_fp

        sl_rep = jnp.repeat(salt_lo, A)
        sh_rep = jnp.repeat(salt_hi, A)
        key_lo, key_hi = salt_fp(slo, shi, sl_rep, sh_rep)
        par_lo, par_hi = salt_fp(par_lo, par_hi, sl_rep, sh_rep)
    else:
        key_lo, key_hi = slo, shi
    if summary is not None and getattr(insert, "fused_summary", False):
        t_lo, t_hi, p_lo, p_hi, is_new, suspect, ovf = insert(
            t_lo, t_hi, p_lo, p_hi, key_lo, key_hi, par_lo, par_hi, validf,
            summary,
        )
    else:
        t_lo, t_hi, p_lo, p_hi, is_new, ovf = insert(
            t_lo, t_hi, p_lo, p_hi, key_lo, key_hi, par_lo, par_hi, validf
        )
        if summary is not None:
            from ..store.summary import maybe_contains

            slog2, khash = summary_cfg
            suspect = is_new & maybe_contains(
                summary, key_lo, key_hi, slog2, khash
            )
        else:
            suspect = jnp.zeros_like(is_new)
    return (
        t_lo, t_hi, p_lo, p_hi,
        flat, slo, shi, is_new, suspect,
        gen_rows, has_succ, ovf,
    )


def pop_batch(q_states, q_lo, q_hi, q_ebits, q_depth, head, tail, K):
    """Pop up to K rows from the in-device frontier queue as contiguous
    dynamic slices (the queue never wraps — see the resident engine's
    capacity argument). Returns (states, lo, hi, ebits, depth, active,
    new_head). Shared by the resident and sharded engines."""
    L = q_states.shape[1]
    take = jnp.minimum(tail - head, K)
    states = jax.lax.dynamic_slice(q_states, (head, 0), (K, L))
    lo = jax.lax.dynamic_slice(q_lo, (head,), (K,))
    hi = jax.lax.dynamic_slice(q_hi, (head,), (K,))
    ebits = jax.lax.dynamic_slice(q_ebits, (head,), (K,))
    depth = jax.lax.dynamic_slice(q_depth, (head,), (K,))
    active = jnp.arange(K, dtype=jnp.int32) < take
    return states, lo, hi, ebits, depth, active, head + take


def append_new(  # srlint: step-region
    q_states, q_lo, q_hi, q_ebits, q_depth, tail,
    flat, slo, shi, ebits_rows, depth_rows, is_new,
):
    """Append the is_new rows at the queue tail via cumsum-compacted scatter
    (sort-free). Returns the five queue arrays and the new tail. Shared by
    the resident and sharded engines."""
    Q = q_lo.shape[0]
    pos_all = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    qpos = jnp.where(is_new, tail + pos_all, Q)
    q_states = q_states.at[qpos].set(flat, mode="drop")
    q_lo = q_lo.at[qpos].set(slo, mode="drop")
    q_hi = q_hi.at[qpos].set(shi, mode="drop")
    q_ebits = q_ebits.at[qpos].set(ebits_rows, mode="drop")
    q_depth = q_depth.at[qpos].set(depth_rows, mode="drop")
    tail = tail + is_new.sum().astype(jnp.int32)
    return q_states, q_lo, q_hi, q_ebits, q_depth, tail


def resolve_append(append, platform: str) -> str:
    """One source of truth for the queue-append variant default: the
    row-scatter append is pathological on TPU (column-major queue layout;
    44.7% of the paxos-3 step — round-4 silicon profile) while the
    compact+dynamic_update_slice form measured ~5x slower on the 1-core
    CPU backend at 2pc-10 scale, so the default follows the platform the
    engine will actually run on."""
    if append is None:
        return "scatter" if platform == "cpu" else "dus"
    if append not in APPEND_KINDS:  # one knob universe: stateright_tpu/knobs.py
        raise ValueError(f"append must be one of {APPEND_KINDS}, got {append!r}")
    return append


def append_new_dus(  # srlint: step-region
    q_states, q_lo, q_hi, q_ebits, q_depth, tail,
    flat, slo, shi, ebits_rows, depth_rows, is_new,
):
    """DUS-append: compact the is_new rows to the front of an M-row block,
    then write the block at the queue tail with ONE contiguous
    `dynamic_update_slice` per queue array.

    Why this exists next to `append_new` (whole-array scatter): XLA reliably
    updates a DUS'd while-loop carry IN PLACE, while the equivalent scatter
    was measured copying the multi-GB queue arrays every step (2pc-10,
    batch 8192, table 2^27: ~77% of per-step execution time was `copy.*`
    thunks in the round-4 CPU trace — the round-3 "staged append-DUS
    experiment" evidence). CONTRACT: the caller must allocate Q >= max_tail
    + M slack (the resident engine uses Q = S + K*A) so the DUS start never
    clamps; rows [tail + new_count, tail + M) become zero scratch beyond the
    tail, which nothing reads (pops are bounded by tail)."""
    M, L = flat.shape
    pos_all = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    pos = jnp.where(is_new, pos_all, M)
    blk = jnp.zeros((M, L), flat.dtype).at[pos].set(flat, mode="drop")
    b_lo = jnp.zeros(M, q_lo.dtype).at[pos].set(slo, mode="drop")
    b_hi = jnp.zeros(M, q_hi.dtype).at[pos].set(shi, mode="drop")
    b_eb = jnp.zeros(M, q_ebits.dtype).at[pos].set(ebits_rows, mode="drop")
    b_dp = jnp.zeros(M, q_depth.dtype).at[pos].set(depth_rows, mode="drop")
    q_states = jax.lax.dynamic_update_slice(q_states, blk, (tail, 0))
    q_lo = jax.lax.dynamic_update_slice(q_lo, b_lo, (tail,))
    q_hi = jax.lax.dynamic_update_slice(q_hi, b_hi, (tail,))
    q_ebits = jax.lax.dynamic_update_slice(q_ebits, b_eb, (tail,))
    q_depth = jax.lax.dynamic_update_slice(q_depth, b_dp, (tail,))
    tail = tail + is_new.sum().astype(jnp.int32)
    return q_states, q_lo, q_hi, q_ebits, q_depth, tail


def compact_new(flat, slo, shi, is_new):
    """Scatter-compact the is_new rows (and their fingerprints + source row
    indices) to the front — the sort-free replacement for argsort ranking.
    Returns (states, lo, hi, src_rows, new_count)."""
    M, L = flat.shape
    pos_all = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    pos = jnp.where(is_new, pos_all, M)
    out_states = jnp.zeros((M, L), flat.dtype).at[pos].set(flat, mode="drop")
    out_lo = jnp.zeros(M, jnp.uint32).at[pos].set(slo, mode="drop")
    out_hi = jnp.zeros(M, jnp.uint32).at[pos].set(shi, mode="drop")
    src = jnp.arange(M, dtype=jnp.int32)
    out_src = jnp.zeros(M, jnp.int32).at[pos].set(src, mode="drop")
    return out_states, out_lo, out_hi, out_src, is_new.sum()


def compact_flags(flags, is_new):
    """Compact a per-lane flag column with the SAME positions compact_new
    assigns its rows, so flag i annotates compacted row i (used for the
    tiered store's suspect bits)."""
    M = flags.shape[0]
    pos_all = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    pos = jnp.where(is_new, pos_all, M)
    return jnp.zeros(M, dtype=bool).at[pos].set(flags, mode="drop")


def record_discovery(discovered, disc_lo, disc_hi, i, hit, lo, hi):
    """First-witness discovery recording for property bit `i` inside a traced
    search body (shared by the resident and sharded engines). Keeps the first
    hit only; cross-batch/cross-chip races are tolerated exactly as the
    reference tolerates discovery-insertion races (ref: src/checker/bfs.rs:243).
    """
    bit = jnp.uint32(1 << i)
    already = (discovered & bit) != 0
    any_hit = jnp.any(hit)
    first = jnp.argmax(hit)
    record = (~already) & any_hit
    disc_lo = disc_lo.at[i].set(jnp.where(record, lo[first], disc_lo[i]))
    disc_hi = disc_hi.at[i].set(jnp.where(record, hi[first], disc_hi[i]))
    discovered = jnp.where(record, discovered | bit, discovered)
    return discovered, disc_lo, disc_hi


def replay_fp_chain(model: TensorModel, chain: list) -> Path:
    """Re-execute the tensor model along a chain of packed fingerprints,
    recovering decoded states and action labels (the host checkers'
    Path.from_fingerprints technique, ref: src/checker/path.rs:20-97)."""
    init = np.asarray(model.init_states(), dtype=np.uint32)
    ilo, ihi = state_fingerprint(model, jnp.asarray(init))
    init_fps = pack_fp(np.asarray(ilo), np.asarray(ihi))
    rows = np.nonzero(init_fps == np.uint64(chain[0]))[0]
    if len(rows) == 0:
        # srlint: fault-ok host-side path reconstruction after the search; no recovery path exists
        raise RuntimeError(
            "failed to reconstruct init state from device fingerprint; "
            "the tensor model may be nondeterministic"
        )
    cur_row = init[rows[0]]
    pairs = []
    for next_fp in chain[1:]:
        succs, valid = model.expand(jnp.asarray(cur_row[None]))
        slo, shi = state_fingerprint(model, succs[0])
        sfps = pack_fp(np.asarray(slo), np.asarray(shi))
        succs = np.asarray(succs)[0]
        valid = np.asarray(valid)[0]
        hits = np.nonzero(valid & (sfps == np.uint64(next_fp)))[0]
        if len(hits) == 0:
            # srlint: fault-ok host-side path reconstruction after the search; no recovery path exists
            raise RuntimeError(
                "failed to reconstruct a step from device fingerprints; "
                "the tensor model may be nondeterministic"
            )
        a = int(hits[0])
        pairs.append((model.decode(cur_row), model.action_label(cur_row, a)))
        cur_row = succs[a]
    pairs.append((model.decode(cur_row), None))
    return Path(pairs)


def reconstruct_path(model: TensorModel, parent_map: dict, fp: int) -> Path:
    """Walk device parent pointers, then re-execute (the TLC
    fingerprint-stack technique, ref: src/checker/bfs.rs:380-409).
    Fingerprints are packed host ints (see tensor/fingerprint.py pack_fp)."""
    chain: list[int] = []
    cur = fp
    while cur:
        chain.append(cur)
        cur = parent_map.get(cur, 0)
    chain.reverse()
    return replay_fp_chain(model, chain)


@dataclass
class SearchResult:
    state_count: int
    unique_state_count: int
    max_depth: int
    discoveries: dict  # name -> device fingerprint (packed int)
    complete: bool  # queue exhausted (vs early exit)
    duration: float
    steps: int = 0
    detail: Optional[dict] = None  # engine-specific (e.g. per-chip balance)


@dataclass
class _Chunk:
    states: np.ndarray  # uint32[n, L]
    lo: np.ndarray  # uint32[n]
    hi: np.ndarray  # uint32[n]
    ebits: np.ndarray  # bool[n, P]
    depth: int


class FrontierSearch:
    # Same variant names/semantics as ResidentSearch.insert_variant (the
    # host-orchestrated engine races the same visited-set designs; the
    # table layout here is always split). THE dispatch table — defined once
    # in tensor/inserts.py, aliased (never restated) here; knobs.
    # check_registry() pins the alias.
    INSERT_VARIANTS = INSERT_TABLE
    # Corpus warm ladder: the ONE kind vocabulary and the ONE preload seam
    # (store/warm.py) — aliased, never restated; knobs.check_registry()
    # pins both on every engine.
    WARM_KINDS = WARM_KINDS
    WARM_SEAM = warm_seam

    def __init__(
        self,
        model: TensorModel,
        batch_size: int = 1024,
        table_log2: int = 20,
        insert_variant: str = "sort",
        store: str = "device",
        high_water: float = 0.85,
        low_water: Optional[float] = None,
        summary_log2: int = 20,
        telemetry: bool = True,
        telemetry_log2: int = 12,
        tracer=None,
    ):
        """`store="tiered"` enables the two-tier state store
        (stateright_tpu/store/): when device-table occupancy crosses
        `high_water`, cold non-full buckets are evicted to a host spill
        tier and a device Bloom summary (2^summary_log2 bits) filters
        re-probes — searches whose unique-state count exceeds the table
        degrade gracefully instead of aborting. With the default
        `store="device"` behavior is byte-identical to before.

        `telemetry=True` (default) records one obs.STEP_COLS metrics row
        per device step, host-side — this engine already fetches every
        per-step scalar the row needs, so telemetry adds no device work or
        sync; the digest lands in `SearchResult.detail["telemetry"]`.
        `tracer` (obs.Tracer) records host phases (step dispatch, suspect
        resolution, eviction) as Chrome trace events."""
        self.model = model
        self.batch_size = batch_size
        if insert_variant not in self.INSERT_VARIANTS:
            raise ValueError(
                f"insert_variant must be one of "
                f"{sorted(self.INSERT_VARIANTS)}, got {insert_variant!r}"
            )
        self.insert_variant = insert_variant
        # Variant-aware handle (PallasHashTable for "pallas", so seeding
        # probes the variant's own slot layout) + the shared tiling guard —
        # both defined once in tensor/inserts.py.
        self.table = make_table(insert_variant, table_log2)
        if store not in STORE_KINDS:  # one knob universe: stateright_tpu/knobs.py
            raise ValueError(f"store must be one of {STORE_KINDS}, got {store!r}")
        self.store = store
        self._store = None
        if store == "tiered":
            from ..store.tiered import TieredConfig, TieredStore

            self._store = TieredStore(
                self.table.size,
                TieredConfig(
                    high_water=high_water,
                    low_water=low_water,
                    summary_log2=summary_log2,
                ),
            )
            # Spill trigger with one-batch headroom: a single step can claim
            # up to batch x max_actions slots, and eviction only runs
            # between steps — without the headroom a near-high-water table
            # can blow straight through to a hard insert overflow.
            ka = batch_size * model.max_actions
            self._spill_trigger = min(
                self._store.high_slots, self.table.size - ka
            )
            if self._spill_trigger <= self._store.low_slots:
                raise ValueError(
                    "table too small for tiered spilling at this batch: "
                    f"table 2^{table_log2} minus one batch of claims "
                    f"({ka}) leaves no room above the low-water mark "
                    f"({self._store.low_slots} slots); raise table_log2 or "
                    "lower batch_size/low_water"
                )
        self._hot_claims = 0  # occupied device-table slots (claims - evictions)
        self._telemetry = telemetry
        self._tm_capacity = 1 << telemetry_log2  # host row-retention window
        self._ring: Optional[StepRing] = None  # created per seed (fresh search)
        self._tracer = as_tracer(tracer)
        # Weakly registered: /metrics scrapes can see any live engine, and
        # the registry never keeps a finished search alive (obs/registry.py).
        self._metrics_name = REGISTRY.register("frontier", self.metrics)
        # Calibration comparator (obs/calib.py): joins the step times this
        # engine already measures against the costmodel prediction for this
        # exact config — host arithmetic only, observes and never steers.
        self._calib = None
        if telemetry:
            # Lazy import: obs.calib prices through tensor.costmodel, so a
            # module-level import would cycle when obs loads first.
            from ..obs.calib import CalibConfig, Comparator, calib_enabled

        if telemetry and calib_enabled():
            self._calib = Comparator(CalibConfig(
                engine="frontier",
                variant=ENGINE_VARIANTS.get(
                    ("split", insert_variant), "split"
                ),
                lanes=model.lanes,
                max_actions=model.max_actions,
                batch=batch_size,
                table_log2=table_log2,
                spill=(store == "tiered"),
            ))
            REGISTRY.register("calib", self._calib.metrics)
        # Placeholder summary operand for store="device" (the step signature
        # is uniform so both modes share one code path).
        self._no_summary = jnp.zeros(1, dtype=jnp.uint32)
        self.properties = model.properties()
        self._step = self._build_step()
        # Resumable search state (seeded lazily by run(); see _seed).
        self._q = None
        self._counts = None
        self._disc: dict = {}
        # Warm-start corpus payload (store/corpus.py; see warm_start).
        self._warm: Optional[dict] = None
        self._warm_states = 0
        self._warm_kind: Optional[str] = None  # knobs.WARM_KINDS rung served

    # -- the fused device step -------------------------------------------------

    def _build_step(self):
        model = self.model
        K = self.batch_size
        props = self.properties
        tiered = self._store is not None
        if tiered:
            s_cfg = (
                self._store.config.summary_log2,
                self._store.config.summary_hashes,
            )
        else:
            s_cfg = None
        insert = resolve_insert(self.insert_variant, summary_cfg=s_cfg)

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def step(t_lo, t_hi, p_lo, p_hi, states, lo, hi, active, summary):
            # Property masks on the input states (ref: bfs.rs:230-280).
            prop_masks = (
                jnp.stack([p.condition(model, states) for p in props])
                if props
                else jnp.zeros((0, K), dtype=bool)
            )
            # Tiered store: a fresh device claim whose fingerprint hits the
            # Bloom summary of the spilled set is a SUSPECT — possibly a
            # revisit of an evicted state (expand_insert computes the mask,
            # fused into the Pallas kernel's own partition pass when that
            # variant is selected). The host resolves suspects exactly
            # (store/host.py); a summary miss PROVES novelty, so the
            # common path never leaves the device.
            (
                t_lo, t_hi, p_lo, p_hi,
                flat, slo, shi, is_new, suspect,
                gen_rows, has_succ, ovf,
            ) = expand_insert(
                model, t_lo, t_hi, p_lo, p_hi, states, lo, hi, active,
                insert=insert,
                summary=summary if tiered else None,
                summary_cfg=s_cfg,
            )
            gen_count = gen_rows.sum()
            out_states, out_lo, out_hi, out_src, new_count = compact_new(
                flat, slo, shi, is_new
            )
            out_sus = compact_flags(suspect, is_new)
            return (
                t_lo, t_hi, p_lo, p_hi,
                out_states, out_lo, out_hi, out_src, out_sus,
                new_count, gen_count, has_succ, ovf, prop_masks,
            )

        return step

    # -- static analysis -------------------------------------------------------

    def audit_step(self):
        """(step_fn, abstract_operands, host_slots) for the jaxpr auditor
        (analysis/auditor.py): operands mirror one run() dispatch as
        ShapeDtypeStructs, so tracing touches no device data. host_slots
        are the operand indices the host re-uploads every step (this
        engine's per-step PCIe floor: the popped batch + active mask)."""
        K, L, S = self.batch_size, self.model.lanes, self.table.size
        sds = jax.ShapeDtypeStruct
        summary = (
            self._store.device_summary()
            if self._store is not None
            else self._no_summary
        )
        args = (
            sds((S,), jnp.uint32), sds((S,), jnp.uint32),
            sds((S,), jnp.uint32), sds((S,), jnp.uint32),
            sds((K, L), jnp.uint32), sds((K,), jnp.uint32),
            sds((K,), jnp.uint32), sds((K,), jnp.bool_),
            sds(summary.shape, summary.dtype),
        )
        return self._step, args, (4, 5, 6, 7)

    # -- host orchestration ----------------------------------------------------

    def _seed(self) -> None:
        """Seed the resumable search state (queue + counters + discoveries)
        held on the instance — `run()` continues where the last call left
        off, which is what makes checkpoint/resume possible."""
        model = self.model
        K = self.batch_size
        P = len(self.properties)
        eventually_i = [
            i
            for i, p in enumerate(self.properties)
            if p.expectation == Expectation.EVENTUALLY
        ]
        init, init_lo, init_hi, n_raw = seed_init(model)
        n0 = len(init)
        self._counts = dict(
            state_count=n_raw,  # host checkers count pre-dedup (bfs.rs:54)
            unique_count=0,
            max_depth=0,
            steps=0,
            early_exit=False,
        )
        self._disc = {}
        self._hot_claims = 0
        self._ring = StepRing(self._tm_capacity) if self._telemetry else None

        # Chaos-plane boundary: the seed inserts below dispatch to the
        # device and can overflow exactly like a run() step; before this
        # boundary a seeding fault was the one engine failure surface the
        # chaos plane could not reach (found by srlint SR004).
        maybe_fault("engine.step", engine="frontier", phase="seed")
        # Insert init states (chunked to batch size).
        for b0 in range(0, n0, K):
            sl = slice(b0, min(b0 + K, n0))
            n = sl.stop - sl.start
            lo_pad = np.zeros(K, dtype=np.uint32)
            hi_pad = np.zeros(K, dtype=np.uint32)
            lo_pad[:n] = init_lo[sl]
            hi_pad[:n] = init_hi[sl]
            res = self.table.insert(
                jnp.asarray(lo_pad),
                jnp.asarray(hi_pad),
                jnp.zeros(K, dtype=jnp.uint32),
                jnp.zeros(K, dtype=jnp.uint32),
                jnp.asarray(np.arange(K) < n),
            )
            if bool(res.overflow):
                raise RuntimeError("hash table full; raise table_log2")
            n_new = int(np.asarray(res.is_new).sum())
            self._counts["unique_count"] += n_new
            self._hot_claims += n_new

        ebits0 = np.zeros((n0, P), dtype=bool)
        for i in eventually_i:
            ebits0[:, i] = True
        self._q = deque()
        self._q.append(_Chunk(init, init_lo, init_hi, ebits0, depth=1))

    def warm_start(self, entry, kind: Optional[str] = None) -> int:
        """Preload a published corpus entry (store/corpus.py CorpusEntry:
        packed unsalted fps/parents + serialized Bloom summary) into the
        tiered store BEFORE the first run() — the standalone-engine half of
        the cross-job warm-start, routed through the one seam
        (store/warm.py; knobs.WARM_KINDS).

        A COMPLETE entry replays: known states dedup-filter on device from
        the very first expansion (the seeding inserts init states into the
        device table as usual; their successors hit the pre-warmed summary
        and resolve as spilled duplicates on host), the search collapses to
        the init frontier, and the result replays the publisher's
        bookkeeping bit-identically. A PARTIAL entry (corpus v2: an
        interrupted run's visited prefix + frontier snapshot) CONTINUES:
        the prefix preloads the same way, the frontier snapshot seeds the
        queue in place of the init states, counters/discoveries restore
        from the entry's meta, and run() picks up exactly where the
        publisher was cut — the completed result is bit-identical to a
        cold run and (on the service path) supersedes the partial.

        Standalone engines run unsalted, so a matching summary geometry
        takes the serialized-summary fast path (no re-hash). Call before
        run(); applies to an uninterrupted run (checkpoints do not carry
        the replay payload). The caller owns key discipline here: the
        entry must have been published for THIS model + lowering config
        (`warm.can_replay` / `warm.can_continue` are the gates), and a
        replay's run() must use the publisher's finish policy — the
        service path (service/scheduler.py) derives and checks the
        content key for you. `kind` labels the rung served, drawn from
        knobs.WARM_KINDS ("exact" when omitted; "near" for a family
        match; "delta" for a Spec-CI salvage — an entry store/warm.
        salvage_delta already re-evaluated/re-derived for an edited
        definition; partials default to "partial"). Returns the state
        count preloaded."""
        if self._store is None:
            raise ValueError(
                "warm_start requires store='tiered' (known states are "
                "dedup-filtered through the spill tier's Bloom suspect "
                "path)"
            )
        n = warm_seam.preload_store(self._store, entry)
        self._warm_states = n
        if getattr(entry, "complete", True):
            self._warm = dict(entry.meta)
            self._warm_kind = kind or "exact"
            return n
        # Partial continuation: frontier snapshot -> queue (in place of
        # _seed(); the prefix's states — init included — live in the
        # preloaded spill tier), counters/discoveries -> meta baselines.
        # No self._warm: the run accumulates real counts, never replays.
        if entry.frontier is None:
            raise ValueError(
                "partial corpus entry has no frontier snapshot (coverage-"
                "only); a continuation needs the publisher's cut frontier"
            )
        self._warm_kind = kind if kind == "delta" else "partial"
        m = entry.meta
        self._q = deque()
        for states, c_lo, c_hi, ebits, depth in warm_seam.frontier_chunks(
            entry
        ):
            self._q.append(_Chunk(states, c_lo, c_hi, ebits, depth))
        self._counts = dict(
            state_count=int(m["state_count"]),
            unique_count=int(m["unique_count"]),
            max_depth=int(m["max_depth"]),
            steps=0,
            early_exit=False,
        )
        self._disc = dict(m.get("discoveries", {}))
        self._hot_claims = 0
        self._ring = StepRing(self._tm_capacity) if self._telemetry else None
        return n

    def run(
        self,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        target_state_count: Optional[int] = None,
        target_max_depth: Optional[int] = None,
        timeout: Optional[float] = None,
        progress: Optional[callable] = None,
        max_steps: Optional[int] = None,
    ) -> SearchResult:
        model = self.model
        K = self.batch_size
        A = model.max_actions
        P = len(self.properties)
        start = time.monotonic()
        props = self.properties
        prop_is = {
            "always": [i for i, p in enumerate(props) if p.expectation == Expectation.ALWAYS],
            "sometimes": [i for i, p in enumerate(props) if p.expectation == Expectation.SOMETIMES],
            "eventually": [i for i, p in enumerate(props) if p.expectation == Expectation.EVENTUALLY],
        }

        if self._q is None:
            self._seed()
        queue = self._q
        counts = self._counts
        discoveries = self._disc
        state_count = counts["state_count"]
        unique_count = counts["unique_count"]
        max_depth = counts["max_depth"]
        steps = counts["steps"]
        run_steps = 0

        complete = True
        while queue:
            if timeout is not None and time.monotonic() - start > timeout:
                complete = False
                break
            chunk = queue.popleft()
            # Coalesce same-depth chunks so narrow frontiers still fill the
            # batch (depths in the queue are monotonically nondecreasing).
            while queue and queue[0].depth == chunk.depth:
                nxt = queue.popleft()
                chunk = _Chunk(
                    np.concatenate([chunk.states, nxt.states]),
                    np.concatenate([chunk.lo, nxt.lo]),
                    np.concatenate([chunk.hi, nxt.hi]),
                    np.concatenate([chunk.ebits, nxt.ebits]),
                    chunk.depth,
                )
            max_depth = max(max_depth, chunk.depth)
            if target_max_depth is not None and chunk.depth >= target_max_depth:
                # Not expanded, not evaluated (ref: bfs.rs:219-224).
                continue
            n = len(chunk.states)
            for b0 in range(0, n, K):
                b1 = min(b0 + K, n)
                m = b1 - b0
                st = np.zeros((K, model.lanes), dtype=np.uint32)
                st[:m] = chunk.states[b0:b1]
                lo = np.zeros(K, dtype=np.uint32)
                lo[:m] = chunk.lo[b0:b1]
                hi = np.zeros(K, dtype=np.uint32)
                hi[:m] = chunk.hi[b0:b1]
                active = np.arange(K) < m

                # Chaos-plane boundary: simulated device OOM / XlaRuntime
                # errors land BEFORE the dispatch, so a faulted step never
                # half-updates the visited tables (faults/plan.py).
                maybe_fault("engine.step", engine="frontier", step=steps)
                t_step0 = time.monotonic()
                with self._tracer.span("frontier.step", cat="engine"):
                    (
                        t_lo, t_hi, p_lo, p_hi,
                        out_states, out_lo, out_hi, out_src, out_sus,
                        new_count, gen_count, has_succ, overflow, prop_masks,
                    ) = self._step(
                        self.table.t_lo,
                        self.table.t_hi,
                        self.table.p_lo,
                        self.table.p_hi,
                        jnp.asarray(st),
                        jnp.asarray(lo),
                        jnp.asarray(hi),
                        jnp.asarray(active),
                        self._store.device_summary()
                        if self._store is not None
                        else self._no_summary,
                    )
                    self.table.t_lo, self.table.t_hi = t_lo, t_hi
                    self.table.p_lo, self.table.p_hi = p_lo, p_hi
                    steps += 1
                    run_steps += 1
                    if bool(overflow):  # first host sync of the step
                        raise RuntimeError(
                            "hash table full; raise table_log2"
                        )
                step_us = (time.monotonic() - t_step0) * 1e6

                prop_masks = np.asarray(prop_masks)
                ebits = chunk.ebits[b0:b1]

                # Discoveries (ref: bfs.rs:230-280).
                for i in prop_is["always"]:
                    if props[i].name in discoveries:
                        continue
                    viol = active[:m] & ~prop_masks[i][:m]
                    if viol.any():
                        j = int(np.argmax(viol))
                        discoveries[props[i].name] = int(pack_fp(lo[j], hi[j]))
                for i in prop_is["sometimes"]:
                    if props[i].name in discoveries:
                        continue
                    sat = active[:m] & prop_masks[i][:m]
                    if sat.any():
                        j = int(np.argmax(sat))
                        discoveries[props[i].name] = int(pack_fp(lo[j], hi[j]))
                if prop_is["eventually"]:
                    for i in prop_is["eventually"]:
                        # Clear pending bits where observed; successors
                        # inherit the cleared bits below.
                        ebits[:, i] &= ~prop_masks[i][:m]
                    # Terminal states with pending eventually bits are
                    # counterexamples (ref: bfs.rs:326-333).
                    term = ~np.asarray(has_succ)[:m]
                    for i in prop_is["eventually"]:
                        if props[i].name in discoveries:
                            continue
                        bad = term & ebits[:, i]
                        if bad.any():
                            j = int(np.argmax(bad))
                            discoveries[props[i].name] = int(
                                pack_fp(lo[j], hi[j])
                            )

                # Early exit when every property is discovered
                # (ref: bfs.rs:278-280) or finish_when matches.
                if (props and len(discoveries) == len(props)) or (
                    finish_when.matches(props, set(discoveries))
                ):
                    if self._ring is not None:
                        # The exiting step ran but its contribution is
                        # discarded (never counted) — record it as an
                        # uncaptured step so telemetry steps == result
                        # steps while dropped_steps marks the gap.
                        self._ring.note_uncaptured()
                    complete = False
                    counts["early_exit"] = True
                    queue.clear()
                    break

                gen_i = int(gen_count)
                state_count += gen_i
                nc = int(new_count)
                claims = nc  # device slot claims this step (incl. suspects)
                sus_n = 0
                self._hot_claims += nc
                if nc:
                    out_states = np.asarray(out_states[:nc])
                    out_lo = np.asarray(out_lo[:nc])
                    out_hi = np.asarray(out_hi[:nc])
                    parent_rows = np.asarray(out_src[:nc]) // A
                    if self._store is not None:
                        sus = np.asarray(out_sus[:nc])
                        sus_n = int(sus.sum())
                        if sus.any():
                            # Exact membership check against the spill tier:
                            # confirmed duplicates of spilled states are
                            # dropped (not unique, not re-enqueued); Bloom
                            # false positives stay.
                            with self._tracer.span(
                                "tiered.suspect_resolve", cat="store",
                                suspects=sus_n,
                            ):
                                dup = self._store.resolve_suspects(
                                    out_lo[sus], out_hi[sus]
                                )
                            if dup.any():
                                keep = np.ones(nc, dtype=bool)
                                keep[np.nonzero(sus)[0][dup]] = False
                                out_states = out_states[keep]
                                out_lo = out_lo[keep]
                                out_hi = out_hi[keep]
                                parent_rows = parent_rows[keep]
                                nc = int(keep.sum())
                unique_count += nc
                if nc:
                    child_ebits = (
                        ebits[parent_rows]
                        if P
                        else np.zeros((nc, 0), dtype=bool)
                    )
                    queue.append(
                        _Chunk(
                            out_states, out_lo, out_hi, child_ebits,
                            chunk.depth + 1,
                        )
                    )
                if (
                    self._store is not None
                    and self._hot_claims >= self._spill_trigger
                ):
                    with self._tracer.span("tiered.evict", cat="store"):
                        tl, th, pl, ph, n_ev = self._store.evict(
                            self.table.t_lo, self.table.t_hi,
                            self.table.p_lo, self.table.p_hi,
                            self._hot_claims,
                        )
                    if n_ev == 0:
                        raise RuntimeError(
                            "tiered store could not free any bucket (every "
                            "bucket is full and pinned); raise table_log2 "
                            "or lower high_water"
                        )
                    self.table.t_lo, self.table.t_hi = tl, th
                    self.table.p_lo, self.table.p_hi = pl, ph
                    self._hot_claims -= n_ev
                if self._ring is not None:
                    # Every scalar here was already fetched for the counters
                    # above — telemetry adds no device work or extra sync.
                    self._ring.append(
                        active=m,
                        generated=gen_i,
                        claimed=claims,
                        queue_len=(
                            sum(len(c.lo) for c in queue) + (n - b1)
                        ),
                        table_claims=self._hot_claims,
                        suspects=sus_n,
                        depth=chunk.depth,
                        step_us=step_us,
                    )
                    if self._calib is not None:
                        # Same already-fetched scalars, joined against the
                        # costmodel prediction at chunk granularity.
                        self._calib.observe(steps, step_us, state_count)
                if (
                    target_state_count is not None
                    and state_count >= target_state_count
                ):
                    complete = False
                    counts["early_exit"] = True
                    queue.clear()
                    break
                if max_steps is not None and run_steps >= max_steps:
                    # Suspend mid-search, preserving the unprocessed rest of
                    # this chunk for resume (possibly after a checkpoint).
                    if b1 < n:
                        queue.appendleft(
                            _Chunk(
                                chunk.states[b1:],
                                chunk.lo[b1:],
                                chunk.hi[b1:],
                                chunk.ebits[b1:],
                                chunk.depth,
                            )
                        )
                    complete = False
                    break
                if progress is not None:
                    progress(state_count, unique_count, max_depth)
            else:
                continue
            break

        if (
            self._warm is not None
            and complete
            and not queue
            and not counts.get("early_exit", False)
        ):
            # Warm-start replay (store/corpus.py): the run only
            # re-expanded the init frontier (everything deeper
            # dedup-filtered against the preloaded corpus), so the result
            # bookkeeping is the publisher's — bit-identical to what this
            # search's own cold run would have produced for this content
            # key. Discoveries replay into self._disc so reconstruct_path
            # walks the preloaded spill-tier parent chains.
            w = self._warm
            state_count = w["state_count"]
            unique_count = w["unique_count"]
            max_depth = w["max_depth"]
            discoveries.clear()
            discoveries.update(w["discoveries"])
        counts["state_count"] = state_count
        counts["unique_count"] = unique_count
        counts["max_depth"] = max_depth
        counts["steps"] = steps
        detail = self._detail()
        if self._warm_kind is not None:
            detail = dict(detail or {})
            detail["corpus"] = {
                "warm_start": True,
                "preloaded_states": self._warm_states,
                "warm_kind": self._warm_kind,
            }
        return SearchResult(
            state_count=state_count,
            unique_state_count=unique_count,
            max_depth=max_depth,
            discoveries=dict(discoveries),
            # An early-exited search stays incomplete across resumed run()
            # calls and checkpoint/restore (the frontier was discarded).
            complete=complete
            and not queue
            and not counts.get("early_exit", False),
            duration=time.monotonic() - start,
            steps=steps,
            detail=detail,
        )

    def store_stats(self) -> Optional[dict]:
        """Per-tier occupancy counters (None with the plain device store) —
        surfaced in SearchResult.detail, the bench JSON, and `/.status`."""
        if self._store is None:
            return None
        return self._store.stats(self._hot_claims)

    def telemetry_summary(self) -> Optional[dict]:
        """Step-telemetry digest (obs/ring.py; None with telemetry off) —
        surfaced in SearchResult.detail["telemetry"] and `/metrics`."""
        if self._ring is None:
            return None
        return self._ring.summary(self.table.size, self.batch_size)

    def metrics(self) -> dict:
        """Flat counter snapshot for the obs registry / Prometheus export
        (host-side values only — scraping never touches the device). The
        ring's totals update per step, so a mid-search scrape sees LIVE
        steps/generated values (self._counts is only written back when
        run() returns); non-numeric leaves (the store's kind string) are
        dropped by the Prometheus renderer itself."""
        if self._ring is not None:
            out = {
                "steps": self._ring.steps,
                "generated_states": self._ring.generated_total,
                "claimed_states": self._ring.claimed_total,
            }
        else:
            out = {
                "steps": self._counts["steps"] if self._counts else 0,
                "generated_states": (
                    self._counts["state_count"] if self._counts else 0
                ),
            }
        out["table_fill"] = round(self._hot_claims / self.table.size, 4)
        stats = self.store_stats()
        if stats:
            out["store"] = stats
        return out

    def _detail(self) -> Optional[dict]:
        """SearchResult.detail under the one documented schema
        (obs/schema.py, shared assembly in obs.build_detail)."""
        detail = build_detail(self.store_stats(), self.telemetry_summary())
        if self._calib is not None:
            self._calib.finish()
        if self._calib is not None and self._calib.chunks:
            detail = dict(detail or {})
            detail["calib"] = self._calib.detail()
            self._calib.flush_records()
        return detail

    # -- checkpoint / resume ---------------------------------------------------
    # SURVEY.md §5: the reference has no partial-search checkpointing; with
    # the frontier and visited set as device arrays it is nearly free here.

    def checkpoint(self, path: str) -> None:
        """Dump the visited table, pending frontier queue, counters, and
        discoveries to `path` (.npz). Valid any time `run()` has returned —
        including after a suspension via max_steps/timeout — so an
        interrupted search can be resumed elsewhere via `load_checkpoint`.
        The write is crash-atomic (tmp+fsync+rename with a CRC32 footer,
        previous generation kept at `path + ".prev"` — faults/ckptio.py):
        a torn write can never poison resume."""
        import json

        if self._q is None:
            # srlint: fault-ok caller-contract guard, not an I/O/device surface
            raise RuntimeError("nothing to checkpoint: run() has not started")
        self._tracer.instant("checkpoint", cat="engine", path=path)
        chunks = list(self._q)
        # Tiered runs serialize the spill tier alongside the device table
        # (the Bloom summary is rebuilt from the fingerprints on load).
        spill = self._store.to_checkpoint() if self._store is not None else {}
        arrays = dict(
            **spill,
            t_lo=np.asarray(self.table.t_lo),
            t_hi=np.asarray(self.table.t_hi),
            p_lo=np.asarray(self.table.p_lo),
            p_hi=np.asarray(self.table.p_hi),
            q_states=(
                np.concatenate([c.states for c in chunks])
                if chunks
                else np.zeros((0, self.model.lanes), np.uint32)
            ),
            q_lo=(
                np.concatenate([c.lo for c in chunks])
                if chunks
                else np.zeros(0, np.uint32)
            ),
            q_hi=(
                np.concatenate([c.hi for c in chunks])
                if chunks
                else np.zeros(0, np.uint32)
            ),
            q_ebits=(
                np.concatenate([c.ebits for c in chunks])
                if chunks
                else np.zeros((0, len(self.properties)), bool)
            ),
            q_lens=np.asarray([len(c.states) for c in chunks], np.int64),
            q_depths=np.asarray([c.depth for c in chunks], np.int64),
            meta=np.frombuffer(
                json.dumps(
                    {
                        "counts": self._counts,
                        "discoveries": self._disc,
                        "lanes": self.model.lanes,
                        "max_actions": self.model.max_actions,
                        "properties": [p.name for p in self.properties],
                        "table_log2": self.table.log2_size,
                        "insert_variant": self.insert_variant,
                        "hot_claims": self._hot_claims,
                        "store": (
                            self._store.meta()
                            if self._store is not None
                            else None
                        ),
                    }
                ).encode(),
                dtype=np.uint8,
            ),
        )
        fenced_savez(path, arrays)

    @classmethod
    def load_checkpoint(
        cls, model: TensorModel, path: str, batch_size: int = 1024
    ) -> "FrontierSearch":
        """Rebuild a suspended search from a `checkpoint` file; the next
        `run()` continues exactly where the dump left off. The CRC footer
        is verified; a corrupt current generation falls back to
        `path + ".prev"` instead of raising (faults/ckptio.load_latest)."""
        import json

        data, _src = load_latest(path)
        meta = json.loads(bytes(data["meta"].tobytes()).decode())
        if (meta["lanes"], meta["max_actions"]) != (
            model.lanes,
            model.max_actions,
        ):
            raise ValueError(
                "checkpoint was taken with a different model layout "
                f"(lanes/max_actions {meta['lanes']}/{meta['max_actions']} "
                f"!= {model.lanes}/{model.max_actions})"
            )
        prop_names = [p.name for p in model.properties()]
        if meta.get("properties", prop_names) != prop_names:
            # q_ebits columns and discovery bits are indexed by property
            # position; a different set/order would silently misalign them.
            raise ValueError(
                "checkpoint was taken with a different property list "
                f"({meta['properties']} != {prop_names})"
            )
        store_meta = meta.get("store")
        fs = cls(
            model,
            batch_size=batch_size,
            table_log2=meta["table_log2"],
            insert_variant=meta.get("insert_variant", "sort"),
            store="tiered" if store_meta else "device",
            **(
                {
                    "high_water": store_meta["high_water"],
                    "low_water": store_meta["low_water"],
                    "summary_log2": store_meta["summary_log2"],
                }
                if store_meta
                else {}
            ),
        )
        if store_meta:
            from ..store.tiered import TieredStore

            fs._store.close()  # replaced by the checkpointed tier
            fs._store = TieredStore.from_checkpoint(
                fs.table.size, store_meta,
                data["spill_fps"], data["spill_parents"],
            )
        fs.table.t_lo = jnp.asarray(data["t_lo"])
        fs.table.t_hi = jnp.asarray(data["t_hi"])
        fs.table.p_lo = jnp.asarray(data["p_lo"])
        fs.table.p_hi = jnp.asarray(data["p_hi"])
        fs._counts = meta["counts"]
        fs._disc = dict(meta["discoveries"])
        fs._hot_claims = int(meta.get("hot_claims", 0))
        if fs._telemetry:
            # Pre-restore steps happened in another process: count them as
            # uncaptured so the resumed digest stays honest.
            fs._ring = StepRing(fs._tm_capacity)
            fs._ring.skip_to(int(meta["counts"].get("steps", 0)))
        fs._q = deque()
        off = 0
        for ln, depth in zip(data["q_lens"], data["q_depths"]):
            ln = int(ln)
            fs._q.append(
                _Chunk(
                    data["q_states"][off : off + ln],
                    data["q_lo"][off : off + ln],
                    data["q_hi"][off : off + ln],
                    data["q_ebits"][off : off + ln],
                    int(depth),
                )
            )
            off += ln
        return fs

    # -- path reconstruction ---------------------------------------------------

    def reconstruct_path(self, fp: int) -> Path:
        parent_map = self.table.dump()
        if self._store is not None:
            # Spill entries win on keys present in both tiers: they carry
            # the ORIGINAL (BFS-discovery) parent, which keeps the walked
            # chain acyclic; a post-spill re-claim's parent can sit deeper
            # than the state itself.
            parent_map.update(self._store.parent_map())
        return reconstruct_path(self.model, parent_map, fp)
