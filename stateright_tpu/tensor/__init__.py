"""The TPU-native checker core: batched frontier expansion on device.

This is the performance path that replaces the reference's thread/work-stealing
design (src/checker/bfs.rs + src/job_market.rs) with frontier-synchronous
batched BFS (SURVEY.md §7):

- a state is a fixed-width row of uint32 lanes; a model defines one vectorized
  transition kernel `expand(states) -> (successors, valid_mask)` with the
  action dimension enumerated statically — one `jit` call expands thousands of
  states per step instead of one thread expanding one state at a time;
- fingerprints are 64-bit mixes computed on device; the visited set is a
  device-resident open-addressing hash table in HBM whose insert kernel also
  stores parent fingerprints for TLC-style path reconstruction
  (mirroring the parent pointers at src/checker/bfs.rs:301-315);
- property predicates are vectorized masks; eventually-bits ride along as a
  per-state bitmask lane (src/checker.rs:580-587 semantics preserved);
- multi-chip runs shard the table by fingerprint ownership and exchange
  successors with all_to_all collectives (stateright_tpu.tensor.sharding),
  replacing the job market's work stealing.

Importing this package enables 64-bit array types (needed for on-device u64
fingerprints; TPUs emulate 64-bit integer ops).
"""

import jax

jax.config.update("jax_enable_x64", True)

from .model import TensorModel, TensorProperty  # noqa: E402
from .fingerprint import device_fingerprint  # noqa: E402
from .hashtable import HashTable  # noqa: E402
from .frontier import FrontierSearch, SearchResult  # noqa: E402

__all__ = [
    "TensorModel",
    "TensorProperty",
    "device_fingerprint",
    "HashTable",
    "FrontierSearch",
    "SearchResult",
]
