"""The TPU-native checker core: batched frontier expansion on device.

This is the performance path that replaces the reference's thread/work-stealing
design (src/checker/bfs.rs + src/job_market.rs) with frontier-synchronous
batched BFS (SURVEY.md §7):

- a state is a fixed-width row of uint32 lanes; a model defines one vectorized
  transition kernel `expand(states) -> (successors, valid_mask)` with the
  action dimension enumerated statically — one `jit` call expands thousands of
  states per step instead of one thread expanding one state at a time;
- fingerprints are 64-bit identities carried as PAIRS of uint32 lanes (TPUs
  have no native 64-bit integer ALU; see tensor/fingerprint.py) computed on
  device; the visited set is a device-resident bucketed hash table in HBM
  whose insert kernel also stores parent fingerprints for TLC-style path
  reconstruction (mirroring the parent pointers at src/checker/bfs.rs:301-315);
- property predicates are vectorized masks; eventually-bits ride along as a
  per-state bitmask lane (src/checker.rs:580-587 semantics preserved);
- multi-chip runs shard the table by fingerprint ownership and exchange
  successors with all_to_all collectives (stateright_tpu.parallel.sharded),
  replacing the job market's work stealing.

Everything is 32-bit on device: no `jax_enable_x64` required (the round-1
design forced it globally and paid u64 emulation tax in every hot op).
"""

from .model import TensorModel, TensorProperty
from .adapter import TensorModelAdapter, as_host_model
from .fingerprint import device_fingerprint, pack_fp, unpack_fp
from .hashtable import HashTable
from .frontier import FrontierSearch, SearchResult
from .lowering import (
    LoweredActorModel,
    LoweringError,
    lower_actor_model,
    refine_check,
)
from .simulation import DeviceSimulation

__all__ = [
    "DeviceSimulation",
    "TensorModelAdapter",
    "as_host_model",
    "TensorModel",
    "TensorProperty",
    "device_fingerprint",
    "pack_fp",
    "unpack_fp",
    "HashTable",
    "FrontierSearch",
    "SearchResult",
    "LoweredActorModel",
    "LoweringError",
    "lower_actor_model",
    "refine_check",
]
