"""Roofline cost model for the device engine step (VERDICT r5 #2).

Purpose: decide the split / kv / phased / capped insert race from COMMITTED
predictions instead of blind staging — the TPU tunnel admits a client a few
hours per round at best, so every silicon hour must race designs the model
already ranked, and every surprise must become a calibration update.

Anchor measurement (round-4 silicon, v5e, paxos-3: lanes=21, max_actions=14,
batch 3072, table 2^22, split sort-claim insert + DUS append): 12.9 ms/step
at 627k states/s, with the xplane attribution (ROUND4_NOTES.md "Round-5
perf breadcrumbs"):

    fusion.1137 (expand + fingerprint + props + append)   5.77 ms
    while.95    (insert: 4-op sort + bucket gathers + claim)  4.75 ms
    everything else (pop, compact, counters, masks)       ~2.4 ms

The per-op-class achieved bandwidths below are FIT to that attribution and
sit far below the v5e's 819 GB/s peak on purpose: rounds 4-5 measured the
engine at 1-2% effective HBM bandwidth, and the model's job is to
extrapolate from the machine that was measured, not the machine the spec
sheet promises. The VALUE of the model is the scaling structure — how each
term moves with batch, table size, lane count, and the new-candidate
fraction — which is what ranks the variants; absolute times are anchored
but soft.

This module is deliberately pure Python (no jax import): it must be usable
from bench.py's host side, the tuner, and tests without touching a backend.
Keep the layout constants in sync with tensor/hashtable.py (asserted by
tests/test_costmodel.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import NamedTuple, Optional

from ..knobs import COST_VARIANTS, SIM_DEDUP_KINDS

# Mirrors of tensor/hashtable.py layout constants (pinned by test).
BUCKET = 128
KV_BUCKET = 64
CLAIM_TILE = 4096
CAP_MAX_TILES = 64
BUCKET_ROW_BYTES = BUCKET * 4  # one gathered bucket row (512 B)

# Sort operand counts: the hoisted round-1 sort is 3 u32 operands
# (rotr-packed key, lo, iota — hashtable._insert_impl round-5 shape); the
# overflow-loop sort is 4 but runs ~zero iterations at sane load factors.
SORT_OPERANDS = 3

# The cost-variant alphabet lives in the one knob registry
# (stateright_tpu/knobs.py COST_VARIANTS); re-exported under the name this
# module has always used.
INSERT_VARIANTS = COST_VARIANTS

# (table_layout, insert_variant) engine options -> cost-model variant name.
# The single source of truth for this mapping: bench.py's roofline
# annotation and scripts/tpu_tune.py's predicted_ms both read it, so a new
# engine variant only needs a row here to be costed everywhere.
ENGINE_VARIANTS = {
    ("split", "sort"): "split",
    ("kv", "sort"): "kv",
    ("split", "phased"): "phased",
    ("split", "capped"): "capped",
    ("kv", "capped"): "capped-kv",
    ("split", "capped-phased"): "capped",
    ("split", "pallas"): "pallas",
}

# Mirrors of tensor/pallas_hashtable.py partitioning constants (pinned by
# tests/test_costmodel.py — this module stays jax-free, so the formula is
# restated, not imported).
PALLAS_ROW_ALIGN = 1024
PALLAS_DEFAULT_PARTITIONS = 64


def pallas_partition_count(table_slots: int) -> int:
    """pallas_hashtable.pallas_partitions without the jax import."""
    return max(
        1, min(PALLAS_DEFAULT_PARTITIONS, table_slots // PALLAS_ROW_ALIGN)
    )


@dataclass(frozen=True)
class DeviceSpec:
    """Peak numbers plus ACHIEVED per-op-class rates (calibrated, see module
    docstring). `hbm_gbps` is the roofline peak used for hbm_frac; the
    gbps_* rates are what this engine actually sustains per op class."""

    name: str
    hbm_gbps: float  # peak HBM bandwidth (roofline denominator)
    gbps_gather: float  # [B, 128] bucket-row gathers
    gbps_sort: float  # lax.sort, per operand-byte per pass-equivalent
    gbps_scatter: float  # claim/unsort scatters + readbacks
    gbps_stream: float  # contiguous DUS/compaction traffic
    ns_expand_elem: float  # expand+fingerprint+props fusion, per succ lane
    ns_other_lane: float  # pop/masks/counters residue, per flat succ lane
    ms_dispatch: float  # per serialized probe round / claim tile
    # Host link for the tiered store's eviction traffic (device-to-host
    # window pulls + spilled fingerprints). Uncalibrated default: no spill
    # event has run on silicon yet; the first tiered tunnel day anchors it.
    pcie_gbps: float = 12.0


# Fit to the r4 anchor (see module docstring); the split prediction for the
# anchor config must stay within ~20% of 12.9 ms (tests/test_costmodel.py).
V5E = DeviceSpec(
    name="tpu-v5e",
    hbm_gbps=819.0,
    gbps_gather=15.0,
    gbps_sort=8.0,
    gbps_scatter=3.0,
    gbps_stream=20.0,
    ns_expand_elem=6.15,
    ns_other_lane=55.8,
    ms_dispatch=0.01,
)

# Round-4 silicon: the row-scatter queue append moved ~2.4 GiB/s effective
# (44.7% of the paxos-3 step before the DUS form replaced it).
GBPS_APPEND_SCATTER = 2.6

# One CPU core of the rehearsal box, roughed in from the r4 CPU sweeps
# (paxos-3 b=32768 ~101k gen/s; no per-op attribution exists, so treat CPU
# *times* as low-confidence — CPU *bytes* are exact and are what
# cpu_bytes_per_state reports).
CPU1 = DeviceSpec(
    name="cpu-1core",
    hbm_gbps=12.0,
    gbps_gather=4.0,
    gbps_sort=0.8,
    gbps_scatter=2.0,
    gbps_stream=6.0,
    ns_expand_elem=15.0,
    ns_other_lane=80.0,
    ms_dispatch=0.05,
)


# -- calibration overlay (obs/calib.py fits it; this module only loads) ----
#: Env var naming a fitted-overlay JSON ({"base": <kind>, "rates": {...}})
#: written by `tpu_tune --calibrate`. Loading yields a NEW DeviceSpec — the
#: committed V5E/CPU1 anchors are never mutated, so the r4 anchor pin holds
#: with or without an overlay active.
CALIB_ENV = "SR_TPU_COSTMODEL_CALIB"

#: The committed per-kind specs, by DeviceSpec.name.
DEVICE_KINDS = {V5E.name: V5E, CPU1.name: CPU1}


def stock_device(kind: str) -> "DeviceSpec":
    """The committed spec for a device-kind name ("tpu-v5e" | "cpu-1core")."""
    try:
        return DEVICE_KINDS[kind]
    except KeyError:
        raise ValueError(
            f"unknown device kind {kind!r}; known: {sorted(DEVICE_KINDS)}"
        )


_CALIB_CACHE: dict = {}  # path -> (mtime, DeviceSpec)


def load_calibration(path: Optional[str] = None) -> Optional["DeviceSpec"]:
    """The fitted-overlay DeviceSpec from `path` (default: $CALIB_ENV), or
    None when no overlay is configured/readable. The returned spec keeps
    the base kind's `name` and `hbm_gbps` (roofline denominator) and
    overrides only the achieved rates present in the overlay's "rates"
    dict — a NEW instance every load path; stock specs stay frozen."""
    import json
    import os

    path = path or os.environ.get(CALIB_ENV)
    if not path:
        return None
    try:
        mtime = os.path.getmtime(path)
        hit = _CALIB_CACHE.get(path)
        if hit is not None and hit[0] == mtime:
            return hit[1]
        with open(path, "r") as f:
            doc = json.load(f)
        base = stock_device(doc["base"])
        rates = doc.get("rates") or {}
        fields = {
            k: float(v) for k, v in rates.items()
            if k in DeviceSpec.__dataclass_fields__ and float(v) > 0
        }
        from dataclasses import replace

        spec = replace(base, **fields)
    except (OSError, ValueError, KeyError, TypeError):
        return None
    _CALIB_CACHE[path] = (mtime, spec)
    return spec


class OpCost(NamedTuple):
    name: str
    bytes: float  # HBM bytes touched
    ms: float  # predicted time at the calibrated achieved rate


class StepCost(NamedTuple):
    total_ms: float
    total_bytes: float  # roofline numerator for hbm_frac
    ops: tuple  # OpCost rows, the per-op breakdown


def _ms(nbytes: float, gbps: float) -> float:
    return nbytes / (gbps * 1e9) * 1e3


SPILL_ENTRY_BYTES = 16  # (lo, hi, parent_lo, parent_hi) per evicted slot


def step_cost(
    lanes: int,
    max_actions: int,
    batch: int,
    table_log2: int,
    *,
    variant: str = "split",
    append: str = "dus",
    new_frac: float = 0.5,
    phased_rounds: float = 3.9,
    tile: int = CLAIM_TILE,
    device: DeviceSpec = V5E,
    spill: Optional[dict] = None,
) -> StepCost:
    """Predict one engine step for an insert `variant` (INSERT_VARIANTS).

    `new_frac` is the fraction of the B = batch x max_actions flat successor
    lanes the capped path must tile over — the POPULATED lanes (active and
    in-boundary; padding on sub-batch frontiers is compacted away before
    any tile runs). Estimate it as generated-states-per-step / B from a
    run, or 1.0 for a frontier that fills the batch. It only moves the
    capped variants.

    `phased_rounds` is the average serialized probe-round count of the
    phased scatter-max insert (r4 silicon measured ~3.9 on paxos-3).

    `table_log2` is DELIBERATELY inert for the XLA variants: per-lane probe
    traffic is one fixed 512-byte bucket row regardless of table size, and
    chain-overflow rounds are ~zero at sane load factors, so table size
    only matters through load factor — a term the r4 anchor cannot
    calibrate. The PALLAS variant is the exception: its kernel streams the
    whole partitioned table through VMEM once per insert call, so its
    `insert_stream` term scales directly with 2^table_log2 (the ranking
    lever — see the variant branch below).

    `spill` (None = plain device store; the None path is byte- and
    ms-identical to the pre-tiered model, pinned by the 1% anchor
    regression in tests/test_costmodel.py) models the tiered store's two
    device-side costs:
    - the per-step Bloom SUMMARY PROBE: `summary_hashes` (default 4) word
      gathers per flat successor lane, at the gather rate;
    - amortized EVICTION traffic: `evict_per_step` states/step crossing
      PCIe (window pull + spilled entries, 2x SPILL_ENTRY_BYTES each) plus
      the zeroed-window write-back at the stream rate.
    Host-side suspect resolution is deliberately NOT a device term: it
    overlaps the next dispatch on the host thread.
    """
    if variant not in INSERT_VARIANTS:
        raise ValueError(
            f"variant must be one of {INSERT_VARIANTS}, got {variant!r}"
        )
    K, A, L = batch, max_actions, lanes
    B = K * A
    ops = []

    # -- expand + fingerprint + property masks (the mega-fusion) ---------------
    expand_bytes = 4 * (K * L + 2 * B * L)
    ops.append(OpCost("expand_fuse", expand_bytes, B * L * device.ns_expand_elem * 1e-6))

    # -- visited-set insert, per variant ---------------------------------------
    log2_b = math.log2(max(B, 2))
    sort_bytes_full = SORT_OPERANDS * 4 * B * log2_b
    gather_lanes = 1 if variant in ("kv", "capped-kv") else 2
    gathers_full = gather_lanes * B * BUCKET_ROW_BYTES
    claim_misc_full = 8 * B * 4  # table scatters + unsort iota + readbacks

    if variant in ("split", "kv"):
        ops.append(OpCost("insert_sort", sort_bytes_full, _ms(sort_bytes_full, device.gbps_sort)))
        ops.append(OpCost("insert_gather", gathers_full, _ms(gathers_full, device.gbps_gather)))
        ops.append(OpCost("insert_claim", claim_misc_full, _ms(claim_misc_full, device.gbps_scatter) + device.ms_dispatch))
    elif variant == "phased":
        # No sort; `phased_rounds` serialized rounds, each a full-width
        # bucket gather + 3 scatter-max phases with readback gets.
        per_round_scatter = 16 * B * 4
        ops.append(OpCost(
            "insert_gather",
            phased_rounds * gathers_full,
            phased_rounds * _ms(gathers_full, device.gbps_gather),
        ))
        ops.append(OpCost(
            "insert_claim",
            phased_rounds * per_round_scatter,
            phased_rounds * (_ms(per_round_scatter, device.gbps_scatter) + device.ms_dispatch),
        ))
    elif variant == "pallas":
        # Route-then-probe (tensor/pallas_hashtable.py): ONE stable sort of
        # the batch by partition id (2 u32 operands: packed pid + iota)
        # replaces the sort-claim phase entirely; the kernel then streams
        # EVERY partition through VMEM once per insert call — a read+write
        # of all four table arrays, the table-size term no XLA variant has
        # (their per-lane probe traffic is one bucket row regardless of
        # table size). In-partition probes run serially at VMEM speed
        # (~free next to the HBM terms); the per-partition grid step is
        # not, and neither are the routing scatter-pack and the verdict
        # un-route. This is why the committed prediction ranks pallas by
        # the table:batch ratio — it wins only when the routed batch
        # amortizes the full-table round trip.
        S = 1 << table_log2
        n_parts = pallas_partition_count(S)
        route_sort = 2 * 4 * B * log2_b
        part_stream = 2 * 4 * S * 4  # 4 u32 arrays in + out of VMEM
        pack_bytes = 10 * B * 4  # route scatter-pack + verdict un-route
        ops.append(OpCost(
            "insert_sort", route_sort, _ms(route_sort, device.gbps_sort)
        ))
        ops.append(OpCost(
            "insert_stream", part_stream,
            _ms(part_stream, device.gbps_stream),
        ))
        ops.append(OpCost(
            "insert_claim", pack_bytes,
            _ms(pack_bytes, device.gbps_scatter)
            + n_parts * device.ms_dispatch,
        ))
    else:  # capped / capped-kv: active-compaction + claim tiles
        pow2_b = 1 << max(int(B) - 1, 1).bit_length()
        T = min(pow2_b, max(tile, pow2_b // CAP_MAX_TILES))
        n_tiles = max(math.ceil(new_frac * B / T), 0)
        compact = 10 * B * 4  # 5 u32 arrays, read+write, cumsum-scatter
        tile_sort = n_tiles * SORT_OPERANDS * 4 * T * math.log2(max(T, 2))
        tile_gather = n_tiles * gather_lanes * T * BUCKET_ROW_BYTES
        tile_claim = n_tiles * 8 * T * 4
        ops.append(OpCost("insert_compact", compact, _ms(compact, device.gbps_stream)))
        ops.append(OpCost("insert_sort", tile_sort, _ms(tile_sort, device.gbps_sort)))
        ops.append(OpCost("insert_gather", tile_gather, _ms(tile_gather, device.gbps_gather)))
        ops.append(OpCost(
            "insert_claim", tile_claim,
            _ms(tile_claim, device.gbps_scatter) + n_tiles * device.ms_dispatch,
        ))

    # -- queue append ----------------------------------------------------------
    append_bytes = 2 * 4 * (L + 4) * B  # compaction build + block write
    append_gbps = device.gbps_stream if append == "dus" else GBPS_APPEND_SCATTER
    ops.append(OpCost("append", append_bytes, _ms(append_bytes, append_gbps)))

    # -- tiered store: summary probe + amortized eviction ----------------------
    if spill is not None:
        if variant == "pallas":
            # The fused kernel probes the summary INSIDE its partition pass
            # (no separate maybe_contains gather sweep): the word array
            # rides into VMEM once per partition, so the probe cost is the
            # grid-replicated summary stream, not k gathers per lane.
            slog2 = int(spill.get("summary_log2", 20))
            n_parts = pallas_partition_count(1 << table_log2)
            # The kernel pads the word array to a tile-aligned block
            # (>= ROW_ALIGN words) and streams the WHOLE padded block per
            # grid step — small summaries still pay the padded size.
            probe_bytes = n_parts * max(
                PALLAS_ROW_ALIGN * 4, (1 << slog2) // 8
            )
            ops.append(OpCost(
                "spill_probe", probe_bytes,
                _ms(probe_bytes, device.gbps_stream),
            ))
        else:
            hashes = int(spill.get("summary_hashes", 4))
            probe_bytes = hashes * B * 4  # k word gathers per flat lane
            ops.append(OpCost(
                "spill_probe", probe_bytes,
                _ms(probe_bytes, device.gbps_gather),
            ))
        evict_per_step = float(spill.get("evict_per_step", 0.0))
        if evict_per_step > 0:
            pcie_bytes = evict_per_step * 2 * SPILL_ENTRY_BYTES
            wb_bytes = evict_per_step * SPILL_ENTRY_BYTES
            ops.append(OpCost(
                "spill_evict",
                pcie_bytes + wb_bytes,
                _ms(pcie_bytes, device.pcie_gbps)
                + _ms(wb_bytes, device.gbps_stream),
            ))

    # -- pop / counters / discovery residue ------------------------------------
    other_bytes = 4 * (L + 4) * B
    ops.append(OpCost("other", other_bytes, B * device.ns_other_lane * 1e-6))

    return StepCost(
        total_ms=sum(o.ms for o in ops),
        total_bytes=sum(o.bytes for o in ops),
        ops=tuple(ops),
    )


def bytes_per_state(
    lanes: int,
    max_actions: int,
    batch: int,
    table_log2: int,
    states_per_step: float,
    *,
    variant: str = "split",
    append: str = "dus",
    new_frac: float = 0.5,
    device: DeviceSpec = V5E,
    spill: Optional[dict] = None,
) -> float:
    """HBM bytes touched per GENERATED state: the step's modeled byte total
    over the measured states-per-step (state_count / steps from a run)."""
    sc = step_cost(
        lanes, max_actions, batch, table_log2,
        variant=variant, append=append, new_frac=new_frac, device=device,
        spill=spill,
    )
    return sc.total_bytes / max(states_per_step, 1e-9)


def hbm_frac(
    states_per_sec: float,
    bytes_per_state_: float,
    device: DeviceSpec = V5E,
) -> float:
    """Effective HBM fraction — the MFU analogue this engine is judged on
    (VERDICT r4/r5: ~1-2%): modeled bytes moved per second over peak."""
    return states_per_sec * bytes_per_state_ / (device.hbm_gbps * 1e9)


def sim_step_cost(
    lanes: int,
    max_actions: int,
    traces: int,
    *,
    dedup: str = "trace",
    cycle_log2: int = 9,
    ring: int = 64,
    table_log2: int = 20,
    variant: str = "capped",
    device: DeviceSpec = V5E,
) -> StepCost:
    """Predict one device-simulation walk step (tensor/simulation.py): all
    `traces` lanes evaluate properties, detect cycles, and step at once.

    The structure is the frontier step minus the queue plane (walks carry
    no frontier; the per-lane path append is a contiguous column write)
    plus the cycle-detection term the exhaustive engines do not have:

    - ``dedup="trace"``: the per-lane generation-stamped cycle table — an
      expected ~2 serialized probe rounds of one-slot gathers across three
      [T, 2^cycle_log2] arrays (random access, gather rate).
    - ``dedup="shared"``: the per-walk ring scan (3 contiguous [T, ring]
      arrays, stream rate) plus the shared-table insert — the same
      tensor/inserts.py design the exhaustive engines run, priced by the
      existing `step_cost` insert branch at batch = traces x 1 flat lane.

    Walks/s for a workload follows as traces / (mean_walk_len x step_time)
    (`sim_walks_per_sec`); with continuous walk batching the lanes stay
    full, so the prediction needs no tail-idle correction — that is the
    point of the design.
    """
    if dedup not in SIM_DEDUP_KINDS:  # knob universe: knobs.py
        raise ValueError(
            f"dedup must be one of {SIM_DEDUP_KINDS}, got {dedup!r}"
        )
    T, A, L = traces, max_actions, lanes
    B = T * A
    ops = []

    # expand + fingerprint + property masks (same mega-fusion shape).
    expand_bytes = 4 * (T * L + 2 * B * L)
    ops.append(OpCost(
        "expand_fuse", expand_bytes, B * L * device.ns_expand_elem * 1e-6
    ))

    # uniform successor choice: per-lane RNG fold-in + cumsum/argmax pick.
    choose_bytes = 8 * B * 4
    ops.append(OpCost(
        "walk_choose", choose_bytes, _ms(choose_bytes, device.gbps_stream)
    ))

    if dedup == "trace":
        # ~2 serialized probe rounds, one random slot per lane per round
        # across (lo, hi, gen); each round is a dispatch.
        probe_rounds = 2.0
        probe_bytes = probe_rounds * 3 * T * 4
        ops.append(OpCost(
            "cycle_probe", probe_bytes,
            _ms(probe_bytes, device.gbps_gather)
            + probe_rounds * device.ms_dispatch,
        ))
    else:
        ring_bytes = 3 * T * ring * 4
        ops.append(OpCost(
            "cycle_ring", ring_bytes, _ms(ring_bytes, device.gbps_stream)
        ))
        # The shared-table insert at batch = traces (one fp per lane per
        # step): the SAME priced design the exhaustive engines run.
        insert = step_cost(
            lanes, 1, traces, table_log2, variant=variant, device=device
        )
        for op in insert.ops:
            if op.name.startswith("insert_"):
                ops.append(op)

    # path append (contiguous column write) + ending/restart residue.
    other_bytes = 4 * (L + 6) * T
    ops.append(OpCost("other", other_bytes, T * device.ns_other_lane * 1e-6))

    return StepCost(
        total_ms=sum(o.ms for o in ops),
        total_bytes=sum(o.bytes for o in ops),
        ops=tuple(ops),
    )


def sim_walks_per_sec(
    lanes: int,
    max_actions: int,
    traces: int,
    mean_walk_len: float,
    *,
    dedup: str = "trace",
    device: DeviceSpec = V5E,
    **kw,
) -> float:
    """Committed walks/s prediction: with continuous batching every lane
    completes a walk every `mean_walk_len` steps, so throughput is
    traces / (mean_walk_len x step_time)."""
    sc = sim_step_cost(
        lanes, max_actions, traces, dedup=dedup, device=device, **kw
    )
    return traces / (max(mean_walk_len, 1.0) * sc.total_ms * 1e-3)


def predict_ranking(
    lanes: int,
    max_actions: int,
    batch: int,
    table_log2: int,
    *,
    new_frac: float = 0.5,
    append: str = "dus",
    device: DeviceSpec = V5E,
    variants: Optional[tuple] = None,
    spill: Optional[dict] = None,
) -> list:
    """Rank insert variants by predicted step time (fastest first). Returns
    [{"variant", "total_ms", "insert_ms", "bytes"}...] — the committed
    prediction format ROUND6_NOTES.md and the tuner's ranking JSON use."""
    out = []
    for v in variants or INSERT_VARIANTS:
        sc = step_cost(
            lanes, max_actions, batch, table_log2,
            variant=v, append=append, new_frac=new_frac, device=device,
            spill=spill,
        )
        out.append({
            "variant": v,
            "total_ms": round(sc.total_ms, 3),
            "insert_ms": round(
                sum(o.ms for o in sc.ops if o.name.startswith("insert_")), 3
            ),
            "bytes": int(sc.total_bytes),
        })
    return sorted(out, key=lambda r: r["total_ms"])
