"""Device-resident visited set: an open-addressing hash table over HBM.

Replaces the reference's sharded concurrent `DashMap<Fingerprint,
Option<Fingerprint>>` (ref: src/checker/bfs.rs:29-30): keys are nonzero uint64
fingerprints, values are parent fingerprints for path reconstruction.

The batched insert-if-absent kernel resolves intra-batch slot races with a
scatter-max claim: every still-probing lane proposes its fingerprint for its
current (free) slot, the maximum proposal wins the slot, losers advance to the
next probe position. Linear-probing lookups stay correct because slots are
claimed only when observed free along the probe chain and are never emptied.

The caller must pre-deduplicate fingerprints within a batch (two lanes with the
same fp would both observe a "hit" or both claim — FrontierSearch sorts and
masks duplicates before inserting).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

MAX_PROBES = 128


class InsertResult(NamedTuple):
    keys: jnp.ndarray  # uint64[S]
    parents: jnp.ndarray  # uint64[S]
    is_new: jnp.ndarray  # bool[B] — inserted by this call
    overflow: jnp.ndarray  # bool — some lane exhausted MAX_PROBES


class HashTable:
    """Host-side handle; the arrays live on device."""

    def __init__(self, log2_size: int):
        self.log2_size = log2_size
        self.size = 1 << log2_size
        self.keys = jnp.zeros(self.size, dtype=jnp.uint64)
        self.parents = jnp.zeros(self.size, dtype=jnp.uint64)

    def insert(self, fps, parent_fps, active) -> InsertResult:
        res = _insert(self.keys, self.parents, fps, parent_fps, active)
        self.keys, self.parents = res.keys, res.parents
        return res

    def dump(self) -> dict:
        """Host dict {fingerprint: parent_fingerprint (0 = init)} — used once
        per search for path reconstruction."""
        import numpy as np

        keys = np.asarray(self.keys)
        parents = np.asarray(self.parents)
        nz = keys != 0
        return dict(zip(keys[nz].tolist(), parents[nz].tolist()))


def _insert_impl(keys, parents, fps, parent_fps, active) -> InsertResult:
    size = keys.shape[0]
    mask = jnp.uint64(size - 1)
    idx = (fps & mask).astype(jnp.int64)

    def cond(carry):
        _keys, _parents, _idx, done, _is_new, probes = carry
        return (~jnp.all(done)) & (probes < MAX_PROBES)

    def body(carry):
        keys, parents, idx, done, is_new, probes = carry
        cur = keys[idx]
        hit = cur == fps
        free = cur == 0
        attempt = (~done) & free
        # Scatter-max claim: duplicate target slots resolve deterministically
        # to the largest proposing fingerprint; done lanes propose 0 (no-op).
        proposal = jnp.where(attempt, fps, jnp.uint64(0))
        keys = keys.at[idx].max(proposal)
        claimed = attempt & (keys[idx] == fps)
        # Record the parent for claimed slots (claimed slots are unique per
        # lane, so a plain dropped-out-of-bounds scatter is race-free).
        pidx = jnp.where(claimed, idx, size)
        parents = parents.at[pidx].set(parent_fps, mode="drop")
        done = done | hit | claimed
        is_new = is_new | claimed
        idx = jnp.where(done, idx, (idx + 1) & jnp.int64(size - 1))
        return keys, parents, idx, done, is_new, probes + 1

    done0 = ~active
    is_new0 = jnp.zeros_like(active)
    keys, parents, idx, done, is_new, _probes = jax.lax.while_loop(
        cond, body, (keys, parents, idx, done0, is_new0, jnp.int32(0))
    )
    return InsertResult(keys, parents, is_new, ~jnp.all(done))


_insert = partial(jax.jit, donate_argnums=(0, 1))(_insert_impl)
