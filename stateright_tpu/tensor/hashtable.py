"""Device-resident visited set: a bucketed open-addressing hash table over
HBM, keyed by (lo, hi) uint32 fingerprint pairs.

Replaces the reference's sharded concurrent `DashMap<Fingerprint,
Option<Fingerprint>>` (ref: src/checker/bfs.rs:29-30): key identity is the
full 64-bit fingerprint (as two u32 lanes — see tensor/fingerprint.py for why
pairs), values are parent fingerprints for path reconstruction.

TPU-shaped design: random HBM access is the enemy (a probe loop touching one
slot at a time serializes; it measured ~270 ms per 128k-insert batch on a
v5e). So slots are grouped into BUCKETS of 8 contiguous u32s — one gather
fetches a whole 32-byte bucket row — and a round inspects 8 slots at once:

1. gather the bucket rows for all still-unresolved keys,
2. hit if any slot matches (lo, hi),
3. otherwise claim the first free slot (lo == 0) in phased scatter-max
   steps: propose `lo` (slot winner = max proposal), lo-winners propose `hi`
   (tie-break among equal-lo distinct keys), then (lo, hi)-winners race their
   lane index in a scratch arena so exactly ONE of several identical
   fingerprints in the same batch wins `is_new`. Losers of phases 1-2 retry
   next round; identical-fingerprint losers of phase 3 resolve as duplicates;
   full buckets overflow to the next bucket, wrapping modulo the table.

Safety argument for the phased claim: a committed slot always has lo != 0, so
later rounds/calls never scatter into it (free-slot claims only); within a
round all proposals land in one scatter-max, so rivals are serialized by the
max semantics, and losers observe a mismatched readback and retry. Claimed
slots are never emptied, so linear bucket probing stays correct.

Unlike the round-1 design, batches may contain duplicate fingerprints: the
phase-3 arena attributes exactly one `is_new` per distinct new key (the
engines no longer pre-sort batches — sorting 64-bit keys was a per-step tax).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

BUCKET = 8
MAX_ROUNDS = 64


class InsertResult(NamedTuple):
    t_lo: jnp.ndarray  # uint32[S]
    t_hi: jnp.ndarray  # uint32[S]
    p_lo: jnp.ndarray  # uint32[S]
    p_hi: jnp.ndarray  # uint32[S]
    is_new: jnp.ndarray  # bool[B] — inserted by this call (one per distinct key)
    overflow: jnp.ndarray  # bool — some lane exhausted MAX_ROUNDS


class HashTable:
    """Host-side handle; the arrays live on device."""

    def __init__(self, log2_size: int):
        self.log2_size = log2_size
        self.size = 1 << log2_size
        if self.size < BUCKET:
            raise ValueError(f"table must have at least {BUCKET} slots")
        self.t_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.t_hi = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_hi = jnp.zeros(self.size, dtype=jnp.uint32)

    def insert(self, lo, hi, parent_lo, parent_hi, active) -> InsertResult:
        res = _insert(
            self.t_lo, self.t_hi, self.p_lo, self.p_hi,
            lo, hi, parent_lo, parent_hi, active,
        )
        self.t_lo, self.t_hi, self.p_lo, self.p_hi = res[:4]
        return res

    def dump(self) -> dict:
        """Host dict {fingerprint: parent_fingerprint (0 = init)} — used once
        per search for path reconstruction."""
        from .fingerprint import pack_fp

        t_lo = np.asarray(self.t_lo)
        nz = t_lo != 0
        keys = pack_fp(t_lo[nz], np.asarray(self.t_hi)[nz])
        parents = pack_fp(np.asarray(self.p_lo)[nz], np.asarray(self.p_hi)[nz])
        return dict(zip(keys.tolist(), parents.tolist()))


def _insert_impl(t_lo, t_hi, p_lo, p_hi, lo, hi, parent_lo, parent_hi, active):
    """Batched insert-if-absent. Returns InsertResult; see module docstring.

    The phase-3 arena reuses `p_lo` as scratch: a freshly claimed slot's
    parent entry is still zero (parents are only written at the end, to slots
    whose claim succeeded), so claimants race `lane_index + 1` there with
    scatter-max and exactly one survives; the real parent value overwrites the
    arena residue immediately after the loop.
    """
    size = t_lo.shape[0]
    n_buckets = size // BUCKET
    bmask = jnp.uint32(n_buckets - 1)
    b0 = hi & bmask
    lane_ix = jnp.arange(lo.shape[0], dtype=jnp.uint32) + jnp.uint32(1)

    def cond(carry):
        (_tl, _th, _pl, done, _new, _slot, _off, rounds) = carry
        return (~jnp.all(done)) & (rounds < MAX_ROUNDS)

    def body(carry):
        t_lo, t_hi, p_lo, done, is_new, slot, off, rounds = carry
        b = ((b0 + off) & bmask).astype(jnp.int32)
        rows_lo = t_lo.reshape(n_buckets, BUCKET)[b]  # [B, 8] one 32B gather
        rows_hi = t_hi.reshape(n_buckets, BUCKET)[b]
        hit_j = (rows_lo == lo[:, None]) & (rows_hi == hi[:, None])
        hit = (~done) & jnp.any(hit_j, axis=1)
        hit_slot = b * BUCKET + jnp.argmax(hit_j, axis=1).astype(jnp.int32)

        free = rows_lo == 0
        has_free = jnp.any(free, axis=1)
        cand = b * BUCKET + jnp.argmax(free, axis=1).astype(jnp.int32)
        attempt = (~done) & (~hit) & has_free

        # Phase 1: claim the slot's lo by scatter-max (winner = max lo).
        tgt = jnp.where(attempt, cand, size)
        t_lo = t_lo.at[tgt].max(jnp.where(attempt, lo, 0), mode="drop")
        got_lo = attempt & (t_lo.at[cand].get(mode="fill", fill_value=0) == lo)
        # Phase 2: lo-winners tie-break on hi (equal-lo distinct keys).
        tgt = jnp.where(got_lo, cand, size)
        t_hi = t_hi.at[tgt].max(jnp.where(got_lo, hi, 0), mode="drop")
        claimed = got_lo & (
            t_hi.at[cand].get(mode="fill", fill_value=0) == hi
        )
        # Phase 3: identical fingerprints all pass phase 2 together; race the
        # lane index in the arena so exactly one wins is_new.
        tgt = jnp.where(claimed, cand, size)
        p_lo = p_lo.at[tgt].max(jnp.where(claimed, lane_ix, 0), mode="drop")
        winner = claimed & (
            p_lo.at[cand].get(mode="fill", fill_value=0) == lane_ix
        )

        slot = jnp.where(hit | claimed, jnp.where(hit, hit_slot, cand), slot)
        is_new = is_new | winner
        newly_done = hit | claimed
        # Full bucket (no free slot, no hit): overflow to the next bucket.
        off = jnp.where((~done) & (~newly_done) & (~has_free), off + 1, off)
        return (
            t_lo, t_hi, p_lo, done | newly_done, is_new, slot, off, rounds + 1
        )

    done0 = ~active
    zeros_i = jnp.zeros_like(lo, dtype=jnp.int32)
    t_lo, t_hi, p_lo, done, is_new, slot, _off, _rounds = jax.lax.while_loop(
        cond,
        body,
        (t_lo, t_hi, p_lo, done0, jnp.zeros_like(active), zeros_i, zeros_i,
         jnp.int32(0)),
    )

    # Parents: one scatter per component, winning lanes only (unique slots),
    # overwriting any phase-3 arena residue in p_lo.
    ptgt = jnp.where(is_new, slot, size)
    p_lo = p_lo.at[ptgt].set(parent_lo, mode="drop")
    p_hi = p_hi.at[ptgt].set(parent_hi, mode="drop")
    return InsertResult(t_lo, t_hi, p_lo, p_hi, is_new, ~jnp.all(done))


_insert = partial(jax.jit, donate_argnums=(0, 1, 2, 3))(_insert_impl)
