"""Device-resident visited set: a bucketed open-addressing hash table over
HBM, keyed by (lo, hi) uint32 fingerprint pairs.

Replaces the reference's sharded concurrent `DashMap<Fingerprint,
Option<Fingerprint>>` (ref: src/checker/bfs.rs:29-30): key identity is the
full 64-bit fingerprint (as two u32 lanes — see tensor/fingerprint.py for why
pairs), values are parent fingerprints for path reconstruction.

TPU-shaped design: random HBM access is the enemy (a probe loop touching one
slot at a time serializes; it measured ~270 ms per 128k-insert batch on a
v5e). So slots are grouped into BUCKETS of 128 contiguous u32s — one row
gather fetches a whole 512-byte bucket — and a round inspects 128 slots at
once. The bucket width IS the TPU lane count on purpose: a (S/128, 128)
view of the flat table is layout-identical under T(8,128) tiling, so the
per-round reshape inside the probe loop is a free bitcast. (The previous
8-wide bucket view was tile-padded 16x and MATERIALIZED every probe round —
an 8 GB HLO temp at table 2^27 that OOMed 2pc-10 on a 16 GB v5e; and a
flat 2D-index gather of 8-slot rows measured 1.3-1.8x slower than the row
gather it replaced.) A 128-slot bucket also makes chain overflow to the
next bucket vanishingly rare at any sane load factor:

1. sort the batch by (bucket, key) — duplicates become adjacent (one REP
   lane per distinct key; the rest resolve immediately), same-bucket
   claimants become contiguous,
2. gather each lane's bucket row; a rep hits if any slot matches (lo, hi),
3. otherwise reps claim DISTINCT free slots — the rank-th same-bucket rep
   takes the rank-th free lane (ranks from prefix sums over the sorted
   order) — so every table write is a race-free unique_indices scatter;
   full buckets overflow to the next bucket, wrapping modulo the table.

Safety argument: claim targets are unique by construction (distinct
(bucket, rank) pairs), a committed slot always has lo != 0 and is never
emptied, so linear bucket probing and first-non-full-bucket membership stay
correct across rounds and calls. Batches may contain duplicate
fingerprints: rep selection attributes exactly one `is_new` per distinct
new key. See `_insert_impl` for why this sort-claim form replaced the
round-1..3 phased scatter-max claim (silicon profile: ~3.9 serialized
rounds per step and sort-based non-unique scatter lowering).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

BUCKET = 128
MAX_ROUNDS = 64

# Capped insert path (see make_capped_insert): claim tiles are at least
# this many lanes (power of two — keeps tile shapes static under
# jit/while_loop); CAP_MAX_TILES bounds the serialized tile count by
# growing the tile for very large batches.
CLAIM_TILE = 4096
CAP_MAX_TILES = 64


class InsertResult(NamedTuple):
    t_lo: jnp.ndarray  # uint32[S]
    t_hi: jnp.ndarray  # uint32[S]
    p_lo: jnp.ndarray  # uint32[S]
    p_hi: jnp.ndarray  # uint32[S]
    is_new: jnp.ndarray  # bool[B] — inserted by this call (one per distinct key)
    overflow: jnp.ndarray  # bool — some lane exhausted MAX_ROUNDS


class HashTable:
    """Host-side handle; the arrays live on device."""

    def __init__(self, log2_size: int):
        self.log2_size = log2_size
        self.size = 1 << log2_size
        self.t_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.t_hi = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_hi = jnp.zeros(self.size, dtype=jnp.uint32)

    def insert(self, lo, hi, parent_lo, parent_hi, active) -> InsertResult:
        res = _insert(
            self.t_lo, self.t_hi, self.p_lo, self.p_hi,
            lo, hi, parent_lo, parent_hi, active,
        )
        self.t_lo, self.t_hi, self.p_lo, self.p_hi = res[:4]
        return res

    def dump(self) -> dict:
        """Host dict {fingerprint: parent_fingerprint (0 = init)} — used once
        per search for path reconstruction."""
        from .fingerprint import pack_fp

        t_lo = np.asarray(self.t_lo)
        nz = t_lo != 0
        keys = pack_fp(t_lo[nz], np.asarray(self.t_hi)[nz])
        parents = pack_fp(np.asarray(self.p_lo)[nz], np.asarray(self.p_hi)[nz])
        return dict(zip(keys.tolist(), parents.tolist()))


def _rotr(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """uint32 rotate-right by a static 0 <= k < 32 (k=0 is the identity —
    guarded because a shift by 32 is undefined in HLO)."""
    if k == 0:
        return x
    return (x >> jnp.uint32(k)) | (x << jnp.uint32(32 - k))


def _insert_impl(t_lo, t_hi, p_lo, p_hi, lo, hi, parent_lo, parent_hi, active):
    """Batched insert-if-absent. Returns InsertResult; see module docstring.

    Sort-claim design (round-4 silicon profile: the previous phased
    scatter-max claim averaged ~3.9 probe rounds per engine step — colliding
    keys raced for the SAME free slot and serialized round by round — and
    its non-unique scatters lowered to sort-based HLO; together the insert
    was 54% of the paxos-3 step). Here every round is race-free:

    1. sort lanes by (target bucket, hi, lo) — one lax.sort; identical keys
       become adjacent (first pending lane of a run is the REP; the rest
       resolve as duplicates immediately), same-bucket reps are contiguous;
    2. gather each lane's bucket row; reps hit if their key is present;
    3. reps needing a slot get a per-bucket RANK (prefix sums over the
       sorted order) and claim the rank-th free lane of their bucket row —
       distinct (bucket, rank) pairs make all claim targets UNIQUE, so all
       four table components are written with single unique_indices
       scatters: no phases, no readbacks, no arena, and exactly one is_new
       per distinct new key by construction;
    4. only reps whose bucket ran out of free lanes carry to the next round
       (off+1 — chain overflow), so the expected round count is ~1.

    Round-5 shape: the first round is HOISTED out of the while loop as a
    3-operand sort (the loop's 4-operand sort was the single largest op of
    the paxos-3 step — `while.95`, 4.75 of 12.9 ms in the round-4 silicon
    profile). In round 1 every pending lane probes bucket `hi & mask`, so
    the (bucket, hi) sort pair collapses into ONE key: `rotr(hi, log2_nb)`
    moves the bucket bits to the top — sorting by it IS sorting by
    (bucket, rest-of-hi), and it is a bijection of hi, so run detection and
    bucket recovery read the sorted operand directly (no gathers). Inactive
    lanes sort to the unique sentinel pair (0xFFFFFFFF, lo=0) — a real key
    never has lo == 0 (tensor/fingerprint.py forces lo nonzero) — which
    also keeps equal-key runs contiguous in the one tie block. The unsort
    is one iota scatter (the inverse permutation) + cheap gathers instead
    of three scatters. The while loop below only runs for bucket-overflow
    carries (per-lane probe offsets diverge there, so it keeps the general
    4-operand sort) — at sane load factors it executes ZERO iterations.

    Resolved/inactive lanes sort to a sentinel bucket past the end, which
    also keeps a key run's rep well-defined when some of its lanes are
    inactive. Claimed slots are never emptied, so linear bucket probing and
    the membership argument (a key absent from the first non-full bucket of
    its chain is absent) stay correct.
    """
    size = t_lo.shape[0]
    bucket = min(BUCKET, size)  # tiny tables (tests) shrink to one bucket
    n_buckets = size // bucket
    log2_nb = n_buckets.bit_length() - 1  # size and bucket are powers of 2
    B = lo.shape[0]
    bmask = jnp.int32(n_buckets - 1)
    b0 = (hi & jnp.uint32(n_buckets - 1)).astype(jnp.int32)
    idx = jnp.arange(B, dtype=jnp.int32)

    def claim(t_lo, t_hi, p_lo, p_hi, is_new_in, sb, s_hi, s_lo, s_active,
              perm):
        """One race-free claim round over pre-sorted lanes (shared by the
        hoisted fast path and the overflow loop; see the kv variant for the
        same shape). Returns carry_on in ORIGINAL lane order."""
        same_prev = (
            (sb == jnp.roll(sb, 1))
            & (s_hi == jnp.roll(s_hi, 1))
            & (s_lo == jnp.roll(s_lo, 1))
        ).at[0].set(False)
        rep = s_active & ~same_prev

        rows_lo = t_lo.reshape(n_buckets, bucket)[sb]  # free bitcast view
        rows_hi = t_hi.reshape(n_buckets, bucket)[sb]
        hit = rep & jnp.any(
            (rows_lo == s_lo[:, None]) & (rows_hi == s_hi[:, None]), axis=1
        )
        need = rep & ~hit

        # Per-bucket rank of `need` lanes: exclusive prefix count within the
        # sorted bucket segment (segment base carried forward by cummax —
        # the exclusive prefix is non-decreasing, and lane 0 always starts a
        # segment, so the -1 filler never wins).
        seg_start = (sb != jnp.roll(sb, 1)).at[0].set(True)
        excl = jnp.cumsum(need.astype(jnp.int32)) - need.astype(jnp.int32)
        seg_base = jax.lax.cummax(jnp.where(seg_start, excl, jnp.int32(-1)))
        rank = excl - seg_base

        free_m = rows_lo == 0
        # Lane-wise inclusive prefix count as one MXU matmul against an
        # upper-triangular ones matrix: XLA lowers an axis-1 cumsum to
        # reduce_window (~2.7 ms/step on v5e at engine batch sizes) while
        # the [B,128]@[128,128] matmul is ~free; counts <= 128 are exact in
        # bf16 with f32 accumulation.
        tri = jnp.triu(jnp.ones((bucket, bucket), jnp.bfloat16))
        fcum = (
            jnp.dot(
                free_m.astype(jnp.bfloat16),
                tri,
                preferred_element_type=jnp.float32,
            )
            .astype(jnp.int32)
        )
        pick = free_m & (fcum == (rank + 1)[:, None])  # rank-th free lane
        can_claim = need & jnp.any(pick, axis=1)
        slot = sb * bucket + jnp.argmax(pick, axis=1).astype(jnp.int32)

        tgt = jnp.where(can_claim, slot, size)
        t_lo = t_lo.at[tgt].set(s_lo, mode="drop", unique_indices=True)
        t_hi = t_hi.at[tgt].set(s_hi, mode="drop", unique_indices=True)
        p_lo = p_lo.at[tgt].set(
            parent_lo[perm], mode="drop", unique_indices=True
        )
        p_hi = p_hi.at[tgt].set(
            parent_hi[perm], mode="drop", unique_indices=True
        )

        # Unsort via the inverse permutation: one iota scatter + gathers.
        inv_perm = jnp.zeros(B, jnp.int32).at[perm].set(
            idx, unique_indices=True
        )
        is_new = is_new_in | can_claim[inv_perm]
        carry_on = (need & ~can_claim)[inv_perm]  # full -> probe bucket +1
        return t_lo, t_hi, p_lo, p_hi, is_new, carry_on

    # -- round 1, hoisted: 3-operand sort-claim at probe offset 0 --------------
    key0 = jnp.where(active, _rotr(hi, log2_nb), jnp.uint32(0xFFFFFFFF))
    lo_m = jnp.where(active, lo, jnp.uint32(0))
    s_key0, s_lo, perm = jax.lax.sort((key0, lo_m, idx), num_keys=2)
    s_active = ~((s_key0 == jnp.uint32(0xFFFFFFFF)) & (s_lo == 0))
    s_hi = _rotr(s_key0, (32 - log2_nb) % 32)  # rotate back: bijection
    sb = (
        (s_key0 >> jnp.uint32(32 - log2_nb)).astype(jnp.int32)
        if log2_nb
        else jnp.zeros(B, jnp.int32)
    )
    t_lo, t_hi, p_lo, p_hi, is_new0, carry0 = claim(
        t_lo, t_hi, p_lo, p_hi, jnp.zeros_like(active), sb, s_hi, s_lo,
        s_active, perm,
    )
    off0 = carry0.astype(jnp.int32)

    def cond(carry):
        (_tl, _th, _pl, _ph, pending, _new, _off, rounds) = carry
        return jnp.any(pending) & (rounds < MAX_ROUNDS)

    def body(carry):
        t_lo, t_hi, p_lo, p_hi, pending, is_new, off, rounds = carry
        b = (b0 + off) & bmask
        bkey = jnp.where(pending, b, jnp.int32(n_buckets))
        sb, s_hi, s_lo, perm = jax.lax.sort(
            (bkey, hi, lo, idx), num_keys=3
        )
        s_active = sb < jnp.int32(n_buckets)
        sb_c = jnp.minimum(sb, jnp.int32(n_buckets - 1))
        t_lo, t_hi, p_lo, p_hi, is_new, carry_on = claim(
            t_lo, t_hi, p_lo, p_hi, is_new, sb_c, s_hi, s_lo, s_active, perm
        )
        off = off + carry_on.astype(jnp.int32)
        return t_lo, t_hi, p_lo, p_hi, carry_on, is_new, off, rounds + 1

    t_lo, t_hi, p_lo, p_hi, pending, is_new, _off, _rounds = (
        jax.lax.while_loop(
            cond,
            body,
            (t_lo, t_hi, p_lo, p_hi, carry0, is_new0, off0, jnp.int32(1)),
        )
    )
    return InsertResult(t_lo, t_hi, p_lo, p_hi, is_new, jnp.any(pending))


_insert = partial(jax.jit, donate_argnums=(0, 1, 2, 3))(_insert_impl)


class InsertKvResult(NamedTuple):
    t_kv: jnp.ndarray  # uint32[2S] interleaved-bucket table
    p_lo: jnp.ndarray  # uint32[S]
    p_hi: jnp.ndarray  # uint32[S]
    is_new: jnp.ndarray  # bool[B]
    overflow: jnp.ndarray  # bool


KV_BUCKET = 64  # slots per bucket; a row is 2*KV_BUCKET = 128 lanes (lo|hi)


def _insert_impl_kv(t_kv, p_lo, p_hi, lo, hi, parent_lo, parent_hi, active):
    """Interleaved-bucket variant of `_insert_impl`: the table is ONE
    uint32[2S] array whose 128-lane rows hold a 64-slot bucket as
    [lo_0..lo_63 | hi_0..hi_63], so each probe gathers HALF the bytes of
    the split layout (one [B, 128] row fetch instead of two) while the
    128-lane row keeps the (nb, 128) view a free bitcast under T(8,128)
    tiling — the same tile-padding argument that fixed the round-4 16x tax
    (module docstring). 64-slot buckets overflow to the next bucket exactly
    like 128-slot ones (vanishingly rare at sane load factors, and the
    carry loop handles it). Parents stay split (p_lo/p_hi, indexed by slot
    id) — they are only ever written here, never gathered.

    Claim logic is byte-for-byte the split fast path with bucket=64; see
    `_insert_impl` for the algorithm and safety argument. Flag-gated via
    the engines' `table_layout="kv"` until the silicon race decides a
    default (VERDICT r4 next #1: the bucket-row gathers were the
    second-largest slice of the insert after the sort).
    """
    size = p_lo.shape[0]  # S slots; t_kv has 2S lanes
    bucket = min(KV_BUCKET, size)
    n_buckets = size // bucket
    log2_nb = n_buckets.bit_length() - 1
    row_w = 2 * bucket
    B = lo.shape[0]
    bmask = jnp.int32(n_buckets - 1)
    b0 = (hi & jnp.uint32(n_buckets - 1)).astype(jnp.int32)
    idx = jnp.arange(B, dtype=jnp.int32)

    def claim(t_kv, p_lo, p_hi, is_new_in, sb, s_hi, s_lo, s_active, perm):
        """One race-free claim round over pre-sorted lanes (shared by the
        hoisted fast path and the overflow loop)."""
        same_prev = (
            (sb == jnp.roll(sb, 1))
            & (s_hi == jnp.roll(s_hi, 1))
            & (s_lo == jnp.roll(s_lo, 1))
        ).at[0].set(False)
        rep = s_active & ~same_prev

        rows = t_kv.reshape(n_buckets, row_w)[sb]  # free bitcast view
        rows_lo = rows[:, :bucket]
        rows_hi = rows[:, bucket:]
        hit = rep & jnp.any(
            (rows_lo == s_lo[:, None]) & (rows_hi == s_hi[:, None]), axis=1
        )
        need = rep & ~hit

        seg_start = (sb != jnp.roll(sb, 1)).at[0].set(True)
        excl = jnp.cumsum(need.astype(jnp.int32)) - need.astype(jnp.int32)
        seg_base = jax.lax.cummax(
            jnp.where(seg_start, excl, jnp.int32(-1))
        )
        rank = excl - seg_base

        free_m = rows_lo == 0
        tri = jnp.triu(jnp.ones((bucket, bucket), jnp.bfloat16))
        fcum = (
            jnp.dot(
                free_m.astype(jnp.bfloat16), tri,
                preferred_element_type=jnp.float32,
            )
            .astype(jnp.int32)
        )
        pick = free_m & (fcum == (rank + 1)[:, None])
        can_claim = need & jnp.any(pick, axis=1)
        lane = jnp.argmax(pick, axis=1).astype(jnp.int32)

        tgt_lo = jnp.where(can_claim, sb * row_w + lane, 2 * size)
        tgt_hi = jnp.where(can_claim, sb * row_w + bucket + lane, 2 * size)
        slot = jnp.where(can_claim, sb * bucket + lane, size)
        t_kv = t_kv.at[tgt_lo].set(s_lo, mode="drop", unique_indices=True)
        t_kv = t_kv.at[tgt_hi].set(s_hi, mode="drop", unique_indices=True)
        p_lo = p_lo.at[slot].set(
            parent_lo[perm], mode="drop", unique_indices=True
        )
        p_hi = p_hi.at[slot].set(
            parent_hi[perm], mode="drop", unique_indices=True
        )

        inv_perm = jnp.zeros(B, jnp.int32).at[perm].set(
            idx, unique_indices=True
        )
        is_new = is_new_in | can_claim[inv_perm]
        carry_on = (need & ~can_claim)[inv_perm]
        return t_kv, p_lo, p_hi, is_new, carry_on

    # -- round 1, hoisted: 3-operand sort at probe offset 0 --------------------
    key0 = jnp.where(active, _rotr(hi, log2_nb), jnp.uint32(0xFFFFFFFF))
    lo_m = jnp.where(active, lo, jnp.uint32(0))
    s_key0, s_lo, perm = jax.lax.sort((key0, lo_m, idx), num_keys=2)
    s_active = ~((s_key0 == jnp.uint32(0xFFFFFFFF)) & (s_lo == 0))
    s_hi = _rotr(s_key0, (32 - log2_nb) % 32)
    sb = (
        (s_key0 >> jnp.uint32(32 - log2_nb)).astype(jnp.int32)
        if log2_nb
        else jnp.zeros(B, jnp.int32)
    )
    t_kv, p_lo, p_hi, is_new0, carry0 = claim(
        t_kv, p_lo, p_hi, jnp.zeros_like(active), sb, s_hi, s_lo,
        s_active, perm,
    )
    off0 = carry0.astype(jnp.int32)

    # -- overflow carries: generic 4-operand rounds (rare) ---------------------
    def cond(carry):
        (_kv, _pl, _ph, pending, _new, _off, rounds) = carry
        return jnp.any(pending) & (rounds < MAX_ROUNDS)

    def body(carry):
        t_kv, p_lo, p_hi, pending, is_new, off, rounds = carry
        b = (b0 + off) & bmask
        bkey = jnp.where(pending, b, jnp.int32(n_buckets))
        sb, s_hi, s_lo, perm = jax.lax.sort(
            (bkey, hi, lo, idx), num_keys=3
        )
        s_active = sb < jnp.int32(n_buckets)
        sb_c = jnp.minimum(sb, jnp.int32(n_buckets - 1))
        t_kv, p_lo, p_hi, is_new, carry_on = claim(
            t_kv, p_lo, p_hi, is_new, sb_c, s_hi, s_lo, s_active, perm
        )
        off = off + carry_on.astype(jnp.int32)
        return t_kv, p_lo, p_hi, carry_on, is_new, off, rounds + 1

    t_kv, p_lo, p_hi, pending, is_new, _off, _rounds = jax.lax.while_loop(
        cond, body, (t_kv, p_lo, p_hi, carry0, is_new0, off0, jnp.int32(1))
    )
    return InsertKvResult(t_kv, p_lo, p_hi, is_new, jnp.any(pending))


class HashTableKV:
    """Host-side handle for the interleaved-bucket table (tests + dump)."""

    def __init__(self, log2_size: int):
        self.log2_size = log2_size
        self.size = 1 << log2_size
        self.t_kv = jnp.zeros(2 * self.size, dtype=jnp.uint32)
        self.p_lo = jnp.zeros(self.size, dtype=jnp.uint32)
        self.p_hi = jnp.zeros(self.size, dtype=jnp.uint32)

    def insert(self, lo, hi, parent_lo, parent_hi, active) -> InsertKvResult:
        res = _insert_kv(
            self.t_kv, self.p_lo, self.p_hi,
            lo, hi, parent_lo, parent_hi, active,
        )
        self.t_kv, self.p_lo, self.p_hi = res[:3]
        return res

    def dump(self) -> dict:
        from .fingerprint import pack_fp

        bucket = min(KV_BUCKET, self.size)
        kv = np.asarray(self.t_kv).reshape(-1, 2 * bucket)
        t_lo = kv[:, :bucket].reshape(-1)
        t_hi = kv[:, bucket:].reshape(-1)
        nz = t_lo != 0
        keys = pack_fp(t_lo[nz], t_hi[nz])
        parents = pack_fp(
            np.asarray(self.p_lo)[nz], np.asarray(self.p_hi)[nz]
        )
        return dict(zip(keys.tolist(), parents.tolist()))


_insert_kv = partial(jax.jit, donate_argnums=(0, 1, 2))(_insert_impl_kv)


def _insert_impl_phased(
    t_lo, t_hi, p_lo, p_hi, lo, hi, parent_lo, parent_hi, active
):
    """The round-1..3 PHASED scatter-max insert, revived as a raceable
    variant (VERDICT r4 next #7): at paxos-2 scale the sort-claim insert's
    fixed sort cost dominated tiny frontiers (162k -> 94k states/s at
    b=2048 on v5e) while the phased design's ~few serialized probe rounds
    are cheap when batches are small and collisions rare. The engines race
    it per-workload via `ResidentSearch(insert_variant="phased")` /
    scripts/tpu_tune.py; the sort-claim stays the at-scale default (2.5-3.7x
    faster at paxos-3 scale — the 54%-of-step profile that retired this
    design, now with the round-5 128-lane buckets it never had).

    Claim protocol per probe round (all races resolved by scatter-max):
    phase 1 races `lo` into the bucket's first free slot (winner = max lo),
    phase 2 tie-breaks equal-lo distinct keys on `hi`, phase 3 races the
    lane index into the parent slot (still zero for a fresh claim) so
    exactly one duplicate lane wins `is_new`; real parents overwrite the
    arena residue after the loop. Losers re-probe next round; full buckets
    overflow to the next bucket.
    """
    size = t_lo.shape[0]
    bucket = min(BUCKET, size)
    n_buckets = size // bucket
    bmask = jnp.uint32(n_buckets - 1)
    b0 = hi & bmask
    lane_ix = jnp.arange(lo.shape[0], dtype=jnp.uint32) + jnp.uint32(1)

    def cond(carry):
        (_tl, _th, _pl, done, _new, _slot, _off, rounds) = carry
        return (~jnp.all(done)) & (rounds < MAX_ROUNDS)

    def body(carry):
        t_lo, t_hi, p_lo, done, is_new, slot, off, rounds = carry
        b = ((b0 + off) & bmask).astype(jnp.int32)
        rows_lo = t_lo.reshape(n_buckets, bucket)[b]  # free bitcast view
        rows_hi = t_hi.reshape(n_buckets, bucket)[b]
        hit_j = (rows_lo == lo[:, None]) & (rows_hi == hi[:, None])
        hit = (~done) & jnp.any(hit_j, axis=1)
        hit_slot = b * bucket + jnp.argmax(hit_j, axis=1).astype(jnp.int32)

        free = rows_lo == 0
        has_free = jnp.any(free, axis=1)
        cand = b * bucket + jnp.argmax(free, axis=1).astype(jnp.int32)
        attempt = (~done) & (~hit) & has_free

        tgt = jnp.where(attempt, cand, size)
        t_lo = t_lo.at[tgt].max(jnp.where(attempt, lo, 0), mode="drop")
        got_lo = attempt & (
            t_lo.at[cand].get(mode="fill", fill_value=0) == lo
        )
        tgt = jnp.where(got_lo, cand, size)
        t_hi = t_hi.at[tgt].max(jnp.where(got_lo, hi, 0), mode="drop")
        claimed = got_lo & (
            t_hi.at[cand].get(mode="fill", fill_value=0) == hi
        )
        tgt = jnp.where(claimed, cand, size)
        p_lo = p_lo.at[tgt].max(jnp.where(claimed, lane_ix, 0), mode="drop")
        winner = claimed & (
            p_lo.at[cand].get(mode="fill", fill_value=0) == lane_ix
        )

        slot = jnp.where(
            hit | claimed, jnp.where(hit, hit_slot, cand), slot
        )
        is_new = is_new | winner
        newly_done = hit | claimed
        off = jnp.where(
            (~done) & (~newly_done) & (~has_free), off + 1, off
        )
        return (
            t_lo, t_hi, p_lo, done | newly_done, is_new, slot, off,
            rounds + 1,
        )

    done0 = ~active
    zeros_i = jnp.zeros_like(lo, dtype=jnp.int32)
    t_lo, t_hi, p_lo, done, is_new, slot, _off, _rounds = (
        jax.lax.while_loop(
            cond,
            body,
            (t_lo, t_hi, p_lo, done0, jnp.zeros_like(active), zeros_i,
             zeros_i, jnp.int32(0)),
        )
    )
    ptgt = jnp.where(is_new, slot, size)
    p_lo = p_lo.at[ptgt].set(parent_lo, mode="drop")
    p_hi = p_hi.at[ptgt].set(parent_hi, mode="drop")
    return InsertResult(t_lo, t_hi, p_lo, p_hi, is_new, ~jnp.all(done))


# -- batch-monotonic capped insert ---------------------------------------------
#
# The sort-claim inserts above pay a FULL-BATCH sort per call — B·log(B)
# regardless of how many lanes actually need attention. At engine scale B
# is batch × max_actions, many of those lanes are padding (sub-full
# frontiers pop fixed-size batches) or duplicates of already-visited
# states, and the sort volume is why measured states/s FALLS with batch
# size (b=32768 was 1.6x slower than b=4096 on paxos-3 — ROUND4_NOTES;
# same super-linear term on the CPU backend, so it is algorithmic). The
# capped path makes per-call probe AND sort cost scale with the POPULATED
# lanes instead:
#
# 1. active lanes are cumsum-compacted into a dense prefix (the
#    compact_new technique from tensor/frontier.py — O(B) elementwise, no
#    128-wide gathers, no sort);
# 2. fixed-size power-of-two CLAIM TILES of that prefix run the underlying
#    insert — tile shapes are static, so the whole thing lives happily
#    inside jit / lax.while_loop. Each tile's own bucket-row probe IS the
#    membership filter: lanes whose key is already committed resolve as
#    hits, so the duplicate-claim sort never exceeds T·log(T) per tile and
#    total tile work is ~n_active/T tiles, not B/T. Duplicates that
#    straddle tiles are resolved because a later tile's probe simply hits
#    the earlier tile's committed slot.
#
# (A variant with a SEPARATE up-front membership probe — gather all B
# home-bucket rows, then tile only the missing lanes — was measured and
# cost-modeled: the extra full-width gather re-reads rows the claim tiles
# gather again, and loses to this fused form at every candidate fraction;
# see tensor/costmodel.py and ROUND6_NOTES.md.)
#
# Correctness rides entirely on the underlying insert: the wrapper only
# compacts and re-batches the active lanes, each original lane lands in
# exactly one tile, and tile order is deterministic — so per-call `is_new`
# attribution (one per distinct new key) is inherited unchanged.


def make_capped_insert(inner, n_state, result_cls, tile=CLAIM_TILE):
    """Wrap an insert impl (`inner`, taking `n_state` table arrays followed
    by lo/hi/parent_lo/parent_hi/active and returning `result_cls`) in the
    active-compaction + claim-tile structure described above."""

    def capped(*args):
        state = args[:n_state]
        lo, hi, parent_lo, parent_hi, active = args[n_state:]
        B = lo.shape[0]
        pow2_B = 1 << max(B - 1, 1).bit_length()
        # Tile size: at least CLAIM_TILE lanes, growing for huge batches so
        # the serialized tile count never exceeds CAP_MAX_TILES.
        T = min(pow2_B, max(tile, pow2_B // CAP_MAX_TILES))
        P = -(-B // T) * T  # padded prefix length: dynamic_slice never clamps

        n_act = active.sum().astype(jnp.int32)

        # Dense-prefix compaction (sort-free cumsum scatter); invalid lanes
        # land at P / map back to the out-of-range index B ("drop").
        pos_all = jnp.cumsum(active.astype(jnp.int32)) - 1
        pos = jnp.where(active, pos_all, P)
        c_lo = jnp.zeros(P, jnp.uint32).at[pos].set(lo, mode="drop")
        c_hi = jnp.zeros(P, jnp.uint32).at[pos].set(hi, mode="drop")
        c_plo = jnp.zeros(P, jnp.uint32).at[pos].set(parent_lo, mode="drop")
        c_phi = jnp.zeros(P, jnp.uint32).at[pos].set(parent_hi, mode="drop")
        c_src = jnp.full(P, B, jnp.int32).at[pos].set(
            jnp.arange(B, dtype=jnp.int32), mode="drop"
        )

        tix = jnp.arange(T, dtype=jnp.int32)
        n_tiles = (n_act + (T - 1)) // T

        def cond_f(carry):
            return carry[-1] < n_tiles

        def body_f(carry):
            st = carry[:n_state]
            is_new, ovf, t = carry[n_state:]
            start = t * T
            res = inner(
                *st,
                jax.lax.dynamic_slice(c_lo, (start,), (T,)),
                jax.lax.dynamic_slice(c_hi, (start,), (T,)),
                jax.lax.dynamic_slice(c_plo, (start,), (T,)),
                jax.lax.dynamic_slice(c_phi, (start,), (T,)),
                (start + tix) < n_act,
            )
            src = jax.lax.dynamic_slice(c_src, (start,), (T,))
            is_new = is_new.at[src].set(
                res[n_state], mode="drop", unique_indices=True
            )
            return (*res[:n_state], is_new, ovf | res[n_state + 1], t + 1)

        out = jax.lax.while_loop(
            cond_f,
            body_f,
            (*state, jnp.zeros(B, dtype=bool), jnp.bool_(False), jnp.int32(0)),
        )
        return result_cls(*out[: n_state + 2])

    return capped


_insert_impl_capped = make_capped_insert(_insert_impl, 4, InsertResult)
_insert_impl_kv_capped = make_capped_insert(_insert_impl_kv, 3, InsertKvResult)
_insert_impl_phased_capped = make_capped_insert(
    _insert_impl_phased, 4, InsertResult
)
