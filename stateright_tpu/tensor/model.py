"""The tensor model contract: the device analogue of `Model`.

Where the host `Model` (ref: src/lib.rs:152-257) yields per-state Python
actions, a `TensorModel` defines one batched transition kernel with a STATIC
maximum action fan-out: `expand` maps `[B, lanes] -> ([B, A, lanes], [B, A])`,
where invalid/ignored action slots are masked out. Wasted lanes are fine — the
reference wastes a whole thread on one state at a time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax.numpy as jnp

from ..core.model import Expectation


@dataclass(frozen=True)
class TensorProperty:
    """A vectorized property: `fn(model, states[B, L]) -> bool[B]`."""

    expectation: Expectation
    name: str
    condition: Callable

    @staticmethod
    def always(name, condition) -> "TensorProperty":
        return TensorProperty(Expectation.ALWAYS, name, condition)

    @staticmethod
    def sometimes(name, condition) -> "TensorProperty":
        return TensorProperty(Expectation.SOMETIMES, name, condition)

    @staticmethod
    def eventually(name, condition) -> "TensorProperty":
        return TensorProperty(Expectation.EVENTUALLY, name, condition)


class TensorModel:
    """A transition system over fixed-width uint32 state rows.

    Required: `lanes`, `max_actions`, `init_states()`, `expand(states)`.
    Optional: `properties()`, `within_boundary(states)`, `decode(row)`,
    `action_label(row, action_index)` for human-readable paths, and
    `representative(states) -> states` for symmetry reduction (a batched
    canonicalization kernel; see `stateright_tpu.tensor.symmetry`). When
    defined, the engines fingerprint the canonical form but keep searching
    with the original states (ref: src/checker/dfs.rs:309-334).
    """

    lanes: int
    max_actions: int
    representative = None  # overridden as a method by symmetric models

    def init_states(self) -> jnp.ndarray:
        """Initial states as uint32[N0, lanes]."""
        raise NotImplementedError

    def expand(self, states: jnp.ndarray):
        """Batched successor generation.

        Args:  states: uint32[B, lanes]
        Returns: (successors uint32[B, max_actions, lanes],
                  valid bool[B, max_actions])
        """
        raise NotImplementedError

    def properties(self) -> list[TensorProperty]:
        return []

    def within_boundary(self, states: jnp.ndarray) -> jnp.ndarray:
        """bool[B]; states outside are not expanded (ref: src/lib.rs:245)."""
        return jnp.ones(states.shape[0], dtype=bool)

    # -- host-side display / parity hooks --------------------------------------

    def decode(self, row) -> Any:
        """Decode one state row (numpy/int tuple) to a human-readable value."""
        return tuple(int(x) for x in row)

    def action_label(self, row, action_index: int) -> Any:
        """Label for taking action slot `action_index` in the state `row`."""
        return action_index

    def format_action(self, action) -> str:
        """Display hook used by `Path.format` (tensor paths carry the
        `action_label` values as their actions)."""
        return str(action)

    def format_step(self, last_state, action) -> Any:
        return None

    def property_by_name(self, name: str) -> TensorProperty:
        for p in self.properties():
            if p.name == name:
                return p
        raise KeyError(f"no property named {name!r}")

    def checker(self):
        """Fluent checker config, like `Model.checker()` — `spawn_tpu()` is
        the natural spawn for tensor models."""
        from ..checker.builder import CheckerBuilder

        return CheckerBuilder(self)
