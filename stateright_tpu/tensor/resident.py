"""Fully device-resident frontier search: the ENTIRE breadth-first check runs
as one `lax.while_loop` inside one `jit` dispatch.

Motivation: the host-orchestrated loop (frontier.py) pays a host↔device round
trip per step — fatal when the device is reached over a network tunnel and
merely wasteful otherwise. Here the frontier queue itself lives in HBM; each
loop iteration pops a batch (a contiguous dynamic slice — the queue never
wraps, see below), expands it with the model kernel, fingerprints + dedups +
inserts into the visited table, evaluates property masks, and appends fresh
states to the queue tail — no host involvement until the search finishes.

Everything on device is 32-bit (u32 fingerprint pairs, u32-pair generated
counters): TPUs emulate 64-bit integer ops, so the round-1 u64 design paid
emulation tax on every hot op.

Capacity argument (also why the queue needs no ring wraparound): every unique
state is enqueued exactly once, so a queue with as many rows as the hash
table has slots can never fill before the table overflows.

Early-exit parity with the reference checkers: the loop stops when every
property has a discovery (src/checker/bfs.rs:278-280), when the configured
`HasDiscoveries` policy matches (encoded as required/any bitmask pairs), when
`target_state_count` is reached, or when the queue drains.
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from ..knobs import (
    INSERT_VARIANTS,
    PHASED_VARIANTS,
    STORE_KINDS,
    TABLE_LAYOUTS,
    WARM_KINDS,
)
from ..faults.ckptio import fenced_savez, load_latest, normalize_ckpt_path
from ..faults.plan import maybe_fault
from ..store import warm as warm_seam
from ..obs import N_COLS, REGISTRY, StepRing, as_tracer, build_detail
from .costmodel import ENGINE_VARIANTS
from .fingerprint import pack_fp
from .frontier import (
    SearchResult,
    append_new,
    append_new_dus,
    count_add,
    count_ge,
    expand_insert,
    pop_batch,
    reconstruct_path,
    resolve_append,
    record_discovery as _record,
    seed_init,
)
from .hashtable import KV_BUCKET, _insert_impl
from .inserts import check_table_log2, resolve_insert
from .model import TensorModel


def _finish_masks(finish_when: HasDiscoveries, props) -> tuple[int, int]:
    """Encode a HasDiscoveries policy as (required_mask, any_mask):
    stop when (discovered & required) == required != 0, or
    (discovered & any_mask) != 0."""
    name_bit = {p.name: 1 << i for i, p in enumerate(props)}
    failure_bits = sum(
        1 << i
        for i, p in enumerate(props)
        if p.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY)
    )
    all_bits = (1 << len(props)) - 1
    k = finish_when.kind
    if k == "all":
        return all_bits, 0
    if k == "any":
        return 0, all_bits
    if k == "any_failures":
        return 0, failure_bits
    if k == "all_failures":
        return failure_bits, 0
    if k == "all_of":
        return sum(name_bit[n] for n in finish_when.names), 0
    if k == "any_of":
        return 0, sum(name_bit[n] for n in finish_when.names)
    raise ValueError(f"unknown HasDiscoveries kind {k!r}")


# Abort-code bits carried in _Carry.overflow (uint32): nonzero aborts the
# loop; the bits name the resource that actually ran out, so overflow
# recovery (checkpoint + load_checkpoint into bigger arrays) can grow the
# RIGHT one instead of guessing.
ABORT_TABLE = 1  # hash-table insert exhausted MAX_ROUNDS (table full)
ABORT_QUEUE = 2  # frontier queue tail crossed its capacity
# Non-fatal exit (tiered store only): the loop hands control back to the
# host — occupancy crossed the spill trigger, the suspect buffer is near
# capacity, or the queue tail needs compaction. The host services the
# condition (store/tiered.py) and resumes the same carry; it is never
# surfaced as an error.
EXIT_SERVICE = 4


def _abort_reason(code: int) -> str:
    parts = []
    if code & ABORT_TABLE:
        parts.append("hash table full (raise table_log2)")
    if code & ABORT_QUEUE:
        parts.append("frontier queue full (raise queue_log2)")
    return " and ".join(parts) if parts else "overflow"


class _Carry(NamedTuple):
    t_lo: jnp.ndarray  # uint32[S] visited-table key halves
    t_hi: jnp.ndarray  # uint32[S]
    p_lo: jnp.ndarray  # uint32[S] parent halves
    p_hi: jnp.ndarray  # uint32[S]
    q_states: jnp.ndarray  # uint32[Q, L]
    q_lo: jnp.ndarray  # uint32[Q]
    q_hi: jnp.ndarray  # uint32[Q]
    q_ebits: jnp.ndarray  # uint32[Q]
    q_depth: jnp.ndarray  # uint32[Q]
    head: jnp.ndarray  # int32
    tail: jnp.ndarray  # int32
    gen_lo: jnp.ndarray  # uint32 generated-count pair
    gen_hi: jnp.ndarray  # uint32
    unique_count: jnp.ndarray  # int32
    max_depth: jnp.ndarray  # uint32
    discovered: jnp.ndarray  # uint32 bitmask
    disc_lo: jnp.ndarray  # uint32[P]
    disc_hi: jnp.ndarray  # uint32[P]
    overflow: jnp.ndarray  # uint32 abort code (0 ok; ABORT_*|EXIT_SERVICE)
    steps: jnp.ndarray  # int32
    # -- tiered store (store="tiered"; zero-sized placeholders otherwise) ------
    hot_claims: jnp.ndarray  # int32: occupied device-table slots
    s_states: jnp.ndarray  # uint32[SQ, L] suspect buffer (Bloom-positive claims
    s_lo: jnp.ndarray  # uint32[SQ]       awaiting exact host resolution)
    s_hi: jnp.ndarray  # uint32[SQ]
    s_ebits: jnp.ndarray  # uint32[SQ]
    s_depth: jnp.ndarray  # uint32[SQ]
    s_tail: jnp.ndarray  # int32
    summary: jnp.ndarray  # uint32[W] Bloom words (read-only in-loop)
    # -- step telemetry (obs/ring.py; zero-row placeholder when disabled) ------
    tm_rows: jnp.ndarray  # uint32[TMR, N_COLS] in-carry metrics ring


def _resolve_chunking(budget, timeout, progress, carry):
    """Shared run()-entry policy for the chunked engines: validate the
    budget, decide whether this run is chunked, and default the chunk size
    (64 steps between wall-clock polls; effectively-unbounded otherwise)."""
    if budget is not None and budget <= 0:
        raise ValueError("budget must be a positive step count")
    chunked = (
        budget is not None
        or timeout is not None
        or progress is not None
        or carry is not None
    )
    if timeout is not None and budget is None:
        budget = 64  # poll granularity for wall-clock checks
    if chunked and budget is None:
        budget = 1 << 20
    return chunked, budget


_ins_jit = jax.jit(_insert_impl)  # one compile cache shared by every regrow
# The pallas table's slot layout is partition-relative (partition = hi mod P,
# row = hi div P — tensor/pallas_hashtable.py), so a pallas run's regrow must
# re-hash through the pallas insert itself; every XLA variant shares the
# global bucket = hi mod n_buckets layout and regrows through _insert_impl.
_ins_jit_pallas = None


def _regrow_insert(insert_variant: str):
    global _ins_jit_pallas
    if insert_variant != "pallas":
        return _ins_jit
    if _ins_jit_pallas is None:
        _ins_jit_pallas = jax.jit(resolve_insert("pallas"))
    return _ins_jit_pallas


# `.npz`-suffix normalization so `checkpoint(p)` / `load_checkpoint(..., p)`
# round-trip on the same string (now owned by the atomic checkpoint writer).
_ckpt_path = normalize_ckpt_path


def _validate_ckpt_meta(model, meta: dict) -> None:
    """Shared layout/property guards for engine checkpoints: lane widths and
    property positions index into the dumped arrays, so any mismatch would
    silently misalign them."""
    if (meta["lanes"], meta["max_actions"]) != (
        model.lanes,
        model.max_actions,
    ):
        raise ValueError(
            "checkpoint was taken with a different model layout "
            f"(lanes/max_actions {meta['lanes']}/{meta['max_actions']} "
            f"!= {model.lanes}/{model.max_actions})"
        )
    prop_names = [p.name for p in model.properties()]
    if meta["properties"] != prop_names:
        raise ValueError(
            "checkpoint was taken with a different property list "
            f"({meta['properties']} != {prop_names})"
        )


def _regrow(
    model, fields, old_log2: int, new_log2: int, K: int,
    queue_rows: Optional[int] = None,
    insert_variant: str = "sort",
) -> dict:
    """Re-hash a checkpointed visited table into a larger one and pad the
    frontier queue to `queue_rows` (default: the new table size — what the
    sharded engine's per-shard queues use; the resident engine passes its
    slacked capacity so the queue is padded exactly once). Queue rows live
    at [0, tail). Bucket slots depend on the table size, so growth is a
    full re-insert of every occupied slot — done on device in `K`-row
    batches."""
    S_new = 1 << new_log2
    Q_new = queue_rows if queue_rows is not None else S_new
    t_lo, t_hi = fields["t_lo"], fields["t_hi"]
    p_lo, p_hi = fields["p_lo"], fields["p_hi"]
    nz = t_lo != 0  # lo == 0 is the empty-slot sentinel (fingerprint.py)
    keys = [a[nz] for a in (t_lo, t_hi, p_lo, p_hi)]
    ins = _regrow_insert(insert_variant)
    zero = jnp.zeros(S_new, dtype=jnp.uint32)
    tl, th, pl, ph = zero, zero, zero, zero
    n = keys[0].size
    for i in range(0, max(n, 1), K):
        batch = [np.zeros(K, dtype=np.uint32) for _ in range(4)]
        m = min(K, n - i) if n else 0
        for b, k in zip(batch, keys):
            b[:m] = k[i : i + m]
        active = np.arange(K) < m
        tl, th, pl, ph, _, ovf = ins(tl, th, pl, ph, *batch, active)
        if bool(ovf):
            # srlint: fault-ok deterministic capacity wall during host-side regrow, not injectable infra
            raise RuntimeError(
                "table overflow while re-growing; raise table_log2 further"
            )
    out = {"t_lo": tl, "t_hi": th, "p_lo": pl, "p_hi": ph}
    for f in ("q_states", "q_lo", "q_hi", "q_ebits", "q_depth"):
        old = fields[f]
        grown = np.zeros((Q_new,) + old.shape[1:], dtype=old.dtype)
        keep = min(old.shape[0], Q_new)
        grown[:keep] = old[:keep]
        out[f] = grown
    # The carry's abort code is NOT touched here: a checkpointed carry sits
    # at the last sound chunk boundary (code 0), and the reason for the
    # abort that prompted the regrow travels in checkpoint meta
    # ("abort_reason"), where load_checkpoint enforces that the overflowed
    # resource actually grew.
    return out


@jax.jit
def _compact_queue(q_states, q_lo, q_hi, q_ebits, q_depth, head):
    """Shift live queue rows [head, tail) to the front (one gather per
    array) — the tiered store's answer to the append-only tail growing past
    capacity once uniques exceed the table. Static shapes: the out-of-range
    tail of the gather fills with zeros, which nothing past the new tail
    reads."""
    idx = head + jnp.arange(q_lo.shape[0], dtype=jnp.int32)
    one = lambda a: jnp.take(a, idx, mode="fill", fill_value=0)  # noqa: E731
    return (
        jnp.take(q_states, idx, axis=0, mode="fill", fill_value=0),
        one(q_lo), one(q_hi), one(q_ebits), one(q_depth),
    )


@jax.jit
def _inject_rows(
    q_states, q_lo, q_hi, q_ebits, q_depth, tail,
    b_states, b_lo, b_hi, b_eb, b_dp,
):
    """Write a host-built block of confirmed-new suspect rows at the queue
    tail (one contiguous dynamic_update_slice per array; rows past the
    caller's real count are scratch beyond the new tail). The caller
    guarantees tail + block_rows <= Q via the tiered queue slack."""
    upd2 = jax.lax.dynamic_update_slice(q_states, b_states, (tail, 0))
    one = lambda q, b: jax.lax.dynamic_update_slice(q, b, (tail,))  # noqa: E731
    return (
        upd2, one(q_lo, b_lo), one(q_hi, b_hi),
        one(q_ebits, b_eb), one(q_depth, b_dp),
    )


class ResidentSearch:
    """One-dispatch whole-search engine for a `TensorModel`."""

    # Corpus warm ladder: the ONE kind vocabulary and the ONE preload seam
    # (store/warm.py) — aliased, never restated; knobs.check_registry()
    # pins both on every engine.
    WARM_KINDS = WARM_KINDS
    WARM_SEAM = warm_seam

    def __init__(
        self,
        model: TensorModel,
        batch_size: int = 2048,
        table_log2: int = 20,
        donate_chunks: bool = False,
        queue_log2: Optional[int] = None,
        append: Optional[str] = None,
        table_layout: str = "split",
        insert_variant: str = "sort",
        store: str = "device",
        high_water: float = 0.85,
        low_water: Optional[float] = None,
        summary_log2: int = 20,
        telemetry: bool = True,
        telemetry_log2: int = 12,
        tracer=None,
    ):
        """`donate_chunks=True` donates the carry to each chunked dispatch:
        XLA updates the tables/queue IN PLACE instead of copying the whole
        multi-GB carry per dispatch (measured ~280 s/dispatch at table 2^27
        on the CPU backend — the dominant cost of chunked long-haul runs).
        The trade: on a table/queue overflow the pre-chunk carry no longer
        exists, so the checkpoint-then-regrow recovery is unavailable —
        run big spaces with a right-sized table, or leave this off when
        overflow recovery matters more than throughput.

        `queue_log2` caps the frontier queue at 2^queue_log2 rows (default:
        table_log2, the always-sufficient bound). The queue dominates HBM
        when states are wide — 2pc-10 at table 2^27 needs 9.1 GB of queue
        for at most 61.5 M uniques (< 2^26): right-sizing it is what fits
        the workload on a 16 GB v5e. Exceeding the cap is detected as the
        same overflow signal as a full table (never a silent drop).

        `telemetry=True` (default) appends one obs.STEP_COLS metrics row
        per loop step into a device-resident ring of 2^telemetry_log2 rows
        carried through the while_loop — a ~32-byte scatter next to the
        megabytes the step already moves, with NO host involvement; the
        ring is drained in bulk at boundaries where the host has already
        synced (chunk returns, run end) and digested into
        `SearchResult.detail["telemetry"]`. `tracer` (obs.Tracer) records
        the host phases (chunk dispatch, tiered-store servicing,
        checkpoint) as Chrome trace events."""
        self.model = model
        self.batch_size = batch_size
        self.table_log2 = table_log2
        self.queue_log2 = table_log2 if queue_log2 is None else queue_log2
        self.donate_chunks = donate_chunks
        # Queue-append variant: XLA lays the queue out column-major (fast
        # per-lane reads for the model kernels), which makes the row-scatter
        # append pathological on TPU — the round-4 silicon profile measured
        # it at 44.7% of the paxos-3 step (2.4 GiB/s effective); the
        # compact-then-dynamic_update_slice form writes 21 contiguous
        # column runs instead (paxos-3 627k -> 1.06M states/s). The 1-core
        # CPU backend measured the OPPOSITE at 2pc-10 scale (DUS ~5x
        # slower), so the default follows the effective backend; pass
        # append="scatter"|"dus" to pin it.
        self.append = resolve_append(append, jax.default_backend())
        # table_layout="kv": interleaved 64-slot lo|hi buckets — one probe
        # gather fetches half the bytes of the split layout (see
        # hashtable._insert_impl_kv). Carry convention: t_lo holds the
        # uint32[2S] kv array and t_hi a zero-length placeholder.
        # Flag-gated pending the silicon race; checkpoint regrow is
        # split-only for now.
        if table_layout not in TABLE_LAYOUTS:  # knob universe: knobs.py
            raise ValueError(
                f"table_layout must be one of {TABLE_LAYOUTS}, "
                f"got {table_layout!r}"
            )
        self.table_layout = table_layout
        # insert_variant selects the visited-set insert design:
        #   "sort"   — full-batch sort-claim (the at-scale default);
        #   "phased" — pre-sort-claim scatter-max insert, raceable per
        #              workload — its fixed costs win on tiny frontiers
        #              (paxos-2 class; see hashtable._insert_impl_phased);
        #   "capped" — batch-monotonic path: active-compaction + fixed-size
        #              claim tiles, so per-step probe AND sort cost scale
        #              with the populated lanes instead of the full
        #              expanded batch (hashtable.make_capped_insert);
        #              composes with table_layout="kv";
        #   "capped-phased" — the same cap around the phased insert;
        #   "pallas" — the partitioned-VMEM route-then-probe kernel
        #              (tensor/pallas_hashtable.py; interpret mode on
        #              non-TPU backends). Split layout only; the table must
        #              tile into (8,128) VMEM blocks, so table_log2 >= 10.
        if insert_variant not in INSERT_VARIANTS:  # knob universe: knobs.py
            raise ValueError(
                f"insert_variant must be one of {INSERT_VARIANTS}, "
                f"got {insert_variant!r}"
            )
        if (
            insert_variant in PHASED_VARIANTS or insert_variant == "pallas"
        ) and table_layout == "kv":
            raise ValueError(
                f"insert_variant={insert_variant!r} supports the split "
                "table layout only"
            )
        check_table_log2(insert_variant, table_log2)  # pallas tiling guard
        self.insert_variant = insert_variant
        # store="tiered": two-tier state store (stateright_tpu/store/) —
        # past `high_water` fill, cold non-full buckets spill to a host
        # fingerprint store over PCIe and a device Bloom summary
        # (2^summary_log2 bits) filters re-probes. The while_loop exits to
        # the host (EXIT_SERVICE) instead of aborting, so spaces bigger
        # than the table degrade gracefully; tiered runs are always
        # chunked (the host must get control between dispatches).
        if store not in STORE_KINDS:  # knob universe: knobs.py
            raise ValueError(f"store must be one of {STORE_KINDS}, got {store!r}")
        if store == "tiered" and table_layout != "split":
            raise ValueError("store='tiered' supports the split table layout only")
        self.store = store
        self._store = None
        self._store_args = (high_water, low_water, summary_log2)
        ka = batch_size * model.max_actions
        if store == "tiered":
            self._fresh_store()
            # One-batch headroom: a single step can claim up to K*A slots
            # and eviction only runs between dispatches.
            self._spill_trigger = min(
                self._store.high_slots, (1 << table_log2) - ka
            )
            if self._spill_trigger <= self._store.low_slots:
                raise ValueError(
                    "table too small for tiered spilling at this batch: "
                    f"table 2^{table_log2} minus one batch of claims ({ka}) "
                    "leaves no room above the low-water mark "
                    f"({self._store.low_slots} slots); raise table_log2 or "
                    "lower batch_size/low_water"
                )
            # Suspect buffer: 2 batches of accumulation + 1 batch of append
            # slack before a service exit is forced.
            self._SQ = 3 * ka
        else:
            self._spill_trigger = 0
            self._SQ = 0
        self._q_compacted = False
        # Telemetry ring capacity (0 disables the in-carry ring entirely —
        # the kernels compile without it, the A/B knob for bench OBS rows).
        self._TMR = (1 << telemetry_log2) if telemetry else 0
        self._ring = StepRing(self._TMR) if telemetry else None
        self._tracer = as_tracer(tracer)
        self._metrics_name = REGISTRY.register("resident", self.metrics)
        # Calibration comparator (obs/calib.py): consumes the already-synced
        # ring drains below — no extra device work, observes, never steers.
        self._calib = None
        if telemetry:
            # Lazy import: obs.calib prices through tensor.costmodel, so a
            # module-level import would cycle when obs loads first.
            from ..obs.calib import CalibConfig, Comparator, calib_enabled

        if telemetry and calib_enabled():
            self._calib = Comparator(CalibConfig(
                engine="resident",
                variant=ENGINE_VARIANTS.get(
                    (table_layout, insert_variant), "split"
                ),
                lanes=model.lanes,
                max_actions=model.max_actions,
                batch=batch_size,
                table_log2=table_log2,
                spill=(store == "tiered"),
            ))
            REGISTRY.register("calib", self._calib.metrics)
        self.props = model.properties()
        self._kernel, self._seed_k, self._chunk_k = self._build()
        self._last_tables = None
        self._parent_map = None
        self._seed = None
        # Operand tables (lowered models): round-varying baked tables flow
        # into the kernels as ARGUMENTS instead of jaxpr constants, so
        # `set_dyn_tables` can swap their contents (same shapes) with no
        # retrace/recompile — what makes refine_check's per-round restarts
        # cheap (VERDICT r3 next #8).
        self._dyn_dev = (
            jax.device_put(model.dyn_tables())
            if hasattr(model, "dyn_tables")
            else {}
        )
        # Suspended-search carry (chunked runs only): retained across run()
        # calls so budget/timeout suspensions and overflows are resumable.
        self._carry = None
        # Warm-start corpus payload (store/warm.py; see warm_start).
        self._warm: Optional[dict] = None
        self._warm_states = 0
        self._warm_kind: Optional[str] = None  # knobs.WARM_KINDS rung served
        self._warm_summary_pending = False
        # Abort code of the last overflow (ABORT_TABLE | ABORT_QUEUE bits);
        # written into checkpoint meta so recovery grows the right resource.
        self._last_abort = 0

    def _fresh_store(self) -> None:
        """(Re)build the tiered store — a fresh search owes nothing to a
        previous run's spill tier or Bloom summary."""
        from ..store.tiered import TieredConfig, TieredStore

        if self._store is not None:
            self._store.close()  # stop the old spill tier's compactor
        high_water, low_water, summary_log2 = self._store_args
        self._store = TieredStore(
            1 << self.table_log2,
            TieredConfig(
                high_water=high_water,
                low_water=low_water,
                summary_log2=summary_log2,
            ),
        )

    def store_stats(self) -> Optional[dict]:
        """Per-tier occupancy counters (None with the plain device store) —
        surfaced in SearchResult.detail, the bench JSON, and `/.status`."""
        if self._store is None:
            return None
        hot = int(self._carry.hot_claims) if self._carry is not None else 0
        return self._store.stats(hot)

    def _insert_fn(self, summary_cfg=None):
        """Resolve through THE dispatch table (tensor/inserts.py) — the one
        name → insert-fn resolution point all three engines share.
        `summary_cfg=(summary_log2, hashes)` requests the tiered store's
        fused suspect probe where the variant has one (pallas)."""
        return resolve_insert(
            self.insert_variant, self.table_layout, summary_cfg=summary_cfg
        )

    def _build(self):
        model = self.model
        K = self.batch_size
        A = model.max_actions
        L = model.lanes
        _append = append_new if self.append == "scatter" else append_new_dus
        S = 1 << self.table_log2
        tiered = self._store is not None
        if tiered:
            from ..store.summary import summary_words

            slog2 = self._store.config.summary_log2
            khash = self._store.config.summary_hashes
            W = summary_words(slog2)
            s_cfg = (slog2, khash)
        else:
            W = 1
            s_cfg = None
        # Seed inserts run against a fresh (empty-summary) table — always
        # the plain form; the step insert carries the fused Bloom probe
        # when the variant supports it (expand_insert keys on the marker).
        insert = self._insert_fn()
        insert_step = self._insert_fn(summary_cfg=s_cfg)
        SQ = self._SQ
        TMR = self._TMR
        TRIGGER = jnp.int32(self._spill_trigger) if tiered else None
        # Queue capacity: every unique state is enqueued exactly once (<= S
        # before the table overflows, and <= 2^queue_log2 when the caller
        # right-sized the queue below the table — see __init__), plus K*A
        # rows of slack so either append variant (scatter `append_new` —
        # the default; measured faster than `append_new_dus` on CPU at
        # 2pc-10 scale — or the DUS block) stays in bounds right up to the
        # overflow signal. Tiered runs add SQ more rows: the live frontier
        # is still bounded by 2^queue_log2 (uniques beyond the table spill,
        # and the tail is host-compacted at each service exit), and the
        # extra slack guarantees the suspect-injection block always fits.
        QL = 1 << self.queue_log2
        Q = QL + K * A + (SQ if tiered else 0)
        self._Q = Q
        props = self.props
        P = len(props)
        always_i = [i for i, p in enumerate(props) if p.expectation == Expectation.ALWAYS]
        sometimes_i = [i for i, p in enumerate(props) if p.expectation == Expectation.SOMETIMES]
        eventually_i = [i for i, p in enumerate(props) if p.expectation == Expectation.EVENTUALLY]
        ebits0 = np.uint32(sum(1 << i for i in eventually_i))
        all_bits = jnp.uint32((1 << P) - 1)

        def body(c: _Carry, tmd) -> _Carry:
            # -- pop a batch: contiguous dynamic slice (no wraparound) ---------
            states, lo, hi, ebits, depth, active, head = pop_batch(
                c.q_states, c.q_lo, c.q_hi, c.q_ebits, c.q_depth,
                c.head, c.tail, K,
            )

            max_depth = jnp.maximum(
                c.max_depth, jnp.max(jnp.where(active, depth, 0))
            )
            # target_max_depth: states at the cutoff are neither evaluated
            # nor expanded (ref: bfs.rs:219-224); 0 = no limit.
            active = active & ((tmd == 0) | (depth < tmd))

            # -- property evaluation (ref: bfs.rs:230-280) ---------------------
            discovered = c.discovered
            disc_lo, disc_hi = c.disc_lo, c.disc_hi
            if P:
                masks = jnp.stack([p.condition(model, states) for p in props])
                for i in always_i:
                    discovered, disc_lo, disc_hi = _record(
                        discovered, disc_lo, disc_hi, i, active & ~masks[i], lo, hi
                    )
                for i in sometimes_i:
                    discovered, disc_lo, disc_hi = _record(
                        discovered, disc_lo, disc_hi, i, active & masks[i], lo, hi
                    )
                for i in eventually_i:
                    ebits = jnp.where(
                        masks[i], ebits & jnp.uint32(~(1 << i) & 0xFFFFFFFF), ebits
                    )

            # -- expand + fingerprint + dedup + insert (shared core) -----------
            (
                t_lo, t_hi, p_lo, p_hi,
                flat, slo, shi, is_new, suspect,
                gen_rows, has_succ, ovf,
            ) = expand_insert(
                model, c.t_lo, c.t_hi, c.p_lo, c.p_hi, states, lo, hi,
                active, insert=insert_step,
                summary=c.summary if tiered else None,
                summary_cfg=s_cfg,
            )
            gen = gen_rows.sum()

            # -- eventually counterexamples at terminal states -----------------
            if eventually_i:
                term = active & ~has_succ
                for i in eventually_i:
                    bad = term & ((ebits >> jnp.uint32(i)) & 1).astype(bool)
                    discovered, disc_lo, disc_hi = _record(
                        discovered, disc_lo, disc_hi, i, bad, lo, hi
                    )

            # -- tiered store: split claims into enqueue vs suspect ------------
            # A fresh claim whose fingerprint hits the Bloom summary of the
            # spilled set might be a revisit of an evicted state: it is
            # buffered for exact host resolution instead of enqueued (a
            # summary MISS proves novelty, so the common path never leaves
            # the device). The claim itself stays in the table either way —
            # that is what dedups further on-device probes of the same key.
            # expand_insert computes the suspect mask (fused into the Pallas
            # kernel's own partition pass when that variant is selected).
            enq = is_new & ~suspect if tiered else is_new

            # -- append new states to the queue tail (cumsum compaction) -------
            src_row = jnp.arange(K * A, dtype=jnp.int32) // A
            q_states, q_lo, q_hi, q_ebits, q_depth, tail = _append(
                c.q_states, c.q_lo, c.q_hi, c.q_ebits, c.q_depth, c.tail,
                flat, slo, shi, ebits[src_row], depth[src_row] + 1, enq,
            )
            new_count = tail - c.tail
            hot_claims = c.hot_claims + is_new.sum().astype(jnp.int32)
            if tiered:
                (
                    s_states, s_lo, s_hi, s_ebits, s_depth, s_tail,
                ) = _append(
                    c.s_states, c.s_lo, c.s_hi, c.s_ebits, c.s_depth,
                    c.s_tail,
                    flat, slo, shi, ebits[src_row], depth[src_row] + 1,
                    suspect,
                )
                # Host-service exits (non-fatal): spill trigger crossed,
                # suspect buffer near capacity, or queue tail past the
                # compaction threshold.
                service = (
                    (hot_claims >= TRIGGER)
                    | (s_tail > SQ - K * A)
                    | (tail > QL)
                )
                q_full = jnp.bool_(False)  # the host decides queue fatality
            else:
                s_states, s_lo, s_hi = c.s_states, c.s_lo, c.s_hi
                s_ebits, s_depth, s_tail = c.s_ebits, c.s_depth, c.s_tail
                service = jnp.bool_(False)
                # tail beyond S means more uniques than table slots — the
                # table is overflowing anyway; the K*A slack above keeps the
                # DUS and the next pop's dynamic_slice in bounds right up to
                # that point.
                q_full = tail > Q - K * A

            gen_lo, gen_hi = count_add(c.gen_lo, c.gen_hi, gen)

            # -- step telemetry row (obs/ring.py STEP_COLS order) --------------
            # One tiny scatter into the in-carry ring; the host drains it in
            # bulk at chunk boundaries — zero per-step host involvement.
            if TMR:
                tm_row = jnp.stack(
                    [
                        c.steps.astype(jnp.uint32),
                        active.sum().astype(jnp.uint32),
                        gen.astype(jnp.uint32),
                        is_new.sum().astype(jnp.uint32),
                        (tail - head).astype(jnp.uint32),
                        hot_claims.astype(jnp.uint32),
                        s_tail.astype(jnp.uint32),
                        max_depth.astype(jnp.uint32),
                    ]
                )
                tm_rows = c.tm_rows.at[
                    jnp.remainder(c.steps, TMR)
                ].set(tm_row)
            else:
                tm_rows = c.tm_rows
            return _Carry(
                t_lo=t_lo,
                t_hi=t_hi,
                p_lo=p_lo,
                p_hi=p_hi,
                q_states=q_states,
                q_lo=q_lo,
                q_hi=q_hi,
                q_ebits=q_ebits,
                q_depth=q_depth,
                head=head,
                tail=tail,
                gen_lo=gen_lo,
                gen_hi=gen_hi,
                unique_count=c.unique_count + new_count,
                max_depth=max_depth,
                discovered=discovered,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                overflow=c.overflow
                | (ovf.astype(jnp.uint32) * jnp.uint32(ABORT_TABLE))
                | (q_full.astype(jnp.uint32) * jnp.uint32(ABORT_QUEUE))
                | (service.astype(jnp.uint32) * jnp.uint32(EXIT_SERVICE)),
                steps=c.steps + 1,
                hot_claims=hot_claims,
                s_states=s_states,
                s_lo=s_lo,
                s_hi=s_hi,
                s_ebits=s_ebits,
                s_depth=s_depth,
                s_tail=s_tail,
                summary=c.summary,
                tm_rows=tm_rows,
            )

        def should_continue(
            c: _Carry, req, anym, have_target, target_lo, target_hi, max_steps
        ):
            drained = c.head >= c.tail
            all_found = (P > 0) & (c.discovered == all_bits)
            policy = ((req != 0) & ((c.discovered & req) == req)) | (
                (c.discovered & anym) != 0
            )
            count_hit = have_target & count_ge(
                c.gen_lo, c.gen_hi, target_lo, target_hi
            )
            return (
                (~drained)
                & (~all_found)
                & (~policy)
                & (~count_hit)
                & (c.overflow == 0)
                & (c.steps < max_steps)
            )

        def make_carry(init_states, init_lo, init_hi, n0, seed_lo, seed_hi):
            # Tables are allocated in-trace: a fresh search per dispatch, and
            # no host-side zero-fill round trip over the device tunnel.
            if self.table_layout == "kv":
                t_lo = jnp.zeros(2 * S, dtype=jnp.uint32)  # the kv array
                t_hi = jnp.zeros(0, dtype=jnp.uint32)  # placeholder
            else:
                t_lo = jnp.zeros(S, dtype=jnp.uint32)
                t_hi = jnp.zeros(S, dtype=jnp.uint32)
            p_lo = jnp.zeros(S, dtype=jnp.uint32)
            p_hi = jnp.zeros(S, dtype=jnp.uint32)
            init_active = jnp.arange(K, dtype=jnp.int32) < n0
            t_lo, t_hi, p_lo, p_hi, is_new, ovf = insert(
                t_lo, t_hi, p_lo, p_hi,
                init_lo, init_hi,
                jnp.zeros(K, dtype=jnp.uint32), jnp.zeros(K, dtype=jnp.uint32),
                init_active,
            )
            q_states = jnp.zeros((Q, L), dtype=jnp.uint32)
            q_lo = jnp.zeros(Q, dtype=jnp.uint32)
            q_hi = jnp.zeros(Q, dtype=jnp.uint32)
            q_ebits = jnp.zeros(Q, dtype=jnp.uint32)
            q_depth = jnp.zeros(Q, dtype=jnp.uint32)
            slot = jnp.arange(K, dtype=jnp.int32)
            qpos = jnp.where(slot < n0, slot, Q)
            q_states = q_states.at[qpos].set(init_states, mode="drop")
            q_lo = q_lo.at[qpos].set(init_lo, mode="drop")
            q_hi = q_hi.at[qpos].set(init_hi, mode="drop")
            q_ebits = q_ebits.at[qpos].set(jnp.uint32(ebits0), mode="drop")
            q_depth = q_depth.at[qpos].set(jnp.uint32(1), mode="drop")

            return _Carry(
                t_lo=t_lo,
                t_hi=t_hi,
                p_lo=p_lo,
                p_hi=p_hi,
                q_states=q_states,
                q_lo=q_lo,
                q_hi=q_hi,
                q_ebits=q_ebits,
                q_depth=q_depth,
                head=jnp.int32(0),
                tail=n0.astype(jnp.int32),
                gen_lo=seed_lo,
                gen_hi=seed_hi,
                unique_count=is_new.sum().astype(jnp.int32),
                max_depth=jnp.uint32(0),
                discovered=jnp.uint32(0),
                disc_lo=jnp.zeros(max(P, 1), dtype=jnp.uint32),
                disc_hi=jnp.zeros(max(P, 1), dtype=jnp.uint32),
                overflow=ovf.astype(jnp.uint32) * jnp.uint32(ABORT_TABLE),
                steps=jnp.int32(0),
                hot_claims=is_new.sum().astype(jnp.int32),
                s_states=jnp.zeros((SQ, L), dtype=jnp.uint32),
                s_lo=jnp.zeros(SQ, dtype=jnp.uint32),
                s_hi=jnp.zeros(SQ, dtype=jnp.uint32),
                s_ebits=jnp.zeros(SQ, dtype=jnp.uint32),
                s_depth=jnp.zeros(SQ, dtype=jnp.uint32),
                s_tail=jnp.int32(0),
                summary=jnp.zeros(W, dtype=jnp.uint32),
                tm_rows=jnp.zeros((TMR, N_COLS), dtype=jnp.uint32),
            )

        def summary_of(carry: _Carry, stop):
            # Pack every host-facing scalar into ONE small vector so the host
            # reads the whole result in a single device transfer (each fetch
            # over the device tunnel costs a full round trip). Layout:
            # [0..9] as before, [10] hot_claims, [11] s_tail, then
            # disc_lo/disc_hi.
            return jnp.concatenate(
                [
                    jnp.stack(
                        [
                            carry.gen_lo,
                            carry.gen_hi,
                            carry.unique_count.astype(jnp.uint32),
                            carry.max_depth,
                            carry.discovered,
                            carry.head.astype(jnp.uint32),
                            carry.tail.astype(jnp.uint32),
                            carry.overflow.astype(jnp.uint32),
                            carry.steps.astype(jnp.uint32),
                            stop.astype(jnp.uint32),
                            carry.hot_claims.astype(jnp.uint32),
                            carry.s_tail.astype(jnp.uint32),
                        ]
                    ),
                    carry.disc_lo,
                    carry.disc_hi,
                ]
            )

        @partial(jax.jit, static_argnums=(3, 4, 7))
        def search(
            init_states,  # uint32[K, L] padded
            init_lo,  # uint32[K]
            init_hi,  # uint32[K]
            required_mask: int,
            any_mask: int,
            target_lo,  # uint32 scalar pair (0, 0 = none)
            target_hi,
            max_steps: int,
            n0,  # int32: number of active seed rows
            seed_lo,  # uint32 pair: pre-dedup init count (host count parity)
            seed_hi,
            target_max_depth,  # uint32 (0 = no limit)
            dyn={},  # operand tables for lowered models (see __init__)
        ):
            model._dyn = dyn
            try:
                req = jnp.uint32(required_mask)
                anym = jnp.uint32(any_mask)
                have_target = (target_lo | target_hi) != 0
                carry = make_carry(
                    init_states, init_lo, init_hi, n0, seed_lo, seed_hi
                )
                carry = jax.lax.while_loop(
                    lambda c: should_continue(
                        c, req, anym, have_target, target_lo, target_hi,
                        max_steps,
                    ),
                    lambda c: body(c, target_max_depth),
                    carry,
                )
                summary = summary_of(carry, jnp.bool_(True))
            finally:
                model._dyn = None
            return (
                carry.t_lo, carry.t_hi, carry.p_lo, carry.p_hi, summary,
                carry.tm_rows,
            )

        @jax.jit
        def seed_k(init_states, init_lo, init_hi, n0, seed_lo, seed_hi):
            return make_carry(init_states, init_lo, init_hi, n0, seed_lo, seed_hi)

        # NOTE: NOT donated by default — the host keeps the pre-chunk carry
        # alive so a table/queue overflow can revert to the last sound chunk
        # boundary (checkpoint-then-raise instead of discarding the run).
        # `donate_chunks=True` flips this trade (see __init__).
        def chunk_k(
            carry: _Carry,
            req,  # uint32 dynamic (one compiled chunk kernel per model/shape)
            anym,
            target_lo,
            target_hi,
            target_max_depth,
            budget,  # int32: max loop steps THIS dispatch
            max_steps,  # int32: global step cap
            dyn={},  # operand tables for lowered models (see __init__)
        ):
            model._dyn = dyn
            try:
                have_target = (target_lo | target_hi) != 0
                start = carry.steps

                def cond(c: _Carry):
                    return should_continue(
                        c, req, anym, have_target, target_lo, target_hi,
                        max_steps,
                    ) & (c.steps < start + budget)

                carry = jax.lax.while_loop(
                    cond, lambda c: body(c, target_max_depth), carry
                )
                stop = ~should_continue(
                    carry, req, anym, have_target, target_lo, target_hi,
                    max_steps,
                )
                out = carry, summary_of(carry, stop)
            finally:
                model._dyn = None
            return out

        chunk_k = (
            partial(jax.jit, donate_argnums=(0,))(chunk_k)
            if self.donate_chunks
            else jax.jit(chunk_k)
        )
        return search, seed_k, chunk_k

    # -- static analysis -------------------------------------------------------

    def audit_step(self):
        """(chunk_fn, abstract_operands, host_slots) for the jaxpr auditor
        (analysis/auditor.py). The carry shapes come from eval_shape over
        the engine's own seed kernel — abstract only, no device work. The
        chunked dispatch re-uploads nothing per step (host_slots empty):
        the auditor's while-body extraction reports the per-step cost."""
        K, L = self.batch_size, self.model.lanes
        sds = jax.ShapeDtypeStruct
        u32 = lambda *s: sds(s, jnp.uint32)  # noqa: E731
        carry = jax.eval_shape(
            self._seed_k,
            u32(K, L), u32(K), u32(K), sds((), jnp.int32), u32(), u32(),
        )
        dyn = jax.tree.map(
            lambda x: sds(x.shape, x.dtype), self._dyn_dev
        )
        args = (
            carry, u32(), u32(), u32(), u32(), u32(),
            sds((), jnp.int32), sds((), jnp.int32), dyn,
        )
        return self._chunk_k, args, ()

    # -- host entry ------------------------------------------------------------

    def warm_start(self, entry, kind: Optional[str] = None) -> int:
        """Preload a published corpus entry before the first run() — the
        resident engine's leg of the ONE warm-start seam (store/warm.py;
        knobs.WARM_KINDS), closing the gap where this engine started cold
        on every job.

        A COMPLETE entry replays: the prefix lands in the spill tier and
        the Bloom summary, the summary is patched into the seeded carry
        (make_carry builds an empty one), so the init frontier's children
        all resolve as spilled duplicates at the stop-drain and the run
        collapses to one expansion wave; the result then replays the
        publisher's bookkeeping. A PARTIAL entry (corpus v2) CONTINUES:
        the frontier snapshot is packed into a host-built carry (the
        load_checkpoint recipe against an empty hot table — the visited
        prefix dedups through the preloaded spill tier), counters and
        discoveries restore from the entry's meta, and run() finishes the
        remainder. The caller owns key discipline (`warm.can_replay` /
        `warm.can_continue`, and `warm.salvage_delta` for the Spec-CI
        "delta" rung — pass the salvaged entry it returns with
        kind="delta"); a replay must use the publisher's finish policy.
        Returns the state count preloaded."""
        if self._store is None:
            raise ValueError(
                "warm_start requires store='tiered' (known states are "
                "dedup-filtered through the spill tier's Bloom suspect "
                "path)"
            )
        if self._carry is not None:
            raise ValueError("warm_start must run before the first run()")
        n = warm_seam.preload_store(self._store, entry)
        self._warm_states = n
        if getattr(entry, "complete", True):
            self._warm = dict(entry.meta)
            self._warm_kind = kind or "exact"
            # The seeded carry's summary is patched in run() — the preload
            # above already rebuilt self._store.summary_np.
            self._warm_summary_pending = True
            return n
        if entry.frontier is None:
            raise ValueError(
                "partial corpus entry has no frontier snapshot (coverage-"
                "only); a continuation needs the publisher's cut frontier"
            )
        self._warm_kind = kind if kind == "delta" else "partial"
        meta = entry.meta
        f = entry.frontier
        nf = int(np.asarray(f["lo"]).size)
        if nf > (1 << self.queue_log2):
            raise ValueError(
                f"partial entry's frontier ({nf} rows) exceeds the queue "
                f"(queue_log2={self.queue_log2}); raise queue_log2"
            )
        model = self.model
        P = len(self.props)
        Q, L = self._Q, model.lanes
        q_states = np.zeros((Q, L), np.uint32)
        q_lo = np.zeros(Q, np.uint32)
        q_hi = np.zeros(Q, np.uint32)
        q_ebits = np.zeros(Q, np.uint32)
        q_depth = np.zeros(Q, np.uint32)
        q_states[:nf] = np.asarray(f["states"], np.uint32)
        q_lo[:nf] = np.asarray(f["lo"], np.uint32)
        q_hi[:nf] = np.asarray(f["hi"], np.uint32)
        q_ebits[:nf] = warm_seam.pack_ebits(np.asarray(f["ebits"]))
        q_depth[:nf] = np.asarray(f["depths"], np.uint32)
        disc = meta.get("discoveries", {})
        discovered = 0
        disc_lo = np.zeros(max(P, 1), np.uint32)
        disc_hi = np.zeros(max(P, 1), np.uint32)
        for i, p in enumerate(self.props):
            if p.name in disc:
                discovered |= 1 << i
                fp = int(disc[p.name])
                disc_lo[i] = np.uint32(fp & 0xFFFFFFFF)
                disc_hi[i] = np.uint32(fp >> 32)
        S = 1 << self.table_log2
        sc = int(meta["state_count"])
        fields = dict(
            t_lo=np.zeros(S, np.uint32),
            t_hi=np.zeros(S, np.uint32),
            p_lo=np.zeros(S, np.uint32),
            p_hi=np.zeros(S, np.uint32),
            q_states=q_states, q_lo=q_lo, q_hi=q_hi,
            q_ebits=q_ebits, q_depth=q_depth,
            head=np.int32(0), tail=np.int32(nf),
            gen_lo=np.uint32(sc & 0xFFFFFFFF),
            gen_hi=np.uint32(sc >> 32),
            unique_count=np.int32(meta["unique_count"]),
            max_depth=np.uint32(meta["max_depth"]),
            discovered=np.uint32(discovered),
            disc_lo=disc_lo, disc_hi=disc_hi,
            overflow=np.uint32(0), steps=np.int32(0),
            hot_claims=np.int32(0),
            s_states=np.zeros((self._SQ, L), np.uint32),
            s_lo=np.zeros(self._SQ, np.uint32),
            s_hi=np.zeros(self._SQ, np.uint32),
            s_ebits=np.zeros(self._SQ, np.uint32),
            s_depth=np.zeros(self._SQ, np.uint32),
            s_tail=np.int32(0),
            summary=self._store.summary_np,
            tm_rows=np.zeros((self._TMR, N_COLS), np.uint32),
        )
        self._carry = _Carry(
            **{k: jax.device_put(jnp.asarray(v)) for k, v in fields.items()}
        )
        return n

    def run(
        self,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        target_state_count: Optional[int] = None,
        target_max_depth: Optional[int] = None,
        timeout: Optional[float] = None,
        max_steps: int = 1 << 30,
        budget: Optional[int] = None,
        progress: Optional[callable] = None,
    ) -> SearchResult:
        """Run (or resume) the search.

        Without `budget`, the whole search is ONE device dispatch (fastest;
        no suspension possible). With `budget`, the search runs in chunks of
        at most `budget` loop steps per dispatch, which enables:
        - `progress(state_count, unique_count, max_depth)` between chunks,
        - `timeout` (polled between chunks, so it overshoots by <=1 chunk),
        - `checkpoint()` / resume (a later `run()` continues the carry), and
        - recoverable overflow: the carry reverts to the last chunk boundary
          so `checkpoint()` + `load_checkpoint(..., table_log2=bigger)` can
          continue the run instead of discarding it.
        """
        # Tiered runs are always chunked: the host must regain control for
        # spill eviction and suspect resolution (the ISSUE's "exit to host
        # on high-water instead of aborting").
        if self._store is not None and budget is None and timeout is None:
            budget = 1 << 20
        chunked, budget = _resolve_chunking(
            budget, timeout, progress, self._carry
        )
        model = self.model
        K = self.batch_size
        start = time.monotonic()
        self._parent_map = None  # invalidate any prior reconstruction cache
        if self._ring is not None and self._carry is None and self._ring.steps:
            # Fresh search (no suspended carry): telemetry starts over too.
            self._ring = self._ring.fresh()

        # seed_init is deterministic per model; cache it (and its padded
        # device-side form) so repeat runs skip the host<->device round trips.
        if self._seed is None:
            init, init_lo, init_hi, n_raw = seed_init(model)
            if len(init) > K:
                raise ValueError(
                    "more init states than batch_size; raise batch_size"
                )
            n0 = len(init)
            st = np.zeros((K, model.lanes), dtype=np.uint32)
            st[:n0] = init
            lo = np.zeros(K, dtype=np.uint32)
            lo[:n0] = init_lo
            hi = np.zeros(K, dtype=np.uint32)
            hi[:n0] = init_hi
            dev = jax.device_put((st, lo, hi))
            self._seed = (dev, n0, n_raw)
        dev, n0, n_raw = self._seed

        # Vacuously-true finish policies (e.g. ALL with zero properties) stop
        # before exploring anything, matching the host checkers' immediate
        # is_awaiting_discoveries early-out (ref: bfs.rs:278-280).
        if finish_when.matches(self.props, set()) or not self.props:
            z = np.zeros(1 << self.table_log2, dtype=np.uint32)
            self._last_tables = (
                (np.zeros(2 << self.table_log2, np.uint32),
                 np.zeros(0, np.uint32), z, z)
                if self.table_layout == "kv"
                else (z, z, z, z)
            )
            return SearchResult(
                state_count=n_raw,
                unique_state_count=n0,
                max_depth=1 if n0 else 0,
                discoveries={},
                complete=False,
                duration=time.monotonic() - start,
                steps=0,
            )

        required_mask, any_mask = _finish_masks(finish_when, self.props)
        target = int(target_state_count or 0)
        t_lo32 = jnp.uint32(target & 0xFFFFFFFF)
        t_hi32 = jnp.uint32(target >> 32)
        tmd = jnp.uint32(target_max_depth or 0)

        timed_out = False
        if not chunked:
            # Chaos-plane boundary: a simulated OOM/XLA fault lands before
            # the whole-search dispatch (faults/plan.py).
            maybe_fault("engine.step", engine="resident")
            with self._tracer.span("resident.search", cat="engine"):
                t_lo, t_hi, p_lo, p_hi, summary, tm_rows = self._kernel(
                    *dev,
                    required_mask,
                    any_mask,
                    t_lo32,
                    t_hi32,
                    max_steps,
                    jnp.int32(n0),
                    jnp.uint32(n_raw & 0xFFFFFFFF),
                    jnp.uint32(n_raw >> 32),
                    tmd,
                    self._dyn_dev,
                )
                # ONE device->host transfer for the entire result.
                summary = np.asarray(summary)
            if self._ring is not None:
                # Whole-search dispatch: one bulk drain at the end (the ring
                # holds the LAST 2^telemetry_log2 steps; earlier rows count
                # as dropped). The window average includes compile time on a
                # cold first run.
                w_us = (time.monotonic() - start) * 1e6
                self._ring.drain(np.asarray(tm_rows), int(summary[8]),
                                 window_us=w_us)
                if self._calib is not None:
                    self._calib.observe(
                        self._ring.steps, w_us, self._ring.generated_total
                    )
            # On overflow the failed run's tables are unsound AND a previous
            # run's snapshot must not silently serve paths for states this
            # run discovered — invalidate (matches the sharded engine).
            self._last_tables = (
                (t_lo, t_hi, p_lo, p_hi) if not summary[7] else None
            )
        else:
            if self._carry is None:
                self._carry = self._seed_k(
                    *dev,
                    jnp.int32(n0),
                    jnp.uint32(n_raw & 0xFFFFFFFF),
                    jnp.uint32(n_raw >> 32),
                )
                if self._warm_summary_pending:
                    # Warm replay: make_carry built an empty Bloom summary;
                    # patch in the preloaded one (warm_start already rebuilt
                    # the store's words) so the very first expansion's
                    # children dedup-filter against the corpus prefix.
                    self._warm_summary_pending = False
                    self._carry = self._carry._replace(
                        summary=jax.device_put(
                            jnp.asarray(self._store.summary_np)
                        )
                    )
            req = jnp.uint32(required_mask)
            anym = jnp.uint32(any_mask)
            if self.donate_chunks:
                # Donating self._carry deletes the buffers a previous run's
                # _last_tables may alias; drop the alias now so a later
                # reconstruct_path gets a clear "no tables" error instead of
                # jax's "Array has been deleted".
                self._last_tables = None
            while True:
                # Chaos-plane boundary: faults land BEFORE the dispatch, so
                # a faulted chunk never half-updates the retained carry.
                maybe_fault("engine.step", engine="resident")
                t_chunk0 = time.monotonic()
                with self._tracer.span("resident.chunk", cat="engine"):
                    carry, summary = self._chunk_k(
                        self._carry,
                        req,
                        anym,
                        t_lo32,
                        t_hi32,
                        tmd,
                        jnp.int32(budget),
                        jnp.int32(max_steps),
                        self._dyn_dev,
                    )
                    summary = np.asarray(summary)  # one small transfer/chunk
                if self._ring is not None:
                    # The chunk already synced (summary fetch); pulling the
                    # ring here adds a bulk copy, never a per-step sync.
                    w_us = (time.monotonic() - t_chunk0) * 1e6
                    self._ring.drain(np.asarray(carry.tm_rows),
                                     int(summary[8]), window_us=w_us)
                    if self._calib is not None:
                        self._calib.observe(
                            self._ring.steps, w_us,
                            self._ring.generated_total,
                        )
                code = int(summary[7])
                if code & EXIT_SERVICE and not (
                    code & (ABORT_TABLE | ABORT_QUEUE)
                ):
                    # Non-fatal host-service exit (tiered store): drain the
                    # suspect buffer, evict past-high-water buckets, compact
                    # the queue, clear the flag, resume the same carry.
                    self._carry = carry
                    self._service()
                    continue
                if code:  # fatal overflow (abort code)
                    self._last_abort = code & (ABORT_TABLE | ABORT_QUEUE)
                    reason = _abort_reason(self._last_abort)
                    if self.donate_chunks:
                        # The pre-chunk carry was donated into the dispatch;
                        # there is no sound state to recover.
                        self._carry = None
                        raise RuntimeError(
                            f"hash table or queue full — {reason}; "
                            "donate_chunks=True sacrificed the recovery "
                            "carry — rerun with the larger size (or "
                            "donate_chunks=False for checkpoint-then-regrow "
                            "recovery)"
                        )
                    # Revert to the pre-chunk carry so checkpoint() +
                    # load_checkpoint(table_log2=bigger) can resume exactly
                    # from the last sound chunk boundary — and point the
                    # reconstruction snapshot at that same boundary so
                    # paths reflect THIS run, not a stale prior one.
                    self._last_tables = (
                        self._carry.t_lo,
                        self._carry.t_hi,
                        self._carry.p_lo,
                        self._carry.p_hi,
                    )
                    self._parent_map = None
                    raise RuntimeError(
                        f"hash table or queue full — {reason}; the search "
                        "carry was kept at the last chunk boundary — "
                        "checkpoint(path) then "
                        "ResidentSearch.load_checkpoint(model, path, ...) "
                        "with the named size raised to continue without "
                        "losing the run (the abort reason is preserved in "
                        "the checkpoint and load_checkpoint enforces the "
                        "growth)"
                    )
                self._carry = carry
                # Chaos-plane boundary: simulated preemption mid-run —
                # raised at a chunk boundary where the carry is sound, the
                # same place a real TPU preemption would surface when the
                # host regains control.
                maybe_fault("engine.chunk", engine="resident")
                if progress is not None:
                    gl, gh, uc, md = (int(x) for x in summary[:4])
                    progress(gl | (gh << 32), uc, md)
                if summary[9]:  # stop: search finished (or hit max_steps)
                    if self._store is not None and int(summary[11]) > 0:
                        # The queue drained with suspects still buffered:
                        # resolve them — confirmed-new rows reopen the
                        # frontier; the next chunk re-evaluates the stop
                        # with an empty buffer, so this cannot loop.
                        self._service()
                        continue
                    break
                if timeout is not None and time.monotonic() - start > timeout:
                    timed_out = True
                    break
            self._last_tables = (
                self._carry.t_lo,
                self._carry.t_hi,
                self._carry.p_lo,
                self._carry.p_hi,
            )

        (
            gen_lo,
            gen_hi,
            unique_count,
            max_depth,
            discovered,
            head,
            tail,
            overflow,
            steps,
            _stop,
        ) = (int(x) for x in summary[:10])
        if overflow:
            self._last_abort = overflow
            raise RuntimeError(
                f"hash table or queue full — {_abort_reason(overflow)}"
            )

        P = len(self.props)
        disc_lo = summary[12 : 12 + max(P, 1)]
        disc_hi = summary[12 + max(P, 1) :]
        discoveries = {
            p.name: int(pack_fp(disc_lo[i], disc_hi[i]))
            for i, p in enumerate(self.props)
            if discovered & (1 << i)
        }
        state_count = gen_lo | (gen_hi << 32)
        if self._warm is not None and head >= tail and not timed_out:
            # Warm-start replay (store/warm.py): the run only re-expanded
            # the init frontier (everything deeper dedup-filtered against
            # the preloaded corpus through the Bloom suspect path), so the
            # result bookkeeping is the publisher's — bit-identical to this
            # engine's own cold run for this content key.
            w = self._warm
            state_count = w["state_count"]
            unique_count = w["unique_count"]
            max_depth = w["max_depth"]
            discoveries = dict(w["discoveries"])
        detail = self._detail()
        if self._warm_kind is not None:
            detail = dict(detail or {})
            detail["corpus"] = {
                "warm_start": True,
                "preloaded_states": self._warm_states,
                "warm_kind": self._warm_kind,
            }
        return SearchResult(
            state_count=state_count,
            unique_state_count=unique_count,
            max_depth=max_depth,
            discoveries=discoveries,
            complete=head >= tail and not timed_out,
            duration=time.monotonic() - start,
            steps=steps,
            detail=detail,
        )

    def telemetry_summary(self) -> Optional[dict]:
        """Step-telemetry digest (obs/ring.py; None with telemetry off) —
        surfaced in SearchResult.detail["telemetry"] and `/metrics`."""
        if self._ring is None:
            return None
        return self._ring.summary(1 << self.table_log2, self.batch_size)

    def metrics(self) -> dict:
        """Flat counter snapshot for the obs registry / Prometheus export.
        Host-side values only (drained telemetry + store counters) — a
        scrape never syncs the device mid-search."""
        out: dict = {}
        if self._ring is not None:
            out.update(
                steps=self._ring.steps,
                generated_states=self._ring.generated_total,
                claimed_states=self._ring.claimed_total,
            )
        stats = self.store_stats()
        if stats:
            # Non-numeric leaves (the store's kind string) are dropped by
            # the Prometheus renderer's flatten step.
            out["store"] = stats
        return out

    def _detail(self) -> Optional[dict]:
        """SearchResult.detail under the one documented schema
        (obs/schema.py, shared assembly in obs.build_detail)."""
        detail = build_detail(self.store_stats(), self.telemetry_summary())
        if self._calib is not None:
            self._calib.finish()
        if self._calib is not None and self._calib.chunks:
            detail = dict(detail or {})
            detail["calib"] = self._calib.detail()
            self._calib.flush_records()
        return detail

    def _service(self) -> None:
        """Host half of the tiered store, run between chunked dispatches on
        an EXIT_SERVICE (or a drained queue with buffered suspects):

        1. compact the frontier queue (live rows shift to the front — with
           spilling, total uniques exceed the table, so the append-only
           tail would otherwise grow without bound);
        2. drain the suspect buffer: exact membership against the host
           spill store; confirmed duplicates are dropped, Bloom false
           positives are injected at the queue tail and counted unique;
        3. evict past-high-water occupancy: cold non-full buckets move to
           the spill tier and the Bloom summary absorbs their keys.

        The carry is rebuilt with the service bit cleared; the caller
        resumes the same while_loop."""
        # Chaos-plane boundary: the whole host half is retriable from the
        # suspended carry (no host/device state mutated yet) — before this
        # boundary the tiered service raises below were failure surfaces
        # the chaos plane could not reach (found by srlint SR004).
        maybe_fault("store.service", engine="resident")
        c = self._carry
        L = self.model.lanes
        SQ = self._SQ
        head, tail = int(c.head), int(c.tail)
        s_tail = int(c.s_tail)
        hot = int(c.hot_claims)
        unique = int(c.unique_count)
        q_states, q_lo, q_hi = c.q_states, c.q_lo, c.q_hi
        q_ebits, q_depth = c.q_ebits, c.q_depth

        if head > 0:
            with self._tracer.span("tiered.queue_compact", cat="store"):
                q_states, q_lo, q_hi, q_ebits, q_depth = _compact_queue(
                    q_states, q_lo, q_hi, q_ebits, q_depth, jnp.int32(head)
                )
            tail -= head
            head = 0
            self._q_compacted = True
        if tail > (1 << self.queue_log2):
            # The LIVE frontier exceeds the queue even compacted — a real
            # capacity wall, recoverable exactly like the device-store
            # queue abort (the carry is sound; checkpoint + regrow).
            self._carry = c._replace(
                q_states=q_states, q_lo=q_lo, q_hi=q_hi,
                q_ebits=q_ebits, q_depth=q_depth,
                head=jnp.int32(head), tail=jnp.int32(tail),
                overflow=jnp.uint32(0),
            )
            self._last_abort = ABORT_QUEUE
            raise RuntimeError(
                f"frontier queue full — {_abort_reason(ABORT_QUEUE)}; the "
                "live frontier exceeds the compacted queue — checkpoint() "
                "then load_checkpoint with a larger queue_log2 to continue"
            )

        if s_tail > 0:
            self._tracer.instant(
                "tiered.suspect_resolve", cat="store", suspects=s_tail
            )
            sus_lo = np.asarray(c.s_lo[:s_tail])
            sus_hi = np.asarray(c.s_hi[:s_tail])
            dup = self._store.resolve_suspects(sus_lo, sus_hi)
            keep = ~dup
            n_conf = int(keep.sum())
            if n_conf:
                blk_states = np.zeros((SQ, L), dtype=np.uint32)
                blk_lo = np.zeros(SQ, dtype=np.uint32)
                blk_hi = np.zeros(SQ, dtype=np.uint32)
                blk_eb = np.zeros(SQ, dtype=np.uint32)
                blk_dp = np.zeros(SQ, dtype=np.uint32)
                blk_states[:n_conf] = np.asarray(c.s_states[:s_tail])[keep]
                blk_lo[:n_conf] = sus_lo[keep]
                blk_hi[:n_conf] = sus_hi[keep]
                blk_eb[:n_conf] = np.asarray(c.s_ebits[:s_tail])[keep]
                blk_dp[:n_conf] = np.asarray(c.s_depth[:s_tail])[keep]
                q_states, q_lo, q_hi, q_ebits, q_depth = _inject_rows(
                    q_states, q_lo, q_hi, q_ebits, q_depth,
                    jnp.int32(tail),
                    jnp.asarray(blk_states), jnp.asarray(blk_lo),
                    jnp.asarray(blk_hi), jnp.asarray(blk_eb),
                    jnp.asarray(blk_dp),
                )
                tail += n_conf
                unique += n_conf

        t_lo, t_hi, p_lo, p_hi = c.t_lo, c.t_hi, c.p_lo, c.p_hi
        if hot >= self._spill_trigger:
            with self._tracer.span("tiered.evict", cat="store"):
                t_lo, t_hi, p_lo, p_hi, n_ev = self._store.evict(
                    t_lo, t_hi, p_lo, p_hi, hot
                )
            if n_ev == 0:
                raise RuntimeError(
                    "tiered store could not free any bucket (every bucket "
                    "is full and pinned); raise table_log2 or lower "
                    "high_water"
                )
            hot -= n_ev

        self._carry = c._replace(
            t_lo=t_lo, t_hi=t_hi, p_lo=p_lo, p_hi=p_hi,
            q_states=q_states, q_lo=q_lo, q_hi=q_hi,
            q_ebits=q_ebits, q_depth=q_depth,
            head=jnp.int32(head), tail=jnp.int32(tail),
            unique_count=jnp.int32(unique),
            hot_claims=jnp.int32(hot),
            s_tail=jnp.int32(0),
            # A FRESH upload, never the store's cached device array: with
            # donate_chunks the next dispatch donates (deletes) whatever
            # sits in the carry, and a later no-eviction service would
            # otherwise re-install the same deleted buffer. The words are
            # tiny; one upload per (rare) service event is free.
            summary=jnp.asarray(self._store.summary_np),
            overflow=jnp.uint32(0),
        )

    def set_dyn_tables(self, tables: dict) -> None:
        """Swap the lowered model's operand tables. Same pytree keys and
        shapes reuse the already-compiled kernels untouched (no retrace);
        `refine_check` calls this between rounds after `extend()`."""
        self._dyn_dev = jax.device_put(tables)

    def reset(self) -> None:
        """Drop any suspended carry so the next `run()` starts fresh."""
        self._carry = None
        self._parent_map = None
        self._last_tables = None
        self._last_abort = 0  # a fresh run owes nothing to an old overflow
        self._q_compacted = False
        if self._ring is not None:
            self._ring = self._ring.fresh()  # telemetry starts over too
        if self._store is not None:
            self._fresh_store()  # spill tier + Bloom summary start empty

    def dump_states(
        self, decode: bool = True, evaluated_only: bool = False,
        raw: bool = False, start: int = 0,
    ) -> list:
        """Batched state dump: every unique state the search reached, pulled
        from the frontier queue in ONE device transfer (the queue never
        wraps, so rows [0, tail) are exactly the unique states ever
        enqueued). This is the device analogue of the reference's
        `StateRecorder` visitor (ref: src/checker/visitor.rs:75-111) — exact
        state-set assertions against device engines. Requires a chunked run
        (`budget=`/`timeout=`/`progress=`), which retains the carry.

        `evaluated_only` restricts the dump to rows the search popped
        ([0, head)) — on an early exit the tail also holds never-evaluated
        frontier rows; for an exhausted run the two dumps coincide. (Rows
        cut off by target_max_depth are popped-but-unevaluated and still
        appear — the one divergence from reference visitor semantics.)"""
        if self._carry is None:
            # srlint: fault-ok caller-contract guard, not an I/O/device surface
            raise RuntimeError(
                "no retained carry to dump: run with budget=... (chunked "
                "dispatch) before dump_states()"
            )
        if self._q_compacted:
            # srlint: fault-ok caller-contract guard, not an I/O/device surface
            raise RuntimeError(
                "dump_states is unavailable once the tiered store has "
                "compacted the frontier queue (rows [0, tail) no longer "
                "cover every unique state; spilled states live host-side) — "
                "use store='device' for exact state-set dumps"
            )
        end = int(self._carry.head if evaluated_only else self._carry.tail)
        if raw:
            # The bulk form: uint32[n, lanes]. refine_check's per-round
            # poison scan works on millions of rows — python tuple-building
            # dominated the round cost before this. `start` slices on device
            # so incremental callers transfer only the delta.
            return np.asarray(self._carry.q_states[start:end])
        rows = np.asarray(self._carry.q_states[:end])
        if not decode:
            return [tuple(int(x) for x in r) for r in rows]
        return [self.model.decode(r) for r in rows]

    # -- checkpoint / resume ---------------------------------------------------
    # SURVEY.md §5: the reference has no partial-search checkpointing; the
    # whole resident carry (tables + queue + counters) is a handful of device
    # arrays, so dumping it is one transfer. Only chunked runs
    # (`run(budget=...)`) keep a carry to dump.

    def checkpoint(self, path: str) -> None:
        """Dump the suspended search carry to `path` (.npz). Valid after a
        chunked `run()` has suspended (budget/timeout exhausted) or raised on
        overflow; `load_checkpoint` rebuilds the search — optionally into a
        LARGER table — and the next `run()` continues exactly."""
        import json

        if self._carry is None:
            # srlint: fault-ok caller-contract guard, not an I/O/device surface
            raise RuntimeError(
                "nothing to checkpoint: no suspended carry (run with "
                "budget=... to enable chunked dispatch)"
            )
        if self.table_layout != "split":
            # load_checkpoint refuses kv checkpoints (regrow is split-only);
            # fail at SAVE time rather than handing back a file that can
            # never be restored.
            raise NotImplementedError(
                "checkpointing is split-layout-only for now; use "
                "table_layout='split' (default) for checkpoint/resume runs"
            )
        c = self._carry
        with self._tracer.span("checkpoint", cat="engine", path=path):
            arrays = {f: np.asarray(getattr(c, f)) for f in c._fields}
        if self._store is not None:
            # Spill tier rides along; the Bloom summary is rebuilt from the
            # fingerprints on load (see store/tiered.py).
            arrays.update(self._store.to_checkpoint())
        arrays["meta"] = np.frombuffer(
            json.dumps(
                {
                    "lanes": self.model.lanes,
                    "max_actions": self.model.max_actions,
                    "properties": [p.name for p in self.props],
                    "table_log2": self.table_log2,
                    "queue_log2": self.queue_log2,
                    "batch_size": self.batch_size,
                    "table_layout": self.table_layout,
                    "insert_variant": self.insert_variant,
                    "store": (
                        self._store.meta() if self._store is not None else None
                    ),
                    "q_compacted": self._q_compacted,
                    # Why the run aborted (0 = clean suspension): lets
                    # load_checkpoint refuse a resume that would hit the
                    # same wall again.
                    "abort_reason": self._last_abort,
                }
            ).encode(),
            dtype=np.uint8,
        )
        # Crash-atomic write (tmp+fsync+rename, CRC32 footer, previous
        # generation kept at `path + ".prev"` — faults/ckptio.py).
        fenced_savez(path, arrays)

    @classmethod
    def load_checkpoint(
        cls,
        model: TensorModel,
        path: str,
        batch_size: Optional[int] = None,
        table_log2: Optional[int] = None,
        donate_chunks: bool = False,
        queue_log2: Optional[int] = None,
    ) -> "ResidentSearch":
        """Rebuild a suspended search from a `checkpoint` file. Passing a
        larger `table_log2` re-hashes the visited set into the bigger table
        (the recovery path for an overflow abort); the queue is padded to the
        matching capacity. The next `run()` continues where the dump left
        off. The CRC footer is verified; a corrupt current generation falls
        back to `path + ".prev"` instead of raising."""
        import json

        data, _src = load_latest(path)
        meta = json.loads(bytes(data["meta"].tobytes()).decode())
        _validate_ckpt_meta(model, meta)
        if meta.get("table_layout", "split") != "split":
            raise NotImplementedError(
                "checkpoint resume is split-layout-only for now; rerun the "
                "search with table_layout='split' (default) if you need "
                "checkpoint/regrow"
            )
        log2 = table_log2 if table_log2 is not None else meta["table_log2"]
        if log2 < meta["table_log2"]:
            raise ValueError("cannot shrink the table on resume")
        meta_q = meta.get("queue_log2", meta["table_log2"])
        if queue_log2 is None:
            # Default-sized checkpoints (queue == table) keep following the
            # table through a regrow — the overflow-recovery path needs the
            # bigger queue. An explicitly right-sized queue is preserved.
            queue_log2 = log2 if meta_q == meta["table_log2"] else meta_q
        # Enforce that the resource the aborted run actually ran out of
        # (preserved in meta by checkpoint()) grew — a same-size resume
        # would hit the identical wall and lose the recovery attempt.
        abort = int(meta.get("abort_reason", 0))
        if abort & ABORT_TABLE and log2 <= meta["table_log2"]:
            raise ValueError(
                "this checkpoint was taken after a hash-table overflow "
                f"(table_log2={meta['table_log2']}); pass a larger "
                "table_log2 to load_checkpoint to regrow the table"
            )
        if abort & ABORT_QUEUE and queue_log2 <= meta_q:
            raise ValueError(
                "this checkpoint was taken after a frontier-queue overflow "
                f"(queue_log2={meta_q}); pass a larger queue_log2 to "
                "load_checkpoint to regrow the queue"
            )
        store_meta = meta.get("store")
        rs = cls(
            model,
            batch_size=batch_size or meta["batch_size"],
            table_log2=log2,
            donate_chunks=donate_chunks,
            queue_log2=queue_log2,
            # A capped/phased run must resume on the same insert design —
            # overflow recovery happens exactly on the long at-scale runs
            # where silently falling back to the full-batch sort would
            # reintroduce the cost the variant was chosen to avoid.
            insert_variant=meta.get("insert_variant", "sort"),
            store="tiered" if store_meta else "device",
            **(
                {
                    "high_water": store_meta["high_water"],
                    "low_water": store_meta["low_water"],
                    "summary_log2": store_meta["summary_log2"],
                }
                if store_meta
                else {}
            ),
        )
        if store_meta:
            from ..store.tiered import TieredStore

            rs._store.close()  # replaced by the checkpointed tier
            rs._store = TieredStore.from_checkpoint(
                1 << log2, store_meta,
                data["spill_fps"], data["spill_parents"],
            )
            rs._q_compacted = bool(meta.get("q_compacted", False))
        # Pre-tiered checkpoints lack the suspect-buffer/summary fields;
        # default them to this engine's (empty) shapes.
        defaults = {
            "hot_claims": np.int32((np.asarray(data["t_lo"]) != 0).sum()),
            "s_states": np.zeros((rs._SQ, model.lanes), np.uint32),
            "s_lo": np.zeros(rs._SQ, np.uint32),
            "s_hi": np.zeros(rs._SQ, np.uint32),
            "s_ebits": np.zeros(rs._SQ, np.uint32),
            "s_depth": np.zeros(rs._SQ, np.uint32),
            "s_tail": np.int32(0),
            "summary": np.zeros(1, np.uint32),
            "tm_rows": np.zeros((rs._TMR, N_COLS), np.uint32),
        }
        fields = {
            f: data[f] if f in data else defaults[f] for f in _Carry._fields
        }
        # The telemetry ring is observability, not search state: a restore
        # with a different ring size (or a pre-obs checkpoint) just starts
        # the ring empty, with the pre-restore steps counted as uncaptured.
        if np.asarray(fields["tm_rows"]).shape != (rs._TMR, N_COLS):
            fields["tm_rows"] = np.zeros((rs._TMR, N_COLS), np.uint32)
        if rs._ring is not None:
            rs._ring.skip_to(int(np.asarray(fields["steps"])))
        # The suspect buffer is sized by batch_size x max_actions: a resume
        # with a different batch size renormalizes it like the queue below
        # (live rows [0, s_tail) are preserved; shrinking past them is
        # refused).
        if store_meta:
            s_tail_live = int(fields["s_tail"])
            if s_tail_live > rs._SQ - rs.batch_size * model.max_actions:
                raise ValueError(
                    "batch_size too small for the checkpointed suspect "
                    f"buffer ({s_tail_live} live suspects); resume with the "
                    "original batch_size"
                )
            for f in ("s_states", "s_lo", "s_hi", "s_ebits", "s_depth"):
                old = fields[f]
                if old.shape[0] != rs._SQ:
                    grown = np.zeros(
                        (rs._SQ,) + old.shape[1:], dtype=old.dtype
                    )
                    keep = min(old.shape[0], rs._SQ)
                    grown[:keep] = old[:keep]
                    fields[f] = grown
            # The summary is a pure function of the spilled set — always
            # use the freshly rebuilt words (also covers regrown tables).
            fields["summary"] = rs._store.summary_np
        # Pre-abort-code checkpoints stored overflow as a bool; the carry
        # now holds a uint32 abort bitmask. Clear it on resume: a chunked
        # checkpoint sits at a sound boundary (code 0) already, but a
        # SEED-insert overflow leaves its code in the carry itself — and
        # the guards above have just enforced that whatever resource
        # aborted has grown, so carrying the old code forward would only
        # re-abort the recovered run on its first step.
        fields["overflow"] = np.zeros_like(
            np.asarray(fields["overflow"]), dtype=np.uint32
        )
        if log2 != meta["table_log2"]:
            fields.update(
                _regrow(
                    model, fields, meta["table_log2"], log2, rs.batch_size,
                    queue_rows=rs._Q,
                    insert_variant=rs.insert_variant,
                )
            )
            # Bucket residency changed wholesale; recount occupied slots
            # (the spilled set is untouched by a regrow).
            fields["hot_claims"] = np.int32(
                (np.asarray(fields["t_lo"]) != 0).sum()
            )
        # Normalize queue arrays to this search's capacity (covers
        # checkpoints from the pre-slack format, changed batch sizes, and
        # regrown tables). Live rows sit at [0, tail); the guard makes the
        # normalization a pure extension — silently dropping frontier rows
        # would corrupt the resumed search.
        ckpt_tail = int(fields["tail"])
        if ckpt_tail > rs._Q - rs.batch_size * model.max_actions:
            raise ValueError(
                f"queue_log2={rs.queue_log2} gives {rs._Q} rows but the "
                f"checkpointed frontier tail is {ckpt_tail}; the queue "
                "cannot shrink below the live frontier"
            )
        for f in ("q_states", "q_lo", "q_hi", "q_ebits", "q_depth"):
            old = fields[f]
            if old.shape[0] != rs._Q:
                grown = np.zeros(
                    (rs._Q,) + old.shape[1:], dtype=old.dtype
                )
                keep = min(old.shape[0], rs._Q)
                grown[:keep] = old[:keep]
                fields[f] = grown
        rs._carry = _Carry(
            **{f: jax.device_put(jnp.asarray(v)) for f, v in fields.items()}
        )
        return rs

    def build_parent_map(self) -> dict:
        """{fingerprint: parent fingerprint (0 = init)} decoded from the
        last run's table snapshot — layout-aware (split vs kv) and cached;
        shared by path reconstruction and the TPU checker's visitors."""
        if self._parent_map is None:
            if self._last_tables is None:
                # srlint: fault-ok caller-contract guard, not an I/O/device surface
                raise RuntimeError(
                    "no table snapshot to reconstruct from: run() has not "
                    "completed since the last reset/donated resume"
                )
            t_lo, t_hi, p_lo, p_hi = (
                np.asarray(x) for x in self._last_tables
            )
            if self.table_layout == "kv":
                b = min(KV_BUCKET, 1 << self.table_log2)
                kv = t_lo.reshape(-1, 2 * b)
                t_lo = kv[:, :b].reshape(-1)
                t_hi = kv[:, b:].reshape(-1)
            nz = t_lo != 0
            keys = pack_fp(t_lo[nz], t_hi[nz])
            parents = pack_fp(p_lo[nz], p_hi[nz])
            self._parent_map = dict(zip(keys.tolist(), parents.tolist()))
            if self._store is not None:
                # Spill entries win on keys present in both tiers: they
                # carry the ORIGINAL (BFS-discovery) parent, which keeps
                # reconstructed chains acyclic.
                self._parent_map.update(self._store.parent_map())
        return self._parent_map

    def reconstruct_path(self, fp: int):
        """TLC-style reconstruction from the final table contents (the logic
        is shared with the host-orchestrated engine)."""
        self.build_parent_map()
        return reconstruct_path(self.model, self._parent_map, fp)
