"""Fully device-resident frontier search: the ENTIRE breadth-first check runs
as one `lax.while_loop` inside one `jit` dispatch.

Motivation: the host-orchestrated loop (frontier.py) pays a host↔device round
trip per step — fatal when the device is reached over a network tunnel and
merely wasteful otherwise. Here the frontier queue itself lives in HBM as a
ring buffer; each loop iteration pops a batch, expands it with the model
kernel, fingerprints + dedups + inserts into the visited table, evaluates
property masks, and appends fresh states to the queue tail — no host
involvement until the search finishes.

Capacity argument: every unique state is enqueued exactly once, so a queue with
as many rows as the hash table has slots can never overflow before the table
does.

Early-exit parity with the reference checkers: the loop stops when every
property has a discovery (src/checker/bfs.rs:278-280), when the configured
`HasDiscoveries` policy matches (encoded as required/any bitmask pairs), when
`target_state_count` is reached, or when the queue drains.
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from .frontier import (
    SearchResult,
    expand_insert,
    reconstruct_path,
    record_discovery as _record,
    seed_init,
)
from .hashtable import _insert_impl
from .model import TensorModel


def _finish_masks(finish_when: HasDiscoveries, props) -> tuple[int, int]:
    """Encode a HasDiscoveries policy as (required_mask, any_mask):
    stop when (discovered & required) == required != 0, or
    (discovered & any_mask) != 0."""
    name_bit = {p.name: 1 << i for i, p in enumerate(props)}
    failure_bits = sum(
        1 << i
        for i, p in enumerate(props)
        if p.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY)
    )
    all_bits = (1 << len(props)) - 1
    k = finish_when.kind
    if k == "all":
        return all_bits, 0
    if k == "any":
        return 0, all_bits
    if k == "any_failures":
        return 0, failure_bits
    if k == "all_failures":
        return failure_bits, 0
    if k == "all_of":
        return sum(name_bit[n] for n in finish_when.names), 0
    if k == "any_of":
        return 0, sum(name_bit[n] for n in finish_when.names)
    raise ValueError(f"unknown HasDiscoveries kind {k!r}")


class _Carry(NamedTuple):
    keys: jnp.ndarray  # uint64[S]
    parents: jnp.ndarray  # uint64[S]
    q_states: jnp.ndarray  # uint32[Q, L]
    q_fps: jnp.ndarray  # uint64[Q]
    q_ebits: jnp.ndarray  # uint32[Q]
    q_depth: jnp.ndarray  # uint32[Q]
    head: jnp.ndarray  # int64
    tail: jnp.ndarray  # int64
    state_count: jnp.ndarray  # int64
    unique_count: jnp.ndarray  # int64
    max_depth: jnp.ndarray  # uint32
    discovered: jnp.ndarray  # uint32 bitmask
    disc_fps: jnp.ndarray  # uint64[P]
    stop: jnp.ndarray  # bool
    overflow: jnp.ndarray  # bool
    steps: jnp.ndarray  # int64


class ResidentSearch:
    """One-dispatch whole-search engine for a `TensorModel`."""

    def __init__(
        self,
        model: TensorModel,
        batch_size: int = 2048,
        table_log2: int = 20,
    ):
        self.model = model
        self.batch_size = batch_size
        self.table_log2 = table_log2
        self.props = model.properties()
        self._kernel = self._build()
        self._last_tables = None
        self._parent_map = None
        self._seed = None

    def _build(self):
        model = self.model
        K = self.batch_size
        A = model.max_actions
        L = model.lanes
        S = 1 << self.table_log2
        Q = S  # see capacity argument in the module docstring
        props = self.props
        P = len(props)
        always_i = [i for i, p in enumerate(props) if p.expectation == Expectation.ALWAYS]
        sometimes_i = [i for i, p in enumerate(props) if p.expectation == Expectation.SOMETIMES]
        eventually_i = [i for i, p in enumerate(props) if p.expectation == Expectation.EVENTUALLY]
        ebits0 = np.uint32(sum(1 << i for i in eventually_i))
        all_bits = jnp.uint32((1 << P) - 1)

        def body(c: _Carry) -> _Carry:
            # -- pop a batch from the queue ------------------------------------
            avail = c.tail - c.head
            take = jnp.minimum(avail, K)
            pos = (c.head + jnp.arange(K, dtype=jnp.int64)) % Q
            active = jnp.arange(K) < take
            states = c.q_states[pos]
            fps = c.q_fps[pos]
            ebits = c.q_ebits[pos]
            depth = c.q_depth[pos]
            head = c.head + take

            max_depth = jnp.maximum(
                c.max_depth, jnp.max(jnp.where(active, depth, 0))
            )

            # -- property evaluation (ref: bfs.rs:230-280) ---------------------
            discovered = c.discovered
            disc_fps = c.disc_fps
            if P:
                masks = jnp.stack([p.condition(model, states) for p in props])
                for i in always_i:
                    hit = active & ~masks[i]
                    discovered, disc_fps = _record(
                        discovered, disc_fps, i, hit, fps
                    )
                for i in sometimes_i:
                    hit = active & masks[i]
                    discovered, disc_fps = _record(
                        discovered, disc_fps, i, hit, fps
                    )
                for i in eventually_i:
                    ebits = jnp.where(
                        masks[i], ebits & jnp.uint32(~(1 << i) & 0xFFFFFFFF), ebits
                    )

            # -- expand + fingerprint + dedup + insert (shared core) -----------
            (
                keys,
                parents,
                out_states,
                out_fps,
                src_rows,
                new_count,
                gen,
                has_succ,
                ovf,
            ) = expand_insert(model, c.keys, c.parents, states, fps, active)

            # -- eventually counterexamples at terminal states -----------------
            if eventually_i:
                term = active & ~has_succ
                for i in eventually_i:
                    bad = term & ((ebits >> jnp.uint32(i)) & 1).astype(bool)
                    discovered, disc_fps = _record(
                        discovered, disc_fps, i, bad, fps
                    )

            # -- append new states to the queue tail ---------------------------
            new_count = new_count.astype(jnp.int64)
            slot = jnp.arange(K * A, dtype=jnp.int64)
            qpos = jnp.where(slot < new_count, (c.tail + slot) % Q, Q)
            q_states = c.q_states.at[qpos].set(out_states, mode="drop")
            q_fps = c.q_fps.at[qpos].set(out_fps, mode="drop")
            child_ebits = ebits[src_rows // A]
            q_ebits = c.q_ebits.at[qpos].set(child_ebits, mode="drop")
            child_depth = depth[src_rows // A] + 1
            q_depth = c.q_depth.at[qpos].set(child_depth, mode="drop")
            tail = c.tail + new_count

            return _Carry(
                keys=keys,
                parents=parents,
                q_states=q_states,
                q_fps=q_fps,
                q_ebits=q_ebits,
                q_depth=q_depth,
                head=head,
                tail=tail,
                state_count=c.state_count + gen.astype(jnp.int64),
                unique_count=c.unique_count + new_count,
                max_depth=max_depth,
                discovered=discovered,
                disc_fps=disc_fps,
                stop=c.stop,
                overflow=c.overflow | ovf,
                steps=c.steps + 1,
            )

        @partial(jax.jit, static_argnums=(3, 4, 7))
        def search(
            init_states,  # uint32[K, L] padded
            init_fps,  # uint64[K]
            init_active,  # bool[K]
            required_mask: int,
            any_mask: int,
            target_state_count,  # int64 scalar (0 = none)
            n_raw_seed,  # int64: pre-dedup init count (host count parity)
            max_steps: int,
        ):
            # Tables are allocated in-trace: a fresh search per dispatch, and
            # no host-side zero-fill round trip over the device tunnel.
            keys = jnp.zeros(S, dtype=jnp.uint64)
            parents = jnp.zeros(S, dtype=jnp.uint64)
            # Seed the table and queue with the (pre-deduped) init batch.
            keys, parents, is_new, ovf = _insert_impl(
                keys, parents, init_fps, jnp.zeros(K, dtype=jnp.uint64), init_active
            )
            n0 = init_active.sum().astype(jnp.int64)
            q_states = jnp.zeros((Q, L), dtype=jnp.uint32)
            q_fps = jnp.zeros(Q, dtype=jnp.uint64)
            q_ebits = jnp.zeros(Q, dtype=jnp.uint32)
            q_depth = jnp.zeros(Q, dtype=jnp.uint32)
            slot = jnp.arange(K, dtype=jnp.int64)
            qpos = jnp.where(slot < n0, slot, Q)
            q_states = q_states.at[qpos].set(init_states, mode="drop")
            q_fps = q_fps.at[qpos].set(init_fps, mode="drop")
            q_ebits = q_ebits.at[qpos].set(jnp.uint32(ebits0), mode="drop")
            q_depth = q_depth.at[qpos].set(jnp.uint32(1), mode="drop")

            req = jnp.uint32(required_mask)
            anym = jnp.uint32(any_mask)

            def cond(c: _Carry):
                drained = c.head >= c.tail
                all_found = (P > 0) & (c.discovered == all_bits)
                policy = ((req != 0) & ((c.discovered & req) == req)) | (
                    (c.discovered & anym) != 0
                )
                count_hit = (target_state_count > 0) & (
                    c.state_count >= target_state_count
                )
                return (
                    (~drained)
                    & (~all_found)
                    & (~policy)
                    & (~count_hit)
                    & (~c.overflow)
                    & (c.steps < max_steps)
                )

            carry = _Carry(
                keys=keys,
                parents=parents,
                q_states=q_states,
                q_fps=q_fps,
                q_ebits=q_ebits,
                q_depth=q_depth,
                head=jnp.int64(0),
                tail=n0,
                state_count=n_raw_seed,
                unique_count=is_new.sum().astype(jnp.int64),
                max_depth=jnp.uint32(0),
                discovered=jnp.uint32(0),
                disc_fps=jnp.zeros(max(P, 1), dtype=jnp.uint64),
                stop=jnp.bool_(False),
                overflow=ovf,
                steps=jnp.int64(0),
            )
            carry = jax.lax.while_loop(cond, body, carry)
            # Pack every host-facing scalar into ONE small vector so the host
            # reads the whole result in a single device transfer (each fetch
            # over the device tunnel costs a full round trip).
            summary = jnp.concatenate(
                [
                    jnp.stack(
                        [
                            carry.state_count.astype(jnp.uint64),
                            carry.unique_count.astype(jnp.uint64),
                            carry.max_depth.astype(jnp.uint64),
                            carry.discovered.astype(jnp.uint64),
                            carry.head.astype(jnp.uint64),
                            carry.tail.astype(jnp.uint64),
                            carry.overflow.astype(jnp.uint64),
                            carry.steps.astype(jnp.uint64),
                        ]
                    ),
                    carry.disc_fps,
                ]
            )
            return carry.keys, carry.parents, summary

        return search

    # -- host entry ------------------------------------------------------------

    def run(
        self,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        target_state_count: Optional[int] = None,
        target_max_depth: Optional[int] = None,
        timeout: Optional[float] = None,
        max_steps: int = 1 << 31,
    ) -> SearchResult:
        if target_max_depth is not None:
            raise NotImplementedError(
                "target_max_depth on the resident engine lands with the "
                "depth-masked body; use the host-orchestrated FrontierSearch "
                "(TpuChecker(resident=False)) meanwhile"
            )
        del timeout  # device loops can't be interrupted; bound via max_steps
        model = self.model
        K = self.batch_size
        start = time.monotonic()
        self._parent_map = None  # invalidate any prior reconstruction cache

        # seed_init is deterministic per model; cache it (and its padded
        # device-side form) so repeat runs skip the host<->device round trips.
        if self._seed is None:
            init, init_fps, n_raw = seed_init(model)
            if len(init) > K:
                raise ValueError(
                    "more init states than batch_size; raise batch_size"
                )
            n0 = len(init)
            st = np.zeros((K, model.lanes), dtype=np.uint32)
            st[:n0] = init
            fp = np.zeros(K, dtype=np.uint64)
            fp[:n0] = init_fps
            active = np.arange(K) < n0
            dev = jax.device_put((st, fp, active))
            self._seed = (dev, n0, n_raw)
        dev, n0, n_raw = self._seed

        # Vacuously-true finish policies (e.g. ALL with zero properties) stop
        # before exploring anything, matching the host checkers' immediate
        # is_awaiting_discoveries early-out (ref: bfs.rs:278-280).
        if finish_when.matches(self.props, set()) or not self.props:
            self._last_tables = (
                np.zeros(1 << self.table_log2, dtype=np.uint64),
                np.zeros(1 << self.table_log2, dtype=np.uint64),
            )
            return SearchResult(
                state_count=n_raw,
                unique_state_count=n0,
                max_depth=1 if n0 else 0,
                discoveries={},
                complete=False,
                duration=time.monotonic() - start,
                steps=0,
            )

        required_mask, any_mask = _finish_masks(finish_when, self.props)
        keys, parents, summary = self._kernel(
            *dev,
            required_mask,
            any_mask,
            jnp.int64(target_state_count or 0),
            jnp.int64(n_raw),
            max_steps,
        )
        # ONE device->host transfer for the entire result.
        summary = np.asarray(summary)
        (
            state_count,
            unique_count,
            max_depth,
            discovered,
            head,
            tail,
            overflow,
            steps,
        ) = (int(x) for x in summary[:8])
        if overflow:
            raise RuntimeError("hash table full; raise table_log2")
        self._last_tables = (keys, parents)

        disc_fps = summary[8:]
        discoveries = {
            p.name: int(disc_fps[i])
            for i, p in enumerate(self.props)
            if discovered & (1 << i)
        }
        return SearchResult(
            state_count=state_count,
            unique_state_count=unique_count,
            max_depth=max_depth,
            discoveries=discoveries,
            complete=head >= tail,
            duration=time.monotonic() - start,
            steps=steps,
        )

    def reconstruct_path(self, fp: int):
        """TLC-style reconstruction from the final table contents (the logic
        is shared with the host-orchestrated engine)."""
        if self._parent_map is None:
            keys, parents = self._last_tables
            keys = np.asarray(keys)
            parents = np.asarray(parents)
            nz = keys != 0
            self._parent_map = dict(
                zip(keys[nz].tolist(), parents[nz].tolist())
            )
        return reconstruct_path(self.model, self._parent_map, fp)
