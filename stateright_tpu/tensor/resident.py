"""Fully device-resident frontier search: the ENTIRE breadth-first check runs
as one `lax.while_loop` inside one `jit` dispatch.

Motivation: the host-orchestrated loop (frontier.py) pays a host↔device round
trip per step — fatal when the device is reached over a network tunnel and
merely wasteful otherwise. Here the frontier queue itself lives in HBM; each
loop iteration pops a batch (a contiguous dynamic slice — the queue never
wraps, see below), expands it with the model kernel, fingerprints + dedups +
inserts into the visited table, evaluates property masks, and appends fresh
states to the queue tail — no host involvement until the search finishes.

Everything on device is 32-bit (u32 fingerprint pairs, u32-pair generated
counters): TPUs emulate 64-bit integer ops, so the round-1 u64 design paid
emulation tax on every hot op.

Capacity argument (also why the queue needs no ring wraparound): every unique
state is enqueued exactly once, so a queue with as many rows as the hash
table has slots can never fill before the table overflows.

Early-exit parity with the reference checkers: the loop stops when every
property has a discovery (src/checker/bfs.rs:278-280), when the configured
`HasDiscoveries` policy matches (encoded as required/any bitmask pairs), when
`target_state_count` is reached, or when the queue drains.
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from .fingerprint import pack_fp
from .frontier import (
    SearchResult,
    append_new,
    count_add,
    count_ge,
    expand_insert,
    pop_batch,
    reconstruct_path,
    record_discovery as _record,
    seed_init,
)
from .hashtable import _insert_impl
from .model import TensorModel


def _finish_masks(finish_when: HasDiscoveries, props) -> tuple[int, int]:
    """Encode a HasDiscoveries policy as (required_mask, any_mask):
    stop when (discovered & required) == required != 0, or
    (discovered & any_mask) != 0."""
    name_bit = {p.name: 1 << i for i, p in enumerate(props)}
    failure_bits = sum(
        1 << i
        for i, p in enumerate(props)
        if p.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY)
    )
    all_bits = (1 << len(props)) - 1
    k = finish_when.kind
    if k == "all":
        return all_bits, 0
    if k == "any":
        return 0, all_bits
    if k == "any_failures":
        return 0, failure_bits
    if k == "all_failures":
        return failure_bits, 0
    if k == "all_of":
        return sum(name_bit[n] for n in finish_when.names), 0
    if k == "any_of":
        return 0, sum(name_bit[n] for n in finish_when.names)
    raise ValueError(f"unknown HasDiscoveries kind {k!r}")


class _Carry(NamedTuple):
    t_lo: jnp.ndarray  # uint32[S] visited-table key halves
    t_hi: jnp.ndarray  # uint32[S]
    p_lo: jnp.ndarray  # uint32[S] parent halves
    p_hi: jnp.ndarray  # uint32[S]
    q_states: jnp.ndarray  # uint32[Q, L]
    q_lo: jnp.ndarray  # uint32[Q]
    q_hi: jnp.ndarray  # uint32[Q]
    q_ebits: jnp.ndarray  # uint32[Q]
    q_depth: jnp.ndarray  # uint32[Q]
    head: jnp.ndarray  # int32
    tail: jnp.ndarray  # int32
    gen_lo: jnp.ndarray  # uint32 generated-count pair
    gen_hi: jnp.ndarray  # uint32
    unique_count: jnp.ndarray  # int32
    max_depth: jnp.ndarray  # uint32
    discovered: jnp.ndarray  # uint32 bitmask
    disc_lo: jnp.ndarray  # uint32[P]
    disc_hi: jnp.ndarray  # uint32[P]
    overflow: jnp.ndarray  # bool
    steps: jnp.ndarray  # int32


class ResidentSearch:
    """One-dispatch whole-search engine for a `TensorModel`."""

    def __init__(
        self,
        model: TensorModel,
        batch_size: int = 2048,
        table_log2: int = 20,
    ):
        self.model = model
        self.batch_size = batch_size
        self.table_log2 = table_log2
        self.props = model.properties()
        self._kernel, self._seed_k, self._chunk_k = self._build()
        self._last_tables = None
        self._parent_map = None
        self._seed = None

    def _build(self):
        model = self.model
        K = self.batch_size
        A = model.max_actions
        L = model.lanes
        S = 1 << self.table_log2
        Q = S  # see capacity argument in the module docstring
        props = self.props
        P = len(props)
        always_i = [i for i, p in enumerate(props) if p.expectation == Expectation.ALWAYS]
        sometimes_i = [i for i, p in enumerate(props) if p.expectation == Expectation.SOMETIMES]
        eventually_i = [i for i, p in enumerate(props) if p.expectation == Expectation.EVENTUALLY]
        ebits0 = np.uint32(sum(1 << i for i in eventually_i))
        all_bits = jnp.uint32((1 << P) - 1)

        def body(c: _Carry, tmd) -> _Carry:
            # -- pop a batch: contiguous dynamic slice (no wraparound) ---------
            states, lo, hi, ebits, depth, active, head = pop_batch(
                c.q_states, c.q_lo, c.q_hi, c.q_ebits, c.q_depth,
                c.head, c.tail, K,
            )

            max_depth = jnp.maximum(
                c.max_depth, jnp.max(jnp.where(active, depth, 0))
            )
            # target_max_depth: states at the cutoff are neither evaluated
            # nor expanded (ref: bfs.rs:219-224); 0 = no limit.
            active = active & ((tmd == 0) | (depth < tmd))

            # -- property evaluation (ref: bfs.rs:230-280) ---------------------
            discovered = c.discovered
            disc_lo, disc_hi = c.disc_lo, c.disc_hi
            if P:
                masks = jnp.stack([p.condition(model, states) for p in props])
                for i in always_i:
                    discovered, disc_lo, disc_hi = _record(
                        discovered, disc_lo, disc_hi, i, active & ~masks[i], lo, hi
                    )
                for i in sometimes_i:
                    discovered, disc_lo, disc_hi = _record(
                        discovered, disc_lo, disc_hi, i, active & masks[i], lo, hi
                    )
                for i in eventually_i:
                    ebits = jnp.where(
                        masks[i], ebits & jnp.uint32(~(1 << i) & 0xFFFFFFFF), ebits
                    )

            # -- expand + fingerprint + dedup + insert (shared core) -----------
            (
                t_lo, t_hi, p_lo, p_hi,
                flat, slo, shi, is_new,
                gen, has_succ, ovf,
            ) = expand_insert(
                model, c.t_lo, c.t_hi, c.p_lo, c.p_hi, states, lo, hi, active
            )

            # -- eventually counterexamples at terminal states -----------------
            if eventually_i:
                term = active & ~has_succ
                for i in eventually_i:
                    bad = term & ((ebits >> jnp.uint32(i)) & 1).astype(bool)
                    discovered, disc_lo, disc_hi = _record(
                        discovered, disc_lo, disc_hi, i, bad, lo, hi
                    )

            # -- append new states to the queue tail (cumsum compaction) -------
            src_row = jnp.arange(K * A, dtype=jnp.int32) // A
            q_states, q_lo, q_hi, q_ebits, q_depth, tail = append_new(
                c.q_states, c.q_lo, c.q_hi, c.q_ebits, c.q_depth, c.tail,
                flat, slo, shi, ebits[src_row], depth[src_row] + 1, is_new,
            )
            new_count = tail - c.tail
            # A nearly-full queue would make the next pop's dynamic_slice
            # clamp mis-align with the active mask (and a full one would drop
            # appends); stopping at Q - K fires before either can corrupt
            # results, and the table overflows around the same occupancy
            # anyway. Surfaced to the host as overflow.
            q_full = tail > Q - K

            gen_lo, gen_hi = count_add(c.gen_lo, c.gen_hi, gen)
            return _Carry(
                t_lo=t_lo,
                t_hi=t_hi,
                p_lo=p_lo,
                p_hi=p_hi,
                q_states=q_states,
                q_lo=q_lo,
                q_hi=q_hi,
                q_ebits=q_ebits,
                q_depth=q_depth,
                head=head,
                tail=tail,
                gen_lo=gen_lo,
                gen_hi=gen_hi,
                unique_count=c.unique_count + new_count,
                max_depth=max_depth,
                discovered=discovered,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                overflow=c.overflow | ovf | q_full,
                steps=c.steps + 1,
            )

        def should_continue(
            c: _Carry, req, anym, have_target, target_lo, target_hi, max_steps
        ):
            drained = c.head >= c.tail
            all_found = (P > 0) & (c.discovered == all_bits)
            policy = ((req != 0) & ((c.discovered & req) == req)) | (
                (c.discovered & anym) != 0
            )
            count_hit = have_target & count_ge(
                c.gen_lo, c.gen_hi, target_lo, target_hi
            )
            return (
                (~drained)
                & (~all_found)
                & (~policy)
                & (~count_hit)
                & (~c.overflow)
                & (c.steps < max_steps)
            )

        def make_carry(init_states, init_lo, init_hi, n0, seed_lo, seed_hi):
            # Tables are allocated in-trace: a fresh search per dispatch, and
            # no host-side zero-fill round trip over the device tunnel.
            t_lo = jnp.zeros(S, dtype=jnp.uint32)
            t_hi = jnp.zeros(S, dtype=jnp.uint32)
            p_lo = jnp.zeros(S, dtype=jnp.uint32)
            p_hi = jnp.zeros(S, dtype=jnp.uint32)
            init_active = jnp.arange(K, dtype=jnp.int32) < n0
            t_lo, t_hi, p_lo, p_hi, is_new, ovf = _insert_impl(
                t_lo, t_hi, p_lo, p_hi,
                init_lo, init_hi,
                jnp.zeros(K, dtype=jnp.uint32), jnp.zeros(K, dtype=jnp.uint32),
                init_active,
            )
            q_states = jnp.zeros((Q, L), dtype=jnp.uint32)
            q_lo = jnp.zeros(Q, dtype=jnp.uint32)
            q_hi = jnp.zeros(Q, dtype=jnp.uint32)
            q_ebits = jnp.zeros(Q, dtype=jnp.uint32)
            q_depth = jnp.zeros(Q, dtype=jnp.uint32)
            slot = jnp.arange(K, dtype=jnp.int32)
            qpos = jnp.where(slot < n0, slot, Q)
            q_states = q_states.at[qpos].set(init_states, mode="drop")
            q_lo = q_lo.at[qpos].set(init_lo, mode="drop")
            q_hi = q_hi.at[qpos].set(init_hi, mode="drop")
            q_ebits = q_ebits.at[qpos].set(jnp.uint32(ebits0), mode="drop")
            q_depth = q_depth.at[qpos].set(jnp.uint32(1), mode="drop")

            return _Carry(
                t_lo=t_lo,
                t_hi=t_hi,
                p_lo=p_lo,
                p_hi=p_hi,
                q_states=q_states,
                q_lo=q_lo,
                q_hi=q_hi,
                q_ebits=q_ebits,
                q_depth=q_depth,
                head=jnp.int32(0),
                tail=n0.astype(jnp.int32),
                gen_lo=seed_lo,
                gen_hi=seed_hi,
                unique_count=is_new.sum().astype(jnp.int32),
                max_depth=jnp.uint32(0),
                discovered=jnp.uint32(0),
                disc_lo=jnp.zeros(max(P, 1), dtype=jnp.uint32),
                disc_hi=jnp.zeros(max(P, 1), dtype=jnp.uint32),
                overflow=ovf,
                steps=jnp.int32(0),
            )

        def summary_of(carry: _Carry, stop):
            # Pack every host-facing scalar into ONE small vector so the host
            # reads the whole result in a single device transfer (each fetch
            # over the device tunnel costs a full round trip).
            return jnp.concatenate(
                [
                    jnp.stack(
                        [
                            carry.gen_lo,
                            carry.gen_hi,
                            carry.unique_count.astype(jnp.uint32),
                            carry.max_depth,
                            carry.discovered,
                            carry.head.astype(jnp.uint32),
                            carry.tail.astype(jnp.uint32),
                            carry.overflow.astype(jnp.uint32),
                            carry.steps.astype(jnp.uint32),
                            stop.astype(jnp.uint32),
                        ]
                    ),
                    carry.disc_lo,
                    carry.disc_hi,
                ]
            )

        @partial(jax.jit, static_argnums=(3, 4, 7))
        def search(
            init_states,  # uint32[K, L] padded
            init_lo,  # uint32[K]
            init_hi,  # uint32[K]
            required_mask: int,
            any_mask: int,
            target_lo,  # uint32 scalar pair (0, 0 = none)
            target_hi,
            max_steps: int,
            n0,  # int32: number of active seed rows
            seed_lo,  # uint32 pair: pre-dedup init count (host count parity)
            seed_hi,
            target_max_depth,  # uint32 (0 = no limit)
        ):
            req = jnp.uint32(required_mask)
            anym = jnp.uint32(any_mask)
            have_target = (target_lo | target_hi) != 0
            carry = make_carry(
                init_states, init_lo, init_hi, n0, seed_lo, seed_hi
            )
            carry = jax.lax.while_loop(
                lambda c: should_continue(
                    c, req, anym, have_target, target_lo, target_hi, max_steps
                ),
                lambda c: body(c, target_max_depth),
                carry,
            )
            summary = summary_of(carry, jnp.bool_(True))
            return carry.t_lo, carry.t_hi, carry.p_lo, carry.p_hi, summary

        @jax.jit
        def seed_k(init_states, init_lo, init_hi, n0, seed_lo, seed_hi):
            return make_carry(init_states, init_lo, init_hi, n0, seed_lo, seed_hi)

        @partial(jax.jit, donate_argnums=(0,))
        def chunk_k(
            carry: _Carry,
            req,  # uint32 dynamic (one compiled chunk kernel per model/shape)
            anym,
            target_lo,
            target_hi,
            target_max_depth,
            budget,  # int32: max loop steps THIS dispatch
            max_steps,  # int32: global step cap
        ):
            have_target = (target_lo | target_hi) != 0
            start = carry.steps

            def cond(c: _Carry):
                return should_continue(
                    c, req, anym, have_target, target_lo, target_hi, max_steps
                ) & (c.steps < start + budget)

            carry = jax.lax.while_loop(
                cond, lambda c: body(c, target_max_depth), carry
            )
            stop = ~should_continue(
                carry, req, anym, have_target, target_lo, target_hi, max_steps
            )
            return carry, summary_of(carry, stop)

        return search, seed_k, chunk_k

    # -- host entry ------------------------------------------------------------

    def run(
        self,
        finish_when: HasDiscoveries = HasDiscoveries.ALL,
        target_state_count: Optional[int] = None,
        target_max_depth: Optional[int] = None,
        timeout: Optional[float] = None,
        max_steps: int = 1 << 30,
    ) -> SearchResult:
        if timeout is not None:
            raise NotImplementedError(
                "a device-resident while_loop cannot be interrupted by wall "
                "clock; use the host-orchestrated FrontierSearch for timeouts "
                "(spawn_tpu routes there automatically) or bound via "
                "max_steps"
            )
        model = self.model
        K = self.batch_size
        start = time.monotonic()
        self._parent_map = None  # invalidate any prior reconstruction cache

        # seed_init is deterministic per model; cache it (and its padded
        # device-side form) so repeat runs skip the host<->device round trips.
        if self._seed is None:
            init, init_lo, init_hi, n_raw = seed_init(model)
            if len(init) > K:
                raise ValueError(
                    "more init states than batch_size; raise batch_size"
                )
            n0 = len(init)
            st = np.zeros((K, model.lanes), dtype=np.uint32)
            st[:n0] = init
            lo = np.zeros(K, dtype=np.uint32)
            lo[:n0] = init_lo
            hi = np.zeros(K, dtype=np.uint32)
            hi[:n0] = init_hi
            dev = jax.device_put((st, lo, hi))
            self._seed = (dev, n0, n_raw)
        dev, n0, n_raw = self._seed

        # Vacuously-true finish policies (e.g. ALL with zero properties) stop
        # before exploring anything, matching the host checkers' immediate
        # is_awaiting_discoveries early-out (ref: bfs.rs:278-280).
        if finish_when.matches(self.props, set()) or not self.props:
            z = np.zeros(1 << self.table_log2, dtype=np.uint32)
            self._last_tables = (z, z, z, z)
            return SearchResult(
                state_count=n_raw,
                unique_state_count=n0,
                max_depth=1 if n0 else 0,
                discoveries={},
                complete=False,
                duration=time.monotonic() - start,
                steps=0,
            )

        required_mask, any_mask = _finish_masks(finish_when, self.props)
        target = int(target_state_count or 0)
        t_lo, t_hi, p_lo, p_hi, summary = self._kernel(
            *dev,
            required_mask,
            any_mask,
            jnp.uint32(target & 0xFFFFFFFF),
            jnp.uint32(target >> 32),
            max_steps,
            jnp.int32(n0),
            jnp.uint32(n_raw & 0xFFFFFFFF),
            jnp.uint32(n_raw >> 32),
            jnp.uint32(target_max_depth or 0),
        )
        # ONE device->host transfer for the entire result.
        summary = np.asarray(summary)
        (
            gen_lo,
            gen_hi,
            unique_count,
            max_depth,
            discovered,
            head,
            tail,
            overflow,
            steps,
            _stop,
        ) = (int(x) for x in summary[:10])
        if overflow:
            raise RuntimeError("hash table full; raise table_log2")
        self._last_tables = (t_lo, t_hi, p_lo, p_hi)

        P = len(self.props)
        disc_lo = summary[10 : 10 + max(P, 1)]
        disc_hi = summary[10 + max(P, 1) :]
        discoveries = {
            p.name: int(pack_fp(disc_lo[i], disc_hi[i]))
            for i, p in enumerate(self.props)
            if discovered & (1 << i)
        }
        return SearchResult(
            state_count=gen_lo | (gen_hi << 32),
            unique_state_count=unique_count,
            max_depth=max_depth,
            discoveries=discoveries,
            complete=head >= tail,
            duration=time.monotonic() - start,
            steps=steps,
        )

    def reconstruct_path(self, fp: int):
        """TLC-style reconstruction from the final table contents (the logic
        is shared with the host-orchestrated engine)."""
        if self._parent_map is None:
            t_lo, t_hi, p_lo, p_hi = (
                np.asarray(x) for x in self._last_tables
            )
            nz = t_lo != 0
            keys = pack_fp(t_lo[nz], t_hi[nz])
            parents = pack_fp(p_lo[nz], p_hi[nz])
            self._parent_map = dict(zip(keys.tolist(), parents.tolist()))
        return reconstruct_path(self.model, self._parent_map, fp)
