"""THE insert-variant dispatch table — one module, imported by all three
engine spines and the check service.

Before this module each spine hand-wired its own variant-name → insert-fn
chain: `FrontierSearch.INSERT_VARIANTS` (a dict), `ResidentSearch._insert_fn`
(an if/else plus a kv adapter), the sharded engine (hard-wired `_insert_impl`
with no knob at all), and the service (re-pointing at FrontierSearch's dict).
The r10 fix list showed what that costs — every new variant was wired three
or four times, drifting independently. This module is the step-core pre-work
(ROADMAP item 3): `knobs.py` owns the NAMES, this module owns the name → fn
DISPATCH, and `knobs.check_registry()` pins the two against each other.

Every entry shares one traced signature:

    insert(t_lo, t_hi, p_lo, p_hi, lo, hi, parent_lo, parent_hi, active)
        -> (t_lo, t_hi, p_lo, p_hi, is_new, overflow)

(kv layout: t_lo carries the uint32[2S] interleaved array and t_hi a
zero-length placeholder — the adapter below hides the narrower kv table
signature). The Pallas variant additionally offers a FUSED form for the
tiered store (`resolve_insert(..., summary_cfg=...)`): a 10th `summary`
operand and a 7-tuple result whose extra element is the suspect mask,
computed by the kernel's in-pass Bloom probe instead of a separate
post-insert gather sweep (tensor/pallas_hashtable.py). Fused inserts are
marked with `fn.fused_summary = True`; `frontier.expand_insert` keys on the
marker.
"""

from __future__ import annotations

from ..knobs import INSERT_VARIANTS, PHASED_VARIANTS, TABLE_LAYOUTS
from .hashtable import (
    HashTable,
    _insert_impl,
    _insert_impl_capped,
    _insert_impl_kv,
    _insert_impl_kv_capped,
    _insert_impl_phased,
    _insert_impl_phased_capped,
)
from .pallas_hashtable import PallasHashTable, make_engine_insert

#: the uniform-signature Pallas insert (partition count and interpret mode
#: resolved at trace time from the table shape / backend).
_insert_impl_pallas = make_engine_insert()

#: split-layout dispatch: keys are exactly knobs.INSERT_VARIANTS (pinned by
#: knobs.check_registry()).
INSERT_TABLE = {
    "sort": _insert_impl,
    "phased": _insert_impl_phased,
    "capped": _insert_impl_capped,
    "capped-phased": _insert_impl_phased_capped,
    "pallas": _insert_impl_pallas,
}


def _kv_adapt(kv_insert):
    """Lift a kv-table insert (3 table arrays) to the uniform 4-array
    signature: t_lo is the uint32[2S] kv array, t_hi the placeholder."""

    def kv_adapter(t_kv, t_empty, p_lo, p_hi, lo, hi, plo, phi, active):
        r = kv_insert(t_kv, p_lo, p_hi, lo, hi, plo, phi, active)
        return r.t_kv, t_empty, r.p_lo, r.p_hi, r.is_new, r.overflow

    return kv_adapter


#: kv-layout dispatch — only the variants with a kv lowering (the phased
#: family and pallas are split-only; the engines enforce that before
#: resolving).
KV_INSERT_TABLE = {
    "sort": _kv_adapt(_insert_impl_kv),
    "capped": _kv_adapt(_insert_impl_kv_capped),
}


def check_table_log2(insert_variant: str, table_log2: int) -> None:
    """Shared constructor guard — ONE spelling of the pallas tiling
    precondition instead of one per engine (the drift class this module
    exists to bound). Only pallas constrains the table size: its
    partitioned table must tile into (8, 128) VMEM blocks
    (pallas_hashtable.ROW_ALIGN); the XLA designs handle any size the
    engines otherwise accept (tests deliberately run tiny overflow
    tables)."""
    if insert_variant == "pallas" and table_log2 < 10:
        raise ValueError(
            "insert_variant='pallas' needs table_log2 >= 10 (the pallas "
            "partitioned table must tile into 8x128 VMEM blocks — "
            "tensor/pallas_hashtable.py)"
        )


def make_table(insert_variant: str, table_log2: int):
    """Host-side table handle for a variant (split layout). The Pallas
    table probes its own slot layout (partition + in-partition row —
    pallas_hashtable.py), so EVERY insert into it, seeding included, must
    go through the Pallas path; the handle's insert() is that path for
    the host-orchestrated engines' seed loops."""
    check_table_log2(insert_variant, table_log2)
    if insert_variant == "pallas":
        return PallasHashTable(table_log2)
    return HashTable(table_log2)


def resolve_insert(
    insert_variant: str,
    table_layout: str = "split",
    *,
    summary_cfg=None,
):
    """variant name (+ layout) → traced insert fn; the ONE resolution point
    all engines and the service call.

    `summary_cfg=(summary_log2, hashes)` requests the tiered store's fused
    suspect probe where the variant supports it (pallas only today): the
    returned fn takes the summary as a 10th operand and returns the suspect
    mask as a 7th result (marked `fused_summary=True`). Variants without a
    fused form return their plain insert — callers probe the summary with
    `store.summary.maybe_contains` after the insert, exactly as before.
    """
    if table_layout not in TABLE_LAYOUTS:  # knob universe: knobs.py
        raise ValueError(
            f"table_layout must be one of {TABLE_LAYOUTS}, "
            f"got {table_layout!r}"
        )
    if insert_variant not in INSERT_VARIANTS:  # knob universe: knobs.py
        raise ValueError(
            f"insert_variant must be one of {INSERT_VARIANTS}, "
            f"got {insert_variant!r}"
        )
    if table_layout == "kv":
        if insert_variant in PHASED_VARIANTS or insert_variant == "pallas":
            raise ValueError(
                f"insert_variant={insert_variant!r} supports the split "
                "table layout only"
            )
        return KV_INSERT_TABLE[insert_variant]
    if insert_variant == "pallas" and summary_cfg is not None:
        return make_engine_insert(summary_cfg=summary_cfg)
    return INSERT_TABLE[insert_variant]
