"""Sorted-pool surgery without sorting — a MEASURED-SLOWER alternative to
the `jnp.sort` pool rebuild in the network multiset kernels, kept for the
record and for wider-pool models where the trade may flip.

The canonical network-pool state is a SORTED vector of u32 envelope ids with
EMPTY (0xFFFFFFFF) sentinels packed at the tail. Every Deliver successor
drops one slot and inserts <= k emissions; the models rebuild the invariant
with `jnp.sort` over a [B, A, M+k] tensor. Both inputs are already sorted,
so the rank-based merge here does the same job in O(M*k) elementwise
compares with no sort at all — but the round-4 v5e A/B measured it ~2x
SLOWER end-to-end than the sort form it replaced (paxos-3 443k -> 228k
states/s; lowered paxos5s4c 314k -> 140k): at pool widths ~14, XLA expands
the small-axis sort into a fully-fused compare-exchange network, while the
merge's take_along_axis gathers and [.., M, k] mask reductions fuse worse.
The sort stays the production form; parity tests (tests/test_poolops.py)
keep this alternative honest. The mechanics:

- the drop is a shift-left past the dropped slot (`drop_slot`);
- each (sorted) emission's output position is its rank in the pool plus its
  emission index; each pool element shifts right by the number of strictly
  smaller emissions (`merge_insert_sorted`);
- merge positions are a permutation of 0..M+k-1 (the standard two-pointer
  merge argument: pool elements count strictly-smaller emissions, emissions
  count less-or-equal pool elements, so ties route pool-first and no two
  elements share a position);
- an element pushed past M overflows exactly when the sort-based form would
  have left a non-EMPTY in the truncated tail — same signal, same
  "never silently drop" contract.

EMPTY emissions never place (their rank is past every slot, including the
EMPTY pool tail), and EMPTY pool slots pushed off the end are not overflow.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

EMPTY = np.uint32(0xFFFFFFFF)


def drop_slot(pool, d):
    """Remove the element at index `d` from a sorted pool, shifting the tail
    left and refilling with EMPTY.

    pool: u32[..., M] sorted; d: int[...] (same leading shape) slot index.
    """
    M = pool.shape[-1]
    j = jnp.arange(M, dtype=jnp.int32)
    j = j.reshape((1,) * (pool.ndim - 1) + (M,))
    src = j + (j >= d[..., None]).astype(jnp.int32)
    out = jnp.take_along_axis(pool, jnp.minimum(src, M - 1), axis=-1)
    return jnp.where(src >= M, EMPTY, out)


def merge_insert_sorted(pool, ems):
    """Insert up to k emissions into a sorted pool; -> (out[..., M], ovf).

    pool: u32[..., M] sorted with EMPTY tail. ems: u32[..., k] in any order
    (k small and static; EMPTY = absent). Returns the merged sorted pool and
    an overflow mask — True where a real (non-EMPTY) element of the merged
    multiset fell past slot M-1.
    """
    M = pool.shape[-1]
    k = ems.shape[-1]
    ems = jnp.sort(ems, axis=-1)  # k tiny: XLA expands to a compare network
    j = jnp.arange(M, dtype=jnp.int32)
    j = j.reshape((1,) * (pool.ndim - 1) + (M,))

    # Emission ranks: pool elements <= e go first, equal emissions keep
    # their (sorted) order.
    pos_e = (pool[..., :, None] <= ems[..., None, :]).sum(
        axis=-2, dtype=jnp.int32
    ) + jnp.arange(k, dtype=jnp.int32)
    # Pool shift: strictly smaller emissions go first.
    cnt_lt = (ems[..., None, :] < pool[..., :, None]).sum(
        axis=-1, dtype=jnp.int32
    )

    placed = pos_e[..., None, :] == j[..., :, None]  # [..., M, k]
    is_em = placed.any(axis=-1)
    em_at = jnp.where(placed, ems[..., None, :], 0).sum(
        axis=-1, dtype=jnp.uint32
    )
    shift = (pos_e[..., None, :] <= j[..., :, None]).sum(
        axis=-1, dtype=jnp.int32
    )
    q_idx = jnp.clip(j - shift, 0, M - 1)
    q_shift = jnp.take_along_axis(pool, q_idx, axis=-1)
    out = jnp.where(is_em, em_at, q_shift)

    ovf = ((pos_e >= M) & (ems != EMPTY)).any(axis=-1) | (
        ((j + cnt_lt >= M) & (pool != EMPTY)).any(axis=-1)
    )
    return out, ovf
