"""Sorted-pool surgery without `jnp.sort`.

PRODUCTION: `rank_sort` / `rank_sort_pool` — the unrolled rank-by-counting
rebuild the network multiset kernels use (a minor-axis jnp.sort pays
cross-lane shuffles over the 128-padded lane dim on TPU; the unrolled form
measured paxos-3 568k -> 616k states/s and abd-ordered +18% on v5e).

RECORD: `drop_slot` / `merge_insert_sorted` — a rank-based MERGE that was
measured ~2x SLOWER end-to-end than the sort it replaced (paxos-3 443k ->
228k; gather-heavy), reverted, and kept parity-tested for the record and
for wider-pool models where the trade may flip.

The canonical network-pool state is a SORTED vector of u32 envelope ids with
EMPTY (0xFFFFFFFF) sentinels packed at the tail. Every Deliver successor
drops one slot and inserts <= k emissions, then restores the invariant.
Parity tests (tests/test_poolops.py) pin every form here against a
plain-sort reference. Mechanics of the record-only merge:

- the drop is a shift-left past the dropped slot (`drop_slot`);
- each (sorted) emission's output position is its rank in the pool plus its
  emission index; each pool element shifts right by the number of strictly
  smaller emissions (`merge_insert_sorted`);
- merge positions are a permutation of 0..M+k-1 (the standard two-pointer
  merge argument: pool elements count strictly-smaller emissions, emissions
  count less-or-equal pool elements, so ties route pool-first and no two
  elements share a position);
- an element pushed past M overflows exactly when the sort-based form would
  have left a non-EMPTY in the truncated tail — same signal, same
  "never silently drop" contract.

EMPTY emissions never place (their rank is past every slot, including the
EMPTY pool tail), and EMPTY pool slots pushed off the end are not overflow.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

EMPTY = np.uint32(0xFFFFFFFF)


def rank_sort(parts, keep):
    """Sort a small multiset given as K separate element arrays; return the
    ascending `keep`-prefix stacked on a new minor axis plus an overflow
    mask (a real element ranked past `keep`).

    parts: list of K uint32[...] arrays (identical shapes) — the elements
    of one multiset per row. The sort is an unrolled rank-by-counting
    network: one compare per unordered pair assigns each element its exact
    output position (ties broken by part index, so it is stable), then a
    one-hot select builds each kept slot. Every op is ELEMENTWISE over the
    part arrays — unlike `jnp.sort` along a minor axis, which on TPU pays
    cross-lane shuffles over the 128-padded lane dim (measured 3.6 ms for
    a [4096,14,17] pool sort vs ~0.3 ms for this form — the single
    largest slice of the paxos-3 expand fusion). The graph grows O(K^2 +
    K*keep) HLO ops — fine for the <= 30-wide pools the models use (it
    did raise paxos5s4c's cold compile 52 s -> 231 s), unsuitable for
    hundreds."""
    K = len(parts)
    if not 0 < keep <= K:
        # keep > K would silently pad with 0x0 (a phantom id-0 envelope,
        # NOT the EMPTY sentinel); keep == 0 has no meaning here.
        raise ValueError(f"keep must be in 1..{K}, got {keep}")
    i32 = jnp.int32
    ranks = [jnp.zeros(parts[0].shape, i32) for _ in range(K)]
    for i in range(K):
        for j in range(i + 1, K):
            le = parts[i] <= parts[j]  # ties: earlier part sorts first
            ranks[j] = ranks[j] + le.astype(i32)
            ranks[i] = ranks[i] + (~le).astype(i32)
    zero_u = jnp.uint32(0)
    outs = []
    for j in range(keep):
        acc = jnp.zeros(parts[0].shape, jnp.uint32)
        for i in range(K):
            acc = acc | jnp.where(ranks[i] == j, parts[i], zero_u)
        outs.append(acc)
    ovf = jnp.zeros(parts[0].shape, bool)
    for i in range(K):
        ovf = ovf | ((ranks[i] >= keep) & (parts[i] != EMPTY))
    return jnp.stack(outs, axis=-1), ovf


def rank_sort_pool(pool, emits, n_slots):
    """Insert per-slot emissions into an (unchanged) sorted pool: the
    timeout/random lowering form. pool: u32[B, P]; emits: u32[B, n, k];
    -> (u32[B, n, P], overflow[B, n])."""
    B, P = pool.shape
    parts = [
        jnp.broadcast_to(pool[:, i : i + 1], (B, n_slots)) for i in range(P)
    ] + [emits[:, :, j] for j in range(emits.shape[2])]
    return rank_sort(parts, P)


def drop_slot(pool, d):
    """Remove the element at index `d` from a sorted pool, shifting the tail
    left and refilling with EMPTY.

    pool: u32[..., M] sorted; d: int[...] (same leading shape) slot index.
    """
    M = pool.shape[-1]
    j = jnp.arange(M, dtype=jnp.int32)
    j = j.reshape((1,) * (pool.ndim - 1) + (M,))
    src = j + (j >= d[..., None]).astype(jnp.int32)
    out = jnp.take_along_axis(pool, jnp.minimum(src, M - 1), axis=-1)
    return jnp.where(src >= M, EMPTY, out)


def merge_insert_sorted(pool, ems):
    """Insert up to k emissions into a sorted pool; -> (out[..., M], ovf).

    pool: u32[..., M] sorted with EMPTY tail. ems: u32[..., k] in any order
    (k small and static; EMPTY = absent). Returns the merged sorted pool and
    an overflow mask — True where a real (non-EMPTY) element of the merged
    multiset fell past slot M-1.
    """
    M = pool.shape[-1]
    k = ems.shape[-1]
    ems = jnp.sort(ems, axis=-1)  # k tiny: XLA expands to a compare network
    j = jnp.arange(M, dtype=jnp.int32)
    j = j.reshape((1,) * (pool.ndim - 1) + (M,))

    # Emission ranks: pool elements <= e go first, equal emissions keep
    # their (sorted) order.
    pos_e = (pool[..., :, None] <= ems[..., None, :]).sum(
        axis=-2, dtype=jnp.int32
    ) + jnp.arange(k, dtype=jnp.int32)
    # Pool shift: strictly smaller emissions go first.
    cnt_lt = (ems[..., None, :] < pool[..., :, None]).sum(
        axis=-1, dtype=jnp.int32
    )

    placed = pos_e[..., None, :] == j[..., :, None]  # [..., M, k]
    is_em = placed.any(axis=-1)
    em_at = jnp.where(placed, ems[..., None, :], 0).sum(
        axis=-1, dtype=jnp.uint32
    )
    shift = (pos_e[..., None, :] <= j[..., :, None]).sum(
        axis=-1, dtype=jnp.int32
    )
    q_idx = jnp.clip(j - shift, 0, M - 1)
    q_shift = jnp.take_along_axis(pool, q_idx, axis=-1)
    out = jnp.where(is_em, em_at, q_shift)

    ovf = ((pos_e >= M) & (ems != EMPTY)).any(axis=-1) | (
        ((j + cnt_lt >= M) & (pool != EMPTY)).any(axis=-1)
    )
    return out, ovf
