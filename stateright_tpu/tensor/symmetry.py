"""Device-side symmetry reduction: canonicalization kernels.

The reference reduces symmetric state spaces by mapping each state to a
canonical orbit representative before dedup (Symmetric-Spin,
ref: src/checker/representative.rs; the plan derivation is a double argsort,
ref: src/checker/rewrite_plan.rs:81-107). That double-argsort shape is
*naturally* TPU-friendly: a `TensorModel` opts in by defining
`representative(states) -> states`, built from the helpers here — one stable
argsort over per-entity keys plus gathers/bit-permutes — and the engines then
fingerprint the canonical form while continuing the search with the original
state (preserving the reference DFS's representative-insert/original-continue
semantics, ref: src/checker/dfs.rs:309-334).

Count parity: a stable sort keyed on the entity value places equal-key
entities in original index order, so the induced state partition — and hence
the unique-state count — is independent of the key order chosen, matching the
host `RewritePlan.from_values_to_sort` counts (e.g. 2PC-5: 8,832 → 665).
"""

from __future__ import annotations

import jax.numpy as jnp


def stable_argsort(keys: jnp.ndarray) -> jnp.ndarray:
    """Per-row stable argsort: `keys[B, n] -> perm[B, n]` where `perm[b, j]`
    is the original index of the entity placed at slot j."""
    return jnp.argsort(keys, axis=1, stable=True)


def gather_entities(lanes: jnp.ndarray, perm: jnp.ndarray) -> jnp.ndarray:
    """Apply a permutation to per-entity lanes: `lanes[B, n][b, perm[b, j]]`."""
    return jnp.take_along_axis(lanes, perm, axis=1)


def permute_mask_bits(mask: jnp.ndarray, perm: jnp.ndarray) -> jnp.ndarray:
    """Permute the low `n` bits of a per-row bitmask: new bit j = old bit
    `perm[b, j]`. Bits at positions >= n are dropped (handle separately)."""
    n = perm.shape[1]
    bits = (mask[:, None] >> perm.astype(mask.dtype)) & mask.dtype.type(1)
    weights = (mask.dtype.type(1) << jnp.arange(n, dtype=mask.dtype))[None, :]
    return (bits * weights).sum(axis=1, dtype=mask.dtype)
