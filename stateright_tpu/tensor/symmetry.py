"""Device-side symmetry reduction: canonicalization kernels.

The reference reduces symmetric state spaces by mapping each state to a
canonical orbit representative before dedup (Symmetric-Spin,
ref: src/checker/representative.rs; the plan derivation is a double argsort,
ref: src/checker/rewrite_plan.rs:81-107). That double-argsort shape is
*naturally* TPU-friendly: a `TensorModel` opts in by defining
`representative(states) -> states`, built from the helpers here — one stable
argsort over per-entity keys plus gathers/bit-permutes — and the engines then
fingerprint the canonical form while continuing the search with the original
state (preserving the reference DFS's representative-insert/original-continue
semantics, ref: src/checker/dfs.rs:309-334).

COUNT CONTRACT — device counts intentionally differ from reference
`check-sym` goldens. The reference sorts entities by their primary value
only (`RewritePlan.from_values_to_sort`, ref: src/checker/rewrite_plan.rs:
81-107), which breaks ties between equal-valued entities by original index;
states whose satellite bits (e.g. 2PC's per-RM prepared/message flags)
differ only under a tie permutation then land on different representatives,
so the reduced count depends on traversal order (2PC-5: 8,832 → 665 under
the reference's DFS). The canonicalizations built from these helpers key
the sort on the FULL per-entity tuple (value + satellite bits), which is a
true orbit invariant: every member of a permutation orbit maps to the same
representative regardless of which engine or traversal order found it
(2PC-5: 8,832 → 314; cross-validated against a host DFS using the same
canonicalization in tests/test_tensor_symmetry.py). Both reductions are
sound for property checking — they only affect which orbit member is
counted/stored — but the counts are NOT comparable:

- assert device-engine symmetry counts against full-key goldens (314);
- assert host `spawn_dfs` + `symmetry_fn` counts against the reference's
  value-sort goldens (665), which that path reproduces exactly.

Why the device engines do not (and should not) target the 665 golden:
value-sort reduction is TRAVERSAL-ORDER-DEPENDENT. Measured on 2PC-5
(tests/test_tensor_symmetry.py::test_value_sort_reduction_is_traversal_order_dependent):

    reduction     BFS order   DFS order
    value-sort        508         665      <- order-dependent
    full-key          314         314      <- orbit invariant

The device engines are parallel level-synchronous BFS with scatter-resolved
dedup: which orbit member is inserted first depends on batch layout, so a
value-sort port could never pin a meaningful golden there. The full-key
canonicalization is the only choice whose count is a property of the state
space rather than of the schedule — every engine (host DFS, host BFS, device
frontier/resident/sharded at any batch size) lands on the same number.
Property verdicts are identical under both reductions and under no reduction
(verdict-parity tests in tests/test_tensor_symmetry.py).
"""

from __future__ import annotations

import jax.numpy as jnp


def stable_argsort(keys: jnp.ndarray) -> jnp.ndarray:
    """Per-row stable argsort: `keys[B, n] -> perm[B, n]` where `perm[b, j]`
    is the original index of the entity placed at slot j."""
    return jnp.argsort(keys, axis=1, stable=True)


def gather_entities(lanes: jnp.ndarray, perm: jnp.ndarray) -> jnp.ndarray:
    """Apply a permutation to per-entity lanes: `lanes[B, n][b, perm[b, j]]`."""
    return jnp.take_along_axis(lanes, perm, axis=1)


def permute_mask_bits(mask: jnp.ndarray, perm: jnp.ndarray) -> jnp.ndarray:
    """Permute the low `n` bits of a per-row bitmask: new bit j = old bit
    `perm[b, j]`. Bits at positions >= n are dropped (handle separately)."""
    n = perm.shape[1]
    bits = (mask[:, None] >> perm.astype(mask.dtype)) & mask.dtype.type(1)
    weights = (mask.dtype.type(1) << jnp.arange(n, dtype=mask.dtype))[None, :]
    return (bits * weights).sum(axis=1, dtype=mask.dtype)


def device_dfs_unique_count(model, max_pops: int = 1 << 20) -> int:
    """Sequential DFS driven by the DEVICE kernels (expand + canonicalize +
    fingerprint all run on the jax backend; only the stack lives on host).

    This exists for one purpose: value-sort canonicalization
    (`TensorTwoPhaseSys(symmetry="value")`) is traversal-order-dependent, so
    its published golden (2PC-5 = 665, ref: examples/2pc.rs:163-168) is only
    reproducible under the reference DFS's order — push successors in action
    order, pop last-first, insert the representative's fingerprint, continue
    from the ORIGINAL state (ref: src/checker/dfs.rs:309-334). The batched
    engines are level-synchronous and cannot pin that golden (symmetry
    module docstring); this driver runs the same device kernels one state at
    a time in exactly that order, closing the count-parity gap as an opt-in.
    """
    import numpy as np

    from .fingerprint import pack_fp
    from .frontier import state_fingerprint

    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(row):
        succs, valid = model.expand(row[None])
        lo, hi = state_fingerprint(model, succs[0])
        return succs[0], valid[0], lo, hi

    init = np.asarray(model.init_states(), dtype=np.uint32)
    ilo, ihi = (
        np.asarray(x)
        for x in state_fingerprint(model, jnp.asarray(init))
    )
    init_fps = pack_fp(ilo, ihi)
    seen = set()
    stack = []
    for row, fp in zip(init, init_fps):
        if int(fp) not in seen:
            seen.add(int(fp))
            stack.append(row)
    pops = 0
    while stack:
        if pops >= max_pops:
            raise RuntimeError(f"exceeded max_pops={max_pops}")
        pops += 1
        row = stack.pop()
        succs, valid, lo, hi = step(jnp.asarray(row))
        succs, valid = np.asarray(succs), np.asarray(valid)
        fps = pack_fp(np.asarray(lo), np.asarray(hi))
        for a in range(valid.shape[0]):
            if valid[a] and int(fps[a]) not in seen:
                seen.add(int(fps[a]))
                stack.append(succs[a])
    return len(seen)
