"""Generic ActorModel -> TensorModel lowering: ANY bounded actor system gets
device checking, without a hand-written tensor encoding.

The reference's core capability is that any actor system lowers automatically
into the generic model interface (`ActorModel`, ref: src/actor/model.rs:24-40)
and from there into any checker. Round 1 only accelerated four hand-encoded
models; this module closes that gap the TPU-first way: the user's Python actor
code cannot run inside an XLA kernel, so the lowering LIFTS IT TO DATA —

1. A host-side *local closure* pass enumerates, once, every reachable
   (local state, incoming envelope) reaction per actor and every
   (local state, timer) reaction, by running the actual `Actor.on_msg` /
   `on_timeout` code on a worklist. Local state spaces are usually tiny even
   when the global product space is huge — that asymmetry is what makes the
   lowering profitable.
2. Reactions compile to dense uint32 lookup tables (new-state id, emitted
   envelope ids, timer set/clear masks, validity, history event).
3. The device `expand` kernel is then pure gathers + lane arithmetic: deliver
   the envelope in each action slot, look up the reaction, apply it
   branchlessly. Histories (e.g. consistency testers) are lowered the same
   way: the history object vocabulary is closed over *history events*
   (delivered envelope + ordered emissions), and host predicates over
   histories — `serialized_history() is not None` included — are evaluated
   once per history id at build time and become boolean gather tables.

Host-semantics parity (all cited behaviors preserved exactly):
- one Deliver action per distinct deliverable envelope; Drop actions when
  lossy (ref: src/actor/model.rs:258-282);
- no-op elision: delivery that leaves the actor unchanged and emits nothing is
  not a transition (ref: src/actor/model.rs:345-347);
- timeout semantics incl. the fired-timer-consumed rule and the
  unchanged-state + re-set-same-timer elision (ref: src/actor.rs:277-287,
  src/actor/model.rs:386-392);
- unordered duplicating networks keep the envelope set + `last_msg` lane
  (redelivery changes the fingerprint, ref: src/actor/network.rs:52,224-228);
  unordered non-duplicating networks are a sorted bounded multiset pool;
  ordered networks are per-directed-flow left-aligned FIFO rings where only
  flow heads are deliverable and a no-op delivery still pops the head
  (ref: src/actor/network.rs:243-265, src/actor/model.rs:345-347);
- state identity covers (actor states, history, timers, network), matching
  `ActorModelState`'s manual Hash (ref: src/actor/model_state.rs:134-145).

Three closure strategies (`closure=`), trading host work against the size of
the abstraction:

- "independent" (default): closes each actor against the whole envelope
  vocabulary — cheapest, but the per-actor cross product explodes when local
  states accumulate message contents (Paxos quorum sets overflow a 2^16 cap
  at 2 clients), and it REQUIRES `local_boundary` whenever handlers can grow
  state unboundedly.
- "joint": worklist over actor-sid VECTORS with a sticky envelope vocabulary —
  keeps inter-actor correlations, but still needs `local_boundary` for models
  bounded only by a global `within_boundary` (a sid vector cannot evaluate a
  global-state predicate), and the sticky network is still too coarse for
  Paxos-scale entanglement.
- "exact": one host BFS of the REAL global model records precisely the
  reaction pairs + history transitions that occur. Self-bounding, no
  `local_boundary` needed, and it is what lowers the reference's headline
  configs: paxos-2 (16,668 unique) closes in ~3 s, paxos-3 (1,194,428
  unique) in ~6.5 min, both at exact golden parity (probe:
  scripts/probe_lowering_paxos2.py). The closure costs one host traversal of
  the global space — worth it because the resulting tables are tiny (paxos-3:
  675/723/777 local states per server, 240 envelopes, 7 histories) and every
  subsequent device run (re-checks, symmetry variants, sharded scale-out,
  simulation walks) reuses them.
- "seed" + `refine_check`: INCREMENTAL, device-search-driven closure. Start
  from a tiny best-effort joint seed; each search surfaces exactly the
  uncovered (state, envelope) pairs — and, for histories, the uncovered
  (history, event) transitions — as poison PAYLOAD rows; `extend()` runs the
  real handlers for just those; repeat until poison-free. Host work scales
  with the number of distinct reaction pairs (paxos-2: ~2.3k local states ×
  touched envelopes), NOT with the global edge count like "exact" — the
  device does the state-space heavy lifting, the host only compiles the
  reaction vocabulary the search proves it needs.

Soundness guards: every closure is bounded (`max_local_states`,
`max_histories`, `max_envelopes`, `max_joint_states`); if the device search
ever reaches a (state, envelope) pair the closure did not cover (possible
only when `local_boundary` under-approximates the model's real boundary), the
successor becomes the reserved POISON row and the auto-added "lowering
coverage" property reports it as a counterexample instead of silently
mis-exploring.

Random choices lower via per-actor vocabularies (pending-choice maps, choice
values, and command deltas become gather tables; SelectRandom action slots pop
a choice and run the real `on_random` reaction); crash injection lowers to a
crash-bitmask lane with per-actor Crash actions that clear timers and pending
choices (ref: src/actor/model.rs:291-313, 400-426). Both are auxiliary state
the reference EXCLUDES from identity (manual Hash,
ref: src/actor/model_state.rs:134-145) — the lowering mirrors that through the
`representative` canonicalization hook, so engines fingerprint states with
those lanes stripped while continuing the search with the originals.
"""

from __future__ import annotations

import itertools
import os

from collections import deque
from functools import partial
from typing import Callable, Optional

import numpy as np

import jax

import jax.numpy as jnp

from ..actor import CancelTimer, ChooseRandom, Id, Out, Send, SetTimer
from ..actor.model import ActorModel
from ..actor.network import (
    Envelope,
    ORDERED,
    UNORDERED_NONDUPLICATING,
)
from ..core.discovery import HasDiscoveries
from ..core.model import Expectation
from .model import TensorModel, TensorProperty
from .poolops import rank_sort, rank_sort_pool

EMPTY = np.uint32(0xFFFFFFFF)
_UNEXPLORED = 0  # D_state value marking an uncovered (eid, sid) combo
_ELIDED = 1  # no-op elision (not a transition)
_VALID0 = 2  # new_sid = D_state - _VALID0


class LoweringError(Exception):
    pass


class LoweredActorModel(TensorModel):
    """TensorModel auto-derived from an ActorModel. Build via
    `lower_actor_model(...)`; then check with any device engine
    (FrontierSearch / ResidentSearch / ShardedSearch / spawn_tpu)."""

    def __init__(
        self,
        model: ActorModel,
        *,
        pool_size: Optional[int] = None,
        flow_depth: Optional[int] = None,
        max_emit: int = 4,
        local_boundary: Optional[Callable] = None,
        max_local_states: int = 1 << 12,
        max_envelopes: int = 1 << 12,
        max_histories: int = 1 << 16,
        properties: Optional[Callable] = None,
        boundary: Optional[Callable] = None,
        closure: str = "independent",
        max_joint_states: int = 1 << 20,
        closure_max_depth: Optional[int] = None,
    ):
        self.model = model
        self.kind = model.init_network.kind
        if model.max_crashes and len(model.actors) > 32:
            raise LoweringError("crash lowering supports at most 32 actors")
        self.max_crashes = model.max_crashes
        # None = default capacity (16/8), which exact mode auto-sizes to
        # the PROVEN maximum (see the exact-closure walk); an explicit
        # value is always respected — it is the documented remedy knob for
        # capacity overflows.
        self._pool_size_arg = pool_size
        self._flow_depth_arg = flow_depth
        self.pool_size = 16 if pool_size is None else pool_size
        self.flow_depth = 8 if flow_depth is None else flow_depth
        self.max_emit = max_emit
        self.local_boundary = local_boundary or (lambda i, s: True)
        self.max_local_states = max_local_states
        self.max_envelopes = max_envelopes
        self.max_histories = max_histories
        if closure not in ("independent", "joint", "exact", "seed"):
            raise ValueError(
                "closure must be 'independent', 'joint', 'exact', or 'seed'"
            )
        # "independent" closes each actor against the whole envelope
        # vocabulary — cheap, but the cross product explodes for actors whose
        # local state accumulates message contents (e.g. Paxos quorum sets).
        # "joint" explores the actor-sid VECTOR with a sticky (monotone)
        # envelope vocabulary — a tighter over-approximation of reachability
        # that only closes (state, envelope) pairs some relaxed execution
        # produces, the same abstraction _close_histories uses. "exact"
        # enumerates the REAL global model once on the host and records
        # exactly the reaction pairs + history transitions that occur — the
        # closure cost then scales with the global space (host-BFS speed),
        # which is the right trade when local states accumulate message
        # contents too entangled for either abstraction (Paxos quorum sets:
        # 2-client Paxos overflows a 2^16 per-actor cap under "independent"
        # and a 2^20 vector cap under "joint"). All modes are sound: the
        # POISON coverage guard flags any under-coverage at search time
        # instead of mis-exploring.
        # "seed" = best-effort joint closure: stop silently at the vector cap
        # instead of raising; the gaps become poison payloads that
        # `refine_check` feeds back through `extend()` (incremental,
        # device-search-driven closure — no host traversal of the global
        # space).
        self.joint = closure in ("joint", "seed")
        self.best_effort = closure == "seed"
        self.exact = closure == "exact"
        self.max_joint_states = max_joint_states
        if self.best_effort and (
            max_local_states > 1 << 16
            or max_envelopes > 1 << 24
            or max_histories > 1 << 24
        ):
            # Poison payloads pack sid into 16 bits and eid/hid into 24;
            # beyond that a surfaced gap would decode as the WRONG pair and
            # refinement would loop on it forever.
            raise ValueError(
                "closure='seed' (refinement) requires max_local_states <= "
                "2^16, max_envelopes <= 2^24, and max_histories <= 2^24 — "
                "the poison-payload field widths"
            )
        # Exact-mode depth bound for DEEP-BFS workloads whose full space is
        # not enumerable: the closure covers exactly the states within
        # `closure_max_depth` (init = depth 1, expand while depth < bound),
        # matching the engines' target_max_depth semantics — device runs MUST
        # pass target_max_depth <= closure_max_depth. `closure_stats` records
        # the host traversal's (generated, unique, max_depth) as the parity
        # oracle for that bounded space.
        if closure_max_depth is not None and not self.exact:
            raise ValueError("closure_max_depth requires closure='exact'")
        self.closure_max_depth = closure_max_depth
        self.closure_stats: Optional[dict] = None
        self._properties_fn = properties
        self._boundary_fn = boundary

        self.n = len(model.actors)
        self.track_history = model.init_history is not None
        # Capacity classes (refinement mode only): vocabulary-sized array
        # dims are rounded UP to monotonically-growing power-of-two caps so
        # successive `extend()` rounds keep identical table SHAPES — the
        # engines can then take the tables as kernel OPERANDS and reuse one
        # compiled kernel across rounds instead of re-jitting per round
        # (VERDICT r3 next #8; the recompile was the dominant per-round cost
        # on both CPU and the TPU tunnel). Padded entries read as
        # unexplored/undeliverable, which the POISON guard already handles.
        self._caps: dict = {}
        self._dyn = None  # engine-injected operand pytree (see _tbl)
        self._close()
        self._finalize()

    def _dyn_cap(self, key: str, n: int, floor: int = 16) -> int:
        """Monotone power-of-two capacity class for a vocabulary dim
        (identity outside refinement mode, where exact sizes keep the eager
        closure paths byte-identical to round 3)."""
        if not self.best_effort or n == 0:
            return n
        c = max(self._caps.get(key, floor), floor)
        while c < n:
            c *= 2
        self._caps[key] = c
        return c

    def _reg(self, name: str, arr) -> str:
        """Register a round-varying baked array under a stable name so the
        engines can pass it as a kernel operand (see `_tbl`)."""
        self._dyn_host[name] = arr
        return name

    def _tbl(self, name: str):
        """Read a baked table: the engine-injected operand when tracing
        under an operand-aware engine, else the host array as a constant."""
        d = self._dyn
        if d is not None and name in d:
            return d[name]
        return jnp.asarray(self._dyn_host[name])

    def dyn_tables(self) -> dict:
        """The round-varying baked tables as a {name: array} pytree. An
        engine that passes this as a kernel operand (and installs it via
        `self._dyn` around tracing) can swap table CONTENTS between runs
        with no retrace/recompile as long as the shapes (capacity classes)
        are unchanged — `refine_check` relies on this."""
        return {k: jnp.asarray(v) for k, v in self._dyn_host.items()}

    def _finalize(self) -> None:
        """Layout + tables + properties from the current closure contents;
        rerun by `extend()` after incremental closure growth."""
        self._dyn_host: dict = {}
        self._layout()
        self._bake_tables()
        for i, a in enumerate(self._D):
            self._reg(f"D{i}", a)
        for i, a in enumerate(self._T):
            self._reg(f"T{i}", a)
        if self.has_randoms:
            for i, a in enumerate(self._R):
                self._reg(f"R{i}", a)
        self._reg("E_dst", self._E_dst)
        if self.kind == ORDERED:
            self._reg("E_flow", self._E_flow)
        self._reg("hd", self._hd)
        self._props = self._build_properties()
        if self.has_randoms or self.max_crashes:
            # Pending random choices and crash flags are auxiliary state the
            # reference EXCLUDES from identity (manual Hash,
            # ref: src/actor/model_state.rs:134-145): engines fingerprint the
            # canonical form below while continuing with the original state.
            self.representative = self._strip_aux

    def extend(self, gaps) -> None:
        """Incrementally close the given coverage gaps — (kind, idx1, idx2,
        sid) tuples as decoded by `poison_payload` — by running the REAL
        handlers for exactly those pairs, then re-derive histories, layout,
        and tables. New local states / envelopes a reaction creates stay
        unexplored until a later search surfaces them as gaps: coverage is
        driven by actual device-search reachability, one frontier layer per
        round (see `refine_check`)."""
        hist_gaps = []
        for kind, i1, i2, sid in gaps:
            if kind == 0:
                self._react_deliver(i1, sid)
            elif kind == 1:
                self._react_timeout(i1, i2, sid)
            elif kind == 2:
                self._react_random(i1, i2, sid)
            elif kind == 4:
                hist_gaps.append((i1, i2))
            else:
                raise LoweringError(f"cannot extend gap kind {kind}")
        self._close_randoms()
        # Lazy mode: _close_histories keeps the vocabulary, assigns hevents
        # to the new entries, and re-bakes; then apply the surfaced
        # (history, event) transitions exactly.
        self._close_histories()
        if hist_gaps:
            _hevent_id, apply_event, hid_of = self._hist_fns
            for hid, ev in hist_gaps:
                self._htrans[(hid, ev)] = hid_of(
                    apply_event(self.histories[hid], self.hevents[ev])
                )
            self._bake_hd()
        self._finalize()

    def _strip_aux(self, states):
        if self.has_randoms:
            states = states.at[
                :, self.rand_off : self.rand_off + self.n
            ].set(0)
        if self.max_crashes:
            states = states.at[:, self.crash_off].set(0)
        return states

    # -- host closure ----------------------------------------------------------

    def _close(self) -> None:
        model = self.model
        self.envs: list[Envelope] = []  # eid -> envelope
        self.env_ids: dict = {}
        self.sids: list[dict] = [dict() for _ in range(self.n)]  # state->sid
        self.states: list[list] = [[] for _ in range(self.n)]  # sid->state
        self.timer_ids: list[dict] = [dict() for _ in range(self.n)]
        self.timers: list[list] = [[] for _ in range(self.n)]

        # Random-choice vocabularies (ref: src/actor/model.rs:302-313,
        # 411-426). A randoms MAP (key -> choices) is a canonical tuple of
        # items sorted by key repr; a DELTA is the ordered ChooseRandom ops a
        # transition issued; a CHOICE is one selectable value.
        self.rmaps: list[list] = [[()] for _ in range(self.n)]  # rid -> map
        self.rmap_ids: list[dict] = [{(): 0} for _ in range(self.n)]
        self.rdeltas: list[list] = [[()] for _ in range(self.n)]  # did -> ops
        self.rdelta_ids: list[dict] = [{(): 0} for _ in range(self.n)]
        self.rchoices: list[list] = [[] for _ in range(self.n)]  # cid -> value
        self.rchoice_ids: list[dict] = [dict() for _ in range(self.n)]

        pending: deque = deque()  # ("d", eid, sid) | ("t", actor, tid, sid)
        #                         | ("r", actor, cid, sid)
        done: set = set()
        # sids whose local_boundary failed: encoded but never expanded.
        frozen: set = set()  # (actor, sid)

        def env_id(env: Envelope) -> int:
            key = (int(env.src), int(env.dst), env.msg)
            eid = self.env_ids.get(key)
            if eid is None:
                eid = len(self.envs)
                if eid >= self.max_envelopes:
                    raise LoweringError(
                        "envelope vocabulary exceeded max_envelopes="
                        f"{self.max_envelopes}; the message space may be "
                        "unbounded (add a local_boundary or raise the cap)"
                    )
                self.env_ids[key] = eid
                self.envs.append(Envelope(Id(key[0]), Id(key[1]), env.msg))
                dst = key[1]
                if not (self.joint or self.exact) and dst < self.n:
                    for sid in range(len(self.states[dst])):
                        if (dst, sid) not in frozen:
                            pending.append(("d", eid, sid))
            return eid

        def sid_of(actor: int, state) -> int:
            sid = self.sids[actor].get(state)
            if sid is None:
                sid = len(self.states[actor])
                if sid >= self.max_local_states:
                    raise LoweringError(
                        f"actor {actor} exceeded max_local_states="
                        f"{self.max_local_states}; its local state space may "
                        "be unbounded (add a local_boundary or raise the cap)"
                    )
                self.sids[actor][state] = sid
                self.states[actor].append(state)
                if not self.local_boundary(actor, state):
                    frozen.add((actor, sid))
                elif not (self.joint or self.exact):
                    for eid, env in enumerate(self.envs):
                        if int(env.dst) == actor:
                            pending.append(("d", eid, sid))
                    for tid in range(len(self.timers[actor])):
                        pending.append(("t", actor, tid, sid))
                    for cid in range(len(self.rchoices[actor])):
                        pending.append(("r", actor, cid, sid))
            return sid

        def timer_id(actor: int, timer) -> int:
            tid = self.timer_ids[actor].get(timer)
            if tid is None:
                tid = len(self.timers[actor])
                if tid >= 32:
                    raise LoweringError(f"actor {actor} has > 32 timer kinds")
                self.timer_ids[actor][timer] = tid
                self.timers[actor].append(timer)
                if not (self.joint or self.exact):
                    for sid in range(len(self.states[actor])):
                        if (actor, sid) not in frozen:
                            pending.append(("t", actor, tid, sid))
            return tid

        def choice_id(actor: int, value) -> int:
            cid = self.rchoice_ids[actor].get(value)
            if cid is None:
                cid = len(self.rchoices[actor])
                self.rchoice_ids[actor][value] = cid
                self.rchoices[actor].append(value)
                if not (self.joint or self.exact):
                    for sid in range(len(self.states[actor])):
                        if (actor, sid) not in frozen:
                            pending.append(("r", actor, cid, sid))
            return cid

        def delta_id(actor: int, rops: tuple) -> int:
            did = self.rdelta_ids[actor].get(rops)
            if did is None:
                did = len(self.rdeltas[actor])
                self.rdelta_ids[actor][rops] = did
                self.rdeltas[actor].append(rops)
            return did

        def run_commands(actor: int, out: Out):
            """-> (emit eids in order, tclr mask, tset mask, randoms delta)"""
            emits: list[int] = []
            tclr = 0
            tset = 0
            rops: list = []
            for c in out:
                if isinstance(c, Send):
                    if len(emits) >= self.max_emit:
                        raise LoweringError(
                            f"a transition of actor {actor} emits more than "
                            f"max_emit={self.max_emit} messages"
                        )
                    emits.append(env_id(Envelope(Id(actor), c.dst, c.msg)))
                elif isinstance(c, SetTimer):
                    bit = 1 << timer_id(actor, c.timer)
                    tset |= bit
                    tclr &= ~bit
                elif isinstance(c, CancelTimer):
                    bit = 1 << timer_id(actor, c.timer)
                    tclr |= bit
                    tset &= ~bit
                elif isinstance(c, ChooseRandom):
                    for v in c.choices:
                        choice_id(actor, v)
                    rops.append((c.key, tuple(c.choices)))
                else:
                    raise LoweringError(f"unknown command {c!r}")
            return emits, tclr, tset, delta_id(actor, tuple(rops))

        # Seed: envelopes pre-loaded in the init network first (the
        # reference's seeded-network pattern), then on_start per actor
        # (matches ActorModel.init_states, ref: src/actor/model.rs:236-256).
        for env in model.init_network.iter_all():
            env_id(env)
        if model.init_network.last_msg is not None:
            env_id(model.init_network.last_msg)
        self._init_sids = []
        self._init_emits = []  # ordered emissions for history replay
        self._init_tset = [0] * self.n
        for index, actor in enumerate(model.actors):
            out = Out()
            state = actor.on_start(Id(index), out)
            emits, _tclr, tset, _did = run_commands(index, out)
            self._init_sids.append(sid_of(index, state))
            self._init_emits.extend(emits)
            self._init_tset[index] = tset

        # Reaction closure. The react_* functions run one real handler call,
        # memoize its compiled entry, and are shared by both closure modes.
        self.deliver: dict = {}  # (eid, sid) -> entry dict
        self.timeout: dict = {}  # (actor, tid, sid) -> entry dict
        self.random: dict = {}  # (actor, cid, sid) -> entry dict

        def react_random(actor: int, cid: int, sid: int):
            key = (actor, cid, sid)
            if key in self.random:
                return self.random[key]
            value = self.rchoices[actor][cid]
            state = self.states[actor][sid]
            out = Out()
            try:
                nxt = model.actors[actor].on_random(
                    Id(actor), state, value, out
                )
            except Exception as e:
                raise LoweringError(
                    f"actor {actor} on_random raised during closure: "
                    f"state={state!r}, random={value!r}"
                ) from e
            emits, tclr, tset, did = run_commands(actor, out)
            new_sid = sid if nxt is None else sid_of(actor, nxt)
            # No elision: selecting consumes the pending choice even when
            # the handler does nothing (ref: src/actor/model.rs:411-426).
            entry = dict(
                new_sid=new_sid, emits=emits, tclr=tclr, tset=tset,
                env=None, delta=did,
            )
            self.random[key] = entry
            return entry

        def react_deliver(eid: int, sid: int):
            key = (eid, sid)
            if key in self.deliver:
                return self.deliver[key]
            env = self.envs[eid]
            dst = int(env.dst)
            state = self.states[dst][sid]
            out = Out()
            try:
                nxt = model.actors[dst].on_msg(
                    Id(dst), state, env.src, env.msg, out
                )
            except Exception as e:
                raise LoweringError(
                    f"actor {dst} on_msg raised for a (state, message) "
                    "combination explored by the lowering closure (the "
                    "closure over-approximates reachability, so handlers "
                    f"must be total): state={state!r}, env={env!r}"
                ) from e
            emits, tclr, tset, did = run_commands(dst, out)
            # No-op elision — except on ordered networks, where delivery
            # still pops the flow head (ref: src/actor/model.rs:345-347).
            if nxt is None and not out.commands and self.kind != ORDERED:
                entry = None  # elided no-op
            else:
                new_sid = sid if nxt is None else sid_of(dst, nxt)
                entry = dict(
                    new_sid=new_sid, emits=emits, tclr=tclr, tset=tset,
                    env=eid, delta=did,
                )
            self.deliver[key] = entry
            return entry

        def react_timeout(actor: int, tid: int, sid: int):
            key = (actor, tid, sid)
            if key in self.timeout:
                return self.timeout[key]
            timer = self.timers[actor][tid]
            state = self.states[actor][sid]
            out = Out()
            try:
                nxt = model.actors[actor].on_timeout(
                    Id(actor), state, timer, out
                )
            except Exception as e:
                raise LoweringError(
                    f"actor {actor} on_timeout raised during closure: "
                    f"state={state!r}, timer={timer!r}"
                ) from e
            emits, tclr, tset, did = run_commands(actor, out)
            if (
                nxt is None
                and len(out.commands) == 1
                and isinstance(out.commands[0], SetTimer)
                and out.commands[0].timer == timer
            ):
                entry = None  # elided (unchanged state, same timer re-set)
            else:
                new_sid = sid if nxt is None else sid_of(actor, nxt)
                bit = 1 << tid
                if not (tset & bit):
                    tclr |= bit  # fired timer is consumed unless re-set
                entry = dict(
                    new_sid=new_sid, emits=emits, tclr=tclr, tset=tset,
                    env=None, delta=did,
                )
            self.timeout[key] = entry
            return entry

        def exact_bfs():
            """closure='exact': breadth-first enumerate the REAL global model
            on the host and record exactly the (envelope, local-state)
            reaction pairs and (history, event) transitions that occur. No
            over-approximation — the tables cover precisely global
            reachability, at the cost of one host traversal of the space."""
            from ..actor.model import (
                Deliver as ADeliver,
                SelectRandom as ASelect,
                Timeout as ATimeout,
            )

            track = self.track_history
            self.hevents = []
            self._hevent_ids = {}
            self.hids = {}
            self.histories = []

            def hevent_id(env_eid, emits) -> int:
                key = (env_eid, tuple(emits))
                hid = self._hevent_ids.get(key)
                if hid is None:
                    hid = len(self.hevents)
                    self._hevent_ids[key] = hid
                    self.hevents.append(key)
                return hid

            def hid_of(h) -> int:
                nid = self.hids.get(h)
                if nid is None:
                    nid = len(self.histories)
                    if nid >= self.max_histories:
                        raise LoweringError(
                            "history vocabulary exceeded max_histories="
                            f"{self.max_histories}; raise the cap"
                        )
                    self.hids[h] = nid
                    self.histories.append(h)
                return nid

            trans: dict = {}  # (hid, hevent) -> next hid
            tmd = self.closure_max_depth
            init = [
                s for s in model.init_states() if model.within_boundary(s)
            ]
            for s in init:
                for i, a in enumerate(s.actor_states):
                    sid_of(i, a)
                if track:
                    hid_of(s.history)
            generated = len(init)  # pre-dedup seed, mirroring seed_init
            seen_max_depth = 1 if init else 0
            seen = set(init)
            work = deque((s, 1) for s in set(init))

            # Exact mode PROVES the network-capacity bound: track the max
            # in-flight occupancy over every GENERATED successor — measured
            # PRE-boundary, because the device expand generates successors
            # before boundary masking and the rings must hold them without
            # tripping the capacity-poison guard — and auto-size the
            # ring/pool lanes to it below. The default flow_depth=8 /
            # pool_size=16 lanes made abd-ordered rows 118 lanes wide when
            # the protocol never holds more than a few messages per flow,
            # taxing every expand/fingerprint/queue byte (VERDICT r4
            # next #5 groundwork).
            def net_use(st) -> int:
                net = st.network
                if net.kind == ORDERED:
                    return max(
                        (len(v) for v in net._data.values()), default=0
                    )
                if net.kind == UNORDERED_NONDUPLICATING:
                    return sum(net._data.values())
                return 0  # duplicating: bitmask lanes, no capacity dim

            max_net = max((net_use(s) for s in seen), default=0)
            while work:
                st, depth = work.popleft()
                if tmd is not None and depth >= tmd:
                    continue  # at the cutoff: not expanded (bfs.rs:219-224)
                acts: list = []
                model.actions(st, acts)
                for a in acts:
                    entry = None
                    if isinstance(a, ADeliver):
                        dst = int(a.dst)
                        if dst < self.n:
                            eid = env_id(Envelope(a.src, a.dst, a.msg))
                            sid = sid_of(dst, st.actor_states[dst])
                            if (dst, sid) not in frozen:
                                entry = react_deliver(eid, sid)
                    elif isinstance(a, ATimeout):
                        actor = int(a.id)
                        tid = timer_id(actor, a.timer)
                        sid = sid_of(actor, st.actor_states[actor])
                        if (actor, sid) not in frozen:
                            entry = react_timeout(actor, tid, sid)
                    elif isinstance(a, ASelect):
                        actor = int(a.actor)
                        cid = choice_id(actor, a.random)
                        sid = sid_of(actor, st.actor_states[actor])
                        if (actor, sid) not in frozen:
                            entry = react_random(actor, cid, sid)
                    # Crash / DropEnv need no reaction table (crash lane /
                    # lossy-drop are modeled directly on device).
                    if track and entry is not None and "hevent" not in entry:
                        entry["hevent"] = hevent_id(
                            entry["env"], entry["emits"]
                        )
                    nxt = model.next_state(st, a)
                    if nxt is None:
                        continue
                    # Pre-boundary occupancy: the device generates this
                    # successor (and needs ring/pool room for it) even when
                    # the boundary then masks it out.
                    max_net = max(max_net, net_use(nxt))
                    if not model.within_boundary(nxt):
                        continue
                    generated += 1
                    if track and entry is not None:
                        trans[(hid_of(st.history), entry["hevent"])] = hid_of(
                            nxt.history
                        )
                    if nxt not in seen:
                        if len(seen) >= self.max_joint_states:
                            raise LoweringError(
                                "exact closure exceeded max_joint_states="
                                f"{self.max_joint_states}; the global space "
                                "is too large to enumerate on the host — "
                                "use closure='independent'/'joint' with a "
                                "local_boundary, or a hand encoding"
                            )
                        seen.add(nxt)
                        work.append((nxt, depth + 1))
                        seen_max_depth = max(seen_max_depth, depth + 1)
            # Auto-size the network lanes to the PROVEN bound (sound for
            # any device run within this closure's coverage, i.e. the same
            # target_max_depth contract that already applies to exact mode;
            # anything that somehow escapes still hits the detected
            # capacity-poison guard, never silent truncation). Explicit
            # constructor values are never overridden — they remain the
            # remedy knob for capacity overflows.
            if self.kind == ORDERED and self._flow_depth_arg is None:
                self.flow_depth = max(1, max_net)
            elif (
                self.kind == UNORDERED_NONDUPLICATING
                and self._pool_size_arg is None
            ):
                self.pool_size = max(1, max_net)
            self.closure_stats = {
                "generated": generated,
                "unique": len(seen),
                "max_depth": seen_max_depth,
                "max_net": max_net,
            }
            if track:
                self._hd = np.zeros(
                    (len(self.histories), max(len(self.hevents), 1)),
                    np.uint32,
                )
                for (hid, ev), nid in trans.items():
                    self._hd[hid, ev] = nid
            else:
                self._hd = np.zeros((1, 1), np.uint32)
            self._h0 = 0

        if self.exact:
            exact_bfs()
        elif self.joint:
            self._close_joint(react_deliver, react_timeout, react_random, frozen)
        else:
            while pending:
                item = pending.popleft()
                if item in done:
                    continue
                done.add(item)
                if item[0] == "r":
                    react_random(item[1], item[2], item[3])
                elif item[0] == "d":
                    react_deliver(item[1], item[2])
                else:
                    react_timeout(item[1], item[2], item[3])

        # Kept for incremental extension (`extend`).
        self._react_deliver = react_deliver
        self._react_timeout = react_timeout
        self._react_random = react_random
        self._frozen = frozen

        self._close_randoms()
        if not self.exact:  # exact mode closed histories during the BFS
            self._close_histories()

    def _close_joint(self, react_deliver, react_timeout, react_random,
                     frozen) -> None:
        """Joint reaction closure: a worklist over actor-sid VECTORS with a
        sticky (grow-only) envelope/timer/choice vocabulary. Network, timer,
        and pending-choice availability are relaxed — anything ever emitted
        stays deliverable, any timer kind can fire, any known choice value
        can be selected — so the explored vectors over-approximate every real
        interleaving's projection while preserving the correlations BETWEEN
        actors that the independent closure throws away (the cross product
        that explodes for quorum-accumulating actors like Paxos servers).
        Each (vector, vocabulary-entry) pair is processed exactly once via
        per-vector watermarks; vocabulary growth re-enqueues only the vectors
        whose watermark is stale."""
        zero = (0,) * self.n
        init_vec = tuple(self._init_sids)
        jmarks: dict = {init_vec: None}  # vec -> (e, t-tuple, c-tuple) marks
        jwork = deque([init_vec])

        def visit(vec):
            marks = jmarks[vec]
            e0, t0, c0 = marks if marks is not None else (0, zero, zero)
            nE = len(self.envs)
            nT = tuple(len(self.timers[a]) for a in range(self.n))
            nC = tuple(len(self.rchoices[a]) for a in range(self.n))

            def push(a, new_sid):
                if new_sid == vec[a]:
                    return
                nv = vec[:a] + (new_sid,) + vec[a + 1 :]
                if nv not in jmarks:
                    if len(jmarks) >= self.max_joint_states:
                        if self.best_effort:
                            return  # seed mode: the gap will poison-surface
                        raise LoweringError(
                            "joint closure exceeded max_joint_states="
                            f"{self.max_joint_states}; tighten local_boundary "
                            "or raise the cap"
                        )
                    jmarks[nv] = None
                    jwork.append(nv)

            for eid in range(e0, nE):
                dst = int(self.envs[eid].dst)
                if dst >= self.n:
                    continue
                sid = vec[dst]
                if (dst, sid) in frozen:
                    continue
                entry = react_deliver(eid, sid)
                if entry is not None:
                    push(dst, entry["new_sid"])
            for a in range(self.n):
                sid = vec[a]
                if (a, sid) in frozen:
                    continue
                for tid in range(t0[a], nT[a]):
                    entry = react_timeout(a, tid, sid)
                    if entry is not None:
                        push(a, entry["new_sid"])
                for cid in range(c0[a], nC[a]):
                    push(a, react_random(a, cid, sid)["new_sid"])
            jmarks[vec] = (nE, nT, nC)

        while True:
            while jwork:
                visit(jwork.popleft())
            # Reactions may have grown the vocabulary after a vector was
            # visited; re-enqueue exactly the stale ones and fix-point.
            nE = len(self.envs)
            nT = tuple(len(self.timers[a]) for a in range(self.n))
            nC = tuple(len(self.rchoices[a]) for a in range(self.n))
            stale = [
                v for v, m in jmarks.items() if m != (nE, nT, nC)
            ]
            if not stale:
                return
            jwork.extend(stale)

    def _close_randoms(self) -> None:
        """Close the per-actor randoms-map vocabulary (key -> pending
        choices) under delta application and choice-popping, and resolve the
        flattened SelectRandom slot tables. Over-approximates by applying
        every delta to every map — sound, and bounded for the usual
        replace-or-clear usage of choose_random."""
        self.has_randoms = any(
            any(ops for ops in deltas) for deltas in self.rdeltas
        )
        self._rapply: list[dict] = []
        self._rsel: list[dict] = []  # (rid, j) -> (cid, rid_after_pop)
        self.max_rand_slots: list[int] = []
        for i in range(self.n):
            maps = self.rmaps[i]
            ids = self.rmap_ids[i]

            def canon(d):
                return tuple(sorted(d.items(), key=lambda kv: repr(kv[0])))

            work = deque(range(len(maps)))

            def map_id(t):
                mid = ids.get(t)
                if mid is None:
                    mid = len(maps)
                    if mid >= 4096:
                        raise LoweringError(
                            f"actor {i} randoms-map vocabulary exceeded 4096; "
                            "choose_random usage may be unbounded"
                        )
                    ids[t] = mid
                    maps.append(t)
                    work.append(mid)
                return mid

            rapply: dict = {}
            rsel: dict = {}
            seen: set = set()
            max_j = 0
            while work:
                rid = work.popleft()
                if rid in seen:
                    continue
                seen.add(rid)
                base = dict(maps[rid])
                for did, ops in enumerate(self.rdeltas[i]):
                    d2 = dict(base)
                    for key, choices in ops:
                        if choices:
                            d2[key] = choices
                        else:
                            d2.pop(key, None)
                    rapply[(rid, did)] = map_id(canon(d2))
                j = 0
                for key, choices in maps[rid]:
                    d2 = dict(base)
                    d2.pop(key, None)
                    popped = map_id(canon(d2))
                    for v in choices:
                        rsel[(rid, j)] = (self.rchoice_ids[i][v], popped)
                        j += 1
                max_j = max(max_j, j)
            self._rapply.append(rapply)
            self._rsel.append(rsel)
            self.max_rand_slots.append(max_j)
    def _close_histories(self) -> None:
        """Build the history vocabulary + transition table over history
        EVENTS (delivered envelope + ordered emissions), replaying the
        model's record_msg_in/out hooks (ref: src/actor/model.rs:348-357).

        Histories are closed JOINTLY with the per-actor local-state vector:
        an event only fires from joint states where its destination actor is
        in the gating local state, and firing advances that actor. Relaxing
        only the network/timer availability keeps this a sound
        over-approximation of reachability while staying bounded for
        histories that a pure history-times-event closure would blow up
        (e.g. consistency testers, where replaying one event forever would
        append operations without bound).

        In refinement mode (`closure="seed"`), histories are LAZY instead:
        the transition table defaults to a sentinel, the device search
        surfaces missing (history, event) transitions as kind-4 poison
        payloads, and `extend()` applies exactly those — the same
        search-driven strategy as the reaction closure, which sidesteps the
        joint over-approximation blowing up as refinement grows the tables.
        """
        model = self.model
        lazy = self.best_effort
        fresh = not (lazy and hasattr(self, "_htrans"))
        if fresh:
            self.hevents: list = []  # id -> (eid or None, tuple emit eids)
            self._hevent_ids: dict = {}
            self.hids: dict = {}
            self.histories: list = []
            self._htrans: dict = {}  # (hid, hevent) -> next hid
        if not self.track_history:
            self._hd = np.zeros((1, 1), np.uint32)
            return

        def hevent_id(env_eid, emits) -> int:
            key = (env_eid, tuple(emits))
            hid = self._hevent_ids.get(key)
            if hid is None:
                hid = len(self.hevents)
                self._hevent_ids[key] = hid
                self.hevents.append(key)
            return hid

        for entry in (
            list(self.deliver.values())
            + list(self.timeout.values())
            + list(self.random.values())
        ):
            if entry is not None and "hevent" not in entry:
                entry["hevent"] = hevent_id(entry["env"], entry["emits"])

        def apply_event(history, event):
            env_eid, emits = event
            if env_eid is not None:
                env = self.envs[env_eid]
                nh = model.record_msg_in_(model.cfg, history, env)
                if nh is not None:
                    history = nh
            for e in emits:
                env = self.envs[e]
                nh = model.record_msg_out_(model.cfg, history, env)
                if nh is not None:
                    history = nh
            return history

        def hid_of(h) -> int:
            nid = self.hids.get(h)
            if nid is None:
                nid = len(self.histories)
                if nid >= self.max_histories:
                    raise LoweringError(
                        "history vocabulary exceeded max_histories="
                        f"{self.max_histories}; raise the cap, or the "
                        "history may be genuinely unbounded (e.g. "
                        "unbounded counters)"
                    )
                self.hids[h] = nid
                self.histories.append(h)
            return nid

        self._hist_fns = (hevent_id, apply_event, hid_of)

        # The initial history replays on_start emissions (record_msg_out).
        h0 = apply_event(model.init_history, (None, tuple(self._init_emits)))
        if fresh:
            self.hids = {h0: 0}
            self.histories = [h0]

        if not lazy:
            # Gated transitions: (dst actor, gate sid, new sid, hevent).
            gated = []
            for (eid, sid), entry in self.deliver.items():
                if entry is not None:
                    dst = int(self.envs[eid].dst)
                    gated.append((dst, sid, entry["new_sid"], entry["hevent"]))
            for (actor, _tid, sid), entry in self.timeout.items():
                if entry is not None:
                    gated.append((actor, sid, entry["new_sid"], entry["hevent"]))
            for (actor, _cid, sid), entry in self.random.items():
                if entry is not None:
                    gated.append((actor, sid, entry["new_sid"], entry["hevent"]))

            start = (tuple(self._init_sids), 0)
            seen = {start}
            worklist = deque([start])
            max_joint = self.max_histories * 16
            while worklist:
                sid_vec, hid = worklist.popleft()
                h = self.histories[hid]
                for dst, gate, new_sid, ev in gated:
                    if sid_vec[dst] != gate:
                        continue
                    nid = self._htrans.get((hid, ev))
                    if nid is None:
                        nid = hid_of(apply_event(h, self.hevents[ev]))
                        self._htrans[(hid, ev)] = nid
                    nxt = (
                        sid_vec[:dst] + (new_sid,) + sid_vec[dst + 1 :],
                        nid,
                    )
                    if nxt not in seen:
                        if len(seen) >= max_joint:
                            raise LoweringError(
                                "joint (actor-states, history) closure "
                                f"exceeded {max_joint} states; the history "
                                "may be too entangled with the global state "
                                "to lower (refine_check closes histories "
                                "lazily instead)"
                            )
                        seen.add(nxt)
                        worklist.append(nxt)
        self._bake_hd()

    def _bake_hd(self) -> None:
        """Bake the (history, event) transition matrix. Unknown combos are 0
        in the eager modes (unreachable per the joint over-approximation —
        harmless) but the EMPTY sentinel in lazy/refinement mode, where the
        device search must surface them as kind-4 poison payloads."""
        if not self.track_history:
            self._hd = np.zeros((1, 1), np.uint32)
            return
        n_events = len(self.hevents)
        if self.best_effort and n_events > 1 << 16:
            raise LoweringError(
                "history-event vocabulary exceeds the 16-bit poison-payload "
                "field; refinement cannot address these transitions (use "
                "closure='exact')"
            )
        default = EMPTY if self.best_effort else np.uint32(0)
        self._hd = np.full(
            (
                self._dyn_cap("H", len(self.histories)),
                self._dyn_cap("HE", max(n_events, 1)),
            ),
            default,
            np.uint32,
        )
        for (hid, ev), nid in self._htrans.items():
            self._hd[hid, ev] = nid
        self._h0 = 0

    # -- device layout ---------------------------------------------------------

    def _layout(self) -> None:
        self.E = self._dyn_cap("E", len(self.envs))
        self.has_timers = any(self.timers[i] for i in range(self.n))
        self.timeout_slots = [
            (i, tid)
            for i in range(self.n)
            for tid in range(len(self.timers[i]))
        ]
        lane = 0
        self.sid_off = lane
        lane += self.n
        self.timer_off = lane
        if self.has_timers:
            lane += self.n
        self.hist_off = lane
        if self.track_history:
            lane += 1
        # Randoms / crashed lanes are EXCLUDED from state identity via
        # `representative` (the reference's manual Hash skips them,
        # ref: src/actor/model_state.rs:134-145).
        self.rand_off = lane
        if self.has_randoms:
            lane += self.n
        self.crash_off = lane
        if self.max_crashes:
            lane += 1
        self.net_off = lane
        if self.kind == UNORDERED_NONDUPLICATING:
            lane += self.pool_size
            n_net_actions = self.pool_size
        elif self.kind == ORDERED:
            # Per directed flow: a left-aligned FIFO ring of eids. Flows are
            # the (src, dst) pairs observed in the envelope vocabulary.
            self.flows = sorted(
                {(int(e.src), int(e.dst)) for e in self.envs}
            )
            self.flow_ids = {f: i for i, f in enumerate(self.flows)}
            self.F = len(self.flows)
            self._E_flow = np.asarray(
                (
                    [
                        self.flow_ids[(int(e.src), int(e.dst))]
                        for e in self.envs
                    ]
                    + [0] * (self.E - len(self.envs))
                )
                or [0],
                np.uint32,
            )
            lane += self.F * self.flow_depth
            n_net_actions = self.F
        else:  # duplicating: envelope-set bitmask + last_msg lane
            self.nbits = (self.E + 31) // 32
            lane += self.nbits + 1
            n_net_actions = self.E
        self.lanes = lane
        if self.E == 0:
            # The closure proves no message is ever sent: no network actions.
            n_net_actions = 0
        self.deliver_slots = n_net_actions
        self.drop_slots = n_net_actions if self.model.lossy_network else 0
        self.random_slots = [
            (i, j)
            for i in range(self.n)
            for j in range(self.max_rand_slots[i] if self.has_randoms else 0)
        ]
        self.crash_slots = self.n if self.max_crashes else 0
        # At least one (all-invalid) slot keeps expand shapes well-formed for
        # degenerate models with no actions at all.
        self.max_actions = max(
            self.deliver_slots
            + self.drop_slots
            + len(self.timeout_slots)
            + len(self.random_slots)
            + self.crash_slots,
            1,
        )

    def _bake_tables(self) -> None:
        E = self.E
        maxS = self._dyn_cap("S", max((len(s) for s in self.states), default=1))
        self.maxS = maxS
        # Deliver tables [E, maxS] flattened. D_state: 0 = unexplored (POISON
        # if reached), 1 = elided no-op, else new_sid + 2.
        D_state = np.zeros((E, maxS), np.uint32)
        D_emits = np.full((E, maxS, self.max_emit), EMPTY, np.uint32)
        D_tclr = np.zeros((E, maxS), np.uint32)
        D_tset = np.zeros((E, maxS), np.uint32)
        D_hev = np.zeros((E, maxS), np.uint32)
        D_delta = np.zeros((E, maxS), np.uint32)
        for (eid, sid), entry in self.deliver.items():
            if entry is None:
                D_state[eid, sid] = _ELIDED
                continue
            D_state[eid, sid] = entry["new_sid"] + _VALID0
            for j, e in enumerate(entry["emits"]):
                D_emits[eid, sid, j] = e
            D_tclr[eid, sid] = entry["tclr"]
            D_tset[eid, sid] = entry["tset"]
            D_hev[eid, sid] = entry.get("hevent", 0)
            D_delta[eid, sid] = entry["delta"]
        self._D = (D_state, D_emits, D_tclr, D_tset, D_hev, D_delta)
        self._E_dst = np.asarray(
            (
                [
                    int(e.dst) if int(e.dst) < self.n else self.n
                    for e in self.envs
                ]
                + [self.n] * (E - len(self.envs))  # padded: undeliverable
            )
            or [0],
            np.uint32,
        )

        nT = len(self.timeout_slots)
        T_state = np.zeros((max(nT, 1), maxS), np.uint32)
        T_emits = np.full((max(nT, 1), maxS, self.max_emit), EMPTY, np.uint32)
        T_tclr = np.zeros((max(nT, 1), maxS), np.uint32)
        T_tset = np.zeros((max(nT, 1), maxS), np.uint32)
        T_hev = np.zeros((max(nT, 1), maxS), np.uint32)
        T_delta = np.zeros((max(nT, 1), maxS), np.uint32)
        _missing = object()
        for k, (i, tid) in enumerate(self.timeout_slots):
            for sid in range(len(self.states[i])):
                entry = self.timeout.get((i, tid, sid), _missing)
                if entry is _missing:
                    continue  # unexplored (T_state stays 0)
                if entry is None:
                    T_state[k, sid] = _ELIDED  # elided no-op
                    continue
                T_state[k, sid] = entry["new_sid"] + _VALID0
                for j, e in enumerate(entry["emits"]):
                    T_emits[k, sid, j] = e
                T_tclr[k, sid] = entry["tclr"]
                T_tset[k, sid] = entry["tset"]
                T_hev[k, sid] = entry.get("hevent", 0)
                T_delta[k, sid] = entry["delta"]
        self._T = (T_state, T_emits, T_tclr, T_tset, T_hev, T_delta)

        if self.has_randoms:
            maxR = self._dyn_cap("R", max(len(m) for m in self.rmaps), 4)
            maxD = self._dyn_cap("Rd", max(len(d) for d in self.rdeltas), 4)
            maxC = self._dyn_cap(
                "Rc", max((len(c) for c in self.rchoices), default=1) or 1, 4
            )
            nJ = max(self.max_rand_slots) or 1
            RAPP = np.zeros((self.n, maxR, maxD), np.uint32)
            for i in range(self.n):
                for (rid, did), nrid in self._rapply[i].items():
                    RAPP[i, rid, did] = nrid
            RSEL = np.zeros((self.n, maxR, nJ), np.uint32)  # cid + 1; 0 = none
            RPOP = np.zeros((self.n, maxR, nJ), np.uint32)
            for i in range(self.n):
                for (rid, j), (cid, popped) in self._rsel[i].items():
                    RSEL[i, rid, j] = cid + 1
                    RPOP[i, rid, j] = popped
            R_state = np.zeros((self.n, maxC, maxS), np.uint32)
            R_emits = np.full(
                (self.n, maxC, maxS, self.max_emit), EMPTY, np.uint32
            )
            R_tclr = np.zeros((self.n, maxC, maxS), np.uint32)
            R_tset = np.zeros((self.n, maxC, maxS), np.uint32)
            R_hev = np.zeros((self.n, maxC, maxS), np.uint32)
            R_delta = np.zeros((self.n, maxC, maxS), np.uint32)
            for (i, cid, sid), entry in self.random.items():
                R_state[i, cid, sid] = entry["new_sid"] + _VALID0
                for j, e in enumerate(entry["emits"]):
                    R_emits[i, cid, sid, j] = e
                R_tclr[i, cid, sid] = entry["tclr"]
                R_tset[i, cid, sid] = entry["tset"]
                R_hev[i, cid, sid] = entry.get("hevent", 0)
                R_delta[i, cid, sid] = entry["delta"]
            self._R = (RAPP, RSEL, RPOP, R_state, R_emits, R_tclr, R_tset,
                       R_hev, R_delta)
            self._R_dims = (maxR, maxD, maxC, nJ)

    # -- encode / decode -------------------------------------------------------

    def encode_state(self, sys_state) -> np.ndarray:
        """Host ActorModelState -> device row (used for seeding and tests)."""
        row = np.zeros(self.lanes, np.uint32)
        for i, st in enumerate(sys_state.actor_states):
            row[self.sid_off + i] = self.sids[i][st]
        if self.has_timers:
            for i, tset in enumerate(sys_state.timers_set):
                mask = 0
                for t in tset:
                    mask |= 1 << self.timer_ids[i][t]
                row[self.timer_off + i] = mask
        if self.track_history:
            row[self.hist_off] = self.hids[sys_state.history]
        if self.has_randoms:
            for i, randoms in enumerate(sys_state.random_choices):
                canon = tuple(
                    sorted(randoms.items(), key=lambda kv: repr(kv[0]))
                )
                row[self.rand_off + i] = self.rmap_ids[i][canon]
        if self.max_crashes:
            mask = 0
            for i, c in enumerate(sys_state.crashed):
                if c:
                    mask |= 1 << i
            row[self.crash_off] = mask
        if self.kind == UNORDERED_NONDUPLICATING:
            pool = sorted(
                self.env_ids[(int(e.src), int(e.dst), e.msg)]
                for e in sys_state.network.iter_all()
            )
            if len(pool) > self.pool_size:
                raise LoweringError("init network exceeds pool_size")
            for j, e in enumerate(pool):
                row[self.net_off + j] = e
            for j in range(len(pool), self.pool_size):
                row[self.net_off + j] = EMPTY
        elif self.kind == ORDERED:
            row[self.net_off : self.net_off + self.F * self.flow_depth] = EMPTY
            counts = [0] * self.F
            for e in sys_state.network.iter_all():  # FIFO order per flow
                f = self.flow_ids[(int(e.src), int(e.dst))]
                if counts[f] >= self.flow_depth:
                    raise LoweringError("init network exceeds flow_depth")
                row[self.net_off + f * self.flow_depth + counts[f]] = (
                    self.env_ids[(int(e.src), int(e.dst), e.msg)]
                )
                counts[f] += 1
        else:
            for e in sys_state.network.iter_all():
                eid = self.env_ids[(int(e.src), int(e.dst), e.msg)]
                row[self.net_off + eid // 32] |= np.uint32(1 << (eid % 32))
            lm = sys_state.network.last_msg
            row[self.net_off + self.nbits] = (
                self.env_ids[(int(lm.src), int(lm.dst), lm.msg)]
                if lm is not None
                else EMPTY
            )
        return row

    def poison_payload(self, row):
        """Decode a poison marker row -> (kind, idx1, idx2, sid) or None.
        kind: 0 deliver-gap / 1 timeout-gap / 2 random-gap; +16 = capacity
        overflow on a covered pair (see expand's materialization block)."""
        row = [int(x) for x in row]
        if row[0] != int(EMPTY):
            return None
        if len(row) < 3 or row[1] == int(EMPTY):
            return (-1, 0, 0, 0)  # payload-less narrow marker (no refinement)
        return (
            row[1] >> 24,
            row[1] & 0xFFFFFF,
            row[2] >> 16,
            row[2] & 0xFFFF,
        )

    def poison_scan(self, rows: np.ndarray):
        """Vectorized `poison_payload` over a raw uint32[n, lanes] dump:
        returns (gaps set, capacity list, narrow bool). refine_check scans
        millions of queue rows per round — the per-row python decode was a
        measurable slice of the round cost."""
        if rows.shape[0] == 0:
            return set(), [], False
        pois = rows[:, 0] == EMPTY
        if not pois.any():
            return set(), [], False
        if rows.shape[1] < 3:
            return set(), [], True
        sub = rows[pois]
        if (sub[:, 1] == EMPTY).any():
            return set(), [], True
        r1 = sub[:, 1].astype(np.int64)
        r2 = sub[:, 2].astype(np.int64)
        payloads = zip(
            (r1 >> 24).tolist(),
            (r1 & 0xFFFFFF).tolist(),
            (r2 >> 16).tolist(),
            (r2 & 0xFFFF).tolist(),
        )
        gaps, capacity = set(), []
        for p in payloads:
            if p[0] & 16:
                capacity.append(p)
            else:
                gaps.add(p)
        return gaps, capacity, False

    def affected_rows_mask(self, rows: np.ndarray, gaps) -> np.ndarray:
        """Which raw queue rows could realize one of `gaps` now that extend()
        covered them — a sound over-approximation (false positives only cost
        re-expansion; false negatives are impossible for deliver gaps, and
        the timeout/random/history forms match on every lane the reaction
        reads). Drives refine_check's warm rounds: instead of re-searching
        the whole grown space after each extend(), only these rows are
        re-enqueued into the carried search."""
        def env_present(eid: int) -> np.ndarray:
            if self.kind == UNORDERED_NONDUPLICATING:
                pool = rows[:, self.net_off : self.net_off + self.pool_size]
                return (pool == eid).any(axis=1)
            if self.kind == ORDERED:
                f = int(self._E_flow[eid])
                # Deliverable only at the flow head.
                return rows[:, self.net_off + f * self.flow_depth] == eid
            return (  # duplicating bitmask
                (rows[:, self.net_off + eid // 32] >> (eid % 32)) & 1 == 1
            )

        mask = np.zeros(rows.shape[0], dtype=bool)
        nonpois = rows[:, 0] != EMPTY
        for kind, i1, i2, sid in gaps:
            k = kind & 15
            if k == 0:  # deliver (eid, sid): dst actor in sid + env present
                eid = i1
                dst = int(self.envs[eid].dst)
                m = (rows[:, self.sid_off + dst] == sid) & env_present(eid)
            elif k in (1, 2):  # timeout/random: (actor, tid/cid, sid)
                m = rows[:, self.sid_off + i1] == sid
            elif k == 4:  # history transition (hid, hevent): the hevent key
                # carries the delivered eid, so require it in-flight too —
                # hid alone matches every state sharing the history, which
                # made the warm-injection sets balloon.
                m = (
                    rows[:, self.hist_off] == i1
                    if self.track_history
                    else np.ones(rows.shape[0], dtype=bool)
                )
                ev_eid = (
                    self.hevents[i2][0] if i2 < len(self.hevents) else None
                )
                if ev_eid is not None:
                    m &= env_present(int(ev_eid))
            else:
                m = np.ones(rows.shape[0], dtype=bool)
            mask |= m
        return mask & nonpois

    def decode(self, row):
        """Device row -> a readable dict mirroring ActorModelState."""
        payload = self.poison_payload(row)
        if payload is not None:
            kind, i1, i2, sid = payload
            if kind < 0:
                return "<poison: closure coverage exceeded>"
            what = {0: "deliver", 1: "timeout", 2: "random", 4: "history"}.get(
                kind & 15, "?"
            )
            tag = "capacity overflow" if kind & 16 else "closure gap"
            return (
                f"<poison ({tag}): {what} idx1={i1} idx2={i2} sid={sid}>"
            )
        row = [int(x) for x in row]
        out = {
            "actor_states": tuple(
                self.states[i][row[self.sid_off + i]] for i in range(self.n)
            )
        }
        if self.has_timers:
            out["timers"] = tuple(
                frozenset(
                    self.timers[i][t]
                    for t in range(len(self.timers[i]))
                    if row[self.timer_off + i] >> t & 1
                )
                for i in range(self.n)
            )
        if self.track_history:
            out["history"] = self.histories[row[self.hist_off]]
        if self.has_randoms:
            out["random_choices"] = tuple(
                dict(self.rmaps[i][row[self.rand_off + i]])
                for i in range(self.n)
            )
        if self.max_crashes:
            out["crashed"] = tuple(
                bool(row[self.crash_off] >> i & 1) for i in range(self.n)
            )
        if self.kind == UNORDERED_NONDUPLICATING:
            out["network"] = [
                self.envs[e]
                for e in row[self.net_off : self.net_off + self.pool_size]
                if e != int(EMPTY)
            ]
        elif self.kind == ORDERED:
            out["network"] = {
                self.flows[f]: [
                    self.envs[e].msg
                    for e in row[
                        self.net_off + f * self.flow_depth :
                        self.net_off + (f + 1) * self.flow_depth
                    ]
                    if e != int(EMPTY)
                ]
                for f in range(self.F)
                if row[self.net_off + f * self.flow_depth] != int(EMPTY)
            }
        else:
            out["network"] = [
                self.envs[e]
                for e in range(self.E)
                if row[self.net_off + e // 32] >> (e % 32) & 1
            ]
            lm = row[self.net_off + self.nbits]
            out["last_msg"] = self.envs[lm] if lm != int(EMPTY) else None
        return out

    def _slot_env(self, row, j: int) -> int:
        if self.kind == UNORDERED_NONDUPLICATING:
            return int(row[self.net_off + j])
        if self.kind == ORDERED:
            return int(row[self.net_off + j * self.flow_depth])  # flow head
        return j

    def action_label(self, row, action_index):
        if action_index < self.deliver_slots:
            e = self._slot_env(row, action_index)
            if e == int(EMPTY):
                return "noop"
            env = self.envs[e]
            return f"Deliver {{ src: {env.src!r}, dst: {env.dst!r}, msg: {env.msg!r} }}"
        if action_index < self.deliver_slots + self.drop_slots:
            e = self._slot_env(row, action_index - self.deliver_slots)
            if e == int(EMPTY):
                return "noop"
            return f"Drop({self.envs[e]!r})"
        k = action_index - self.deliver_slots - self.drop_slots
        if k < len(self.timeout_slots):
            i, tid = self.timeout_slots[k]
            return f"Timeout({Id(i)!r}, {self.timers[i][tid]!r})"
        k -= len(self.timeout_slots)
        if k < len(self.random_slots):
            i, j = self.random_slots[k]
            rid = int(row[self.rand_off + i]) if self.has_randoms else 0
            sel = self._rsel[i].get((rid, j))
            if sel is None:
                return "noop"
            cid, _popped = sel
            return (
                f"SelectRandom {{ actor: {Id(i)!r}, "
                f"random: {self.rchoices[i][cid]!r} }}"
            )
        k -= len(self.random_slots)
        return f"Crash({Id(k)!r})"

    # -- TensorModel interface -------------------------------------------------

    def init_states(self):
        rows = [self.encode_state(s) for s in self.model.init_states()]
        return jnp.asarray(np.stack(rows))

    def expand(self, states):
        B = states.shape[0]
        n, M = self.n, self.max_actions
        u = jnp.uint32
        D_state, D_emits, D_tclr, D_tset, D_hev, D_delta = (
            self._tbl(f"D{i}") for i in range(6)
        )
        T_state, T_emits, T_tclr, T_tset, T_hev, T_delta = (
            self._tbl(f"T{i}") for i in range(6)
        )
        E_dst = self._tbl("E_dst")
        maxS = self.maxS

        sid_lanes = states[:, self.sid_off : self.sid_off + n]  # [B, n]
        if self.has_randoms:
            rand_lanes = states[:, self.rand_off : self.rand_off + n]
            maxR, maxD, maxC, nJ = self._R_dims
        if self.max_crashes:
            crash_mask = states[:, self.crash_off]  # [B] bitmask

        def not_crashed(actor_idx):
            """actor_idx: [B, S] -> bool[B, S]; True when no crash support."""
            if not self.max_crashes:
                return jnp.ones(actor_idx.shape, bool)
            return (
                (crash_mask[:, None] >> actor_idx.astype(u)) & u(1)
            ) == 0

        succ_parts = []
        valid_parts = []
        # Stashes for the poison-payload block at the end (which (eid, sid)
        # pair each slot would have taken — what incremental refinement needs
        # to extend the closure).
        deliver_eids = None
        t_sid_stash = None
        r_cid_stash = r_sid_stash = None
        # Poison rows are terminal: everything expanding FROM one is invalid
        # (they only exist to carry the uncovered pair to the host).
        src_poison = states[:, 0] == jnp.uint32(EMPTY)

        deliver_stash = {}  # st/hev/sid reused by the poison-payload block

        def gated_take(tbl, flat, flag):
            """Gather a reaction table, or skip the gather entirely when the
            model cannot populate it (the table is all-zero by construction
            and TPU gathers pay per element). The apply paths are gated on
            the same feature flags."""
            t = tbl.reshape(-1)
            return (
                jnp.take(t, flat) if flag else jnp.zeros(flat.shape, t.dtype)
            )

        def lookup_deliver(eid, deliverable):
            """eid: [B, S] delivered envelope per slot; -> per-slot updates."""
            safe = jnp.minimum(eid, u(self.E - 1)).astype(jnp.int32)
            dst = jnp.take(E_dst, safe)  # [B, S]; == n for undeliverable
            dst_ok = dst < n
            d_srv = jnp.where(dst_ok, dst, 0).astype(jnp.int32)
            sid = jnp.take_along_axis(sid_lanes, d_srv, axis=1)  # [B, S]
            flat = safe * maxS + sid.astype(jnp.int32)
            st = jnp.take(D_state.reshape(-1), flat)
            explored = st != _UNEXPLORED
            is_txn = st >= _VALID0
            new_sid = jnp.where(is_txn, st - u(_VALID0), sid)
            emits = jnp.take(
                D_emits.reshape(-1, self.max_emit), flat, axis=0
            )  # [B, S, max_emit]
            tclr = gated_take(D_tclr, flat, self.has_timers)
            tset = gated_take(D_tset, flat, self.has_timers)
            hev = gated_take(D_hev, flat, self.track_history)
            delta = gated_take(D_delta, flat, self.has_randoms)
            # Delivery to a crashed actor is not a transition
            # (ref: src/actor/model.rs:332-337).
            alive = not_crashed(d_srv)
            valid = deliverable & dst_ok & is_txn & alive
            poison = deliverable & dst_ok & ~explored & alive
            deliver_stash.update(st=st, hev=hev, sid=sid)
            return d_srv, new_sid, emits, tclr, tset, hev, delta, valid, poison

        def apply_common(
            d_actor, new_sid, emits, tclr, tset, hev, base_succ,
            delta=None, rid_base=None,
        ):
            """Write actor/timers/history/randoms lanes shared by
            deliver/timeout/select-random transitions."""
            succ = base_succ
            sel = (
                jnp.arange(n)[None, None, :] == d_actor[:, :, None]
            )  # [B, S, n]
            new_lanes = jnp.where(
                sel, new_sid[:, :, None], sid_lanes[:, None, :]
            )
            succ = succ.at[:, :, self.sid_off : self.sid_off + n].set(new_lanes)
            if self.has_timers:
                tl = states[:, self.timer_off : self.timer_off + n]
                ntl = jnp.where(
                    sel, (tl[:, None, :] & ~tclr[:, :, None]) | tset[:, :, None], tl[:, None, :]
                )
                succ = succ.at[:, :, self.timer_off : self.timer_off + n].set(ntl)
            if self.track_history:
                hid = states[:, self.hist_off]
                nh = jnp.take(
                    self._tbl("hd").reshape(-1),
                    (hid[:, None] * u(self._hd.shape[1]) + hev).astype(jnp.int32),
                )
                succ = succ.at[:, :, self.hist_off].set(nh)
            if self.has_randoms and delta is not None:
                RAPP = self._tbl("R0")
                if rid_base is None:
                    rid_base = jnp.take_along_axis(
                        rand_lanes, d_actor, axis=1
                    )
                flat_r = (
                    d_actor * (maxR * maxD)
                    + rid_base.astype(jnp.int32) * maxD
                    + delta.astype(jnp.int32)
                )
                nrid = jnp.take(RAPP.reshape(-1), flat_r)
                nrl = jnp.where(
                    sel, nrid[:, :, None], rand_lanes[:, None, :]
                )
                succ = succ.at[:, :, self.rand_off : self.rand_off + n].set(nrl)
            return succ

        base = jnp.broadcast_to(
            states[:, None, :], (B, self.deliver_slots, self.lanes)
        )

        def push_emits_ordered(flows4, emits):
            """Append emissions to their flows' tails, in order.
            flows4: [B, S, F, Dq]; emits: [B, S, max_emit].
            Returns (flows4, overflow[B, S])."""
            F, Dq = self.F, self.flow_depth
            flow_of = self._tbl("E_flow")
            overflow = jnp.zeros(flows4.shape[:2], bool)
            for j in range(self.max_emit):
                em = emits[:, :, j]  # [B, S]
                tf = jnp.take(
                    flow_of,
                    jnp.minimum(em, u(self.E - 1)).astype(jnp.int32),
                ).astype(jnp.int32)
                cnt = (flows4 != EMPTY).sum(axis=3)  # [B, S, F]
                pos = jnp.take_along_axis(cnt, tf[:, :, None], axis=2)[:, :, 0]
                live = em != EMPTY
                overflow = overflow | (live & (pos >= Dq))
                sel = (
                    (jnp.arange(F)[None, None, :, None] == tf[:, :, None, None])
                    & (
                        jnp.arange(Dq)[None, None, None, :]
                        == pos[:, :, None, None]
                    )
                    & live[:, :, None, None]
                )
                flows4 = jnp.where(sel, em[:, :, None, None], flows4)
            return flows4, overflow

        if self.deliver_slots == 0:
            pass  # no envelopes can ever exist (E == 0)
        elif self.kind == ORDERED:
            F, Dq = self.F, self.flow_depth
            flows = states[:, self.net_off : self.net_off + F * Dq].reshape(
                B, F, Dq
            )
            head = flows[:, :, 0]  # [B, F]
            deliver_eids = head
            deliverable = head != EMPTY
            (
                d_actor, new_sid, emits, tclr, tset, hev, delta, valid, poison
            ) = lookup_deliver(head, deliverable)
            succ = apply_common(
                d_actor, new_sid, emits, tclr, tset, hev, base, delta=delta
            )
            # Pop the delivered flow's head (slot f pops flow f), then push
            # emissions FIFO.
            shifted = jnp.concatenate(
                [flows[:, :, 1:], jnp.full((B, F, 1), EMPTY)], axis=2
            )
            eye = jnp.arange(F)[:, None] == jnp.arange(F)[None, :]  # [S, F]
            # Slot f pops flow f (shared by deliver and drop successors).
            popped = jnp.where(
                eye[None, :, :, None],
                shifted[:, None, :, :],
                flows[:, None, :, :],
            )
            flows4, push_ovf = push_emits_ordered(popped, emits)
            succ = succ.at[:, :, self.net_off : self.net_off + F * Dq].set(
                flows4.reshape(B, F, F * Dq)
            )
            poison = poison | (valid & push_ovf)
            succ_parts.append(succ)
            valid_parts.append((valid | poison, poison))

            if self.drop_slots:
                dbase = jnp.broadcast_to(
                    states[:, None, :], (B, F, self.lanes)
                )
                dsucc = dbase.at[
                    :, :, self.net_off : self.net_off + F * Dq
                ].set(popped.reshape(B, F, F * Dq))
                succ_parts.append(dsucc)
                valid_parts.append((deliverable, jnp.zeros_like(deliverable)))
        elif self.kind == UNORDERED_NONDUPLICATING:
            pool = states[:, self.net_off : self.net_off + self.pool_size]
            e = pool  # [B, P]
            deliver_eids = e
            nonempty = e != EMPTY
            first = jnp.concatenate(
                [jnp.ones((B, 1), bool), e[:, 1:] != e[:, :-1]], axis=1
            )
            deliverable = nonempty & first
            (
                d_actor, new_sid, emits, tclr, tset, hev, delta, valid, poison
            ) = lookup_deliver(e, deliverable)
            succ = apply_common(
                d_actor, new_sid, emits, tclr, tset, hev, base, delta=delta
            )
            # Pool: drop the delivered slot, add emissions, restore the
            # sorted-multiset invariant with the unrolled rank-sort
            # (tensor/poolops.py — a minor-axis jnp.sort pays cross-lane
            # shuffles on TPU).
            P = self.pool_size
            act = jnp.arange(P, dtype=jnp.uint32)[None, :]
            dropped_parts = [
                jnp.where(act == i, EMPTY, pool[:, i : i + 1])
                for i in range(P)
            ]
            npool, overflow = rank_sort(
                dropped_parts
                + [emits[:, :, j] for j in range(self.max_emit)],
                P,
            )
            succ = succ.at[:, :, self.net_off : self.net_off + P].set(npool)
            poison = poison | (valid & overflow)
            succ_parts.append(succ)
            valid_parts.append((valid | poison, poison))

            if self.drop_slots:
                dbase = jnp.broadcast_to(
                    states[:, None, :], (B, P, self.lanes)
                )
                dpool, _ = rank_sort(dropped_parts, P)
                dsucc = dbase.at[:, :, self.net_off : self.net_off + P].set(
                    dpool
                )
                succ_parts.append(dsucc)
                valid_parts.append((deliverable, jnp.zeros_like(deliverable)))
        else:
            # Duplicating: one deliver slot per envelope-vocab id.
            bits = states[:, self.net_off : self.net_off + self.nbits]
            eids = jnp.arange(self.E, dtype=u)[None, :]  # [1, E]
            in_flight = (
                bits[:, (jnp.arange(self.E) // 32)]
                >> (eids % u(32))
            ) & u(1)
            deliverable = in_flight.astype(bool)
            e = jnp.broadcast_to(eids, (B, self.E))
            deliver_eids = e
            (
                d_actor, new_sid, emits, tclr, tset, hev, delta, valid, poison
            ) = lookup_deliver(e, deliverable)
            succ = apply_common(
                d_actor, new_sid, emits, tclr, tset, hev, base, delta=delta
            )
            # Network: set unchanged except emissions OR-ed in; last_msg = e.
            nbits_arr = bits[:, None, :]  # [B, E, nbits]
            for j in range(self.max_emit):
                em = emits[:, :, j]
                emv = jnp.minimum(em, u(self.E - 1))
                word = (emv // u(32)).astype(jnp.int32)
                bit = u(1) << (emv % u(32))
                sel_w = (
                    jnp.arange(self.nbits)[None, None, :] == word[:, :, None]
                )
                add = jnp.where(
                    (em != EMPTY)[:, :, None] & sel_w, bit[:, :, None], u(0)
                )
                nbits_arr = nbits_arr | add
            succ = succ.at[:, :, self.net_off : self.net_off + self.nbits].set(
                nbits_arr
            )
            succ = succ.at[:, :, self.net_off + self.nbits].set(e)
            succ_parts.append(succ)
            valid_parts.append((valid | poison, poison))

            if self.drop_slots:
                dbase = jnp.broadcast_to(
                    states[:, None, :], (B, self.E, self.lanes)
                )
                word = (jnp.arange(self.E) // 32)[None, :]
                clr = ~(u(1) << (eids % u(32)))
                sel_w = (
                    jnp.arange(self.nbits)[None, None, :]
                    == word[:, :, None]
                )
                nb = jnp.where(
                    sel_w, bits[:, None, :] & clr[:, :, None], bits[:, None, :]
                )
                dsucc = dbase.at[
                    :, :, self.net_off : self.net_off + self.nbits
                ].set(nb)
                succ_parts.append(dsucc)
                valid_parts.append((deliverable, jnp.zeros_like(deliverable)))

        # Timeouts.
        if self.timeout_slots:
            nT = len(self.timeout_slots)
            t_actor = jnp.asarray(
                [i for i, _ in self.timeout_slots], jnp.int32
            )[None, :]
            t_bit = jnp.asarray(
                [1 << tid for _, tid in self.timeout_slots], np.uint32
            )[None, :]
            t_actor_b = jnp.broadcast_to(t_actor, (B, nT))
            tl = states[:, self.timer_off : self.timer_off + n]
            tmask = jnp.take_along_axis(tl, t_actor_b, axis=1)
            armed = (tmask & t_bit) != 0
            sid = jnp.take_along_axis(sid_lanes, t_actor_b, axis=1)
            t_sid_stash = sid
            flat = (
                jnp.arange(nT, dtype=jnp.int32)[None, :] * maxS
                + sid.astype(jnp.int32)
            )
            st = jnp.take(T_state.reshape(-1), flat)
            explored = st != _UNEXPLORED
            is_txn = st >= _VALID0
            new_sid = jnp.where(is_txn, st - u(_VALID0), sid)
            emits = jnp.take(T_emits.reshape(-1, self.max_emit), flat, axis=0)
            # Timers are live here by construction; the rest stay gated.
            tclr = jnp.take(T_tclr.reshape(-1), flat)
            tset = jnp.take(T_tset.reshape(-1), flat)
            hev = gated_take(T_hev, flat, self.track_history)
            delta = gated_take(T_delta, flat, self.has_randoms)
            alive = not_crashed(t_actor_b)
            valid = armed & is_txn & alive
            poison = armed & ~explored & alive
            tbase = jnp.broadcast_to(states[:, None, :], (B, nT, self.lanes))
            succ = apply_common(
                t_actor_b, new_sid, emits, tclr, tset, hev, tbase, delta=delta
            )
            if self.E == 0:
                pass  # no envelope vocabulary: timeouts cannot emit
            elif self.kind == ORDERED:
                F, Dq = self.F, self.flow_depth
                flows = states[
                    :, self.net_off : self.net_off + F * Dq
                ].reshape(B, F, Dq)
                tflows4 = jnp.broadcast_to(
                    flows[:, None, :, :], (B, nT, F, Dq)
                )
                tflows4, push_ovf = push_emits_ordered(tflows4, emits)
                succ = succ.at[
                    :, :, self.net_off : self.net_off + F * Dq
                ].set(tflows4.reshape(B, nT, F * Dq))
                poison = poison | (valid & push_ovf)
            elif self.kind == UNORDERED_NONDUPLICATING:
                pool = states[:, self.net_off : self.net_off + self.pool_size]
                P = self.pool_size
                npool, overflow = rank_sort_pool(pool, emits, nT)
                succ = succ.at[:, :, self.net_off : self.net_off + P].set(
                    npool
                )
                poison = poison | (valid & overflow)
            else:
                nbits_arr = states[:, None, self.net_off : self.net_off + self.nbits]
                nbits_arr = jnp.broadcast_to(
                    nbits_arr, (B, nT, self.nbits)
                )
                for j in range(self.max_emit):
                    em = emits[:, :, j]
                    emv = jnp.minimum(em, u(self.E - 1))
                    word = (emv // u(32)).astype(jnp.int32)
                    bit = u(1) << (emv % u(32))
                    sel_w = (
                        jnp.arange(self.nbits)[None, None, :]
                        == word[:, :, None]
                    )
                    add = jnp.where(
                        (em != EMPTY)[:, :, None] & sel_w,
                        bit[:, :, None],
                        u(0),
                    )
                    nbits_arr = nbits_arr | add
                succ = succ.at[
                    :, :, self.net_off : self.net_off + self.nbits
                ].set(nbits_arr)
            succ_parts.append(succ)
            valid_parts.append((valid | poison, poison))

        # SelectRandom actions (ref: src/actor/model.rs:302-313, 411-426).
        if self.random_slots:
            RAPP, RSEL, RPOP, R_state, R_emits, R_tclr, R_tset, R_hev, R_delta = (
                self._tbl(f"R{i}") for i in range(9)
            )
            nR = len(self.random_slots)
            r_actor = jnp.asarray(
                [i for i, _ in self.random_slots], jnp.int32
            )[None, :]
            r_j = jnp.asarray([j for _, j in self.random_slots], jnp.int32)[
                None, :
            ]
            r_actor_b = jnp.broadcast_to(r_actor, (B, nR))
            rid = jnp.take_along_axis(rand_lanes, r_actor_b, axis=1)
            flat_sel = (
                r_actor * (maxR * nJ) + rid.astype(jnp.int32) * nJ + r_j
            )
            cid1 = jnp.take(RSEL.reshape(-1), flat_sel)  # cid + 1; 0 = none
            popped = jnp.take(RPOP.reshape(-1), flat_sel)
            has_choice = cid1 != 0
            cid = jnp.where(has_choice, cid1 - u(1), u(0)).astype(jnp.int32)
            sid = jnp.take_along_axis(sid_lanes, r_actor_b, axis=1)
            r_cid_stash, r_sid_stash = cid, sid
            flat_rr = (
                r_actor * (maxC * maxS)
                + cid * maxS
                + sid.astype(jnp.int32)
            )
            st = jnp.take(R_state.reshape(-1), flat_rr)
            explored = st != _UNEXPLORED
            is_txn = st >= _VALID0
            new_sid = jnp.where(is_txn, st - u(_VALID0), sid)
            emits = jnp.take(R_emits.reshape(-1, self.max_emit), flat_rr, axis=0)
            tclr = gated_take(R_tclr, flat_rr, self.has_timers)
            tset = gated_take(R_tset, flat_rr, self.has_timers)
            hev = gated_take(R_hev, flat_rr, self.track_history)
            delta = jnp.take(R_delta.reshape(-1), flat_rr)
            alive = not_crashed(r_actor_b)
            valid = has_choice & is_txn & alive
            poison = has_choice & ~explored & alive
            rbase = jnp.broadcast_to(states[:, None, :], (B, nR, self.lanes))
            # The selected key's pending choice is consumed BEFORE the
            # handler's own choose_random commands apply
            # (ref: src/actor/model.rs:411-426).
            succ = apply_common(
                r_actor_b, new_sid, emits, tclr, tset, hev, rbase,
                delta=delta, rid_base=popped,
            )
            if self.E == 0:
                pass
            elif self.kind == ORDERED:
                F, Dq = self.F, self.flow_depth
                flows = states[
                    :, self.net_off : self.net_off + F * Dq
                ].reshape(B, F, Dq)
                rflows4 = jnp.broadcast_to(
                    flows[:, None, :, :], (B, nR, F, Dq)
                )
                rflows4, push_ovf = push_emits_ordered(rflows4, emits)
                succ = succ.at[
                    :, :, self.net_off : self.net_off + F * Dq
                ].set(rflows4.reshape(B, nR, F * Dq))
                poison = poison | (valid & push_ovf)
            elif self.kind == UNORDERED_NONDUPLICATING:
                pool = states[:, self.net_off : self.net_off + self.pool_size]
                P = self.pool_size
                npool, overflow = rank_sort_pool(pool, emits, nR)
                succ = succ.at[:, :, self.net_off : self.net_off + P].set(
                    npool
                )
                poison = poison | (valid & overflow)
            else:
                bits = states[:, self.net_off : self.net_off + self.nbits]
                nbits_arr = jnp.broadcast_to(
                    bits[:, None, :], (B, nR, self.nbits)
                )
                for j in range(self.max_emit):
                    em = emits[:, :, j]
                    emv = jnp.minimum(em, u(self.E - 1))
                    word = (emv // u(32)).astype(jnp.int32)
                    bit = u(1) << (emv % u(32))
                    sel_w = (
                        jnp.arange(self.nbits)[None, None, :]
                        == word[:, :, None]
                    )
                    add = jnp.where(
                        (em != EMPTY)[:, :, None] & sel_w,
                        bit[:, :, None],
                        u(0),
                    )
                    nbits_arr = nbits_arr | add
                succ = succ.at[
                    :, :, self.net_off : self.net_off + self.nbits
                ].set(nbits_arr)
            succ_parts.append(succ)
            valid_parts.append((valid | poison, poison))

        # Crash actions (ref: src/actor/model.rs:291-300, 431-437): mark the
        # actor crashed, clear its timers and pending random choices.
        if self.crash_slots:
            nC = self.n
            c_actor = jnp.arange(nC, dtype=jnp.int32)[None, :]
            already = (
                (crash_mask[:, None] >> c_actor.astype(u)) & u(1)
            ) != 0
            n_crashed = jnp.zeros((B,), jnp.int32)
            for i in range(nC):
                n_crashed = n_crashed + (
                    (crash_mask >> u(i)) & u(1)
                ).astype(jnp.int32)
            valid = (~already) & (n_crashed < self.max_crashes)[:, None]
            cbase = jnp.broadcast_to(states[:, None, :], (B, nC, self.lanes))
            nmask = crash_mask[:, None] | (u(1) << c_actor.astype(u))
            succ = cbase.at[:, :, self.crash_off].set(nmask)
            sel = jnp.arange(nC)[None, None, :] == c_actor[:, :, None]
            if self.has_timers:
                tl = states[:, self.timer_off : self.timer_off + nC]
                succ = succ.at[
                    :, :, self.timer_off : self.timer_off + nC
                ].set(jnp.where(sel, u(0), tl[:, None, :]))
            if self.has_randoms:
                # Crashed actors lose their pending choices: empty map id 0.
                succ = succ.at[
                    :, :, self.rand_off : self.rand_off + nC
                ].set(jnp.where(sel, u(0), rand_lanes[:, None, :]))
            succ_parts.append(succ)
            valid_parts.append((valid, jnp.zeros_like(valid)))

        if not succ_parts:  # degenerate: no possible actions at all
            return (
                jnp.broadcast_to(states[:, None, :], (B, 1, self.lanes)),
                jnp.zeros((B, 1), dtype=bool),
            )
        succs = jnp.concatenate(succ_parts, axis=1)
        valid = jnp.concatenate([v for v, _ in valid_parts], axis=1)
        poison = jnp.concatenate([p for _, p in valid_parts], axis=1)
        # Poison rows are terminal (without this they would expand through
        # clamped garbage gathers into phantom states).
        valid = valid & ~src_poison[:, None]
        poison = poison & ~src_poison[:, None]
        # Lazy-history mode: a successor whose history transition hit the
        # EMPTY sentinel is a (history, event) coverage gap — poison it too
        # (kind 4 below) so refinement can apply exactly that transition.
        hgap = None
        if self.track_history and self.best_effort:
            hgap = valid & (succs[:, :, self.hist_off] == u(EMPTY))
            poison = poison | hgap

        # -- poison materialization -------------------------------------------
        # A poisoned successor becomes a TERMINAL marker row (lane0 = EMPTY —
        # impossible for a real state, whose lane0 is a sid < maxS) that
        # ENCODES the uncovered pair, so incremental refinement can read the
        # exact (slot kind, eid/actor, tid/cid, sid) gaps back out of a
        # state dump: lane1 = kind << 24 | idx1, lane2 = idx2 << 16 | sid.
        # kind: 0 deliver / 1 timeout / 2 random; +16 when the pair IS
        # covered and the poison is a capacity overflow (pool/flow/emit) —
        # refinement must grow capacity, not the closure. The auto "lowering
        # coverage" property reports marker rows either way.
        if self.lanes >= 3:
            def seg_zero(width):
                z = jnp.zeros((B, width), u)
                return z, z, z, z, z

            segs = []  # (kind, idx1, idx2, sid) per part, same order/widths

            def deliver_seg(eid):
                # st/hev/sid were stashed by lookup_deliver — same gathers,
                # no re-derivation to drift out of sync.
                st = deliver_stash["st"]
                psid = deliver_stash["sid"]
                kind = jnp.where(st != _UNEXPLORED, u(16), u(0))
                return kind, eid, jnp.zeros_like(psid), psid, deliver_stash["hev"]

            if self.deliver_slots:
                segs.append(deliver_seg(deliver_eids))
                if self.drop_slots:
                    segs.append(seg_zero(self.deliver_slots))
            if self.timeout_slots:
                nT = len(self.timeout_slots)
                ta = jnp.broadcast_to(
                    jnp.asarray(
                        [i for i, _ in self.timeout_slots], u
                    )[None, :],
                    (B, nT),
                )
                tt = jnp.broadcast_to(
                    jnp.asarray(
                        [tid for _, tid in self.timeout_slots], u
                    )[None, :],
                    (B, nT),
                )
                tflat = (
                    jnp.arange(nT, dtype=jnp.int32)[None, :] * maxS
                    + t_sid_stash.astype(jnp.int32)
                )
                tst = jnp.take(T_state.reshape(-1), tflat)
                tkind = jnp.where(
                    tst != _UNEXPLORED, u(17), u(1)
                )
                thev = gated_take(T_hev, tflat, self.track_history)
                segs.append((tkind, ta, tt, t_sid_stash, thev))
            if self.random_slots:
                nR = len(self.random_slots)
                ra = jnp.broadcast_to(
                    jnp.asarray(
                        [i for i, _ in self.random_slots], u
                    )[None, :],
                    (B, nR),
                )
                maxR_, maxD_, maxC_, nJ_ = self._R_dims
                rflat = (
                    ra.astype(jnp.int32) * (maxC_ * maxS)
                    + r_cid_stash * maxS
                    + r_sid_stash.astype(jnp.int32)
                )
                rst = jnp.take(jnp.asarray(self._R[3]).reshape(-1), rflat)
                rhev = gated_take(
                    jnp.asarray(self._R[7]), rflat, self.track_history
                )
                # Covered pair + poison = capacity overflow (kind 2 | 16),
                # same convention as the deliver/timeout segments.
                rkind = jnp.where(rst != _UNEXPLORED, u(18), u(2))
                segs.append(
                    (rkind, ra, r_cid_stash.astype(u), r_sid_stash, rhev)
                )
            if self.crash_slots:
                segs.append(seg_zero(self.n))
            kind = jnp.concatenate([s[0] for s in segs], axis=1)
            idx1 = jnp.concatenate([s[1] for s in segs], axis=1)
            idx2 = jnp.concatenate([s[2] for s in segs], axis=1)
            psid = jnp.concatenate([s[3] for s in segs], axis=1)
            if hgap is not None:
                # A pure history gap (the reaction itself IS covered):
                # kind 4, idx1 = source hid, idx2 = hevent.
                hev = jnp.concatenate([s[4] for s in segs], axis=1)
                pure = hgap & ~jnp.concatenate(
                    [p for _, p in valid_parts], axis=1
                )
                src_hid = jnp.broadcast_to(
                    states[:, None, self.hist_off], (B, M)
                )
                kind = jnp.where(pure, u(4), kind)
                idx1 = jnp.where(pure, src_hid, idx1)
                idx2 = jnp.where(pure, hev, idx2)
                psid = jnp.where(pure, u(0), psid)
            prow = jnp.full((B, M, self.lanes), EMPTY, u)
            prow = prow.at[:, :, 1].set((kind << u(24)) | idx1)
            prow = prow.at[:, :, 2].set((idx2 << u(16)) | psid)
            succs = jnp.where(poison[:, :, None], prow, succs)
        else:
            # Too few lanes to carry a payload: uniform marker row (coverage
            # detection still works; refinement is unavailable).
            succs = jnp.where(poison[:, :, None], jnp.uint32(EMPTY), succs)

        assert succs.shape[1] == M, (succs.shape, M)
        return succs, valid

    # -- properties ------------------------------------------------------------

    def _build_properties(self):
        # View-helper tables register under counter-based names; the counter
        # resets here so each _finalize() re-registers the SAME names in the
        # same order (properties_fn is deterministic) and operand-aware
        # engines see stable pytree keys across refinement rounds.
        self._view_ct = 0
        view = LoweredView(self)
        props = list(self._properties_fn(view)) if self._properties_fn else []
        if self._boundary_fn is not None:
            self._tensor_boundary = self._boundary_fn(view)
        else:
            self._tensor_boundary = None

        def coverage(model, states):
            # lane0 == EMPTY is the poison marker (impossible for a real
            # state — lane0 is actor 0's sid, bounded by the closure size).
            return states[:, 0] != jnp.uint32(EMPTY)

        def shield(p: TensorProperty) -> TensorProperty:
            # User predicates read real state lanes; on a POISON marker row
            # those lanes hold the gap payload, so an unshielded ALWAYS
            # property can record a garbage counterexample fingerprint (and,
            # during refine_check's warm rounds, freeze the carried search
            # via the all-found early exit), a SOMETIMES property a garbage
            # witness, and an EVENTUALLY property a phantom observation.
            # Poison semantics belong to exactly one property — "lowering
            # coverage" below.
            cond = p.condition
            if p.expectation == Expectation.ALWAYS:
                shielded = lambda m, s: cond(m, s) | (  # noqa: E731
                    s[:, 0] == jnp.uint32(EMPTY)
                )
            else:
                shielded = lambda m, s: cond(m, s) & (  # noqa: E731
                    s[:, 0] != jnp.uint32(EMPTY)
                )
            return TensorProperty(p.expectation, p.name, shielded)

        props = [shield(p) for p in props]
        props.append(TensorProperty.always("lowering coverage", coverage))
        return props

    def properties(self):
        return list(self._props)

    def within_boundary(self, states):
        if self._tensor_boundary is None:
            return jnp.ones(states.shape[0], dtype=bool)
        # Poison rows bypass the boundary so they reach the coverage property.
        is_poison = states[:, 0] == jnp.uint32(EMPTY)
        return self._tensor_boundary(states) | is_poison


class LoweredView:
    """Helpers for writing vectorized properties/boundaries against a lowered
    model: plain Python predicates are evaluated over the (small) closure
    vocabularies at build time and become gather tables."""

    def __init__(self, lowered: LoweredActorModel):
        self.m = lowered

    def actor_feature(self, fn: Callable) -> Callable:
        """fn(actor_index, local_state) -> int. Returns states -> [B, n]."""
        m = self.m
        tab = np.zeros((m.n, m.maxS), np.int32)
        for i in range(m.n):
            for sid, st in enumerate(m.states[i]):
                tab[i, sid] = fn(i, st)
        name = m._reg(f"view{m._view_ct}", tab)
        m._view_ct += 1

        def eval_(states):
            sids = states[:, m.sid_off : m.sid_off + m.n].astype(jnp.int32)
            flat = jnp.arange(m.n, dtype=jnp.int32)[None, :] * m.maxS + sids
            return jnp.take(m._tbl(name).reshape(-1), flat)

        return eval_

    def history_pred(self, fn: Callable) -> Callable:
        """fn(history) -> bool. Returns states -> [B] bool."""
        m = self.m
        if not m.track_history:
            raise LoweringError("model has no history")
        # Dedup-first semantics (semantics/batch.py): the closure's history
        # vocabulary IS a post-dedup batch — resolve consistency-tester
        # verdicts in one batched call (canonical-class collapse + witness
        # guidance + parallel search) so predicates like `h.is_consistent()`
        # hit a warm cache. Feedback-gated: the batch fires only after the
        # first fn() that actually consults the plane — a structural
        # predicate that never reads verdicts costs zero speculative
        # searches (and non-tester histories skip at type-check cost).
        from ..semantics.batch import prefetch_verdicts
        from ..semantics.canonical import local_consultations

        tab = np.zeros(m._hd.shape[0], bool)  # padded to the hid capacity
        prefetched = False
        mark = local_consultations()
        for hid, h in enumerate(m.histories):
            tab[hid] = bool(fn(h))
            if not prefetched and local_consultations() != mark:
                prefetched = True
                prefetch_verdicts(m.histories[hid + 1:])
        name = m._reg(f"view{m._view_ct}", tab)
        m._view_ct += 1

        def eval_(states):
            return m._tbl(name)[states[:, m.hist_off].astype(jnp.int32)]

        return eval_

    def any_env(self, pred: Callable) -> Callable:
        """pred(envelope) -> bool over in-flight envelopes.
        Returns states -> [B] bool."""
        m = self.m
        match = np.zeros(m.E, bool)  # padded eids stay False
        for eid, e in enumerate(m.envs):
            match[eid] = bool(pred(e))
        if m.kind in (UNORDERED_NONDUPLICATING, ORDERED):
            name = m._reg(f"view{m._view_ct}", match)
        else:
            mask = np.zeros(m.nbits, np.uint32)
            for e in np.nonzero(match)[0]:
                mask[e // 32] |= np.uint32(1 << (e % 32))
            name = m._reg(f"view{m._view_ct}", mask)
        m._view_ct += 1

        def eval_(states):
            if m.kind == UNORDERED_NONDUPLICATING:
                pool = states[:, m.net_off : m.net_off + m.pool_size]
                safe = jnp.minimum(pool, jnp.uint32(m.E - 1)).astype(jnp.int32)
                ok = jnp.take(m._tbl(name), safe) & (pool != EMPTY)
                return jnp.any(ok, axis=1)
            if m.kind == ORDERED:
                # Deliverable envelopes = flow heads (iter_deliverable
                # semantics, matching host properties like "value chosen").
                flows = states[
                    :, m.net_off : m.net_off + m.F * m.flow_depth
                ].reshape(states.shape[0], m.F, m.flow_depth)
                head = flows[:, :, 0]
                safe = jnp.minimum(head, jnp.uint32(m.E - 1)).astype(jnp.int32)
                ok = jnp.take(m._tbl(name), safe) & (head != EMPTY)
                return jnp.any(ok, axis=1)
            bits = states[:, m.net_off : m.net_off + m.nbits]
            return jnp.any(bits & m._tbl(name) != 0, axis=1)

        return eval_


def lower_actor_model(model: ActorModel, **kwargs) -> LoweredActorModel:
    """Lower an `ActorModel` to a device-checkable `TensorModel`. See
    `LoweredActorModel` for options; `properties=` / `boundary=` take
    callables receiving a `LoweredView` and returning the vectorized
    `TensorProperty` list / boundary mask function."""
    return LoweredActorModel(model, **kwargs)


_INJECT_CHUNK = 4096


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def _inject_k(q_states, q_lo, q_hi, q_ebits, q_depth, tail, idx):
    """Re-enqueue existing queue rows: gather rows at `idx` (a fixed-width
    padded chunk) and write them contiguously at the tail. Padded entries
    land beyond the advanced tail (the caller adds only the true count), so
    they are dead rows; gathers read pre-update values (SSA), so donation
    is safe — the gathered region [0, tail) and the written region
    [tail, tail+chunk) are disjoint."""
    upd = lambda a: jax.lax.dynamic_update_slice(  # noqa: E731
        a,
        jnp.take(a, idx, axis=0),
        (tail,) + (0,) * (a.ndim - 1),
    )
    return upd(q_states), upd(q_lo), upd(q_hi), upd(q_ebits), upd(q_depth)


def _requeue_affected(search, lowered, rows, new_gaps) -> bool:
    """Warm-refinement injection: append the affected queue rows (with their
    original ebits/depth) at the carried search's tail so the next run()
    re-expands exactly them against the newly-realized tables. Returns False
    when injection is impossible (no affected rows — the mask can miss
    parents whose realizable pair sits behind another actor's lane — or no
    queue slack), telling the caller to fall back to a full fresh round."""
    if os.environ.get("REFINE_INJECT_ALL"):  # mask-completeness probe
        mask = rows[:, 0] != EMPTY
    else:
        mask = lowered.affected_rows_mask(rows, new_gaps)
    c = search._carry
    # Rows at [head, tail) are still pending — the continued run will expand
    # them against the new tables anyway; re-injecting them would balloon
    # the queue with duplicates (measured: paxos-3 tail grew to ~1M rows in
    # 12 rounds before this cut). Only already-popped rows need requeueing.
    mask[int(c.head):] = False
    idx = np.nonzero(mask)[0]
    updates = {}
    if idx.size:
        Q = c.q_lo.shape[0]
        tail = int(c.tail)
        n_chunks = -(-idx.size // _INJECT_CHUNK)
        if tail + n_chunks * _INJECT_CHUNK > Q:
            return False  # no queue slack; a full round is the sound fallback
        qs, ql, qh, qe, qd = (
            c.q_states, c.q_lo, c.q_hi, c.q_ebits, c.q_depth
        )
        for i in range(0, idx.size, _INJECT_CHUNK):
            chunk = idx[i : i + _INJECT_CHUNK]
            n = chunk.size
            padded = np.zeros(_INJECT_CHUNK, np.int32)
            padded[:n] = chunk
            qs, ql, qh, qe, qd = _inject_k(
                qs, ql, qh, qe, qd, jnp.int32(tail), jnp.asarray(padded)
            )
            tail += n
        updates = dict(
            q_states=qs, q_lo=ql, q_hi=qh, q_ebits=qe, q_depth=qd,
            tail=jnp.int32(tail),
        )
    elif int(c.head) >= int(c.tail):
        return False  # nothing to requeue and no backlog: full verify next
    # (Else: nothing popped needs requeueing, but the pending backlog makes
    # continuing worthwhile — it expands against the new tables.)
    #
    # Stale discoveries would freeze the continued search: with every
    # property bit recorded (e.g. a SOMETIMES witness plus the coverage
    # violation), the all-found early-exit stops every later warm run at its
    # first pop. Intermediate discoveries are never returned — the final
    # result always comes from a fresh full verification run — so clearing
    # them is pure bookkeeping, not semantics.
    search._carry = c._replace(
        discovered=jnp.uint32(0),
        disc_lo=jnp.zeros_like(c.disc_lo),
        disc_hi=jnp.zeros_like(c.disc_hi),
        **updates,
    )
    return True


def _clear_discoveries(search) -> None:
    """Drop the carried search's recorded discoveries (warm refinement
    only). A slab that early-exited on all-found would otherwise never run
    another step — with no user properties lowered, the coverage property
    ALONE satisfies all-found at the first poison pop, freezing every later
    slab at zero steps. Intermediate discoveries are never returned (the
    final result comes from a fresh full verification run)."""
    c = search._carry
    if c is not None:
        search._carry = c._replace(
            discovered=jnp.uint32(0),
            disc_lo=jnp.zeros_like(c.disc_lo),
            disc_hi=jnp.zeros_like(c.disc_hi),
        )


def refine_check(
    model: ActorModel,
    *,
    batch_size: int = 1024,
    table_log2: int = 16,
    seed_states: int = 2048,
    max_rounds: int = 64,
    progress=None,
    run_kwargs: Optional[dict] = None,
    engine: str = "resident",
    mesh=None,
    warm: bool = False,
    **lower_kwargs,
):
    """Incremental, device-search-driven lowering + check: the closure is
    grown by the search itself instead of by a host traversal.

    Start from a cheap best-effort seed closure, run the device search, read
    the uncovered (state, envelope) pairs back out of the poison payloads in
    the state dump, run the REAL handlers for exactly those pairs
    (`extend`), rebuild the tables, and repeat until a run is poison-free.
    Host work is proportional to the number of distinct reaction pairs the
    search actually reaches — NOT to the global state count, which is the
    difference from `closure="exact"` (one host handler call per pair vs one
    `next_state` per global edge). Rounds ≈ the protocol's reaction-dependency
    depth. With the resident engine, rounds reuse ONE compiled kernel: the
    baked tables are padded to capacity classes and passed as operands
    (`set_dyn_tables`), so a round only re-jits when a capacity class
    actually grows.

    Round structure (round 5): intermediate rounds are GAP-FINDING
    restarts that stop at the first POPPED poison row
    (finish_when=any_of(["lowering coverage"])) — by then a whole frontier
    layer of poison rows already sits in the queue for the vectorized scan,
    so exploring further only re-walks space the next round re-walks
    anyway. The EXACT result comes from a full verification search under
    the caller's own finish semantics once gaps stop surfacing (skipped
    when the terminal gap-finding round already exhausted the space and no
    finish policy would have stopped it earlier — finish policies are
    monotone in the discovery set). `warm=True` (resident engine only)
    instead CARRIES one search across extend() rounds, re-enqueueing only
    the already-popped rows that could realize a newly-covered pair
    (`affected_rows_mask`) in small budgeted slabs: poison rows stay in the
    carried table as phantom entries, which is sound because warm rounds
    exist only to find gaps — their counts are never returned. Measured on
    paxos-3 (ROUND5_NOTES.md): restart+coverage-exit 478 s vs warm >900 s
    (the affected-cone re-expansion loses once gap layers number in the
    thousands); warm wins on models with few layers relative to the
    space.

    Returns (final SearchResult, LoweredActorModel). Raises LoweringError on
    capacity overflows (grow pool_size/flow_depth/max_emit) or
    non-convergence; a table overflow raises the engine's RuntimeError
    (raise table_log2).

    `progress(slab_index, new_gap_count, result)` is called after each slab
    that surfaced new gaps; slab indices count budgeted warm slabs (many per
    extend era), and `result` is the INTERMEDIATE carried-search snapshot
    (its counts include phantom poison entries and re-expansions).
    `engine="sharded"` refines over the multi-chip engine (optionally on an
    explicit `mesh`) — the state dump unions the per-shard queues, so gaps
    surface from every chip.
    """
    if engine == "resident":
        if mesh is not None:
            raise ValueError(
                "mesh is only meaningful with engine='sharded' (a mesh "
                "passed to the single-chip resident engine would be "
                "silently ignored)"
            )
        from .resident import ResidentSearch

        def make_search(lowered):
            # donate_chunks: warm rounds dispatch many small budgeted slabs;
            # without donation every slab dispatch copies the whole
            # table+queue carry (hundreds of MB at paxos-3 sizes — the same
            # copy tax the 2pc-10 long-haul run measured at ~280 s/dispatch,
            # ROUND4_NOTES). The donation trade (no overflow-recovery carry)
            # is fine here: a refinement overflow just means re-running with
            # a bigger table_log2.
            return ResidentSearch(
                lowered, batch_size=batch_size, table_log2=table_log2,
                donate_chunks=True,
            )
    elif engine == "sharded":
        from ..parallel.sharded import ShardedSearch

        def make_search(lowered):
            return ShardedSearch(
                lowered,
                mesh=mesh,
                batch_size=batch_size,
                table_log2=table_log2,
            )
    else:
        raise ValueError("engine must be 'resident' or 'sharded'")

    lowered = LoweredActorModel(
        model, closure="seed", max_joint_states=seed_states, **lower_kwargs
    )
    rkw = dict(run_kwargs or {})
    rkw.setdefault("budget", 1 << 20)

    def shape_sig(m):
        """Everything that forces a rebuild when it changes: the state/action
        layout plus every operand-table shape. With the capacity-class
        padding (`_dyn_cap`) this is STABLE across most extend() rounds, so
        the resident engine's compiled kernels are reused round to round —
        the per-round re-jit was the dominant refinement cost (VERDICT r3
        next #8)."""
        return (
            m.lanes,
            m.max_actions,
            tuple(sorted((k, v.shape) for k, v in m._dyn_host.items())),
        )

    # Warm rounds (resident engine): intermediate rounds only need to FIND
    # gaps — their counts are never returned — so after extend() the carried
    # search is CONTINUED with just the affected rows re-enqueued
    # (affected_rows_mask) instead of re-searching the whole grown space
    # from scratch. Exact counts come from a fresh full verification run
    # once the incremental rounds stop surfacing new gaps; if that full run
    # still finds gaps (the affected-mask is an over-approximation of
    # realizability, not of reach-ability through OTHER parents' cones),
    # refinement resumes incrementally — convergence is unchanged because
    # every extend() realizes at least one previously-unrealized pair.
    # Carrying the search across extend() is sound HERE (unlike carrying
    # counts): stale poison rows stay as phantom table entries, which only
    # skews the intermediate counters nobody reads, and realized successors
    # have different fingerprints from the poison markers that announced
    # them. (VERDICT r4 next #6; the per-round full re-search was the
    # dominant refinement cost after the re-jit fix.)
    if warm and engine != "resident":
        raise ValueError(
            "warm=True requires engine='resident' (the sharded engine has "
            "no carried-search injection path)"
        )
    dbg = os.environ.get("REFINE_DEBUG")
    # Warm rounds run in SMALL budgeted slabs: a gap's poison row is visible
    # to the dump scan the moment it is GENERATED (enqueued), not when it is
    # popped, so scanning every few steps surfaces the next layer almost as
    # soon as it exists and extend() runs before the search wastes steps
    # exploring the rest of the frontier against stale tables. (Both
    # extremes measured worse on paxos-2: drain-to-completion warm rounds
    # re-explore each newly-opened subtree to the bottom before the next
    # layer is admitted — 66 s — and full restarts, the round-4 design, pay
    # the whole grown space per layer.)
    warm_budget = 24
    search = None
    sig = None
    done: set = set()
    full_run = True  # the first round is always a fresh full search
    extends = 0
    era_pairs: set = set()  # pairs extended since the last injection sweep
    scanned = 0  # incremental scan mark (queue rows below it are scanned)
    last_steps = -1  # progress marker for stuck-slab detection
    # The loop is unbounded in SLABS (gap-free drain slabs scale with state
    # count, like the single run() of a restart round); only EXTENDS are
    # capped by max_rounds — each one makes real progress (realizes at
    # least one previously-unrealized reaction pair).
    for rnd in itertools.count():
        if search is None:
            search = make_search(lowered)
            sig = shape_sig(lowered) if engine == "resident" else None
        if full_run:
            scanned = 0  # fresh searches restart the incremental scan
            last_steps = -1
            result = search.run(**rkw)
        elif warm:
            result = search.run(**{**rkw, "budget": warm_budget})
        else:
            # Restart-mode gap-finding round: stop at the FIRST popped
            # poison row — by then a whole frontier layer of poison rows
            # already sits in the queue for the scan (they surface when
            # GENERATED), so exploring further only re-walks space the
            # next round re-walks anyway. This is the principled form of
            # an accident the round-4 design relied on: garbage property
            # discoveries on poison rows tripped the all-found exit early;
            # shielding the properties (above) removed that throttle and
            # made each round pay the full poison-truncated space —
            # measured 597 s vs 472 s for round-4 on the same box/config
            # before this finish_when landed.
            scanned = 0
            last_steps = -1
            result = search.run(
                **{
                    **rkw,
                    "finish_when": HasDiscoveries.any_of(
                        ["lowering coverage"]
                    ),
                }
            )
        # Incremental poison scan: rows before `scanned` were already
        # scanned on a previous slab (injected rows are copies of real
        # rows, so injection cannot add poison below the scan mark).
        rows = search.dump_states(decode=False, raw=True, start=scanned)
        gaps, capacity, narrow = lowered.poison_scan(rows)
        scanned += rows.shape[0]
        if dbg:
            c = getattr(search, "_carry", None)
            ht = (
                (int(c.head), int(c.tail), int(c.steps))
                if c is not None and hasattr(c, "head")
                else None
            )
            print(
                f"[refine] rnd={rnd} full={full_run} rows={rows.shape[0]} "
                f"gaps={len(gaps)} done={len(done)} "
                f"gen={result.state_count} head/tail/steps={ht}",
                flush=True,
            )
        if narrow:
            raise LoweringError(
                "coverage gap without a decodable payload (model rows "
                "too narrow for refinement; use closure='exact')"
            )
        if capacity:
            raise LoweringError(
                f"capacity overflow during refinement ({len(capacity)} "
                f"poisoned transitions, e.g. {capacity[:3]}): raise "
                "pool_size / flow_depth / max_emit"
            )
        new_gaps = gaps - done
        if not new_gaps:
            if not warm and not full_run and result.complete:
                # The terminal gap-finding round exhausted the space with
                # no poison pop — its ONLY semantic difference from the
                # verification run is the finish_when override, and finish
                # policies are monotone in the discovery set: if the
                # final set would not have stopped the user's run, it never
                # matched mid-run either, so this result already IS the
                # exact answer and the full re-search can be skipped.
                fw = rkw.get("finish_when", HasDiscoveries.ALL)
                props_now = lowered.properties()
                names = set(result.discoveries)
                if not fw.matches(props_now, names) and len(names) < len(
                    props_now
                ):
                    return result, lowered
            if full_run:
                if "lowering coverage" in result.discoveries:
                    raise LoweringError(
                        "coverage counterexample without a decodable payload "
                        "(model rows too narrow for refinement; use "
                        "closure='exact')"
                    )
                return result, lowered
            if warm and not result.complete and result.steps != last_steps:
                last_steps = result.steps
                continue  # budgeted slab, gap-free so far: keep draining
            # (A slab that made NO progress — e.g. an early exit the carry
            # cannot move past — falls through to the injection sweep /
            # full verify instead of spinning on `continue`.)
            if era_pairs and warm:
                # Drained with tables realized mid-era: ONE injection sweep
                # re-enqueues the already-popped parents of every pair the
                # era extended (injecting per-extend measured ~3x duplicate
                # re-expansion on paxos-2 — most realizations matter to
                # frontier states that had not been popped yet, which the
                # ongoing search already expands against the new tables).
                all_rows = search.dump_states(decode=False, raw=True)
                injected = _requeue_affected(
                    search, lowered, all_rows, era_pairs
                )
                era_pairs = set()
                if injected:
                    last_steps = -1
                    continue
            # Warm search drained with no new gaps: fresh full search for
            # exact counts (and anything the affected-mask under-reached).
            search.reset()
            full_run = True
            continue
        if extends >= max_rounds:
            raise LoweringError(
                f"refinement did not converge in {max_rounds} rounds "
                f"(vocabulary at exit: {len(lowered.envs)} envelopes, "
                f"{[len(x) for x in lowered.states]} local states per "
                "actor). If these grew every round, the model's state space "
                "is likely UNBOUNDED from the search's point of view — "
                "refinement only bounds host work, not reachability; pass "
                "boundary= (a device-evaluable state bound) the way the "
                "search itself would need one, or use closure='exact' with "
                "closure_max_depth"
            )
        extends += 1
        if progress is not None:
            progress(rnd, len(new_gaps), result)
        done |= new_gaps
        era_pairs |= new_gaps
        lowered.extend(sorted(new_gaps))
        if warm:
            if shape_sig(lowered) != sig:
                # A capacity class grew: rebuild the kernels but transplant
                # the carry (queue/table shapes don't depend on the
                # vocabulary sizes — only the operand tables changed shape).
                carry = search._carry
                search = make_search(lowered)
                sig = shape_sig(lowered)
                search._carry = carry
            else:
                search.set_dyn_tables(lowered.dyn_tables())
            _clear_discoveries(search)
            if full_run:
                # A full run's carry is a clean drained search; continue it
                # warm (the injection sweep happens when slabs next drain).
                full_run = not _requeue_affected(
                    search, lowered,
                    search.dump_states(decode=False, raw=True), new_gaps,
                )
                era_pairs -= new_gaps
                if full_run:
                    search.reset()
        else:
            # Restart rounds (the default; measured FASTER than warm mode on
            # paxos-3, whose 14k+ reaction pairs make the affected-cone
            # re-exploration exceed the full-space restarts it avoids —
            # warm mode wins when gap layers are few relative to the space;
            # opt in with warm=True).
            if engine == "resident" and shape_sig(lowered) == sig:
                search.set_dyn_tables(lowered.dyn_tables())
                search.reset()
            else:
                search = make_search(lowered)
                sig = shape_sig(lowered) if engine == "resident" else None
            # Next round is a gap-finding restart (coverage-exit); the
            # full verification run happens once gaps stop surfacing.
            full_run = False

