"""Ordered reliable link (ORL): an actor wrapper layering per-flow ordered,
exactly-once delivery over the unreliable fabric
(ref: src/actor/ordered_reliable_link.rs).

The real UDP runtime (and the lossy/duplicating model networks) may drop,
duplicate, and reorder. `ActorWrapper` restores sanity the classic way:

- outgoing messages get a per-destination sequence number and are retained
  until acknowledged;
- a periodic resend timer retransmits everything unacknowledged;
- receivers ack every `Deliver` (including re-deliveries, so lost acks heal)
  but hand the payload to the wrapped actor only when the sequence number is
  exactly the next expected for that source — dropping duplicates and
  buffering nothing (out-of-order messages are simply re-sent later).

The wrapper is itself model-checkable: tests prove the delivery guarantees as
properties under a lossy duplicating network, the same strategy as the
reference's embedded tests (ref: src/actor/ordered_reliable_link.rs:215-325).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from . import Actor, Id, Out, model_timeout


# -- wire messages (ref: src/actor/ordered_reliable_link.rs:41-50) -------------


@dataclass(frozen=True)
class Deliver:
    seq: int
    msg: Any

    def __repr__(self):
        return f"Deliver({self.seq}, {self.msg!r})"


@dataclass(frozen=True)
class Ack:
    seq: int

    def __repr__(self):
        return f"Ack({self.seq})"


# -- timers --------------------------------------------------------------------


@dataclass(frozen=True)
class Resend:
    def __repr__(self):
        return "Resend"


@dataclass(frozen=True)
class InnerTimer:
    """A wrapped actor's own timer, namespaced away from `Resend`."""

    timer: Any

    def __repr__(self):
        return f"InnerTimer({self.timer!r})"


# -- state ---------------------------------------------------------------------


def _map_get(pairs: Tuple[tuple, ...], key, default):
    for k, v in pairs:
        if k == key:
            return v
    return default


def _map_set(pairs: Tuple[tuple, ...], key, value) -> Tuple[tuple, ...]:
    out = tuple((k, v) for k, v in pairs if k != key) + ((key, value),)
    return tuple(sorted(out, key=lambda kv: kv[0]))


@dataclass(frozen=True)
class StateWrapper:
    """ORL bookkeeping around the wrapped actor's state
    (ref: src/actor/ordered_reliable_link.rs:55-67).

    All maps are canonical sorted tuples so states fingerprint stably."""

    wrapped: Any
    next_send_seq: Tuple[tuple, ...] = ()  # (dst, next seq) sorted
    pending_ack: Tuple[tuple, ...] = ()  # ((dst, seq), msg) in send order
    last_delivered: Tuple[tuple, ...] = ()  # (src, last seq) sorted

    def __repr__(self):
        return (
            f"ORL {{ wrapped: {self.wrapped!r}, pending: "
            f"{[k for k, _ in self.pending_ack]!r}, "
            f"delivered: {dict(self.last_delivered)!r} }}"
        )


class ActorWrapper(Actor):
    """Wraps `inner`, translating its sends/timers through the link
    (ref: src/actor/ordered_reliable_link.rs:78-213)."""

    def __init__(self, inner: Actor, resend_interval=None):
        self.inner = inner
        self.resend_interval = resend_interval or model_timeout()

    def name(self) -> str:
        inner = self.inner.name()
        return f"ORL({inner})" if inner else "ORL"

    # -- helpers ---------------------------------------------------------------

    def _translate(self, state: StateWrapper, inner_out: Out, out: Out):
        """Wrap the inner actor's outgoing commands: sends become sequenced
        Delivers retained until acked; timers are namespaced."""
        from . import CancelTimer, ChooseRandom, Send, SetTimer

        next_send_seq = state.next_send_seq
        pending = state.pending_ack
        for c in inner_out:
            if isinstance(c, Send):
                seq = _map_get(next_send_seq, c.dst, 1)
                next_send_seq = _map_set(next_send_seq, c.dst, seq + 1)
                pending = pending + (((c.dst, seq), c.msg),)
                out.send(c.dst, Deliver(seq, c.msg))
            elif isinstance(c, SetTimer):
                out.set_timer(InnerTimer(c.timer), c.duration)
            elif isinstance(c, CancelTimer):
                out.cancel_timer(InnerTimer(c.timer))
            elif isinstance(c, ChooseRandom):
                out.commands.append(c)
            else:
                out.commands.append(c)
        return StateWrapper(
            wrapped=state.wrapped,
            next_send_seq=next_send_seq,
            pending_ack=pending,
            last_delivered=state.last_delivered,
        )

    # -- Actor interface -------------------------------------------------------

    def on_start(self, id: Id, out: Out):
        inner_out = Out()
        wrapped = self.inner.on_start(id, inner_out)
        out.set_timer(Resend(), self.resend_interval)
        state = StateWrapper(wrapped=wrapped)
        return self._translate(state, inner_out, out)

    def on_msg(self, id: Id, state: StateWrapper, src: Id, msg, out: Out):
        if isinstance(msg, Ack):
            key = (src, msg.seq)
            if not any(k == key for k, _ in state.pending_ack):
                return None
            return StateWrapper(
                wrapped=state.wrapped,
                next_send_seq=state.next_send_seq,
                pending_ack=tuple(
                    (k, m) for k, m in state.pending_ack if k != key
                ),
                last_delivered=state.last_delivered,
            )
        if isinstance(msg, Deliver):
            # Always ack — a lost ack otherwise wedges the sender forever.
            out.send(src, Ack(msg.seq))
            expected = _map_get(state.last_delivered, src, 0) + 1
            if msg.seq != expected:
                return None  # duplicate or out-of-order: dropped, will resend
            inner_out = Out()
            new_wrapped = self.inner.on_msg(
                id, state.wrapped, src, msg.msg, inner_out
            )
            mid = StateWrapper(
                wrapped=state.wrapped if new_wrapped is None else new_wrapped,
                next_send_seq=state.next_send_seq,
                pending_ack=state.pending_ack,
                last_delivered=_map_set(state.last_delivered, src, msg.seq),
            )
            return self._translate(mid, inner_out, out)
        return None

    def on_timeout(self, id: Id, state: StateWrapper, timer, out: Out):
        if isinstance(timer, Resend):
            out.set_timer(Resend(), self.resend_interval)
            for (dst, seq), msg in state.pending_ack:
                out.send(dst, Deliver(seq, msg))
            return None
        if isinstance(timer, InnerTimer):
            inner_out = Out()
            new_wrapped = self.inner.on_timeout(
                id, state.wrapped, timer.timer, inner_out
            )
            mid = StateWrapper(
                wrapped=state.wrapped if new_wrapped is None else new_wrapped,
                next_send_seq=state.next_send_seq,
                pending_ack=state.pending_ack,
                last_delivered=state.last_delivered,
            )
            return self._translate(mid, inner_out, out)
        return None

    def on_random(self, id: Id, state: StateWrapper, random, out: Out):
        inner_out = Out()
        new_wrapped = self.inner.on_random(id, state.wrapped, random, inner_out)
        mid = StateWrapper(
            wrapped=state.wrapped if new_wrapped is None else new_wrapped,
            next_send_seq=state.next_send_seq,
            pending_ack=state.pending_ack,
            last_delivered=state.last_delivered,
        )
        return self._translate(mid, inner_out, out)
