"""Real-world actor execution over UDP (ref: src/actor/spawn.rs).

One thread per actor; each binds the UDP socket encoded in its `Id`, JSON-serdes
messages, and multiplexes socket reads with a timer wheel (`next_interrupts`:
interrupt → deadline) via socket timeouts, mirroring the reference's event loop
(ref: src/actor/spawn.rs:64-154). Model-checked `choose_random` commands become
delayed interrupts resolved with a real RNG (ref: src/actor/spawn.rs:163-232).

Message serde: by default messages are encoded as JSON with a `{"__type__":
ClassName, ...fields}` convention for dataclasses (plus native JSON scalars /
lists). Pass a `msg_types` registry (class list) for decoding, or override
`serialize`/`deserialize` entirely — the reference likewise takes explicit
serde functions.
"""

from __future__ import annotations

import dataclasses
import json
import random
import socket
import threading
import time
from typing import Callable, Iterable, Optional, Tuple

from . import Actor, CancelTimer, ChooseRandom, Id, Out, Send, SetTimer

_MAX_DATAGRAM = 65_507


def _encode_value(v):
    # Tagged forms keep every container/identity type EXACT through the
    # round trip (the reference's serde_json on typed structs does the same,
    # ref: src/actor/spawn.rs:64-130): tuples are not degraded to lists,
    # frozensets/sets survive, and Id stays Id.
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {
            "__type__": type(v).__name__,
            **{
                f.name: _encode_value(getattr(v, f.name))
                for f in dataclasses.fields(v)
            },
        }
    if isinstance(v, Id):
        return {"__id__": int(v)}
    if isinstance(v, bool) or v is None or isinstance(v, (str, float)):
        return v
    if isinstance(v, int):
        return v
    if isinstance(v, tuple):
        return {"__tuple__": [_encode_value(x) for x in v]}
    if isinstance(v, list):
        return [_encode_value(x) for x in v]
    if isinstance(v, (set, frozenset)):
        items = sorted((_encode_value(x) for x in v), key=json.dumps)
        tag = "__frozenset__" if isinstance(v, frozenset) else "__set__"
        return {tag: items}
    if isinstance(v, dict):
        # Insertion order IS part of dict semantics (the repo uses dicts as
        # insertion-ordered sets) — encode pairs in order, no sorting.
        return {
            "__dict__": [
                [_encode_value(k), _encode_value(x)] for k, x in v.items()
            ]
        }
    raise TypeError(f"cannot JSON-encode message part {v!r}; pass custom serde")


def make_json_serde(msg_types: Iterable[type] = ()):
    """Default JSON codec: dataclasses tagged by class name; tuples, sets,
    frozensets, dicts, and `Id` carry explicit tags so every message value
    round-trips EXACTLY (lww/vector-clock-style tuple- and set-valued
    messages included)."""
    registry = {t.__name__: t for t in msg_types}

    def serialize(msg) -> bytes:
        return json.dumps(_encode_value(msg)).encode("utf-8")

    def _decode(v):
        if isinstance(v, dict):
            if "__type__" in v:
                cls = registry.get(v["__type__"])
                if cls is None:
                    raise ValueError(
                        f"unknown message type {v['__type__']!r}"
                    )
                return cls(
                    **{
                        f.name: _decode(v[f.name])
                        for f in dataclasses.fields(cls)
                        if f.name in v
                    }
                )
            if "__id__" in v:
                return Id(v["__id__"])
            if "__tuple__" in v:
                return tuple(_decode(x) for x in v["__tuple__"])
            if "__frozenset__" in v:
                return frozenset(_decode(x) for x in v["__frozenset__"])
            if "__set__" in v:
                return {_decode(x) for x in v["__set__"]}
            if "__dict__" in v:
                return {_decode(k): _decode(x) for k, x in v["__dict__"]}
            return v
        if isinstance(v, list):
            return [_decode(x) for x in v]
        return v

    def deserialize(data: bytes):
        return _decode(json.loads(data.decode("utf-8")))

    return serialize, deserialize


class _ActorRuntime(threading.Thread):
    def __init__(self, id: Id, actor: Actor, serialize, deserialize, stop_event):
        super().__init__(name=f"actor-{int(id)}", daemon=True)
        self.id = Id(id)
        self.actor = actor
        self.serialize = serialize
        self.deserialize = deserialize
        self.stop_event = stop_event
        self.rng = random.Random()
        # interrupt key -> (deadline, payload); keys are ("timer", timer) or
        # ("random", key) (ref: src/actor/spawn.rs:156-160).
        self.next_interrupts: dict = {}
        ip, port = self.id.to_addr()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))

    def _on_command(self, cmd) -> None:
        """ref: src/actor/spawn.rs:163-232"""
        if isinstance(cmd, Send):
            ip, port = Id(cmd.dst).to_addr()
            try:
                self.sock.sendto(self.serialize(cmd.msg), (ip, port))
            except OSError:
                pass  # unreachable peers are dropped datagrams, like UDP itself
        elif isinstance(cmd, SetTimer):
            lo, hi = cmd.duration
            delay = self.rng.uniform(lo, hi) if hi > lo else lo
            self.next_interrupts[("timer", cmd.timer)] = (
                time.monotonic() + delay,
                cmd.timer,
            )
        elif isinstance(cmd, CancelTimer):
            self.next_interrupts.pop(("timer", cmd.timer), None)
        elif isinstance(cmd, ChooseRandom):
            if not cmd.choices:
                self.next_interrupts.pop(("random", cmd.key), None)
            else:
                # Random choices become near-immediate interrupts resolved
                # with a real RNG.
                self.next_interrupts[("random", cmd.key)] = (
                    time.monotonic() + self.rng.uniform(0.0, 0.01),
                    self.rng.choice(cmd.choices),
                )

    def run(self) -> None:
        out = Out()
        state = self.actor.on_start(self.id, out)
        for cmd in out:
            self._on_command(cmd)

        while not self.stop_event.is_set():
            # Wait until the next interrupt (or a message arrives).
            timeout = 0.5
            if self.next_interrupts:
                nearest = min(d for d, _ in self.next_interrupts.values())
                timeout = max(0.0, min(timeout, nearest - time.monotonic()))
            self.sock.settimeout(timeout if timeout > 0 else 0.000001)
            out = Out()
            try:
                data, addr = self.sock.recvfrom(_MAX_DATAGRAM)
                try:
                    msg = self.deserialize(data)
                except Exception:
                    continue  # malformed datagrams are ignored
                src = Id.from_addr(addr[0], addr[1])
                next_state = self.actor.on_msg(self.id, state, src, msg, out)
                if next_state is not None:
                    state = next_state
            except socket.timeout:
                now = time.monotonic()
                due = [
                    (k, payload)
                    for k, (deadline, payload) in self.next_interrupts.items()
                    if deadline <= now
                ]
                for key, payload in due:
                    del self.next_interrupts[key]
                    if key[0] == "timer":
                        next_state = self.actor.on_timeout(
                            self.id, state, payload, out
                        )
                    else:
                        next_state = self.actor.on_random(
                            self.id, state, payload, out
                        )
                    if next_state is not None:
                        state = next_state
            except OSError:
                break
            for cmd in out:
                self._on_command(cmd)
        self.sock.close()


def spawn(
    actors: Iterable[Tuple[Id, Actor]],
    serialize: Optional[Callable] = None,
    deserialize: Optional[Callable] = None,
    msg_types: Iterable[type] = (),
    block: bool = True,
):
    """Run actors for real over UDP (ref: src/actor/spawn.rs:64-154).

    Each (id, actor) pair gets a thread bound to the socket address encoded in
    its id. With `block=True` (default) this joins forever (ctrl-C to stop);
    otherwise returns (threads, stop_event) for the caller to manage.
    """
    if serialize is None or deserialize is None:
        default_ser, default_de = make_json_serde(msg_types)
        serialize = serialize or default_ser
        deserialize = deserialize or default_de
    stop_event = threading.Event()
    threads = [
        _ActorRuntime(id, actor, serialize, deserialize, stop_event)
        for id, actor in actors
    ]
    for t in threads:
        t.start()
    if not block:
        return threads, stop_event
    try:
        while any(t.is_alive() for t in threads):
            time.sleep(0.5)
    except KeyboardInterrupt:
        stop_event.set()
    return threads, stop_event
