"""Actor test fixtures (ref: src/actor/actor_test_util.rs).

The ping-pong pair exercises the full ActorModel state-space shape: message
counters, history recording, boundary, and all three property expectations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..core.model import Expectation
from . import Actor, Id, Out
from .model import ActorModel


@dataclass(frozen=True)
class Ping:
    value: int

    def __repr__(self):
        return f"Ping({self.value})"


@dataclass(frozen=True)
class Pong:
    value: int

    def __repr__(self):
        return f"Pong({self.value})"


@dataclass
class PingPongActor(Actor):
    """ref: src/actor/actor_test_util.rs:8-51"""

    serve_to: Optional[Id] = None

    def on_start(self, id: Id, out: Out):
        if self.serve_to is not None:
            out.send(self.serve_to, Ping(0))
        return 0

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if isinstance(msg, Pong) and state == msg.value:
            out.send(src, Ping(msg.value + 1))
            return state + 1
        if isinstance(msg, Ping) and state == msg.value:
            out.send(src, Pong(msg.value))
            return state + 1
        return None


@dataclass
class PingPongCfg:
    """ref: src/actor/actor_test_util.rs:53-126"""

    maintains_history: bool = False
    max_nat: int = 1

    def into_model(self) -> ActorModel:
        def record_in(cfg, history, env):
            if cfg.maintains_history:
                msg_in, msg_out = history
                return (msg_in + 1, msg_out)
            return None

        def record_out(cfg, history, env):
            if cfg.maintains_history:
                msg_in, msg_out = history
                return (msg_in, msg_out + 1)
            return None

        return (
            ActorModel.new(self, (0, 0))
            .actor(PingPongActor(serve_to=Id(1)))
            .actor(PingPongActor(serve_to=None))
            .record_msg_in(record_in)
            .record_msg_out(record_out)
            .with_within_boundary(
                lambda cfg, state: all(c <= cfg.max_nat for c in state.actor_states)
            )
            .property(
                Expectation.ALWAYS,
                "delta within 1",
                lambda m, s: max(s.actor_states) - min(s.actor_states) <= 1,
            )
            .property(
                Expectation.SOMETIMES,
                "can reach max",
                lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
            )
            .property(
                Expectation.EVENTUALLY,
                "must reach max",
                lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
            )
            .property(
                Expectation.EVENTUALLY,
                "must exceed max",  # falsifiable due to the boundary
                lambda m, s: any(c == m.cfg.max_nat + 1 for c in s.actor_states),
            )
            .property(
                Expectation.ALWAYS,
                "#in <= #out",
                lambda m, s: s.history[0] <= s.history[1],
            )
            .property(
                Expectation.EVENTUALLY,
                "#out <= #in + 1",
                lambda m, s: s.history[1] <= s.history[0] + 1,
            )
        )
