"""The model-level communication fabric (ref: src/actor/network.rs).

Three pluggable delivery semantics:

- ``unordered_duplicating`` — a set of in-flight envelopes plus the last
  delivered envelope; delivery does NOT remove from the set, so messages race
  and can be redelivered. Tracking `last_msg` makes a redelivery that doesn't
  change actor state still produce a distinct fingerprint
  (ref: src/actor/network.rs:52, 224-228). Dropping means "never deliver
  again" (removes from the set).
- ``unordered_nonduplicating`` — a multiset (envelope → count); delivery/drop
  decrements.
- ``ordered`` — per directed (src, dst) flow FIFO queues; only the head of each
  flow is deliverable. Empty flows are deleted to keep the state canonical
  (ref: src/actor/network.rs:243-265).

Networks here are IMMUTABLE values: `send`/`on_deliver`/`on_drop` return new
networks. That matches this framework's immutable-state convention and makes
states safely shareable across the frontier without deep copies.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Optional


@dataclass(frozen=True)
class Envelope:
    """Source, destination, and message (ref: src/actor/network.rs:24-29)."""

    src: Any  # Id
    dst: Any  # Id
    msg: Any


UNORDERED_DUPLICATING = "unordered_duplicating"
UNORDERED_NONDUPLICATING = "unordered_nonduplicating"
ORDERED = "ordered"


class Network:
    __slots__ = ("kind", "_data", "last_msg", "_hash")

    def __init__(self, kind: str, data: dict, last_msg: Optional[Envelope] = None):
        self.kind = kind
        # unordered_duplicating: {Envelope: None}   (insertion-ordered set)
        # unordered_nonduplicating: {Envelope: count}
        # ordered: {(src, dst): tuple(msgs)}
        self._data = data
        self.last_msg = last_msg

    # -- constructors (ref: src/actor/network.rs:84-137) -----------------------

    @staticmethod
    def new_unordered_duplicating(envelopes=()) -> "Network":
        n = Network(UNORDERED_DUPLICATING, {})
        for env in envelopes:
            n = n.send(env)
        return n

    @staticmethod
    def new_unordered_duplicating_with_last_msg(
        envelopes=(), last_msg: Optional[Envelope] = None
    ) -> "Network":
        n = Network.new_unordered_duplicating(envelopes)
        return Network(UNORDERED_DUPLICATING, n._data, last_msg)

    @staticmethod
    def new_unordered_nonduplicating(envelopes=()) -> "Network":
        n = Network(UNORDERED_NONDUPLICATING, {})
        for env in envelopes:
            n = n.send(env)
        return n

    @staticmethod
    def new_ordered(envelopes=()) -> "Network":
        n = Network(ORDERED, {})
        for env in envelopes:
            n = n.send(env)
        return n

    @staticmethod
    def names() -> list[str]:
        """CLI-selectable names (ref: src/actor/network.rs:140-166)."""
        return [ORDERED, UNORDERED_DUPLICATING, UNORDERED_NONDUPLICATING]

    @staticmethod
    def from_str(s: str) -> "Network":
        """ref: src/actor/network.rs:318-331"""
        if s == ORDERED:
            return Network.new_ordered()
        if s == UNORDERED_DUPLICATING:
            return Network.new_unordered_duplicating()
        if s == UNORDERED_NONDUPLICATING:
            return Network.new_unordered_nonduplicating()
        raise ValueError(f"unable to parse network name: {s}")

    # -- iteration -------------------------------------------------------------

    def iter_deliverable(self) -> Iterator[Envelope]:
        """Distinct deliverable envelopes; for ordered networks only flow heads
        (ref: src/actor/network.rs:180-190, 414-440)."""
        if self.kind == ORDERED:
            for (src, dst) in sorted(self._data):
                msgs = self._data[(src, dst)]
                yield Envelope(src, dst, msgs[0])
        else:
            yield from self._data.keys()

    def iter_all(self) -> Iterator[Envelope]:
        """Every in-flight envelope including multiset/flow repeats
        (ref: src/actor/network.rs:169-177, 350-412)."""
        if self.kind == UNORDERED_DUPLICATING:
            yield from self._data.keys()
        elif self.kind == UNORDERED_NONDUPLICATING:
            for env, count in self._data.items():
                for _ in range(count):
                    yield env
        else:
            for (src, dst) in sorted(self._data):
                for msg in self._data[(src, dst)]:
                    yield Envelope(src, dst, msg)

    def __len__(self) -> int:
        if self.kind == UNORDERED_DUPLICATING:
            return len(self._data)
        if self.kind == UNORDERED_NONDUPLICATING:
            return sum(self._data.values())
        return sum(len(msgs) for msgs in self._data.values())

    # -- mutation (functional; ref: src/actor/network.rs:203-315) --------------

    def send(self, envelope: Envelope) -> "Network":
        data = dict(self._data)
        if self.kind == UNORDERED_DUPLICATING:
            data[envelope] = None
        elif self.kind == UNORDERED_NONDUPLICATING:
            data[envelope] = data.get(envelope, 0) + 1
        else:
            key = (envelope.src, envelope.dst)
            data[key] = data.get(key, ()) + (envelope.msg,)
        return Network(self.kind, data, self.last_msg)

    def on_deliver(self, envelope: Envelope) -> "Network":
        if self.kind == UNORDERED_DUPLICATING:
            # Delivery does not consume; remember the last delivery so
            # state-preserving redeliveries still change the fingerprint.
            return Network(self.kind, self._data, envelope)
        if self.kind == UNORDERED_NONDUPLICATING:
            return self._remove_one(envelope)
        return self._remove_from_flow(envelope)

    def on_drop(self, envelope: Envelope) -> "Network":
        if self.kind == UNORDERED_DUPLICATING:
            data = dict(self._data)
            data.pop(envelope, None)
            return Network(self.kind, data, self.last_msg)
        if self.kind == UNORDERED_NONDUPLICATING:
            return self._remove_one(envelope)
        return self._remove_from_flow(envelope)

    def _remove_one(self, envelope: Envelope) -> "Network":
        count = self._data.get(envelope)
        if not count:
            raise KeyError(f"envelope not found: {envelope!r}")
        data = dict(self._data)
        if count == 1:
            del data[envelope]
        else:
            data[envelope] = count - 1
        return Network(self.kind, data, self.last_msg)

    def _remove_from_flow(self, envelope: Envelope) -> "Network":
        key = (envelope.src, envelope.dst)
        msgs = self._data.get(key)
        if msgs is None:
            raise KeyError(f"flow not found: src={envelope.src!r} dst={envelope.dst!r}")
        try:
            i = msgs.index(envelope.msg)
        except ValueError:
            raise KeyError(f"message not found in flow: {envelope.msg!r}") from None
        data = dict(self._data)
        remaining = msgs[:i] + msgs[i + 1 :]
        if remaining:
            data[key] = remaining
        else:
            del data[key]  # canonicalize: no empty flows
        return Network(self.kind, data, self.last_msg)

    def __rewrite__(self, plan) -> "Network":
        """Apply a symmetry rewrite plan to every envelope
        (ref: src/actor/network.rs:333-348)."""
        from ..symmetry import rewrite

        if self.kind == ORDERED:
            n = Network(self.kind, {})
            for (src, dst) in sorted(self._data):
                key = (plan.rewrite_id(src), plan.rewrite_id(dst))
                n._data[key] = tuple(rewrite(m, plan) for m in self._data[(src, dst)])
            return n
        n = Network(self.kind, {})
        for env in self._data:
            new_env = Envelope(
                plan.rewrite_id(env.src), plan.rewrite_id(env.dst), rewrite(env.msg, plan)
            )
            if self.kind == UNORDERED_DUPLICATING:
                n._data[new_env] = None
            else:
                n._data[new_env] = n._data.get(new_env, 0) + self._data[env]
        if self.kind == UNORDERED_DUPLICATING:
            n.last_msg = (
                None
                if self.last_msg is None
                else Envelope(
                    plan.rewrite_id(self.last_msg.src),
                    plan.rewrite_id(self.last_msg.dst),
                    rewrite(self.last_msg.msg, plan),
                )
            )
        return n

    # -- identity --------------------------------------------------------------

    def __stable_encode__(self):
        if self.kind == UNORDERED_DUPLICATING:
            return (self.kind, frozenset(self._data.keys()), self.last_msg)
        if self.kind == UNORDERED_NONDUPLICATING:
            return (self.kind, self._data)
        return (self.kind, self._data)

    def __eq__(self, other) -> bool:
        if not isinstance(other, Network) or self.kind != other.kind:
            return False
        if self.kind == UNORDERED_DUPLICATING:
            return (
                set(self._data.keys()) == set(other._data.keys())
                and self.last_msg == other.last_msg
            )
        return self._data == other._data

    def __hash__(self) -> int:
        # Networks are functional (every mutation returns a new Network), so
        # the deep hash over the frozenset is computed once and cached.
        h = getattr(self, "_hash", None)
        if h is None:
            if self.kind == UNORDERED_DUPLICATING:
                h = hash((self.kind, frozenset(self._data.keys()), self.last_msg))
            else:
                h = hash((self.kind, frozenset(self._data.items())))
            self._hash = h
        return h

    def __repr__(self) -> str:
        if self.kind == UNORDERED_DUPLICATING:
            return (
                f"Network.unordered_duplicating({list(self._data.keys())!r}, "
                f"last_msg={self.last_msg!r})"
            )
        if self.kind == UNORDERED_NONDUPLICATING:
            return f"Network.unordered_nonduplicating({self._data!r})"
        return f"Network.ordered({self._data!r})"
