"""Sequence-diagram rendering for actor-model paths (ref: src/actor/model.rs:551-754).

Original implementation (not a port of the reference's drawing code): vertical
lifelines per actor, one row per path step, arrows for deliveries, self-loops
for timeouts/crashes/random selections. Returned as an SVG string for the
Explorer UI.
"""

from __future__ import annotations

from html import escape
from typing import Optional

LANE_W = 140
ROW_H = 36
TOP = 40
CHAR_W = 7


def sequence_diagram(model, path) -> Optional[str]:
    from .model import Crash, Deliver, DropEnv, SelectRandom, Timeout

    pairs = path.into_pairs() if hasattr(path, "into_pairs") else list(path)
    steps = [(s, a) for s, a in pairs if a is not None]
    n = len(model.actors)
    if n == 0:
        return None
    width = LANE_W * n + 40
    height = TOP + ROW_H * (len(steps) + 1) + 20

    def lane_x(i: int) -> int:
        return 20 + LANE_W * i + LANE_W // 2

    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" height="{height}" '
        f'font-family="monospace" font-size="12">',
        '<defs><marker id="arrow" markerWidth="10" markerHeight="10" refX="9" refY="3" '
        'orient="auto"><path d="M0,0 L9,3 L0,6 z"/></marker></defs>',
    ]
    for i, actor in enumerate(model.actors):
        name = actor.name() or f"Actor {i}"
        x = lane_x(i)
        parts.append(
            f'<text x="{x}" y="20" text-anchor="middle" font-weight="bold">'
            f"{escape(name)} (Id({i}))</text>"
        )
        parts.append(
            f'<line x1="{x}" y1="{TOP - 10}" x2="{x}" y2="{height - 10}" '
            'stroke="#bbb" stroke-dasharray="4,3"/>'
        )

    for row, (_state, action) in enumerate(steps):
        y = TOP + ROW_H * (row + 1)
        if isinstance(action, Deliver):
            x1, x2 = lane_x(int(action.src)), lane_x(int(action.dst))
            if x1 == x2:
                x2 = x1 + 24
            label = escape(repr(action.msg))
            parts.append(
                f'<line x1="{x1}" y1="{y}" x2="{x2}" y2="{y}" stroke="#333" '
                'marker-end="url(#arrow)"/>'
            )
            mid = (x1 + x2) // 2
            parts.append(
                f'<text x="{mid}" y="{y - 5}" text-anchor="middle">{label}</text>'
            )
        elif isinstance(action, DropEnv):
            env = action.envelope
            x1, x2 = lane_x(int(env.src)), lane_x(int(env.dst))
            if x1 == x2:
                x2 = x1 + 24
            parts.append(
                f'<line x1="{x1}" y1="{y}" x2="{x2}" y2="{y}" stroke="#c00" '
                'stroke-dasharray="5,3" marker-end="url(#arrow)"/>'
            )
            mid = (x1 + x2) // 2
            parts.append(
                f'<text x="{mid}" y="{y - 5}" text-anchor="middle" fill="#c00">'
                f"DROP {escape(repr(env.msg))}</text>"
            )
        else:
            if isinstance(action, Timeout):
                actor_i, label = int(action.id), f"timeout {action.timer!r}"
            elif isinstance(action, Crash):
                actor_i, label = int(action.id), "CRASH"
            elif isinstance(action, SelectRandom):
                actor_i, label = int(action.actor), f"random {action.random!r}"
            else:
                continue
            x = lane_x(actor_i)
            parts.append(
                f'<path d="M{x},{y - 8} C{x + 28},{y - 8} {x + 28},{y + 8} {x},{y + 8}" '
                'fill="none" stroke="#06c" marker-end="url(#arrow)"/>'
            )
            parts.append(
                f'<text x="{x + 32}" y="{y + 4}" fill="#06c">{escape(label)}</text>'
            )
    parts.append("</svg>")
    return "".join(parts)
