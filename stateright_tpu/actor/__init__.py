"""Actor framework (ref: src/actor.rs).

An `Actor` is an event-driven state machine: it initializes via `on_start` and
reacts to messages/timeouts/random choices, emitting `Out` commands. Actor
systems can be model checked (`ActorModel` lowers them into the generic `Model`
interface) or executed for real over UDP (`spawn`).

Handler convention (the Python analogue of the reference's `Cow<State>`
copy-on-write, ref: src/actor.rs:270-287): handlers receive the current state
as an immutable value and RETURN the next state, or `None` to signal "state
unchanged". A handler that returns `None` and emits no commands is a no-op,
which `ActorModel` elides from the state space (ref: src/actor/model.rs:345-347).

Heterogeneous actor systems need no special machinery here: the reference's
`choice::Choice` exists to give Rust a type for mixed actor lists
(ref: src/actor.rs:391-548); in Python `ActorModel.actor(...)` accepts any mix
of Actor implementations directly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Tuple


class Id(int):
    """Actor identity: an index for model checking, an encoded IPv4+port for
    spawned actors (ref: src/actor.rs:109-157, src/actor/spawn.rs:10-34)."""

    def __repr__(self) -> str:
        return f"Id({int(self)})"

    @staticmethod
    def vec_from(ids: Iterable) -> list["Id"]:
        return [Id(i) for i in ids]

    @staticmethod
    def from_addr(ip: str, port: int) -> "Id":
        """Encode an IPv4 address + port into an Id (spawn runtime)."""
        parts = [int(p) for p in ip.split(".")]
        ip_num = (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]
        return Id((ip_num << 16) | port)

    def to_addr(self) -> Tuple[str, int]:
        """Decode an Id into (ip, port) (spawn runtime)."""
        v = int(self)
        port = v & 0xFFFF
        ip_num = v >> 16
        ip = f"{(ip_num >> 24) & 255}.{(ip_num >> 16) & 255}.{(ip_num >> 8) & 255}.{ip_num & 255}"
        return ip, port


# -- commands (ref: src/actor.rs:159-266) -------------------------------------


@dataclass(frozen=True)
class Send:
    dst: Id
    msg: Any


@dataclass(frozen=True)
class SetTimer:
    timer: Any
    duration: Tuple[float, float]  # (lo, hi) seconds; ignored by the checker


@dataclass(frozen=True)
class CancelTimer:
    timer: Any


@dataclass(frozen=True)
class ChooseRandom:
    key: str
    choices: tuple


class Out:
    """Collects commands emitted by an actor handler (ref: src/actor.rs:172-266)."""

    def __init__(self):
        self.commands: list = []

    def send(self, recipient: Id, msg) -> None:
        self.commands.append(Send(Id(recipient), msg))

    def broadcast(self, recipients: Iterable[Id], msg) -> None:
        for r in recipients:
            self.send(r, msg)

    def set_timer(self, timer, duration: Tuple[float, float]) -> None:
        self.commands.append(SetTimer(timer, tuple(duration)))

    def cancel_timer(self, timer) -> None:
        self.commands.append(CancelTimer(timer))

    def choose_random(self, key: str, choices: list) -> None:
        """Record a nondeterministic choice, creating a branch in the search
        tree keyed by `key` (later calls with the same key overwrite)."""
        self.commands.append(ChooseRandom(str(key), tuple(choices)))

    def remove_random(self, key: str) -> None:
        self.commands.append(ChooseRandom(str(key), ()))

    def append(self, other: "Out") -> None:
        self.commands.extend(other.commands)
        other.commands.clear()

    def __iter__(self):
        return iter(self.commands)

    def __len__(self):
        return len(self.commands)

    def __repr__(self):
        return repr(self.commands)


def model_timeout() -> Tuple[float, float]:
    """Timer range for model checking — durations are abstracted away entirely
    (ref: src/actor/model.rs:76-78)."""
    return (0.0, 0.0)


def model_peers(self_ix: int, count: int) -> list[Id]:
    """Peer ids for actor `self_ix` in a `count`-actor system
    (ref: src/actor/model.rs:82-87)."""
    return [Id(j) for j in range(count) if j != self_ix]


def majority(cluster_size: int) -> int:
    """Node count constituting a majority (ref: src/actor.rs:605-607)."""
    return cluster_size // 2 + 1


def peer_ids(self_id: Id, other_ids: Iterable[Id]):
    """All of `other_ids` except `self_id` (ref: src/actor.rs:610-615)."""
    return (i for i in other_ids if i != self_id)


class Actor:
    """Event-driven state machine (ref: src/actor.rs:293-389).

    Handlers return the next state, or None for "unchanged"."""

    def on_start(self, id: Id, out: Out):
        """Return the initial state, optionally emitting commands."""
        raise NotImplementedError

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        return None  # no-op by default

    def on_timeout(self, id: Id, state, timer, out: Out):
        return None  # no-op by default

    def on_random(self, id: Id, state, random, out: Out):
        return None  # no-op by default

    def name(self) -> str:
        return ""


@dataclass
class ScriptedActor(Actor):
    """Sends a series of messages in sequence, waiting for any delivery between
    each — useful for driving actor systems under test (the reference implements
    `Actor` for `Vec<(Id, Msg)>`, ref: src/actor.rs:565-602)."""

    script: list  # [(dst_id, msg), ...]

    def on_start(self, id: Id, out: Out):
        if self.script:
            dst, msg = self.script[0]
            out.send(dst, msg)
            return 1
        return 0

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if state < len(self.script):
            dst, m = self.script[state]
            out.send(dst, m)
            return state + 1
        return None


# Re-exports for a flat `stateright_tpu.actor` namespace mirroring the
# reference's `use stateright::actor::*`.
from .network import Envelope, Network  # noqa: E402
from .model import (  # noqa: E402
    ActorModel,
    ActorModelAction,
    ActorModelState,
    Deliver,
    DropEnv,
    Timeout,
    Crash,
    SelectRandom,
    LossyNetwork,
)

__all__ = [
    "Id",
    "Out",
    "Send",
    "SetTimer",
    "CancelTimer",
    "ChooseRandom",
    "Actor",
    "ScriptedActor",
    "model_timeout",
    "model_peers",
    "majority",
    "peer_ids",
    "Envelope",
    "Network",
    "ActorModel",
    "ActorModelAction",
    "ActorModelState",
    "Deliver",
    "DropEnv",
    "Timeout",
    "Crash",
    "SelectRandom",
    "LossyNetwork",
]
