"""Register-like actor interface and reusable client harness
(ref: src/actor/register.rs).

`RegisterMsg` defines the external protocol (Put/Get + oks, plus Internal for
the system's own messages). `RegisterActor` wraps a server actor under test
with scripted clients that Put `put_count` times round-robin across servers and
then Get. `record_invocations`/`record_returns` wire the message traffic into a
`ConsistencyTester` carried as the ActorModel history.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..semantics.register import Read, ReadOk, Write, WriteOk
from . import Actor, Id, Out


# -- protocol messages (ref: src/actor/register.rs:17-31) ----------------------


@dataclass(frozen=True)
class Internal:
    msg: Any

    def __repr__(self):
        return f"Internal({self.msg!r})"


@dataclass(frozen=True)
class Put:
    request_id: int
    value: Any

    def __repr__(self):
        return f"Put({self.request_id}, {self.value!r})"


@dataclass(frozen=True)
class Get:
    request_id: int

    def __repr__(self):
        return f"Get({self.request_id})"


@dataclass(frozen=True)
class PutOk:
    request_id: int

    def __repr__(self):
        return f"PutOk({self.request_id})"


@dataclass(frozen=True)
class GetOk:
    request_id: int
    value: Any

    def __repr__(self):
        return f"GetOk({self.request_id}, {self.value!r})"


# -- history recorders (ref: src/actor/register.rs:38-91) ----------------------


def record_invocations(cfg, history, env):
    """Pass to `ActorModel.record_msg_out`: records Read on Get, Write on Put."""
    if isinstance(env.msg, Get):
        return history.on_invoke(env.src, Read())
    if isinstance(env.msg, Put):
        return history.on_invoke(env.src, Write(env.msg.value))
    return None


def record_returns(cfg, history, env):
    """Pass to `ActorModel.record_msg_in`: records ReadOk on GetOk, WriteOk on
    PutOk."""
    if isinstance(env.msg, GetOk):
        return history.on_return(env.dst, ReadOk(env.msg.value))
    if isinstance(env.msg, PutOk):
        return history.on_return(env.dst, WriteOk())
    return None


# -- client/server harness (ref: src/actor/register.rs:93-275) -----------------


@dataclass(frozen=True)
class ClientState:
    awaiting: Any  # request id or None
    op_count: int

    def __repr__(self):
        return f"Client(awaiting={self.awaiting!r}, op_count={self.op_count})"


@dataclass(frozen=True)
class ServerState:
    state: Any

    def __repr__(self):
        return f"Server({self.state!r})"


class RegisterClient(Actor):
    """A client that Puts `put_count` values round-robin across the servers
    (which must occupy actor ids 0..server_count) and then issues a Get.
    Value scheme matches the reference: first Put sends chr(ord('A') + k) for
    client k, subsequent Puts send chr(ord('Z') - k)
    (ref: src/actor/register.rs:145-237)."""

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, out: Out):
        index = int(id)
        if index < self.server_count:
            raise RuntimeError(
                "RegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return ClientState(awaiting=None, op_count=0)
        unique_request_id = index  # 1 * index
        value = chr(ord("A") + index - self.server_count)
        out.send(Id(index % self.server_count), Put(unique_request_id, value))
        return ClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if not isinstance(state, ClientState) or state.awaiting is None:
            return None
        index = int(id)
        if isinstance(msg, PutOk) and msg.request_id == state.awaiting:
            unique_request_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    Id((index + state.op_count) % self.server_count),
                    Put(unique_request_id, value),
                )
            else:
                out.send(
                    Id((index + state.op_count) % self.server_count),
                    Get(unique_request_id),
                )
            return ClientState(awaiting=unique_request_id, op_count=state.op_count + 1)
        if isinstance(msg, GetOk) and msg.request_id == state.awaiting:
            return ClientState(awaiting=None, op_count=state.op_count + 1)
        return None


class RegisterServer(Actor):
    """Wraps a server actor under test so its state is tagged distinctly from
    client states (the reference's RegisterActor::Server variant)."""

    def __init__(self, server_actor: Actor):
        self.server_actor = server_actor

    def name(self) -> str:
        return self.server_actor.name() or "Server"

    def on_start(self, id: Id, out: Out):
        return ServerState(self.server_actor.on_start(id, out))

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        inner = self.server_actor.on_msg(id, state.state, src, msg, out)
        return None if inner is None else ServerState(inner)

    def on_timeout(self, id: Id, state, timer, out: Out):
        inner = self.server_actor.on_timeout(id, state.state, timer, out)
        return None if inner is None else ServerState(inner)

    def on_random(self, id: Id, state, random, out: Out):
        inner = self.server_actor.on_random(id, state.state, random, out)
        return None if inner is None else ServerState(inner)
