"""`ActorModel`: lowers an actor system + network + timers + crashes + random
choices + history into the generic `Model` interface — the bridge that makes
actor systems checkable (ref: src/actor/model.rs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..core.model import Expectation, Model, Property
from . import (
    Actor,
    CancelTimer,
    ChooseRandom,
    Id,
    Out,
    Send,
    SetTimer,
)
from .network import Envelope, Network, ORDERED


class LossyNetwork:
    """Whether the network loses messages (ref: src/actor/model.rs:67-71).
    Message loss is indistinguishable from unlimited delay unless invariants
    inspect the network, so `NO` often checks faster."""

    YES = True
    NO = False


# -- actions (ref: src/actor/model.rs:44-62) -----------------------------------


@dataclass(frozen=True)
class Deliver:
    src: Id
    dst: Id
    msg: Any

    def __repr__(self):
        return f"Deliver {{ src: {self.src!r}, dst: {self.dst!r}, msg: {self.msg!r} }}"


@dataclass(frozen=True)
class DropEnv:
    envelope: Envelope

    def __repr__(self):
        return f"Drop({self.envelope!r})"


@dataclass(frozen=True)
class Timeout:
    id: Id
    timer: Any

    def __repr__(self):
        return f"Timeout({self.id!r}, {self.timer!r})"


@dataclass(frozen=True)
class Crash:
    id: Id

    def __repr__(self):
        return f"Crash({self.id!r})"


@dataclass(frozen=True)
class SelectRandom:
    actor: Id
    key: str
    random: Any

    def __repr__(self):
        return f"SelectRandom {{ actor: {self.actor!r}, key: {self.key!r}, random: {self.random!r} }}"


ActorModelAction = (Deliver, DropEnv, Timeout, Crash, SelectRandom)


class ActorModelState:
    """Snapshot of the entire actor system (ref: src/actor/model_state.rs:15-22).

    Identity (fingerprint/equality) covers actor_states, history, timers_set,
    and network — NOT random_choices or crashed, mirroring the reference's
    manual Hash/PartialEq impls (ref: src/actor/model_state.rs:134-161).
    """

    __slots__ = (
        "actor_states",
        "network",
        "timers_set",
        "random_choices",
        "crashed",
        "history",
        "_hash",  # lazy deep-hash cache (states are frozen before hashing)
    )

    def __init__(
        self,
        actor_states: tuple,
        network: Network,
        timers_set: tuple,  # tuple[frozenset, ...]
        random_choices: tuple,  # tuple[dict[str, tuple], ...]
        crashed: tuple,  # tuple[bool, ...]
        history,
    ):
        self.actor_states = actor_states
        self.network = network
        self.timers_set = timers_set
        self.random_choices = random_choices
        self.crashed = crashed
        self.history = history
        self._hash = None

    def __stable_encode__(self):
        # Field order matches the reference's Hash impl
        # (ref: src/actor/model_state.rs:139-145).
        return (self.actor_states, self.history, self.timers_set, self.network)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ActorModelState)
            and self.actor_states == other.actor_states
            and self.history == other.history
            and self.timers_set == other.timers_set
            and self.network == other.network
        )

    def __hash__(self) -> int:
        # States are frozen before they are ever hashed (next_state stages
        # then _freeze-s); cache the deep hash — host search sets/dicts and
        # the exact-closure BFS re-hash every state many times (measured
        # ~30% of paxos-2 exact-closure time before caching).
        h = self._hash
        if h is None:
            h = self._hash = hash(
                (self.actor_states, self.history, self.timers_set, self.network)
            )
        return h

    def __repr__(self) -> str:
        return (
            f"ActorModelState {{ actor_states: {list(self.actor_states)!r}, "
            f"history: {self.history!r}, timers: {[sorted(map(repr, t)) for t in self.timers_set]!r}, "
            f"network: {self.network!r} }}"
        )

    def representative(self) -> "ActorModelState":
        """Canonical member of this state's symmetry equivalence class: sort
        actor states, then rewrite every Id accordingly
        (ref: src/actor/model_state.rs:163-182)."""
        from ..symmetry import RewritePlan, rewrite

        plan = RewritePlan.from_values_to_sort(self.actor_states)
        return ActorModelState(
            actor_states=plan.reindex(self.actor_states),
            network=rewrite(self.network, plan),
            timers_set=plan.reindex(self.timers_set),
            random_choices=plan.reindex(self.random_choices),
            crashed=plan.reindex(self.crashed),
            history=rewrite(self.history, plan),
        )


class ActorModel(Model):
    """A system of communicating actors as a checkable `Model`
    (ref: src/actor/model.rs:24-40, 228-763).

    `H` (the history) is auxiliary state in the TLA+ sense, updated by the
    `record_msg_in`/`record_msg_out` hooks — the integration point for the
    consistency testers in `stateright_tpu.semantics`.
    """

    def __init__(self, cfg=None, init_history=None):
        self.actors: list[Actor] = []
        self.cfg = cfg
        self.init_history = init_history
        self.init_network: Network = Network.new_unordered_duplicating()
        self.lossy_network: bool = LossyNetwork.NO
        self.max_crashes: int = 0
        self._properties: list[Property] = []
        self.record_msg_in_: Callable = lambda cfg, history, env: None
        self.record_msg_out_: Callable = lambda cfg, history, env: None
        self.within_boundary_: Callable = lambda cfg, state: True

    # -- builder (ref: src/actor/model.rs:95-186) ------------------------------

    @staticmethod
    def new(cfg=None, init_history=None) -> "ActorModel":
        return ActorModel(cfg, init_history)

    def actor(self, actor: Actor) -> "ActorModel":
        self.actors.append(actor)
        return self

    def add_actors(self, actors) -> "ActorModel":
        self.actors.extend(actors)
        return self

    def with_init_network(self, network: Network) -> "ActorModel":
        self.init_network = network
        return self

    def with_lossy_network(self, lossy: bool) -> "ActorModel":
        self.lossy_network = lossy
        return self

    def with_max_crashes(self, n: int) -> "ActorModel":
        self.max_crashes = n
        return self

    def property(self, expectation: Expectation, name: str, condition) -> "ActorModel":
        self._properties.append(Property(expectation, name, condition))
        return self

    def record_msg_in(self, fn: Callable) -> "ActorModel":
        """fn(cfg, history, envelope) -> new history or None (no update)."""
        self.record_msg_in_ = fn
        return self

    def record_msg_out(self, fn: Callable) -> "ActorModel":
        self.record_msg_out_ = fn
        return self

    def with_within_boundary(self, fn: Callable) -> "ActorModel":
        """fn(cfg, state) -> bool."""
        self.within_boundary_ = fn
        return self

    # -- command processing (ref: src/actor/model.rs:188-225) ------------------

    def _process_commands(self, id: Id, out: Out, staging: dict) -> None:
        index = int(id)
        for c in out:
            if isinstance(c, Send):
                env = Envelope(Id(id), c.dst, c.msg)
                new_history = self.record_msg_out_(self.cfg, staging["history"], env)
                if new_history is not None:
                    staging["history"] = new_history
                staging["network"] = staging["network"].send(env)
            elif isinstance(c, SetTimer):
                staging["timers"][index] = staging["timers"][index] | {c.timer}
            elif isinstance(c, CancelTimer):
                staging["timers"][index] = staging["timers"][index] - {c.timer}
            elif isinstance(c, ChooseRandom):
                randoms = dict(staging["randoms"][index])
                if not c.choices:
                    randoms.pop(c.key, None)
                else:
                    randoms[c.key] = c.choices
                staging["randoms"][index] = randoms
            else:
                raise TypeError(f"unknown command {c!r}")

    def _freeze(self, staging: dict) -> ActorModelState:
        return ActorModelState(
            actor_states=tuple(staging["actor_states"]),
            network=staging["network"],
            timers_set=tuple(staging["timers"]),
            random_choices=tuple(staging["randoms"]),
            crashed=tuple(staging["crashed"]),
            history=staging["history"],
        )

    def _stage(self, state: ActorModelState) -> dict:
        return {
            "actor_states": list(state.actor_states),
            "network": state.network,
            "timers": list(state.timers_set),
            "randoms": list(state.random_choices),
            "crashed": list(state.crashed),
            "history": state.history,
        }

    # -- Model interface (ref: src/actor/model.rs:228-763) ---------------------

    def init_states(self) -> list:
        n = len(self.actors)
        staging = {
            "actor_states": [],
            "network": self.init_network,
            "timers": [frozenset()] * n,
            "randoms": [{}] * n,
            "crashed": [False] * n,
            "history": self.init_history,
        }
        for index, actor in enumerate(self.actors):
            out = Out()
            state = actor.on_start(Id(index), out)
            staging["actor_states"].append(state)
            self._process_commands(Id(index), out, staging)
        return [self._freeze(staging)]

    def actions(self, state: ActorModelState, actions: list) -> None:
        # Deliveries and drops (ref: src/actor/model.rs:258-282). For ordered
        # networks iter_deliverable already restricts to flow heads.
        for env in state.network.iter_deliverable():
            if self.lossy_network:
                actions.append(DropEnv(env))
            if int(env.dst) < len(self.actors):
                actions.append(Deliver(env.src, env.dst, env.msg))

        # Timeouts (ref: :284-289).
        for index, timers in enumerate(state.timers_set):
            for timer in sorted(timers, key=repr):
                actions.append(Timeout(Id(index), timer))

        # Crashes (ref: :291-300).
        n_crashed = sum(1 for c in state.crashed if c)
        if n_crashed < self.max_crashes:
            for index, crashed in enumerate(state.crashed):
                if not crashed:
                    actions.append(Crash(Id(index)))

        # Random choices (ref: :302-313).
        for index, randoms in enumerate(state.random_choices):
            for key, choices in randoms.items():
                for choice in choices:
                    actions.append(SelectRandom(Id(index), key, choice))

    def next_state(self, last_sys_state: ActorModelState, action):
        if isinstance(action, DropEnv):
            staging = self._stage(last_sys_state)
            staging["network"] = staging["network"].on_drop(action.envelope)
            return self._freeze(staging)

        if isinstance(action, Deliver):
            index = int(action.dst)
            if index >= len(last_sys_state.actor_states):
                return None  # recipient does not exist
            if last_sys_state.crashed[index]:
                return None  # recipient crashed
            last_actor_state = last_sys_state.actor_states[index]
            out = Out()
            next_actor_state = self.actors[index].on_msg(
                Id(index), last_actor_state, action.src, action.msg, out
            )
            # No-op elision prunes the state space, except on ordered networks
            # where delivery still pops the flow head
            # (ref: src/actor/model.rs:345-347).
            if (
                next_actor_state is None
                and not out.commands
                and self.init_network.kind != ORDERED
            ):
                return None
            env = Envelope(action.src, action.dst, action.msg)
            new_history = self.record_msg_in_(self.cfg, last_sys_state.history, env)
            staging = self._stage(last_sys_state)
            staging["network"] = staging["network"].on_deliver(env)
            if next_actor_state is not None:
                staging["actor_states"][index] = next_actor_state
            if new_history is not None:
                staging["history"] = new_history
            self._process_commands(Id(index), out, staging)
            return self._freeze(staging)

        if isinstance(action, Timeout):
            index = int(action.id)
            out = Out()
            next_actor_state = self.actors[index].on_timeout(
                Id(index), last_sys_state.actor_states[index], action.timer, out
            )
            # No-op-with-timer: unchanged state and the only command renews the
            # same timer — elide entirely. A handler that does nothing at all
            # is NOT elided: the timer fired and is consumed
            # (ref: src/actor.rs:277-287, src/actor/model.rs:386-392).
            if (
                next_actor_state is None
                and len(out.commands) == 1
                and isinstance(out.commands[0], SetTimer)
                and out.commands[0].timer == action.timer
            ):
                return None
            staging = self._stage(last_sys_state)
            staging["timers"][index] = staging["timers"][index] - {action.timer}
            if next_actor_state is not None:
                staging["actor_states"][index] = next_actor_state
            self._process_commands(Id(index), out, staging)
            return self._freeze(staging)

        if isinstance(action, Crash):
            index = int(action.id)
            staging = self._stage(last_sys_state)
            staging["timers"][index] = frozenset()
            staging["randoms"][index] = {}
            staging["crashed"][index] = True
            return self._freeze(staging)

        if isinstance(action, SelectRandom):
            index = int(action.actor)
            out = Out()
            next_actor_state = self.actors[index].on_random(
                Id(index), last_sys_state.actor_states[index], action.random, out
            )
            staging = self._stage(last_sys_state)
            randoms = dict(staging["randoms"][index])
            randoms.pop(action.key, None)  # the choice is no longer valid
            staging["randoms"][index] = randoms
            if next_actor_state is not None:
                staging["actor_states"][index] = next_actor_state
            self._process_commands(Id(index), out, staging)
            return self._freeze(staging)

        raise TypeError(f"unknown action {action!r}")

    def properties(self) -> list[Property]:
        return list(self._properties)

    def within_boundary(self, state: ActorModelState) -> bool:
        return self.within_boundary_(self.cfg, state)

    # -- display (ref: src/actor/model.rs:428-548) -----------------------------

    def format_action(self, action) -> str:
        if isinstance(action, Deliver):
            return f"{action.src!r} → {action.msg!r} → {action.dst!r}"
        if isinstance(action, SelectRandom):
            return f"{action.actor!r} select random {action.random!r}"
        return repr(action)

    def format_step(self, last_state: ActorModelState, action) -> Optional[str]:
        if isinstance(action, DropEnv):
            return f"DROP: {action.envelope!r}"
        if isinstance(action, Crash):
            index = int(action.id)
            if index >= len(last_state.actor_states):
                return None
            return f"CRASH: {last_state.actor_states[index]!r}"
        handlers = {
            Deliver: lambda s, o: self.actors[int(action.dst)].on_msg(
                action.dst, s, action.src, action.msg, o
            ),
            Timeout: lambda s, o: self.actors[int(action.id)].on_timeout(
                action.id, s, action.timer, o
            ),
            SelectRandom: lambda s, o: self.actors[int(action.actor)].on_random(
                action.actor, s, action.random, o
            ),
        }
        handler = handlers.get(type(action))
        if handler is None:
            return None
        target = action.dst if isinstance(action, Deliver) else (
            action.id if isinstance(action, Timeout) else action.actor
        )
        index = int(target)
        if index >= len(last_state.actor_states):
            return None
        last_actor_state = last_state.actor_states[index]
        out = Out()
        next_actor_state = handler(last_actor_state, out)
        lines = [f"OUT: {out!r}", ""]
        if next_actor_state is not None:
            lines += [f"NEXT_STATE: {next_actor_state!r}", "", f"PREV_STATE: {last_actor_state!r}"]
        else:
            lines.append(f"UNCHANGED: {last_actor_state!r}")
        return "\n".join(lines)

    def as_svg(self, path) -> Optional[str]:
        """Sequence diagram of a path (ref: src/actor/model.rs:551-754)."""
        from .svg import sequence_diagram

        return sequence_diagram(self, path)
